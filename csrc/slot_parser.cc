// Native slot-format parser: the data-loader hot path in C++.
//
// The reference parses sample text in C++ worker threads
// (SlotPaddleBoxDataFeed::ParseOneInstance, data_feed.cc:2951-3061, with
// optional dlopen'd ISlotParser plugins, :2594-2655). This library is that
// tier for the TPU framework: one call parses a whole file buffer into
// columnar arrays (values + per-record offsets) that numpy wraps zero-copy
// on the Python side (utils/native.py).
//
// Line format (identical to data/parser.py::parse_line):
//   [1 <ins_id>] [1 <logkey>] {<num> <v...>} per slot in schema order
// - counts must be nonzero (error)
// - sparse (uint64) slots drop 0-valued feasigns unless dense
// - float slots drop |v| < 1e-6 unless dense
// - a record with zero remaining uint64 feasigns is skipped
// - logkey hex fields: cmatch [11:14), rank [14:16), search_id [16:32)
//
// ABI: C, handle-based. The caller copies results out through pointer
// getters then frees the handle. Thread-safe (no globals): one handle per
// file per reader thread.

#include <cctype>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Parsed {
  // flat values; record r's slot s lives at
  // [u64_base[r] + u64_off[r*(S1)+s], u64_base[r] + u64_off[r*(S1)+s+1])
  std::vector<uint64_t> u64_values;
  std::vector<uint32_t> u64_offsets;  // record-local, n_records*(n_sparse+1)
  std::vector<int64_t> u64_base;
  std::vector<float> f_values;
  std::vector<uint32_t> f_offsets;  // record-local, n_records*(n_float+1)
  std::vector<int64_t> f_base;
  std::vector<uint64_t> search_id;
  std::vector<int32_t> cmatch;
  std::vector<int32_t> rank;
  std::vector<int64_t> ins_id_off;  // offsets into ins_id_chars (n_records+1)
  std::string ins_id_chars;
  int64_t n_records = 0;
  int64_t skipped = 0;
  std::string error;
};

inline const char* skip_spaces(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline bool parse_u64(const char*& p, const char* end, uint64_t* out) {
  p = skip_spaces(p, end);
  if (p >= end || !isdigit((unsigned char)*p)) return false;
  uint64_t v = 0;
  while (p < end && isdigit((unsigned char)*p)) {
    v = v * 10u + (uint64_t)(*p - '0');
    ++p;
  }
  *out = v;
  return true;
}

inline bool parse_f32(const char*& p, const char* end, float* out) {
  p = skip_spaces(p, end);
  if (p >= end) return false;
  char* ep = nullptr;
  // buffer is NUL-terminated by the caller; strtof stops at whitespace
  float v = strtof(p, &ep);
  if (ep == p) return false;
  p = ep;
  *out = v;
  return true;
}

inline bool parse_token(const char*& p, const char* end, const char** tok,
                        size_t* len) {
  p = skip_spaces(p, end);
  if (p >= end || *p == '\n') return false;
  *tok = p;
  while (p < end && *p != ' ' && *p != '\t' && *p != '\n' && *p != '\r') ++p;
  *len = (size_t)(p - *tok);
  return true;
}

// Parse s[a..b) as hex; a non-hex char sets *ok = false (the Python
// oracle's int(_, 16) raises — mapping bad chars to 0 would silently
// corrupt cmatch/rank/search_id).
inline uint64_t hex_field(const char* s, int a, int b, bool* ok) {
  uint64_t v = 0;
  for (int i = a; i < b; ++i) {
    char c = s[i];
    uint64_t d;
    if (c >= '0' && c <= '9') d = (uint64_t)(c - '0');
    else if (c >= 'a' && c <= 'f') d = (uint64_t)(c - 'a' + 10);
    else if (c >= 'A' && c <= 'F') d = (uint64_t)(c - 'A' + 10);
    else { *ok = false; return 0; }
    v = (v << 4) | d;
  }
  return v;
}

}  // namespace

extern "C" {

// slot_kinds[i]: 0 = sparse uint64, 1 = float; is_dense[i]: keep zeros;
// is_used[i]: 0 = parse-and-discard (schema 'used' parity). Errors land in
// errbuf and NULL is returned.
void* pbx_parse_buffer(const char* data, int64_t len, int n_slots,
                       const uint8_t* slot_kinds, const uint8_t* is_dense,
                       const uint8_t* is_used, int parse_ins_id,
                       int parse_logkey, char* errbuf, int errbuf_len) {
  auto fail = [&](const std::string& msg, int64_t line_no) {
    if (errbuf && errbuf_len > 0) {
      snprintf(errbuf, (size_t)errbuf_len, "line %lld: %s",
               (long long)(line_no + 1), msg.c_str());
    }
    return (void*)nullptr;
  };
  Parsed* out = new Parsed();
  int n_sparse = 0, n_float = 0;
  for (int i = 0; i < n_slots; ++i)
    if (is_used[i]) (slot_kinds[i] ? n_float : n_sparse)++;

  const char* p = data;
  const char* end = data + len;
  int64_t line_no = 0;
  out->ins_id_off.push_back(0);

  std::vector<uint64_t> u_tmp;
  std::vector<float> f_tmp;
  std::vector<uint32_t> u_off(n_sparse + 1), f_off(n_float + 1);

  while (p < end) {
    const char* line_end = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!line_end) line_end = end;
    const char* q = skip_spaces(p, line_end);
    if (q == line_end) {  // blank line
      p = line_end + 1;
      ++line_no;
      continue;
    }
    u_tmp.clear();
    f_tmp.clear();
    uint64_t sid = 0;
    int32_t cm = 0, rk = 0;
    const char* ins_tok = nullptr;
    size_t ins_len = 0;

    if (parse_ins_id) {
      uint64_t one;
      const char* tok;
      size_t tl;
      if (!parse_u64(q, line_end, &one) || one != 1 ||
          !parse_token(q, line_end, &tok, &tl)) {
        delete out;
        return fail("bad ins_id field", line_no);
      }
      ins_tok = tok;
      ins_len = tl;
    }
    if (parse_logkey) {
      uint64_t one;
      const char* tok;
      size_t tl;
      if (!parse_u64(q, line_end, &one) || one != 1 ||
          !parse_token(q, line_end, &tok, &tl) || tl < 17) {
        delete out;
        return fail("bad logkey field (need > 16 hex chars)", line_no);
      }
      int e1 = tl < 14 ? (int)tl : 14;
      int e2 = tl < 16 ? (int)tl : 16;
      int e3 = tl < 32 ? (int)tl : 32;
      bool hex_ok = true;
      cm = (int32_t)hex_field(tok, 11, e1, &hex_ok);
      rk = (int32_t)hex_field(tok, 14, e2, &hex_ok);
      sid = hex_field(tok, 16, e3, &hex_ok);
      if (!hex_ok) {
        delete out;
        return fail("non-hex character in logkey", line_no);
      }
      // the logkey IS the ins_id (parser.py sets it unconditionally)
      ins_tok = tok;
      ins_len = tl;
    }

    int ui = 0, fi = 0;
    u_off[0] = 0;
    f_off[0] = 0;
    for (int s = 0; s < n_slots; ++s) {
      uint64_t cnt;
      if (!parse_u64(q, line_end, &cnt)) {
        delete out;
        return fail("truncated slot line (ran out of tokens)", line_no);
      }
      if (cnt == 0) {
        delete out;
        return fail("zero-count slot (pad in the data generator)", line_no);
      }
      if (!is_used[s]) {  // consume and discard
        const char* tok;
        size_t tl;
        for (uint64_t j = 0; j < cnt; ++j) {
          if (!parse_token(q, line_end, &tok, &tl)) {
            delete out;
            return fail("truncated slot line (ran out of tokens)", line_no);
          }
        }
        continue;
      }
      if (slot_kinds[s]) {  // float
        for (uint64_t j = 0; j < cnt; ++j) {
          float v;
          if (!parse_f32(q, line_end, &v)) {
            delete out;
            return fail("truncated slot line (ran out of tokens)", line_no);
          }
          // keep-test must be !(|v| < eps): NaN fails every comparison, and
          // the Python oracle (abs(v) < 1e-6 -> skip) KEEPS NaN values
          if (is_dense[s] || !(fabsf(v) < 1e-6f)) f_tmp.push_back(v);
        }
        f_off[++fi] = (uint32_t)f_tmp.size();
      } else {
        for (uint64_t j = 0; j < cnt; ++j) {
          uint64_t v;
          if (!parse_u64(q, line_end, &v)) {
            delete out;
            return fail("truncated slot line (ran out of tokens)", line_no);
          }
          if (v != 0 || is_dense[s]) u_tmp.push_back(v);
        }
        u_off[++ui] = (uint32_t)u_tmp.size();
      }
    }

    if (u_tmp.empty()) {  // no surviving feasigns: skip record
      out->skipped++;
    } else {
      out->u64_base.push_back((int64_t)out->u64_values.size());
      out->u64_values.insert(out->u64_values.end(), u_tmp.begin(), u_tmp.end());
      out->u64_offsets.insert(out->u64_offsets.end(), u_off.begin(), u_off.end());
      out->f_base.push_back((int64_t)out->f_values.size());
      out->f_values.insert(out->f_values.end(), f_tmp.begin(), f_tmp.end());
      out->f_offsets.insert(out->f_offsets.end(), f_off.begin(), f_off.end());
      out->search_id.push_back(sid);
      out->cmatch.push_back(cm);
      out->rank.push_back(rk);
      if (ins_tok) out->ins_id_chars.append(ins_tok, ins_len);
      out->ins_id_off.push_back((int64_t)out->ins_id_chars.size());
      out->n_records++;
    }
    p = (line_end < end) ? line_end + 1 : end;
    ++line_no;
  }
  return (void*)out;
}

int64_t pbx_num_records(void* h) { return ((Parsed*)h)->n_records; }
int64_t pbx_num_skipped(void* h) { return ((Parsed*)h)->skipped; }
int64_t pbx_num_u64(void* h) { return (int64_t)((Parsed*)h)->u64_values.size(); }
int64_t pbx_num_f(void* h) { return (int64_t)((Parsed*)h)->f_values.size(); }
int64_t pbx_ins_chars(void* h) {
  return (int64_t)((Parsed*)h)->ins_id_chars.size();
}

const uint64_t* pbx_u64_values(void* h) { return ((Parsed*)h)->u64_values.data(); }
const uint32_t* pbx_u64_offsets(void* h) { return ((Parsed*)h)->u64_offsets.data(); }
const int64_t* pbx_u64_base(void* h) { return ((Parsed*)h)->u64_base.data(); }
const float* pbx_f_values(void* h) { return ((Parsed*)h)->f_values.data(); }
const uint32_t* pbx_f_offsets(void* h) { return ((Parsed*)h)->f_offsets.data(); }
const int64_t* pbx_f_base(void* h) { return ((Parsed*)h)->f_base.data(); }
const uint64_t* pbx_search_ids(void* h) { return ((Parsed*)h)->search_id.data(); }
const int32_t* pbx_cmatch(void* h) { return ((Parsed*)h)->cmatch.data(); }
const int32_t* pbx_rank(void* h) { return ((Parsed*)h)->rank.data(); }
const int64_t* pbx_ins_id_off(void* h) { return ((Parsed*)h)->ins_id_off.data(); }
const char* pbx_ins_id_chars_ptr(void* h) {
  return ((Parsed*)h)->ins_id_chars.data();
}

void pbx_free(void* h) { delete (Parsed*)h; }

}  // extern "C"
