// Native per-batch packer: the MiniBatchGpuPack hot loop in C++.
//
// The reference packs minibatches on pinned host memory in C++ worker
// threads (MiniBatchGpuPack::pack_instance, data_feed.h:1418-1542) and
// dedups keys on device (DedupKeysAndFillIdx, box_wrapper_impl.h:103). On
// TPU the whole resolution happens host-side once per batch: keys were
// already mapped to pass-local table rows when the pass was finalized
// (PassWorkingSet), so packing a batch is a ragged gather over the
// columnar record store + first-occurrence dedup + segment-id emission —
// one native call, no Python per-record work.
//
// Dedup uses an epoch-stamped scratch table sized by the pass row count:
// O(L) per batch, no clearing, no hashing (rows are dense pass-local ids).
//
// ABI: C, handle-based; one handle per packer thread (the scratch is the
// only mutable state). ctypes releases the GIL during calls, so packer
// threads genuinely overlap with each other and the device step.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Packer {
  // borrowed pass-scoped views (owned by numpy on the Python side; the
  // pass object must outlive the handle)
  const int32_t* rows;         // [total_keys] pass-local row per key
  const int64_t* rec_base;     // [n_records] record base into rows
  const uint32_t* rec_off;     // [n_records * (n_sparse+1)] record-local
  int n_sparse;
  int64_t n_records;
  // dedup scratch, epoch-stamped
  std::vector<int64_t> stamp;
  std::vector<int32_t> uniq_of_row;
  int64_t epoch = 0;
};

}  // namespace

extern "C" {

void* pbx_packer_create(const int32_t* rows, const int64_t* rec_base,
                        const uint32_t* rec_off, int64_t n_records,
                        int n_sparse, int64_t n_table_rows) {
  Packer* p = new Packer();
  p->rows = rows;
  p->rec_base = rec_base;
  p->rec_off = rec_off;
  p->n_sparse = n_sparse;
  p->n_records = n_records;
  p->stamp.assign((size_t)n_table_rows, -1);
  p->uniq_of_row.resize((size_t)n_table_rows);
  return (void*)p;
}

// Pack records `indices[0..B)` into slot-major arrays. Caller buffers:
// uniq_rows [>=L], inverse [>=L], segments [>=L] where L = total key count
// of the batch (caller computes it from the offsets; returns -1 if a
// record index or row is out of range). Writes the first-occurrence unique
// rows and per-key (uniq index, slot*B+ins segment); returns U, the unique
// count. No padding here — the Python wrapper buckets and pads.
int64_t pbx_pack_batch(void* h, const int64_t* indices, int64_t B,
                       int32_t* uniq_rows, int32_t* inverse,
                       int32_t* segments) {
  Packer* p = (Packer*)h;
  const int S1 = p->n_sparse + 1;
  const int64_t epoch = ++p->epoch;
  int64_t* stamp = p->stamp.data();
  int32_t* uniq_of_row = p->uniq_of_row.data();
  const int64_t n_rows = (int64_t)p->stamp.size();
  int64_t k = 0, U = 0;
  for (int s = 0; s < p->n_sparse; ++s) {
    for (int64_t i = 0; i < B; ++i) {
      const int64_t r = indices[i];
      if (r < 0 || r >= p->n_records) return -1;
      const uint32_t* off = p->rec_off + r * S1;
      const int64_t a = p->rec_base[r] + off[s];
      const int64_t b = p->rec_base[r] + off[s + 1];
      const int32_t seg = (int32_t)(s * B + i);
      for (int64_t j = a; j < b; ++j) {
        const int32_t row = p->rows[j];
        if (row < 0 || row >= n_rows) return -1;
        if (stamp[row] != epoch) {
          stamp[row] = epoch;
          uniq_of_row[row] = (int32_t)U;
          uniq_rows[U++] = row;
        }
        inverse[k] = uniq_of_row[row];
        segments[k] = seg;
        ++k;
      }
    }
  }
  return U;
}

void pbx_packer_free(void* h) { delete (Packer*)h; }

// --- pass-scoped helpers (vectorized host work that is awkward/slow in
// numpy but trivial here) ------------------------------------------------

// Ragged gather: out[i] = concat of values[base[idx]+off[idx][slot]..+1)
// for one slot over many records — used for whole-pass label extraction
// and columnar select(). Lengths must be uniform (dim) per record.
void pbx_gather_f32_slot(const float* values, const int64_t* base,
                         const uint32_t* off, int n_float_p1,
                         const int64_t* indices, int64_t n, int slot, int dim,
                         float* out) {
  for (int64_t i = 0; i < n; ++i) {
    const int64_t r = indices[i];
    const uint32_t* o = off + r * n_float_p1;
    const int64_t a = base[r] + o[slot];
    const int64_t len = (int64_t)(o[slot + 1] - o[slot]);
    const int64_t c = len < dim ? len : dim;
    for (int64_t d = 0; d < c; ++d) out[i * dim + d] = values[a + d];
    for (int64_t d = c; d < dim; ++d) out[i * dim + d] = 0.0f;
  }
}

// Pass-prepare pad sweep: per device-block (L, max unique rows per shard)
// for the resident feed's shape freeze (ensure_sharded). The reference
// equalizes pass shapes with counters + one allreduce
// (compute_thread_batch_nccl, data_set.cc:2069-2135); this is the
// counter side — one GIL-released native sweep over the whole block
// matrix replaces a per-(device, batch) Python unique/bincount loop.
//
// rows: int32 [total_keys] pass-local row per key occurrence;
// base/counts: int64 [n_records] flat key span per record;
// indices: int64 [n_blocks * b] record ids, row-major blocks.
// Dedup is epoch-stamped by block id over the n_rows id space; per-shard
// unique counters reset per block (ns is small). Returns 0, or -1 on an
// out-of-range record/row.
int pbx_block_stats(const int32_t* rows, const int64_t* base,
                    const int64_t* counts, int64_t n_records,
                    const int64_t* indices, int64_t n_blocks, int64_t b,
                    int64_t cap, int64_t ns, int64_t n_rows,
                    int64_t* L_out, int64_t* bmax_out) {
  std::vector<int64_t> stamp((size_t)n_rows, -1);
  std::vector<int64_t> scnt((size_t)ns, 0);
  for (int64_t blk = 0; blk < n_blocks; ++blk) {
    std::fill(scnt.begin(), scnt.end(), 0);
    int64_t L = 0, bmax = 0;
    const int64_t* idx = indices + blk * b;
    for (int64_t i = 0; i < b; ++i) {
      const int64_t r = idx[i];
      if (r < 0 || r >= n_records) return -1;
      const int64_t a = base[r];
      const int64_t e = a + counts[r];
      L += counts[r];
      for (int64_t j = a; j < e; ++j) {
        const int32_t row = rows[j];
        if (row < 0 || row >= n_rows) return -1;
        if (stamp[row] != blk) {
          stamp[row] = blk;
          const int64_t c = ++scnt[row / cap];
          if (c > bmax) bmax = c;
        }
      }
    }
    L_out[blk] = L;
    bmax_out[blk] = bmax;
  }
  return 0;
}

}  // extern "C"
