// Native per-batch packer: the MiniBatchGpuPack hot loop in C++.
//
// The reference packs minibatches on pinned host memory in C++ worker
// threads (MiniBatchGpuPack::pack_instance, data_feed.h:1418-1542) and
// dedups keys on device (DedupKeysAndFillIdx, box_wrapper_impl.h:103). On
// TPU the whole resolution happens host-side once per batch: keys were
// already mapped to pass-local table rows when the pass was finalized
// (PassWorkingSet), so packing a batch is a ragged gather over the
// columnar record store + first-occurrence dedup + segment-id emission —
// one native call, no Python per-record work.
//
// Dedup uses an epoch-stamped scratch table sized by the pass row count:
// O(L) per batch, no clearing, no hashing (rows are dense pass-local ids).
//
// ABI: C, handle-based; one handle per packer thread (the scratch is the
// only mutable state). ctypes releases the GIL during calls, so packer
// threads genuinely overlap with each other and the device step.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Packer {
  // borrowed pass-scoped views (owned by numpy on the Python side; the
  // pass object must outlive the handle)
  const int32_t* rows;         // [total_keys] pass-local row per key
  const int64_t* rec_base;     // [n_records] record base into rows
  const uint32_t* rec_off;     // [n_records * (n_sparse+1)] record-local
  int n_sparse;
  int64_t n_records;
  // dedup scratch, epoch-stamped
  std::vector<int64_t> stamp;
  std::vector<int32_t> uniq_of_row;
  int64_t epoch = 0;
};

}  // namespace

extern "C" {

void* pbx_packer_create(const int32_t* rows, const int64_t* rec_base,
                        const uint32_t* rec_off, int64_t n_records,
                        int n_sparse, int64_t n_table_rows) {
  Packer* p = new Packer();
  p->rows = rows;
  p->rec_base = rec_base;
  p->rec_off = rec_off;
  p->n_sparse = n_sparse;
  p->n_records = n_records;
  p->stamp.assign((size_t)n_table_rows, -1);
  p->uniq_of_row.resize((size_t)n_table_rows);
  return (void*)p;
}

// Pack records `indices[0..B)` into slot-major arrays. Caller buffers:
// uniq_rows [>=L], inverse [>=L], segments [>=L] where L = total key count
// of the batch (caller computes it from the offsets; returns -1 if a
// record index or row is out of range). Writes the first-occurrence unique
// rows and per-key (uniq index, slot*B+ins segment); returns U, the unique
// count. No padding here — the Python wrapper buckets and pads.
int64_t pbx_pack_batch(void* h, const int64_t* indices, int64_t B,
                       int32_t* uniq_rows, int32_t* inverse,
                       int32_t* segments) {
  Packer* p = (Packer*)h;
  const int S1 = p->n_sparse + 1;
  const int64_t epoch = ++p->epoch;
  int64_t* stamp = p->stamp.data();
  int32_t* uniq_of_row = p->uniq_of_row.data();
  const int64_t n_rows = (int64_t)p->stamp.size();
  int64_t k = 0, U = 0;
  for (int s = 0; s < p->n_sparse; ++s) {
    for (int64_t i = 0; i < B; ++i) {
      const int64_t r = indices[i];
      if (r < 0 || r >= p->n_records) return -1;
      const uint32_t* off = p->rec_off + r * S1;
      const int64_t a = p->rec_base[r] + off[s];
      const int64_t b = p->rec_base[r] + off[s + 1];
      const int32_t seg = (int32_t)(s * B + i);
      for (int64_t j = a; j < b; ++j) {
        const int32_t row = p->rows[j];
        if (row < 0 || row >= n_rows) return -1;
        if (stamp[row] != epoch) {
          stamp[row] = epoch;
          uniq_of_row[row] = (int32_t)U;
          uniq_rows[U++] = row;
        }
        inverse[k] = uniq_of_row[row];
        segments[k] = seg;
        ++k;
      }
    }
  }
  return U;
}

void pbx_packer_free(void* h) { delete (Packer*)h; }

// --- pass-scoped helpers (vectorized host work that is awkward/slow in
// numpy but trivial here) ------------------------------------------------

// Ragged gather: out[i] = concat of values[base[idx]+off[idx][slot]..+1)
// for one slot over many records — used for whole-pass label extraction
// and columnar select(). Lengths must be uniform (dim) per record.
void pbx_gather_f32_slot(const float* values, const int64_t* base,
                         const uint32_t* off, int n_float_p1,
                         const int64_t* indices, int64_t n, int slot, int dim,
                         float* out) {
  for (int64_t i = 0; i < n; ++i) {
    const int64_t r = indices[i];
    const uint32_t* o = off + r * n_float_p1;
    const int64_t a = base[r] + o[slot];
    const int64_t len = (int64_t)(o[slot + 1] - o[slot]);
    const int64_t c = len < dim ? len : dim;
    for (int64_t d = 0; d < c; ++d) out[i * dim + d] = values[a + d];
    for (int64_t d = c; d < dim; ++d) out[i * dim + d] = 0.0f;
  }
}

// Pass-prepare pad sweep: per device-block (L, max unique rows per shard)
// for the resident feed's shape freeze (ensure_sharded). The reference
// equalizes pass shapes with counters + one allreduce
// (compute_thread_batch_nccl, data_set.cc:2069-2135); this is the
// counter side — one GIL-released native sweep over the whole block
// matrix replaces a per-(device, batch) Python unique/bincount loop.
//
// rows: int32 [total_keys] pass-local row per key occurrence;
// base/counts: int64 [n_records] flat key span per record;
// indices: int64 [n_blocks * b] record ids, row-major blocks.
// Dedup is a per-block gather + sort + run walk: work scales with the
// block's key count, never with the table's row count (an epoch-stamp
// table over the row id space would memset O(n_rows) per CALL — at a
// 45M-row pass that is 365 MB of writes before any work). The scratch
// buffer reuses its high-water allocation across blocks. Returns 0, or
// -1 on an out-of-range record/row.
int pbx_block_stats(const int32_t* rows, const int64_t* base,
                    const int64_t* counts, int64_t n_records,
                    const int64_t* indices, int64_t n_blocks, int64_t b,
                    int64_t cap, int64_t ns, int64_t n_rows,
                    int64_t* L_out, int64_t* bmax_out) {
  std::vector<uint32_t> buf, tmp;
  for (int64_t blk = 0; blk < n_blocks; ++blk) {
    const int64_t* idx = indices + blk * b;
    int64_t L = 0;
    for (int64_t i = 0; i < b; ++i) {
      const int64_t r = idx[i];
      if (r < 0 || r >= n_records || counts[r] < 0) return -1;
      L += counts[r];
    }
    buf.resize((size_t)L);
    tmp.resize((size_t)L);
    // gather: each record's key rows are contiguous -> one memcpy per
    // record (rows are validated against n_rows during the run walk via
    // the max; negative values wrap to huge uint32 and fail the check)
    size_t w = 0;
    for (int64_t i = 0; i < b; ++i) {
      const int64_t r = idx[i];
      const int64_t c = counts[r];
      std::memcpy(buf.data() + w, rows + base[r], (size_t)c * sizeof(int32_t));
      w += (size_t)c;
    }
    // LSD radix sort, 4x8-bit passes: ~3-5x faster than comparison sort
    // at the 1e5-1e6 keys a device block carries
    uint32_t maxv = 0;
    for (size_t k = 0; k < w; ++k) maxv = buf[k] > maxv ? buf[k] : maxv;
    // compare in int64: a uint32-truncated n_rows would falsely reject
    // everything at exactly 2^32 rows (negative int32 rows arrive here
    // wrapped to huge uint32 values, so they fail this check too)
    if ((int64_t)maxv >= n_rows) return -1;
    uint32_t cnt[256];
    for (int shift = 0; shift < 32 && (maxv >> shift); shift += 8) {
      std::memset(cnt, 0, sizeof(cnt));
      for (size_t k = 0; k < w; ++k) ++cnt[(buf[k] >> shift) & 0xFF];
      uint32_t run = 0;
      for (int v = 0; v < 256; ++v) {
        const uint32_t c = cnt[v];
        cnt[v] = run;
        run += c;
      }
      for (size_t k = 0; k < w; ++k) tmp[cnt[(buf[k] >> shift) & 0xFF]++] = buf[k];
      buf.swap(tmp);
    }
    // unique runs, counted per shard (rows are shard-major: shard=row/cap)
    int64_t bmax = 0, scur = -1, c = 0;
    uint32_t prev = 0xFFFFFFFFu;
    for (size_t k = 0; k < w; ++k) {
      const uint32_t row = buf[k];
      if (row == prev) continue;
      prev = row;
      const int64_t s = (int64_t)row / cap;
      if (s >= ns) return -1;  // row beyond the [ns, cap] shard grid
      if (s != scur) {
        scur = s;
        c = 0;
      }
      if (++c > bmax) bmax = c;
    }
    L_out[blk] = L;
    bmax_out[blk] = bmax;
  }
  return 0;
}

}  // extern "C"
