// Native host sparse-table store: the mem + SSD tiers of BoxPS in C++.
//
// The reference keeps its 1e10..1e11-key feature table inside the closed
// libbox_ps.so, tiered across SSD and host RAM and promoted to HBM per pass
// (box_wrapper.cc:1325 LoadSSD2Mem; cmake/external/box_ps.cmake). This file
// is the open TPU-side equivalent of that host tier: a sharded open-
// addressing uint64 -> fp32-row store with
//
//   - batch pull_or_create / push (the pass finalize + writeback hot path;
//     the Python-dict fallback measured ~160k keys/s, this runs tens of
//     millions/s and threads across shards with the GIL released),
//   - deterministic per-key initialization (splitmix64 counter RNG, so
//     init is order- and shard-independent — stronger than the reference's
//     sequential RNG, and required for multi-host reproducibility),
//   - touched-row tracking for delta saves (SaveDelta parity,
//     box_wrapper.cc:1288-1331),
//   - pass-boundary decay+shrink (pslib show_click_decay_rate + shrink),
//   - a per-shard disk spill tier: cold rows are evicted to append-only
//     shard files and lazily promoted (with catch-up decay) when a later
//     pass touches them — LoadSSD2Mem semantics inverted for the host side.
//
// ABI: plain C, handle-based, ctypes-bound (utils/native.py); all calls are
// thread-safe via per-shard mutexes.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace {

constexpr uint64_t kHashMult = 0x9E3779B97F4A7C15ull;

inline uint64_t mix_shard(uint64_t key) { return (key * kHashMult) >> 33; }

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// Hash-slot states. kDisk entries hold a byte offset into the shard's
// spill file instead of a mem row id.
enum : uint8_t { kEmpty = 0, kMem = 1, kDisk = 2 };

struct SpillRec {  // on-disk record header, followed by width floats
  uint64_t key;
  int64_t epoch;    // table pass-epoch at spill time (for catch-up decay)
  uint64_t touched; // delta-save flag survives the disk tier
};

struct Shard {
  // open-addressing hash: slot -> (key, where)
  std::vector<uint64_t> hkeys;
  std::vector<int64_t> hval;  // mem row id (kMem) or file offset (kDisk)
  std::vector<uint8_t> hstate;
  uint64_t mask = 0;  // capacity - 1 (power of two)
  int64_t n_used = 0;  // mem + disk entries in the hash

  // mem tier rows
  std::vector<float> values;        // [n_rows * width]
  std::vector<uint64_t> row_key;    // [n_rows]
  std::vector<uint8_t> row_touched; // [n_rows]
  std::vector<int64_t> row_epoch;   // [n_rows] last-touched table epoch
  int64_t n_rows = 0;

  // cumulative tier counters (monotone; exported via pbx_table_tier_stats)
  int64_t n_spilled = 0;        // mem rows written to the disk tier
  int64_t n_promoted = 0;       // disk rows brought back to mem
  int64_t n_admit_spilled = 0;  // spills forced by the admission threshold
  int64_t n_lazy_shrunk = 0;    // disk rows dropped at promote (decayed out)

  // disk tier
  FILE* spill = nullptr;
  std::string spill_path;
  int64_t n_disk = 0;
  int64_t n_disk_touched = 0;
  // records in the spill file no longer referenced by any hash entry
  // (promotes and lazy shrinks leave their bytes behind — the file is
  // append-only between compactions). When dead outnumber live, the
  // shard's file is rewritten (compact_spill) so a many-pass run's spill
  // stays bounded by its LIVE cold set, not its history.
  int64_t dead_disk = 0;

  std::mutex mtx;

  ~Shard() {
    if (spill) fclose(spill);
  }
};

// Cumulative IO-overlap telemetry (pbx_table_io_stats). Atomics because
// shard workers update them concurrently; pure observation — none of these
// feed back into table state, so they cannot perturb bitwise results.
struct IoStats {
  std::atomic<int64_t> spill_gather_ns{0};   // row serialize into staging
  std::atomic<int64_t> spill_fwrite_ns{0};   // staged fwrite (flusher side)
  std::atomic<int64_t> prepass_read_ns{0};   // push pre-pass header freads
  std::atomic<int64_t> stage_flushes{0};     // staged buffers handed off
  std::atomic<int64_t> stage_bytes{0};       // bytes through the stage path
};

struct Table {
  int n_shards;
  int width;
  int show_col;
  int clk_col;
  uint64_t seed;
  std::vector<int32_t> init_cols;  // columns getting uniform(-r, r) init
  float init_range;
  std::string spill_dir;  // empty => spill disabled
  int64_t epoch = 0;      // incremented by decay_shrink (pass boundary)
  float last_decay = 1.0f;
  float last_threshold = 0.0f;
  IoStats io;
  std::vector<Shard> shards;

  Table(int ns) : shards(ns) {}
};

inline int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline int shard_of(const Table* t, uint64_t key) {
  return (int)(mix_shard(key) % (uint64_t)t->n_shards);
}

void shard_grow_hash(Shard* s) {
  uint64_t new_cap = s->mask ? (s->mask + 1) * 2 : 1024;
  std::vector<uint64_t> nk(new_cap);
  std::vector<int64_t> nv(new_cap);
  std::vector<uint8_t> ns(new_cap, kEmpty);
  uint64_t nmask = new_cap - 1;
  if (s->mask) {
    for (uint64_t i = 0; i <= s->mask; ++i) {
      if (s->hstate[i] == kEmpty) continue;
      uint64_t j = splitmix64(s->hkeys[i]) & nmask;
      while (ns[j] != kEmpty) j = (j + 1) & nmask;
      nk[j] = s->hkeys[i];
      nv[j] = s->hval[i];
      ns[j] = s->hstate[i];
    }
  }
  s->hkeys.swap(nk);
  s->hval.swap(nv);
  s->hstate.swap(ns);
  s->mask = nmask;
}

// find slot of key; returns slot index, or the empty slot to insert into.
// *found says whether the key is present.
inline uint64_t shard_find(Shard* s, uint64_t key, bool* found) {
  uint64_t j = splitmix64(key) & s->mask;
  while (true) {
    if (s->hstate[j] == kEmpty) {
      *found = false;
      return j;
    }
    if (s->hkeys[j] == key) {
      *found = true;
      return j;
    }
    j = (j + 1) & s->mask;
  }
}

inline void shard_maybe_grow(Shard* s) {
  if (s->mask == 0 || (uint64_t)s->n_used * 10 >= (s->mask + 1) * 7)
    shard_grow_hash(s);
}

int64_t shard_new_row(const Table* t, Shard* s, uint64_t key) {
  int64_t row = s->n_rows++;
  if ((int64_t)s->row_key.size() < s->n_rows) {
    int64_t cap = s->row_key.size() ? (int64_t)s->row_key.size() * 2 : 1024;
    if (cap < s->n_rows) cap = s->n_rows;
    s->row_key.resize(cap);
    s->row_touched.resize(cap, 0);
    s->row_epoch.resize(cap, 0);
    s->values.resize(cap * (int64_t)t->width);
  }
  s->row_key[row] = key;
  s->row_touched[row] = 0;
  s->row_epoch[row] = t->epoch;
  return row;
}

void init_row(const Table* t, uint64_t key, float* dst) {
  std::memset(dst, 0, sizeof(float) * t->width);
  // one full mix per key, then a cheap counter advance per column — the
  // sequence is a pure function of (seed, key, column order), so init stays
  // deterministic and shard/host-count independent
  uint64_t st = splitmix64(t->seed ^ splitmix64(key));
  for (int32_t c : t->init_cols) {
    st += 0x9E3779B97F4A7C15ull;
    uint64_t r = splitmix64(st);  // full finalizer: real avalanche per column
    float u = (float)(r >> 40) * (1.0f / 16777216.0f);
    dst[c] = (2.0f * u - 1.0f) * t->init_range;
  }
}

bool shard_open_spill(Table* t, int si) {
  Shard* s = &t->shards[si];
  if (s->spill) return true;
  if (t->spill_dir.empty()) return false;
  char buf[64];
  snprintf(buf, sizeof(buf), "/spill-%05d.bin", si);
  s->spill_path = t->spill_dir + buf;
  s->spill = fopen(s->spill_path.c_str(), "w+b");
  return s->spill != nullptr;
}

// Promote a disk entry at hash slot j to a mem row, applying catch-up
// decay for the passes it slept through. Returns the new row id, or -1 if
// the decayed row falls below the shrink threshold (entry is dropped).
// seek_end=false defers the append-position restore (batched promotes
// seek once at the end so stdio read-ahead survives across reads).
int64_t promote(Table* t, Shard* s, uint64_t j, bool seek_end = true) {
  int64_t off = s->hval[j];
  SpillRec rec;
  std::vector<float> buf(t->width);
  fseeko(s->spill, off, SEEK_SET);
  if (fread(&rec, sizeof(rec), 1, s->spill) != 1 ||
      fread(buf.data(), sizeof(float), t->width, s->spill) != (size_t)t->width)
    return -2;  // IO error
  if (seek_end) fseeko(s->spill, 0, SEEK_END);
  int64_t missed = t->epoch - rec.epoch;
  if (missed > 0 && t->last_decay < 1.0f) {
    // one multiply per slept-through pass, in pass order — NOT an
    // accumulated power: (s*d)*d != s*(d*d) in fp32 for non-pow2 rates,
    // and a promoted row must match its never-spilled twin bitwise
    for (int64_t i = 0; i < missed; ++i) {
      buf[t->show_col] *= t->last_decay;
      buf[t->clk_col] *= t->last_decay;
    }
  }
  s->n_disk--;
  s->dead_disk++;  // the on-disk bytes at `off` are now garbage
  if (rec.touched) s->n_disk_touched--;
  if (missed > 0 && buf[t->show_col] < t->last_threshold) {
    // lazily shrunk: delete the entry entirely
    s->hstate[j] = kEmpty;
    s->n_used--;
    // re-insert any displaced linear-probe followers
    uint64_t k = (j + 1) & s->mask;
    while (s->hstate[k] != kEmpty) {
      uint64_t kk = s->hkeys[k];
      int64_t vv = s->hval[k];
      uint8_t st = s->hstate[k];
      s->hstate[k] = kEmpty;
      s->n_used--;
      bool f;
      uint64_t slot = shard_find(s, kk, &f);
      s->hkeys[slot] = kk;
      s->hval[slot] = vv;
      s->hstate[slot] = st;
      s->n_used++;
      k = (k + 1) & s->mask;
    }
    s->n_lazy_shrunk++;
    return -1;
  }
  int64_t row = shard_new_row(t, s, s->hkeys[j]);
  std::memcpy(&s->values[row * t->width], buf.data(),
              sizeof(float) * t->width);
  s->row_touched[row] = rec.touched ? 1 : 0;
  s->hval[j] = row;
  s->hstate[j] = kMem;
  s->n_promoted++;
  return row;
}

// Partition keys by shard once, then run fn(shard_id, key_positions) over
// shards on a thread pool (ctypes released the GIL for us). Each worker
// owns the strided shard set {w, w+nt, ...} — disjoint ownership, so any
// per-shard side output (shard_ns below) is written race-free without a
// merge lock; per-shard mutexes still guard against concurrent API calls.
//
// `threads` <= 0 picks the legacy auto heuristic (hardware concurrency
// capped at 16, serial below 64k keys); `threads` == 1 forces the serial
// path; larger values request an explicit pool (capped at n_shards). The
// shard visit ORDER inside a worker and the per-shard work are identical
// at every thread count — only interleaving differs, which per-shard locks
// make unobservable — so results are bitwise-equal across `threads`.
//
// `shard_ns`, when non-null, receives per-shard wall nanoseconds spent in
// fn (length n_shards; written by the owning worker only).
template <typename Fn>
int for_shards_ex(const Table* t, const uint64_t* keys, int64_t n,
                  int threads, int64_t* shard_ns, Fn fn) {
  int ns = t->n_shards;
  std::vector<int64_t> count(ns, 0);
  std::vector<int> sh((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(t, keys[i]);
    sh[i] = s;
    count[s]++;
  }
  std::vector<int64_t> start(ns + 1, 0);
  for (int s = 0; s < ns; ++s) start[s + 1] = start[s] + count[s];
  std::vector<int64_t> pos(start.begin(), start.end() - 1);
  std::vector<int64_t> order((size_t)n);
  for (int64_t i = 0; i < n; ++i) order[pos[sh[i]]++] = i;
  if (shard_ns)
    for (int s = 0; s < ns; ++s) shard_ns[s] = 0;

  int nt;
  if (threads > 0) {
    nt = threads;
  } else {
    nt = (int)std::thread::hardware_concurrency();
    if (nt > 16) nt = 16;
    if (n < 65536) nt = 1;
  }
  if (nt > ns) nt = ns;
  if (nt < 1) nt = 1;
  std::vector<int> rc(nt, 0);
  auto work = [&](int w) {
    for (int s = w; s < ns; s += nt) {
      int64_t t0 = shard_ns ? now_ns() : 0;
      int r = fn(s, order.data() + start[s], count[s]);
      if (shard_ns) shard_ns[s] = now_ns() - t0;
      if (r != 0) rc[w] = r;
    }
  };
  if (nt == 1) {
    work(0);
  } else {
    std::vector<std::thread> th;
    for (int w = 0; w < nt; ++w) th.emplace_back(work, w);
    for (auto& x : th) x.join();
  }
  for (int w = 0; w < (int)rc.size(); ++w)
    if (rc[w] != 0) return rc[w];
  return 0;
}

template <typename Fn>
int for_shards(const Table* t, const uint64_t* keys, int64_t n, Fn fn) {
  return for_shards_ex(t, keys, n, /*threads=*/0, /*shard_ns=*/nullptr, fn);
}

// Rewrite one shard's spill file with only the LIVE records (hash entries
// in kDisk state). Caller holds the shard lock. Failure-safe: hash offsets
// are staged in a side vector and applied only after the tmp file is fully
// flushed and renamed over the old one — any IO error (short read, ENOSPC
// at write or flush time, failed rename) leaves the shard exactly as it
// was, old file and offsets intact. Live records are read in OFFSET order
// (sequential IO, same trick as the batched-promote path). Returns live
// records kept, or negative on IO error.
int64_t compact_spill(Table* t, Shard* s) {
  if (!s->spill) return 0;
  std::vector<std::pair<int64_t, uint64_t>> live;  // (old offset, hash slot)
  for (uint64_t j = 0; j <= s->mask && s->mask; ++j)
    if (s->hstate[j] == kDisk) live.push_back({s->hval[j], j});
  std::sort(live.begin(), live.end());
  std::string tmp = s->spill_path + ".tmp";
  FILE* nf = fopen(tmp.c_str(), "w+b");
  if (!nf) return -2;
  std::vector<float> buf(t->width);
  std::vector<int64_t> new_off(live.size());
  auto fail = [&]() {
    fclose(nf);
    remove(tmp.c_str());
    fseeko(s->spill, 0, SEEK_END);
    return (int64_t)-2;
  };
  for (size_t i = 0; i < live.size(); ++i) {
    SpillRec rec;
    fseeko(s->spill, live[i].first, SEEK_SET);
    if (fread(&rec, sizeof(rec), 1, s->spill) != 1 ||
        fread(buf.data(), sizeof(float), t->width, s->spill) !=
            (size_t)t->width)
      return fail();
    new_off[i] = ftello(nf);
    if (fwrite(&rec, sizeof(rec), 1, nf) != 1 ||
        fwrite(buf.data(), sizeof(float), t->width, nf) != (size_t)t->width)
      return fail();
  }
  if (fflush(nf) != 0) return fail();
  if (rename(tmp.c_str(), s->spill_path.c_str()) != 0) return fail();
  fclose(s->spill);
  s->spill = nf;  // nf refers to the renamed (now canonical) file on POSIX
  fseeko(s->spill, 0, SEEK_END);
  for (size_t i = 0; i < live.size(); ++i)
    s->hval[live[i].second] = new_off[i];
  s->dead_disk = 0;
  return (int64_t)live.size();
}

enum : int { kSpillFifo = 0, kSpillFreq = 1 };

// Serialize victims[lo..hi) of one shard into `out` as the exact byte
// stream the legacy per-record fwrite loop produced: SpillRec header
// followed by width floats, in victim order.
void gather_spill_chunk(const Table* t, const Shard* s,
                        const std::vector<int64_t>& victims, int64_t lo,
                        int64_t hi, size_t recsz, std::vector<char>* out) {
  out->resize((size_t)(hi - lo) * recsz);
  char* p = out->data();
  for (int64_t i = lo; i < hi; ++i) {
    int64_t r = victims[i];
    SpillRec rec{s->row_key[r], t->epoch, s->row_touched[r] ? 1ull : 0ull};
    std::memcpy(p, &rec, sizeof(rec));
    std::memcpy(p + sizeof(rec), &s->values[r * (int64_t)t->width],
                sizeof(float) * t->width);
    p += recsz;
  }
}

// Write the given mem rows (any order) of one shard to its spill file,
// convert their hash entries to kDisk, and compact the surviving mem rows
// in place. Caller holds the shard lock and has opened the spill file.
// Returns rows spilled, or -2 on IO error.
//
// The write is double-buffered: records are append-only with a fixed size,
// so every victim's disk offset is analytic (base + i*recsz) and the next
// chunk's row gather can run while a flusher thread has the previous
// chunk's fwrite in flight. The byte stream is identical to the legacy
// per-record loop; on an IO error the hash/counter state is untouched
// (strictly cleaner than the legacy mid-loop bail, which had already
// bumped n_disk_touched for the records it got through).
int64_t shard_spill_rows(Table* t, Shard* s,
                         const std::vector<int64_t>& victims) {
  if (victims.empty()) return 0;
  fseeko(s->spill, 0, SEEK_END);
  const int64_t base = ftello(s->spill);
  const size_t recsz = sizeof(SpillRec) + sizeof(float) * (size_t)t->width;
  const int64_t nv = (int64_t)victims.size();
  std::vector<uint8_t> is_victim(s->n_rows, 0);
  std::vector<int64_t> disk_off(s->n_rows, 0);
  int64_t touched_delta = 0;
  for (int64_t i = 0; i < nv; ++i) {
    int64_t r = victims[i];
    is_victim[r] = 1;
    disk_off[r] = base + i * (int64_t)recsz;
    if (s->row_touched[r]) touched_delta++;
  }
  // ~1 MiB staging chunks: big enough that fwrite syscall/lock overhead
  // amortizes, small enough that two buffers stay cache-friendly
  int64_t chunk = (int64_t)((1u << 20) / recsz);
  if (chunk < 64) chunk = 64;
  int64_t gather_ns = 0, fwrite_ns = 0, flushes = 0;
  bool werr = false;
  if (nv <= chunk) {
    // small spill: one gather, one fwrite — no thread, same bytes
    std::vector<char> buf;
    int64_t t0 = now_ns();
    gather_spill_chunk(t, s, victims, 0, nv, recsz, &buf);
    gather_ns = now_ns() - t0;
    t0 = now_ns();
    if (fwrite(buf.data(), 1, buf.size(), s->spill) != buf.size()) werr = true;
    fwrite_ns = now_ns() - t0;
    flushes = 1;
  } else {
    // two staging buffers in ping-pong: the main thread gathers chunk k+1
    // while the flusher writes chunk k. Only the flusher touches s->spill
    // between here and the join.
    std::vector<char> bufs[2];
    std::mutex m;
    std::condition_variable cv;
    int pending = -1;  // buffer index handed to the flusher, -1 = none
    bool done = false;
    std::thread flusher([&] {
      std::unique_lock<std::mutex> lk(m);
      while (true) {
        cv.wait(lk, [&] { return pending >= 0 || done; });
        if (pending < 0) return;
        int b = pending;
        lk.unlock();
        int64_t t0 = now_ns();
        size_t wr = fwrite(bufs[b].data(), 1, bufs[b].size(), s->spill);
        int64_t dt = now_ns() - t0;
        lk.lock();
        fwrite_ns += dt;
        pending = -1;
        if (wr != bufs[b].size()) {
          werr = true;
          done = true;
        }
        cv.notify_all();
      }
    });
    int cur = 0;
    for (int64_t lo = 0; lo < nv; lo += chunk) {
      int64_t hi = std::min(nv, lo + chunk);
      int64_t t0 = now_ns();
      gather_spill_chunk(t, s, victims, lo, hi, recsz, &bufs[cur]);
      gather_ns += now_ns() - t0;
      std::unique_lock<std::mutex> lk(m);
      cv.wait(lk, [&] { return pending < 0; });
      if (werr) break;
      pending = cur;
      flushes++;
      cv.notify_all();
      cur ^= 1;
    }
    {
      std::unique_lock<std::mutex> lk(m);
      cv.wait(lk, [&] { return pending < 0; });  // drain the last chunk
      done = true;
      cv.notify_all();
    }
    flusher.join();
  }
  t->io.spill_gather_ns += gather_ns;
  t->io.spill_fwrite_ns += fwrite_ns;
  t->io.stage_flushes += flushes;
  t->io.stage_bytes += nv * (int64_t)recsz;
  if (werr) return -2;
  s->n_disk_touched += touched_delta;
  fflush(s->spill);
  // compact survivors
  std::vector<int64_t> remap(s->n_rows, -1);
  int64_t keep = 0;
  for (int64_t r = 0; r < s->n_rows; ++r)
    if (!is_victim[r]) remap[r] = keep++;
  for (int64_t r = 0; r < s->n_rows; ++r) {
    int64_t nr = remap[r];
    if (nr < 0 || nr == r) continue;
    std::memcpy(&s->values[nr * t->width], &s->values[r * t->width],
                sizeof(float) * t->width);
    s->row_key[nr] = s->row_key[r];
    s->row_touched[nr] = s->row_touched[r];
    s->row_epoch[nr] = s->row_epoch[r];
  }
  for (uint64_t j = 0; j <= s->mask && s->mask; ++j) {
    if (s->hstate[j] != kMem) continue;
    int64_t r = s->hval[j];
    if (is_victim[r]) {
      s->hstate[j] = kDisk;
      s->hval[j] = disk_off[r];
      s->n_disk++;
    } else {
      s->hval[j] = remap[r];
    }
  }
  s->n_rows = keep;
  s->n_spilled += (int64_t)victims.size();
  // opportunistic space reclaim: once dead records outnumber live ones
  // the file is mostly garbage — rewrite it now, while we already hold
  // the shard lock at a pass boundary
  if (s->dead_disk > s->n_disk && s->dead_disk >= 1024) {
    if (compact_spill(t, s) < 0) return -2;
  }
  return (int64_t)victims.size();
}

// Coldness-ranked victim pick for one shard: every row under the admission
// threshold goes first (disk-first admission — sub-threshold keys don't get
// to occupy RAM past a cap sweep), then the coldest rows by (lowest decayed
// show, oldest last-touched epoch, lowest row id) until `want` victims.
// Rows at or above the pin threshold are spilled only once every colder
// candidate is gone. Caller holds the shard lock.
void pick_victims_freq(const Table* t, const Shard* s, int64_t want,
                       float pin_show, float admit_show,
                       std::vector<int64_t>* victims, int64_t* admitted) {
  std::vector<int64_t> ranked;  // below pin threshold: normal candidates
  std::vector<int64_t> pinned;  // at/above pin threshold: last resort
  for (int64_t r = 0; r < s->n_rows; ++r) {
    float show = s->values[r * t->width + t->show_col];
    if (admit_show > 0.0f && show < admit_show) {
      victims->push_back(r);
      continue;
    }
    if (pin_show > 0.0f && show >= pin_show)
      pinned.push_back(r);
    else
      ranked.push_back(r);
  }
  *admitted = (int64_t)victims->size();
  auto colder = [&](int64_t a, int64_t b) {
    float sa = s->values[a * t->width + t->show_col];
    float sb = s->values[b * t->width + t->show_col];
    if (sa != sb) return sa < sb;
    if (s->row_epoch[a] != s->row_epoch[b])
      return s->row_epoch[a] < s->row_epoch[b];
    return a < b;
  };
  int64_t extra = want - *admitted;
  for (auto* pool : {&ranked, &pinned}) {
    if (extra <= 0) break;
    if ((int64_t)pool->size() > extra) {
      std::partial_sort(pool->begin(), pool->begin() + extra, pool->end(),
                        colder);
      pool->resize(extra);
    } else {
      std::sort(pool->begin(), pool->end(), colder);
    }
    victims->insert(victims->end(), pool->begin(), pool->end());
    extra -= (int64_t)pool->size();
  }
}

int64_t spill_cold_impl(Table* t, int64_t max_mem_rows, int policy,
                        float pin_show, float admit_show) {
  if (t->spill_dir.empty()) return -1;
  std::vector<int64_t> shard_mem(t->n_shards, 0);
  int64_t mem = 0;
  for (int si = 0; si < t->n_shards; ++si) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    shard_mem[si] = s->n_rows;
    mem += s->n_rows;
  }
  int64_t over = mem - max_mem_rows;
  if (over <= 0) return 0;
  int64_t spilled_total = 0;
  if (policy == kSpillFreq) {
    // exact largest-remainder apportionment of `over` across shards in
    // proportion to their occupancy: the post-sweep mem tier stays
    // balanced by shard and totals exactly max_mem_rows (admission
    // evictions may push it lower — that's the point of admission)
    std::vector<int64_t> want(t->n_shards, 0);
    int64_t assigned = 0;
    for (int si = 0; si < t->n_shards; ++si) {
      want[si] = over * shard_mem[si] / mem;
      assigned += want[si];
    }
    int64_t rem = over - assigned;
    while (rem > 0) {
      bool progress = false;
      for (int si = 0; si < t->n_shards && rem > 0; ++si) {
        if (want[si] < shard_mem[si]) {
          want[si]++;
          rem--;
          progress = true;
        }
      }
      if (!progress) break;
    }
    for (int si = 0; si < t->n_shards; ++si) {
      Shard* s = &t->shards[si];
      std::lock_guard<std::mutex> g(s->mtx);
      if (s->n_rows == 0) continue;
      if (want[si] <= 0 && admit_show <= 0.0f) continue;
      if (!shard_open_spill(t, si)) return -2;
      std::vector<int64_t> victims;
      int64_t admitted = 0;
      pick_victims_freq(t, s, want[si], pin_show, admit_show, &victims,
                        &admitted);
      int64_t n = shard_spill_rows(t, s, victims);
      if (n < 0) return n;
      s->n_admit_spilled += admitted;
      spilled_total += n;
    }
    return spilled_total;
  }
  // fifo (legacy, kept as the A/B baseline): untouched rows in creation
  // order, then touched rows, greedily shard by shard until under cap
  int64_t need = over;
  for (int si = 0; si < t->n_shards && need > 0; ++si) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    if (s->n_rows == 0) continue;
    if (!shard_open_spill(t, si)) return -2;
    std::vector<int64_t> victims;
    for (int64_t r = 0; r < s->n_rows && (int64_t)victims.size() < need; ++r)
      if (!s->row_touched[r]) victims.push_back(r);
    for (int64_t r = 0; r < s->n_rows && (int64_t)victims.size() < need; ++r)
      if (s->row_touched[r]) victims.push_back(r);
    if (victims.empty()) continue;
    int64_t n = shard_spill_rows(t, s, victims);
    if (n < 0) return n;
    need -= n;
    spilled_total += n;
  }
  return spilled_total;
}

}  // namespace

extern "C" {

void* pbx_table_create(int n_shards, int width, int show_col, int clk_col,
                       uint64_t seed, const int32_t* init_cols,
                       int n_init_cols, float init_range,
                       const char* spill_dir) {
  Table* t = new Table(n_shards);
  t->n_shards = n_shards;
  t->width = width;
  t->show_col = show_col;
  t->clk_col = clk_col;
  t->seed = seed;
  t->init_cols.assign(init_cols, init_cols + n_init_cols);
  t->init_range = init_range;
  if (spill_dir && spill_dir[0]) t->spill_dir = spill_dir;
  return (void*)t;
}

void pbx_table_free(void* h) { delete (Table*)h; }

int64_t pbx_table_size(void* h) {
  Table* t = (Table*)h;
  int64_t n = 0;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> g(s.mtx);
    n += s.n_used;
  }
  return n;
}

int64_t pbx_table_mem_rows(void* h) {
  Table* t = (Table*)h;
  int64_t n = 0;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> g(s.mtx);
    n += s.n_used - s.n_disk;
  }
  return n;
}

int64_t pbx_table_disk_rows(void* h) {
  Table* t = (Table*)h;
  int64_t n = 0;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> g(s.mtx);
    n += s.n_disk;
  }
  return n;
}

// Batch pull: rows for keys[i] -> out[i*width .. ], creating (with
// deterministic init) or promoting from disk as needed. Returns 0, or
// negative on IO error.
int pbx_table_pull_or_create(void* h, const uint64_t* keys, int64_t n,
                             float* out) {
  Table* t = (Table*)h;
  return for_shards(t, keys, n, [&](int si, const int64_t* idx, int64_t m) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    // reserve for the worst case (every key new) upfront: one rehash
    // instead of ~log2(m) incremental doublings on first-pass creates
    while ((s->mask + 1) * 7 < (uint64_t)(s->n_used + m + 1) * 10)
      shard_grow_hash(s);
    // pass-finalize pattern: a pass's working set promotes MANY disk rows
    // at once — read them in file-offset order (sequential-ish IO, no
    // per-read seek-to-end) instead of key order. Skipped when the disk
    // tier is tiny: the extra O(m) probe pass would cost more than the few
    // inline promotes the main loop handles anyway.
    if (s->n_disk >= 64) {
      std::vector<std::pair<int64_t, uint64_t>> hits;  // (offset, key)
      for (int64_t q = 0; q < m; ++q) {
        bool found;
        uint64_t j = shard_find(s, keys[idx[q]], &found);
        if (found && s->hstate[j] == kDisk)
          hits.emplace_back(s->hval[j], s->hkeys[j]);
      }
      std::sort(hits.begin(), hits.end());
      for (auto& hit : hits) {
        bool found;
        uint64_t j = shard_find(s, hit.second, &found);
        if (!found || s->hstate[j] != kDisk) continue;
        int64_t r = promote(t, s, j, /*seek_end=*/false);
        if (r == -2) return -2;  // IO error (-1 lazily shrunk: main loop
                                 // recreates the key fresh below)
      }
      if (!hits.empty()) fseeko(s->spill, 0, SEEK_END);
    }
    for (int64_t q = 0; q < m; ++q) {
      int64_t i = idx[q];
      uint64_t key = keys[i];
      bool found;
      uint64_t j = shard_find(s, key, &found);
      int64_t row;
      if (!found) {
        row = shard_new_row(t, s, key);
        init_row(t, key, &s->values[row * t->width]);
        s->hkeys[j] = key;
        s->hval[j] = row;
        s->hstate[j] = kMem;
        s->n_used++;
      } else if (s->hstate[j] == kDisk) {
        row = promote(t, s, j);
        if (row == -2) return -2;
        if (row == -1) {  // lazily shrunk: recreate fresh
          shard_maybe_grow(s);
          bool f2;
          j = shard_find(s, key, &f2);
          row = shard_new_row(t, s, key);
          init_row(t, key, &s->values[row * t->width]);
          s->hkeys[j] = key;
          s->hval[j] = row;
          s->hstate[j] = kMem;
          s->n_used++;
        }
      } else {
        row = s->hval[j];
      }
      s->row_epoch[row] = t->epoch;  // a pull is a touch (recency signal)
      std::memcpy(out + i * t->width, &s->values[row * t->width],
                  sizeof(float) * t->width);
    }
    return 0;
  });
}

namespace {

// One shard's slice of a push batch. Caller dispatch holds nothing; the
// shard lock is taken here. Shared by pbx_table_push (auto thread
// heuristic) and pbx_table_push_mt (explicit writer pool).
int push_shard_batch(Table* t, int si, const uint64_t* keys,
                     const float* rows, const int64_t* idx, int64_t m) {
  Shard* s = &t->shards[si];
  std::lock_guard<std::mutex> g(s->mtx);
  while ((s->mask + 1) * 7 < (uint64_t)(s->n_used + m + 1) * 10)
    shard_grow_hash(s);
  // disk-resident keys in this batch are fully overwritten below — only
  // the header's touched bit matters. Read those headers in file-offset
  // order (one sequential sweep, same trick as the batched promote in
  // pull) instead of an fseeko pair per superseded record. The reads are
  // double-buffered: a reader thread freads chunk k+1's headers while
  // this thread applies chunk k's hash/counter updates (the apply side
  // never touches the FILE*, so the handoff is the only sync point).
  if (s->n_disk >= 64) {
    std::vector<std::pair<int64_t, uint64_t>> hits;  // (offset, key)
    for (int64_t q = 0; q < m; ++q) {
      bool found;
      uint64_t j = shard_find(s, keys[idx[q]], &found);
      if (found && s->hstate[j] == kDisk)
        hits.emplace_back(s->hval[j], s->hkeys[j]);
    }
    std::sort(hits.begin(), hits.end());
    const int64_t nh = (int64_t)hits.size();
    const int64_t chunk = 512;
    auto read_chunk = [&](int64_t lo, int64_t hi,
                          std::vector<SpillRec>* out) -> int {
      out->resize((size_t)(hi - lo));
      int64_t t0 = now_ns();
      for (int64_t i = lo; i < hi; ++i) {
        fseeko(s->spill, hits[i].first, SEEK_SET);
        if (fread(&(*out)[i - lo], sizeof(SpillRec), 1, s->spill) != 1) {
          t->io.prepass_read_ns += now_ns() - t0;
          return -2;
        }
      }
      t->io.prepass_read_ns += now_ns() - t0;
      return 0;
    };
    auto apply_chunk = [&](int64_t lo, int64_t hi,
                           const std::vector<SpillRec>& recs) {
      for (int64_t i = lo; i < hi; ++i) {
        bool found;
        uint64_t j = shard_find(s, hits[i].second, &found);
        if (!found || s->hstate[j] != kDisk) continue;  // dup in batch
        if (recs[i - lo].touched) s->n_disk_touched--;
        s->n_disk--;
        s->dead_disk++;  // the superseded on-disk record is garbage now
        // row contents stay undefined until the main loop's memcpy — every
        // pre-pass key is in this batch, so each gets overwritten below
        int64_t row = shard_new_row(t, s, hits[i].second);
        s->hval[j] = row;
        s->hstate[j] = kMem;
      }
    };
    if (nh <= 2 * chunk) {
      std::vector<SpillRec> recs;
      if (nh > 0) {
        if (read_chunk(0, nh, &recs) != 0) return -2;
        apply_chunk(0, nh, recs);
      }
    } else {
      std::vector<SpillRec> bufs[2];
      int rerr = read_chunk(0, chunk, &bufs[0]);
      int cur = 0;
      for (int64_t lo = 0; lo < nh; lo += chunk) {
        if (rerr != 0) return -2;
        int64_t hi = std::min(nh, lo + chunk);
        int64_t nlo = hi, nhi = std::min(nh, hi + chunk);
        std::thread reader;
        if (nlo < nhi)
          reader = std::thread(
              [&, nlo, nhi, cur] { rerr = read_chunk(nlo, nhi, &bufs[cur ^ 1]); });
        apply_chunk(lo, hi, bufs[cur]);
        if (reader.joinable()) reader.join();
        cur ^= 1;
      }
    }
    if (nh > 0) fseeko(s->spill, 0, SEEK_END);
  }
  for (int64_t q = 0; q < m; ++q) {
    int64_t i = idx[q];
    uint64_t key = keys[i];
    bool found;
    uint64_t j = shard_find(s, key, &found);
    int64_t row;
    if (!found) {
      row = shard_new_row(t, s, key);
      s->hkeys[j] = key;
      s->hval[j] = row;
      s->hstate[j] = kMem;
      s->n_used++;
    } else if (s->hstate[j] == kDisk) {
      // full-row overwrite: only the header's touched bit matters
      SpillRec rec;
      fseeko(s->spill, s->hval[j], SEEK_SET);
      if (fread(&rec, sizeof(rec), 1, s->spill) != 1) return -2;
      fseeko(s->spill, 0, SEEK_END);
      if (rec.touched) s->n_disk_touched--;
      s->n_disk--;
      s->dead_disk++;  // the superseded on-disk record is garbage now
      row = shard_new_row(t, s, key);
      s->hval[j] = row;
      s->hstate[j] = kMem;
    } else {
      row = s->hval[j];
    }
    std::memcpy(&s->values[row * t->width], rows + i * t->width,
                sizeof(float) * t->width);
    s->row_touched[row] = 1;
    s->row_epoch[row] = t->epoch;  // a push is a touch
  }
  return 0;
}

}  // namespace

// Batch push (upsert full rows) + mark touched. Returns 0 or negative.
int pbx_table_push(void* h, const uint64_t* keys, const float* rows,
                   int64_t n) {
  Table* t = (Table*)h;
  return for_shards(t, keys, n, [&](int si, const int64_t* idx, int64_t m) {
    return push_shard_batch(t, si, keys, rows, idx, m);
  });
}

// Batch push with an explicit writer pool: `threads` <= 0 = auto heuristic
// (identical to pbx_table_push), 1 = forced serial, else a fixed pool of
// min(threads, n_shards) workers each owning a disjoint strided shard set.
// Bitwise-equal to pbx_table_push at every thread count (see for_shards_ex).
// `shard_ns`, when non-null, receives per-shard wall nanoseconds (length
// n_shards) — the per-shard histogram feed. Returns 0 or negative.
int pbx_table_push_mt(void* h, const uint64_t* keys, const float* rows,
                      int64_t n, int threads, int64_t* shard_ns) {
  Table* t = (Table*)h;
  return for_shards_ex(t, keys, n, threads, shard_ns,
                       [&](int si, const int64_t* idx, int64_t m) {
                         return push_shard_batch(t, si, keys, rows, idx, m);
                       });
}

// Cumulative IO-overlap telemetry, 5 int64 slots:
//   [spill_gather_ns, spill_fwrite_ns, prepass_read_ns, stage_flushes,
//    stage_bytes]
void pbx_table_io_stats(void* h, int64_t* out) {
  Table* t = (Table*)h;
  out[0] = t->io.spill_gather_ns.load();
  out[1] = t->io.spill_fwrite_ns.load();
  out[2] = t->io.prepass_read_ns.load();
  out[3] = t->io.stage_flushes.load();
  out[4] = t->io.stage_bytes.load();
}

// Pass-boundary decay + shrink over the MEM tier (disk rows catch up
// lazily at promotion). Returns number of mem rows dropped.
int64_t pbx_table_decay_shrink(void* h, float decay, float threshold) {
  Table* t = (Table*)h;
  t->epoch++;
  t->last_decay = decay;
  t->last_threshold = threshold;
  int64_t dropped = 0;
  std::mutex dm;
  int nt = (int)std::thread::hardware_concurrency();
  if (nt > t->n_shards) nt = t->n_shards;
  if (nt > 16) nt = 16;
  if (nt < 1) nt = 1;
  auto work = [&](int w) {
    int64_t local = 0;
    for (int si = w; si < t->n_shards; si += nt) {
      Shard* s = &t->shards[si];
      std::lock_guard<std::mutex> g(s->mtx);
      // decay all rows; collect keep mask
      int64_t keep = 0;
      std::vector<int64_t> remap(s->n_rows, -1);
      for (int64_t r = 0; r < s->n_rows; ++r) {
        float* v = &s->values[r * t->width];
        v[t->show_col] *= decay;
        v[t->clk_col] *= decay;
        if (v[t->show_col] >= threshold) remap[r] = keep++;
      }
      if (keep == s->n_rows) continue;
      local += s->n_rows - keep;
      // compact rows in place (remap is monotone)
      for (int64_t r = 0; r < s->n_rows; ++r) {
        int64_t nr = remap[r];
        if (nr < 0 || nr == r) continue;
        std::memcpy(&s->values[nr * t->width], &s->values[r * t->width],
                    sizeof(float) * t->width);
        s->row_key[nr] = s->row_key[r];
        s->row_touched[nr] = s->row_touched[r];
        s->row_epoch[nr] = s->row_epoch[r];
      }
      s->n_rows = keep;
      // rebuild the hash from scratch: survivors remapped, disk entries
      // carried over, dropped rows simply not reinserted (O(cap), no
      // probe-chain deletion subtleties)
      std::vector<uint64_t> ok;
      std::vector<int64_t> ov;
      std::vector<uint8_t> os;
      ok.swap(s->hkeys);
      ov.swap(s->hval);
      os.swap(s->hstate);
      uint64_t omask = s->mask;
      s->mask = 0;
      s->n_used = 0;
      shard_grow_hash(s);
      while ((s->mask + 1) * 7 < (uint64_t)(keep + s->n_disk) * 10)
        shard_grow_hash(s);
      for (uint64_t j = 0; j <= omask && omask; ++j) {
        if (os[j] == kEmpty) continue;
        int64_t v = os[j] == kMem ? remap[ov[j]] : ov[j];
        if (os[j] == kMem && v < 0) continue;  // dropped
        bool f;
        uint64_t slot = shard_find(s, ok[j], &f);
        s->hkeys[slot] = ok[j];
        s->hval[slot] = v;
        s->hstate[slot] = os[j];
        s->n_used++;
      }
    }
    std::lock_guard<std::mutex> g(dm);
    dropped += local;
  };
  if (nt == 1) {
    work(0);
  } else {
    std::vector<std::thread> th;
    for (int w = 0; w < nt; ++w) th.emplace_back(work, w);
    for (auto& x : th) x.join();
  }
  return dropped;
}

// Spill cold mem rows to the shard disk files until total mem rows <=
// max_mem_rows, with the touched bit preserved in the on-disk record so
// delta saves stay exact. Victim selection by policy: kSpillFifo keeps the
// legacy creation-order sweep (untouched rows first); kSpillFreq ranks by
// coldness — admission-threshold rows disk-first, then lowest decayed
// show / oldest last-touched epoch, with rows at/above pin_show spilled
// only when no colder victim remains, and the sweep apportioned across
// shards in proportion to occupancy. Returns rows spilled, or negative if
// spill is disabled (-1) / IO fails (-2).
int64_t pbx_table_spill_cold_ex(void* h, int64_t max_mem_rows, int policy,
                                float pin_show, float admit_show) {
  return spill_cold_impl((Table*)h, max_mem_rows, policy, pin_show,
                         admit_show);
}

// Legacy entry point: creation-order (fifo) sweep, no thresholds.
int64_t pbx_table_spill_cold(void* h, int64_t max_mem_rows) {
  return spill_cold_impl((Table*)h, max_mem_rows, kSpillFifo, 0.0f, 0.0f);
}

// Per-shard tier stats, 8 int64 slots per shard:
//   [mem_rows, disk_rows, spilled_total, promoted_total,
//    admit_spilled_total, lazy_shrunk_total, dead_records,
//    spill_file_bytes]
// `out` must hold n_shards * 8 entries. Returns n_shards.
int64_t pbx_table_tier_stats(void* h, int64_t* out) {
  Table* t = (Table*)h;
  for (int si = 0; si < t->n_shards; ++si) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    int64_t bytes = 0;
    if (s->spill) {
      fflush(s->spill);
      off_t cur = ftello(s->spill);
      fseeko(s->spill, 0, SEEK_END);
      bytes = (int64_t)ftello(s->spill);
      fseeko(s->spill, cur, SEEK_SET);
    }
    int64_t* o = out + (int64_t)si * 8;
    o[0] = s->n_used - s->n_disk;
    o[1] = s->n_disk;
    o[2] = s->n_spilled;
    o[3] = s->n_promoted;
    o[4] = s->n_admit_spilled;
    o[5] = s->n_lazy_shrunk;
    o[6] = s->dead_disk;
    o[7] = bytes;
  }
  return t->n_shards;
}

// Force-compact every shard's spill file that holds any dead records.
// Returns live records kept across all shards, or negative on IO error.
int64_t pbx_table_compact_spill(void* h) {
  Table* t = (Table*)h;
  if (t->spill_dir.empty()) return -1;
  int64_t live = 0;
  for (int si = 0; si < t->n_shards; ++si) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    if (!s->spill || s->dead_disk == 0) {
      live += s->n_disk;
      continue;
    }
    int64_t r = compact_spill(t, s);
    if (r < 0) return r;
    live += r;
  }
  return live;
}

// Spill-tier occupancy: live records, dead (reclaimable) records, and the
// total on-disk bytes across shard files.
void pbx_table_spill_stats(void* h, int64_t* live, int64_t* dead,
                           int64_t* bytes) {
  Table* t = (Table*)h;
  int64_t l = 0, d = 0, b = 0;
  for (int si = 0; si < t->n_shards; ++si) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    l += s->n_disk;
    d += s->dead_disk;
    if (s->spill) {
      fflush(s->spill);
      off_t cur = ftello(s->spill);
      fseeko(s->spill, 0, SEEK_END);
      b += (int64_t)ftello(s->spill);
      fseeko(s->spill, cur, SEEK_SET);
    }
  }
  *live = l;
  *dead = d;
  *bytes = b;
}

// Export only the SHOW column of one shard (cache-threshold scans): at
// most `cap` floats are written (the caller sized the buffer from
// snapshot_count; a concurrent push between the two calls must clamp, not
// overrun). Disk rows get catch-up decay. Returns floats written, or
// negative on IO error.
int64_t pbx_table_shard_shows(void* h, int shard, float* out, int64_t cap) {
  Table* t = (Table*)h;
  Shard* s = &t->shards[shard];
  std::lock_guard<std::mutex> g(s->mtx);
  int64_t n = 0;
  for (int64_t r = 0; r < s->n_rows && n < cap; ++r)
    out[n++] = s->values[r * t->width + t->show_col];
  if (s->n_disk > 0 && s->spill) {
    // batched sequential read: visit records in file-offset order (the
    // caller only wants the show distribution, so order is free) instead
    // of a random seek per hash slot — at scale the cache_threshold scan
    // was dominating pass-end time
    std::vector<int64_t> offs;
    offs.reserve((size_t)s->n_disk);
    for (uint64_t j = 0; j <= s->mask && s->mask; ++j)
      if (s->hstate[j] == kDisk) offs.push_back(s->hval[j]);
    std::sort(offs.begin(), offs.end());
    SpillRec rec;
    float show;
    for (int64_t off : offs) {
      if (n >= cap) break;
      fseeko(s->spill, off, SEEK_SET);
      if (fread(&rec, sizeof(rec), 1, s->spill) != 1 ||
          fseeko(s->spill, t->show_col * (off_t)sizeof(float), SEEK_CUR) != 0 ||
          fread(&show, sizeof(float), 1, s->spill) != 1)
        return -2;
      int64_t missed = t->epoch - rec.epoch;
      if (missed > 0 && t->last_decay < 1.0f)
        for (int64_t i = 0; i < missed; ++i) show *= t->last_decay;
      out[n++] = show;
    }
    fseeko(s->spill, 0, SEEK_END);
  }
  return n;
}

// Read-only show peek for a key batch: out[i] = the decayed show of keys[i]
// if it is resident on the MEM tier, else 0 (disk rows and absent keys both
// read cold). No creation, no promotion, no touch, no decay catch-up — this
// feeds the adaptive-ICI-wire hotness bit, which must never perturb tier
// state (spill policy only evicts cold rows, so a hot key reading 0 from
// disk just rides the int8 region until its next pull — the same graceful
// degrade as hot-fraction overflow).
int pbx_table_shows_peek(void* h, const uint64_t* keys, int64_t n, float* out) {
  Table* t = (Table*)h;
  return for_shards(t, keys, n, [&](int si, const int64_t* idx, int64_t m) {
    Shard* s = &t->shards[si];
    std::lock_guard<std::mutex> g(s->mtx);
    for (int64_t q = 0; q < m; ++q) {
      int64_t i = idx[q];
      float show = 0.0f;
      if (s->mask) {  // shard_find on an empty hash would scan forever
        bool found;
        uint64_t j = shard_find(s, keys[i], &found);
        if (found && s->hstate[j] == kMem)
          show = s->values[s->hval[j] * t->width + t->show_col];
      }
      out[i] = show;
    }
    return 0;
  });
}

// Export one shard's keys (mem + disk — all live in the hash, no file
// reads). At most `cap` keys written; returns the count.
int64_t pbx_table_shard_keys(void* h, int shard, uint64_t* out, int64_t cap) {
  Table* t = (Table*)h;
  Shard* s = &t->shards[shard];
  std::lock_guard<std::mutex> g(s->mtx);
  int64_t n = 0;
  for (uint64_t j = 0; j <= s->mask && s->mask && n < cap; ++j)
    if (s->hstate[j] != kEmpty) out[n++] = s->hkeys[j];
  return n;
}

// Drop all touched flags (after a load, which arrives via push).
void pbx_table_clear_touched(void* h) {
  Table* t = (Table*)h;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> g(s.mtx);
    for (int64_t r = 0; r < s.n_rows; ++r) s.row_touched[r] = 0;
    // disk rows: touched bits live in the file; a load never spills, so
    // n_disk_touched entries (if any) are rewritten lazily at next
    // snapshot — clear the counter's view by scanning only if needed
    if (s.n_disk_touched > 0 && s.spill) {
      for (uint64_t j = 0; j <= s.mask && s.mask; ++j) {
        if (s.hstate[j] != kDisk) continue;
        SpillRec rec;
        fseeko(s.spill, s.hval[j], SEEK_SET);
        if (fread(&rec, sizeof(rec), 1, s.spill) != 1) break;
        if (rec.touched) {
          rec.touched = 0;
          fseeko(s.spill, s.hval[j], SEEK_SET);
          fwrite(&rec, sizeof(rec), 1, s.spill);
          if (--s.n_disk_touched == 0) break;
        }
      }
      fflush(s.spill);
      fseeko(s.spill, 0, SEEK_END);
    }
  }
}

// Snapshot item count for one shard: touched rows (mem + disk) when
// only_touched, everything otherwise.
int64_t pbx_table_snapshot_count(void* h, int shard, int only_touched) {
  Table* t = (Table*)h;
  Shard* s = &t->shards[shard];
  std::lock_guard<std::mutex> g(s->mtx);
  if (only_touched) {
    int64_t n = s->n_disk_touched;
    for (int64_t r = 0; r < s->n_rows; ++r) n += s->row_touched[r] ? 1 : 0;
    return n;
  }
  return s->n_used;
}

// Fill keys_out / vals_out (caller-sized via snapshot_count with the same
// only_touched under no concurrent mutation). Disk rows are read back with
// catch-up decay applied so a base save reflects current semantics; with
// clear_touched the on-disk header's touched bit is rewritten in place.
// Returns count written, or negative on IO error.
int64_t pbx_table_snapshot(void* h, int shard, int only_touched,
                           int clear_touched, uint64_t* keys_out,
                           float* vals_out) {
  Table* t = (Table*)h;
  Shard* s = &t->shards[shard];
  std::lock_guard<std::mutex> g(s->mtx);
  int64_t n = 0;
  for (int64_t r = 0; r < s->n_rows; ++r) {
    if (only_touched && !s->row_touched[r]) continue;
    keys_out[n] = s->row_key[r];
    std::memcpy(vals_out + n * t->width, &s->values[r * t->width],
                sizeof(float) * t->width);
    n++;
    if (clear_touched) s->row_touched[r] = 0;
  }
  bool scan_disk =
      s->spill && (only_touched ? s->n_disk_touched > 0 : s->n_disk > 0);
  if (scan_disk) {
    // offset-ordered scan (sequential IO, same trick as batched promote);
    // disk rows land in the snapshot in file order, which no caller
    // depends on — loads replay records through push, order-insensitive
    std::vector<std::pair<int64_t, uint64_t>> drecs;  // (offset, hash slot)
    for (uint64_t j = 0; j <= s->mask && s->mask; ++j)
      if (s->hstate[j] == kDisk) drecs.push_back({s->hval[j], j});
    std::sort(drecs.begin(), drecs.end());
    std::vector<float> buf(t->width);
    for (auto& dr : drecs) {
      SpillRec rec;
      fseeko(s->spill, dr.first, SEEK_SET);
      if (fread(&rec, sizeof(rec), 1, s->spill) != 1 ||
          fread(buf.data(), sizeof(float), t->width, s->spill) !=
              (size_t)t->width)
        return -2;
      if (only_touched && !rec.touched) continue;
      int64_t missed = t->epoch - rec.epoch;
      if (missed > 0 && t->last_decay < 1.0f) {
        // sequential multiplies: bitwise parity with the mem-tier decay
        for (int64_t i = 0; i < missed; ++i) {
          buf[t->show_col] *= t->last_decay;
          buf[t->clk_col] *= t->last_decay;
        }
      }
      keys_out[n] = s->hkeys[dr.second];
      std::memcpy(vals_out + n * t->width, buf.data(),
                  sizeof(float) * t->width);
      n++;
      if (clear_touched && rec.touched) {
        rec.touched = 0;
        fseeko(s->spill, dr.first, SEEK_SET);
        if (fwrite(&rec, sizeof(rec), 1, s->spill) != 1) return -2;
        s->n_disk_touched--;
      }
    }
    fflush(s->spill);
    fseeko(s->spill, 0, SEEK_END);
  }
  return n;
}

}  // extern "C"
