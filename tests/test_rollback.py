"""Confirm/revert pass rollback (FleetWrapper::Confirm/Revert parity,
fleet_wrapper.h:319-321, pslib __init__.py:673-690).

The done-criterion scenario: a pass dies mid-way (possibly after a partial
or even full writeback), is reverted, and retraining the same data then
produces EXACTLY the state a never-interrupted run produces."""

import jax
import numpy as np
import optax
import pytest

from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
from paddlebox_tpu.train.rollback import PassGuard

LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
NS, B = 4, 16


def _write(tmp_path, n=96):
    rng = np.random.default_rng(5)
    path = tmp_path / "d.txt"
    with open(path, "w") as f:
        for _ in range(n):
            keys = rng.integers(1, 400, NS)
            f.write(
                f"1 {int(keys[0]) % 2}.0 "
                + " ".join(f"1 {k}" for k in keys) + "\n"
            )
    return str(path)


def _build(path):
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    ds = BoxPSDataset(schema, table, batch_size=B, seed=0)
    ds.set_filelist([path])
    model = DeepFM(num_slots=NS, feat_width=LAYOUT.pull_width,
                   embedx_dim=4, hidden=(8,))
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=LAYOUT, sparse_opt=OPT, auc_buckets=500
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    return table, ds, tr


def _full_pass(ds, tr):
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    tr.train_pass(ds)
    ds.end_pass(tr.trained_table(), shrink=False)


def test_kill_mid_pass_revert_retrain_equals_never_started(tmp_path):
    path = _write(tmp_path)

    # reference run: one clean uninterrupted pass
    table_ref, ds_ref, tr_ref = _build(path)
    _full_pass(ds_ref, tr_ref)
    keys_ref = np.sort(table_ref.keys())
    vals_ref = table_ref.pull_or_create(keys_ref)

    # interrupted run: train half the pass, partially write back (the worst
    # crash window), revert, then retrain from scratch
    table, ds, tr = _build(path)
    ds.load_into_memory()
    ds.begin_pass(round_to=64, enable_revert=True, trainer=tr)
    pre_keys = ds.ws.sorted_keys.copy()
    pre_vals = table.pull_or_create(pre_keys).copy()
    tr.train_pass(ds, n_batches=3)
    ds.ws.writeback(tr.trained_table())  # partial pass PUBLISHED, then dies

    assert not np.allclose(table.pull_or_create(pre_keys), pre_vals)
    ds.revert_pass()
    np.testing.assert_array_equal(table.pull_or_create(pre_keys), pre_vals)

    # trainer dense side restored to init: retrain == never-started
    tr._packer_cache = None
    ds.begin_pass(round_to=64)
    tr.train_pass(ds)
    ds.end_pass(tr.trained_table(), shrink=False)
    keys = np.sort(table.keys())
    np.testing.assert_array_equal(keys, keys_ref)
    np.testing.assert_allclose(
        table.pull_or_create(keys), vals_ref, rtol=1e-6, atol=1e-7
    )
    for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(tr_ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_end_pass_confirms_and_revert_requires_arming(tmp_path):
    path = _write(tmp_path, n=32)
    table, ds, tr = _build(path)
    ds.load_into_memory()
    ds.begin_pass(round_to=64, enable_revert=True, trainer=tr)
    tr.train_pass(ds)
    ds.end_pass(tr.trained_table(), shrink=False)
    # confirmed at end_pass: nothing left to revert
    with pytest.raises(RuntimeError, match="revert"):
        ds.revert_pass()
    # and without arming, revert is rejected up front
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    with pytest.raises(RuntimeError, match="enable_revert"):
        ds.revert_pass()


def test_pass_guard_standalone_surface():
    """Confirm/Revert as a bare table-level API (no dataset)."""
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    keys = np.arange(1, 50, dtype=np.uint64)
    base = table.pull_or_create(keys).copy()
    guard = PassGuard(table)
    guard.begin(keys)
    table.push(keys, base + 7.0)
    guard.revert()
    np.testing.assert_array_equal(table.pull_or_create(keys), base)
    guard.begin(keys)
    table.push(keys, base + 3.0)
    guard.confirm()
    with pytest.raises(RuntimeError):
        guard.revert()
    np.testing.assert_allclose(table.pull_or_create(keys), base + 3.0)
