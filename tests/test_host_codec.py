"""Host-wire codec unit coverage (ops/host_codec.py).

Round-trip exactness and malformed-input rejection for all three codecs —
delta+varint sorted-u64 key streams, narrow-int row ids, chunked zlib
frames — plus the self-describing key-stream wrapper the working-set
exchange ships. Edge cases named by the issue: empty stream, single key,
max-gap uint64 deltas, non-monotonic rejection, truncated/bit-flipped
compressed frames.
"""

from __future__ import annotations

import numpy as np
import pytest

from paddlebox_tpu.ops import host_codec as hc
from paddlebox_tpu.ops.host_codec import HostCodecError


# ---------------------------------------------------------------------------
# sorted-u64 delta+varint
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [0, 1, 2, 7, 1000, 50_000])
def test_sorted_u64_roundtrip_exact(n):
    rng = np.random.default_rng(n)
    keys = np.unique(rng.integers(0, 2**63, n).astype(np.uint64))
    out = hc.decode_sorted_u64(hc.encode_sorted_u64(keys))
    assert out.dtype == np.uint64
    np.testing.assert_array_equal(out, keys)


def test_single_key_and_empty_stream():
    assert len(hc.decode_sorted_u64(hc.encode_sorted_u64(np.zeros(0, np.uint64)))) == 0
    one = np.array([2**64 - 1], np.uint64)
    np.testing.assert_array_equal(
        hc.decode_sorted_u64(hc.encode_sorted_u64(one)), one
    )


def test_max_gap_uint64_deltas():
    """The widest representable gaps: 0 -> 2^64-1 is a 10-byte varint."""
    keys = np.array([0, 1, 2**63, 2**64 - 1], np.uint64)
    enc = hc.encode_sorted_u64(keys)
    np.testing.assert_array_equal(hc.decode_sorted_u64(enc), keys)


def test_duplicate_keys_roundtrip():
    """Non-decreasing (not strictly increasing) streams are legal."""
    keys = np.array([5, 5, 5, 9, 9], np.uint64)
    np.testing.assert_array_equal(
        hc.decode_sorted_u64(hc.encode_sorted_u64(keys)), keys
    )


def test_dense_keyspace_compresses_hard():
    """The CTR shape the codec exists for: dense sign spaces land near
    1 byte/key, an ~8x cut vs raw uint64."""
    rng = np.random.default_rng(0)
    keys = np.unique(rng.integers(0, 10**6, 100_000).astype(np.uint64))
    enc = hc.encode_sorted_u64(keys)
    assert keys.nbytes / len(enc) > 4.0


def test_non_monotonic_input_rejected():
    with pytest.raises(HostCodecError):
        hc.encode_sorted_u64(np.array([7, 3], np.uint64))


def test_truncated_stream_rejected():
    keys = np.unique(np.random.default_rng(1).integers(0, 10**9, 500).astype(np.uint64))
    enc = hc.encode_sorted_u64(keys)
    for cut in (len(enc) - 1, len(enc) // 2, hc._U64_HDR.size - 1, 0):
        with pytest.raises(HostCodecError):
            hc.decode_sorted_u64(enc[:cut])


def test_count_lie_rejected():
    """A header claiming more values than the varint stream terminates."""
    keys = np.arange(10, dtype=np.uint64)
    enc = bytearray(hc.encode_sorted_u64(keys))
    enc[:8] = hc._U64_HDR.pack(11)
    with pytest.raises(HostCodecError):
        hc.decode_sorted_u64(bytes(enc))


def test_overlong_varint_rejected():
    """11 continuation bytes can never be a uint64."""
    bad = hc._U64_HDR.pack(1) + b"\x80" * 11 + b"\x00"
    with pytest.raises(HostCodecError):
        hc.decode_sorted_u64(bad)


def test_uint64_overflow_rejected():
    """A 10th varint byte above 1 overflows 64 bits — and a delta stream
    whose cumsum wraps is corrupt, not a key set."""
    bad = hc._U64_HDR.pack(1) + b"\xff" * 9 + b"\x7f"
    with pytest.raises(HostCodecError):
        hc.decode_sorted_u64(bad)
    # two max-value deltas wrap the cumsum
    wrap = (
        hc._U64_HDR.pack(2)
        + hc._varint_encode(np.array([2**64 - 1, 2**64 - 1], np.uint64)).tobytes()
    )
    with pytest.raises(HostCodecError):
        hc.decode_sorted_u64(wrap)


# ---------------------------------------------------------------------------
# key-stream wrapper (marker byte: raw ablation interoperates with codec)
# ---------------------------------------------------------------------------

def test_key_stream_wrapper_both_markers():
    keys = np.unique(np.random.default_rng(2).integers(0, 10**7, 3000).astype(np.uint64))
    for codec in (True, False):
        enc = hc.encode_key_stream(keys, codec)
        np.testing.assert_array_equal(hc.decode_key_stream(enc), keys)
    assert len(hc.encode_key_stream(keys, True)) < len(
        hc.encode_key_stream(keys, False)
    )


def test_key_stream_wrapper_rejects_garbage():
    with pytest.raises(HostCodecError):
        hc.decode_key_stream(b"")
    with pytest.raises(HostCodecError):
        hc.decode_key_stream(bytes([99]) + b"whatever")
    # raw marker with a non-multiple-of-8 body
    with pytest.raises(HostCodecError):
        hc.decode_key_stream(bytes([hc.KEYS_RAW]) + b"12345")


# ---------------------------------------------------------------------------
# narrow-int row ids
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "bound,width",
    [(200, 1), (65_535, 2), (65_536, 4), (2**32 - 1, 4), (2**32, 8)],
)
def test_row_ids_narrowest_width(bound, width):
    rng = np.random.default_rng(bound % 97)
    rows = rng.integers(0, bound + 1, 257).astype(np.int64)
    enc = hc.encode_row_ids(rows, bound)
    assert len(enc) == hc._ROW_HDR.size + width * len(rows)
    out = hc.decode_row_ids(enc)
    assert out.dtype == np.int64
    np.testing.assert_array_equal(out, rows)


def test_row_ids_empty_roundtrip():
    enc = hc.encode_row_ids(np.zeros(0, np.int64), 1000)
    assert len(hc.decode_row_ids(enc)) == 0


def test_row_ids_overflow_asserts():
    with pytest.raises(HostCodecError):
        hc.encode_row_ids(np.array([70_000], np.int64), 65_535)
    with pytest.raises(HostCodecError):
        hc.encode_row_ids(np.array([-1], np.int64), 65_535)


def test_row_ids_malformed_rejected():
    enc = hc.encode_row_ids(np.arange(10, dtype=np.int64), 1000)
    with pytest.raises(HostCodecError):
        hc.decode_row_ids(enc[:-1])  # truncated body
    with pytest.raises(HostCodecError):
        hc.decode_row_ids(enc[: hc._ROW_HDR.size - 1])  # truncated header
    bad = bytearray(enc)
    bad[0] = 3  # width not in {1,2,4,8}
    with pytest.raises(HostCodecError):
        hc.decode_row_ids(bytes(bad))


# ---------------------------------------------------------------------------
# chunked zlib frames
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("size", [0, 1, 511, 4096, 3_000_000])
def test_chunked_zlib_roundtrip(size):
    rng = np.random.default_rng(size % 101)
    blob = bytes(rng.integers(0, 8, size, dtype=np.uint8))
    enc = hc.compress_chunked(blob, level=1)
    assert hc.decompress_chunked(enc) == blob


def test_chunked_zlib_multi_chunk_bounded():
    """chunk_bytes bounds each inflate; a 10-chunk frame round-trips."""
    blob = b"paddlebox" * 5000
    enc = hc.compress_chunked(blob, level=1, chunk_bytes=len(blob) // 10 + 1)
    assert hc.decompress_chunked(enc) == blob


def test_chunked_zlib_truncation_rejected():
    enc = hc.compress_chunked(b"hello world" * 500, level=1)
    for cut in (len(enc) - 2, hc._ZFRAME_HDR.size + 1, 3):
        with pytest.raises(HostCodecError):
            hc.decompress_chunked(enc[:cut])


def test_chunked_zlib_bitflip_rejected():
    enc = bytearray(hc.compress_chunked(b"hello world" * 500, level=1))
    enc[hc._ZFRAME_HDR.size + 6] ^= 0xFF  # inside the deflate stream
    with pytest.raises(HostCodecError):
        hc.decompress_chunked(bytes(enc))


def test_chunked_zlib_length_lie_rejected():
    """A header that lies about the raw length is caught, not trusted."""
    blob = b"x" * 1000
    enc = bytearray(hc.compress_chunked(blob, level=1))
    enc[: hc._ZFRAME_HDR.size] = hc._ZFRAME_HDR.pack(
        999, hc.DEFAULT_CHUNK_BYTES, 1
    )
    with pytest.raises(HostCodecError):
        hc.decompress_chunked(bytes(enc))
