"""Tests for the utils tier: fs dispatch, line readers, timers, stats, dumps,
trace (reference behaviors: io/fs.cc pipe dispatch, data_feed.cc:57 sampling,
platform/{timer,monitor,profiler}, DumpWork part files)."""

import json
import os

import numpy as np
import pytest

from paddlebox_tpu.utils import fs as pfs
from paddlebox_tpu.utils.dump import DumpWorkerPool, dump_fields, dump_param
from paddlebox_tpu.utils.line_reader import BufferedLineFileReader, LineFileReader
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_GET, STAT_RESET, all_stats
from paddlebox_tpu.utils.timer import STAGE_TIMERS, Timer, TimerRegistry
from paddlebox_tpu.utils.trace import PROFILER


def test_fs_local_roundtrip(tmp_path):
    p = str(tmp_path / "sub" / "a.txt")
    with pfs.fs_open_write(p) as f:
        f.write("hello\nworld\n")
    with pfs.fs_open_read(p) as f:
        assert f.read() == "hello\nworld\n"
    assert pfs.fs_exists(p)
    pfs.fs_remove(p)
    assert not pfs.fs_exists(p)


def test_fs_gz_and_converter(tmp_path):
    p = str(tmp_path / "a.gz")
    with pfs.fs_open_write(p) as f:
        f.write("line1\nline2\n")
    with pfs.fs_open_read(p) as f:
        assert f.read().splitlines() == ["line1", "line2"]
    # converter command spliced into the read pipe (fs converter parity)
    with pfs.fs_open_read(p, converter="tr a-z A-Z") as f:
        assert f.read().splitlines() == ["LINE1", "LINE2"]


def test_fs_converter_failure_raises(tmp_path):
    p = str(tmp_path / "a.txt")
    with pfs.fs_open_write(p) as f:
        f.write("x\n")
    with pytest.raises(RuntimeError):
        with pfs.fs_open_read(p, converter="false") as f:
            f.read()


def test_filemgr(tmp_path):
    mgr = pfs.FileMgr()
    d = str(tmp_path / "dir")
    mgr.mkdir(d)
    for name in ("p1", "p2"):
        mgr.touch(os.path.join(d, name))
    assert sorted(os.path.basename(x) for x in mgr.ls(d)) == ["p1", "p2"]
    mgr.download(os.path.join(d, "p1"), str(tmp_path / "copy"))
    assert mgr.exists(str(tmp_path / "copy"))
    mgr.remove(d)
    assert not mgr.exists(d)


def test_line_reader_counts(tmp_path):
    p = str(tmp_path / "f.txt")
    with open(p, "w") as f:
        f.write("".join(f"line{i}\n" for i in range(100)))
    r = LineFileReader(p)
    assert sum(1 for _ in r) == 100
    assert r.lines_read == 100


def test_buffered_reader_sampling(tmp_path):
    p = str(tmp_path / "f.txt")
    with open(p, "w") as f:
        f.write("".join(f"{i}\n" for i in range(2000)))
    r = BufferedLineFileReader(p, sample_rate=0.25, seed=7)
    kept = sum(1 for _ in r)
    assert r.lines_read == 2000
    assert kept == r.lines_kept
    assert 350 < kept < 650  # ~500 expected
    # deterministic given the seed
    r2 = BufferedLineFileReader(p, sample_rate=0.25, seed=7)
    assert sum(1 for _ in r2) == kept


def test_timer_registry():
    reg = TimerRegistry()
    with reg.scope("pull"):
        pass
    with reg.scope("pull"):
        pass
    assert reg["pull"].count == 2
    assert "pull=" in reg.report()
    reg.reset()
    assert reg["pull"].count == 0
    t = Timer()
    t.start()
    t.pause()
    assert t.elapsed_sec() >= 0
    assert STAGE_TIMERS is not None


def test_monitor_stats():
    STAT_RESET()
    STAT_ADD("total_feasign_num_in_mem", 10)
    STAT_ADD("total_feasign_num_in_mem", 5)
    assert STAT_GET("total_feasign_num_in_mem") == 15
    assert "total_feasign_num_in_mem" in all_stats()
    STAT_RESET("total_feasign_num_in_mem")
    assert STAT_GET("total_feasign_num_in_mem") == 0


def test_dump_pool_and_fields(tmp_path):
    pool = DumpWorkerPool(str(tmp_path), n_threads=2)
    pool.start()
    n = dump_fields(
        pool,
        ins_ids=["a", "b", "c"],
        fields={"q": np.array([[0.1], [0.2], [0.3]]), "label": np.array([1, 0, 1])},
    )
    dump_param(pool, "fc_w", np.ones((2, 2)))
    pool.finalize()
    assert n == 3
    lines = []
    for f in sorted(os.listdir(tmp_path)):
        with open(tmp_path / f) as fh:
            lines += fh.read().splitlines()
    assert len(lines) == 4  # 3 instances + 1 param
    ins_lines = [l for l in lines if l.startswith(("a\t", "b\t", "c\t"))]
    assert len(ins_lines) == 3
    assert any("q:0.1" in l for l in ins_lines)
    assert any(l.startswith("fc_w\t") for l in lines)


def test_dump_modes():
    pool = DumpWorkerPool("/tmp/unused_dump")  # never started; write() unused
    # mode 2: only steps hitting the interval dump
    n0 = dump_fields.__wrapped__ if hasattr(dump_fields, "__wrapped__") else None
    assert n0 is None  # plain function
    from paddlebox_tpu.utils.dump import _want_ins

    assert _want_ins(0, 1, "x", 0)
    assert _want_ins(2, 10, "x", 20)
    assert not _want_ins(2, 10, "x", 21)
    picks = [_want_ins(1, 4, f"ins{i}", 0) for i in range(100)]
    assert 0 < sum(picks) < 100  # hash-sampled subset


def test_profiler_chrome_trace(tmp_path):
    PROFILER.reset()
    PROFILER.enable()
    with PROFILER.record_event("pack_batch"):
        pass
    with PROFILER.record_event("train_step", category="device"):
        pass
    PROFILER.disable()
    out = str(tmp_path / "trace.json")
    n = PROFILER.export_chrome_trace(out)
    assert n == 2  # data events only; metadata rows don't count
    with open(out) as f:
        data = json.load(f)
    spans = [e for e in data["traceEvents"] if e["ph"] != "M"]
    meta = [e for e in data["traceEvents"] if e["ph"] == "M"]
    assert {e["name"] for e in spans} == {"pack_batch", "train_step"}
    assert all(e["ph"] == "X" for e in spans)
    # chrome metadata rows: a labeled process + the recording thread
    meta_names = {m["name"] for m in meta}
    assert {"process_name", "thread_name"} <= meta_names
    # stable small tids, consistent between span and its thread_name row
    tids = {e["tid"] for e in spans}
    assert tids <= {m["tid"] for m in meta if m["name"] == "thread_name"}
    assert all(isinstance(t, int) and 0 < t < 1000 for t in tids)
    PROFILER.reset()


def test_profiler_ring_bounds_and_drop_counter(tmp_path):
    from paddlebox_tpu.utils.monitor import STAT_GET
    from paddlebox_tpu.utils.trace import Profiler

    before = STAT_GET("trace.dropped_events")
    p = Profiler(max_events=4)
    p.enable()
    for i in range(10):
        with p.record_event(f"span{i}"):
            pass
    out = str(tmp_path / "ring.json")
    assert p.export_chrome_trace(out) == 4
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    names = [e["name"] for e in events if e["ph"] == "X"]
    # ring keeps the NEWEST spans, drops the oldest
    assert names == ["span6", "span7", "span8", "span9"]
    assert p.dropped_events == 6
    assert STAT_GET("trace.dropped_events") - before == 6


def test_profiler_set_process_stamps_rank(tmp_path):
    from paddlebox_tpu.utils.trace import Profiler

    p = Profiler()
    p.enable()
    with p.record_event("before_label"):
        pass
    p.set_process(3)  # after recording: export restamps coherently
    out = str(tmp_path / "rank.json")
    p.export_chrome_trace(out)
    with open(out) as f:
        doc = json.load(f)
    assert all(e["pid"] == 3 for e in doc["traceEvents"])
    pname = [e for e in doc["traceEvents"] if e["name"] == "process_name"]
    assert pname and pname[0]["args"]["name"] == "rank3"
    assert doc["otherData"]["rank"] == 3


def test_fs_open_retry_until_available(tmp_path):
    """Retry-until-open parity (data_feed.cc:2738-2740): a file that appears
    after the first attempt is read, not fatal."""
    import threading
    import time as _time

    from paddlebox_tpu.utils.fs import fs_open_read_retry, fs_read_bytes_retry

    late = tmp_path / "late.txt"

    def publish():
        _time.sleep(0.4)
        late.write_text("hello\n")

    t = threading.Thread(target=publish)
    t.start()
    stream = fs_open_read_retry(str(late), retries=5, backoff_s=0.3)
    assert stream.read() == "hello\n"
    stream.close()
    t.join()
    assert fs_read_bytes_retry(str(late)) == b"hello\n"

    import pytest

    with pytest.raises(OSError):
        fs_open_read_retry(str(tmp_path / "never.txt"), retries=2, backoff_s=0.05)


def test_train_pass_chrome_trace(tmp_path):
    """RecordEvent-parity spans from a real pass: feed/step on the main
    thread, pack+upload in worker threads (the overlap is visible)."""
    import json as _json

    import pytest

    from paddlebox_tpu.utils import native as _native

    if not _native.available():
        pytest.skip("pack+upload spans need the columnar fast path")

    import jax
    import numpy as np
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    rng = np.random.default_rng(0)
    path = tmp_path / "d.txt"
    with open(path, "w") as f:
        for _ in range(64):
            keys = rng.integers(1, 100, 3)
            f.write(f"1 {int(keys[0]) % 2}.0 " + " ".join(f"1 {k}" for k in keys) + "\n")
    layout = ValueLayout(embedx_dim=4)
    opt = SparseOptimizerConfig(embedx_threshold=0.0)
    table = HostSparseTable(layout, opt, n_shards=2, seed=0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(3)],
        label_slot="label",
    )
    ds = BoxPSDataset(schema, table, batch_size=16, seed=0)
    ds.set_filelist([str(path)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    model = LogisticRegression(num_slots=3, feat_width=layout.pull_width)
    cfg = TrainStepConfig(num_slots=3, batch_size=16, layout=layout,
                          sparse_opt=opt, auc_buckets=100)
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))

    from paddlebox_tpu import config as _config

    # resident path (default): superstep spans
    PROFILER.reset()
    PROFILER.enable()
    try:
        tr.train_pass(ds)
    finally:
        PROFILER.disable()
    out = str(tmp_path / "trace.json")
    n = PROFILER.export_chrome_trace(out)
    assert n > 0
    names = {e["name"] for e in _json.load(open(out))["traceEvents"]}
    assert {"resident_prepare", "superstep_dispatch"} <= names

    # classic host-packed path: per-batch feed/dispatch spans
    prev_flag = _config.get_flag("enable_resident_feed")
    _config.set_flag("enable_resident_feed", 0)
    PROFILER.reset()
    PROFILER.enable()
    try:
        tr.train_pass(ds)
    finally:
        PROFILER.disable()
        _config.set_flag("enable_resident_feed", prev_flag)
    out2 = str(tmp_path / "trace2.json")
    assert PROFILER.export_chrome_trace(out2) > 0
    names2 = {e["name"] for e in _json.load(open(out2))["traceEvents"]}
    assert {"feed_wait", "train_step_dispatch", "pack+upload"} <= names2
    PROFILER.reset()


def test_stat_registry_wired_into_runtime(tmp_path):
    """Monitor parity: passes bump the process STAT registry
    (STAT_total_feasign_num_in_mem, box_wrapper.cc:1282)."""
    import jax
    import numpy as np
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from paddlebox_tpu.utils.monitor import STAT_GET, STAT_RESET

    STAT_RESET()
    rng = np.random.default_rng(0)
    path = tmp_path / "d.txt"
    with open(path, "w") as f:
        for _ in range(64):
            keys = rng.integers(1, 100, 3)
            f.write(f"1 {int(keys[0]) % 2}.0 " + " ".join(f"1 {k}" for k in keys) + "\n")
    layout = ValueLayout(embedx_dim=4)
    opt = SparseOptimizerConfig(embedx_threshold=0.0)
    table = HostSparseTable(layout, opt, n_shards=2, seed=0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(3)],
        label_slot="label",
    )
    ds = BoxPSDataset(schema, table, batch_size=16, seed=0)
    ds.set_filelist([str(path)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    assert STAT_GET("total_records_in_mem") == 64
    assert STAT_GET("total_feasign_num_in_mem") == ds.stats.keys > 0
    model = LogisticRegression(num_slots=3, feat_width=layout.pull_width)
    cfg = TrainStepConfig(num_slots=3, batch_size=16, layout=layout,
                          sparse_opt=opt, auc_buckets=100)
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    tr.train_pass(ds)
    assert STAT_GET("train_batches") == 4
    assert STAT_GET("train_samples_processed") == 64
    assert STAT_GET("train_ins_num") == 64
    STAT_RESET()
