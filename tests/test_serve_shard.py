"""Mesh-sharded scoring tier tests (PR 19).

The gates the device-resident hot-key tier must hold:

- tiered lookups are BITWISE-equal to the host ``TableVersion.lookup_rows``
  at every request-shape bucket boundary (empty batch, exactly
  ``serve_key_bucket``, bucket+1, all-miss, all-hit, mixed), with exact
  ``serve.device_tier_hits`` / ``serve.device_tier_misses`` /
  ``serve.key_misses`` counter deltas;
- the tier installs under the SAME atomic swap as the host version: a
  crash injected mid-tier-build (fault site ``serve.tier_build``) leaves
  the old version — object identity and scores — untouched, and the
  healed retry commits bitwise (FLT008 recovery contract);
- ``device_scoring_tier=off`` (and hotness=None) is bitwise-identical to
  the host-only path: no tier object, no device work;
- end-to-end: a follower with the tier on serves scores bitwise-equal to
  trainer-direct scoring, gossips per-rank tier stats, and feeds the
  ``serve.request_ms`` histogram (the obs_report SLO series);
- the fleet client's least-loaded-of-two pick reroutes on gossiped queue
  depth (counted under ``serve.lb_rerouted``) and degrades to pure
  round-robin with ``serve_lb_least_loaded=False``.
"""

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.serve import FleetView, ScoreServer
from paddlebox_tpu.serve.scoring_table import ScoringTable
from paddlebox_tpu.utils.faultinject import InjectedFault, fail_once, inject
from paddlebox_tpu.utils.monitor import STAT_GET, STAT_HIST

from tests.test_serve import DATE, SCHEMA, PublishStack

BUCKET = 16
WIDTH = 6


@pytest.fixture
def _tier_flags():
    names = (
        "serve_key_bucket",
        "serve_row_bucket",
        "device_scoring_tier",
        "device_tier_hot_show",
        "device_tier_capacity",
        "serve_lb_least_loaded",
    )
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("serve_key_bucket", BUCKET)
    config.set_flag("serve_row_bucket", 8)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _committed_version(hotness=True):
    """A synthetic version: 64 keys, even-indexed ones hot (shows=2)."""
    rng = np.random.default_rng(7)
    keys = np.sort(
        rng.choice(100_000, 64, replace=False).astype(np.uint64)
    )
    rows = rng.standard_normal((64, WIDTH)).astype(np.float32)
    shows = np.zeros(64, dtype=np.float32)
    shows[::2] = 2.0
    st = ScoringTable(WIDTH)
    v = st.commit(
        keys,
        rows,
        date=DATE,
        delta_idx=0,
        decay_epoch=0,
        hotness=shows if hotness else None,
    )
    hot = keys[::2]
    cold = keys[1::2]
    absent = (np.uint64(2**63) + np.arange(40, dtype=np.uint64)).astype(
        np.uint64
    )
    return st, v, hot, cold, absent


# ---- bucket-boundary parity + exact miss split -----------------------------


@pytest.mark.parametrize(
    "case",
    ["empty", "bucket", "bucket_plus_1", "all_miss", "all_hit", "mixed"],
)
def test_tiered_lookup_bitwise_and_counter_split(_tier_flags, case):
    _, v, hot, cold, absent = _committed_version()
    assert v.device_tier is not None and v.device_tier.n_rows == 32
    q, want = {
        # (hits, tier_misses, key_misses)
        "empty": (np.zeros(0, dtype=np.uint64), (0, 0, 0)),
        "bucket": (hot[:BUCKET], (BUCKET, 0, 0)),
        "bucket_plus_1": (hot[: BUCKET + 1], (BUCKET + 1, 0, 0)),
        "all_miss": (absent[:12], (0, 12, 12)),
        "all_hit": (hot, (len(hot), 0, 0)),
        "mixed": (
            np.concatenate([hot[:10], cold[:10], absent[:5]]),
            (10, 15, 5),
        ),
    }[case]
    ref, ref_miss = v.lookup_rows(q)  # host path (bumps serve.key_misses)
    before = {
        n: STAT_GET(n)
        for n in (
            "serve.device_tier_hits",
            "serve.device_tier_misses",
            "serve.key_misses",
        )
    }
    got, n_tier_miss, n_key_miss = v.lookup_rows_tiered(q)
    np.testing.assert_array_equal(ref, got)  # bitwise, zero-rows included
    hits, tier_misses, key_misses = want
    assert (n_tier_miss, n_key_miss) == (tier_misses, key_misses)
    assert ref_miss == key_misses  # host path agrees on true misses
    assert STAT_GET("serve.device_tier_hits") - before["serve.device_tier_hits"] == hits
    assert (
        STAT_GET("serve.device_tier_misses")
        - before["serve.device_tier_misses"]
        == tier_misses
    )
    assert STAT_GET("serve.key_misses") - before["serve.key_misses"] == key_misses


def test_capacity_truncation_keeps_hottest(_tier_flags):
    config.set_flag("device_tier_capacity", 8)
    _, v, hot, _, _ = _committed_version()
    # only 8 of the 32 hot rows fit; every served row is still bitwise
    assert v.device_tier.n_rows == 8
    ref, _ = v.lookup_rows(hot)
    got, n_tier_miss, n_key_miss = v.lookup_rows_tiered(hot)
    np.testing.assert_array_equal(ref, got)
    assert n_tier_miss == len(hot) - 8 and n_key_miss == 0


def test_ablation_off_builds_no_tier(_tier_flags):
    _, v, hot, cold, _ = _committed_version(hotness=False)
    assert v.device_tier is None
    q = np.concatenate([hot[:5], cold[:5]])
    rows, n_tier_miss, n_key_miss = v.lookup_rows_tiered(q)
    ref, _ = v.lookup_rows(q)
    np.testing.assert_array_equal(ref, rows)
    assert (n_tier_miss, n_key_miss) == (0, 0)


# ---- serve.tier_build: kill mid-tier-build, FLT008 contract ----------------


def test_kill_mid_tier_build_keeps_old_version_bitwise(_tier_flags):
    st, v0, hot, _, _ = _committed_version()
    probe = np.concatenate([hot, v0.keys[1::2]])
    before = v0.lookup_rows(probe)[0]

    rng = np.random.default_rng(11)
    keys2 = np.sort(rng.choice(100_000, 80, replace=False).astype(np.uint64))
    rows2 = rng.standard_normal((80, WIDTH)).astype(np.float32)
    shows2 = np.full(80, 2.0, dtype=np.float32)
    kw = dict(date=DATE, delta_idx=1, decay_epoch=0, hotness=shows2)
    with inject(fail_once("serve.tier_build")) as plan:
        with pytest.raises(InjectedFault):
            st.commit(keys2, rows2, **kw)
        assert plan.failures("serve.tier_build") == 1
        # no partial tier, no partial version: same object, same rows
        v1 = st.version()
        assert v1 is v0 and v1.delta_idx == 0
        np.testing.assert_array_equal(before, v1.lookup_rows(probe)[0])
        assert st.committed_indices() == [0]
        # healed retry (same plan, budget spent) lands the commit bitwise
        v2 = st.commit(keys2, rows2, **kw)
    assert v2.delta_idx == 1 and v2.device_tier is not None
    assert v2.device_tier.n_rows == 80
    ref, _ = v2.lookup_rows(keys2)
    got, _, _ = v2.lookup_rows_tiered(keys2)
    np.testing.assert_array_equal(ref, got)
    np.testing.assert_array_equal(ref, rows2)


# ---- end-to-end: follower parity, gossip, request_ms -----------------------


def test_follower_device_tier_parity_gossip_and_request_ms(
    _tier_flags, tmp_path
):
    config.set_flag("device_scoring_tier", "on")
    config.set_flag("device_tier_hot_show", 0.5)
    st = PublishStack(tmp_path)
    fol = st.follower
    st.publish_base()
    ref0 = st.trainer_scores()
    assert fol.poll_once() is True
    v0 = fol.version()
    assert v0.device_tier is not None and v0.device_tier.n_rows > 0
    np.testing.assert_array_equal(ref0, st.follower_scores(v0))

    st.publish_delta(lo=120)
    ref1 = st.trainer_scores()
    assert fol.poll_once() is True
    v1 = fol.version()
    assert v1.device_tier is not None and v1.device_tier is not v0.device_tier
    np.testing.assert_array_equal(ref1, st.follower_scores(v1))
    assert v1.device_tier.hits > 0  # the parity probe ran through the tier

    # per-rank tier stats ride the health gossip beat
    snap = fol.health_snapshot()
    assert snap["tier_rows"] == v1.device_tier.n_rows
    assert snap["tier_hits"] == v1.device_tier.hits
    assert snap["tier_misses"] == v1.device_tier.misses

    # the SLO histogram: one serve.request_ms sample per served request
    h_before = STAT_HIST("serve.request_ms")
    n_before = 0 if h_before is None else h_before.count
    srv = ScoreServer(fol, st.scorer, SCHEMA)
    srv.start()
    try:
        preds = srv.score(st.probe, timeout=60.0)
    finally:
        srv.stop()
    np.testing.assert_array_equal(ref1, preds)
    h = STAT_HIST("serve.request_ms")
    assert h is not None and h.count == n_before + 1


# ---- fleet client load balancing: least-loaded-of-two ----------------------


def _ready_beat(queue_depth):
    return {
        "state": "ready",
        "warm": True,
        "delta_idx": 0,
        "ownership_epoch": 0,
        "queue_depth": queue_depth,
    }


def test_pick_least_loaded_of_two_reroutes_and_counts(_tier_flags):
    view = FleetView([1, 2])
    view.observe(1, _ready_beat(queue_depth=50))
    view.observe(2, _ready_beat(queue_depth=0))
    before = STAT_GET("serve.lb_rerouted")
    picks = [view.pick() for _ in range(10)]
    # every rotation landing on the loaded rank 1 reroutes to idle rank 2
    assert picks == [2] * 10
    assert STAT_GET("serve.lb_rerouted") - before == 5
    # equal depths: no reroute, plain rotation
    view.observe(1, _ready_beat(queue_depth=0))
    base = STAT_GET("serve.lb_rerouted")
    assert sorted(view.pick() for _ in range(2)) == [1, 2]
    assert STAT_GET("serve.lb_rerouted") == base


def test_pick_flag_off_is_pure_round_robin(_tier_flags):
    config.set_flag("serve_lb_least_loaded", False)
    view = FleetView([1, 2])
    view.observe(1, _ready_beat(queue_depth=10_000))
    view.observe(2, _ready_beat(queue_depth=0))
    before = STAT_GET("serve.lb_rerouted")
    picks = [view.pick() for _ in range(4)]
    # the ablation ignores load entirely: strict alternation
    assert picks in ([1, 2, 1, 2], [2, 1, 2, 1])
    assert STAT_GET("serve.lb_rerouted") == before
