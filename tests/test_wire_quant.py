"""Quantized wire formats (ops/wire_quant.py): roundtrip tolerances, byte
halving, and end-to-end training equivalence under bf16 boundary/ICI wires.
"""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.parallel.mesh import shard_map
from paddlebox_tpu.ops.wire_quant import (
    fetch_rows,
    row_wire_nbytes,
    send_rows,
)
from paddlebox_tpu.table import ValueLayout


def _rows(rng, n, layout):
    """Realistic table rows: big counters, small embeds, mid g2."""
    x = rng.normal(0, 0.05, (n, layout.width)).astype(np.float32)
    x[:, layout.SHOW] = rng.integers(0, 2000, n)
    x[:, layout.CLK] = rng.integers(0, 200, n)
    x[:, layout.embed_g2_col] = rng.uniform(0, 50, n)
    x[:, layout.embedx_g2_col] = rng.uniform(0, 50, n)
    return x


def test_bf16_row_roundtrip_and_bytes():
    lay = ValueLayout(embedx_dim=16)
    rng = np.random.default_rng(0)
    x = _rows(rng, 64, lay)
    assert row_wire_nbytes(64, lay, "bf16") == x.nbytes // 2
    back = fetch_rows(jax.numpy.asarray(x), lay, "bf16")
    np.testing.assert_allclose(back, x, rtol=8e-3, atol=1e-6)
    up = np.asarray(send_rows(x, lay, "bf16"))
    np.testing.assert_allclose(up, x, rtol=8e-3, atol=1e-6)


def test_wire_stat_counters_track_bytes_on_wire():
    """fetch/send account actual encoded bytes + rows into wire.* stats at
    the transport choke points — the bench JSON 'wire' block's source."""
    from paddlebox_tpu.utils.monitor import STAT_GET

    lay = ValueLayout(embedx_dim=16)
    rng = np.random.default_rng(7)
    x = _rows(rng, 32, lay)
    before = {
        k: STAT_GET(k)
        for k in (
            "wire.fetch_rows_total", "wire.fetch_bytes_total",
            "wire.fetch_fp32_bytes_total", "wire.send_rows_total",
            "wire.send_bytes_total", "wire.send_fp32_bytes_total",
        )
    }
    fetch_rows(jax.numpy.asarray(x), lay, "bf16")
    send_rows(x, lay, "int8")
    assert STAT_GET("wire.fetch_rows_total") - before["wire.fetch_rows_total"] == 32
    assert STAT_GET("wire.send_rows_total") - before["wire.send_rows_total"] == 32
    d_fetch = STAT_GET("wire.fetch_bytes_total") - before["wire.fetch_bytes_total"]
    assert d_fetch == row_wire_nbytes(32, lay, "bf16")
    d_send = STAT_GET("wire.send_bytes_total") - before["wire.send_bytes_total"]
    assert d_send == row_wire_nbytes(32, lay, "int8")
    # the fp32 twin is the denominator for the compression ratio
    for k in ("wire.fetch_fp32_bytes_total", "wire.send_fp32_bytes_total"):
        assert STAT_GET(k) - before[k] == 32 * lay.width * 4
    assert d_fetch < 32 * lay.width * 4 and d_send < 32 * lay.width * 4


def test_int8_rows_keep_counters_and_embeds():
    """int8 scales ONLY the embed block per row — a show=2000 counter must
    not crush 0.05-magnitude embeddings, and counters stay bf16-exact."""
    lay = ValueLayout(embedx_dim=16)
    rng = np.random.default_rng(1)
    x = _rows(rng, 64, lay)
    assert row_wire_nbytes(64, lay, "int8") < x.nbytes // 2
    for back in (
        fetch_rows(jax.numpy.asarray(x), lay, "int8"),
        np.asarray(send_rows(x, lay, "int8")),
    ):
        # counters exact (small ints are bf16-exact up to 256; show up to
        # 2000 has <1% bf16 error)
        np.testing.assert_allclose(
            back[:, lay.SHOW], x[:, lay.SHOW], rtol=8e-3
        )
        # embeds: error bounded by the EMBED block's own per-row scale
        a, b = lay.embed_w_col, lay.embed_g2_col
        emb, emb_back = x[:, a:b], back[:, a:b]
        bound = np.abs(emb).max(axis=1, keepdims=True) / 254 + 1e-7
        assert (np.abs(emb_back - emb) <= bound + 1e-6).all()
    # all-zero rows survive (scale floor, no NaN)
    z = np.zeros((3, lay.width), np.float32)
    np.testing.assert_array_equal(fetch_rows(jax.numpy.asarray(z), lay, "int8"), 0)


def test_int8_per_block_scales_isolate_expand_outliers():
    """embedx and expand quantize with SEPARATE per-row scales: a 10.0
    outlier in the expand block must not crush 0.05-magnitude embedx values
    to noise (a shared scale would give them one step of 10/127 ~ 0.08 —
    larger than the values themselves)."""
    lay = ValueLayout(embedx_dim=8, expand_embed_dim=8)
    rng = np.random.default_rng(3)
    x = _rows(rng, 64, lay)
    # expand block: big outliers; embedx stays small
    x[:, lay.expand_col : lay.expand_col + lay.expand_dim] = rng.normal(
        0, 4.0, (64, lay.expand_dim)
    )
    x[:, lay.expand_col] = 10.0  # hard outlier in every row's expand block
    for back in (
        fetch_rows(jax.numpy.asarray(x), lay, "int8"),
        np.asarray(send_rows(x, lay, "int8")),
    ):
        ax, bx = lay.embedx_col, lay.embedx_col + lay.embedx_dim
        emb, emb_back = x[:, ax:bx], back[:, ax:bx]
        # error bounded by the EMBEDX block's own scale (incl. embed_w col),
        # NOT the expand outlier's
        blk = x[:, lay.embed_w_col : lay.expand_col]
        bound = np.abs(blk).max(axis=1, keepdims=True) / 254 + 1e-7
        assert (np.abs(emb_back - emb) <= bound + 1e-6).all()
        # a shared-scale quantizer could not meet this bound
        assert bound.max() < 10.0 / 254
        # expand block still within its own scale
        ea, eb = lay.expand_col, lay.expand_col + lay.expand_dim
        ebound = np.abs(x[:, ea:eb]).max(axis=1, keepdims=True) / 254 + 1e-7
        assert (np.abs(back[:, ea:eb] - x[:, ea:eb]) <= ebound + 1e-6).all()


def test_unknown_mode_raises():
    lay = ValueLayout(embedx_dim=4)
    with pytest.raises(ValueError):
        send_rows(np.zeros((1, lay.width), np.float32), lay, "fp16")


def _train_two_pass_boundary(tmp_path, mode):
    """Two overlapping carried-boundary passes under a given wire_dtype."""
    from tests.test_carrier import _mk, _write_pass

    prev_c = config.get_flag("enable_carried_table")
    prev_w = config.get_flag("wire_dtype")
    config.set_flag("enable_carried_table", 1)
    config.set_flag("wire_dtype", mode)
    try:
        layout, table, ds, tr = _mk(tmp_path, seed=0)
        out1 = tr.train_pass(ds)
        ds.end_pass(tr.trained_table_device())
        f1 = _write_pass(tmp_path / "p1.txt", seed=1, lo=100, hi=300)
        ds.set_filelist([f1])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        out2 = tr.train_pass(ds)
        ds.end_pass(tr.trained_table_device())
        table.drain_pending()
        keys = np.sort(table.keys())
        return out1["loss"], out2["loss"], keys, table.pull_or_create(keys)
    finally:
        config.set_flag("enable_carried_table", prev_c)
        config.set_flag("wire_dtype", prev_w)


def test_bf16_boundary_wire_trains_equivalently(tmp_path):
    l1f, l2f, kf, vf = _train_two_pass_boundary(tmp_path / "f", "fp32")
    l1b, l2b, kb, vb = _train_two_pass_boundary(tmp_path / "b", "bf16")
    np.testing.assert_array_equal(kb, kf)
    # pass 1 never crosses the wire -> identical; pass 2 differs only by
    # bf16 rounding of the splice/new-key/departure values
    assert np.isclose(l1b, l1f, atol=1e-6)
    assert np.isclose(l2b, l2f, atol=5e-3)
    np.testing.assert_allclose(vb, vf, rtol=2e-2, atol=2e-2)


def test_int8_boundary_wire_trains_sanely(tmp_path):
    """int8 boundary wire: training stays close to fp32 (looser tolerance
    than bf16 — embeds round to 1/254 of their row max per crossing)."""
    l1f, l2f, kf, vf = _train_two_pass_boundary(tmp_path / "f", "fp32")
    l1q, l2q, kq, vq = _train_two_pass_boundary(tmp_path / "q", "int8")
    np.testing.assert_array_equal(kq, kf)
    assert np.isclose(l1q, l1f, atol=1e-6)
    assert np.isclose(l2q, l2f, atol=2e-2)
    # counters (show/clk) must track closely even under int8
    from paddlebox_tpu.table import ValueLayout

    lay = ValueLayout(embedx_dim=4)
    np.testing.assert_allclose(
        vq[:, lay.SHOW], vf[:, lay.SHOW], rtol=2e-2, atol=1e-2
    )


def _train_multi_pass_boundary(tmp_path, mode, n_passes=4):
    """n overlapping carried-boundary passes under a wire_dtype; returns
    the per-pass metric dicts (loss, auc, auc_cumulative)."""
    from tests.test_carrier import _mk, _write_pass

    prev_c = config.get_flag("enable_carried_table")
    prev_w = config.get_flag("wire_dtype")
    config.set_flag("enable_carried_table", 1)
    config.set_flag("wire_dtype", mode)
    try:
        layout, table, ds, tr = _mk(tmp_path, seed=0)
        outs = [tr.train_pass(ds)]
        ds.end_pass(tr.trained_table_device())
        for p in range(1, n_passes):
            f = _write_pass(
                tmp_path / f"p{p}.txt", seed=p, lo=1 + 80 * p, hi=200 + 80 * p
            )
            ds.set_filelist([f])
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            outs.append(tr.train_pass(ds))
            ds.end_pass(tr.trained_table_device())
        table.drain_pending()
        return outs
    finally:
        config.set_flag("enable_carried_table", prev_c)
        config.set_flag("wire_dtype", prev_w)


def test_int8_boundary_wire_auc_delta_pinned(tmp_path):
    """Quality parity under int8, pinned: over a 4-pass run where every
    boundary crosses the quantized wire, per-pass AUC must stay within
    0.01 of fp32 training and cumulative AUC within 0.005 — the numeric
    contract the reference's int16 quant family ships with
    (box_wrapper.cc:419-437), not a loose 'trains sanely' bound."""
    outs_f = _train_multi_pass_boundary(tmp_path / "f", "fp32")
    outs_q = _train_multi_pass_boundary(tmp_path / "q", "int8")
    assert np.isclose(outs_q[0]["loss"], outs_f[0]["loss"], atol=1e-6)
    for i, (of, oq) in enumerate(zip(outs_f, outs_q)):
        assert abs(oq["auc"] - of["auc"]) <= 0.01, (
            f"pass {i}: int8 AUC {oq['auc']:.4f} vs fp32 {of['auc']:.4f}"
        )
    assert abs(outs_q[-1]["auc_cumulative"] - outs_f[-1]["auc_cumulative"]) <= 0.005


def test_bf16_ici_wire_mesh_step(tmp_path):
    """Sharded pull/push with bf16 all_to_all payloads stays within bf16
    tolerance of the fp32 mesh step."""
    from tests.test_carrier import _mk

    prev = config.get_flag("ici_wire_dtype")

    def run(mode):
        config.set_flag("ici_wire_dtype", mode)
        try:
            import optax

            from paddlebox_tpu.models import DeepFM
            from paddlebox_tpu.parallel import make_mesh
            from paddlebox_tpu.table import (
                HostSparseTable,
                SparseOptimizerConfig,
                ValueLayout,
            )
            from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
            from tests.test_carrier import _schema, _write_pass

            layout = ValueLayout(embedx_dim=4)
            opt = SparseOptimizerConfig(embedx_threshold=0.0)
            table = HostSparseTable(layout, opt, n_shards=4, seed=0)
            plan = make_mesh(4)
            from paddlebox_tpu.data import BoxPSDataset

            ds = BoxPSDataset(
                _schema(), table, batch_size=8, n_mesh_shards=4,
                shuffle_mode="none",
            )
            f = _write_pass(tmp_path / f"i{mode}.txt", seed=0, lo=1, hi=200)
            ds.set_filelist([f])
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            model = DeepFM(
                num_slots=4, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )
            cfg = TrainStepConfig(
                num_slots=4, batch_size=2, layout=layout, sparse_opt=opt,
                auc_buckets=100, axis_name=plan.axis,
            )
            tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan)
            tr.init_params(jax.random.PRNGKey(0))
            out = tr.train_pass(ds)
            tab = np.asarray(tr.trained_table())
            ds.end_pass(None)
            return out, tab
        finally:
            config.set_flag("ici_wire_dtype", prev)

    out_f, tab_f = run("fp32")
    out_b, tab_b = run("bf16")
    assert np.isclose(out_b["loss"], out_f["loss"], atol=5e-3)
    np.testing.assert_allclose(tab_b, tab_f, rtol=2e-2, atol=2e-2)
    # int8 ICI wire (per-record scale, counters fp32): looser but bounded
    out_q, tab_q = run("int8")
    assert np.isclose(out_q["loss"], out_f["loss"], atol=2e-2)
    np.testing.assert_allclose(tab_q, tab_f, rtol=6e-2, atol=6e-2)
    lay = ValueLayout(embedx_dim=4)
    # show/clk counters ride fp32 in the int8 payload -> exact
    # (tables are [ns, cap, W]; the counter columns live on the last axis)
    np.testing.assert_allclose(
        tab_q[..., lay.SHOW], tab_f[..., lay.SHOW], rtol=1e-6, atol=1e-6
    )
    np.testing.assert_allclose(
        tab_q[..., lay.CLK], tab_f[..., lay.CLK], rtol=1e-6, atol=1e-6
    )


def test_ici_wire_preserves_full_counter_head_conv_layout():
    """The compressed ICI pull wire must keep the WHOLE counter/stat head
    fp32 — on CONV layouts that includes the conversion count at column 2,
    which can sit at 1e4 next to 0.01-magnitude embeddings: sharing one
    int8 scale with it would quantize every embedding to zero (and bf16
    would round the count itself past 256)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.parallel import make_mesh, sharded_pull
    from paddlebox_tpu.table import FeatureType

    lay = ValueLayout(embedx_dim=8, feature_type=FeatureType.CONV)
    assert lay.embed_w_col == 3  # show, clk, conv | embed_w ...
    ndev, cap = 4, 8
    rng = np.random.default_rng(5)
    tbl = rng.normal(0, 0.01, (ndev, cap, lay.width)).astype(np.float32)
    tbl[:, :, lay.SHOW] = rng.integers(300, 5000, (ndev, cap))
    tbl[:, :, lay.CLK] = rng.integers(0, 500, (ndev, cap))
    tbl[:, :, 2] = rng.integers(1000, 30000, (ndev, cap))  # conv count
    tbl[:, cap - 1] = 0.0  # padding row

    plan = make_mesh(ndev)
    K = 4
    req = rng.integers(0, cap - 1, (ndev, ndev, K)).astype(np.int32)

    def run(mode):
        prev = config.get_flag("ici_wire_dtype")
        config.set_flag("ici_wire_dtype", mode)
        try:
            mapped = jax.jit(
                shard_map(
                    lambda t, r: sharded_pull(
                        t[0], r[0], lay, 0.0, 1.0, plan.axis
                    )[None],
                    mesh=plan.mesh,
                    in_specs=(P(plan.axis), P(plan.axis)),
                    out_specs=P(plan.axis),
                    check_vma=False,
                )
            )
            return np.asarray(
                mapped(
                    jax.device_put(jnp.asarray(tbl), plan.table_sharding),
                    jax.device_put(jnp.asarray(req), plan.batch_sharding),
                )
            )
        finally:
            config.set_flag("ici_wire_dtype", prev)

    ref = run("fp32")
    for mode in ("bf16", "int8"):
        got = run(mode)
        # counter/stat head (show, clk, conv) bit-exact
        np.testing.assert_array_equal(got[..., :3], ref[..., :3], err_msg=mode)
        # embeds within the EMBED value range's own quant resolution, not
        # the conv counter's
        emb_ref = ref[..., 3:]
        bound = np.abs(emb_ref).max(axis=-1, keepdims=True) / (
            120.0 if mode == "int8" else 250.0
        ) + 1e-7
        assert (np.abs(got[..., 3:] - emb_ref) <= bound).all(), mode


def test_ici_int8_extended_pull_sections_isolate_expand():
    """Extended pulls concat embedx + expand into one record; the int8 ICI
    wire must scale them as separate sections — an expand outlier may not
    crush embedx (the same per-family rule as the row wire)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.sharded_pullpush import sharded_pull

    lay = ValueLayout(embedx_dim=8, expand_embed_dim=8)
    ndev, cap = 4, 8
    rng = np.random.default_rng(6)
    tbl = rng.normal(0, 0.01, (ndev, cap, lay.width)).astype(np.float32)
    tbl[:, :, lay.SHOW] = rng.integers(300, 3000, (ndev, cap))
    tbl[:, :, lay.CLK] = rng.integers(0, 300, (ndev, cap))
    # expand block: hard outliers next to 0.01-magnitude embedx
    tbl[:, :, lay.expand_col] = 8.0
    tbl[:, cap - 1] = 0.0

    plan = make_mesh(ndev)
    K = 4
    req = rng.integers(0, cap - 1, (ndev, ndev, K)).astype(np.int32)

    def run(mode):
        prev = config.get_flag("ici_wire_dtype")
        config.set_flag("ici_wire_dtype", mode)
        try:
            mapped = jax.jit(
                shard_map(
                    lambda t, r: sharded_pull(
                        t[0], r[0], lay, 0.0, 1.0, plan.axis, extended=True
                    )[None],
                    mesh=plan.mesh,
                    in_specs=(P(plan.axis), P(plan.axis)),
                    out_specs=P(plan.axis),
                    check_vma=False,
                )
            )
            return np.asarray(
                mapped(
                    jax.device_put(jnp.asarray(tbl), plan.table_sharding),
                    jax.device_put(jnp.asarray(req), plan.batch_sharding),
                )
            )
        finally:
            config.set_flag("ici_wire_dtype", prev)

    ref = run("fp32")
    got = run("int8")
    pw = lay.pull_width
    # counters exact; embedx error bounded by the EMBEDX section's scale
    np.testing.assert_array_equal(got[..., :2], ref[..., :2])
    emb_ref = ref[..., 2:pw]
    bound = np.abs(ref[..., lay.embed_w_col:pw]).max(axis=-1, keepdims=True) / 120.0 + 1e-7
    assert (np.abs(got[..., 2:pw] - emb_ref) <= bound).all()
    assert bound.max() < 8.0 / 254  # a shared scale could not meet this
    # expand section bounded by its own (outlier-sized) scale
    ebound = np.abs(ref[..., pw:]).max(axis=-1, keepdims=True) / 120.0 + 1e-7
    assert (np.abs(got[..., pw:] - ref[..., pw:]) <= ebound).all()


def test_ici_int8_push_sections_isolate_expand_grads():
    """The push wire's section math (head=2 counters, embedx grads and
    expand grads as separate int8 sections — the pw2 pivot in
    sharded_push): counters bit-exact, each grad family bounded by its OWN
    per-record scale even with an expand-grad outlier."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.sharded_pullpush import _compressed_a2a

    lay = ValueLayout(embedx_dim=8, expand_embed_dim=8)
    ndev, K = 4, 4
    pw = lay.push_width
    gw = lay.extended_push_width  # embedx grads + expand grads
    rng = np.random.default_rng(7)
    recs = rng.normal(0, 0.01, (ndev, ndev, K, gw + 2)).astype(np.float32)
    recs[..., 0] = rng.integers(1, 2000, (ndev, ndev, K))  # show counts
    recs[..., 1] = rng.integers(0, 500, (ndev, ndev, K))  # clk counts
    recs[..., 2 + pw] = 5.0  # expand-grad outlier in every record

    plan = make_mesh(ndev)
    # exactly sharded_push's extended section split
    pw2 = 2 + pw
    sections = [(2, pw2), (pw2, gw + 2)]

    def run(mode):
        prev = config.get_flag("ici_wire_dtype")
        config.set_flag("ici_wire_dtype", mode)
        try:
            mapped = jax.jit(
                shard_map(
                    lambda r: _compressed_a2a(r[0], plan.axis, 2, sections)[None],
                    mesh=plan.mesh,
                    in_specs=(P(plan.axis),),
                    out_specs=P(plan.axis),
                    check_vma=False,
                )
            )
            return np.asarray(mapped(jax.device_put(
                jnp.asarray(recs), plan.batch_sharding
            )))
        finally:
            config.set_flag("ici_wire_dtype", prev)

    ref = run("fp32")
    got = run("int8")
    np.testing.assert_array_equal(got[..., :2], ref[..., :2])  # counters
    gbound = np.abs(ref[..., 2:pw2]).max(axis=-1, keepdims=True) / 120.0 + 1e-7
    assert (np.abs(got[..., 2:pw2] - ref[..., 2:pw2]) <= gbound).all()
    assert gbound.max() < 5.0 / 254  # shared scale could not meet this
    ebound = np.abs(ref[..., pw2:]).max(axis=-1, keepdims=True) / 120.0 + 1e-7
    assert (np.abs(got[..., pw2:] - ref[..., pw2:]) <= ebound).all()
    # bf16 mode: counters exact too (the fp32 head path)
    got16 = run("bf16")
    np.testing.assert_array_equal(got16[..., :2], ref[..., :2])


def test_resident_counts_compression_upload_bytes(tmp_path):
    """The resident upload ships uint8 counts (+int32 base) instead of the
    int32 offset matrix — bit-identical training, ~4x smaller offsets."""
    from paddlebox_tpu.train.resident_step import ResidentPass
    from tests.test_carrier import _mk

    _, _, ds, tr = _mk(tmp_path, seed=0)
    tr.train_pass(ds, n_batches=2)  # builds the resident pass
    rp = tr._resident_cache[2]
    assert isinstance(rp, ResidentPass)
    assert rp.off is None and rp.counts is not None  # compact form chosen
    assert rp.counts.dtype == np.uint8
    n, S = rp.counts.shape
    compact = rp.counts.size + rp.base.size * 4
    full = n * (S + 1) * 4
    # >2x smaller even at this tiny S=4 fixture (base array overhead
    # amortizes away at real slot counts: ~4x at S=39)
    assert compact * 2 < full
    ds.end_pass(None)
