"""Regression tests for advisor findings (ADVICE.md rounds 1-2).

Each test pins one specific fixed defect so it can't silently return:
dump wiring, checkpoint dense/sparse skew, transport duplicate frames,
packer handle cleanup, empty-working-set lookup.
"""

import glob
import os
import threading

import numpy as np
import pytest

from paddlebox_tpu.parallel.transport import TcpTransport
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)

LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)


# ---- dump wiring (round-1 finding b: dump_pool accepted but never invoked) --


def _tiny_training(tmp_path, schema_meta=False, **trainer_kw):
    import jax
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    rng = np.random.default_rng(0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(4)],
        label_slot="label",
        parse_ins_id=schema_meta,
    )
    path = tmp_path / "data.txt"
    with open(path, "w") as f:
        for i in range(64):
            keys = rng.integers(1, 500, 4)
            pre = f"1 ins{i:04d} " if schema_meta else ""
            f.write(
                pre + f"1 {int(keys[0]) % 2}.0 "
                + " ".join(f"1 {k}" for k in keys) + "\n"
            )
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    ds = BoxPSDataset(schema, table, batch_size=16, seed=0)
    ds.set_filelist([str(path)])
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    model = LogisticRegression(num_slots=4, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=4, batch_size=16, layout=LAYOUT, sparse_opt=OPT, auc_buckets=100
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), **trainer_kw)
    tr.init_params(jax.random.PRNGKey(0))
    out = tr.train_pass(ds)
    ds.end_pass(tr.trained_table())
    return out, tr


def test_dump_pool_writes_part_files(tmp_path):
    from paddlebox_tpu.utils.dump import DumpWorkerPool

    pool = DumpWorkerPool(str(tmp_path / "dump"), n_threads=1)
    out, tr = _tiny_training(
        tmp_path, schema_meta=True, dump_pool=pool,
        dump_fields_list=("preds", "labels"), dump_params_at_end=True,
    )
    pool.finalize()
    parts = glob.glob(str(tmp_path / "dump" / "part-*"))
    assert parts, "train_pass with dump_pool produced no part files"
    lines = open(parts[0]).read().strip().splitlines()
    # 64 instances dumped + dense param lines at pass end
    ins_lines = [l for l in lines if l.startswith("ins")]
    assert len(ins_lines) == 64
    assert all("preds:" in l and "labels:" in l for l in ins_lines)
    assert len(lines) > len(ins_lines), "dump_params_at_end wrote nothing"


def test_dump_mode_2_every_nth_batch(tmp_path):
    from paddlebox_tpu.utils.dump import DumpWorkerPool

    pool = DumpWorkerPool(str(tmp_path / "dump"), n_threads=1)
    _tiny_training(
        tmp_path, schema_meta=True, dump_pool=pool,
        dump_fields_list=("preds",), dump_mode=2, dump_interval=2,
    )
    pool.finalize()
    lines = [
        l
        for p in glob.glob(str(tmp_path / "dump" / "part-*"))
        for l in open(p).read().strip().splitlines()
    ]
    assert len(lines) == 32  # batches 0 and 2 of 4, 16 instances each


# ---- checkpoint dense versioning (round-1 finding c: skew window) ----------


def test_save_delta_never_overwrites_live_dense(tmp_path):
    """Each save pairs its own dense file via the cursor: a crash after the
    dense write but before the cursor write must leave the PREVIOUS
    (consistent) pair fully intact — nothing the old cursor references is
    overwritten."""
    import json

    import optax

    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.train import CheckpointManager, CTRTrainer, TrainStepConfig

    model = LogisticRegression(num_slots=4, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=4, batch_size=8, layout=LAYOUT, sparse_opt=OPT, auc_buckets=100
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params()
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    table.pull_or_create(np.arange(1, 20, dtype=np.uint64))

    cm = CheckpointManager(str(tmp_path))
    cm.save_base("20260101", table, tr)
    cur0 = cm.cursor()
    dense0 = os.path.join(str(tmp_path), "20260101", cur0["dense"])
    blob0 = open(dense0, "rb").read()

    # mutate params, save a delta — the base's dense file must be untouched
    import jax

    tr.params = jax.tree.map(lambda x: x + 1.0, tr.params)
    table.push(np.arange(1, 5, dtype=np.uint64),
               table.pull_or_create(np.arange(1, 5, dtype=np.uint64)) + 1.0)
    cm.save_delta("20260101", table, tr)
    cur1 = cm.cursor()
    assert cur1["dense"] != cur0["dense"]
    assert open(dense0, "rb").read() == blob0

    # resume restores the delta's dense, not the base's
    tr2 = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr2.init_params()
    t2 = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    got = CheckpointManager(str(tmp_path)).resume(t2, tr2)
    assert got["delta_idx"] == 1
    for a, b in zip(
        np.asarray(jax.tree.leaves(tr.params)[0]).ravel(),
        np.asarray(jax.tree.leaves(tr2.params)[0]).ravel(),
    ):
        assert a == b

    # pre-versioning checkpoints (plain dense.npz, no cursor field) resume
    day = os.path.join(str(tmp_path), "20260101")
    os.replace(os.path.join(day, cur1["dense"]), os.path.join(day, "dense.npz"))
    cur = dict(cur1)
    del cur["dense"]
    with open(os.path.join(str(tmp_path), "cursor.json"), "w") as f:
        json.dump(cur, f)
    tr3 = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr3.init_params()
    assert CheckpointManager(str(tmp_path)).resume(
        HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0), tr3
    )["delta_idx"] == 1


def test_dense_retire_spares_cursor_referenced_file(tmp_path):
    """Deltas saved with trainer=None carry the older dense name forward in
    the cursor; the retire loop must never delete that referenced file."""
    import optax

    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.train import CheckpointManager, CTRTrainer, TrainStepConfig

    model = LogisticRegression(num_slots=4, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=4, batch_size=8, layout=LAYOUT, sparse_opt=OPT, auc_buckets=100
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params()
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    keys = np.arange(1, 10, dtype=np.uint64)
    table.pull_or_create(keys)
    cm = CheckpointManager(str(tmp_path))
    cm.save_base("20260101", table, tr)
    for _ in range(3):  # sparse-only deltas: no trainer
        table.push(keys, table.pull_or_create(keys) + 1.0)
        cm.save_delta("20260101", table)
    cur = cm.cursor()
    assert cur == {"date": "20260101", "delta_idx": 3,
                   "ownership_epoch": 0, "dense": "dense-0000.npz"}
    assert os.path.exists(os.path.join(str(tmp_path), "20260101", "dense-0000.npz"))
    tr2 = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr2.init_params()
    cm.resume(HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0), tr2)
    import jax

    for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(tr2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dump_scalar_field_skipped(tmp_path):
    """A 0-d metric in dump_fields_list is skipped, not crashed on."""
    from paddlebox_tpu.utils.dump import DumpWorkerPool

    pool = DumpWorkerPool(str(tmp_path / "dump"), n_threads=1)
    _tiny_training(
        tmp_path, schema_meta=True, dump_pool=pool,
        dump_fields_list=("loss", "preds"),
    )
    pool.finalize()
    lines = [
        l
        for p in glob.glob(str(tmp_path / "dump" / "part-*"))
        for l in open(p).read().strip().splitlines()
    ]
    assert len(lines) == 64 and all("preds:" in l and "loss" not in l for l in lines)


# ---- transport duplicate frames (round-2 finding: inbox overwrite) ---------


def test_transport_queues_duplicate_tag_frames():
    t = TcpTransport(0, ["127.0.0.1:0"])
    try:
        t.send(0, "dup", b"first")
        t.send(0, "dup", b"second")
        assert t.recv("dup", 0, timeout=5.0) == b"first"
        assert t.recv("dup", 0, timeout=5.0) == b"second"
    finally:
        t.close()


def test_transport_same_tag_two_rounds_loopback():
    """Same-tag alltoall twice in a row (pass_id reuse shape): round N+1's
    frame must not clobber an unconsumed round N frame."""
    t = TcpTransport(0, ["127.0.0.1:0"])
    try:
        t.send(0, "ws-req:0", b"roundA")
        t.send(0, "ws-req:0", b"roundB")
        got = [t.recv("ws-req:0", 0, timeout=5.0),
               t.recv("ws-req:0", 0, timeout=5.0)]
        assert got == [b"roundA", b"roundB"]
    finally:
        t.close()


# ---- packer handle cleanup (round-2 finding: close frees only own thread) --


def test_batch_packer_close_frees_all_thread_handles():
    from paddlebox_tpu.data.device_pack import BatchPacker
    from paddlebox_tpu.data.record_store import ColumnarRecords
    from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native lib unavailable")
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1), SlotInfo("s0")],
        label_slot="label",
    )
    n = 8
    store = ColumnarRecords(
        u64_values=np.arange(1, n + 1, dtype=np.uint64),
        u64_offsets=np.tile([0, 1], (n, 1)).astype(np.uint32),
        u64_base=np.arange(n, dtype=np.int64),
        f_values=np.ones(n, np.float32),
        f_offsets=np.tile([0, 1], (n, 1)).astype(np.uint32),
        f_base=np.arange(n, dtype=np.int64),
    )
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ws = PassWorkingSet()
    ws.add_keys(store.u64_values)
    ws.finalize(table, round_to=8)
    packer = BatchPacker(store, ws, schema, bucket=8)

    def work():
        packer.pack(np.arange(4, dtype=np.int64))

    threads = [threading.Thread(target=work) for _ in range(3)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    packer.pack(np.arange(4, dtype=np.int64))  # main thread too
    handles = list(packer._all_native)
    assert len(handles) >= 2  # several threads spawned native scratch
    packer.close()
    assert all(h._h is None for h in handles), "close() left live handles"
    assert packer._all_native == []
    with pytest.raises(RuntimeError, match="close"):
        handles[0].pack(np.arange(2, dtype=np.int64), 2)


# ---- empty working-set lookup (round-2 finding: IndexError not KeyError) ---


def test_empty_working_set_lookup_raises_keyerror():
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ws = PassWorkingSet()
    ws.finalize(table, round_to=8)
    with pytest.raises(KeyError, match="empty"):
        ws.lookup(np.array([42], dtype=np.uint64))
    assert len(ws.lookup(np.zeros(0, dtype=np.uint64))) == 0


def test_empty_distributed_working_set_lookup_raises_keyerror():
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet

    class _OneRankTransport:
        rank, n_ranks = 0, 1

        def alltoall(self, payloads, tag):
            return list(payloads)

        def allgather(self, payload, tag):
            return [payload]

        def allreduce_max(self, value, tag):
            return int(value)

    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    dws = DistributedWorkingSet(_OneRankTransport(), n_mesh_shards=1)
    dws.finalize(table, round_to=8)
    with pytest.raises(KeyError, match="empty"):
        dws.lookup(np.array([42], dtype=np.uint64))


def test_shuffle_router_chunked_exchange_preserves_multiset():
    """Tiny shuffle_chunk_bytes forces many sub-chunks per destination; the
    exchanged record multiset must survive chunking exactly (and empty
    destinations still deliver their zero-count header)."""
    import numpy as np

    from paddlebox_tpu import config
    from paddlebox_tpu.data.record_store import ColumnarRecords
    from paddlebox_tpu.data.slot_record import SlotRecord
    from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
    from paddlebox_tpu.parallel.transport import TcpTransport, TcpShuffleRouter

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1), SlotInfo("s0")],
        label_slot="label",
    )

    def mk_store(keys):
        recs = [
            SlotRecord(
                u64_values=np.array([k], np.uint64),
                u64_offsets=np.array([0, 1], np.uint32),
                f_values=np.array([float(k % 2)], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
            )
            for k in keys
        ]
        return ColumnarRecords.from_records(recs, schema)

    import socket as _s

    socks = [_s.socket() for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    eps = [f"127.0.0.1:{p}" for p in ports]  # same pattern as _free_ports
    t0 = TcpTransport(0, eps)
    t1 = TcpTransport(1, eps)
    r0, r1 = TcpShuffleRouter(t0), TcpShuffleRouter(t1)

    prev = config.get_flag("shuffle_chunk_bytes")
    config.set_flag("shuffle_chunk_bytes", 64)  # ~a few records per chunk
    try:
        import threading

        # rank 0 sends 100 records to rank 1 and 3 to itself; rank 1 sends
        # nothing anywhere (empty-destination headers)
        out = {}

        def run0():
            r0.exchange(0, [mk_store(range(1, 4)), mk_store(range(100, 200))])
            out[0] = r0.collect(0)

        def run1():
            empty = mk_store([])
            r1.exchange(1, [empty, empty])
            out[1] = r1.collect(1)

        th = [threading.Thread(target=run0), threading.Thread(target=run1)]
        [t.start() for t in th]
        [t.join(timeout=60) for t in th]
        assert not any(t.is_alive() for t in th), "exchange deadlocked"
        got0 = sorted(
            int(k) for c in out[0] for k in np.asarray(c.u64_values)
        )
        got1 = sorted(
            int(k) for c in out[1] for k in np.asarray(c.u64_values)
        )
        assert got0 == [1, 2, 3]
        assert got1 == list(range(100, 200))
        # chunking actually happened (many sub-chunks, not one blob)
        assert len(out[1]) > 3
    finally:
        config.set_flag("shuffle_chunk_bytes", prev)
        t0.close()
        t1.close()
