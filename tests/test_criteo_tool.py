"""Criteo convergence tool: real-format conversion + micro synthetic run."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from criteo_convergence import N_CAT, N_INT, convert_criteo_line  # noqa: E402


def test_convert_criteo_line_real_format():
    ints = [str(i * 3) for i in range(N_INT)]
    ints[4] = ""  # missing integer feature
    cats = [format(0xABCD00 + j, "08x") for j in range(N_CAT)]
    cats[7] = ""  # missing categorical
    line = "\t".join(["1"] + ints + cats)
    out = convert_criteo_line(line)
    toks = out.split()
    assert toks[0] == "1" and toks[1] == "1.0"  # label slot
    # 39 slots, each "1 <key>"
    assert len(toks) == 2 + 2 * (N_INT + N_CAT)
    keys = np.array([int(toks[3 + 2 * i]) for i in range(N_INT + N_CAT)], np.uint64)
    # slot id rides the top bits -> no cross-slot key collisions
    np.testing.assert_array_equal(keys >> np.uint64(40), np.arange(N_INT + N_CAT))
    # missing features map to the reserved bucket (key 1 in-slot), not 0
    assert int(keys[4] & ((1 << 40) - 1)) == 1
    assert int(keys[N_INT + 7] & ((1 << 40) - 1)) == 1
    # log2 bucketization: value 3 -> bucket 3 (log2(4)=2, +1)
    assert int(keys[1] & ((1 << 40) - 1)) == int(np.log2(3 + 1)) + 1 + 1

    # malformed line rejected
    assert convert_criteo_line("1\t2\t3") is None


def test_real_format_data_dir_end_to_end(tmp_path):
    """The --data-dir path runs against a checked-in Kaggle-format fixture
    (tabs, missing fields, negative ints, hex categoricals): converter +
    loader + training + artifact, end to end — so the day real data
    appears, nothing breaks."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fixture = os.path.join(repo, "tests", "fixtures", "criteo_train_sample.txt")
    data_dir = tmp_path / "criteo"
    data_dir.mkdir()
    import shutil

    shutil.copy(fixture, data_dir / "train.txt")
    out = tmp_path / "conv.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "tools", "criteo_convergence.py"),
            "--data-dir", str(data_dir),
            "--rows", "320",
            "--batch", "32",
            "--passes", "1",
            "--embedx", "4",
            "--cpu",
            "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=repo,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    art = json.loads(out.read_text())
    assert art["mode"] == "criteo-kaggle"
    assert art["rows"] == 320
    assert np.isfinite(art["final_auc"])
    assert art["table_keys"] > 0


def test_micro_synthetic_convergence(tmp_path):
    """The committed artifact flow end to end at micro scale: AUC beats
    chance on the planted-structure synthetic within one pass."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "conv.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(repo, "tools", "criteo_convergence.py"),
            "--synthetic", "--cpu", "--rows", "24000", "--passes", "4",
            "--batch", "512", "--model", "lr", "--embedx", "4",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    art = json.loads(out.read_text())
    assert art["mode"] == "synthetic-criteo-shaped"
    assert art["rows"] == 24000 and len(art["auc_per_pass"]) == 4
    assert art["auc_per_pass"][-1] > 0.6  # planted structure learned
    assert art["holdout_eval_auc"] is not None  # eval-mode pass ran
