"""Test env: force an 8-device virtual CPU mesh.

Mirrors the reference's CI posture (closed GPU libs absent, tests run the
open pipeline on CPU; SURVEY.md §4): sharding/collective paths are exercised
on a virtual device mesh; the real-TPU path is covered by bench.py and the
driver's compile checks.

Note: this environment preloads a TPU PJRT plugin via sitecustomize with
JAX_PLATFORMS baked in, and jax is already imported by then — so the switch
to CPU must go through jax.config.update, not os.environ.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _isolate_compile_cache():
    """The persistent XLA compile cache (utils/compilecache) is
    process-global jax state. A supervisor built inside one test enables it
    under that test's tmp checkpoint root; left in place it changes compile
    behavior for every later test in the process. Detach it after each
    test so suite results never depend on test order."""
    yield
    from paddlebox_tpu.utils import compilecache

    if compilecache.enabled_dir() is not None:
        compilecache.disable()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: fault-injected robustness schedules (fast ones run in tier-1)"
    )
    config.addinivalue_line("markers", "slow: excluded from the tier-1 suite")
