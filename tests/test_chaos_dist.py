"""Distributed chaos: the multi-rank host plane under seeded faults.

The acceptance bar for the distributed-robustness tentpole: a 3-rank
in-process cluster (threads, real localhost TCP) running a shuffled
distributed pass — ins_id global shuffle through TcpShuffleRouter, working
set key exchange through DistributedWorkingSet, deterministic train +
writeback — must produce row assignment, host tables, and AUC BITWISE
equal to a fault-free run while seeded ``inject()`` rules flake
``transport.send`` and ``transport.recv_frame``; a deliberately hung rank
must produce a barrier timeout naming that rank; and a PassSupervisor
verdict abort on one rank must revert and retry the pass on EVERY rank.
Deterministic, CPU-only, tier-1 under the ``chaos`` marker.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
import zlib
from types import SimpleNamespace

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, DataPoisonedError, read_dead_letter
from paddlebox_tpu.data.dataset import shuffle_route_store
from paddlebox_tpu.data.record_store import ColumnarRecords
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.parallel.transport import (
    PeerDeadError,
    TcpShuffleRouter,
    TcpTransport,
    TransportTimeout,
    VersionMismatchError,
    _CODEC_RAW,
    _CODEC_ZLIB,
    _FRAME,
    _HELLO,
    _HELLO_REPLY,
    _KIND_DATA,
    _MAGIC,
    _VERSION,
)
from paddlebox_tpu.table.dist_ws import DistributedWorkingSet
from paddlebox_tpu.table.sparse_table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train.supervisor import (
    CoordinatedAbort,
    EpochCoordinator,
    HealthGates,
    PassSupervisor,
    RetryPolicy,
)
from paddlebox_tpu.utils.faultinject import fail_nth, fail_prob, inject
from paddlebox_tpu.utils.monitor import STAT_GET

pytestmark = pytest.mark.chaos

N_RANKS = 3
S = 2  # sparse slots


@pytest.fixture(autouse=True)
def _fast_transport():
    """Test-speed transport knobs; restored after each test.

    ``transport_send_retries=6`` with a ``times``-capped fault budget below
    7 makes send-path exhaustion IMPOSSIBLE by construction — every
    injected schedule must heal, so equality assertions can't flake."""
    names = (
        "transport_heartbeat_s",
        "transport_backoff_s",
        "transport_send_retries",
        "transport_peer_dead_s",
    )
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_send_retries", 6)
    config.set_flag("transport_peer_dead_s", 60.0)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _cluster(n=N_RANKS, timeout=30.0):
    eps = [f"127.0.0.1:{p}" for p in _free_ports(n)]
    return [TcpTransport(r, eps, timeout=timeout) for r in range(n)]


def _run_ranks(fn, n=N_RANKS):
    """Run fn(rank) on n threads; re-raise the first worker exception."""
    results = [None] * n
    errors = []

    def wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[0][1]
    return results


# ---------------------------------------------------------------------------
# acceptance: shuffled distributed pass, faulted == clean bitwise
# ---------------------------------------------------------------------------

_SCHEMA = SlotSchema(
    [SlotInfo("label", type="float", dense=True, dim=1)]
    + [SlotInfo(f"s{i}") for i in range(S)],
    label_slot="label",
    parse_ins_id=True,
)


def _rank_store(rank: int) -> ColumnarRecords:
    """Deterministic per-rank records (unequal counts across ranks)."""
    rng = np.random.default_rng(1000 + rank)
    recs = []
    for i in range(24 + 8 * rank):
        keys, offs = [], [0]
        for _s in range(S):
            nk = int(rng.integers(1, 4))
            keys.extend(int(k) for k in rng.integers(1, 400, nk))
            offs.append(offs[-1] + nk)
        recs.append(
            SlotRecord(
                u64_values=np.array(keys, np.uint64),
                u64_offsets=np.array(offs, np.uint32),
                f_values=np.array([float(rng.integers(0, 2))], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
                ins_id=f"ins-{rank}-{i:04d}",
            )
        )
    return ColumnarRecords.from_records(recs, _SCHEMA)


def _distributed_pass(transports, epoch=0):
    """One full shuffled pass over the host plane (no device mesh needed:
    the classic DistributedWorkingSet finalize is pure numpy). Returns the
    per-rank observable state the bitwise assertions compare."""
    routers = [TcpShuffleRouter(t) for t in transports]

    def worker(rank):
        t = transports[rank]
        store = _rank_store(rank)
        dest = shuffle_route_store(store, N_RANKS, "ins_id", seed=0)
        routers[rank].exchange(
            rank,
            [store.select(np.nonzero(dest == d)[0]) for d in range(N_RANKS)],
        )
        got = [c for c in routers[rank].collect(rank) if len(c)]
        mine = ColumnarRecords.concat(got)

        layout = ValueLayout(embedx_dim=2)
        table = HostSparseTable(
            layout, SparseOptimizerConfig(embedx_threshold=0.0),
            n_shards=2, seed=0,
        )
        ws = DistributedWorkingSet(t, N_RANKS, pass_id=7, epoch=epoch)
        ws.add_keys(mine.u64_values)
        dev = ws.finalize(table, round_to=8)
        # deterministic order-independent "training" + writeback
        dev = dev * 1.01 + 0.25
        ws.writeback(dev)

        # per-record prediction from the GLOBAL row assignment (the thing
        # a divergent retry would corrupt), label from the record
        rows = ws.lookup(mine.u64_values)
        sums = np.add.reduceat(
            rows.astype(np.int64),
            mine.u64_base.astype(np.int64)[: len(mine)],
        ) if len(mine) else np.zeros(0, np.int64)
        preds = ((sums % 97) / 97.0).astype(np.float32)
        labels = mine.f_values[: len(mine)].astype(np.float32)
        ins = [mine.ins_id(i) for i in range(len(mine))]
        order = np.argsort(np.array(ins))
        t.barrier(f"pass-done@e{epoch}")
        keys = np.sort(table.keys())
        return dict(
            ins=[ins[i] for i in order],
            preds=preds[order],
            labels=labels[order],
            sorted_keys=ws.sorted_keys,
            rows=ws.row_of_sorted,
            capacity=ws.capacity,
            host_keys=keys,
            host_vals=table.pull_or_create(keys),
        )

    return _run_ranks(worker)


def _auc(results):
    """AUC over the globally shuffled pass, via the repo's metric."""
    import jax.numpy as jnp

    from paddlebox_tpu.metrics.auc import auc_compute, auc_init, auc_update

    preds = np.concatenate([r["preds"] for r in results])
    labels = np.concatenate([r["labels"] for r in results])
    state = auc_update(auc_init(1000), jnp.asarray(preds), jnp.asarray(labels))
    return auc_compute(state)


def test_faulted_pass_bitwise_equals_clean():
    """THE acceptance test: seeded transport.send / transport.recv_frame
    faults during a 3-rank shuffled pass; every per-rank observable (row
    assignment, capacity, host tables, ins routing) and the global AUC is
    bitwise-equal to the fault-free run."""
    tps = _cluster()
    try:
        clean = _distributed_pass(tps, epoch=0)
    finally:
        for t in tps:
            t.close()

    tps = _cluster()
    try:
        with inject(
            fail_prob("transport.send", 0.2, seed=11, times=6),
            fail_nth("transport.recv_frame", 9, times=2),
        ) as plan:
            faulted = _distributed_pass(tps, epoch=0)
        assert plan.failures("transport.send") + plan.failures(
            "transport.recv_frame"
        ) > 0, "schedule injected nothing — the test proved nothing"
    finally:
        for t in tps:
            t.close()

    for r in range(N_RANKS):
        c, f = clean[r], faulted[r]
        assert c["ins"] == f["ins"]
        assert c["capacity"] == f["capacity"]
        np.testing.assert_array_equal(c["sorted_keys"], f["sorted_keys"])
        np.testing.assert_array_equal(c["rows"], f["rows"])
        np.testing.assert_array_equal(c["preds"], f["preds"])
        np.testing.assert_array_equal(c["host_keys"], f["host_keys"])
        np.testing.assert_array_equal(c["host_vals"], f["host_vals"])
    auc_c, auc_f = _auc(clean), _auc(faulted)
    assert auc_c == auc_f
    # shuffle actually crossed ranks (the faults had something to hit)
    assert any(
        i.split("-")[1] != str(r)
        for r in range(N_RANKS)
        for i in clean[r]["ins"]
    )


def _assert_pass_equal(clean, other):
    for r in range(N_RANKS):
        c, f = clean[r], other[r]
        assert c["ins"] == f["ins"]
        assert c["capacity"] == f["capacity"]
        np.testing.assert_array_equal(c["sorted_keys"], f["sorted_keys"])
        np.testing.assert_array_equal(c["rows"], f["rows"])
        np.testing.assert_array_equal(c["preds"], f["preds"])
        np.testing.assert_array_equal(c["host_keys"], f["host_keys"])
        np.testing.assert_array_equal(c["host_vals"], f["host_vals"])


def test_corrupt_frame_day_bitwise_equals_clean():
    """Seeded corrupt-frame day (satellite of the host-wire codec): decode
    faults at wire.host_decode — a codec frame that passes CRC but fails
    inflate — kill connections mid-pass; the resync must replay each
    killed frame exactly once, leaving every per-rank observable bitwise
    equal to the clean run."""
    tps = _cluster()
    try:
        clean = _distributed_pass(tps, epoch=0)
    finally:
        for t in tps:
            t.close()

    decode_before = STAT_GET("transport.decode_errors")
    tps = _cluster()
    try:
        with inject(
            fail_nth("wire.host_decode", 2, times=1),
            fail_nth("wire.host_decode", 5, times=1),
            fail_prob("transport.send", 0.1, seed=29, times=3),
        ) as plan:
            faulted = _distributed_pass(tps, epoch=0)
        assert plan.failures("wire.host_decode") > 0, (
            "no codec frame was ever decoded — the day shipped nothing "
            "compressed and the test proved nothing"
        )
    finally:
        for t in tps:
            t.close()

    # each injected decode fault surfaced as a killed connection...
    assert (
        STAT_GET("transport.decode_errors")
        >= decode_before + plan.failures("wire.host_decode")
    )
    # ...and healed into a bitwise-identical pass (exactly-once delivery:
    # a double-delivered shuffle chunk would change n_records/preds, a
    # dropped one would change the working set)
    _assert_pass_equal(clean, faulted)
    assert _auc(clean) == _auc(faulted)


def test_codec_ablation_bitwise_equal_and_fewer_bytes():
    """THE host-wire gate at test scale: host_wire_codec on vs off (raw
    ablation) produces bitwise-identical passes, while the wire.host_*
    counters show the codec run shipping at least 2x fewer frame bytes
    and the key-exchange round at least 2x fewer request bytes."""
    def one_run():
        tps = _cluster()
        try:
            sent0 = STAT_GET("wire.host_bytes_sent")
            req0 = STAT_GET("wire.ws_req_bytes")
            res = _distributed_pass(tps, epoch=0)
            return res, (
                STAT_GET("wire.host_bytes_sent") - sent0,
                STAT_GET("wire.ws_req_bytes") - req0,
            )
        finally:
            for t in tps:
                t.close()

    assert config.get_flag("host_wire_codec")  # default on
    codec_res, (codec_sent, codec_req) = one_run()
    config.set_flag("host_wire_codec", False)
    try:
        raw_res, (raw_sent, raw_req) = one_run()
    finally:
        config.set_flag("host_wire_codec", True)

    _assert_pass_equal(codec_res, raw_res)
    assert _auc(codec_res) == _auc(raw_res)
    assert codec_sent > 0 and raw_sent > 0
    assert raw_sent >= 2 * codec_sent, (
        f"raw ablation shipped {raw_sent} frame bytes vs {codec_sent} "
        "with the codec — the >=2x gate failed"
    )
    assert raw_req >= 2 * codec_req, (
        f"key-exchange round: raw {raw_req} vs codec {codec_req} bytes"
    )


def test_barrier_timeout_names_hung_rank():
    """Ranks 0 and 1 reach the barrier; rank 2 never does. The timeout
    error must name rank 2 (and only rank 2) as the straggler."""
    tps = _cluster()
    try:
        def worker(rank):
            if rank == 2:
                return None  # deliberately hung (never enters the barrier)
            with pytest.raises(TransportTimeout) as ei:
                tps[rank].barrier("hung", timeout=1.0)
            return str(ei.value)

        msgs = _run_ranks(worker)
        for r in (0, 1):
            assert "rank 2" in msgs[r], msgs[r]
            assert f"rank {1 - r}" not in msgs[r], msgs[r]
            assert "barrier:hung" in msgs[r]
            assert "waiting on" in msgs[r]
    finally:
        for t in tps:
            t.close()


# ---------------------------------------------------------------------------
# failure detector
# ---------------------------------------------------------------------------

def test_failure_detector_suspect_then_dead():
    """A peer that stops beating transitions alive -> suspect -> dead, and
    a collective waiting on it fails fast NAMING the dead rank instead of
    running out the full timeout."""
    config.set_flag("transport_peer_dead_s", 0.6)
    tps = _cluster(2)
    try:
        tps[0].send(1, "hello", b"x")
        assert tps[1].recv("hello", 0, timeout=5.0) == b"x"
        deadline = time.monotonic() + 5.0
        while tps[0].peer_status(1) != "alive":
            assert time.monotonic() < deadline
            time.sleep(0.01)
        tps[1].close()  # rank 1 dies: no more beats toward rank 0
        seen = set()
        while time.monotonic() < deadline:
            st = tps[0].peer_status(1)
            seen.add(st)
            if st == "dead":
                break
            time.sleep(0.01)
        assert seen >= {"suspect", "dead"}, seen
        t0 = time.monotonic()
        with pytest.raises(PeerDeadError) as ei:
            tps[0].barrier("dead-peer", timeout=30.0)
        assert time.monotonic() - t0 < 5.0  # fail-fast, not the 30s budget
        assert ei.value.dead == [1]
        assert "rank(s) [1]" in str(ei.value)
    finally:
        for t in tps:
            t.close()


# ---------------------------------------------------------------------------
# epoch coordination
# ---------------------------------------------------------------------------

def test_epoch_coordinator_abort_and_lockstep_retry():
    """Rank 1 votes NO at epoch 0: every rank sees the abort with rank 1's
    detail; after advance() the epoch-1 exchange is clean and a straggler
    frame from epoch 0 can no longer be delivered."""
    tps = _cluster()
    try:
        coords = [EpochCoordinator(t, timeout=10.0) for t in tps]
        # a frame the aborted attempt left in flight
        tps[0].send(2, "ws-req:7@e0", b"stale")

        def round0(rank):
            return coords[rank].exchange_verdict(
                "pass:1", ok=(rank != 1), detail="" if rank != 1 else "auc gate"
            )

        for ok, detail in _run_ranks(round0):
            assert not ok
            assert "rank 1" in detail and "auc gate" in detail

        before = STAT_GET("transport.stale_frames_dropped")
        for c in coords:
            c.advance()
            assert c.epoch == 1
        # the stale epoch-0 frame was purged on rank 2
        assert STAT_GET("transport.stale_frames_dropped") > before
        with pytest.raises(TransportTimeout):
            tps[2].recv("ws-req:7@e0", 0, timeout=0.3)

        def round1(rank):
            return coords[rank].exchange_verdict("pass:1", ok=True)

        assert all(ok for ok, _ in _run_ranks(round1))
    finally:
        for t in tps:
            t.close()


# ---------------------------------------------------------------------------
# PassSupervisor: coordinated revert/retry across ranks
# ---------------------------------------------------------------------------

class _FakeDS:
    """Minimal dataset double for the supervised pass loop (the real
    revert/rollback machinery is pinned by test_chaos.py; here the surface
    under test is the cross-rank verdict/epoch protocol)."""

    def __init__(self):
        self.table = None
        self._in_pass = False
        self.pass_epoch = 0
        self.begun = self.ended = self.reverted = 0

    def set_date(self, date):
        pass

    def set_filelist(self, files):
        pass

    def load_into_memory(self):
        pass

    def begin_pass(self, round_to=512, enable_revert=False, trainer=None):
        self._in_pass = True
        self.begun += 1

    def end_pass(self, table, shrink=True):
        self._in_pass = False
        self.ended += 1

    def revert_pass(self):
        self._in_pass = False
        self.reverted += 1
        self.pass_epoch += 1


def _fake_trainer(aucs):
    it = iter(aucs)

    return SimpleNamespace(
        prepare_pass=lambda ds, n: None,
        train_pass=lambda ds, n_batches=None: {
            "batches": 4.0,
            "nan_batches": 0.0,
            "auc": next(it),
        },
        trained_table=lambda: None,
    )


def test_supervisor_peer_abort_reverts_all_ranks():
    """Rank 1's AUC gate rejects attempt 1; rank 0 (locally healthy) must
    hear the NO, revert too, and both ranks retry in the next epoch and
    confirm exactly once."""
    tps = _cluster(2)
    try:
        sups = []
        for r in range(2):
            ds = _FakeDS()
            tr = _fake_trainer([0.1, 0.9] if r == 1 else [0.9, 0.9])
            sups.append(
                PassSupervisor(
                    ds, tr,
                    gates=HealthGates(auc_absolute_floor=0.5, auc_min_history=99),
                    retry=RetryPolicy(backoff_s=0.0, sleep=lambda s: None),
                    transport=tps[r],
                )
            )

        outs = _run_ranks(lambda r: sups[r].run_pass(["f"]), n=2)
        for r, sup in enumerate(sups):
            assert outs[r]["auc"] == 0.9
            assert sup.ds.begun == 2 and sup.ds.reverted == 1
            assert sup.ds.ended == 1  # confirmed exactly once, after retry
            assert sup.coord.epoch == 1  # lockstep epoch bump
        kinds = [[i.kind for i in sup.incidents] for sup in sups]
        assert "peer_abort" in kinds[0], kinds[0]
        assert "gate_auc" in kinds[1], kinds[1]
    finally:
        for t in tps:
            t.close()


def test_supervisor_peer_load_failure_aborts_cleanly():
    """Rank 1's load dies for good: rank 0 must get a PassFailure naming
    the peer instead of hanging in the first exchange; nothing was armed,
    so nothing reverts."""
    from paddlebox_tpu.train.supervisor import PassFailure

    tps = _cluster(2)
    try:
        sups = []
        for r in range(2):
            ds = _FakeDS()
            if r == 1:
                def _boom():
                    raise OSError("input never materialized")

                ds.load_into_memory = _boom
            sups.append(
                PassSupervisor(
                    ds, _fake_trainer([0.9]),
                    retry=RetryPolicy(
                        max_retries=1, backoff_s=0.0, sleep=lambda s: None
                    ),
                    transport=tps[r],
                )
            )

        def worker(r):
            with pytest.raises(PassFailure) as ei:
                sups[r].run_pass(["f"])
            return str(ei.value)

        msgs = _run_ranks(worker, n=2)
        assert "peer load failed" in msgs[0]
        assert "load failed" in msgs[1]
        assert sups[0].ds.reverted == 0 and sups[0].ds.ended == 0
    finally:
        for t in tps:
            t.close()


# ---------------------------------------------------------------------------
# PassSupervisor: poison verdict rides the coordinated allgather
# ---------------------------------------------------------------------------

_POISON_DATE = "20260101"

# every one of these fails BOTH parser tiers
_GARBAGE = [
    "3 zz !! corrupt",
    "?? ?? ??",
    "1 1.0 one 5",
    "2 0.5 x",
    "1 not-a-float 1 5",
]


def _write_pass_file(path, seed, poison=False):
    """64 deterministic slot lines; with poison=True, garbage lines are
    INSERTED so the surviving records equal the clean file's records."""
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(64):
        parts = [f"1 {float(rng.integers(0, 2))}"]
        for _s in range(S):
            k = int(rng.integers(1, 3))
            parts.append(
                f"{k} " + " ".join(str(v) for v in rng.integers(1, 200, k))
            )
        lines.append(" ".join(parts))
    out, injected = [], []
    for i, ln in enumerate(lines):
        if poison and i in (3, 17, 29, 41, 57):
            bad = _GARBAGE[len(injected) % len(_GARBAGE)]
            out.append(bad)
            injected.append(bad)
        out.append(ln)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("\n".join(out) + "\n")
    return str(path), injected


def _records_digest(records):
    h = 0
    for r in records:
        h = zlib.crc32(np.ascontiguousarray(r.u64_values).tobytes(), h)
        h = zlib.crc32(np.ascontiguousarray(r.f_values).tobytes(), h)
    return float(h)


def _digest_trainer():
    """Trainer double over a REAL dataset: 'training' is a digest of the
    admitted records, so lockstep admission differences are bitwise-visible.
    params=None keeps PassGuard to sparse-only snapshots."""
    calls = []

    def train_pass(ds, n_batches=None):
        calls.append(1)
        return {
            "batches": 4.0,
            "nan_batches": 0.0,
            "auc": 0.5,
            "digest": _records_digest(ds.records),
        }

    tr = SimpleNamespace(
        params=None,
        prepare_pass=lambda ds, n: None,
        train_pass=train_pass,
        trained_table=lambda: None,
    )
    return tr, calls


_DS_SCHEMA = SlotSchema(
    [SlotInfo("label", type="float", dense=True, dim=1)]
    + [SlotInfo(f"s{i}") for i in range(S)],
    label_slot="label",
)


def _mk_ds(tmp_path, tag):
    table = HostSparseTable(
        ValueLayout(embedx_dim=2), SparseOptimizerConfig(), n_shards=2, seed=0
    )
    return BoxPSDataset(
        _DS_SCHEMA, table, batch_size=16, shuffle_mode="none",
        quarantine_dir=str(tmp_path / f"q-{tag}"),
    )


def _poison_cluster(tmp_path, tps, on_poisoned, sleeps):
    """3 real datasets (rank 1's file corrupted) under coordinated
    supervisors. Returns (sups, train-call counters, files, rank 1's
    injected garbage lines)."""
    sups, callss, files, injected1 = [], [], [], None
    for r in range(N_RANKS):
        f, injected = _write_pass_file(
            tmp_path / f"r{r}" / "part.txt", seed=50 + r, poison=(r == 1)
        )
        files.append(f)
        if r == 1:
            injected1 = injected
        tr, calls = _digest_trainer()
        callss.append(calls)
        sups.append(
            PassSupervisor(
                _mk_ds(tmp_path, f"r{r}"), tr,
                retry=RetryPolicy(backoff_s=0.0, sleep=sleeps[r].append),
                round_to=8, on_poisoned=on_poisoned, transport=tps[r],
            )
        )
    return sups, callss, files, injected1


def test_poison_verdict_lockstep_fail(tmp_path):
    """Acceptance (3-rank, strict): rank 1's corrupt pass makes EVERY rank
    raise DataPoisonedError after exactly one attempt — zero training, zero
    backoff sleeps — with the clean ranks' verdict naming rank 1."""

    tps = _cluster()
    sleeps = [[] for _ in range(N_RANKS)]
    try:
        sups, callss, files, injected = _poison_cluster(
            tmp_path, tps, None, sleeps
        )

        def worker(r):
            with pytest.raises(DataPoisonedError) as ei:
                sups[r].run_pass([files[r]], date=_POISON_DATE)
            return ei.value

        errs = _run_ranks(worker)
        assert all(s == [] for s in sleeps)  # no backoff burned anywhere
        assert all(c == [] for c in callss)  # nobody trained the pass
        # the poisoned rank names its own dead-letter...
        assert "peer" not in str(errs[1])
        assert errs[1].report["bad_lines"] == len(injected)
        assert errs[1].dead_letter and os.path.exists(errs[1].dead_letter)
        # ...and the clean ranks rejected in lockstep, naming the peer
        for r in (0, 2):
            assert "peer pass data poisoned" in str(errs[r])
            assert "rank 1" in str(errs[r])
        for sup in sups:
            kinds = [(i.kind, i.action) for i in sup.incidents]
            assert kinds == [("data_poisoned", "raise")]
    finally:
        for t in tps:
            t.close()


def test_poison_verdict_lockstep_degrade(tmp_path):
    """Acceptance (3-rank, degrade): the coordinated verdict admits the
    poisoned pass on every rank; rank 1 trains exactly the surviving
    records (digest equals a local load of the pre-cleaned file) and its
    dead-letter round-trips the injected garbage."""

    tps = _cluster()
    sleeps = [[] for _ in range(N_RANKS)]
    try:
        sups, callss, files, injected = _poison_cluster(
            tmp_path, tps, "degrade", sleeps
        )
        outs = _run_ranks(
            lambda r: sups[r].run_pass([files[r]], date=_POISON_DATE)
        )
        assert all(o is not None for o in outs)
        assert all(s == [] for s in sleeps)
        assert all(c == [1] for c in callss)  # one attempt each, no retry
        for sup in sups:
            kinds = [(i.kind, i.action) for i in sup.incidents]
            assert kinds == [("data_poisoned", "degrade")]
        assert outs[1]["quarantined_bad_lines"] == float(len(injected))
        assert outs[0]["quarantined_bad_lines"] == 0.0  # peer-voted
        assert "rank 1" in sups[0].incidents[0].detail

        st = sups[1].ds.stats
        assert st.bad_lines == len(injected)
        dl = read_dead_letter(st.dead_letter)
        assert [e["line"] for e in dl["entries"]] == injected

        # rank 1's admitted pass is bitwise the pre-cleaned file
        clean_f, _ = _write_pass_file(
            tmp_path / "ref" / "part.txt", seed=51, poison=False
        )
        ref = _mk_ds(tmp_path, "ref")
        ref.set_date(_POISON_DATE)
        ref.set_filelist([clean_f])
        ref.load_into_memory()
        assert outs[1]["digest"] == _records_digest(ref.records)
    finally:
        for t in tps:
            t.close()


# ---------------------------------------------------------------------------
# wire-level hardening: CRC + protocol version
# ---------------------------------------------------------------------------

def _raw_connect(port, hello):
    s = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    s.sendall(hello)
    return s


def _assert_closed(s):
    """The peer hung up: EOF or a reset, never data."""
    s.settimeout(2.0)
    try:
        assert s.recv(1) == b""
    # either outcome — EOF bytes or a reset — proves the peer hung up
    # pbox-lint: disable=EXC007
    except (ConnectionError, OSError):
        pass
    s.close()


def _recv_exact_sock(s, n):
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        assert chunk, "peer closed before the expected reply"
        buf += chunk
    return buf


def _handshake(s, expect_delivered=None):
    """Read the listener's _HELLO_REPLY off a raw test socket."""
    magic, version, delivered = _HELLO_REPLY.unpack(
        _recv_exact_sock(s, _HELLO_REPLY.size)
    )
    assert magic == _MAGIC and version == _VERSION
    if expect_delivered is not None:
        assert delivered == expect_delivered
    return delivered


def test_version_mismatch_rejected():
    """v2-style sender vs v3 listener (the 'reverse' handshake direction):
    the listener answers with a typed reply NAMING ITS VERSION before
    closing — the raw peer can see exactly which versions disagree instead
    of diagnosing a silent hangup."""
    tps = _cluster(2)
    try:
        before = STAT_GET("transport.protocol_errors")
        s = _raw_connect(tps[0].port, _HELLO.pack(_MAGIC, _VERSION + 1, 1))
        deadline = time.monotonic() + 5.0
        while STAT_GET("transport.protocol_errors") == before:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # the reject reply carries the listener's version (delivered=0)...
        s.settimeout(5.0)
        magic, version, delivered = _HELLO_REPLY.unpack(
            _recv_exact_sock(s, _HELLO_REPLY.size)
        )
        assert magic == _MAGIC
        assert version == _VERSION  # names the incompatible listener version
        assert delivered == 0
        # ...and then the connection closes, no frame loop entered
        _assert_closed(s)
    finally:
        for t in tps:
            t.close()


def test_v3_sender_vs_v2_listener_typed_error():
    """v3 sender vs a pre-v3 listener, which rejects unknown HELLO
    versions by closing without any reply: the send must fail with the
    typed VersionMismatchError naming both versions — not a hang, not a
    generic ConnectionError after burning the retry budget."""
    ports = _free_ports(2)

    def v2_listener(srv):
        while True:
            try:
                c, _ = srv.accept()
            # accept() raising = listener socket closed = shutdown signal
            # pbox-lint: disable=EXC007
            except OSError:
                return
            c.recv(_HELLO.size)  # reads the v3 HELLO, rejects silently
            c.close()

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", ports[1]))
    srv.listen(4)
    threading.Thread(target=v2_listener, args=(srv,), daemon=True).start()
    t0 = TcpTransport(0, [f"127.0.0.1:{p}" for p in ports], timeout=5.0)
    try:
        before = STAT_GET("transport.send_retries")
        with pytest.raises(VersionMismatchError) as ei:
            t0.send(1, "x", b"hello")
        assert ei.value.local_version == _VERSION
        assert ei.value.peer_version is None  # no reply = pre-v3 signature
        assert f"local v{_VERSION}" in str(ei.value)
        assert "v2" in str(ei.value)
        # fail-fast: protocol errors never burn the reconnect retry budget
        assert STAT_GET("transport.send_retries") == before
    finally:
        t0.close()
        srv.close()


def test_v3_sender_vs_versioned_peer_typed_error():
    """A peer that DOES speak the reply protocol but at another version:
    the typed error names both sides' numbers."""
    ports = _free_ports(2)

    def listener(srv):
        while True:
            try:
                c, _ = srv.accept()
            # accept() raising = listener socket closed = shutdown signal
            # pbox-lint: disable=EXC007
            except OSError:
                return
            c.recv(_HELLO.size)
            c.sendall(_HELLO_REPLY.pack(_MAGIC, _VERSION - 1, 0))
            c.close()

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", ports[1]))
    srv.listen(4)
    threading.Thread(target=listener, args=(srv,), daemon=True).start()
    t0 = TcpTransport(0, [f"127.0.0.1:{p}" for p in ports], timeout=5.0)
    try:
        with pytest.raises(VersionMismatchError) as ei:
            t0.send(1, "x", b"hello")
        assert ei.value.local_version == _VERSION
        assert ei.value.peer_version == _VERSION - 1
        msg = str(ei.value)
        assert f"local v{_VERSION}" in msg and f"peer v{_VERSION - 1}" in msg
    finally:
        t0.close()
        srv.close()


def test_crc_corruption_drops_frame_and_connection():
    tps = _cluster(2)
    try:
        s = _raw_connect(tps[0].port, _HELLO.pack(_MAGIC, _VERSION, 1))
        s.settimeout(5.0)
        _handshake(s, expect_delivered=0)
        tag, payload = b"evil", b"corrupted-payload"
        crc = zlib.crc32(tag + payload) ^ 0xDEADBEEF
        before = STAT_GET("transport.crc_errors")
        s.sendall(
            _FRAME.pack(1, _KIND_DATA, _CODEC_RAW, len(tag), len(payload), crc)
            + tag
            + payload
        )
        deadline = time.monotonic() + 5.0
        while STAT_GET("transport.crc_errors") == before:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # connection was dropped, and the corrupt frame never delivered
        _assert_closed(s)
        with pytest.raises(TransportTimeout):
            tps[0].recv("evil", 1, timeout=0.3)
    finally:
        for t in tps:
            t.close()


def test_bitflipped_codec_frame_kills_connection_before_delivery():
    """A codec-framed payload whose CRC is VALID but whose compressed body
    doesn't inflate (bit-flip after checksumming, or a lying sender): the
    decode error kills the connection pre-delivery — the frame never
    reaches the inbox, and a real sender's resync would replay it."""
    tps = _cluster(2)
    try:
        s = _raw_connect(tps[0].port, _HELLO.pack(_MAGIC, _VERSION, 1))
        s.settimeout(5.0)
        _handshake(s, expect_delivered=0)
        tag, payload = b"evil", b"this-is-not-a-zlib-frame"
        crc = zlib.crc32(tag + payload)  # CRC itself is fine
        before = STAT_GET("transport.decode_errors")
        s.sendall(
            _FRAME.pack(1, _KIND_DATA, _CODEC_ZLIB, len(tag), len(payload), crc)
            + tag
            + payload
        )
        deadline = time.monotonic() + 5.0
        while STAT_GET("transport.decode_errors") == before:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        _assert_closed(s)
        with pytest.raises(TransportTimeout):
            tps[0].recv("evil", 1, timeout=0.3)
    finally:
        for t in tps:
            t.close()


def test_send_error_counted_when_retries_exhausted():
    """A peer that is gone for good surfaces a ConnectionError naming the
    destination, and the failure is counted — never silently swallowed."""
    config.set_flag("transport_send_retries", 1)
    ports = _free_ports(2)
    eps = [f"127.0.0.1:{p}" for p in ports]
    t0 = TcpTransport(0, eps, timeout=5.0)
    try:
        before = STAT_GET("transport.send_errors")
        with pytest.raises(ConnectionError) as ei:
            t0.send(1, "to-nobody", b"x")
        assert "rank 1" in str(ei.value)
        assert STAT_GET("transport.send_errors") == before + 1
    finally:
        t0.close()
