"""Data plane tests: parser, records, columnar batches.

Modeled on the reference's data_feed tests (framework/data_feed_test.cc,
test_paddlebox_datafeed.py): tiny inline samples through the real pipeline.
"""

import numpy as np
import pytest

from paddlebox_tpu.data import (
    SlotInfo,
    SlotSchema,
    build_batch,
    parse_line,
    parse_logkey,
)


def make_schema(**kw):
    return SlotSchema(
        [
            SlotInfo("label", type="float", dense=True, dim=1),
            SlotInfo("dense", type="float", dense=True, dim=3),
            SlotInfo("s0", type="uint64"),
            SlotInfo("s1", type="uint64"),
            SlotInfo("unused", type="uint64", used=False),
        ],
        label_slot="label",
        **kw,
    )


def test_parse_basic():
    schema = make_schema()
    line = "1 1.0 3 0.5 0.0 2.5 2 11 22 1 33 2 7 8"
    rec = parse_line(line, schema)
    assert rec is not None
    # label slot is dense: keeps the 1.0
    np.testing.assert_allclose(rec.slot_floats(0), [1.0])
    # dense slot keeps the 0.0 (dense slots keep zeros)
    np.testing.assert_allclose(rec.slot_floats(1), [0.5, 0.0, 2.5])
    np.testing.assert_array_equal(rec.slot_keys(0), [11, 22])
    np.testing.assert_array_equal(rec.slot_keys(1), [33])


def test_parse_drops_zero_sparse_keys():
    schema = make_schema()
    line = "1 0.0 3 1 2 3 2 0 5 1 0 1 9"
    rec = parse_line(line, schema)
    assert rec is not None
    np.testing.assert_array_equal(rec.slot_keys(0), [5])  # 0 dropped
    np.testing.assert_array_equal(rec.slot_keys(1), [])  # all dropped


def test_parse_rejects_all_zero_record():
    schema = make_schema()
    line = "1 0.0 3 1 2 3 1 0 1 0 1 9"
    assert parse_line(line, schema) is None


def test_parse_zero_count_raises():
    schema = make_schema()
    with pytest.raises(ValueError):
        parse_line("1 0.0 3 1 2 3 0 1 33 1 7", schema)


def test_logkey():
    # hex layout: cmatch [11:14), rank [14:16), search_id [16:32)
    lk = "0" * 11 + "0ab" + "03" + "0000000000000111"
    sid, cmatch, rank = parse_logkey(lk)
    assert sid == 0x111 and cmatch == 0xAB and rank == 3


def test_parse_logkey_line():
    schema = make_schema(parse_logkey=True)
    lk = "0" * 11 + "001" + "02" + "00000000000000ff"
    line = f"1 {lk} 1 1.0 3 1 2 3 1 42 1 43 1 7"
    rec = parse_line(line, schema)
    assert rec.search_id == 0xFF and rec.cmatch == 1 and rec.rank == 2


def test_build_batch_layout():
    schema = make_schema()
    lines = [
        "1 1.0 3 1 2 3 2 11 22 1 33 1 7",
        "1 0.0 3 4 5 6 1 44 2 55 66 1 7",
    ]
    recs = [parse_line(l, schema) for l in lines]
    batch = build_batch(recs, schema)
    assert batch.batch_size == 2
    assert batch.num_sparse_slots == 2
    # slot-major keys: slot s0 (both ins), then slot s1
    np.testing.assert_array_equal(batch.keys, [11, 22, 44, 33, 55, 66])
    np.testing.assert_array_equal(batch.key_offsets[0], [0, 2, 3])
    np.testing.assert_array_equal(batch.key_offsets[1], [3, 4, 6])
    # segment ids: slot*B+ins per key
    np.testing.assert_array_equal(batch.segment_ids(), [0, 0, 1, 2, 3, 3])
    # labels / dense floats
    li = schema.float_slot_index("label")
    np.testing.assert_allclose(batch.dense_float_matrix(li, 1)[:, 0], [1.0, 0.0])
    di = schema.float_slot_index("dense")
    assert batch.dense_float_matrix(di, 3).shape == (2, 3)


def test_ragged_dense_slot_padding():
    schema = make_schema()
    # second record's dense slot has only 2 of 3 values after zero-drop? dense
    # keeps zeros, so craft genuinely short slot
    recs = [
        parse_line("1 1.0 3 1 2 3 1 11 1 33 1 7", schema),
        parse_line("1 0.0 2 4 5 1 44 1 55 1 7", schema),  # only 2 dense vals
    ]
    batch = build_batch(recs, schema)
    di = schema.float_slot_index("dense")
    m = batch.dense_float_matrix(di, 3)
    np.testing.assert_allclose(m[1], [4.0, 5.0, 0.0])
