"""Streaming plane: tail-follow ingestion, crash-safe cuts, compaction.

Pins the recovery CONTRACTS of the PR 20 streaming tentpole
(train/stream.py + CheckpointManager.compact), not just that code runs:

- partial-tail holdback: an incomplete last line of a still-appending file
  is held for the next poll, never consumed torn or quarantined;
- ``stream.tail_read`` (FLT008): a failed tail read holds the position —
  the healed retry re-reads the same bytes, zero records lost;
- ``stream.cut_publish`` (FLT008): a crash in EITHER cut window recovers
  exactly-once — the restarted stream's table is bitwise-identical to an
  uninterrupted twin (no record dropped, none replayed);
- ``ckpt.compact`` (FLT008): a crash in any compact window leaves the old
  chain servable bitwise, and the healed retry folds bitwise;
- compacted-chain resume and follower catch-up are bitwise-equal to the
  uncompacted chain;
- streaming-off ablation: the classic file-list pass mode over the same
  records is bitwise-identical to the streamed cuts;
- a forced mid-stream ownership re-anchor pauses the cut, re-anchors on a
  fresh base, and resumes from the cursor (digest equal to a no-flip twin);
- backlog past budget stretches cadence (``stream.backlog_stretches``)
  instead of crashing, and shrinks back when the backlog drains.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.serve.follower import Follower, apply_published_chain
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import CheckpointManager, CTRTrainer, TrainStepConfig
from paddlebox_tpu.train.stream import (
    DirectoryTailer,
    StreamLineageError,
    StreamSupervisor,
)
from paddlebox_tpu.train.supervisor import HealthGates, PassSupervisor
from paddlebox_tpu.utils.faultinject import InjectedFault, fail_nth, inject
from paddlebox_tpu.utils.monitor import STAT_GET, STAT_HIST

S, B = 4, 16
DATE = "20260807"
LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(
    embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
)
SCHEMA = SlotSchema(
    [SlotInfo("label", type="float", dense=True, dim=1)]
    + [SlotInfo(f"s{i}") for i in range(S)],
    label_slot="label",
)


def _digest(table) -> str:
    """sha256 over the key-sorted full snapshot: bitwise table identity."""
    k = np.sort(table.keys())
    v = table.pull_or_create(k)
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(k).tobytes())
    h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()


def _build(root):
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ds = BoxPSDataset(SCHEMA, table, batch_size=B, shuffle_mode="none")
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=LAYOUT, sparse_opt=OPT,
        auc_buckets=100,
    )
    model = DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,))
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    mgr = CheckpointManager(str(root))
    # micro-passes are tiny by construction: the trailing-AUC gate has no
    # signal at this scale (same knob chaos_probe uses)
    sup = PassSupervisor(
        ds, tr, checkpoint=mgr, gates=HealthGates(auc_min_history=99)
    )
    return table, tr, mgr, sup


def _chunk_lines(rng, rows, lo):
    lines = []
    for _ in range(rows):
        keys = rng.integers(lo, lo + 200, S)
        lines.append(
            f"1 {float(keys[0] % 2)} " + " ".join(f"1 {k}" for k in keys)
        )
    return lines


def _append(stream_dir, name, lines, partial=None):
    # fixture writer emulating the upstream log appender
    # pbox-lint: disable=IO004
    with open(os.path.join(str(stream_dir), name), "a") as f:
        f.write("\n".join(lines) + "\n")
        if partial is not None:
            f.write(partial)  # mid-record flush: no trailing newline
        f.flush()


CHUNKS = [(24, 0), (24, 100), (24, 200), (24, 300)]


def _stream_leg(root, stream_dir, chunks=CHUNKS, compact_every=0, seed=7):
    """Uninterrupted streaming run: one appended chunk per step()."""
    table, tr, mgr, sup = _build(root)
    st = StreamSupervisor(
        sup, str(stream_dir), DATE, pattern="*.txt",
        compact_every=compact_every,
    )
    rng = np.random.default_rng(seed)
    for rows, lo in chunks:
        _append(stream_dir, "a.txt", _chunk_lines(rng, rows, lo))
        assert st.step() is not None
    return table, mgr, st


# ---------------------------------------------------------------------------
# DirectoryTailer: partial-tail holdback + append-only verification


def test_partial_tail_line_held_back_not_quarantined(tmp_path):
    t = DirectoryTailer(str(tmp_path), pattern="*.txt")
    _append(tmp_path, "a.txt", ["rec-1", "rec-2"], partial="rec-3-torn-prefi")
    lines, _ = t.poll()
    # only the COMPLETE lines came out; the torn record stayed private
    assert lines == ["rec-1", "rec-2"]
    off = t.positions["a.txt"]["offset"]
    assert off == len(b"rec-1\nrec-2\n")
    # a poll while the writer is still mid-flush consumes nothing
    assert t.poll()[0] == []
    # the writer finishes the record (and appends another): the ONCE-torn
    # line arrives whole, exactly once
    # pbox-lint: disable=IO004
    with open(tmp_path / "a.txt", "a") as f:
        f.write("x\nrec-4\n")
    lines, _ = t.poll()
    assert lines == ["rec-3-torn-prefix", "rec-4"]


def test_tailer_resume_detects_rewritten_history(tmp_path):
    t = DirectoryTailer(str(tmp_path), pattern="*.txt")
    _append(tmp_path, "a.txt", ["rec-1", "rec-2"])
    t.poll()
    cursor = t.snapshot_positions()
    # same-length rewrite of consumed bytes: offset still fits, CRC must not
    # pbox-lint: disable=IO004
    with open(tmp_path / "a.txt", "w") as f:
        f.write("REC-1\nREC-2\n")
    t2 = DirectoryTailer(str(tmp_path), pattern="*.txt")
    with pytest.raises(StreamLineageError):
        t2.resume(cursor)


# ---------------------------------------------------------------------------
# stream.tail_read (FLT008): a failed read holds the position — the healed
# retry re-reads the SAME bytes, so a transient I/O error costs latency,
# never records.


def test_tail_read_fault_holds_position_zero_loss(tmp_path):
    t = DirectoryTailer(str(tmp_path), pattern="*.txt")
    _append(tmp_path, "a.txt", ["rec-1", "rec-2"])
    with inject(fail_nth("stream.tail_read", 1)) as plan:
        errs0 = STAT_GET("stream.tail_read_errors")
        lines, _ = t.poll()
        assert plan.failures("stream.tail_read") == 1
        assert lines == []  # the read failed: nothing consumed
        assert t.positions["a.txt"]["offset"] == 0  # position held
        assert STAT_GET("stream.tail_read_errors") == errs0 + 1
        # healed retry (same plan): the SAME bytes come out — zero loss
        lines, _ = t.poll()
        assert lines == ["rec-1", "rec-2"]


# ---------------------------------------------------------------------------
# stream.cut_publish (FLT008): crash in either cut window, restart from
# disk, and the table is bitwise-identical to an uninterrupted twin.
# Window 1 (hit 1): intent durable, nothing trained -> the restart replays
# the durable spool (zero loss). Window 2 (hit 2): delta published, stream
# cursor stale -> the restart finalizes WITHOUT retraining (zero dup).


@pytest.mark.parametrize("hit,stat", [(1, "stream.replays"),
                                      (2, "stream.replays_skipped")])
def test_cut_crash_window_recovers_exactly_once(tmp_path, hit, stat):
    clean_root, clean_stream = tmp_path / "c", tmp_path / "cs"
    kill_root, kill_stream = tmp_path / "k", tmp_path / "ks"
    for d in (clean_root, clean_stream, kill_root, kill_stream):
        d.mkdir()
    clean_table, _, _ = _stream_leg(clean_root, clean_stream)

    table, tr, mgr, sup = _build(kill_root)
    st = StreamSupervisor(sup, str(kill_stream), DATE, pattern="*.txt",
                          compact_every=0)
    rng = np.random.default_rng(7)
    for i, (rows, lo) in enumerate(CHUNKS):
        _append(kill_stream, "a.txt", _chunk_lines(rng, rows, lo))
        if i == 1:
            with inject(fail_nth("stream.cut_publish", hit)) as plan:
                with pytest.raises(InjectedFault):
                    st.step()
                assert plan.failures("stream.cut_publish") == 1
            before = STAT_GET(stat)
            # "restart": rebuild the whole stack from durable state only
            table, tr, mgr, sup = _build(kill_root)
            mgr.resume(table, tr)
            st = StreamSupervisor(sup, str(kill_stream), DATE,
                                  pattern="*.txt", compact_every=0)
            assert STAT_GET(stat) == before + 1
            continue  # the crashed cut's records are recovered, not re-cut
        st.step()
    assert st.cut_seq == len(CHUNKS)
    assert _digest(table) == _digest(clean_table)
    # and the published chain agrees with the live table
    ft = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    apply_published_chain(str(kill_root), ft)
    assert _digest(ft) == _digest(clean_table)


# ---------------------------------------------------------------------------
# ckpt.compact (FLT008): a crash in ANY compact window leaves the old
# chain servable bitwise; the healed retry folds bitwise.


@pytest.mark.parametrize("hit", [1, 2, 3])
def test_compact_crash_leaves_old_chain_servable_bitwise(tmp_path, hit):
    root, stream = tmp_path / "r", tmp_path / "s"
    root.mkdir(); stream.mkdir()
    table, mgr, st = _stream_leg(root, stream, compact_every=0)
    want = _digest(table)
    with inject(fail_nth("ckpt.compact", hit)) as plan:
        with pytest.raises(InjectedFault):
            st.mgr.compact(
                DATE, HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
            )
        assert plan.failures("ckpt.compact") == 1
    # old chain still resumes bitwise (cursor never named a torn fold)
    t2, _, mgr2, _ = _build(root)
    state = mgr2.resume(t2)
    assert _digest(t2) == want
    # healed retry folds; the folded resume is bitwise-equal too
    assert mgr.compact(
        DATE, HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ) is not None
    t3, _, mgr3, _ = _build(root)
    state = mgr3.resume(t3)
    assert int(state.get("compact") or 0) == len(CHUNKS) - 1
    assert _digest(t3) == want


# ---------------------------------------------------------------------------
# compaction invariants: compacted resume and follower catch-up are
# bitwise-equal to the uncompacted chain, and catch-up applies O(tail).


def test_compacted_chain_bitwise_and_catchup_bounded(tmp_path):
    plain_root, plain_stream = tmp_path / "p", tmp_path / "ps"
    comp_root, comp_stream = tmp_path / "c", tmp_path / "cs"
    for d in (plain_root, plain_stream, comp_root, comp_stream):
        d.mkdir()
    plain_table, plain_mgr, _ = _stream_leg(plain_root, plain_stream)
    comp_table, comp_mgr, _ = _stream_leg(
        comp_root, comp_stream, compact_every=3
    )
    want = _digest(plain_table)
    assert _digest(comp_table) == want  # compaction never perturbs training
    cur = comp_mgr.cursor()
    covers = int(cur.get("compact") or 0)
    assert covers == 3 and cur["delta_idx"] == len(CHUNKS) - 1

    # trainer resume through the fold == uncompacted resume, bitwise
    t_plain, _, m_plain, _ = _build(plain_root)
    m_plain.resume(t_plain)
    t_comp, _, m_comp, _ = _build(comp_root)
    state = m_comp.resume(t_comp)
    assert int(state.get("compact")) == covers
    assert _digest(t_plain) == want and _digest(t_comp) == want

    # follower catch-up fast-forwards through the fold: one compact load
    # + the post-fold tail, not the whole minute-level chain
    ff0 = STAT_GET("serve.compact_fastforwards")
    ft = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    pos = apply_published_chain(str(comp_root), ft)
    assert STAT_GET("serve.compact_fastforwards") == ff0 + 1
    assert pos["delta_idx"] == cur["delta_idx"]
    assert _digest(ft) == want

    # a polling Follower takes the same fast path: commits = 1 (fold head)
    # + tail deltas, far fewer than the chain length as the day grows
    fol = Follower(str(comp_root), LAYOUT, OPT, n_host_shards=2)
    applies0 = STAT_GET("serve.applies")
    assert fol.poll_once()
    applies = STAT_GET("serve.applies") - applies0
    assert applies == (cur["delta_idx"] - covers) + 1
    # the stream watermark stamps freshness at the chain-head commit
    hist = STAT_HIST("serve.freshness_s")
    assert hist is not None and hist.count > 0


# ---------------------------------------------------------------------------
# streaming-off ablation: the classic file-list pass mode over the same
# records is bitwise-identical to the streamed cuts.


def test_streaming_off_ablation_bitwise(tmp_path):
    s_root, s_stream = tmp_path / "s", tmp_path / "ss"
    c_root = tmp_path / "c"
    for d in (s_root, s_stream, c_root):
        d.mkdir()
    s_table, _, _ = _stream_leg(s_root, s_stream)

    # classic mode: one file per pass, save_base then save_delta — the
    # exact records each cut spooled, replayed as a file list
    table, tr, mgr, sup = _build(c_root)
    rng = np.random.default_rng(7)
    for i, (rows, lo) in enumerate(CHUNKS):
        path = str(c_root / f"pass-{i}.txt")
        # pbox-lint: disable=IO004
        with open(path, "w") as f:
            f.write("\n".join(_chunk_lines(rng, rows, lo)) + "\n")
        sup.run_pass([path], date=DATE, save="base" if i == 0 else "delta")
    assert _digest(table) == _digest(s_table)


# ---------------------------------------------------------------------------
# elastic composition: a forced ownership re-anchor mid-stream pauses the
# cut, re-anchors on a fresh base under the new epoch, and the stream
# resumes from its cursor — digest equal to a twin that never flipped.


def test_forced_reanchor_mid_stream_resumes_from_cursor(tmp_path):
    plain_root, plain_stream = tmp_path / "p", tmp_path / "ps"
    flip_root, flip_stream = tmp_path / "f", tmp_path / "fs"
    for d in (plain_root, plain_stream, flip_root, flip_stream):
        d.mkdir()
    plain_table, _, _ = _stream_leg(plain_root, plain_stream)

    table, tr, mgr, sup = _build(flip_root)
    st = StreamSupervisor(sup, str(flip_stream), DATE, pattern="*.txt",
                          compact_every=0)
    rng = np.random.default_rng(7)
    for i, (rows, lo) in enumerate(CHUNKS):
        if i == 2:
            # ownership flip lands between cuts (what the elastic death/
            # join handlers do): the next save must re-anchor, not extend
            mgr.ownership_epoch += 1
            sup._force_base = True
        _append(flip_stream, "a.txt", _chunk_lines(rng, rows, lo))
        st.step()
    cur = mgr.cursor()
    # cut 3 re-anchored: a fresh base (delta_idx counts from 0 again)
    # under the new epoch, then cut 4 extended it as delta-0001
    assert int(cur["ownership_epoch"]) == 1
    assert cur["delta_idx"] == 1
    assert st.cut_seq == len(CHUNKS)  # no cut lost to the flip
    assert _digest(table) == _digest(plain_table)
    # the published chain under the new epoch is followable end-to-end
    ft = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    pos = apply_published_chain(str(flip_root), ft)
    assert pos["ownership_epoch"] == 1
    assert _digest(ft) == _digest(plain_table)


# ---------------------------------------------------------------------------
# backlog degradation: cuts that overrun the budget stretch the cadence
# (counted), capped at the flag, and shrink back once the backlog drains.


def test_backlog_stretches_cadence_and_recovers(tmp_path):
    import threading

    root, stream = tmp_path / "r", tmp_path / "s"
    root.mkdir(); stream.mkdir()
    table, tr, mgr, sup = _build(root)
    clk = {"t": 0.0}
    st = StreamSupervisor(
        sup, str(stream), DATE, pattern="*.txt",
        micro_pass_s=1.0, poll_interval_s=0.25, compact_every=0,
        clock=lambda: clk["t"],
    )
    # every cut "takes" 3x its window: _train_publish is wrapped to charge
    # fake time, simulating ingest backlog without wall-clock sleeps
    real_tp = st._train_publish

    def slow_tp(*a, **kw):
        out = real_tp(*a, **kw)
        clk["t"] += 3.0 * st.micro_pass_s * st._stretch
        return out

    st._train_publish = slow_tp
    rng = np.random.default_rng(7)
    stop = threading.Event()

    def sleep_fn(dt):
        clk["t"] += max(dt, 0.05)
        if st.cut_seq >= 3:
            stop.set()
        else:  # the upstream appender outruns the (slow) cuts
            _append(stream, "a.txt", _chunk_lines(rng, 16, 100 * st.cut_seq))

    before = STAT_GET("stream.backlog_stretches")
    st.run(stop, sleep=sleep_fn)
    assert st.cut_seq >= 3
    assert STAT_GET("stream.backlog_stretches") > before
    assert st._stretch <= float(config.get_flag("stream_backlog_max_stretch"))
    # drained: fast cuts shrink the window back toward the budget
    stretched = st._stretch
    assert stretched > 1.0
    st._train_publish = real_tp
    stop2 = threading.Event()
    goal = st.cut_seq + 2

    def sleep_fast(dt):
        clk["t"] += max(dt, 0.05)
        if st.cut_seq >= goal:
            stop2.set()
        else:
            _append(stream, "a.txt", _chunk_lines(rng, 16, 900 + st.cut_seq))

    st.run(stop2, sleep=sleep_fast)
    assert st._stretch < stretched
