"""Non-uniform pipeline stages (reference: arbitrary program cut points,
optimizer.py:5194 device_guard sections).

The padded-stacking design must be EXACTLY the unpadded heterogeneous
network — values and gradients — across training steps: zero width padding
and identity layer gates may not leak into the real lanes, and the
optimizer may not move the padding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.parallel import (
    PipelineSpec,
    hetero_mlp_stage_apply,
    hetero_mlp_stage_init,
    init_pipeline_state,
    make_mesh,
    make_pipeline_train_step,
    pipeline_forward,
)
from paddlebox_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

# 4 stages, heterogeneous widths AND layer counts; H = 16, L = 3
WIDTHS = [[6, 10, 16], [16, 12], [12, 9, 14, 12], [12, 8]]
N_STAGES = len(WIDTHS)
D_IN, D_OUT, H = 6, 8, 16
MB, M = 4, 6


@pytest.fixture(scope="module")
def built():
    return hetero_mlp_stage_init(jax.random.PRNGKey(7), WIDTHS)


def seq_forward(raw, x):
    """Unpadded reference: the true heterogeneous relu MLP."""
    for layers in raw:
        for w, b in layers:
            x = jax.nn.relu(x @ w + b)
    return x


def pad_x(x):
    return jnp.pad(x, ((0, 0), (0, 0), (0, H - x.shape[-1])))


def test_chain_mismatch_rejected():
    with pytest.raises(ValueError, match="emits width"):
        hetero_mlp_stage_init(jax.random.PRNGKey(0), [[4, 8], [6, 4]])


def test_hetero_forward_matches_unpadded(built):
    stages, raw = built
    plan = make_mesh(N_STAGES, axis="pp")
    spec = PipelineSpec(n_micro=M, axis_name="pp")
    fwd = pipeline_forward(hetero_mlp_stage_apply, spec)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(M, MB, D_IN)).astype(np.float32))

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    mapped = jax.jit(
        shard_map(
            lambda p, xm: fwd(jax.tree.map(lambda a: a[0], p), xm),
            mesh=plan.mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(mapped(jax.device_put(stacked, plan.batch_sharding),
                            pad_x(x)))
    want_real = np.asarray(
        jax.vmap(lambda xx: seq_forward([[(jnp.asarray(w), jnp.asarray(b))
                                          for w, b in ls] for ls in raw], xx))(x)
    )
    # real lanes match the unpadded net; padded lanes are exactly zero
    np.testing.assert_allclose(got[..., :D_OUT], want_real, rtol=2e-5, atol=2e-5)
    assert np.all(got[..., D_OUT:] == 0.0)


def test_hetero_training_matches_unpadded(built):
    """Multi-step adam on the padded pipeline == adam on the true
    heterogeneous net: no grad leakage into padding, gates never trained."""
    stages, raw = built
    plan = make_mesh(N_STAGES, axis="pp")
    spec = PipelineSpec(n_micro=M, axis_name="pp")
    opt = optax.adam(1e-2)

    def loss_fn(y, tgt):
        return jnp.mean((y[..., :D_OUT] - tgt) ** 2)

    step = make_pipeline_train_step(hetero_mlp_stage_apply, loss_fn, opt,
                                    spec, plan)
    state = init_pipeline_state(plan, stages, opt)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(M, MB, D_IN)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(M, MB, D_OUT))).astype(np.float32))

    # unpadded reference trained with the same adam
    ref_params = [[(jnp.asarray(w), jnp.asarray(b)) for w, b in ls]
                  for ls in raw]

    def ref_loss(ps):
        y = jax.vmap(lambda xx: seq_forward(ps, xx))(x)
        return jnp.mean(jax.vmap(lambda yy, tt: jnp.mean((yy - tt) ** 2))(y, tgt))

    ref_opt = opt.init(ref_params)
    xp = pad_x(x)
    for i in range(5):
        l_ref, g_ref = jax.value_and_grad(ref_loss)(ref_params)
        upd, ref_opt = opt.update(g_ref, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)
        state, loss = step(state, xp, tgt)
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=5e-5,
                                   err_msg=f"step {i}")

    # padded params: real blocks equal the reference, padding still zero,
    # gates untouched
    final = jax.tree.map(lambda a: np.asarray(a), state[0])
    for s, ls in enumerate(raw):
        for l, (w0, _) in enumerate(ls):
            d_in, d_out = w0.shape
            got_w = final["w"][s, l]
            ref_w = np.asarray(ref_params[s][l][0])
            np.testing.assert_allclose(got_w[:d_in, :d_out], ref_w,
                                       rtol=5e-4, atol=5e-5)
            assert np.all(got_w[d_in:, :] == 0.0)
            assert np.all(got_w[:, d_out:] == 0.0)
            np.testing.assert_allclose(final["b"][s, l, :d_out],
                                       np.asarray(ref_params[s][l][1]),
                                       rtol=5e-4, atol=5e-5)
            assert np.all(final["b"][s, l, d_out:] == 0.0)
    want_gate = np.zeros_like(final["g"])
    for s, ws in enumerate(WIDTHS):
        want_gate[s, : len(ws) - 1] = 1.0
    np.testing.assert_array_equal(final["g"], want_gate)


def test_hetero_composes_with_dp(built):
    """pp x dp with heterogeneous stages: one step equals the 1-D run."""
    from paddlebox_tpu.parallel.mesh import make_mesh_2d

    widths2 = [[6, 10, 16], [16, 12, 8]]
    stages2, _ = hetero_mlp_stage_init(jax.random.PRNGKey(9), widths2)
    opt = optax.adam(1e-2)

    def loss_fn(y, tgt):
        return jnp.mean((y[..., :D_OUT] - tgt) ** 2)

    rng = np.random.default_rng(2)
    x = pad_x(jnp.asarray(rng.normal(size=(M, MB, D_IN)).astype(np.float32)))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(M, MB, D_OUT))).astype(np.float32))
    spec = PipelineSpec(n_micro=M, axis_name="pp")

    plan1 = make_mesh(2, axis="pp")
    step1 = make_pipeline_train_step(hetero_mlp_stage_apply, loss_fn, opt,
                                     spec, plan1)
    st1 = init_pipeline_state(plan1, stages2, opt)
    st1, loss1 = step1(st1, x, tgt)

    plan2 = make_mesh_2d(2, 2)
    step2 = make_pipeline_train_step(hetero_mlp_stage_apply, loss_fn, opt,
                                     spec, plan2, dp_axis="dp")
    st2 = init_pipeline_state(plan2, stages2, opt, axis="pp")
    st2, loss2 = step2(st2, x, tgt)

    np.testing.assert_allclose(float(loss2), float(loss1), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(st2[0]), jax.tree.leaves(st1[0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
