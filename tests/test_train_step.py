"""End-to-end single-device slice: synthetic CTR data through the full
pull → seqpool+cvm → model → push → dense-update → AUC pipeline.

Analog of the reference's tiny end-to-end feeds (test_paddlebox_datafeed.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data.device_pack import pack_batch
from paddlebox_tpu.data.slot_record import SlotRecord, build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.metrics.auc import auc_compute
from paddlebox_tpu.models import DeepFM, LogisticRegression
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import TrainStepConfig, make_train_step
from paddlebox_tpu.train.train_step import init_train_state, jit_train_step


NUM_SLOTS = 4
VOCAB = 64
BATCH = 32


def synth_records(rng, n, schema):
    """Labels correlate with a hidden per-key weight -> learnable signal."""
    key_w = rng.normal(size=VOCAB + 1) * 1.2
    recs = []
    for _ in range(n):
        u_vals, u_off = [], np.zeros(NUM_SLOTS + 1, dtype=np.uint32)
        score = 0.0
        for s in range(NUM_SLOTS):
            k = int(rng.integers(1, VOCAB + 1))
            u_vals.append(k)
            score += key_w[k]
            u_off[s + 1] = len(u_vals)
        label = 1.0 if score + rng.normal() * 0.3 > 0 else 0.0
        recs.append(
            SlotRecord(
                u64_values=np.array(u_vals, dtype=np.uint64),
                u64_offsets=u_off,
                f_values=np.array([label], dtype=np.float32),
                f_offsets=np.array([0, 1], dtype=np.uint32),
            )
        )
    return recs


@pytest.fixture(scope="module")
def schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}", type="uint64") for i in range(NUM_SLOTS)],
        label_slot="label",
    )


def run_training(model_cls, schema, steps=60, **model_kw):
    rng = np.random.default_rng(0)
    layout = ValueLayout(embedx_dim=8)
    opt_cfg = SparseOptimizerConfig(
        embed_lr=0.3, embedx_lr=0.3, embedx_threshold=0.0, initial_range=0.01
    )
    table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)
    recs = synth_records(rng, BATCH * 8, schema)

    ws = PassWorkingSet(n_mesh_shards=1)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev_table = ws.finalize(table, round_to=64)

    model = model_cls(
        num_slots=NUM_SLOTS, feat_width=layout.pull_width, **model_kw
    )
    params = model.init(jax.random.PRNGKey(0))
    dense_opt = optax.adam(1e-2)
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS,
        batch_size=BATCH,
        layout=layout,
        sparse_opt=opt_cfg,
        auc_buckets=1000,
    )
    step = jit_train_step(make_train_step(model.apply, dense_opt, cfg))
    state = init_train_state(
        jnp.asarray(dev_table.reshape(-1, layout.width)), params, dense_opt, cfg.auc_buckets
    )

    losses = []
    for i in range(steps):
        batch_recs = [recs[j % len(recs)] for j in range(i * BATCH, (i + 1) * BATCH)]
        batch = build_batch(batch_recs, schema)
        db = pack_batch(batch, ws, schema, bucket=256)
        state, m = step(state, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
        losses.append(float(m["loss"]))

    metrics = auc_compute(state.auc)
    # flush trained table back to host store
    ws.writeback(np.asarray(state.table))
    return losses, metrics, table, ws, layout


def test_lr_learns(schema):
    losses, metrics, *_ = run_training(LogisticRegression, schema, steps=40)
    assert losses[-1] < losses[0] * 0.9
    assert metrics["auc"] > 0.6
    assert metrics["ins_num"] == 40 * BATCH


def test_deepfm_learns_and_writes_back(schema):
    losses, metrics, table, ws, layout = run_training(
        DeepFM, schema, steps=60, embedx_dim=8, hidden=(32, 16)
    )
    assert losses[-1] < losses[0] * 0.8
    assert metrics["auc"] > 0.65
    # show counters flowed back to the host store: every pass key saw traffic
    got = table.pull_or_create(ws.sorted_keys)
    assert np.all(got[:, layout.SHOW] > 0)
    # predicted ctr is calibrated-ish (sanity, not precision)
    assert 0.05 < metrics["predicted_ctr"] < 0.95


def test_train_step_deterministic(schema):
    l1, m1, *_ = run_training(LogisticRegression, schema, steps=10)
    l2, m2, *_ = run_training(LogisticRegression, schema, steps=10)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_adjust_ins_weight_formula_and_effect():
    """AdjustInsWeight parity (downpour_worker.cc:271-340): instances whose
    nid slot's show is under threshold get loss weight
    log(e + (T-show)/T * ratio); counters stay unweighted."""
    import math

    from paddlebox_tpu.data.device_pack import pack_batch
    from paddlebox_tpu.table import PassWorkingSet

    rng = np.random.default_rng(0)
    layout = ValueLayout(embedx_dim=4)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
    NS, B_, T, RATIO = 2, 8, 10.0, 5.0
    # nid slot = slot 0, single feasign per instance
    recs = []
    for i in range(B_):
        keys = np.array([100 + i, 200 + i], dtype=np.uint64)
        recs.append(SlotRecord(
            u64_values=keys, u64_offsets=np.array([0, 1, 2], np.uint32),
            f_values=np.array([float(i % 2)], np.float32),
            f_offsets=np.array([0, 1], np.uint32),
        ))
    sch = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1),
         SlotInfo("nid"), SlotInfo("s1")],
        label_slot="label",
    )
    table = HostSparseTable(layout, opt_cfg, n_shards=2, seed=0)
    ws = PassWorkingSet()
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)
    flat0 = dev.reshape(-1, layout.width)
    # plant nid shows: half under threshold, half over
    nid_keys = np.array([100 + i for i in range(B_)], np.uint64)
    nid_rows = ws.lookup(nid_keys)
    planted = np.array([0.0, 2.0, 5.0, 9.0, 10.0, 50.0, 100.0, 3.0], np.float32)
    flat0[nid_rows, layout.SHOW] = planted

    model = LogisticRegression(num_slots=NS, feat_width=layout.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B_, layout=layout, sparse_opt=opt_cfg,
        auc_buckets=100, adjust_ins_weight=(0, T, RATIO),
    )
    from paddlebox_tpu.train.train_step import adjusted_loss_weight

    batch = build_batch(recs, sch)
    db = pack_batch(batch, ws, sch, bucket=32)
    # reproduce the step's internal pull to check the weight math
    from paddlebox_tpu.ops.pull_push import pull_sparse_rows

    pulled = pull_sparse_rows(
        jnp.asarray(flat0), jnp.asarray(db.uniq_rows), layout, 0.0, 1.0
    )
    flat = jnp.take(pulled, jnp.asarray(db.inverse), axis=0)
    w, denom = adjusted_loss_weight(cfg, flat, jnp.asarray(db.segments), None, B_)
    want = np.array([
        math.log(math.e + (T - s) / T * RATIO) if s < T else 1.0
        for s in planted
    ])
    np.testing.assert_allclose(np.asarray(w), want, rtol=1e-6)
    assert float(denom) == B_

    # end to end: the step runs and under-shown instances move their nid
    # embedding MORE than well-shown ones (per unit gradient)
    step = jit_train_step(make_train_step(model.apply, optax.adam(1e-2), cfg))
    state = init_train_state(
        jnp.asarray(flat0), model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 100
    )
    state, m = step(state, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
    assert np.isfinite(float(m["loss"]))
    newt = np.asarray(state.table)
    # show counters incremented by exactly 1 (unweighted counts)
    np.testing.assert_allclose(newt[nid_rows, layout.SHOW], planted + 1.0, rtol=1e-6)


def test_adjust_ins_weight_mesh_matches_single_device():
    from paddlebox_tpu.data.device_pack import pack_batch, pack_batch_sharded
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.table import PassWorkingSet
    from paddlebox_tpu.train.sharded_step import (
        init_sharded_train_state,
        make_sharded_train_step,
    )

    rng = np.random.default_rng(1)
    layout = ValueLayout(embedx_dim=4)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
    NS, N_DEV, B_ = 2, 4, 16
    recs = []
    for i in range(B_):
        keys = rng.integers(1, 60, NS).astype(np.uint64)
        recs.append(SlotRecord(
            u64_values=keys, u64_offsets=np.arange(NS + 1, dtype=np.uint32),
            f_values=np.array([float(keys[0] % 2)], np.float32),
            f_offsets=np.array([0, 1], np.uint32),
        ))
    sch = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1),
         SlotInfo("nid"), SlotInfo("s1")],
        label_slot="label",
    )
    model = LogisticRegression(num_slots=NS, feat_width=layout.pull_width)

    def run(mesh):
        table = HostSparseTable(layout, opt_cfg, n_shards=2, seed=0)
        ws = PassWorkingSet(n_mesh_shards=N_DEV if mesh else 1)
        for r in recs:
            ws.add_keys(r.u64_values)
        dev = ws.finalize(table, round_to=32)
        cfg = TrainStepConfig(
            num_slots=NS, batch_size=(B_ // N_DEV) if mesh else B_,
            layout=layout, sparse_opt=opt_cfg, auc_buckets=100,
            adjust_ins_weight=(0, 10.0, 5.0),
            axis_name="dp" if mesh else None,
        )
        batch = build_batch(recs, sch)
        if mesh:
            plan = make_mesh(N_DEV)
            step = make_sharded_train_step(model.apply, optax.adam(1e-2), cfg, plan)
            state = init_sharded_train_state(
                plan, dev, model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 100
            )
            db = pack_batch_sharded(batch, ws, sch, N_DEV, bucket=32)
            feed = {
                k: jax.device_put(v, plan.batch_sharding)
                for k, v in db.as_dict().items()
            }
        else:
            step = jit_train_step(make_train_step(model.apply, optax.adam(1e-2), cfg))
            state = init_train_state(
                jnp.asarray(dev.reshape(-1, layout.width)),
                model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 100,
            )
            feed = None
            db = pack_batch(batch, ws, sch, bucket=64)
            feed = {k: jnp.asarray(v) for k, v in db.as_dict().items()}
        state, m = step(state, feed)
        keys = ws.sorted_keys
        tbl = np.asarray(state.table).reshape(-1, layout.width)
        return float(m["loss"]), tbl[ws.lookup(keys)], keys

    l1, t1, k1 = run(False)
    lN, tN, kN = run(True)
    np.testing.assert_allclose(l1, lN, rtol=1e-5)
    np.testing.assert_array_equal(k1, kN)
    np.testing.assert_allclose(t1, tN, rtol=1e-4, atol=1e-6)


def test_adjust_ins_weight_never_resurrects_ghosts():
    """pv ghosts carry a real ad's nid; up-weighting must keep their loss
    weight at exactly zero."""
    from paddlebox_tpu.train.train_step import adjusted_loss_weight

    layout = ValueLayout(embedx_dim=4)
    cfg = TrainStepConfig(
        num_slots=2, batch_size=4, layout=layout,
        sparse_opt=SparseOptimizerConfig(), auc_buckets=10,
        adjust_ins_weight=(0, 10.0, 5.0),
    )
    # 4 instances, nid slot single key each; all shows cold (0.0)
    flat = jnp.zeros((8, layout.pull_width), jnp.float32)
    segments = jnp.array([0, 1, 2, 3, 4, 5, 6, 7], jnp.int32)  # slot0 ins0-3, slot1 ins0-3
    ghosts = jnp.array([1.0, 1.0, 0.0, 0.0], jnp.float32)  # last two = ghosts
    w, denom = adjusted_loss_weight(cfg, flat, segments, ghosts, 4)
    w = np.asarray(w)
    assert w[0] > 1.0 and w[1] > 1.0  # cold real ads up-weighted
    assert w[2] == 0.0 and w[3] == 0.0  # ghosts stay exactly zero
    assert float(denom) == 2.0  # real-instance count
