"""End-to-end single-device slice: synthetic CTR data through the full
pull → seqpool+cvm → model → push → dense-update → AUC pipeline.

Analog of the reference's tiny end-to-end feeds (test_paddlebox_datafeed.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data.device_pack import pack_batch
from paddlebox_tpu.data.slot_record import SlotRecord, build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.metrics.auc import auc_compute
from paddlebox_tpu.models import DeepFM, LogisticRegression
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import TrainStepConfig, make_train_step
from paddlebox_tpu.train.train_step import init_train_state, jit_train_step


NUM_SLOTS = 4
VOCAB = 64
BATCH = 32


def synth_records(rng, n, schema):
    """Labels correlate with a hidden per-key weight -> learnable signal."""
    key_w = rng.normal(size=VOCAB + 1) * 1.2
    recs = []
    for _ in range(n):
        u_vals, u_off = [], np.zeros(NUM_SLOTS + 1, dtype=np.uint32)
        score = 0.0
        for s in range(NUM_SLOTS):
            k = int(rng.integers(1, VOCAB + 1))
            u_vals.append(k)
            score += key_w[k]
            u_off[s + 1] = len(u_vals)
        label = 1.0 if score + rng.normal() * 0.3 > 0 else 0.0
        recs.append(
            SlotRecord(
                u64_values=np.array(u_vals, dtype=np.uint64),
                u64_offsets=u_off,
                f_values=np.array([label], dtype=np.float32),
                f_offsets=np.array([0, 1], dtype=np.uint32),
            )
        )
    return recs


@pytest.fixture(scope="module")
def schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}", type="uint64") for i in range(NUM_SLOTS)],
        label_slot="label",
    )


def run_training(model_cls, schema, steps=60, **model_kw):
    rng = np.random.default_rng(0)
    layout = ValueLayout(embedx_dim=8)
    opt_cfg = SparseOptimizerConfig(
        embed_lr=0.3, embedx_lr=0.3, embedx_threshold=0.0, initial_range=0.01
    )
    table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)
    recs = synth_records(rng, BATCH * 8, schema)

    ws = PassWorkingSet(n_mesh_shards=1)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev_table = ws.finalize(table, round_to=64)

    model = model_cls(
        num_slots=NUM_SLOTS, feat_width=layout.pull_width, **model_kw
    )
    params = model.init(jax.random.PRNGKey(0))
    dense_opt = optax.adam(1e-2)
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS,
        batch_size=BATCH,
        layout=layout,
        sparse_opt=opt_cfg,
        auc_buckets=1000,
    )
    step = jit_train_step(make_train_step(model.apply, dense_opt, cfg))
    state = init_train_state(
        jnp.asarray(dev_table.reshape(-1, layout.width)), params, dense_opt, cfg.auc_buckets
    )

    losses = []
    for i in range(steps):
        batch_recs = [recs[j % len(recs)] for j in range(i * BATCH, (i + 1) * BATCH)]
        batch = build_batch(batch_recs, schema)
        db = pack_batch(batch, ws, schema, bucket=256)
        state, m = step(state, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
        losses.append(float(m["loss"]))

    metrics = auc_compute(state.auc)
    # flush trained table back to host store
    ws.writeback(np.asarray(state.table))
    return losses, metrics, table, ws, layout


def test_lr_learns(schema):
    losses, metrics, *_ = run_training(LogisticRegression, schema, steps=40)
    assert losses[-1] < losses[0] * 0.9
    assert metrics["auc"] > 0.6
    assert metrics["ins_num"] == 40 * BATCH


def test_deepfm_learns_and_writes_back(schema):
    losses, metrics, table, ws, layout = run_training(
        DeepFM, schema, steps=60, embedx_dim=8, hidden=(32, 16)
    )
    assert losses[-1] < losses[0] * 0.8
    assert metrics["auc"] > 0.65
    # show counters flowed back to the host store: every pass key saw traffic
    got = table.pull_or_create(ws.sorted_keys)
    assert np.all(got[:, layout.SHOW] > 0)
    # predicted ctr is calibrated-ish (sanity, not precision)
    assert 0.05 < metrics["predicted_ctr"] < 0.95


def test_train_step_deterministic(schema):
    l1, m1, *_ = run_training(LogisticRegression, schema, steps=10)
    l2, m2, *_ = run_training(LogisticRegression, schema, steps=10)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
