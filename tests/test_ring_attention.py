"""Ring attention + Ulysses sequence parallelism vs full attention.

Both schemes must be EXACT: outputs match single-device full attention to
f32 tolerance, causal and non-causal, and gradients flow (training check).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel import make_mesh, ring_attention, ulysses_attention
from paddlebox_tpu.parallel.mesh import shard_map

N_DEV = 8
B, S_LOC, H, D = 2, 4, 8, 16  # global seq = 32


def full_attention(q, k, v, causal):
    scale = D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Sg = q.shape[1]
        mask = jnp.arange(Sg)[:, None] >= jnp.arange(Sg)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def make_qkv(seed):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.normal(size=(B, S_LOC * N_DEV, H, D)).astype(np.float32)
    )
    return mk(), mk(), mk()


def shard_seq(plan, x):
    # [B, S, H, D] -> seq axis sharded over the mesh
    return jax.device_put(x, plan.sharded(None, plan.axis))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_matches_full_attention(causal, impl):
    plan = make_mesh(N_DEV, axis="sp")
    q, k, v = make_qkv(0)
    fn = ring_attention if impl == "ring" else ulysses_attention

    def local(ql, kl, vl):
        return fn(ql, kl, vl, "sp", causal=causal)

    mapped = jax.jit(
        shard_map(
            local, mesh=plan.mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got = np.asarray(mapped(shard_seq(plan, q), shard_seq(plan, k), shard_seq(plan, v)))
    want = np.asarray(full_attention(q, k, v, causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match():
    """d(sum(out))/d(q,k,v) equals full attention's grads."""
    plan = make_mesh(N_DEV, axis="sp")
    q, k, v = make_qkv(1)

    def ring_sum(ql, kl, vl):
        # LOCAL sum: each device seeds its own block's cotangent once; the
        # transposed ppermutes route cross-block grads (a psum here would
        # seed every device's copy and overcount by n)
        o = ring_attention(ql, kl, vl, "sp", causal=True)
        return jnp.sum(o)

    mapped = jax.jit(
        shard_map(
            jax.grad(ring_sum, argnums=(0, 1, 2)),
            mesh=plan.mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=(P(None, "sp"),) * 3,
            check_vma=False,
        )
    )
    got = mapped(shard_seq(plan, q), shard_seq(plan, k), shard_seq(plan, v))
    want = jax.grad(
        lambda a, b, c: jnp.sum(full_attention(a, b, c, True)), argnums=(0, 1, 2)
    )(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-5)


def test_ulysses_head_divisibility():
    plan = make_mesh(N_DEV, axis="sp")
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(B, S_LOC, 6, D)).astype(np.float32))  # 6 % 8 != 0

    def local(ql):
        return ulysses_attention(ql, ql, ql, "sp")

    with pytest.raises(ValueError, match="divisible"):
        shard_map(
            local, mesh=plan.mesh, in_specs=(P(None, "sp"),),
            out_specs=P(None, "sp"), check_vma=False,
        )(shard_seq(plan, jnp.tile(x, (1, N_DEV, 1, 1))[:, : S_LOC * N_DEV]))


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_bf16_inputs_accumulate_in_f32(impl):
    """bf16 q/k/v must stay close to the f32 reference (f32 accumulators)."""
    plan = make_mesh(N_DEV, axis="sp")
    q, k, v = make_qkv(3)
    fn = ring_attention if impl == "ring" else ulysses_attention

    def local(ql, kl, vl):
        return fn(ql, kl, vl, "sp", causal=True)

    mapped = jax.jit(
        shard_map(
            local, mesh=plan.mesh,
            in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = mapped(shard_seq(plan, qb), shard_seq(plan, kb), shard_seq(plan, vb))
    assert got.dtype == jnp.bfloat16
    want = np.asarray(full_attention(q, k, v, True))
    # error budget = bf16 input rounding only, not n_dev-compounded
    # accumulator drift
    err = np.abs(np.asarray(got, dtype=np.float32) - want).max()
    assert err < 0.02, err
