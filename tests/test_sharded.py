"""Mesh-sharded table path on the 8-device virtual CPU mesh.

Validates the TPU-native multi-chip design (SURVEY.md §7 step 5): sharded
pull via all_to_all matches a direct host gather, and a full sharded train
step is numerically equivalent to the single-device step on the same global
batch (owner-side grad merge == global dedup merge).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.data.device_pack import pack_batch, pack_batch_sharded
from paddlebox_tpu.data.slot_record import build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.metrics.auc import auc_compute
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ops.pull_push import pull_sparse_rows
from paddlebox_tpu.parallel import make_mesh, sharded_pull
from paddlebox_tpu.parallel.mesh import shard_map
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import TrainStepConfig, make_train_step
from paddlebox_tpu.train.sharded_step import (
    init_sharded_train_state,
    make_sharded_train_step,
)
from paddlebox_tpu.train.train_step import init_train_state, jit_train_step

from test_train_step import synth_records

NUM_SLOTS = 4
VOCAB = 64
BATCH = 64  # global; 8 per device on the 8-mesh
N_DEV = 8

LAYOUT = ValueLayout(embedx_dim=8)
OPT = SparseOptimizerConfig(
    embed_lr=0.3, embedx_lr=0.3, embedx_threshold=0.0, initial_range=0.01
)


@pytest.fixture(scope="module")
def schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}", type="uint64") for i in range(NUM_SLOTS)],
        label_slot="label",
    )


@pytest.fixture(scope="module")
def setup(schema):
    rng = np.random.default_rng(7)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    recs = synth_records(rng, BATCH * 4, schema)
    ws = PassWorkingSet(n_mesh_shards=N_DEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev_table = ws.finalize(table, round_to=64)
    return table, recs, ws, dev_table


def test_sharded_pull_matches_direct(schema, setup):
    table, recs, ws, dev_table = setup
    plan = make_mesh(N_DEV)
    batch = build_batch(recs[:BATCH], schema)
    sb = pack_batch_sharded(batch, ws, schema, N_DEV, bucket=32)

    def pull_local(tbl, req, inv):
        pulled = sharded_pull(tbl[0], req[0], LAYOUT, 0.0, 1.0, plan.axis)
        return jnp.take(pulled, inv[0], axis=0)[None]

    mapped = jax.jit(
        shard_map(
            pull_local,
            mesh=plan.mesh,
            in_specs=(P(plan.axis), P(plan.axis), P(plan.axis)),
            out_specs=P(plan.axis),
            check_vma=False,
        )
    )
    tbl = jax.device_put(dev_table, plan.table_sharding)
    got = np.asarray(
        mapped(
            tbl,
            jax.device_put(sb.req_ranks, plan.batch_sharding),
            jax.device_put(sb.inverse, plan.batch_sharding),
        )
    )  # [n_dev, L_pad, PW]

    # direct reference: flat gather from the unsharded table, same key order
    flat_table = dev_table.reshape(-1, LAYOUT.width)
    rows = ws.lookup(batch.keys)
    want_flat = np.asarray(
        pull_sparse_rows(jnp.asarray(flat_table), jnp.asarray(rows), LAYOUT, 0.0, 1.0)
    )
    segments = batch.segment_ids()
    ins = segments % BATCH
    b = BATCH // N_DEV
    dev_of = ins // b
    # keys of device d appear in got[d] in the device's local order; rebuild
    # that order the same way the packer did (stable by flat position)
    for d in range(N_DEV):
        sel = np.nonzero(dev_of == d)[0]
        np.testing.assert_allclose(got[d, : len(sel)], want_flat[sel], rtol=1e-6)
        # any pad entries pull the zero padding row
        assert np.all(got[d, len(sel) :] == 0)


def test_sharded_step_matches_single_device(schema, setup):
    table, recs, ws, dev_table = setup
    plan = make_mesh(N_DEV)

    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width, embedx_dim=8, hidden=(32, 16))
    # two identical-valued but distinct param trees: each step donates its own
    params = model.init(jax.random.PRNGKey(0))
    paramsN = model.init(jax.random.PRNGKey(0))
    dense_opt = optax.adam(1e-2)

    # --- single device on the same global rows (flattened table)
    cfg1 = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=BATCH, layout=LAYOUT, sparse_opt=OPT, auc_buckets=1000
    )
    step1 = jit_train_step(make_train_step(model.apply, dense_opt, cfg1))
    st1 = init_train_state(
        jnp.asarray(dev_table.reshape(-1, LAYOUT.width)), params, dense_opt, 1000
    )

    # --- sharded
    cfgN = TrainStepConfig(
        num_slots=NUM_SLOTS,
        batch_size=BATCH // N_DEV,
        layout=LAYOUT,
        sparse_opt=OPT,
        auc_buckets=1000,
        axis_name=plan.axis,
    )
    stepN = make_sharded_train_step(model.apply, dense_opt, cfgN, plan)
    stN = init_sharded_train_state(plan, dev_table, paramsN, dense_opt, 1000)

    losses1, lossesN = [], []
    for i in range(6):
        batch_recs = [recs[(i * BATCH + j) % len(recs)] for j in range(BATCH)]
        batch = build_batch(batch_recs, schema)
        db1 = pack_batch(batch, ws, schema, bucket=64)
        st1, m1 = step1(st1, {k: jnp.asarray(v) for k, v in db1.as_dict().items()})
        dbN = pack_batch_sharded(batch, ws, schema, N_DEV, bucket=32)
        feed = {
            k: jax.device_put(v, plan.batch_sharding) for k, v in dbN.as_dict().items()
        }
        stN, mN = stepN(stN, feed)
        losses1.append(float(m1["loss"]))
        lossesN.append(float(mN["loss"]))

    # step 1 sees identical inputs -> near-bitwise agreement; later steps
    # drift through f32 reduction order amplified by sparse adagrad, so the
    # trajectory check is looser
    np.testing.assert_allclose(losses1[0], lossesN[0], rtol=1e-5)
    np.testing.assert_allclose(losses1, lossesN, rtol=6e-3)
    # final tables agree row-for-row (same global row layout)
    t1 = np.asarray(st1.table)
    tN = np.asarray(stN.table).reshape(-1, LAYOUT.width)
    # f32 reduction-order noise: per-device partial sums + owner merge vs one
    # global segment_sum
    np.testing.assert_allclose(t1, tN, rtol=2e-3, atol=1e-3)
    # AUC states agree after summing the sharded device slices
    a1, aN = auc_compute(st1.auc), auc_compute(stN.auc)
    assert a1["ins_num"] == aN["ins_num"] == 6 * BATCH
    # preds differ by f32 noise; near-boundary samples may shift one bucket
    np.testing.assert_allclose(a1["auc"], aN["auc"], atol=2e-3)
    # dense params stayed replicated and matched the single-device trajectory
    p1 = jax.tree.leaves(st1.params)
    pN = jax.tree.leaves(stN.params)
    # adam normalizes tiny grads (≈sign) so f32 grad noise shows up scaled by
    # lr — elementwise params can drift a few lr steps on near-zero-grad
    # coordinates; the loss-trajectory lock above is the real equivalence
    # criterion, this is a coarse sanity bound
    for x, y in zip(p1, pN):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=3e-2)
