"""CTR op family tests against loop-reference implementations
(reference: rank_attention.cu.h expand kernels, batch_fc_op.cu strided GEMM,
fused_concat_op.cu, fused_seqpool_cvm_* variant kernels)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ops import (
    batch_fc,
    cvm_with_conv_transform,
    cvm_with_pcoc_transform,
    fused_concat,
    fused_seqpool_cvm_with_conv,
    fused_seqpool_cvm_with_diff_thres,
    fused_seqpool_cvm_with_pcoc,
    rank_attention,
)


def _rank_attention_ref(x, rank_offset, rank_param, max_rank):
    """Direct loop transcription of expand_input/expand_param + matmul."""
    B, F = x.shape
    C = rank_param.shape[-1]
    param = rank_param.reshape(max_rank, max_rank, F, C)
    out = np.zeros((B, C), np.float32)
    for i in range(B):
        own = rank_offset[i, 0] - 1
        if own < 0:
            continue
        for k in range(max_rank):
            pr = rank_offset[i, 2 * k + 1] - 1
            idx = rank_offset[i, 2 * k + 2]
            if pr < 0:
                continue
            out[i] += x[idx] @ param[own, pr]
    return out


def test_rank_attention_matches_reference_loop():
    rng = np.random.default_rng(0)
    B, F, C, R = 6, 4, 5, 3
    x = rng.normal(size=(B, F)).astype(np.float32)
    # pv structure: ins 0-2 in one pv (ranks 1,2,3), ins 3-4 in one pv, ins 5 rankless
    rank_offset = np.zeros((B, 2 * R + 1), np.int32)
    pv1, pv2 = [0, 1, 2], [3, 4]
    for pv in (pv1, pv2):
        for a, i in enumerate(pv):
            rank_offset[i, 0] = a + 1
            for k, j in enumerate(pv):
                rank_offset[i, 2 * k + 1] = k + 1
                rank_offset[i, 2 * k + 2] = j
    param = rng.normal(size=(R * R * F, C)).astype(np.float32)

    got = np.asarray(rank_attention(jnp.asarray(x), jnp.asarray(rank_offset), jnp.asarray(param), R))
    want = _rank_attention_ref(x, rank_offset, param, R)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert np.all(got[5] == 0)  # rankless instance -> zeros


def test_rank_attention_grad_flows_only_to_used_blocks():
    B, F, C, R = 2, 3, 2, 2
    x = jnp.ones((B, F))
    rank_offset = np.zeros((B, 2 * R + 1), np.int32)
    rank_offset[0] = [1, 1, 0, 2, 1]  # own rank 1; peers rank1->ins0, rank2->ins1
    rank_offset[1] = [2, 1, 0, 2, 1]
    param = jnp.zeros((R * R * F, C))

    def loss(p):
        return jnp.sum(rank_attention(x, jnp.asarray(rank_offset), p, R))

    g = np.asarray(jax.grad(loss)(param)).reshape(R, R, F, C)
    # own=0 row used by ins0 (peers 0 and 1), own=1 row used by ins1
    assert np.abs(g[0]).sum() > 0 and np.abs(g[1]).sum() > 0


def test_batch_fc_matches_per_channel_loop():
    rng = np.random.default_rng(1)
    B, cnt, fin, fout = 5, 3, 4, 2
    x = rng.normal(size=(B, cnt * fin)).astype(np.float32)
    w = rng.normal(size=(fin, cnt * fout)).astype(np.float32)
    b = rng.normal(size=(cnt * fout,)).astype(np.float32)
    got = np.asarray(batch_fc(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), cnt))
    for k in range(cnt):
        want = x[:, k * fin : (k + 1) * fin] @ w[:, k * fout : (k + 1) * fout] + b[
            k * fout : (k + 1) * fout
        ]
        np.testing.assert_allclose(got[:, k * fout : (k + 1) * fout], want, rtol=1e-5)


def test_fused_concat():
    xs = [jnp.arange(12.0).reshape(3, 4), 100 + jnp.arange(12.0).reshape(3, 4)]
    out = np.asarray(fused_concat(xs, offset=1, length=2))
    assert out.shape == (3, 4)
    np.testing.assert_allclose(out[0], [1, 2, 101, 102])


def _pool_ref(vals, segments, num_slots, B):
    width = vals.shape[1]
    pooled = np.zeros((num_slots * B, width), np.float32)
    for v, s in zip(vals, segments):
        if s < num_slots * B:
            pooled[s] += v
    return pooled.reshape(num_slots, B, width)


def test_seqpool_with_conv_formula():
    rng = np.random.default_rng(2)
    S, B, D = 2, 3, 2
    width = 3 + D  # show, clk, conv, embedx
    L = 10
    vals = np.abs(rng.normal(size=(L, width))).astype(np.float32)
    segments = rng.integers(0, S * B, L).astype(np.int32)
    got = np.asarray(
        fused_seqpool_cvm_with_conv(jnp.asarray(vals), jnp.asarray(segments), S, B)
    )
    pooled = _pool_ref(vals, segments, S, B)
    want0 = np.log(pooled[..., 0] + 1)
    want1 = np.log(pooled[..., 1] + 1)
    want2 = np.log(pooled[..., 2] + 1) - np.log(pooled[..., 1] + 1)
    got_sb = np.transpose(got, (1, 0, 2))
    np.testing.assert_allclose(got_sb[..., 0], want0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_sb[..., 1], want1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_sb[..., 2], want2, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_sb[..., 3:], pooled[..., 3:], rtol=1e-5, atol=1e-6)
    # show_filter drops the show column
    got_f = np.asarray(
        fused_seqpool_cvm_with_conv(
            jnp.asarray(vals), jnp.asarray(segments), S, B, show_filter=True
        )
    )
    assert got_f.shape[-1] == width - 1
    np.testing.assert_allclose(np.transpose(got_f, (1, 0, 2))[..., 0], want1, rtol=1e-5, atol=1e-6)
    # no-cvm strips the 3-col cvm block
    got_nc = np.asarray(
        fused_seqpool_cvm_with_conv(
            jnp.asarray(vals), jnp.asarray(segments), S, B, use_cvm=False
        )
    )
    assert got_nc.shape[-1] == D


def test_seqpool_with_pcoc_formula():
    rng = np.random.default_rng(3)
    S, B, D, P = 1, 2, 2, 3
    width = 4 + P + D
    L = 6
    vals = np.abs(rng.normal(size=(L, width))).astype(np.float32)
    segments = rng.integers(0, S * B, L).astype(np.int32)
    got = np.asarray(
        fused_seqpool_cvm_with_pcoc(jnp.asarray(vals), jnp.asarray(segments), S, B, pclk_num=P)
    )
    pooled = _pool_ref(vals, segments, S, B)
    ls = np.log(pooled[..., 0] + 1)
    lc = np.log(pooled[..., 1] + 1)
    ljs = np.log(pooled[..., 2] + 1)
    ljc = np.log(pooled[..., 3] + 1)
    lp = np.log(pooled[..., 4 : 4 + P] + 1)
    got_sb = np.transpose(got, (1, 0, 2))
    assert got_sb.shape[-1] == 2 + 2 * P + D
    np.testing.assert_allclose(got_sb[..., 0], ls, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_sb[..., 1], lc - ls, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_sb[..., 2 : 2 + P], lp - ljs[..., None], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        got_sb[..., 2 + P : 2 + 2 * P], lp - ljc[..., None], rtol=1e-5, atol=1e-6
    )


def test_seqpool_diff_thres_per_slot_filter():
    S, B = 2, 1
    width = 3
    # slot 0 key passes its threshold, slot 1 key fails its higher one
    vals = np.array([[1.0, 1.0, 5.0], [1.0, 1.0, 7.0]], np.float32)
    segments = np.array([0, 1], np.int32)  # slot0/ins0, slot1/ins0
    thr = np.array([0.5, 99.0], np.float32)
    got = np.asarray(
        fused_seqpool_cvm_with_diff_thres(
            jnp.asarray(vals), jnp.asarray(segments), S, B,
            threshold_vec=thr, show_coeff=0.2, clk_coeff=1.0,
        )
    )  # [B, S, width]
    assert got[0, 0, 2] == 5.0  # kept
    assert got[0, 1, 2] == 0.0  # filtered by slot-1 threshold


def test_conv_pcoc_transforms_shapes():
    x = jnp.abs(jnp.ones((2, 2, 7)))
    assert cvm_with_conv_transform(x).shape == (2, 2, 7)
    assert cvm_with_conv_transform(x, show_filter=True).shape == (2, 2, 6)
    y = jnp.ones((2, 2, 4 + 3 + 2))
    assert cvm_with_pcoc_transform(y, pclk_num=3).shape == (2, 2, 2 + 6 + 2)
