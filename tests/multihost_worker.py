"""Worker process for the multi-host localhost tests (test_multihost.py).

One real process per host (rank count from conf: 2 or 4), the reference's
own test pattern (test_dist_fleet_base.py:158-260): host plane over
TcpTransport (TcpShuffleRouter global shuffle, DistributedWorkingSet key
exchange, lockstep batch counts), device plane over a REAL cross-process
jax mesh (jax.distributed + gloo CPU collectives) running the sharded
train step.

Modes:
  train  — striped files, no shuffle, 1 trained pass on the global mesh;
           dumps layout/table/metrics for equality vs the 1-process run.
  shuffle — unequal record counts + ins_id global shuffle + lockstep
           wraparound pass on the global mesh; dumps shuffle accounting.
  zero   — ZeRO-1 optimizer-state sharding across the process mesh, TWO
           passes (cross-pass chunked-state carry over non-addressable
           global arrays is the regression surface).
  carried — multi-pass day loop handing end_pass the live device table;
           carried vs classic equality (multi-host MultiHostCarrier).
  pv     — join(pv)->update two-phase pass, ghost-locksteped.
"""

import json
import os
import sys


def main():
    mode, rank_s, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(rank_s)
    with open(os.path.join(workdir, "conf.json")) as f:
        conf = json.load(f)

    import jax

    n_ranks = conf.get("n_ranks", 2)
    local_dev = conf.get("local_devices", 2)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{conf['coord_port']}",
        num_processes=n_ranks,
        process_id=rank,
    )
    import numpy as np
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.transport import TcpTransport, TcpShuffleRouter
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    NS = conf["num_slots"]
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
        parse_ins_id=conf["parse_ins_id"],
    )
    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    opt_cfg = SparseOptimizerConfig(
        embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.01
    )
    table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)

    eps = [f"127.0.0.1:{p}" for p in conf["tp_ports"]]
    transport = TcpTransport(rank, eps, timeout=60.0)
    router = TcpShuffleRouter(transport)

    n_global_dev = n_ranks * local_dev
    plan = make_mesh(n_global_dev)
    assert len(jax.local_devices()) == local_dev
    assert jax.process_count() == n_ranks

    shuffle_mode = "ins_id" if mode == "shuffle" else "none"
    ds = BoxPSDataset(
        schema,
        table,
        batch_size=conf["local_batch"],
        n_mesh_shards=n_global_dev,
        rank=rank,
        nranks=n_ranks,
        shuffle_mode=shuffle_mode,
        router=router,
        transport=transport,
        seed=0,
    )
    ds.set_filelist(conf["files"])  # striped rank::2 internally
    ds.set_date("20260101")

    model = DeepFM(
        num_slots=NS, feat_width=layout.pull_width,
        embedx_dim=conf["embedx_dim"], hidden=(16,),
    )
    cfg = TrainStepConfig(
        num_slots=NS,
        batch_size=conf["local_batch"] // local_dev,  # per device
        layout=layout,
        sparse_opt=opt_cfg,
        auc_buckets=1000,
        axis_name=plan.axis,
    )
    if mode == "zero":
        from paddlebox_tpu.fleet import Zero1Optimizer

        dense_opt = Zero1Optimizer(
            optax.adam(1e-2), axis_name=plan.axis, n_dev=n_global_dev
        )
    else:
        dense_opt = optax.adam(1e-2)
    trainer = CTRTrainer(model, cfg, dense_opt=dense_opt, plan=plan)
    trainer.init_params(jax.random.PRNGKey(0))

    ds.load_into_memory()
    n_local_records = ds.memory_data_size()
    nb = ds.num_batches()
    ds.begin_pass(round_to=conf["round_to"])
    out = trainer.train_pass(ds)
    if mode == "zero":
        # second pass: chunked opt_state carries across passes as a
        # dp-sharded global array (put_sharded passthrough path)
        ds.end_pass(trainer.trained_table(), shrink=False)
        ds.set_date("20260102")
        ds.load_into_memory()
        ds.begin_pass(round_to=conf["round_to"])
        out = trainer.train_pass(ds)
    local_table = trainer.trained_table()  # this host's shard block
    dws = ds.ws
    layout_dump = dict(
        sorted_keys=dws.sorted_keys,
        rows=dws.row_of_sorted,
        capacity=np.array([dws.capacity]),
        local_table=local_table,
        n_records=np.array([n_local_records]),
        num_batches=np.array([nb]),
        batches_run=np.array([out["batches"]]),
        auc=np.array([out["auc"]]),
        loss=np.array([out["loss"]]),
        # which feed tier actually ran (the resident cache only builds when
        # the resident path executes)
        used_resident=np.array(
            [int(getattr(trainer, "_resident_cache", None) is not None)]
        ),
    )
    if conf["parse_ins_id"]:
        ins = sorted(r.ins_id for r in ds.records)
        layout_dump["ins_ids"] = np.array(ins)
    ds.end_pass(local_table, shrink=False)

    # host table after writeback: this host's owned keys only
    keys = np.sort(table.keys())
    layout_dump["host_keys"] = keys
    layout_dump["host_vals"] = table.pull_or_create(keys)
    np.savez(os.path.join(workdir, f"rank{rank}.npz"), **layout_dump)
    print(f"rank {rank}: ok", flush=True)




def _flat_setup(conf, rank):
    """Shared flat-record (non-pv) worker setup. Returns a ``build()``
    closure that constructs a FRESH (table, dataset, trainer) triple over
    the one live transport/mesh — carried_main calls it once; the resume
    worker calls it again after "restarting" to prove a fresh process can
    rebuild from checkpoints alone."""
    import jax

    n_ranks = conf.get("n_ranks", 2)
    local_dev = conf.get("local_devices", 2)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{conf['coord_port']}",
        num_processes=n_ranks,
        process_id=rank,
    )
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.transport import TcpTransport, TcpShuffleRouter
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    NS = conf["num_slots"]
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    # decay on, shrink off: exercises the carrier's accumulated-decay path
    # while keeping carried == classic bit-equivalent (the shrink
    # exemption for carried keys is the one documented semantic delta)
    opt_cfg = SparseOptimizerConfig(
        embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0,
        initial_range=0.01, show_clk_decay=0.95, shrink_threshold=0.0,
    )
    eps = [f"127.0.0.1:{p}" for p in conf["tp_ports"]]
    transport = TcpTransport(rank, eps, timeout=60.0)
    router = TcpShuffleRouter(transport)

    n_global_dev = n_ranks * local_dev
    plan = make_mesh(n_global_dev)
    model = DeepFM(
        num_slots=NS, feat_width=layout.pull_width,
        embedx_dim=conf["embedx_dim"], hidden=(16,),
    )
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=conf["local_batch"] // local_dev,
        layout=layout, sparse_opt=opt_cfg, auc_buckets=1000,
        axis_name=plan.axis,
    )

    def build():
        table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)
        ds = BoxPSDataset(
            schema, table, batch_size=conf["local_batch"],
            n_mesh_shards=n_global_dev, rank=rank, nranks=n_ranks,
            shuffle_mode="none", router=router, transport=transport, seed=0,
        )
        trainer = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan)
        trainer.init_params(jax.random.PRNGKey(0))
        return table, ds, trainer

    return build


def carried_main():
    """Multi-pass day loop over overlapping key streams: every boundary
    hands end_pass the live DEVICE table (trained_table_device). With
    PBOX_ENABLE_CARRIED_TABLE=1 the locksteped gate builds a per-host
    MultiHostCarrier (splice + departure push + new-key upload only); with
    0 the same call takes the classic full writeback. The test asserts the
    two runs produce identical host tables and metrics."""
    _, rank_s, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(rank_s)
    with open(os.path.join(workdir, "conf.json")) as f:
        conf = json.load(f)
    import numpy as np

    table, ds, trainer = _flat_setup(conf, rank)()

    per_pass = conf["files_per_pass"]
    n_passes = len(conf["files"]) // per_pass
    losses, aucs = [], []
    splice = {"common": 0, "new": 0, "departed": 0}
    spliced_passes = 0
    pass_keys = []
    for p in range(n_passes):
        ds.set_filelist(conf["files"][p * per_pass : (p + 1) * per_pass])
        ds.set_date(f"202601{p + 1:02d}")
        ds.load_into_memory()
        ds.begin_pass(round_to=conf["round_to"])
        bs = getattr(ds.ws, "boundary_stats", None)
        if bs is not None:
            spliced_passes += 1
            for k in splice:
                splice[k] += bs[k]
        pass_keys.append(ds.ws.n_keys)
        out = trainer.train_pass(ds)
        losses.append(out["loss"])
        aucs.append(out["auc"])
        ds.end_pass(trainer.trained_table_device())
    table.drain_pending()
    keys = np.sort(table.keys())
    np.savez(
        os.path.join(workdir, f"rank{rank}.npz"),
        losses=np.array(losses),
        aucs=np.array(aucs),
        host_keys=keys,
        host_vals=table.pull_or_create(keys),
        spliced_passes=np.array([spliced_passes]),
        splice_common=np.array([splice["common"]]),
        splice_new=np.array([splice["new"]]),
        splice_departed=np.array([splice["departed"]]),
        pass_keys=np.array(pass_keys),
    )
    print(f"rank {rank}: carried ok", flush=True)


def carried_resume_main():
    """Day-level checkpoint/resume on the multi-host path: train 2 carried
    passes, save_base per host (each host checkpoints its OWN key slice +
    the replicated dense), then REBUILD everything from fresh objects and
    resume from disk alone, and train pass 3 on the resumed state. The
    test pins the final host tables and pass-3 loss EQUAL to an
    uninterrupted 3-pass run (day-level InitializeGPUAndLoadModel parity,
    per host)."""
    _, rank_s, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(rank_s)
    with open(os.path.join(workdir, "conf.json")) as f:
        conf = json.load(f)
    import numpy as np

    from paddlebox_tpu.train import CheckpointManager

    build = _flat_setup(conf, rank)
    table, ds, trainer = build()
    per_pass = conf["files_per_pass"]
    losses = []
    for p in range(2):
        ds.set_filelist(conf["files"][p * per_pass : (p + 1) * per_pass])
        ds.set_date(f"202601{p + 1:02d}")
        ds.load_into_memory()
        ds.begin_pass(round_to=conf["round_to"])
        out = trainer.train_pass(ds)
        losses.append(out["loss"])
        ds.end_pass(trainer.trained_table_device())
    ds.wait_end_pass()
    ckpt = os.path.join(workdir, f"ckpt-{rank}")
    # save_base drains pending carriers via the save path's drain hook
    CheckpointManager(ckpt).save_base("20260102", table, trainer)

    # "process restart": fresh table/dataset/trainer over the live
    # transport; ONLY the checkpoint directory carries state across
    table2, ds2, tr2 = build()
    cur = CheckpointManager(ckpt).resume(table2, tr2)
    assert cur is not None and cur["date"] == "20260102", cur
    p = 2
    ds2.set_filelist(conf["files"][p * per_pass : (p + 1) * per_pass])
    ds2.set_date("20260103")
    ds2.load_into_memory()
    ds2.begin_pass(round_to=conf["round_to"])
    out = tr2.train_pass(ds2)
    losses.append(out["loss"])
    ds2.end_pass(tr2.trained_table_device())
    table2.drain_pending()
    keys = np.sort(table2.keys())
    np.savez(
        os.path.join(workdir, f"rank{rank}.npz"),
        losses=np.array(losses),
        host_keys=keys,
        host_vals=table2.pull_or_create(keys),
    )
    print(f"rank {rank}: carried-resume ok", flush=True)


def _pv_setup(conf, rank, opt_overrides=None):
    """Shared pv-worker setup: jax.distributed init, transport/router,
    global mesh, search_id-shuffled dataset, RankModel (DeepFM +
    rank_attention), and join/update trainers. Both pv entry points build
    from here so their fixtures cannot diverge."""
    import jax

    n_ranks = conf.get("n_ranks", 2)
    local_dev = conf.get("local_devices", 2)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{conf['coord_port']}",
        num_processes=n_ranks,
        process_id=rank,
    )
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ops import rank_attention
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.parallel.transport import TcpTransport, TcpShuffleRouter
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    NS = conf["num_slots"]
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
        parse_logkey=True,
    )
    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    opt_kwargs = dict(
        embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.01
    )
    opt_kwargs.update(opt_overrides or {})
    opt_cfg = SparseOptimizerConfig(**opt_kwargs)
    table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)

    eps = [f"127.0.0.1:{p}" for p in conf["tp_ports"]]
    transport = TcpTransport(rank, eps, timeout=60.0)
    router = TcpShuffleRouter(transport)

    n_global_dev = n_ranks * local_dev
    plan = make_mesh(n_global_dev)
    ds = BoxPSDataset(
        schema, table, batch_size=conf["local_batch"],
        n_mesh_shards=n_global_dev, rank=rank, nranks=n_ranks,
        shuffle_mode="search_id",  # co-locate each pv on its owner host
        router=router, transport=transport, seed=0,
    )

    base = DeepFM(
        num_slots=NS, feat_width=layout.pull_width,
        embedx_dim=conf["embedx_dim"], hidden=(16,),
    )
    in_dim = NS * layout.pull_width

    class RankModel:
        """DeepFM + rank_attention over the pv rank matrix (join phase);
        update phase calls it without rank_offset (attention skipped)."""

        def init(self, rng):
            p = base.init(rng)
            p["rank_param"] = jnp.full((9 * in_dim, 1), 0.01, jnp.float32)
            return p

        def apply(self, p, feats, dense=None, rank_offset=None):
            logit = base.apply(
                {k: v for k, v in p.items() if k != "rank_param"}, feats, dense
            )
            if rank_offset is not None:
                x = feats.reshape(feats.shape[0], -1)
                logit = logit + rank_attention(
                    x, rank_offset, p["rank_param"], 3
                )[:, 0]
            return logit

    model = RankModel()
    per_dev_b = conf["local_batch"] // local_dev
    cfg_join = TrainStepConfig(
        num_slots=NS, batch_size=per_dev_b, layout=layout, sparse_opt=opt_cfg,
        auc_buckets=1000, axis_name=plan.axis, model_takes_rank_offset=True,
    )
    cfg_upd = TrainStepConfig(
        num_slots=NS, batch_size=per_dev_b, layout=layout, sparse_opt=opt_cfg,
        auc_buckets=1000, axis_name=plan.axis,
    )
    join_tr = CTRTrainer(model, cfg_join, dense_opt=optax.adam(1e-2), plan=plan)
    join_tr.init_params(jax.random.PRNGKey(0))
    upd_tr = CTRTrainer(model, cfg_upd, dense_opt=optax.adam(1e-2), plan=plan)
    upd_tr.opt_state = optax.adam(1e-2).init(join_tr.params)  # shapes only
    upd_tr.init_params = lambda rng=None: None
    return ds, table, join_tr, upd_tr, local_dev


def pv_main():
    """Join(pv) -> update two-phase pass on the 2-host mesh: search_id
    global shuffle co-locates each query's ads on its owner host, pv batch
    counts and pack pads are transport-locksteped (ghost batches on the
    short host), then the update phase runs the store fast path."""
    _, rank_s, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(rank_s)
    with open(os.path.join(workdir, "conf.json")) as f:
        conf = json.load(f)
    import numpy as np

    ds, table, join_tr, upd_tr, local_dev = _pv_setup(conf, rank)
    ds.set_filelist(conf["files"])
    ds.set_date("20260101")
    ds.load_into_memory()
    ds.begin_pass(round_to=conf["round_to"])

    ds.set_current_phase(1)
    n_pvs = ds.preprocess_instance()
    local_pv_batches = ds.num_pv_batches(n_devices=local_dev)
    out_j = join_tr.train_pass(ds)
    join_resident = getattr(join_tr, "_resident_cache", None) is not None

    ds.set_current_phase(0)
    ds.postprocess_instance()
    # the update phase continues from the JOIN-TRAINED dense params (one
    # live model across phases, box_wrapper.h:620-622) — bind AFTER the
    # join pass (join_tr.params rebinds to fresh arrays at its pass end)
    upd_tr.params = join_tr.params
    join_tr.handoff_table(ds)  # join-phase sparse updates carry into update
    out_u = upd_tr.train_pass(ds)

    local_table = upd_tr.trained_table()
    ds.end_pass(local_table, shrink=False)
    np.savez(
        os.path.join(workdir, f"rank{rank}.npz"),
        n_pvs=np.array([n_pvs]),
        local_pv_batches=np.array([local_pv_batches]),
        join_batches=np.array([out_j["batches"]]),
        join_loss=np.array([out_j["loss"]]),
        join_auc=np.array([out_j["auc"]]),
        join_ins=np.array([out_j["ins_num"]]),
        upd_batches=np.array([out_u["batches"]]),
        upd_loss=np.array([out_u["loss"]]),
        n_records=np.array([ds.memory_data_size()]),
        join_resident=np.array([int(join_resident)]),
    )
    print(f"rank {rank}: pv ok", flush=True)


def pv2_main():
    """TWO-pass pv (join->update) day loop: composes the multi-host
    resident pv tier with the multi-host carried boundary — every pass
    boundary hands end_pass the live device table, so with
    PBOX_ENABLE_CARRIED_TABLE=1 the second pass's finalize splices the
    update-phase-trained rows per host instead of a full writeback.
    Dumps per-pass metrics + final host table for carried==classic
    equality."""
    _, rank_s, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(rank_s)
    with open(os.path.join(workdir, "conf.json")) as f:
        conf = json.load(f)
    import numpy as np

    ds, table, join_tr, upd_tr, local_dev = _pv_setup(
        conf, rank,
        opt_overrides={"show_clk_decay": 0.95, "shrink_threshold": 0.0},
    )
    per_pass = conf["files_per_pass"]
    n_passes = len(conf["files"]) // per_pass
    join_losses, upd_losses = [], []
    spliced_passes = 0
    for p in range(n_passes):
        ds.set_filelist(conf["files"][p * per_pass : (p + 1) * per_pass])
        ds.set_date(f"202602{p + 1:02d}")
        ds.load_into_memory()
        ds.begin_pass(round_to=conf["round_to"])
        if getattr(ds.ws, "boundary_stats", None) is not None:
            spliced_passes += 1
        ds.set_current_phase(1)
        ds.preprocess_instance()
        out_j = join_tr.train_pass(ds)
        ds.set_current_phase(0)
        ds.postprocess_instance()
        # one live model across phases and passes: update continues from
        # the join-trained dense params, the next pass's join from the
        # update-trained ones (bind AFTER each pass — train_pass rebinds
        # trainer.params to fresh arrays at pass end)
        upd_tr.params = join_tr.params
        join_tr.handoff_table(ds)
        out_u = upd_tr.train_pass(ds)
        join_tr.params = upd_tr.params
        join_losses.append(out_j["loss"])
        upd_losses.append(out_u["loss"])
        # the join-phase trainer shares the dense params; the sparse side
        # ends with the update-phase-trained DEVICE table
        ds.end_pass(upd_tr.trained_table_device())
    table.drain_pending()
    keys = np.sort(table.keys())
    np.savez(
        os.path.join(workdir, f"rank{rank}.npz"),
        join_losses=np.array(join_losses),
        upd_losses=np.array(upd_losses),
        spliced_passes=np.array([spliced_passes]),
        host_keys=keys,
        host_vals=table.pull_or_create(keys),
        join_resident=np.array(
            [int(getattr(join_tr, "_resident_cache", None) is not None)]
        ),
    )
    print(f"rank {rank}: pv2 ok", flush=True)


if __name__ == "__main__":
    if sys.argv[1] == "pv":
        pv_main()
    elif sys.argv[1] == "pv2":
        pv2_main()
    elif sys.argv[1] == "carried":
        carried_main()
    elif sys.argv[1] == "carried_resume":
        carried_resume_main()
    else:
        main()
