"""Opt-in (slow) gate: the native tier must replay clean under ASan+UBSan.

Tier-1 runs ``-m 'not slow'`` so this never taxes the fast lane; the soak
lane (and ``chaos_probe --native-sanitize``) runs it. The driver itself
skips with exit 0 when the image has no g++ or sanitizer runtimes, so the
assertion stays green on build-less lanes too.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_native_sanitize_quick_replay_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "native_sanitize.py"),
         "--quick"],
        capture_output=True, text=True, timeout=900, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow
def test_native_sanitize_tsan_replay_clean():
    # the writer pool + double-buffered spill stage must be race-free,
    # not merely deadlock-free: replay the writeback suites under TSan
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "native_sanitize.py"),
         "--tsan"],
        capture_output=True, text=True, timeout=900, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
