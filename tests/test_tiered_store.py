"""Frequency-aware tiered store: eviction ranking, admission, pin, A/B.

The reference's 1e11-key store survives because hot feasigns stay in the
fast tier (BoxPS LoadSSD2Mem + cache-rate policy, box_wrapper.cc:1325);
the open table's cap sweep (spill_cold) earns the same property with a
CTR-style coldness ranking — lowest decayed show first, oldest
last-touched epoch breaking ties — plus pin/admission thresholds. These
tests pin the policy semantics, the bitwise promote contract under the new
thresholds, the typed SpillIOError path, the tier_stats surface, and the
fifo-vs-freq A/B claim (fewer promotes at equal mem_cap_rows) that
tools/scale_soak.py --zipf measures at scale.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    SpillIOError,
    ValueLayout,
)
from paddlebox_tpu.utils.faultinject import fail_once, inject
from paddlebox_tpu.utils.monitor import STAT_GET

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPILL_FLAGS = ("spill_policy", "spill_pin_show", "spill_admit_show")


@pytest.fixture(autouse=True)
def _restore_spill_flags():
    saved = {n: config.get_flag(n) for n in SPILL_FLAGS}
    yield
    for n, v in saved.items():
        config.set_flag(n, v)


def _native_or_skip():
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native table store unavailable")


def _make_table(d, n_shards=4, decay=1.0, cap=None, embedx=1, spill=True):
    return HostSparseTable(
        ValueLayout(embedx_dim=embedx),
        SparseOptimizerConfig(show_clk_decay=decay, shrink_threshold=0.0),
        n_shards=n_shards,
        seed=0,
        spill_dir=(d if spill else None),
        mem_cap_rows=cap,
    )


def _seed_shows(table, lay, keys, show):
    rows = table.pull_or_create(keys)
    rows[:, lay.SHOW] = show
    table.push(keys, rows)


def test_freq_spills_coldest_keeps_hot_resident():
    """freq ranks victims by decayed show: after a sweep the hot set must
    still be RAM-resident (re-pulling it promotes nothing) even though the
    hot keys were created FIRST — the exact stream that defeats fifo."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d)
        hot = np.arange(1, 101, dtype=np.uint64)
        cold = np.arange(1001, 1901, dtype=np.uint64)
        _seed_shows(table, lay, hot, 50.0)  # created before the cold tail
        _seed_shows(table, lay, cold, 1.0)
        config.set_flag("spill_policy", "freq")
        spilled = table.spill_cold(200)
        assert spilled == 800
        st = table.tier_stats()
        assert st["mem_rows"] == 200 and st["disk_rows"] == 800
        before = st["promoted_total"]
        table.pull_or_create(hot)
        assert table.tier_stats()["promoted_total"] == before  # all resident


def test_fifo_spills_creation_order():
    """The legacy baseline evicts in creation order regardless of show —
    the early-created hot head lands on disk and every re-pull promotes.
    (This contrast is WHY the soak's A/B favors freq.)"""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d, n_shards=1)
        hot = np.arange(1, 101, dtype=np.uint64)
        cold = np.arange(1001, 1901, dtype=np.uint64)
        _seed_shows(table, lay, hot, 50.0)
        _seed_shows(table, lay, cold, 1.0)
        config.set_flag("spill_policy", "fifo")
        assert table.spill_cold(200) == 800
        before = table.tier_stats()["promoted_total"]
        table.pull_or_create(hot)
        # creation-order sweep spilled the whole hot head
        assert table.tier_stats()["promoted_total"] == before + 100


def test_pin_threshold_spills_pinned_only_under_pressure():
    """Rows at/above spill_pin_show are spilled only once every colder
    victim in the shard is gone; when cap pressure exceeds the cold pool
    the sweep must still converge (pins yield rather than deadlock)."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d, n_shards=1)
        hot = np.arange(1, 101, dtype=np.uint64)
        cold = np.arange(1001, 1101, dtype=np.uint64)
        _seed_shows(table, lay, hot, 50.0)
        _seed_shows(table, lay, cold, 1.0)
        config.set_flag("spill_policy", "freq")
        config.set_flag("spill_pin_show", 10.0)
        # need 150 victims but only 100 are colder than the pin: all cold
        # spill first, then exactly 50 pinned rows yield
        assert table.spill_cold(50) == 150
        before = table.tier_stats()["promoted_total"]
        table.pull_or_create(hot)
        assert table.tier_stats()["promoted_total"] == before + 50
        table.pull_or_create(cold)  # every cold row was on disk
        assert table.tier_stats()["promoted_total"] == before + 150


def test_admission_threshold_writes_cold_disk_first():
    """At sweep time every row under spill_admit_show goes disk-first even
    beyond the cap overage, and the admitted count is surfaced."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d, n_shards=1)
        warm = np.arange(1, 51, dtype=np.uint64)
        junk = np.arange(1001, 1051, dtype=np.uint64)
        _seed_shows(table, lay, warm, 10.0)
        _seed_shows(table, lay, junk, 1.0)
        config.set_flag("spill_policy", "freq")
        config.set_flag("spill_admit_show", 5.0)
        # over = 10, but admission must take the whole sub-threshold set
        spilled = table.spill_cold(90)
        st = table.tier_stats()
        assert st["admitted_disk_first"] == 50
        assert spilled == 50 and st["disk_rows"] == 50
        before = st["promoted_total"]
        table.pull_or_create(warm)  # warm rows never left RAM
        assert table.tier_stats()["promoted_total"] == before


def test_promote_catchup_bitwise_with_thresholds():
    """Spill -> decay passes -> promote must reproduce the never-spilled
    table bitwise, with pin/admission thresholds active and a decay rate
    (0.9) whose powers are NOT exact in fp32 — the catch-up must replay
    the same sequential multiplies the resident path applied."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=3)
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(1, 1 << 48, 3000).astype(np.uint64))
    vals = rng.normal(0, 1, (len(keys), lay.width)).astype(np.float32)
    vals[:, lay.SHOW] = rng.uniform(0.5, 60.0, len(keys)).astype(np.float32)
    with tempfile.TemporaryDirectory() as d:
        spilly = _make_table(d, decay=0.9, embedx=3)
        control = _make_table(None, decay=0.9, embedx=3, spill=False)
        for t in (spilly, control):
            t.pull_or_create(keys)
            t.push(keys, vals.copy())
        config.set_flag("spill_policy", "freq")
        config.set_flag("spill_pin_show", 30.0)
        config.set_flag("spill_admit_show", 2.0)
        spilly.spill_cold(len(keys) // 3)
        assert spilly.tier_stats()["disk_rows"] > 0
        for _ in range(5):  # spilled rows fall 5 decay epochs behind
            spilly.decay_and_shrink()
            control.decay_and_shrink()
        got = spilly.pull_or_create(keys)  # promote + catch-up decay
        want = control.pull_or_create(keys)
        np.testing.assert_array_equal(got, want)


def test_freq_beats_fifo_on_zipf_stream():
    """The A/B unit claim: same seeded zipf stream, same mem_cap_rows,
    freq must finish with strictly fewer disk promotes than fifo."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    promotes = {}
    for policy in ("freq", "fifo"):
        with tempfile.TemporaryDirectory() as d:
            table = _make_table(d, n_shards=8, decay=0.98, cap=1500)
            config.set_flag("spill_policy", policy)
            for p in range(4):
                rng = np.random.default_rng((3, p))
                raw = rng.zipf(1.3, 20_000)
                folded = ((raw - 1) % 5000).astype(np.uint64)
                with np.errstate(over="ignore"):
                    keys = folded * np.uint64(0x9E3779B97F4A7C15) + np.uint64(1)
                uniq, counts = np.unique(keys, return_counts=True)
                rows = table.pull_or_create(uniq)
                rows[:, lay.SHOW] += counts.astype(np.float32)
                table.push(uniq, rows)
                table.decay_and_shrink()
                table.maybe_spill()
            promotes[policy] = table.tier_stats()["promoted_total"]
    assert promotes["freq"] < promotes["fifo"], promotes


def test_spill_io_error_typed_and_counted():
    """A failing sweep surfaces as the typed SpillIOError (an IOError, so
    existing retry tiers still catch it), bumps table.spill_errors, and a
    healed retry succeeds."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d, cap=100)
        keys = np.arange(1, 501, dtype=np.uint64)
        _seed_shows(table, lay, keys, 1.0)
        before = STAT_GET("table.spill_errors")
        with inject(fail_once("spill.io")):
            with pytest.raises(SpillIOError) as ei:
                table.maybe_spill()
            assert isinstance(ei.value, IOError)
            assert ei.value.op == "spill_cold" and ei.value.rc == -2
            assert STAT_GET("table.spill_errors") == before + 1
            assert table.maybe_spill() == 400  # healed retry inside plan
        assert table.tier_stats()["mem_rows"] == 100


def test_spill_without_disk_tier_raises_typed():
    """spill_cold on a table built without spill_dir: the native rc -1
    maps to SpillIOError too (fifo + freq alike)."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    table = _make_table(None, spill=False)
    _seed_shows(table, lay, np.arange(1, 301, dtype=np.uint64), 1.0)
    for policy in ("freq", "fifo"):
        config.set_flag("spill_policy", policy)
        with pytest.raises(SpillIOError) as ei:
            table.spill_cold(10)
        assert ei.value.rc == -1


def test_unknown_policy_rejected():
    _native_or_skip()
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d)
        table.pull_or_create(np.arange(1, 101, dtype=np.uint64))
        config.set_flag("spill_policy", "lru")
        with pytest.raises(ValueError, match="spill_policy"):
            table.spill_cold(10)


def test_tier_stats_shape_and_gauges():
    """tier_stats must expose every TIER_STAT_FIELDS total, per-shard
    vectors, and skew maxima consistent with mem_rows/disk_rows; the
    publish hook mirrors the totals into literal table.tier.* gauges."""
    _native_or_skip()
    from paddlebox_tpu.utils.native import TIER_STAT_FIELDS

    lay = ValueLayout(embedx_dim=1)
    with tempfile.TemporaryDirectory() as d:
        table = _make_table(d, n_shards=4)
        _seed_shows(table, lay, np.arange(1, 1001, dtype=np.uint64), 1.0)
        table.spill_cold(300)
        st = table.publish_tier_stats()
        for f in TIER_STAT_FIELDS:
            assert f in st
            assert len(st["per_shard"][f]) == 4
            assert sum(st["per_shard"][f]) == st[f]
        assert st["mem_rows"] == table.mem_rows == 300
        assert st["disk_rows"] == table.disk_rows == 700
        assert st["spilled_total"] == 700
        assert st["spill_bytes"] > 0
        assert st["mem_rows_max_shard"] == max(st["per_shard"]["mem_rows"])
        assert STAT_GET("table.tier.mem_rows") == 300
        assert STAT_GET("table.tier.disk_rows") == 700
        assert STAT_GET("table.tier.spilled_total") == 700
        # the freq sweep apportions by occupancy: no shard hoards the cap
        assert st["mem_rows_max_shard"] <= 300  # trivial bound
        assert st["mem_rows_max_shard"] < 300 or table.n_shards == 1


def test_cap_never_hit_is_bitwise_noop():
    """With mem_cap_rows above the working set the tier machinery must be
    invisible: zero spills and rows bitwise equal to a no-tier table."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=2)
    rng = np.random.default_rng(11)
    keys = np.unique(rng.integers(1, 1 << 40, 2000).astype(np.uint64))
    vals = rng.normal(0, 1, (len(keys), lay.width)).astype(np.float32)
    with tempfile.TemporaryDirectory() as d:
        tiered = _make_table(d, decay=0.9, cap=10_000_000, embedx=2)
        plain = _make_table(None, decay=0.9, embedx=2, spill=False)
        for t in (tiered, plain):
            t.pull_or_create(keys)
            t.push(keys, vals.copy())
            t.decay_and_shrink()
        tiered.maybe_spill()
        st = tiered.tier_stats()
        assert st["spilled_total"] == 0 and st["disk_rows"] == 0
        np.testing.assert_array_equal(
            tiered.pull_or_create(keys), plain.pull_or_create(keys)
        )


def test_scale_soak_zipf_smoke():
    """tools/scale_soak.py --zipf at toy scale: both policies run, tier
    stats land in the JSON, and with a cap that is never hit the two
    policies' table digests are bitwise identical."""
    _native_or_skip()
    with tempfile.TemporaryDirectory() as d:
        out = os.path.join(d, "tier.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "scale_soak.py"),
             "--zipf", "--keys", "1e5", "--passes", "2", "--draws", "3e4",
             "--mem-cap", "1000000000", "--out", out],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr + proc.stdout
        with open(out) as f:
            res = json.load(f)
        assert res["metric"] == "tiered_store_zipf_soak"
        for policy in ("freq", "fifo"):
            pol = res["policies"][policy]
            assert pol["tier_stats"]["spilled_total"] == 0  # cap never hit
            assert len(pol["passes"]) == 2
            assert all(p["spill_hit_rate"] == 1.0 for p in pol["passes"])
        assert res["ab"]["bitwise_equal"] is True
        assert (
            res["policies"]["freq"]["digest"]
            == res["policies"]["fifo"]["digest"]
        )
