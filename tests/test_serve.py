"""Serving follower tests: watermark tailing, atomic delta apply, parity.

The gates the serving plane (paddlebox_tpu/serve/) must hold:

- latest.json is published with every cursor write and names exactly the
  base + ordered delta chain (pinned by manifest CRCs) + paired dense.
- Out-of-lineage watermarks (gaps, rewinds) raise DeltaLineageError on
  both the producer and follower sides.
- A crash injected mid-apply (fault site ``serve.apply_delta``) never
  surfaces a partial delta: the served version — and its scores — stay
  bitwise what they were, and a healed retry catches up.
- THE gate: follower scores after applying delta N are bitwise-equal to
  scoring directly against the trainer's table at pass N (same compiled
  forward, table_source vs version_source).
- Committed version indices and staleness samples are monotone.
"""

import json
import os

import numpy as np
import optax
import pytest

import jax

from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.data.parser import parse_line
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.serve import Follower, Scorer, table_source, version_source
from paddlebox_tpu.table import HostSparseTable, SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import (
    CheckpointManager,
    CTRTrainer,
    DeltaLineageError,
    MembershipEpochError,
    TrainStepConfig,
    read_watermark,
    validate_watermark,
)
from paddlebox_tpu.utils.faultinject import InjectedFault, fail_once, inject
from paddlebox_tpu.utils.monitor import STAT_GET

S, B = 4, 16
DATE = "20260807"
LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(
    embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
)
SCHEMA = SlotSchema(
    [SlotInfo("label", type="float", dense=True, dim=1)]
    + [SlotInfo(f"s{i}") for i in range(S)],
    label_slot="label",
)


class PublishStack:
    """Producer (trainer + CheckpointManager) and follower (own trainer)
    over one tmp checkpoint root. One training pass per published save."""

    def __init__(self, tmp_path, with_follower=True):
        self.tmp = str(tmp_path)
        self.root = os.path.join(self.tmp, "ckpt")
        self.rng = np.random.default_rng(0)
        self.table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
        self.ds = BoxPSDataset(SCHEMA, self.table, batch_size=B, shuffle_mode="none")
        self.cfg = TrainStepConfig(
            num_slots=S, batch_size=B, layout=LAYOUT, sparse_opt=OPT, auc_buckets=500
        )
        model = DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,))
        self.trainer = CTRTrainer(model, self.cfg, dense_opt=optax.adam(1e-2))
        self.trainer.init_params(jax.random.PRNGKey(0))
        self.mgr = CheckpointManager(self.root)
        self.n_files = 0
        self.probe = None  # records scored on both sides of the parity gate
        self.follower = None
        self.scorer = None
        if with_follower:
            model_f = DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,))
            tr_f = CTRTrainer(model_f, self.cfg, dense_opt=optax.adam(1e-2))
            self.follower = Follower(self.root, LAYOUT, OPT, n_host_shards=4, trainer=tr_f)
            self.scorer = Scorer(model_f, self.cfg)

    def _write_file(self, n=96, lo=1):
        path = os.path.join(self.tmp, f"p{self.n_files}.txt")
        self.n_files += 1
        lines = []
        for _ in range(n):
            keys = self.rng.integers(lo, lo + 150, S)
            lines.append(
                f"1 {float(keys[0] % 2)} " + " ".join(f"1 {k}" for k in keys)
            )
        # fixture writer: path derives from the harness tmp dir
        # pbox-lint: disable=IO004
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        if self.probe is None:
            self.probe = [parse_line(ln, SCHEMA) for ln in lines[:24]]
        return path

    def run_pass(self, lo=1):
        path = self._write_file(lo=lo)
        self.ds.set_filelist([path])
        self.ds.load_into_memory()
        self.ds.begin_pass(round_to=8)
        self.trainer.train_pass(self.ds)
        self.ds.end_pass(self.trainer.trained_table_device())
        self.table.drain_pending()

    def publish_base(self):
        self.run_pass(lo=1)
        self.mgr.save_base(DATE, self.table, self.trainer)

    def publish_delta(self, lo):
        self.run_pass(lo=lo)
        self.mgr.save_delta(DATE, self.table, self.trainer)

    # ---- parity probes ---------------------------------------------------

    def trainer_scores(self):
        return self.scorer.score_records(
            self.probe,
            SCHEMA,
            table_source(LAYOUT, self.table),
            self.trainer.params,
            self.trainer.opt_state,
        )

    def follower_scores(self, version=None):
        v = self.follower.version() if version is None else version
        return self.scorer.score_records(
            self.probe, SCHEMA, version_source(LAYOUT, v), v.params, v.opt_state
        )


@pytest.fixture
def stack(tmp_path):
    return PublishStack(tmp_path)


# ---- watermark publish + structure ---------------------------------------

def test_watermark_published_with_every_save(tmp_path):
    st = PublishStack(tmp_path, with_follower=False)
    assert read_watermark(st.root) is None  # nothing published yet
    st.publish_base()
    wm = read_watermark(st.root)
    assert wm["date"] == DATE and wm["delta_idx"] == 0
    assert wm["base"]["path"] == f"{DATE}/base"
    assert isinstance(wm["base"]["manifest_crc"], int)
    assert wm["deltas"] == []
    assert wm["dense"]["path"] == f"{DATE}/dense-0000.npz"
    assert isinstance(wm["dense"]["crc32"], int)
    assert wm["published_unix"] > 0
    validate_watermark(wm)

    st.publish_delta(lo=100)
    st.publish_delta(lo=200)
    wm = st.mgr.read_watermark()
    assert wm["delta_idx"] == 2
    assert [d["path"] for d in wm["deltas"]] == [
        f"{DATE}/delta-0001",
        f"{DATE}/delta-0002",
    ]
    assert all(isinstance(d["manifest_crc"], int) for d in wm["deltas"])
    assert wm["dense"]["path"] == f"{DATE}/dense-0002.npz"
    validate_watermark(wm)


def test_watermark_lineage_validation():
    # chain with a gap: delta_idx 2 but only delta-0002 listed
    wm = {
        "date": DATE,
        "delta_idx": 2,
        "base": {"path": f"{DATE}/base"},
        "deltas": [{"path": f"{DATE}/delta-0002"}],
    }
    with pytest.raises(DeltaLineageError, match="out of lineage"):
        validate_watermark(wm)
    # base from another date
    wm2 = {
        "date": DATE,
        "delta_idx": 0,
        "base": {"path": "20200101/base"},
        "deltas": [],
    }
    with pytest.raises(DeltaLineageError, match="does not belong"):
        validate_watermark(wm2)
    with pytest.raises(DeltaLineageError, match="malformed"):
        validate_watermark({"date": DATE})


def test_producer_refuses_out_of_lineage_publish(tmp_path):
    """Deleting a mid-chain delta dir must make the NEXT save_delta raise
    instead of publishing a chain no trainer state corresponds to."""
    st = PublishStack(tmp_path, with_follower=False)
    st.publish_base()
    st.publish_delta(lo=100)
    st.publish_delta(lo=200)
    import shutil

    shutil.rmtree(os.path.join(st.root, DATE, "delta-0001"))
    st.run_pass(lo=300)
    with pytest.raises(DeltaLineageError, match="out-of-lineage"):
        st.mgr.save_delta(DATE, st.table, st.trainer)


# ---- follower tailing + THE parity gate ----------------------------------

def test_follower_tails_chain_with_bitwise_parity(stack):
    st = stack
    fol = st.follower
    assert fol.poll_once() is False  # nothing published yet
    st.publish_base()
    assert fol.poll_once() is True
    v = fol.version()
    assert (v.date, v.delta_idx) == (DATE, 0)
    assert v.n_rows == len(st.table.keys())
    np.testing.assert_array_equal(st.trainer_scores(), st.follower_scores())

    for i, lo in ((1, 120), (2, 260)):
        st.publish_delta(lo=lo)
        ref = st.trainer_scores()  # trainer-direct, captured at pass i
        assert fol.poll_once() is True
        v = fol.version()
        assert v.delta_idx == i
        np.testing.assert_array_equal(ref, st.follower_scores())

    # versions committed in strictly increasing delta order
    assert fol.scoring.committed_indices() == [0, 1, 2]
    # idempotent poll: nothing new -> no new version
    assert fol.poll_once() is False
    assert fol.scoring.committed_indices() == [0, 1, 2]
    # a key the published model never saw scores from the zero row, not a crash
    rows, n_miss = v.lookup_rows(np.array([2**63 + 17], dtype=np.uint64))
    assert n_miss == 1 and not rows.any()


def test_kill_mid_apply_keeps_old_version_bitwise(stack):
    st = stack
    fol = st.follower
    st.publish_base()
    st.publish_delta(lo=120)
    assert fol.poll_once() is True
    v0 = fol.version()
    before = st.follower_scores(v0)

    st.publish_delta(lo=260)
    with inject(fail_once("serve.apply_delta")):
        with pytest.raises(InjectedFault):
            fol.poll_once()
    # the swap never happened: same version object, same scores, bit for bit
    v1 = fol.version()
    assert v1 is v0 and v1.delta_idx == 1
    np.testing.assert_array_equal(before, st.follower_scores(v1))

    # healed retry catches up (staging re-apply is idempotent)
    assert fol.poll_once() is True
    v2 = fol.version()
    assert v2.delta_idx == 2
    np.testing.assert_array_equal(st.trainer_scores(), st.follower_scores(v2))
    assert fol.scoring.committed_indices() == [0, 1, 2]


def test_corrupt_delta_skipped_and_alarmed(stack):
    st = stack
    fol = st.follower
    st.publish_base()
    assert fol.poll_once() is True
    good = st.follower_scores()

    st.publish_delta(lo=120)
    delta_dir = os.path.join(st.root, DATE, "delta-0001")
    victim = next(
        os.path.join(delta_dir, n)
        for n in sorted(os.listdir(delta_dir))
        if n.endswith(".npz")
    )
    original = open(victim, "rb").read()
    # deliberate corruption of a published delta (raw bytes are the point)
    # pbox-lint: disable=IO004
    with open(victim, "wb") as f:  # flip bytes, keep the size
        f.write(original[:10] + bytes([original[10] ^ 0xFF]) + original[11:])

    skipped0 = STAT_GET("serve.corrupt_skipped")
    assert fol.poll_once() is False  # bad link: nothing applied
    assert STAT_GET("serve.corrupt_skipped") == skipped0 + 1
    v = fol.version()
    assert v.delta_idx == 0  # still the base
    np.testing.assert_array_equal(good, st.follower_scores(v))

    # deliberate in-place repair of the corrupted delta (raw on purpose)
    # pbox-lint: disable=IO004
    with open(victim, "wb") as f:  # repair: publisher re-copies the delta
        f.write(original)
    assert fol.poll_once() is True
    assert fol.version().delta_idx == 1
    np.testing.assert_array_equal(st.trainer_scores(), st.follower_scores())


def test_watermark_rewind_rejected(stack):
    st = stack
    fol = st.follower
    st.publish_base()
    st.publish_delta(lo=120)
    assert fol.poll_once() is True
    assert fol.version().delta_idx == 1

    # hand-roll a rewound watermark: same base, delta_idx back to 0
    wm = read_watermark(st.root)
    wm["delta_idx"], wm["deltas"] = 0, []
    # hand-rolled torn watermark: bypassing atomic_write IS the point
    # pbox-lint: disable=IO004
    with open(os.path.join(st.root, "latest.json"), "w") as f:
        json.dump(wm, f)
    with pytest.raises(DeltaLineageError, match="rewound"):
        fol.poll_once()
    assert fol.version().delta_idx == 1  # still serving, unregressed


# ---- elastic membership on the serve plane --------------------------------

def test_mixed_epoch_watermark_rejected():
    """A chain whose base and deltas were published under different
    ownership epochs covers different key ranges and must never compose:
    validate_watermark rejects it with the typed error."""
    wm = {
        "date": DATE,
        "delta_idx": 1,
        "base": {"path": f"{DATE}/base", "ownership_epoch": 0},
        "deltas": [{"path": f"{DATE}/delta-0001", "ownership_epoch": 1}],
    }
    with pytest.raises(MembershipEpochError, match="mixes ownership epochs"):
        validate_watermark(wm)
    # the typed error IS a DeltaLineageError: every existing alarm-and-
    # keep-serving path (Follower.run, supervisor resume) already catches it
    assert issubclass(MembershipEpochError, DeltaLineageError)
    # one uniform epoch — any epoch — composes fine
    wm["deltas"][0]["ownership_epoch"] = 0
    validate_watermark(wm)


def test_follower_reanchors_across_epoch_flip(stack):
    """The trainer rank set changes mid-day: the re-anchored base under
    the new ownership epoch supersedes the old chain wholesale, and the
    follower reloads it without a restart — score parity holds across
    the flip."""
    st = stack
    fol = st.follower
    st.publish_base()
    st.publish_delta(lo=120)
    assert fol.poll_once() is True
    assert fol.version().delta_idx == 1
    reanchors0 = STAT_GET("serve.epoch_reanchors")

    # a membership change bumps the manager's epoch; the next save_base
    # re-anchors the chain under the SAME date (what the supervisor does
    # after a rank death or a committed migration)
    st.mgr.ownership_epoch = 1
    st.publish_base()
    wm = read_watermark(st.root)
    assert wm["ownership_epoch"] == 1 and wm["delta_idx"] == 0
    assert fol.poll_once() is True
    assert STAT_GET("serve.epoch_reanchors") == reanchors0 + 1
    v = fol.version()
    assert v.delta_idx == 0  # the old chain's position was abandoned
    np.testing.assert_array_equal(st.trainer_scores(), st.follower_scores())
    assert STAT_GET("serve.ownership_epoch") == 1

    # the new-epoch chain tails normally from here
    st.publish_delta(lo=260)
    ref = st.trainer_scores()
    assert fol.poll_once() is True
    assert fol.version().delta_idx == 1
    np.testing.assert_array_equal(ref, st.follower_scores())


def test_staleness_and_served_index_monotonic(stack):
    """Drive the batched front-end across publishes: staleness samples are
    non-negative and stamped once per version in increasing delta order;
    served indices never regress."""
    from paddlebox_tpu.serve import ScoreServer

    st = stack
    fol = st.follower
    st.publish_base()
    fol.poll_once()
    srv = ScoreServer(fol, st.scorer, SCHEMA)
    srv.start()
    try:
        for lo in (120, 260):
            preds = srv.score(st.probe[:8], timeout=60)
            assert preds.shape == (8,) and np.isfinite(preds).all()
            st.publish_delta(lo=lo)
            fol.poll_once()
        preds = srv.score(st.probe[:8], timeout=60)
        np.testing.assert_array_equal(preds, st.trainer_scores()[:8])
    finally:
        srv.stop()

    assert len(srv.staleness) == 3  # one sample per served version
    indices = [i for i, _ in srv.staleness]
    assert indices == sorted(indices) == [0, 1, 2]
    assert all(lag >= 0 for _, lag in srv.staleness)
    served = srv.served_indices
    assert served == sorted(served)  # never regresses
    lat = srv.latency_percentiles()
    assert lat["n"] == 3 and lat["p99_ms"] >= lat["p50_ms"] > 0


# ---- fleet satellites: deadlines, gossip, miss counters ---------------------


def test_score_timeout_flag_surfaces_typed_error_on_stalled_scorer(stack):
    """``serve_request_timeout_ms`` is the default deadline for every
    in-process ``score`` call: a wedged scorer surfaces as the typed
    ServeTimeoutError (a TimeoutError subclass, so pre-fleet callers keep
    working) instead of blocking the caller forever."""
    import time as _time

    from paddlebox_tpu import config
    from paddlebox_tpu.serve import ScoreServer, ServeTimeoutError

    st = stack
    st.publish_base()
    st.follower.poll_once()

    real_score = st.scorer.score_records

    def stalled(*a, **k):
        _time.sleep(0.6)  # wedged longer than the flag deadline below
        return real_score(*a, **k)

    st.scorer.score_records = stalled
    srv = ScoreServer(st.follower, st.scorer, SCHEMA)
    srv.start()
    prev = config.get_flag("serve_request_timeout_ms")
    config.set_flag("serve_request_timeout_ms", 100.0)
    timeouts0 = STAT_GET("serve.request_timeouts")
    try:
        with pytest.raises(ServeTimeoutError):
            srv.score(st.probe[:8])  # no explicit timeout: the flag rules
        assert STAT_GET("serve.request_timeouts") == timeouts0 + 1
        # the builtin-compatibility contract
        with pytest.raises(TimeoutError):
            srv.score(st.probe[:8])
    finally:
        config.set_flag("serve_request_timeout_ms", prev)
        st.scorer.score_records = real_score
        srv.stop()


def test_fleet_view_drains_reanchoring_follower_and_readmits(stack):
    """Staleness gossip across a forced epoch re-anchor mid-serve: the
    fleet view marks the behind-the-flip follower "reanchor" (out of
    rotation) while a peer already serves the new epoch, readmits it once
    its own re-anchor lands, and the per-rank staleness log stays monotone
    per version — (epoch, delta_idx) strictly increases even though the
    raw delta index regresses at the flip."""
    from paddlebox_tpu import config
    from paddlebox_tpu.serve.fleet import FleetView

    st = stack
    fol = st.follower
    view = FleetView([1, 2])
    prev = config.get_flag("serve_health_dead_s")
    config.set_flag("serve_health_dead_s", 60.0)  # no dead marks in-test
    try:

        def beat(rank, snap, state):
            b = dict(snap)
            b["state"] = state
            b["queue_depth"] = 0
            view.observe(rank, b)

        st.publish_base()
        fol.poll_once()
        beat(1, fol.health_snapshot(), "ready")
        beat(2, fol.health_snapshot(), "ready")  # peer at the same position
        assert view.status(1) == "ready"

        st.publish_delta(lo=120)
        fol.poll_once()
        beat(1, fol.health_snapshot(), "ready")
        beat(2, fol.health_snapshot(), "ready")

        # ---- forced epoch re-anchor mid-serve: the peer (rank 2) has
        # already applied the re-anchored base; rank 1 still gossips the
        # old epoch -> drained from rotation without any drain command
        st.mgr.ownership_epoch = 1
        st.publish_base()
        old_snap = dict(fol.health_snapshot())  # rank 1: epoch 0, delta 1
        fol.poll_once()  # rank 1 re-anchors (epoch 1, delta 0)
        new_snap = fol.health_snapshot()
        assert new_snap["ownership_epoch"] == 1 and new_snap["epoch_reanchors"] == 1
        beat(2, new_snap, "ready")  # the peer leads the flip
        beat(1, old_snap, "ready")  # rank 1's gossip is still pre-flip
        assert view.status(1) == "reanchor"  # epoch-behind: not queried
        assert view.queryable() == [2]

        # a follower announcing reanchoring=True is equally out
        mid = dict(new_snap)
        mid["reanchoring"] = True
        beat(1, mid, "reanchor")
        assert view.status(1) == "reanchor"

        # ---- re-anchor lands: readmitted
        beat(1, fol.health_snapshot(), "ready")
        assert view.status(1) == "ready"
        assert sorted(view.queryable()) == [1, 2]

        # ---- staleness gauge monotone per version across the flip
        log = view.staleness_log[1]
        positions = [(e, d) for e, d, _ in log]
        assert positions == sorted(positions)
        assert positions[-1][0] == 1  # the new epoch is in the log
        assert all(s >= 0 for _, _, s in log)
        # the raw delta index DID regress at the flip (1 -> 0): only the
        # (epoch, delta) ordering keeps the gauge monotone
        deltas = [d for _, d in positions]
        assert deltas != sorted(deltas)
    finally:
        config.set_flag("serve_health_dead_s", prev)


def test_zero_row_misses_are_counted_and_exported(stack):
    """Satellite for the silent-miss fix: a lookup over keys the published
    model never saw still scores (zero rows), but bumps ``serve.key_misses``
    by the exact miss count, and the next commit snapshots the cumulative
    counter into ``serve.key_misses_at_commit``."""
    st = stack
    fol = st.follower
    st.publish_base()
    fol.poll_once()
    v = fol.version()

    misses0 = STAT_GET("serve.key_misses")
    bogus = np.array([2**63 + 5, 2**63 + 7, 2**63 + 11], dtype=np.uint64)
    rows, n_miss = v.lookup_rows(bogus)
    assert n_miss == 3 and not rows.any()
    assert STAT_GET("serve.key_misses") == misses0 + 3

    # a mixed batch counts only the genuinely missing keys
    known = v.keys[:2]
    mixed = np.concatenate([known, bogus[:1]])
    _, n_miss2 = v.lookup_rows(mixed)
    assert n_miss2 == 1
    assert STAT_GET("serve.key_misses") == misses0 + 4

    # the next commit exports the cumulative counter as a gauge
    st.publish_delta(lo=120)
    fol.poll_once()
    assert STAT_GET("serve.key_misses_at_commit") == STAT_GET("serve.key_misses")
