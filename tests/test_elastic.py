"""Elastic membership: ownership epochs, rank death, planned key migration.

The acceptance bar for the key-ownership-epoch tentpole:

- :class:`OwnershipMap` is an explicit, versioned shard-range -> rank map
  (largest-remainder uneven splits allowed) whose ``shrink`` is minimal-
  movement: survivors keep their exact ranges and only DEAD ranges move,
  so checkpoint adoption covers every moved shard.
- A supervised multi-rank day that loses a rank mid-pass runs a survivor
  verdict round, adopts the dead rank's shard ranges from its last
  manifest-verified checkpoint, reverts the in-flight pass, and finishes
  the day on N-1 ranks — with sparse-table digest AND per-pass AUC
  bitwise-equal to a fresh N-1 run of the same day.
- Planned migration at a pass boundary (PR 8 skew trigger) streams moving
  ranges owner->owner over epoch-tagged PBTX frames and flips the epoch
  atomically — bitwise-equal to a no-migration ablation of the same day.
- FLT008 recovery contracts for the two new fault sites: a kill mid-adopt
  retried lands bitwise-identical; a kill mid-migration leaves the OLD
  epoch serving and the plan is retried at the next boundary.

Deterministic, CPU-only, tier-1 under the ``chaos`` marker.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.parallel.membership import (
    OwnershipMap,
    adopt_dead_shards,
    apportion,
    commit_staged,
    decode_shard_rows,
    encode_shard_rows,
    migrate_ranges,
    plan_moves,
    plan_rebalance,
)
from paddlebox_tpu.parallel.transport import TcpTransport, TransportTimeout
from paddlebox_tpu.table.dist_ws import DistributedWorkingSet, hot_shard_loads
from paddlebox_tpu.table.sparse_table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
    key_to_shard,
)
from paddlebox_tpu.train.checkpoint import (
    CheckpointManager,
    read_watermark,
    rank_root,
    validate_watermark,
)
from paddlebox_tpu.train.supervisor import (
    ElasticConfig,
    HealthGates,
    PassFailure,
    PassSupervisor,
    RetryPolicy,
)
from paddlebox_tpu.utils.faultinject import InjectedFault, fail_nth, inject
from paddlebox_tpu.utils.monitor import STAT_GET

pytestmark = pytest.mark.chaos

N_MESH = 8
N_RECORDS = 12
DATE = "20260807"
LAYOUT = ValueLayout(embedx_dim=2)
OPT = SparseOptimizerConfig(embedx_threshold=0.0)


@pytest.fixture(autouse=True)
def _fast_transport():
    """Test-speed transport knobs; restored after each test."""
    names = (
        "transport_heartbeat_s",
        "transport_backoff_s",
        "transport_send_retries",
        "transport_peer_dead_s",
    )
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_send_retries", 6)
    config.set_flag("transport_peer_dead_s", 60.0)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _cluster(n, timeout=30.0):
    eps = [f"127.0.0.1:{p}" for p in _free_ports(n)]
    return [TcpTransport(r, eps, timeout=timeout) for r in range(n)]


def _run_ranks(fn, n):
    """Run fn(rank) on n threads; re-raise the first worker exception."""
    results = [None] * n
    errors = []

    def wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[0][1]
    return results


def _mk_table():
    return HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)


# ---------------------------------------------------------------------------
# OwnershipMap: apportionment, queries, minimal-movement shrink
# ---------------------------------------------------------------------------


def test_apportion_largest_remainder():
    assert apportion(10, 3) == [4, 3, 3]
    assert apportion(7, 4) == [2, 2, 2, 1]
    assert apportion(8, 4) == [2, 2, 2, 2]
    assert apportion(2, 4) == [1, 1, 0, 0]  # more ranks than shards
    with pytest.raises(ValueError):
        apportion(4, 0)


def test_ownership_map_queries_and_roundtrip():
    m = OwnershipMap.even(10, 3)
    assert m.starts == (0, 4, 7, 10) and m.epoch == 0
    assert m.live_ranks == (0, 1, 2)
    assert m.range_of(1) == (4, 7) and m.n_owned(2) == 3
    assert m.is_live(1) and not m.is_live(3)
    # vectorized owner query against the scalar definition
    owners = m.owner_of_shard(np.arange(10))
    want = [next(r for r in m.live_ranks
                 if m.range_of(r)[0] <= s < m.range_of(r)[1])
            for s in range(10)]
    np.testing.assert_array_equal(owners, want)
    # value semantics survive the wire form
    back = OwnershipMap.from_json(m.to_json())
    assert back == m and hash(back) == hash(m)
    assert back != m.shrink([1])


def test_ownership_map_validation():
    with pytest.raises(ValueError, match="at least one live rank"):
        OwnershipMap(4, [], [0, 4])
    with pytest.raises(ValueError, match="boundaries"):
        OwnershipMap(4, [0, 1], [0, 2])  # wrong boundary count
    with pytest.raises(ValueError, match="span"):
        OwnershipMap(4, [0, 1], [0, 2, 3])  # doesn't reach n_mesh_shards
    with pytest.raises(ValueError, match="non-decreasing"):
        OwnershipMap(4, [0, 1, 2], [0, 3, 2, 4])


def test_shrink_is_minimal_movement():
    m = OwnershipMap.even(8, 4)  # starts (0, 2, 4, 6, 8)
    s = m.shrink([1])
    assert s.epoch == 1
    assert s.live_ranks == (0, 2, 3)
    assert s.starts == (0, 3, 6, 8)  # dead gap [2,4) split at its midpoint
    # every survivor's old range is contained in its new one...
    for r in s.live_ranks:
        olo, ohi = m.range_of(r)
        nlo, nhi = s.range_of(r)
        assert nlo <= olo and ohi <= nhi
    # ...so every shard that changed owner came from the dead rank: the
    # checkpoint-adoption path covers ALL movement, no live->live transfer
    shards = np.arange(8)
    changed = m.owner_of_shard(shards) != s.owner_of_shard(shards)
    assert set(m.owner_of_shard(shards)[changed].tolist()) == {1}
    # leading / trailing gaps go wholly to the flanking survivor
    assert m.shrink([0]).range_of(1) == (0, 4)
    assert m.shrink([3]).range_of(2) == (4, 8)


def test_shrink_multiple_dead_and_boundaries():
    m = OwnershipMap.even(12, 4)  # starts (0, 3, 6, 9, 12)
    s = m.shrink([1, 2])  # both middle ranks die: gap [3,9) splits at 6
    assert s.live_ranks == (0, 3) and s.starts == (0, 6, 12)
    # zero-width ranges survive a shrink (more ranks than shards)
    tiny = OwnershipMap.even(2, 4)  # (0, 1, 2, 2, 2)
    t = tiny.shrink([1])
    assert t.live_ranks == (0, 2, 3)
    assert t.starts[0] == 0 and t.starts[-1] == 2
    assert sorted(t.owner_of_shard([0, 1]).tolist()) == [0, 2]
    with pytest.raises(ValueError, match="leaves no ranks"):
        OwnershipMap.even(4, 2).shrink([0, 1])


def test_plan_rebalance_and_moves():
    m = OwnershipMap.even(8, 2)  # [0,4) / [4,8)
    loads = np.array([40, 30, 30, 0, 10, 10, 10, 10], np.float64)
    # rank0 carries 100 vs mean 70: over a 1.2 threshold, recut
    p = plan_rebalance(m, loads, 1.2)
    assert p is not None and p.epoch == 1 and p.live_ranks == m.live_ranks
    new_per_rank = [loads[lo:hi].sum() for lo, hi in
                    (p.range_of(r) for r in p.live_ranks)]
    assert max(new_per_rank) < 100  # the hot rank actually shed load
    moves = plan_moves(m, p)
    assert moves and all(m.owner_of_shard([lo])[0] == src
                         and p.owner_of_shard([lo])[0] == dst
                         for lo, hi, src, dst in moves)
    # under the threshold, or with no load at all: no plan
    assert plan_rebalance(m, loads, 3.0) is None
    assert plan_rebalance(m, np.zeros(8), 1.1) is None
    with pytest.raises(ValueError, match="shard loads"):
        plan_rebalance(m, np.zeros(5), 1.1)
    # a dead src never appears in moves (that's the adoption path)
    shrunk = OwnershipMap.even(8, 2).shrink([1])
    assert plan_moves(OwnershipMap.even(8, 2), shrunk) == []


def test_shard_rows_codec_roundtrip():
    keys = np.array([3, 9, 2**40], np.uint64)
    rows = np.arange(3 * LAYOUT.width, dtype=np.float32).reshape(3, -1)
    k, r = decode_shard_rows(encode_shard_rows(keys, rows))
    np.testing.assert_array_equal(k, keys)
    np.testing.assert_array_equal(r, rows)
    k0, r0 = decode_shard_rows(
        encode_shard_rows(np.zeros(0, np.uint64),
                          np.zeros((0, LAYOUT.width), np.float32))
    )
    assert len(k0) == 0 and r0.shape[0] == 0


# ---------------------------------------------------------------------------
# satellite 1: uneven ownership ranges through a real distributed pass
# ---------------------------------------------------------------------------


def _uneven_pass(tps, ownership=None):
    n = len(tps)
    n_mesh = 4  # NOT divisible by 3 ranks — the old constructor refused this

    def worker(r):
        t = tps[r]
        table = _mk_table()
        kw = {} if ownership is None else {"ownership": ownership}
        ws = DistributedWorkingSet(t, n_mesh, pass_id=3, epoch=0, **kw)
        keys = np.arange(1 + r, 120, n).astype(np.uint64)
        ws.add_keys(keys)
        dev = ws.finalize(table, round_to=8)
        dev = dev * np.float32(1.01) + np.float32(0.25)
        ws.writeback(dev)
        rows = ws.lookup(keys)
        t.barrier("uneven-done@e0")
        hk = np.sort(table.keys())
        return dict(
            referenced=keys, rows=rows, cap=ws.capacity,
            spans=(ws.shard_lo, ws.shards_per_host),
            host_keys=hk, host_vals=table.pull_or_create(hk),
        )

    return _run_ranks(worker, n)


def test_uneven_ownership_full_pass():
    tps = _cluster(3)
    try:
        res = _uneven_pass(tps)
    finally:
        for t in tps:
            t.close()
    omap = OwnershipMap.even(4, 3)
    # per-rank spans follow the largest-remainder split [2, 1, 1]
    assert [r["spans"] for r in res] == [(0, 2), (2, 1), (3, 1)]
    assert len({r["cap"] for r in res}) == 1
    # each referenced key was created on exactly its owner
    referenced = np.unique(np.concatenate([r["referenced"] for r in res]))
    all_hosted = np.concatenate([r["host_keys"] for r in res])
    assert len(all_hosted) == len(np.unique(all_hosted))  # disjoint
    np.testing.assert_array_equal(np.sort(all_hosted), referenced)
    for r, out in enumerate(res):
        lo, hi = omap.range_of(r)
        sh = key_to_shard(out["host_keys"], 4)
        assert ((sh >= lo) & (sh < hi)).all()
        # global row ids stay inside the uneven global row space
        assert (out["rows"] >= 0).all()
        assert (out["rows"] < 4 * out["cap"]).all()


def test_uneven_ownership_zero_width_range():
    """A rank owning zero shards still completes the exchange (boundary of
    the uneven split: more ranks than shards in its slice)."""
    omap = OwnershipMap(4, [0, 1, 2], [0, 2, 4, 4])  # rank 2 owns nothing
    tps = _cluster(3)
    try:
        res = _uneven_pass(tps, ownership=omap)
    finally:
        for t in tps:
            t.close()
    assert res[2]["spans"] == (4, 0)
    assert len(res[2]["host_keys"]) == 0
    referenced = np.unique(np.concatenate([r["referenced"] for r in res]))
    all_hosted = np.concatenate([r["host_keys"] for r in res])
    np.testing.assert_array_equal(np.sort(all_hosted), referenced)


# ---------------------------------------------------------------------------
# satellite 2 (half 1): membership.adopt_shard FLT008 recovery contract
# ---------------------------------------------------------------------------


def _seed_dead_checkpoint(root, dead_rank):
    """Give the dead rank a durable base holding trained-looking rows."""
    src = _mk_table()
    keys = np.arange(1, 90, dtype=np.uint64)
    rows = src.pull_or_create(keys) * np.float32(1.01) + np.float32(0.25)
    src.push(keys, rows)
    CheckpointManager(rank_root(root, dead_rank)).save_base(DATE, src)
    return src


def test_adopt_fault_retry_lands_bitwise_identical(tmp_path):
    root = str(tmp_path)
    _seed_dead_checkpoint(root, 1)
    old = OwnershipMap.even(N_MESH, 2)
    new = old.shrink([1])

    ref = _mk_table()
    n_ref = adopt_dead_shards(ref, root, 1, old, new, 0)
    assert n_ref > 0

    t = _mk_table()
    with inject(fail_nth("membership.adopt_shard", 1)) as plan:
        with pytest.raises(InjectedFault):
            adopt_dead_shards(t, root, 1, old, new, 0)
    assert plan.failures("membership.adopt_shard") == 1
    # the kill window is BEFORE the push: nothing partial landed
    assert len(t.keys()) == 0
    # the retried adoption replays the same CRC-verified resume and lands
    # bitwise what the clean adoption did (FLT008 contract)
    assert adopt_dead_shards(t, root, 1, old, new, 0) == n_ref
    k = np.sort(t.keys())
    np.testing.assert_array_equal(k, np.sort(ref.keys()))
    np.testing.assert_array_equal(t.pull_or_create(k), ref.pull_or_create(k))
    # adopting AGAIN is a pure idempotent upsert — rows don't drift
    adopt_dead_shards(t, root, 1, old, new, 0)
    np.testing.assert_array_equal(t.pull_or_create(k), ref.pull_or_create(k))


def test_adopt_cold_death_adopts_nothing(tmp_path):
    # the dead rank never checkpointed: zero keys adopted, the retried
    # pass recreates its keys from the seeded deterministic init
    old = OwnershipMap.even(N_MESH, 2)
    t = _mk_table()
    assert adopt_dead_shards(t, str(tmp_path), 1, old, old.shrink([1]), 0) == 0
    assert len(t.keys()) == 0


def test_adopt_outside_gained_range_is_noop(tmp_path):
    root = str(tmp_path)
    _seed_dead_checkpoint(root, 1)
    old = OwnershipMap.even(N_MESH, 4)
    new = old.shrink([1])
    # rank 3 gains nothing from rank 1's gap (it flanks the far side)
    t = _mk_table()
    assert adopt_dead_shards(t, root, 1, old, new, 3) == 0
    assert len(t.keys()) == 0


# ---------------------------------------------------------------------------
# satellite 2 (half 2): migrate.transfer FLT008 at the membership layer
# ---------------------------------------------------------------------------


def _seeded_tables(omap):
    """Per-rank tables holding deterministic rows for their owned shards."""
    tables = []
    keys = np.arange(1, 200, dtype=np.uint64)
    sh = key_to_shard(keys, omap.n_mesh_shards)
    for r in omap.live_ranks:
        lo, hi = omap.range_of(r)
        t = _mk_table()
        mine = keys[(sh >= lo) & (sh < hi)]
        rows = t.pull_or_create(mine) * np.float32(1.01) + np.float32(0.25)
        t.push(mine, rows)
        tables.append(t)
    return tables


def test_migrate_fault_keeps_old_epoch_then_retry_commits():
    old = OwnershipMap.even(N_MESH, 2)
    new = old.rebalance([0, 2, N_MESH])  # move shards [2,4) from 0 to 1
    tables = _seeded_tables(old)
    before_k = [np.sort(t.keys()) for t in tables]
    before_v = [t.pull_or_create(k) for t, k in zip(tables, before_k)]
    tps = _cluster(2)
    try:
        def faulted(r):
            try:
                migrate_ranges(tps[r], tables[r], old, new, "s1", 0,
                               timeout=2.0)
                return None
            except (InjectedFault, TransportTimeout) as e:
                return e

        with inject(fail_nth("migrate.transfer", 1)) as plan:
            res = _run_ranks(faulted, 2)
        assert plan.failures("migrate.transfer") == 1
        # sender crashed before the wire; receiver timed out waiting
        assert isinstance(res[0], InjectedFault)
        assert isinstance(res[1], TransportTimeout)
        # nothing was staged or pushed: the OLD epoch still serves, both
        # tables bitwise what they were (FLT008 contract)
        for t, k, v in zip(tables, before_k, before_v):
            np.testing.assert_array_equal(np.sort(t.keys()), k)
            np.testing.assert_array_equal(t.pull_or_create(k), v)

        # the retried plan (next boundary, new seq) completes and commits
        def clean(r):
            stats = migrate_ranges(tps[r], tables[r], old, new, "s2", 0,
                                   timeout=10.0)
            commit_staged(tables[r], stats["staged"])
            return stats

        res2 = _run_ranks(clean, 2)
    finally:
        for t in tps:
            t.close()
    moved = before_k[0][key_to_shard(before_k[0], N_MESH) >= 2]
    assert res2[0]["sent_keys"] == len(moved) > 0
    assert res2[1]["recv_keys"] == len(moved)
    assert res2[0]["sent_bytes"] > 0
    # the destination now serves the moved range bitwise as the source held
    got = np.sort(tables[1].keys())
    assert set(moved.tolist()) <= set(got.tolist())
    src_rows = dict(zip(before_k[0].tolist(), before_v[0]))
    rows1 = tables[1].pull_or_create(moved)
    for i, key in enumerate(moved.tolist()):
        np.testing.assert_array_equal(rows1[i], src_rows[key])


# ---------------------------------------------------------------------------
# the supervised elastic day: harness doubles
# ---------------------------------------------------------------------------


class _RankKilled(BaseException):
    """Escapes every supervisor except-Exception tier, like a real death."""


def _global_records(seed, pass_idx, skewed=False):
    """The day's global record stream for one pass: (keys, label) tuples,
    identical for every membership (routing decides who trains which)."""
    rng = np.random.default_rng(1000 * seed + pass_idx)
    if skewed:
        pool = rng.integers(1, 1 << 40, 4096).astype(np.uint64)
        pool = pool[key_to_shard(pool, N_MESH) < 2]  # hot shards 0-1
    else:
        pool = rng.integers(1, 160, 4096).astype(np.uint64)
    recs = []
    for _ in range(N_RECORDS):
        nk = int(rng.integers(1, 4))
        keys = np.unique(rng.choice(pool, nk))
        recs.append((keys, float(rng.integers(0, 2))))
    return recs


class _ElasticDS:
    """Dataset double over a REAL HostSparseTable + DistributedWorkingSet.

    Routing: record i of a pass goes to ``sorted(live)[i % n_live]``, so
    the global record multiset is membership-independent — exactly the
    property the bitwise gates rely on."""

    def __init__(self, transport, table, seed, skewed=False):
        self.transport = transport
        self.table = table
        self.seed = seed
        self.skewed = skewed
        self.n_mesh_shards = N_MESH
        self.ownership = None  # installed by the supervisor on a flip
        self.pass_epoch = 0
        self._in_pass = False
        self.pass_idx = -1
        self.ws = None
        self.dev = None
        self.my_records = []

    def set_date(self, date):
        pass

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self):
        self.pass_idx = int(self._files[0].rsplit("-", 1)[1])

    def _omap(self):
        return self.ownership or OwnershipMap.even(
            self.n_mesh_shards, self.transport.n_ranks
        )

    def begin_pass(self, round_to=8, enable_revert=True, trainer=None):
        omap = self._omap()
        live = list(omap.live_ranks)
        recs = _global_records(self.seed, self.pass_idx, skewed=self.skewed)
        me = self.transport.rank
        self.my_records = [
            rec for i, rec in enumerate(recs) if live[i % len(live)] == me
        ]
        ws = DistributedWorkingSet(
            self.transport, self.n_mesh_shards,
            pass_id=self.pass_idx, epoch=self.pass_epoch, ownership=omap,
        )
        for keys, _ in self.my_records:
            ws.add_keys(keys)
        self.dev = ws.finalize(self.table, round_to=8)
        self.ws = ws
        self._in_pass = True

    def end_pass(self, table, shrink=True):
        self.ws.writeback(self.dev)
        self._in_pass = False

    def revert_pass(self):
        # host rows were only CREATED during finalize (deterministic
        # per-key init), never trained: dropping the device slice reverts
        self.ws = None
        self.dev = None
        self._in_pass = False
        self.pass_epoch += 1


def _elastic_trainer(ds, recorder, kill_at=None):
    """Trainer double: one deterministic transform per pass + per-record
    preds from the GLOBAL row assignment (membership-invariant). A doomed
    rank closes its transport and dies at the top of its kill pass.
    ``kill_at`` is a pass index, or ``(pass, visit)`` to die on the n-th
    attempt of that pass (visit 2 = the retry after a membership round)."""
    visits = {}

    def train_pass(_ds, n_batches=None):
        if kill_at is not None:
            k_pass, k_visit = (
                kill_at if isinstance(kill_at, tuple) else (kill_at, 1)
            )
            if ds.pass_idx == k_pass:
                visits[k_pass] = visits.get(k_pass, 0) + 1
                if visits[k_pass] >= k_visit:
                    ds.transport.close()
                    raise _RankKilled()
        ds.dev = ds.dev * np.float32(1.01) + np.float32(0.25)
        preds, labels = [], []
        for keys, label in ds.my_records:
            rows = ds.ws.lookup(keys).astype(np.int64)
            preds.append(((int(rows.sum()) + ds.pass_idx) % 97) / 97.0)
            labels.append(label)
        recorder[(ds.transport.rank, ds.pass_idx)] = (
            np.array(preds, np.float32), np.array(labels, np.float32),
        )
        return {"batches": 1.0, "nan_batches": 0.0, "auc": 0.5}

    tr = SimpleNamespace(
        params=None,
        prepare_pass=lambda _ds, n: None,
        train_pass=train_pass,
        trained_table=lambda: None,
        init_params=lambda *a, **k: None,
        load_dense=lambda path: None,
        save_dense=lambda path: np.savez(path, z=np.zeros(1, np.float32)),
        _state=None,
        _state_ws=None,
    )
    return tr


def _mk_sup(rank, tps, root, seed, recorder, kill_at=None, skewed=False,
            migrate_skew=0.0, initial_live=None, target_ranks=None):
    table = _mk_table()
    ds = _ElasticDS(tps[rank], table, seed, skewed=skewed)
    tr = _elastic_trainer(ds, recorder, kill_at=kill_at)
    ck = CheckpointManager(rank_root(root, rank))
    return PassSupervisor(
        ds, tr,
        checkpoint=ck,
        gates=HealthGates(auc_min_history=99),
        retry=RetryPolicy(max_retries=2, backoff_s=0.0,
                          sleep=lambda s: None),
        round_to=8,
        transport=tps[rank],
        elastic=ElasticConfig(
            shared_root=root, migrate_skew=migrate_skew,
            member_timeout=3.0, initial_live=initial_live,
            target_ranks=target_ranks,
        ),
    )


def _owned_digest(sup):
    omap = sup.ds._omap()
    lo, hi = omap.range_of(sup.coord.transport.rank)
    keys = np.sort(sup.table.keys())
    sh = key_to_shard(keys, N_MESH)
    keys = keys[(sh >= lo) & (sh < hi)]
    return keys, sup.table.pull_or_create(keys)


def _merged_digest(sups, ranks):
    """Ownership-filtered global digest: every key exactly once, under
    its CURRENT owner — stale copies on migration sources and dead disks
    are unreachable by construction."""
    parts = [_owned_digest(sups[r]) for r in ranks]
    keys = np.concatenate([k for k, _ in parts])
    rows = np.concatenate([v for _, v in parts])
    order = np.argsort(keys, kind="stable")
    assert len(keys) == len(np.unique(keys)), "ownership ranges overlap"
    return keys[order], rows[order]


def _pass_auc(recorder, p):
    """Global AUC of pass ``p`` via the repo metric (order-invariant)."""
    import jax.numpy as jnp

    from paddlebox_tpu.metrics.auc import auc_compute, auc_init, auc_update

    entries = [v for (r, pp), v in sorted(recorder.items()) if pp == p]
    preds = np.concatenate([e[0] for e in entries])
    labels = np.concatenate([e[1] for e in entries])
    state = auc_update(auc_init(1000), jnp.asarray(preds), jnp.asarray(labels))
    return auc_compute(state)


def _run_day(n, root, seed, recorder, kill_rank=None, kill_at=None,
             skewed=False, migrate_skew=0.0, passes=3, kills=None):
    kills = dict(kills or {})
    if kill_rank is not None:
        kills[kill_rank] = kill_at
    tps = _cluster(n)
    sups = [
        _mk_sup(r, tps, root, seed, recorder,
                kill_at=kills.get(r),
                skewed=skewed, migrate_skew=migrate_skew)
        for r in range(n)
    ]
    files = [[f"pass-{p}"] for p in range(passes)]

    def worker(r):
        try:
            return sups[r].run_day(DATE, files)
        except _RankKilled:
            return "killed"

    try:
        res = _run_ranks(worker, n)
    finally:
        for t in tps:
            t.close()
    return sups, res


# ---------------------------------------------------------------------------
# THE gate: rank death mid-pass == fresh shrunk-membership run, bitwise
# ---------------------------------------------------------------------------


def test_rank_death_mid_pass_bitwise_equals_fresh_shrunk_run(tmp_path):
    seed, passes, kill_at = 7, 3, 1
    config.set_flag("transport_peer_dead_s", 0.6)
    adopts_before = STAT_GET("membership.adopts")
    rec_e = {}
    sups, res = _run_day(
        4, str(tmp_path / "elastic"), seed, rec_e,
        kill_rank=1, kill_at=kill_at, passes=passes,
    )
    config.set_flag("transport_peer_dead_s", 60.0)
    assert res[1] == "killed"
    survivors = [0, 2, 3]
    for r in survivors:
        assert len(res[r]) == passes and all(o is not None for o in res[r])
        omap = sups[r].ds.ownership
        assert omap is not None and omap.epoch == 1
        assert list(omap.live_ranks) == survivors
        kinds = [i.kind for i in sups[r].incidents]
        assert "rank_death" in kinds
    # membership telemetry: epoch gauge flipped, adoptions counted
    assert STAT_GET("membership.epoch") == 1
    assert STAT_GET("membership.adopts") >= adopts_before + 2
    # the re-anchored chain publishes under the new epoch and validates
    wm = read_watermark(rank_root(str(tmp_path / "elastic"), 0))
    assert wm["ownership_epoch"] == 1
    validate_watermark(wm)
    # incident bundle (flight recorder): agreed survivor set, adopted
    # ranges, ownership epoch — dumped on every survivor
    for r in survivors:
        paths = glob.glob(os.path.join(
            rank_root(str(tmp_path / "elastic"), r),
            "obs", "incidents", "incident-*.json",
        ))
        bundles = []
        for p in paths:
            with open(p) as f:
                bundles.append(json.load(f))
        deaths = [b for b in bundles if b.get("reason") == "rank_death"]
        assert deaths, f"rank {r}: no rank_death incident bundle"
        detail = json.loads(deaths[-1]["detail"])
        assert detail["dead"] == [1]
        assert detail["survivors"] == survivors
        assert detail["ownership_epoch"] == 1
        assert detail["adopted_ranges"] is not None

    # the reference: a FRESH 3-rank run of the same day
    rec_f = {}
    sups_f, res_f = _run_day(3, str(tmp_path / "fresh"), seed, rec_f,
                             passes=passes)
    assert all(len(r) == passes for r in res_f)
    ek, ev = _merged_digest(sups, survivors)
    fk, fv = _merged_digest(sups_f, [0, 1, 2])
    np.testing.assert_array_equal(ek, fk)
    np.testing.assert_array_equal(ev, fv)
    # per-pass global AUC bitwise-equal (pass 0 at 4 ranks vs 3 ranks is
    # the same record multiset; post-death passes run on the survivors)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_e, p), _pass_auc(rec_f, p))


# ---------------------------------------------------------------------------
# planned migration at a boundary == no-migration ablation, bitwise
# ---------------------------------------------------------------------------


def test_planned_migration_bitwise_equals_no_migration(tmp_path):
    seed, passes = 11, 3
    migrated_before = STAT_GET("membership.migrated_keys")
    rec_m = {}
    sups_m, res_m = _run_day(
        3, str(tmp_path / "mig"), seed, rec_m, skewed=True,
        migrate_skew=1.15, passes=passes,
    )
    rec_0 = {}
    sups_0, res_0 = _run_day(
        3, str(tmp_path / "none"), seed, rec_0, skewed=True,
        migrate_skew=0.0, passes=passes,
    )
    assert all(len(r) == passes for r in (res_m + res_0))
    # the skew trigger actually fired: a commit on every rank, epoch > 0,
    # keys streamed
    for s in sups_m:
        kinds = [i.kind for i in s.incidents]
        assert "migrate" in kinds, kinds
        assert s.ds.ownership is not None and s.ds.ownership.epoch >= 1
    assert STAT_GET("membership.migrated_keys") > migrated_before
    assert all(s.ds.ownership is None for s in sups_0)
    # bitwise gate: recut + streamed ownership serves the exact state the
    # untouched run holds
    mk, mv = _merged_digest(sups_m, [0, 1, 2])
    zk, zv = _merged_digest(sups_0, [0, 1, 2])
    np.testing.assert_array_equal(mk, zk)
    np.testing.assert_array_equal(mv, zv)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_m, p), _pass_auc(rec_0, p))


def test_migrate_fault_aborts_then_next_boundary_commits(tmp_path):
    """FLT008 for migrate.transfer at the supervised-day level: a kill
    mid-migration leaves the OLD epoch serving; the plan is re-derived and
    committed at the NEXT boundary; the day's final state is still bitwise
    the no-migration run's."""
    seed, passes = 11, 3
    aborted_before = STAT_GET("membership.migrations_aborted")
    rec_f = {}
    with inject(fail_nth("migrate.transfer", 1)) as plan:
        sups_f, res_f = _run_day(
            3, str(tmp_path / "fault"), seed, rec_f, skewed=True,
            migrate_skew=1.15, passes=passes,
        )
    assert plan.failures("migrate.transfer") == 1
    assert all(len(r) == passes for r in res_f)
    assert STAT_GET("membership.migrations_aborted") > aborted_before
    kinds = [i.kind for s in sups_f for i in s.incidents]
    assert "migrate_abort" in kinds  # first boundary: abort, old epoch
    assert "migrate" in kinds        # later boundary: the retried plan
    rec_0 = {}
    sups_0, res_0 = _run_day(
        3, str(tmp_path / "none"), seed, rec_0, skewed=True,
        migrate_skew=0.0, passes=passes,
    )
    fk, fv = _merged_digest(sups_f, [0, 1, 2])
    zk, zv = _merged_digest(sups_0, [0, 1, 2])
    np.testing.assert_array_equal(fk, zk)
    np.testing.assert_array_equal(fv, zv)


# ---------------------------------------------------------------------------
# durability of the epoch flip: death in every post-flip window
# ---------------------------------------------------------------------------


def test_death_after_migration_commit_bitwise_equals_fresh_run(tmp_path):
    """The migrate epoch flip is durable BEFORE training resumes. Rank 1
    gains the hot shards in the boundary migration after pass 0 and dies
    mid-pass-1: adoption must restore its migrated-in trained rows from
    the re-anchored (post-flip) chain. Deferring the re-anchor save to
    the next boundary loses them — they exist durably nowhere, and the
    survivors would silently recreate them from the seeded init."""
    seed, passes = 13, 3
    config.set_flag("transport_peer_dead_s", 0.6)
    try:
        rec_e = {}
        sups, res = _run_day(
            3, str(tmp_path / "mig_kill"), seed, rec_e, skewed=True,
            migrate_skew=1.15, kill_rank=1, kill_at=1, passes=passes,
        )
    finally:
        config.set_flag("transport_peer_dead_s", 60.0)
    assert res[1] == "killed"
    survivors = [0, 2]
    for r in survivors:
        assert len(res[r]) == passes and all(o is not None for o in res[r])
        kinds = [i.kind for i in sups[r].incidents]
        assert "migrate" in kinds and "rank_death" in kinds
        omap = sups[r].ds.ownership
        # at least the migrate flip + the death flip (the survivors may
        # legitimately recut again at a later boundary)
        assert omap is not None and omap.epoch >= 2
        assert list(omap.live_ranks) == survivors
    rec_f = {}
    sups_f, res_f = _run_day(2, str(tmp_path / "fresh"), seed, rec_f,
                             skewed=True, passes=passes)
    assert all(len(r) == passes for r in res_f)
    ek, ev = _merged_digest(sups, survivors)
    fk, fv = _merged_digest(sups_f, [0, 1])
    np.testing.assert_array_equal(ek, fk)
    np.testing.assert_array_equal(ev, fv)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_e, p), _pass_auc(rec_f, p))


def test_two_ranks_die_same_pass_bitwise_equals_fresh_run(tmp_path):
    """Two simultaneous deaths: the second dead rank surfaces either in
    the agreed set at once or as a nested PeerDeadError mid-round — the
    re-entrant membership handling must converge instead of killing the
    day, and the result is still bitwise a fresh 2-rank run."""
    seed, passes = 17, 3
    config.set_flag("transport_peer_dead_s", 0.6)
    try:
        rec_e = {}
        sups, res = _run_day(
            4, str(tmp_path / "double"), seed, rec_e,
            kills={1: 1, 2: 1}, passes=passes,
        )
    finally:
        config.set_flag("transport_peer_dead_s", 60.0)
    assert res[1] == "killed" and res[2] == "killed"
    survivors = [0, 3]
    for r in survivors:
        assert len(res[r]) == passes and all(o is not None for o in res[r])
        omap = sups[r].ds.ownership
        assert omap is not None and list(omap.live_ranks) == survivors
        assert "rank_death" in [i.kind for i in sups[r].incidents]
    rec_f = {}
    sups_f, res_f = _run_day(2, str(tmp_path / "fresh"), seed, rec_f,
                             passes=passes)
    assert all(len(r) == passes for r in res_f)
    ek, ev = _merged_digest(sups, survivors)
    fk, fv = _merged_digest(sups_f, [0, 1])
    np.testing.assert_array_equal(ek, fk)
    np.testing.assert_array_equal(ev, fv)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_e, p), _pass_auc(rec_f, p))


def test_death_during_retried_pass_adopts_reanchored_chain(tmp_path):
    """The death-adoption flip has the same durability window as the
    migrate flip: rank 1 dies at pass 1; rank 2 survives that membership
    round — adopting part of rank 1's range and re-anchoring at the new
    epoch — then dies during the RETRIED pass 1, before any boundary
    save. The shard range it gained from rank 1 is durable ONLY in the
    immediate re-anchor base; adoption from it must land pass-0 training
    for those shards bitwise."""
    seed, passes = 19, 3
    config.set_flag("transport_peer_dead_s", 0.6)
    try:
        rec_e = {}
        sups, res = _run_day(
            4, str(tmp_path / "stagger"), seed, rec_e,
            kills={1: 1, 2: (1, 2)}, passes=passes,
        )
    finally:
        config.set_flag("transport_peer_dead_s", 60.0)
    assert res[1] == "killed" and res[2] == "killed"
    survivors = [0, 3]
    for r in survivors:
        assert len(res[r]) == passes and all(o is not None for o in res[r])
        omap = sups[r].ds.ownership
        # two sequential shrinks, two flips
        assert omap is not None and omap.epoch == 2
        assert list(omap.live_ranks) == survivors
    # rank 2 recorded preds on its FIRST (reverted) attempt of pass 1
    # before dying on the retry; drop that stale entry so the per-pass
    # AUC below sees the survivors' record multiset exactly once
    rec_e.pop((2, 1))
    rec_f = {}
    sups_f, res_f = _run_day(2, str(tmp_path / "fresh"), seed, rec_f,
                             passes=passes)
    assert all(len(r) == passes for r in res_f)
    ek, ev = _merged_digest(sups, survivors)
    fk, fv = _merged_digest(sups_f, [0, 1])
    np.testing.assert_array_equal(ek, fk)
    np.testing.assert_array_equal(ev, fv)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_e, p), _pass_auc(rec_f, p))


def test_adopt_fallback_uses_previous_owners_chain(tmp_path):
    """Unit contract for the residual window the end-to-end tests close:
    a dead chain whose recorded epoch predates the installed map (the
    rank died during its own re-anchor save) cannot cover the ranges it
    gained in that flip — adoption falls back to the PREVIOUS owners'
    chains for exactly those pieces, bitwise."""
    root = str(tmp_path)
    m0 = OwnershipMap.even(N_MESH, 4)  # r1 owns [2,4)
    m1 = m0.shrink([1])                # r2 gained shard 3; epoch 1
    m2 = m1.shrink([2])                # r0 gains [3,4), r3 gains [4,6)
    # rank 1's durable chain (epoch 0) holds trained rows for every shard
    # it hosted — including shard 3, which rank 2 gained at the m1 flip
    src = _seed_dead_checkpoint(root, 1)
    # rank 2 died before its re-anchor save: chain stuck at epoch 0,
    # covering only its ORIGINAL range [4,6)
    t2 = _mk_table()
    keys = np.arange(1, 90, dtype=np.uint64)
    sh = key_to_shard(keys, N_MESH)
    mine2 = keys[(sh >= 4) & (sh < 6)]
    t2.push(mine2, t2.pull_or_create(mine2) * np.float32(1.02))
    CheckpointManager(rank_root(root, 2)).save_base(DATE, t2)

    # without prev_map the gained piece [3,4) is silently absent
    bare = _mk_table()
    assert adopt_dead_shards(bare, root, 2, m1, m2, 0) == 0
    assert len(bare.keys()) == 0

    # with prev_map the piece comes bitwise from rank 1's chain
    fb_before = STAT_GET("membership.adopt_fallbacks")
    t = _mk_table()
    want = np.sort(keys[sh == 3])
    assert len(want) > 0
    assert adopt_dead_shards(t, root, 2, m1, m2, 0, prev_map=m0) == len(want)
    assert STAT_GET("membership.adopt_fallbacks") == fb_before + 1
    np.testing.assert_array_equal(np.sort(t.keys()), want)
    np.testing.assert_array_equal(
        t.pull_or_create(want), src.pull_or_create(want)
    )

    # rank 3's piece [4,6) is covered by the dead chain itself: the
    # fallback skips prev_owner == dead_rank, no double restore
    t3 = _mk_table()
    n3 = adopt_dead_shards(t3, root, 2, m1, m2, 3, prev_map=m0)
    assert n3 == len(mine2)
    np.testing.assert_array_equal(np.sort(t3.keys()), np.sort(mine2))
    np.testing.assert_array_equal(
        t3.pull_or_create(mine2), t2.pull_or_create(mine2)
    )


def test_exchange_verdict_fatal_raises_on_local_timeout():
    """A commit-point verdict must not fold a local transport timeout
    into a quiet NO vote (the rank cannot know whether peers committed):
    fatal=True re-raises, the default keeps the historical abort vote."""
    from paddlebox_tpu.train.supervisor import EpochCoordinator

    class _TimeoutTransport:
        rank = 0
        n_ranks = 2

        def allgather(self, payload, tag, timeout=None):
            raise TimeoutError("verdict round timed out")

    coord = EpochCoordinator(_TimeoutTransport())
    ok, detail = coord.exchange_verdict("migrate:x", True)
    assert not ok and "timed out" in detail
    with pytest.raises(TimeoutError):
        coord.exchange_verdict("migrate:x", True, fatal=True)


def test_migrate_load_view_size_mismatch_raises(tmp_path):
    """A mis-sized per-rank load view aborts the recut loudly (counter +
    raise) instead of silently zero-filling it — every rank would derive
    the same deterministic-but-wrong plan from the dropped view."""
    rec = {}
    tps = _cluster(2)
    try:
        sup = _mk_sup(0, tps, str(tmp_path), 3, rec, migrate_skew=1.1)
        good = np.ones(4, "<i8").tobytes()
        sup.coord.transport.allgather = (
            lambda payload, tag, timeout=None: [good, good[:-8]]
        )
        before = STAT_GET("membership.load_view_errors")
        with pytest.raises(RuntimeError, match="load view"):
            sup._maybe_migrate()
        assert STAT_GET("membership.load_view_errors") == before + 1
    finally:
        for t in tps:
            t.close()


# ---------------------------------------------------------------------------
# the grow half: OwnershipMap.grow + hot loads (unit)
# ---------------------------------------------------------------------------


def test_grow_minimal_movement_and_uniform_carve():
    # a middle joiner carves ONLY its flanks; everyone else keeps ranges
    m = OwnershipMap.even_over(N_MESH, [0, 2, 3])  # starts [0,3,6,8]
    g = m.grow(1)
    assert g.epoch == m.epoch + 1
    assert list(g.live_ranks) == [0, 1, 2, 3]
    # uniform carve of the [0,6) flank window lands on the even split
    assert [g.range_of(r) for r in g.live_ranks] == [
        (0, 2), (2, 4), (4, 6), (6, 8)
    ]
    # the non-flank survivor kept its exact range
    assert g.range_of(3) == m.range_of(3)
    # moves are flank -> joiner only
    for _lo, _hi, src, dst in plan_moves(m, g):
        assert dst == 1 and src in (0, 2)


def test_grow_hot_carve_follows_load():
    m = OwnershipMap.even(N_MESH, 3)  # rank 2 owns [6,8)
    # joiner lands at the end: the single flank window is [6,8)
    loads = np.zeros(N_MESH)
    loads[6], loads[7] = 10.0, 1.0
    g = m.grow(3, loads)
    # the hot shard 6 alone crosses the half-load quantile: the flank
    # keeps just it and the joiner takes the cold rim
    assert g.range_of(2) == (6, 7) and g.range_of(3) == (7, 8)
    assert g.range_of(0) == m.range_of(0) and g.range_of(1) == m.range_of(1)
    # load mass piled at the window's FAR edge must not starve the joiner
    # into an empty range: every part still lands at least one shard
    loads[:] = 0.0
    loads[7] = 10.0
    g = m.grow(3, loads)
    assert g.range_of(2) == (6, 7) and g.range_of(3) == (7, 8)


def test_grow_rejects_live_and_negative_ranks():
    m = OwnershipMap.even(N_MESH, 2)
    with pytest.raises(ValueError, match="already live"):
        m.grow(1)
    with pytest.raises(ValueError, match=">= 0"):
        m.grow(-1)
    with pytest.raises(ValueError, match="shard loads"):
        m.grow(2, np.ones(N_MESH - 1))


def test_hot_shard_loads_weights_shows():
    t = _mk_table()
    omap = OwnershipMap.even(N_MESH, 2)  # rank 0 owns [0,4)
    keys = np.arange(1, 50, dtype=np.uint64)
    sh = key_to_shard(keys, N_MESH)
    mine = keys[sh < 4]
    t.pull_or_create(mine)
    base = hot_shard_loads(t, omap, 0)
    assert base.shape == (4,)
    counts = np.bincount(key_to_shard(mine, N_MESH), minlength=4)[:4]
    # residency prior: every populated shard carries positive weight
    assert np.all((base > 0) == (counts > 0))
    # bump decayed shows on shard 0's keys: only that shard's load grows
    hot = mine[key_to_shard(mine, N_MESH) == 0]
    rows = t.pull_or_create(hot)
    rows[:, LAYOUT.SHOW] = np.float32(7.0)
    t.push(hot, rows)
    after = hot_shard_loads(t, omap, 0)
    assert after[0] > base[0]
    np.testing.assert_allclose(after[1:], base[1:])
    # a rank owning nothing contributes the empty vector
    gempty = OwnershipMap(N_MESH, [0, 1], [0, 0, N_MESH], 0)
    assert len(hot_shard_loads(t, gempty, 0)) == 0


# ---------------------------------------------------------------------------
# THE grow gate: join mid-day == fresh grown-membership run, bitwise
# ---------------------------------------------------------------------------


def _join_worker(sups, files, joiner, timeout=60.0):
    def worker(r):
        if r == joiner:
            return sups[r].join_day(files, timeout=timeout)
        return sups[r].run_day(DATE, files)

    return worker


def test_rank_join_mid_day_bitwise_equals_fresh_grown_run(tmp_path):
    seed, passes = 23, 3
    joins_before = STAT_GET("membership.joins_total")
    root = str(tmp_path / "join")
    tps = _cluster(4)
    rec_j = {}
    sups = [
        _mk_sup(r, tps, root, seed, rec_j, initial_live=[0, 1, 2])
        for r in range(3)
    ]
    sups.append(_mk_sup(3, tps, root, seed, rec_j))
    files = [[f"pass-{p}"] for p in range(passes)]
    try:
        res = _run_ranks(_join_worker(sups, files, joiner=3), 4)
    finally:
        for t in tps:
            t.close()
    # every rank converged on the grown map: ONE flip, live [0,1,2,3]
    for r in range(4):
        omap = sups[r].ds.ownership
        assert omap is not None and omap.epoch == 1
        assert list(omap.live_ranks) == [0, 1, 2, 3]
        assert "rank_join" in [i.kind for i in sups[r].incidents]
    # the joiner was admitted at a boundary BEFORE the last pass and ran
    # the rest of the day in lockstep
    assert len(res[3]) >= 1 and all(o is not None for o in res[3])
    assert all(len(res[r]) == passes for r in range(3))
    assert STAT_GET("membership.joins_total") >= joins_before + 4
    assert STAT_GET("membership.live_ranks") == 4
    assert STAT_GET("membership.epoch") == 1
    # the joiner's chain re-anchored at the join epoch, carries the grown
    # live set, and validates as a single-epoch chain
    wm = read_watermark(rank_root(root, 3))
    assert wm["ownership_epoch"] == 1
    assert wm["live_ranks"] == [0, 1, 2, 3]
    validate_watermark(wm)
    # rank_join incident bundle on every rank: joiner + planned ranges
    for r in range(4):
        joins = [i for i in sups[r].incidents if i.kind == "rank_join"]
        assert "joiner=3" in joins[-1].detail

    # the reference: a FRESH 4-rank run of the same day
    rec_f = {}
    sups_f, res_f = _run_day(4, str(tmp_path / "fresh"), seed, rec_f,
                             passes=passes)
    assert all(len(r) == passes for r in res_f)
    jk, jv = _merged_digest(sups, [0, 1, 2, 3])
    fk, fv = _merged_digest(sups_f, [0, 1, 2, 3])
    np.testing.assert_array_equal(jk, fk)
    np.testing.assert_array_equal(jv, fv)
    # per-pass global AUC bitwise-equal (the pre-join passes ran on 3
    # ranks, but the global record multiset per pass is membership-
    # independent by construction)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_j, p), _pass_auc(rec_f, p))


def test_kill_then_rejoin_bitwise_equals_fresh_run(tmp_path):
    """The full elastic cycle in one day: rank 1 dies mid-pass-1 (shrink,
    epoch 1), its replacement incarnation rejoins once the shrunk fleet
    is past the death (grow, epoch 2), and the day's final state is still
    bitwise a fresh fixed-size 4-rank run of the same schedule."""
    seed, passes = 31, 5
    root = str(tmp_path / "rejoin")
    config.set_flag("transport_peer_dead_s", 0.6)
    eps = [f"127.0.0.1:{p}" for p in _free_ports(4)]
    tps = [TcpTransport(r, eps, timeout=30.0) for r in range(4)]
    rec_e = {}
    sups = [
        _mk_sup(r, tps, root, seed, rec_e, kill_at=1 if r == 1 else None)
        for r in range(4)
    ]
    files = [[f"pass-{p}"] for p in range(passes)]

    def worker(r):
        if r != 1:
            return sups[r].run_day(DATE, files)
        try:
            sups[1].run_day(DATE, files)
            raise AssertionError("rank 1 was not killed")
        except _RankKilled:
            pass
        # wait for every survivor to INSTALL the shrink (ownership epoch 1)
        # before announcing: a fresh incarnation's heartbeats would
        # otherwise mask the OLD incarnation's silence from the failure
        # detector, and this is the earliest safe announce point — gating
        # any later (e.g. on a pass count) risks the fleet finishing the
        # day before the join lands
        deadline = time.monotonic() + 60.0
        while not all(
            sups[r].ds.ownership is not None and sups[r].ds.ownership.epoch >= 1
            for r in (0, 2, 3)
        ):
            if time.monotonic() >= deadline:
                raise AssertionError("survivors never installed the shrink")
            time.sleep(0.02)
        tps[1] = TcpTransport(1, eps, timeout=30.0)
        sup2 = _mk_sup(1, tps, root, seed, rec_e)
        sups[1] = sup2
        return sup2.join_day(files, timeout=60.0)

    try:
        res = _run_ranks(worker, 4)
    finally:
        config.set_flag("transport_peer_dead_s", 60.0)
        for t in tps:
            t.close()
    # shrink then grow: epoch 2, the full live set restored
    for r in range(4):
        omap = sups[r].ds.ownership
        assert omap is not None and omap.epoch == 2, r
        assert list(omap.live_ranks) == [0, 1, 2, 3]
    for r in (0, 2, 3):
        kinds = [i.kind for i in sups[r].incidents]
        assert "rank_death" in kinds and "rank_join" in kinds
        assert len(res[r]) == passes and all(o is not None for o in res[r])
    assert "rank_join" in [i.kind for i in sups[1].incidents]
    # the rejoined rank trained at least the final pass
    assert len(res[1]) >= 1 and all(o is not None for o in res[1])
    wm = read_watermark(rank_root(root, 1))
    assert wm["ownership_epoch"] == 2
    assert wm["live_ranks"] == [0, 1, 2, 3]
    validate_watermark(wm)

    rec_f = {}
    sups_f, res_f = _run_day(4, str(tmp_path / "fresh"), seed, rec_f,
                             passes=passes)
    assert all(len(r) == passes for r in res_f)
    ek, ev = _merged_digest(sups, [0, 1, 2, 3])
    fk, fv = _merged_digest(sups_f, [0, 1, 2, 3])
    np.testing.assert_array_equal(ek, fk)
    np.testing.assert_array_equal(ev, fv)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec_e, p), _pass_auc(rec_f, p))


# ---------------------------------------------------------------------------
# FLT008 for the two join fault sites
# ---------------------------------------------------------------------------


def test_join_catchup_fault_aborts_at_old_epoch_then_retry_commits(tmp_path):
    """FLT008 for membership.catchup_apply: a join aborted mid-catch-up
    leaves the fleet at the OLD epoch bitwise (receivers only staged,
    nothing committed), the joiner re-announces, and the RETRIED join at
    the next boundary succeeds — the day still lands bitwise on a fresh
    4-rank run."""
    seed, passes = 37, 3
    aborted_before = STAT_GET("membership.joins_aborted")
    root = str(tmp_path / "jfault")
    tps = _cluster(4)
    rec = {}
    sups = [
        _mk_sup(r, tps, root, seed, rec, initial_live=[0, 1, 2])
        for r in range(3)
    ]
    sups.append(_mk_sup(3, tps, root, seed, rec))
    files = [[f"pass-{p}"] for p in range(passes)]
    try:
        with inject(fail_nth("membership.catchup_apply", 1)) as plan:
            res = _run_ranks(_join_worker(sups, files, joiner=3), 4)
    finally:
        for t in tps:
            t.close()
    assert plan.failures("membership.catchup_apply") == 1
    assert STAT_GET("membership.joins_aborted") >= aborted_before + 4
    for r in range(4):
        kinds = [i.kind for i in sups[r].incidents]
        # the abort strictly precedes the committed retry; exactly ONE
        # flip ever happened (the aborted epoch never existed)
        assert kinds.index("join_abort") < kinds.index("rank_join"), (r, kinds)
        omap = sups[r].ds.ownership
        assert omap is not None and omap.epoch == 1
        assert list(omap.live_ranks) == [0, 1, 2, 3]
    assert all(len(res[r]) == passes for r in range(3))
    # satellite: the abort dumped an incident bundle — joiner rank, the
    # ranges it would have taken, the epoch that never happened, and why
    for r in range(4):
        paths = glob.glob(os.path.join(
            rank_root(root, r), "obs", "incidents", "incident-*.json",
        ))
        bundles = [json.load(open(p)) for p in paths]
        aborts = [b for b in bundles if b.get("reason") == "join_abort"]
        assert aborts, f"rank {r}: no join_abort incident bundle"
        detail = json.loads(aborts[-1]["detail"])
        assert detail["joiner"] == 3
        assert detail["ownership_epoch"] == 1
        assert detail["planned_ranges"]
        assert detail["reason"]

    rec_f = {}
    sups_f, res_f = _run_day(4, str(tmp_path / "fresh"), seed, rec_f,
                             passes=passes)
    assert all(len(r) == passes for r in res_f)
    jk, jv = _merged_digest(sups, [0, 1, 2, 3])
    fk, fv = _merged_digest(sups_f, [0, 1, 2, 3])
    np.testing.assert_array_equal(jk, fk)
    np.testing.assert_array_equal(jv, fv)
    for p in range(passes):
        np.testing.assert_array_equal(_pass_auc(rec, p), _pass_auc(rec_f, p))


def test_join_announce_fault_is_retried_and_join_lands(tmp_path):
    """FLT008 for membership.join_announce: a failed announce moved
    nothing durable — the joiner records the retryable fault and simply
    knocks again; the join still commits."""
    seed, passes = 41, 3
    root = str(tmp_path / "afault")
    tps = _cluster(4)
    rec = {}
    sups = [
        _mk_sup(r, tps, root, seed, rec, initial_live=[0, 1, 2])
        for r in range(3)
    ]
    sups.append(_mk_sup(3, tps, root, seed, rec))
    files = [[f"pass-{p}"] for p in range(passes)]
    try:
        with inject(fail_nth("membership.join_announce", 1)) as plan:
            res = _run_ranks(_join_worker(sups, files, joiner=3), 4)
    finally:
        for t in tps:
            t.close()
    assert plan.failures("membership.join_announce") == 1
    for r in range(4):
        omap = sups[r].ds.ownership
        assert omap is not None and omap.epoch == 1
        assert list(omap.live_ranks) == [0, 1, 2, 3]
        assert "rank_join" in [i.kind for i in sups[r].incidents]
    # the joiner noted the retryable announce fault before landing
    aborts = [i for i in sups[3].incidents if i.kind == "join_abort"]
    assert any("membership.join_announce" in a.detail for a in aborts)
    assert all(len(res[r]) == passes for r in range(3))


def test_autoscale_target_refuses_admission_at_target(tmp_path):
    """The autoscale policy half of the loop: at (or above) target_ranks
    a waiting joiner keeps knocking but is never admitted — the day ends
    at the ORIGINAL epoch and live set, and the joiner times out."""
    seed, passes = 43, 2
    root = str(tmp_path / "tgt")
    tps = _cluster(3)
    rec = {}
    sups = [
        _mk_sup(r, tps, root, seed, rec, initial_live=[0, 1],
                target_ranks=2)
        for r in range(2)
    ]
    sups.append(_mk_sup(2, tps, root, seed, rec))
    files = [[f"pass-{p}"] for p in range(passes)]

    def worker(r):
        if r == 2:
            with pytest.raises(PassFailure, match="not admitted"):
                sups[2].join_day(files, timeout=2.0)
            return "refused"
        return sups[r].run_day(DATE, files)

    try:
        res = _run_ranks(worker, 3)
    finally:
        for t in tps:
            t.close()
    assert res[2] == "refused"
    for r in (0, 1):
        assert len(res[r]) == passes and all(o is not None for o in res[r])
        omap = sups[r].ds.ownership
        assert omap is not None and omap.epoch == 0
        assert list(omap.live_ranks) == [0, 1]
        assert "rank_join" not in [i.kind for i in sups[r].incidents]
