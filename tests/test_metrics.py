"""AUC calculator tests (BasicAucCalculator parity checks)."""

import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.metrics.auc import auc_compute, auc_init, auc_update


def reference_auc(preds, labels):
    """O(n log n) exact AUC by rank statistic."""
    order = np.argsort(preds, kind="stable")
    ranks = np.empty(len(preds), dtype=np.float64)
    # average ranks for ties
    sp = np.asarray(preds)[order]
    i = 0
    r = 1
    while i < len(sp):
        j = i
        while j + 1 < len(sp) and sp[j + 1] == sp[i]:
            j += 1
        ranks[order[i : j + 1]] = (r + r + (j - i)) / 2.0
        r += j - i + 1
        i = j + 1
    labels = np.asarray(labels)
    n_pos = labels.sum()
    n_neg = len(labels) - n_pos
    return (ranks[labels == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_auc_matches_rank_statistic():
    rng = np.random.default_rng(0)
    preds = rng.uniform(size=2000).astype(np.float32)
    labels = (rng.uniform(size=2000) < preds).astype(np.float32)  # informative preds
    st = auc_init(100_000)
    st = auc_update(st, jnp.asarray(preds), jnp.asarray(labels))
    got = auc_compute(st)
    want = reference_auc(preds, labels)
    assert abs(got["auc"] - want) < 2e-3
    assert got["ins_num"] == 2000
    np.testing.assert_allclose(got["actual_ctr"], labels.mean(), rtol=1e-5)
    np.testing.assert_allclose(got["predicted_ctr"], preds.mean(), rtol=1e-4)


def test_auc_perfect_and_random():
    preds = jnp.array([0.1, 0.2, 0.8, 0.9])
    labels = jnp.array([0.0, 0.0, 1.0, 1.0])
    st = auc_update(auc_init(1000), preds, labels)
    assert auc_compute(st)["auc"] == 1.0
    st = auc_update(auc_init(1000), 1.0 - preds, labels)
    assert auc_compute(st)["auc"] == 0.0


def test_auc_mask_excludes_samples():
    preds = jnp.array([0.9, 0.1])
    labels = jnp.array([0.0, 1.0])  # terrible predictions...
    mask = jnp.array([0.0, 0.0])  # ...but masked out
    st = auc_update(auc_init(1000), preds, labels, mask)
    m = auc_compute(st)
    assert m["ins_num"] == 0
    assert m["auc"] == 0.5  # degenerate -> 0.5


def test_auc_accumulates_across_batches():
    rng = np.random.default_rng(1)
    preds = rng.uniform(size=512).astype(np.float32)
    labels = (rng.uniform(size=512) < 0.3).astype(np.float32)
    st = auc_init(10_000)
    for i in range(4):
        st = auc_update(st, jnp.asarray(preds[i::4]), jnp.asarray(labels[i::4]))
    whole = auc_update(auc_init(10_000), jnp.asarray(preds), jnp.asarray(labels))
    np.testing.assert_allclose(auc_compute(st)["auc"], auc_compute(whole)["auc"], rtol=1e-9)
