"""Direct units for the mesh placement helpers and the resident offset
representations — failures localize here instead of inside a 2-process
cluster e2e."""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.parallel.mesh import (
    make_mesh_2d,
    put_axis1_blocks,
    put_per_device_copies,
)

N = 4


def test_put_per_device_copies_layout():
    plan = make_mesh(N)
    arr = np.arange(12, dtype=np.int32).reshape(3, 4)
    g = put_per_device_copies(plan, arr)
    assert g.shape == (N, 3, 4)
    # every device's slice is this process's copy
    got = np.asarray(g)
    for d in range(N):
        np.testing.assert_array_equal(got[d], arr)
    # sharded on the device axis: each shard holds one row
    assert len(g.sharding.device_set) == N


def test_put_axis1_blocks_layout():
    plan = make_mesh(N)
    local = np.arange(2 * N * 3, dtype=np.int32).reshape(2, N, 3)
    g = put_axis1_blocks(plan, local)
    assert g.shape == (2, N, 3)
    np.testing.assert_array_equal(np.asarray(g), local)
    assert len(g.sharding.device_set) == N


def test_put_axis1_blocks_rejects_wrong_local_count():
    plan = make_mesh(N)
    ok = put_axis1_blocks(plan, np.zeros((2, N, 3), np.int32))
    assert ok.shape == (2, N, 3)
    # single-process accepts the full array only (local == global there)


def test_make_mesh_2d_validation():
    with pytest.raises(ValueError, match="n_pp"):
        make_mesh_2d(0, 2)
    plan = make_mesh_2d(2, 2)
    assert plan.axis == "dp"
    assert plan.mesh.shape["pp"] == 2 and plan.mesh.shape["dp"] == 2


def test_batch_offsets_compact_equals_full():
    """The uint8-counts representation rebuilds the exact offset matrix."""
    from paddlebox_tpu.train.resident_step import _batch_offsets

    rng = np.random.default_rng(0)
    n, S = 64, 7
    counts = rng.integers(0, 5, (n, S)).astype(np.int64)
    base = np.concatenate([[0], np.cumsum(counts.sum(axis=1))[:-1]])
    off = base[:, None] + np.concatenate(
        [np.zeros((n, 1), np.int64), np.cumsum(counts, axis=1)], axis=1
    )
    idx = jnp.asarray(rng.permutation(n)[:16].astype(np.int32))
    full = _batch_offsets({"off": jnp.asarray(off.astype(np.int32))}, idx)
    compact = _batch_offsets(
        {
            "off": None,
            "base": jnp.asarray(base.astype(np.int32)),
            "counts": jnp.asarray(counts.astype(np.uint8)),
        },
        idx,
    )
    np.testing.assert_array_equal(np.asarray(full), np.asarray(compact))
