"""Day-loop integration soak: many passes x carried boundaries x delta
saves x day-level resume — the operational flow a production deployment
runs, with every round-4 fast path active.

The carrier defers host writeback; delta/base saves must drain it
(HostSparseTable.drain_pending) so published checkpoints always contain
device-carried training. This pins the whole interplay: N passes of
carried boundaries, a delta save per pass, base save at day start, then a
fresh-process resume that must reproduce the live state exactly and keep
training.
"""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import CheckpointManager, CTRTrainer, TrainStepConfig

S, B = 4, 16
OPT = SparseOptimizerConfig(
    embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
)


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def _write(path, seed, lo, hi, n=64):
    rng = np.random.default_rng(seed)
    path.parent.mkdir(parents=True, exist_ok=True)
    # fixture writer: path derives from tmp_path (helper param hides it)
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        for _ in range(n):
            parts = [f"1 {float(rng.integers(0, 2))}"]
            for _s in range(S):
                k = int(rng.integers(1, 3))
                parts.append(
                    f"{k} " + " ".join(str(v) for v in rng.integers(lo, hi, k))
                )
            f.write(" ".join(parts) + "\n")
    return str(path)


def _build(layout):
    table = HostSparseTable(layout, OPT, n_shards=2, seed=0)
    ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=layout, sparse_opt=OPT,
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    return table, ds, tr


def _run_days(tmp_path, carried: bool):
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1 if carried else 0)
    try:
        layout = ValueLayout(embedx_dim=4)
        table, ds, tr = _build(layout)
        root = str(tmp_path / f"ckpt{int(carried)}")
        cm = CheckpointManager(root)
        losses = []
        seed = 0
        for day_i, date in enumerate(["20260101", "20260102"]):
            for p in range(3):
                # overlapping key windows slide across passes
                lo = 1 + 40 * (day_i * 3 + p)
                f = _write(
                    tmp_path / f"c{int(carried)}" / f"{date}-{p}.txt",
                    seed, lo, lo + 160,
                )
                seed += 1
                ds.set_date(date)
                ds.set_filelist([f])
                ds.load_into_memory()
                ds.begin_pass(round_to=8)
                out = tr.train_pass(ds)
                losses.append(out["loss"])
                ds.end_pass(
                    tr.trained_table_device() if carried else tr.trained_table()
                )
                if p == 0:
                    cm.save_base(date, table, tr)  # drains via save paths
                else:
                    cm.save_delta(date, table, tr)
        table.drain_pending()
        keys = np.sort(table.keys())
        return root, table, tr, keys, table.pull_or_create(keys), losses
    finally:
        config.set_flag("enable_carried_table", prev)


def test_day_loop_carried_equals_classic(tmp_path):
    _, _, _, k_c, v_c, l_c = _run_days(tmp_path / "classic", carried=False)
    _, _, _, k_d, v_d, l_d = _run_days(tmp_path / "carried", carried=True)
    np.testing.assert_array_equal(k_d, k_c)
    np.testing.assert_allclose(l_d, l_c, atol=1e-5)
    np.testing.assert_allclose(v_d, v_c, atol=1e-4)


def test_save_concurrent_with_async_end_pass(tmp_path):
    """A save racing an in-flight end_pass_async worker (carried pass:
    drain + decay + epoch stamp all in play) must produce a checkpoint
    whose rows and epoch stamp AGREE — resuming it equals a quiesced
    save's result up to the decays the stamp declares."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        layout = ValueLayout(embedx_dim=4)
        table, ds, tr = _build(layout)
        for trial in range(3):
            f = _write(tmp_path / f"p{trial}.txt", trial, 1 + 30 * trial, 300)
            ds.set_date("20260101")
            ds.set_filelist([f])
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            tr.train_pass(ds)
            ds.end_pass_async(tr.trained_table_device())
            # immediately save while the worker may still be draining or
            # decaying — the maintenance lock must serialize them
            base = str(tmp_path / f"base{trial}")
            table.save_base(base)
            ds.wait_end_pass()
            fresh = HostSparseTable(layout, OPT, n_shards=2, seed=7)
            fresh.load(base)
            keys = np.sort(fresh.keys())
            got = fresh.pull_or_create(keys)
            # reference: live table now (post-worker), un-decayed back to
            # the save's stamp
            live = table.pull_or_create(keys)
            missed = table.decay_epochs - fresh.decay_epochs
            assert missed in (0, 1)  # the save landed before or after decay
            ref = live.copy()
            if missed:
                ref[:, layout.SHOW] /= OPT.show_clk_decay
                ref[:, layout.CLK] /= OPT.show_clk_decay
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    finally:
        config.set_flag("enable_carried_table", prev)


def test_decay_epoch_lineage(tmp_path):
    """Checkpoint decay-epoch semantics: a base load ADOPTS the file's
    lineage; later deltas catch existing rows up by exactly the decays
    they lived through; stale/foreign stamps never crush counters."""
    layout = ValueLayout(embedx_dim=2)
    t = HostSparseTable(layout, OPT, n_shards=2, seed=0)
    keys = np.arange(1, 101, dtype=np.uint64)
    vals = np.ones((100, layout.width), np.float32)
    vals[:, layout.SHOW] = 10.0
    t.push(keys, vals)
    t.decay_and_shrink()  # epoch 1
    base = str(tmp_path / "base")
    t.save_base(base)
    # two more boundaries decay every host row; a delta then publishes
    # only a TOUCHED subset
    t.decay_and_shrink()
    t.decay_and_shrink()  # epoch 3
    sub = keys[:20]
    sv = t.pull_or_create(sub)
    t.push(sub, sv)
    delta = str(tmp_path / "delta")
    t.save_delta(delta)

    fresh = HostSparseTable(layout, OPT, n_shards=2, seed=1)
    fresh.load(base)
    assert fresh.decay_epochs == 1  # adopted the base lineage
    fresh.apply_delta(delta)
    assert fresh.decay_epochs == 3
    got = fresh.pull_or_create(keys)
    want = t.pull_or_create(keys)
    # every row — including the 80 untouched since the base — matches the
    # live table (catch-up applied the two inter-save decays)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_day_loop_resume_after_carried_saves(tmp_path):
    """A fresh process resuming from checkpoints published DURING carried
    passes sees the drained (complete) state and keeps training."""
    root, table, tr, keys, vals, _ = _run_days(tmp_path, carried=True)
    layout = ValueLayout(embedx_dim=4)
    table2, ds2, tr2 = _build(layout)
    cur = CheckpointManager(root).resume(table2, tr2)
    assert cur is not None and cur["date"] == "20260102"
    np.testing.assert_allclose(
        table2.pull_or_create(keys), vals, rtol=1e-6, atol=1e-6
    )
    for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(tr2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )
    # the resumed stack trains a further carried pass
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        f = _write(tmp_path / "next.txt", 99, 1, 200)
        ds2.set_date("20260103")
        ds2.set_filelist([f])
        ds2.load_into_memory()
        ds2.begin_pass(round_to=8)
        out = tr2.train_pass(ds2)
        assert np.isfinite(out["loss"])
        ds2.end_pass(tr2.trained_table_device())
        table2.drain_pending()
    finally:
        config.set_flag("enable_carried_table", prev)