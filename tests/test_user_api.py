"""User-API tier: DataGenerator protocol, CheckpointManager day resume,
BoxWrapper façade, model zoo (WideDeep/DCN/MMoE) trainability."""

import io
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu import BoxWrapper
from paddlebox_tpu.data import (
    BoxPSDataset,
    MultiSlotDataGenerator,
    SlotInfo,
    SlotSchema,
)
from paddlebox_tpu.models import DCN, MMoE, WideDeep, task_head
from paddlebox_tpu.table import HostSparseTable, SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import CheckpointManager, CTRTrainer, TrainStepConfig

NUM_SLOTS = 4
LAYOUT = ValueLayout(embedx_dim=8)
OPT = SparseOptimizerConfig(
    embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.01,
    show_clk_decay=1.0, shrink_threshold=0.0,
)


def make_schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NUM_SLOTS)],
        label_slot="label",
    )


# ---- data generator -----------------------------------------------------

class MyGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def it():
            if line is None:
                return
            toks = line.split(",")
            yield [("label", [float(toks[0])])] + [
                (f"s{i}", [int(t)]) for i, t in enumerate(toks[1:])
            ]

        return it


def test_data_generator_pipe_protocol(tmp_path):
    """Raw csv -> generator -> slot protocol -> parse_line round trip."""
    gen = MyGen()
    raw = io.StringIO("1.0,7,8,9,10\n0.0,11,12,13,14\n")
    out = io.StringIO()
    n = gen.run_from_stdin(stdin=raw, stdout=out)
    assert n == 2
    lines = out.getvalue().strip().split("\n")
    assert lines[0] == "1 1.0 1 7 1 8 1 9 1 10"

    from paddlebox_tpu.data.parser import parse_line

    schema = make_schema()
    rec = parse_line(lines[0], schema)
    assert rec.slot_floats(0)[0] == 1.0
    assert list(rec.slot_keys(0)) == [7]

    # protocol violations raise
    bad = MyGen()
    with pytest.raises(ValueError, match="no values"):
        bad._gen_str([("label", [])])
    good = MyGen()
    good._gen_str([("a", [1]), ("b", [2])])
    with pytest.raises(ValueError, match="slots"):
        good._gen_str([("a", [1])])
    with pytest.raises(ValueError, match="order"):
        good._gen_str([("b", [1]), ("a", [2])])
    with pytest.raises(ValueError, match="float"):
        good._gen_str([("a", [1.5]), ("b", [2])])


# ---- checkpoint manager -------------------------------------------------

def _write_day(tmp, rng, name, n=128):
    key_w = rng.normal(size=60) * 1.5
    lines = []
    for _ in range(n):
        ks = rng.integers(1, 55, NUM_SLOTS)
        lab = 1.0 if key_w[ks].sum() + rng.normal() * 0.3 > 0 else 0.0
        lines.append(f"1 {lab:.1f} " + " ".join(f"1 {k}" for k in ks))
    p = os.path.join(tmp, name)
    # fixture writer: tmp is the caller's tmp_path
    # pbox-lint: disable=IO004
    open(p, "w").write("\n".join(lines) + "\n")
    return p


def test_checkpoint_day_resume(tmp_path):
    schema = make_schema()
    rng = np.random.default_rng(9)
    f1 = _write_day(str(tmp_path), rng, "d1.txt")
    f2 = _write_day(str(tmp_path), rng, "d2.txt")

    def build():
        table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
        ds = BoxPSDataset(schema, table, batch_size=32, read_threads=1)
        from paddlebox_tpu.models import DeepFM

        model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                       embedx_dim=8, hidden=(16,))
        cfg = TrainStepConfig(num_slots=NUM_SLOTS, batch_size=32, layout=LAYOUT,
                              sparse_opt=OPT, auc_buckets=1000)
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        return table, ds, tr

    root = str(tmp_path / "ckpt")
    table, ds, tr = build()
    cm = CheckpointManager(root)
    assert cm.resume(table, tr) is None  # cold start

    def run_pass(ds, tr, f, date):
        ds.set_date(date)
        ds.set_filelist([f])
        ds.load_into_memory()
        ds.begin_pass(round_to=32)
        tr.train_pass(ds)
        ds.end_pass(tr.trained_table(), shrink=False)

    run_pass(ds, tr, f1, "20260101")
    cm.save_base("20260101", table, tr)
    run_pass(ds, tr, f2, "20260101")
    cm.save_delta("20260101", table, tr)

    # delta without base for a new date is rejected
    with pytest.raises(RuntimeError, match="base"):
        cm.save_delta("20260102", table, tr)

    # fresh process: resume == original state
    table2, ds2, tr2 = build()
    cur = CheckpointManager(root).resume(table2, tr2)
    assert cur["date"] == "20260101" and cur["delta_idx"] == 1
    assert cur["dense"] == "dense-0001.npz"  # per-save dense, no skew window
    keys = np.sort(table.keys())[:200]
    np.testing.assert_allclose(
        table2.pull_or_create(keys), table.pull_or_create(keys), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(tr2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # resumed trainer continues training
    run_pass(ds2, tr2, f2, "20260102")


# ---- boxps façade -------------------------------------------------------

def test_boxwrapper_facade(tmp_path):
    box = BoxWrapper(embedx_dim=8, sparse_opt=OPT, n_host_shards=4)
    assert box.phase == 1
    assert box.flip_phase() == 0 and box.flip_phase() == 1
    box.set_test_mode()
    assert box.test_mode

    schema = make_schema()
    rng = np.random.default_rng(3)
    f = _write_day(str(tmp_path), rng, "d.txt", n=64)
    ds = box.make_dataset(schema, batch_size=32, read_threads=1)
    assert ds.table is box.table
    ds.set_date("20260101")
    ds.set_filelist([f])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)

    box.init_metric("join_auc", phase=1)
    preds = jnp.asarray(rng.uniform(size=64).astype(np.float32))
    labels = (preds > 0.5).astype(jnp.float32)  # perfectly separable
    box.metrics.add_all({"preds": preds, "labels": labels}, phase=1)
    # get_metric_msg reads AND resets (GetMetricMsg contract)
    msg = box.get_metric_msg("join_auc")
    assert "AUC=1.0" in msg, msg
    assert box.get_metric("join_auc")["ins_num"] == 0  # reset happened

    ds.end_pass(None, shrink=False)
    box.save_base(str(tmp_path / "m"), "20260101")
    box2 = BoxWrapper(embedx_dim=8, sparse_opt=OPT, n_host_shards=4)
    assert box2.load_model(str(tmp_path / "m"))["date"] == "20260101"
    assert len(box2.table) == len(box.table)


# ---- model zoo ----------------------------------------------------------

@pytest.mark.parametrize("model_fn", [
    lambda: WideDeep(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width, hidden=(16,)),
    lambda: DCN(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width, n_cross=2, hidden=(16,)),
    lambda: task_head(MMoE(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                           n_experts=3, n_tasks=2, expert_hidden=(16,),
                           tower_hidden=(8,)), task=0),
])
def test_model_zoo_trains(model_fn, tmp_path):
    from test_train_step import synth_records
    from paddlebox_tpu.data.device_pack import pack_batch
    from paddlebox_tpu.data.slot_record import build_batch
    from paddlebox_tpu.table import PassWorkingSet
    from paddlebox_tpu.train.train_step import (
        init_train_state,
        jit_train_step,
        make_train_step,
    )

    schema = make_schema()
    rng = np.random.default_rng(1)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    recs = synth_records(rng, 32 * 6, schema)
    ws = PassWorkingSet()
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)

    model = model_fn()
    cfg = TrainStepConfig(num_slots=NUM_SLOTS, batch_size=32, layout=LAYOUT,
                          sparse_opt=OPT, auc_buckets=1000)
    opt = optax.adam(1e-2)
    step = jit_train_step(make_train_step(model.apply, opt, cfg))
    st = init_train_state(jnp.asarray(dev.reshape(-1, LAYOUT.width)),
                          model.init(jax.random.PRNGKey(0)), opt, 1000)
    losses = []
    for i in range(30):
        br = [recs[(i * 32 + j) % len(recs)] for j in range(32)]
        db = pack_batch(build_batch(br, schema), ws, schema, bucket=64)
        st, m = step(st, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.8 * losses[0], losses[::10]


def test_data_generator_numpy_floats_and_precision():
    g = MultiSlotDataGenerator()
    line = g._gen_str([("label", [np.float32(0.5)]), ("w", [0.12345678])])
    toks = line.split()
    assert toks[1] == "0.5" and float(toks[3]) == 0.12345678


def test_zero_checkpoint_fresh_process_resume(tmp_path):
    """Train with ZeRO, checkpoint, restore into a fresh trainer."""
    from paddlebox_tpu.fleet import Zero1Optimizer
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from test_train_step import synth_records
    from paddlebox_tpu.table import PassWorkingSet

    schema = make_schema()
    N_DEV = 8
    plan = make_mesh(N_DEV)
    rng = np.random.default_rng(13)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    recs = synth_records(rng, 64 * 2, schema)
    ws = PassWorkingSet(n_mesh_shards=N_DEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)

    def build():
        model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                       embedx_dim=8, hidden=(16,))
        zero = Zero1Optimizer(optax.adam(1e-2), axis_name=plan.axis, n_dev=N_DEV)
        cfg = TrainStepConfig(num_slots=NUM_SLOTS, batch_size=64 // N_DEV,
                              layout=LAYOUT, sparse_opt=OPT, auc_buckets=1000,
                              axis_name=plan.axis)
        return CTRTrainer(model, cfg, dense_opt=zero, plan=plan)

    from paddlebox_tpu.data.device_pack import pack_batch_sharded
    from paddlebox_tpu.data.slot_record import build_batch

    tr = build()
    tr.init_params()
    # one manual sharded pass to populate zero state
    st = tr._make_state(dev)
    db = pack_batch_sharded(build_batch(recs[:64], schema), ws, schema, N_DEV, bucket=32)
    feed = {k: jax.device_put(v, plan.batch_sharding) for k, v in db.as_dict().items()}
    st, _ = tr._step(st, feed)
    tr.params, tr.opt_state = st.params, st.opt_state
    tr.save_dense(str(tmp_path / "dense"))

    tr2 = build()
    tr2.init_params()
    assert tr2.opt_state is None
    tr2.load_dense(str(tmp_path / "dense"))  # rebuilds zero state, loads
    for a, b in zip(jax.tree.leaves(tr.opt_state), jax.tree.leaves(tr2.opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(tr2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
