"""tools/proto_check.py contract: the clean membership-protocol model
explores to a fixpoint with zero invariant violations; every deliberately
broken variant is caught on exactly the invariant it breaks; and the
model's tag vocabulary is pinned as a subset of what analysis/protocol.py
extracts from the real package — so the model cannot silently drift away
from the code it claims to verify."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO_CHECK = os.path.join(REPO, "tools", "proto_check.py")


def _load_proto_check():
    spec = importlib.util.spec_from_file_location("pbox_proto_check", PROTO_CHECK)
    mod = importlib.util.module_from_spec(spec)
    # dataclass field resolution looks the module up by name
    sys.modules["pbox_proto_check"] = mod
    spec.loader.exec_module(mod)
    return mod


pc = _load_proto_check()


# ---- clean model ------------------------------------------------------------


def test_clean_model_reaches_fixpoint_with_no_violations():
    res = pc.Checker(ranks=3, deaths=1, joins=0, nos=1, max_epochs=2).run()
    assert res.complete, "state budget must not truncate the bounded model"
    assert res.ok, res.violations
    # the bounds are non-trivial: deaths and no-votes interleave with
    # votes and per-recipient deliveries
    assert res.states > 1_000
    assert res.transitions > res.states


def test_clean_join_path_is_safe():
    res = pc.Checker(ranks=3, deaths=1, joins=1, nos=1, max_epochs=2).run()
    assert res.complete and res.ok, res.violations


def test_budget_exhaustion_is_reported_not_hidden():
    res = pc.Checker(ranks=3, deaths=1, joins=1, nos=1, max_epochs=3,
                     max_states=200).run()
    assert not res.complete
    assert res.states <= 200


# ---- broken variants --------------------------------------------------------


@pytest.mark.parametrize("name", sorted(pc.BROKEN))
def test_broken_variant_trips_exactly_its_invariant(name):
    inv, _desc, bounds = pc.BROKEN[name]
    res = pc.Checker(broken=name, **bounds).run()
    assert res.violations, f"{name} must be caught"
    assert {v["invariant"] for v in res.violations} == {inv}


def test_every_invariant_has_a_broken_witness():
    covered = {pc.BROKEN[n][0] for n in pc.BROKEN}
    assert covered == set(pc.INVARIANTS)


# ---- model vocabulary pinned to the real extraction -------------------------


@pytest.fixture(scope="module")
def real_model():
    from paddlebox_tpu.analysis import extract_protocol
    from paddlebox_tpu.analysis.core import ModuleCtx, iter_py_files

    # package only: scanning tools/ would let proto_check.py's own
    # MODEL_TAGS literals satisfy the pin trivially
    mods = []
    for p in iter_py_files([os.path.join(REPO, "paddlebox_tpu")]):
        rel = os.path.relpath(p, REPO).replace(os.sep, "/")
        mods.append(ModuleCtx.parse(p, rel))
    return extract_protocol(mods)


@pytest.mark.parametrize("transition", sorted(pc.MODEL_TAGS))
def test_model_tags_are_subset_of_extraction(transition, real_model):
    tag = pc.MODEL_TAGS[transition]
    if tag.endswith(":"):
        # a tag-family prefix: some real site must mint tags under it
        pats = real_model.tag_patterns() | {
            s.pattern for s in real_model.literal_tags
        }
        assert any(p.startswith(tag) for p in pats), (
            f"model transition {transition!r} abstracts tag family "
            f"{tag!r}, but no site in the package mints it"
        )
    else:
        assert real_model.covers_tag(tag), (
            f"model transition {transition!r} abstracts tag {tag!r}, "
            f"but the extraction does not know it"
        )


# ---- CLI contract -----------------------------------------------------------


def run_cli(*args):
    return subprocess.run(
        [sys.executable, PROTO_CHECK, *args],
        capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_codes_and_json():
    r = run_cli("--deaths", "0", "--joins", "0", "--nos", "0",
                "--max-epochs", "1", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    d = json.loads(r.stdout)
    assert d["complete"] and d["violations"] == [] and d["states"] > 0

    r = run_cli("--broken", "double_owner")
    assert r.returncode == 1
    assert "VIOLATION I2" in r.stdout

    r = run_cli("--deaths", "1", "--joins", "1", "--max-states", "50")
    assert r.returncode == 2
    assert "budget exhausted" in r.stdout

    r = run_cli("--list-broken")
    assert r.returncode == 0
    for name in pc.BROKEN:
        assert name in r.stdout
