"""Native C++ slot parser: exact parity with the Python parser + speed.

The Python parser (data/parser.py) is the semantics oracle; the native tier
must agree record-for-record on every field, including logkey decoding,
zero dropping, unused-slot skipping, and skip-record rules.
"""

import time

import numpy as np
import pytest

from paddlebox_tpu.data import SlotInfo, SlotSchema
from paddlebox_tpu.data.parser import parse_line
from paddlebox_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native parser lib unavailable"
)


def gen_lines(rng, n, with_logkey=False, n_sparse=5, zero_rate=0.1):
    lines = []
    for i in range(n):
        parts = []
        if with_logkey:
            sid = int(rng.integers(0, 1 << 32))
            logkey = "0" * 11 + f"{int(rng.integers(0, 4095)):03x}" + f"{int(rng.integers(0, 255)):02x}" + f"{sid:016x}"
            parts.append(f"1 {logkey}")
        parts.append(f"1 {rng.uniform(0, 1):.4f}")  # label float
        for s in range(n_sparse):
            cnt = int(rng.integers(1, 4))
            vals = [
                0 if rng.uniform() < zero_rate else int(rng.integers(1, 10**12))
                for _ in range(cnt)
            ]
            parts.append(f"{cnt} " + " ".join(map(str, vals)))
        lines.append(" ".join(parts))
    return lines


def schema_of(with_logkey, n_sparse=5, unused=()):
    slots = [SlotInfo("label", type="float", dense=True, dim=1)]
    for i in range(n_sparse):
        slots.append(SlotInfo(f"s{i}", used=i not in unused))
    return SlotSchema(slots, label_slot="label", parse_logkey=with_logkey)


def assert_records_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        np.testing.assert_array_equal(ra.u64_values, rb.u64_values)
        np.testing.assert_array_equal(ra.u64_offsets, rb.u64_offsets)
        np.testing.assert_allclose(ra.f_values, rb.f_values, rtol=1e-6)
        np.testing.assert_array_equal(ra.f_offsets, rb.f_offsets)
        assert ra.search_id == rb.search_id
        assert ra.cmatch == rb.cmatch and ra.rank == rb.rank
        assert ra.ins_id == rb.ins_id


@pytest.mark.parametrize("with_logkey", [False, True])
@pytest.mark.parametrize("unused", [(), (1, 3)])
def test_native_matches_python(with_logkey, unused):
    rng = np.random.default_rng(0)
    schema = schema_of(with_logkey, unused=unused)
    lines = gen_lines(rng, 200, with_logkey)
    want = [r for r in (parse_line(l, schema) for l in lines) if r is not None]
    buf = ("\n".join(lines) + "\n").encode()
    got = native.parse_buffer(buf, schema)
    assert_records_equal(got, want)


def test_native_skips_all_zero_records():
    schema = schema_of(False, n_sparse=2)
    buf = b"1 0.5 1 0 1 0\n1 0.5 1 7 1 8\n"
    stats = {}
    recs = native.parse_buffer(buf, schema, stats)
    assert len(recs) == 1 and stats["skipped"] == 1
    assert list(recs[0].slot_keys(0)) == [7]


def test_native_error_diagnostics():
    schema = schema_of(False, n_sparse=2)
    with pytest.raises(ValueError, match="line 2.*zero-count"):
        native.parse_buffer(b"1 1.0 1 5 1 6\n1 1.0 0 1 6\n", schema)
    with pytest.raises(ValueError, match="truncated"):
        native.parse_buffer(b"1 1.0 2 5\n", schema)


def test_native_dataset_path_and_speed(tmp_path):
    """Dataset uses the native path by default; native is faster."""
    from paddlebox_tpu import config
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.table import HostSparseTable, SparseOptimizerConfig, ValueLayout

    rng = np.random.default_rng(1)
    schema = schema_of(False)
    lines = gen_lines(rng, 20000, False)
    p = tmp_path / "big.txt"
    p.write_text("\n".join(lines) + "\n")

    table = HostSparseTable(ValueLayout(embedx_dim=4), SparseOptimizerConfig(), n_shards=4)

    def load(native_on):
        config.set_flag("enable_native_parser", native_on)
        ds = BoxPSDataset(schema, table, batch_size=256, read_threads=1)
        ds.set_date("20260101")
        ds.set_filelist([str(p)])
        t0 = time.perf_counter()
        ds.load_into_memory()
        dt = time.perf_counter() - t0
        ds.begin_pass(round_to=64)
        recs = ds.records
        ds.end_pass(None, shrink=False)
        return recs, dt

    try:
        recs_n, dt_n = load(True)
        recs_p, dt_p = load(False)
    finally:
        config.set_flag("enable_native_parser", True)
    assert_records_equal(recs_n, recs_p)
    # native should beat the python line loop comfortably; allow jitter
    assert dt_n < dt_p, (dt_n, dt_p)
    print(f"native {dt_n * 1e3:.1f}ms vs python {dt_p * 1e3:.1f}ms "
          f"({dt_p / dt_n:.1f}x)")


def test_native_edge_parity():
    """Edge cases that must match the oracle exactly."""
    # |v| == 1e-6 is KEPT by the oracle (drops only abs(v) < 1e-6)
    schema = SlotSchema(
        [SlotInfo("f0", type="float"), SlotInfo("s0")], label_slot=None
    )
    buf = b"2 1e-6 1e-7 1 5\n"
    want = parse_line("2 1e-6 1e-7 1 5", schema)
    got = native.parse_buffer(buf, schema)
    assert_records_equal(got, [want])
    assert len(got[0].slot_floats(0)) == 1

    # short (17..31 char) logkeys decode like the oracle's slices
    schema_lk = schema_of(True, n_sparse=1)
    lk = "0" * 11 + "abc" + "1f" + "1234"  # 20 chars: search slice = '1234'
    line = f"1 {lk} 1 0.5 1 9"
    want = parse_line(line, schema_lk)
    got = native.parse_buffer((line + "\n").encode(), schema_lk)
    assert_records_equal(got, [want])
    assert got[0].search_id == 0x1234 and got[0].cmatch == 0xABC

    # NaN floats are KEPT (oracle's abs(v) < 1e-6 is False for NaN); the
    # downstream NaN guardrails own rejection, not the parser
    schema_nan = SlotSchema(
        [SlotInfo("f0", type="float"), SlotInfo("s0")], label_slot=None
    )
    want = parse_line("2 nan 0.5 1 5", schema_nan)
    got = native.parse_buffer(b"2 nan 0.5 1 5\n", schema_nan)
    assert len(want.f_values) == 2 and np.isnan(want.f_values[0])
    assert len(got[0].f_values) == 2 and np.isnan(got[0].f_values[0])
    np.testing.assert_array_equal(got[0].f_offsets, want.f_offsets)

    # non-hex chars in the logkey reject the parse (oracle: int(_,16) raises)
    schema_lk1 = schema_of(True, n_sparse=1)
    bad = "0" * 11 + "xyz" + "1f" + "1234"
    with pytest.raises(ValueError, match="hex"):
        native.parse_buffer(f"1 {bad} 1 0.5 1 9\n".encode(), schema_lk1)
    with pytest.raises(ValueError):
        parse_line(f"1 {bad} 1 0.5 1 9", schema_lk1)

    # ins_id + logkey: the logkey wins as ins_id (parser.py overwrite)
    slots = [SlotInfo("label", type="float", dense=True, dim=1), SlotInfo("s0")]
    schema_both = SlotSchema(slots, label_slot="label",
                             parse_ins_id=True, parse_logkey=True)
    lk32 = "0" * 11 + "001" + "02" + f"{77:016x}"
    line = f"1 myid 1 {lk32} 1 1.0 1 3"
    want = parse_line(line, schema_both)
    got = native.parse_buffer((line + "\n").encode(), schema_both)
    assert_records_equal(got, [want])
    assert got[0].ins_id == lk32
