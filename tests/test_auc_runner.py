"""AucRunner slot-shuffle tests (B15).

Model: the reference exercises AucRunner through BoxHelper::SlotsShuffle on
in-memory records (box_wrapper.h:961-985); here we check reservoir behavior,
exact replace/replace-back round-trip, phase flipping, and the end-to-end
dataset hook.
"""

import numpy as np
import pytest

from paddlebox_tpu.data import SlotInfo, SlotSchema
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.metrics import AucRunner, CandidatePool

NUM_SLOTS = 4


def make_schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NUM_SLOTS)],
        label_slot="label",
    )


def make_records(rng, n, max_len=3):
    recs = []
    for _ in range(n):
        lens = rng.integers(1, max_len + 1, NUM_SLOTS)
        off = np.zeros(NUM_SLOTS + 1, dtype=np.uint32)
        np.cumsum(lens, out=off[1:])
        recs.append(
            SlotRecord(
                u64_values=rng.integers(1, 1000, int(off[-1])).astype(np.uint64),
                u64_offsets=off,
                f_values=np.array([1.0], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
            )
        )
    return recs


def snapshot(recs):
    return [(r.u64_values.copy(), r.u64_offsets.copy()) for r in recs]


def test_reservoir_pool_bounds():
    rng = np.random.default_rng(0)
    pool = CandidatePool(capacity=10, rng=rng)
    ids = [pool.add_and_get({0: np.array([i], np.uint64)}) for i in range(500)]
    assert len(pool) == 10
    assert all(0 <= i < 10 for i in ids)
    # reservoir keeps a (statistically) late-biased-free sample: at least one
    # candidate from the back half of the stream should survive
    vals = [int(c[0][0]) for c in pool.candidates]
    assert max(vals) >= 250


def test_replace_and_replace_back_roundtrip():
    rng = np.random.default_rng(1)
    schema = make_schema()
    recs = make_records(rng, 40)
    before = snapshot(recs)
    runner = AucRunner(schema, replaced_slots=["s1", "s3"], capacity=8, seed=0)
    runner.observe(recs)

    stats = runner.slots_shuffle(recs, {"s1"})
    assert stats["deleted"] > 0 and stats["added"] > 0
    assert runner.phase == 0
    # untouched slots identical; shuffled slot lengths match the candidates
    changed = 0
    for r, (v, o) in zip(recs, before):
        for s in (0, 2, 3):
            lo, hi = r.u64_offsets[s], r.u64_offsets[s + 1]
            np.testing.assert_array_equal(r.u64_values[lo:hi], v[o[s] : o[s + 1]])
        lo, hi = r.u64_offsets[1], r.u64_offsets[2]
        if not np.array_equal(r.u64_values[lo:hi], v[o[1] : o[2]]):
            changed += 1
    assert changed > 0

    # shuffling s3 must restore s1 first (last_slots protocol)
    runner.slots_shuffle(recs, {"s3"})
    for r, (v, o) in zip(recs, before):
        lo, hi = r.u64_offsets[1], r.u64_offsets[2]
        np.testing.assert_array_equal(r.u64_values[lo:hi], v[o[1] : o[2]])

    # empty set = full restore
    runner.slots_shuffle(recs, set())
    after = snapshot(recs)
    for (v0, o0), (v1, o1) in zip(before, after):
        np.testing.assert_array_equal(v0, v1)
        np.testing.assert_array_equal(o0, o1)
    assert runner.phase == 0  # flipped 3 times from 1


def test_undeclared_slot_rejected():
    rng = np.random.default_rng(2)
    schema = make_schema()
    recs = make_records(rng, 5)
    runner = AucRunner(schema, replaced_slots=["s1"], capacity=4)
    runner.observe(recs)
    with pytest.raises(ValueError):
        runner.slots_shuffle(recs, {"s0"})
    with pytest.raises(RuntimeError):
        AucRunner(schema, replaced_slots=["s1"], capacity=4).slots_shuffle(recs, {"s1"})


def test_repeat_shuffle_same_slot_stats_balanced():
    """Re-shuffling the same slot must not double-count feasign stats and
    must still restore exactly."""
    rng = np.random.default_rng(7)
    schema = make_schema()
    recs = make_records(rng, 20)
    before = snapshot(recs)
    runner = AucRunner(schema, replaced_slots=["s1"], capacity=20, seed=0)
    runner.observe(recs)

    def total():
        return sum(len(r.u64_values) for r in recs)

    # invariant: per-call total-length delta == added - deleted
    for slots in ({"s1"}, {"s1"}, set()):
        n0 = total()
        st = runner.slots_shuffle(recs, slots)
        assert total() - n0 == st["added"] - st["deleted"]
    after = snapshot(recs)
    for (v0, _), (v1, _) in zip(before, after):
        np.testing.assert_array_equal(v0, v1)


def test_candidates_self_consistent():
    """Replaced values must come from the pool the record was assigned to."""
    rng = np.random.default_rng(3)
    schema = make_schema()
    recs = make_records(rng, 30)
    runner = AucRunner(schema, replaced_slots=["s2"], capacity=30, seed=1)
    runner.observe(recs)
    pool_vals = {tuple(c[2].tolist()) for c in runner.pools[0].candidates}
    runner.slots_shuffle(recs, {"s2"})
    for r in recs:
        assert tuple(r.slot_keys(2).tolist()) in pool_vals


def test_dataset_slots_shuffle_hook(tmp_path):
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )

    rng = np.random.default_rng(4)
    schema = make_schema()
    lines = []
    for _ in range(32):
        ks = rng.integers(1, 50, NUM_SLOTS)
        lines.append("1 1.0 " + " ".join(f"1 {k}" for k in ks))
    p = tmp_path / "part-000.txt"
    p.write_text("\n".join(lines) + "\n")

    table = HostSparseTable(ValueLayout(embedx_dim=4), SparseOptimizerConfig(), n_shards=4)
    ds = BoxPSDataset(schema, table, batch_size=8, read_threads=1)
    ds.set_date("20260101")
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    assert ds.auc_runner_phase == 1
    ds.slots_shuffle(["s0"])
    assert ds.auc_runner_phase == 0
    # every batch key must still resolve in the pass working set (candidates
    # come from the pass itself)
    for b in ds.batches():
        ds.ws.lookup(b.keys)
    ds.slots_shuffle([])
    assert ds.auc_runner_phase == 1
