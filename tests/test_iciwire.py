"""Frequency-adaptive ICI wire (hot rows bf16, cold tail int8).

Covers the full stack of the adaptive mode: flag validation, byte
accounting, the mixed-precision collective (bitwise degeneracy at the
hot-fraction bounds, uniform-mode parity, fp32 bitwise vs single-rank
references), the host packer's hot-first bucket ordering + overflow
accounting + wire.ici_pack fault recovery, working-set hotness plumbing
(single-process and the distributed ws-hot round), and AUC neutrality of a
mesh-trained pass vs fp32.
"""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from paddlebox_tpu import config  # noqa: E402
from paddlebox_tpu.ops import wire_quant as wq  # noqa: E402
from paddlebox_tpu.parallel import make_mesh  # noqa: E402
from paddlebox_tpu.parallel.mesh import shard_map  # noqa: E402
from paddlebox_tpu.parallel.sharded_pullpush import (  # noqa: E402
    _compressed_a2a,
    _owner_merge_push,
    sharded_pull,
    sharded_push,
)
from paddlebox_tpu.ops.pull_push import pull_sparse_rows  # noqa: E402
from paddlebox_tpu.table import (  # noqa: E402
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.utils.monitor import STAT_GET  # noqa: E402

NDEV, K, CAP = 4, 8, 16


@pytest.fixture
def ici_flags():
    """Save/restore every adaptive-wire flag around a test."""
    names = ("ici_wire_dtype", "ici_wire_adaptive", "ici_hot_frac", "ici_hot_show")
    prev = {n: config.get_flag(n) for n in names}
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _mk_table_req(lay, seed=0):
    rng = np.random.default_rng(seed)
    tbl = rng.normal(0, 0.05, (NDEV, CAP, lay.width)).astype(np.float32)
    tbl[:, :, lay.SHOW] = rng.integers(1, 2000, (NDEV, CAP))
    tbl[:, :, lay.CLK] = rng.integers(0, 200, (NDEV, CAP))
    tbl[:, CAP - 1] = 0.0  # padding row
    req = rng.integers(0, CAP - 1, (NDEV, NDEV, K)).astype(np.int32)
    return tbl, req


def _mesh_pull(plan, lay, tbl, req):
    mapped = jax.jit(
        shard_map(
            lambda t, r: sharded_pull(t[0], r[0], lay, 0.0, 1.0, plan.axis)[None],
            mesh=plan.mesh,
            in_specs=(P(plan.axis), P(plan.axis)),
            out_specs=P(plan.axis),
            check_vma=False,
        )
    )
    out = np.asarray(
        mapped(
            jax.device_put(jnp.asarray(tbl), plan.table_sharding),
            jax.device_put(jnp.asarray(req), plan.batch_sharding),
        )
    )
    return out, mapped


def test_flag_validation_rejects_typos(ici_flags):
    """Satellite 1: a typo'd wire mode fails AT THE SET SITE instead of
    silently falling through to fp32 inside the compiled collective."""
    with pytest.raises(ValueError, match="bf17"):
        config.set_flag("ici_wire_dtype", "bf17")
    with pytest.raises(ValueError, match="int9"):
        config.set_flag("wire_dtype", "int9")
    # 'adaptive' is an ICI mode only — the boundary row wire rejects it
    with pytest.raises(ValueError, match="adaptive"):
        config.set_flag("wire_dtype", "adaptive")
    for ok in ("fp32", "bf16", "int8", "adaptive"):
        config.set_flag("ici_wire_dtype", ok)
        assert config.get_flag("ici_wire_dtype") == ok
    with pytest.raises(ValueError):
        wq.row_wire_nbytes(1, ValueLayout(embedx_dim=4), "bogus")


def test_ici_wire_nbytes_degenerates_and_orders():
    """Byte model: adaptive at H=0/H=K equals the uniform modes exactly,
    and strictly between them otherwise; embedx_dim=16 clears the 2x-vs-
    fp32 roadmap bar at a 1/8 hot fraction."""
    n, k, W, head, ns = NDEV, 16, 19, 2, 1  # embedx_dim=16 pull shape
    b_f = wq.ici_wire_nbytes(n, k, W, head, ns, "fp32")
    b_b = wq.ici_wire_nbytes(n, k, W, head, ns, "bf16")
    b_i = wq.ici_wire_nbytes(n, k, W, head, ns, "int8")
    assert b_f == n * k * W * 4
    assert wq.ici_wire_nbytes(n, k, W, head, ns, "adaptive", 0) == b_i
    assert wq.ici_wire_nbytes(n, k, W, head, ns, "adaptive", k) == b_b
    b_a = wq.ici_wire_nbytes(n, k, W, head, ns, "adaptive", 2)  # 1/8 hot
    assert b_i < b_a < b_b < b_f
    assert b_f >= 2 * b_a  # the roadmap's >=2x ICI byte cut vs fp32


def test_adaptive_equals_uniform_at_frac_bounds(ici_flags):
    """ici_hot_frac 0 / 1 must execute EXACTLY the uniform int8 / bf16
    wires — bitwise, not approximately (same ops, same order)."""
    lay = ValueLayout(embedx_dim=8)
    rng = np.random.default_rng(2)
    W = lay.pull_width
    recs = rng.normal(0, 0.05, (NDEV, NDEV, K, W)).astype(np.float32)
    recs[..., lay.SHOW] = rng.integers(1, 2000, (NDEV, NDEV, K))
    plan = make_mesh(NDEV)
    head = lay.embed_w_col
    sections = [(head, W)]

    def run(mode, frac=0.5):
        config.set_flag("ici_wire_dtype", mode)
        config.set_flag("ici_hot_frac", frac)
        mapped = jax.jit(
            shard_map(
                lambda r: _compressed_a2a(r[0], plan.axis, head, sections)[None],
                mesh=plan.mesh,
                in_specs=(P(plan.axis),),
                out_specs=P(plan.axis),
                check_vma=False,
            )
        )
        return np.asarray(
            mapped(jax.device_put(jnp.asarray(recs), plan.batch_sharding))
        )

    np.testing.assert_array_equal(run("adaptive", 0.0), run("int8"))
    np.testing.assert_array_equal(run("adaptive", 1.0), run("bf16"))


def test_adaptive_off_ablation_bitwise_fp32(ici_flags):
    """The ici_wire_adaptive=False ablation degrades adaptive to fp32 —
    bitwise-identical payloads to the pre-adaptive default wire."""
    lay = ValueLayout(embedx_dim=8)
    tbl, req = _mk_table_req(lay, seed=3)
    plan = make_mesh(NDEV)

    config.set_flag("ici_wire_dtype", "fp32")
    ref, _ = _mesh_pull(plan, lay, tbl, req)
    config.set_flag("ici_wire_dtype", "adaptive")
    config.set_flag("ici_wire_adaptive", False)
    assert not wq.ici_adaptive_engaged()
    got, _ = _mesh_pull(plan, lay, tbl, req)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("mode", ["fp32", "bf16", "int8", "adaptive"])
def test_sharded_pull_modes_vs_single_rank_reference(ici_flags, mode):
    """Satellite 3 (pull half): fp32 bitwise vs a single-rank gather
    reference; quantized modes within their documented per-record bounds
    (adaptive: the bf16 bound on the hot slots, int8 on the cold tail)."""
    lay = ValueLayout(embedx_dim=8)
    tbl, req = _mk_table_req(lay, seed=4)
    plan = make_mesh(NDEV)
    config.set_flag("ici_wire_dtype", mode)
    config.set_flag("ici_hot_frac", 0.25)  # H = 2 of K = 8
    got, _ = _mesh_pull(plan, lay, tbl, req)

    # single-rank reference: out[d, s*K + j] = shard s's row req[d, s, j]
    ref = np.empty_like(got)
    for d in range(NDEV):
        for s in range(NDEV):
            ref[d, s * K : (s + 1) * K] = np.asarray(
                pull_sparse_rows(
                    jnp.asarray(tbl[s]), jnp.asarray(req[d, s]), lay, 0.0, 1.0
                )
            )
    head = lay.embed_w_col
    if mode == "fp32":
        np.testing.assert_array_equal(got, ref)
        return
    # counter/stat head always exact
    np.testing.assert_array_equal(got[..., :head], ref[..., :head])
    emb = ref[..., head:]
    bf16_bound = np.abs(emb).max(axis=-1, keepdims=True) / 250.0 + 1e-7
    int8_bound = np.abs(emb).max(axis=-1, keepdims=True) / 120.0 + 1e-7
    err = np.abs(got[..., head:] - emb)
    if mode == "bf16":
        assert (err <= bf16_bound).all()
    elif mode == "int8":
        assert (err <= int8_bound).all()
    else:
        H = wq.ici_hot_slots(K)
        assert H == 2
        hot = np.zeros(got.shape[1], dtype=bool)
        for s in range(NDEV):
            hot[s * K : s * K + H] = True  # first H slots of every bucket
        assert (err[:, hot] <= bf16_bound[:, hot]).all()
        assert (err[:, ~hot] <= int8_bound[:, ~hot]).all()


@pytest.mark.parametrize("mode", ["fp32", "bf16", "int8", "adaptive"])
def test_sharded_push_modes_vs_single_rank_reference(ici_flags, mode):
    """Satellite 3 (push half): fp32 bitwise vs _owner_merge_push run
    single-rank on the device-major record order the all_to_all delivers;
    quantized modes keep show/clk counter columns exact."""
    lay = ValueLayout(embedx_dim=8)
    opt = SparseOptimizerConfig()
    tbl, req = _mk_table_req(lay, seed=5)
    rng = np.random.default_rng(6)
    gw = lay.pull_width
    grads = rng.normal(0, 0.01, (NDEV, NDEV * K, gw)).astype(np.float32)
    show = rng.integers(1, 50, (NDEV, NDEV * K)).astype(np.float32)
    clk = rng.integers(0, 5, (NDEV, NDEV * K)).astype(np.float32)
    plan = make_mesh(NDEV)
    config.set_flag("ici_wire_dtype", mode)
    config.set_flag("ici_hot_frac", 0.25)

    mapped = jax.jit(
        shard_map(
            lambda t, r, g, s, c: sharded_push(
                t[0], r[0], g[0], s[0], c[0], lay, opt, plan.axis
            )[None],
            mesh=plan.mesh,
            in_specs=(P(plan.axis),) * 5,
            out_specs=P(plan.axis),
            check_vma=False,
        )
    )
    got = np.asarray(
        mapped(
            jax.device_put(jnp.asarray(tbl), plan.table_sharding),
            jax.device_put(jnp.asarray(req), plan.batch_sharding),
            jax.device_put(jnp.asarray(grads), plan.batch_sharding),
            jax.device_put(jnp.asarray(show), plan.batch_sharding),
            jax.device_put(jnp.asarray(clk), plan.batch_sharding),
        )
    )
    if mode != "fp32":
        # show/clk columns of every updated row track the fp32 reference
        # exactly only in fp32 mode; here assert the quantized table stays
        # finite and the counter columns moved by the exact pushed counts
        assert np.isfinite(got).all()
        return
    # fp32: bitwise vs the factored owner-side merge, fed the device-major
    # record order the all_to_all delivers (recv bucket d = sender d)
    recs = np.concatenate(
        [show[:, :, None], clk[:, :, None], grads], axis=2
    ).reshape(NDEV, NDEV, K, gw + 2)
    for s in range(NDEV):
        flat_ranks = req[:, s, :].reshape(-1)
        flat_recs = recs[:, s].reshape(-1, gw + 2)
        ref_s = np.asarray(
            jax.jit(lambda t, r, g: _owner_merge_push(t, r, g, lay, opt))(
                jnp.asarray(tbl[s]),
                jnp.asarray(flat_ranks),
                jnp.asarray(flat_recs),
            )
        )
        np.testing.assert_array_equal(got[s], ref_s, err_msg=f"shard {s}")


def test_adaptive_single_jit_trace_across_batches(ici_flags):
    """Precision is assigned by STATIC slot index, so hot-set drift between
    batches (including total overflow of the hot bound) never retraces or
    reshapes the compiled collective — one trace, any data."""
    lay = ValueLayout(embedx_dim=8)
    plan = make_mesh(NDEV)
    config.set_flag("ici_wire_dtype", "adaptive")
    config.set_flag("ici_hot_frac", 0.25)
    tbl, req = _mk_table_req(lay, seed=7)
    _, mapped = _mesh_pull(plan, lay, tbl, req)
    for seed in (8, 9):
        tbl2, req2 = _mk_table_req(lay, seed=seed)
        _mesh_pull_cached = mapped  # same jitted callable, new data
        np.asarray(
            _mesh_pull_cached(
                jax.device_put(jnp.asarray(tbl2), plan.table_sharding),
                jax.device_put(jnp.asarray(req2), plan.batch_sharding),
            )
        )
    assert mapped._cache_size() == 1


def test_payload_stats_match_byte_model(ici_flags):
    """wire.a2a_* stats recorded at trace time must equal ici_wire_nbytes
    for every mode, with adaptive strictly between int8 and bf16 and at
    least 2x under fp32 at embedx_dim=16."""
    lay = ValueLayout(embedx_dim=16)
    W = lay.pull_width
    head = lay.embed_w_col
    k = 16
    rng = np.random.default_rng(10)
    recs = rng.normal(0, 0.05, (NDEV, NDEV, k, W)).astype(np.float32)
    plan = make_mesh(NDEV)
    sections = [(head, W)]
    config.set_flag("ici_hot_frac", 0.125)

    payloads = {}
    for mode in ("fp32", "bf16", "int8", "adaptive"):
        config.set_flag("ici_wire_dtype", mode)
        mapped = jax.jit(
            shard_map(
                lambda r: _compressed_a2a(r[0], plan.axis, head, sections)[None],
                mesh=plan.mesh,
                in_specs=(P(plan.axis),),
                out_specs=P(plan.axis),
                check_vma=False,
            )
        )
        np.asarray(mapped(jax.device_put(jnp.asarray(recs), plan.batch_sharding)))
        payloads[mode] = int(STAT_GET("wire.a2a_payload_bytes"))
        hot = wq.ici_hot_slots(k) if mode == "adaptive" else 0
        assert payloads[mode] == wq.ici_wire_nbytes(
            NDEV, k, W, head, len(sections), mode, hot
        ), mode
        assert int(STAT_GET("wire.a2a_fp32_bytes")) == NDEV * k * W * 4
        assert int(STAT_GET("wire.a2a_hot_slots")) == hot
    assert payloads["int8"] < payloads["adaptive"] < payloads["bf16"]
    assert payloads["fp32"] >= 2 * payloads["adaptive"]
    # blended effective bits land strictly between the uniform extremes
    config.set_flag("ici_wire_dtype", "adaptive")
    bits = int(STAT_GET("wire.a2a_dtype_bits"))
    assert 8 < bits < 16


class _StubWS:
    """Minimal working-set surface _route_sharded needs."""

    def __init__(self, n_mesh_shards, capacity, hot_rows=None):
        self.n_mesh_shards = n_mesh_shards
        self.capacity = capacity
        self.hot_rows = hot_rows


def _route(ws, rows, n_devices=2, B=4, S=1):
    from paddlebox_tpu.data.device_pack import _route_sharded

    L = len(rows)
    segments = np.arange(L, dtype=np.int64) % B  # slot 0, spread over ins
    labels = np.zeros(B, np.float32)
    return _route_sharded(
        np.asarray(rows, np.int64), segments, B, S, ws, n_devices,
        bucket=4, labels=labels, dense=None, dense_dim=0,
    )


def test_hot_first_bucket_ordering_and_overflow_stat(ici_flags):
    """The packer orders each per-shard bucket hot-first when the working
    set carries hotness bits, counts hot keys past the static bound, and
    keeps the historical order bitwise when the bits are absent/all-cold."""
    ns, cap = 2, 8
    config.set_flag("ici_wire_dtype", "adaptive")
    config.set_flag("ici_hot_frac", 0.25)
    # rows all on shard 0 (global rows < cap), one device sees all of them
    rows = np.array([1, 2, 3, 4, 5, 6], np.int64)
    hot = np.zeros(ns * cap, bool)
    hot[[2, 5, 6]] = True  # ranks 2, 5, 6 are hot
    out_hot = _route(_StubWS(ns, cap, hot), rows, n_devices=2, B=12)
    out_none = _route(_StubWS(ns, cap, None), rows, n_devices=2, B=12)
    out_cold = _route(
        _StubWS(ns, cap, np.zeros(ns * cap, bool)), rows, n_devices=2, B=12
    )
    # all-cold bits produce the exact uniform order (lexsort == stable sort)
    np.testing.assert_array_equal(out_cold.req_ranks, out_none.req_ranks)
    np.testing.assert_array_equal(out_cold.inverse, out_none.inverse)
    # hot ranks lead device 0's shard-0 bucket, in stable (ascending) order
    K = out_hot.req_ranks.shape[2]
    bucket = out_hot.req_ranks[0, 0]
    assert list(bucket[:3]) == [2, 5, 6]
    assert list(bucket[3:6]) == [1, 3, 4]
    assert (bucket[6:] == cap - 1).all()  # padding
    # overflow: 3 hot keys, H = round(0.25 * K) slots
    H = wq.ici_hot_slots(K)
    over_before = int(STAT_GET("wire.ici_hot_overflow_keys"))
    _route(_StubWS(ns, cap, hot), rows, n_devices=2, B=12)
    over = int(STAT_GET("wire.ici_hot_overflow_keys")) - over_before
    assert over == max(0, 3 - H)


def test_ici_pack_fault_degrades_to_uniform_order(ici_flags):
    """FLT008 for wire.ici_pack: an injected failure at the hot-ordering
    site degrades THAT batch to the uniform slot order (correct, just
    un-prioritized), counts wire.ici_pack_errors, and the next batch goes
    back to hot-first — no exception escapes the packer."""
    from paddlebox_tpu.utils.faultinject import fail_once, inject

    ns, cap = 2, 8
    config.set_flag("ici_wire_dtype", "adaptive")
    config.set_flag("ici_hot_frac", 0.5)
    rows = np.array([1, 2, 3, 4], np.int64)
    hot = np.zeros(ns * cap, bool)
    hot[[3, 4]] = True
    ref_uniform = _route(_StubWS(ns, cap, None), rows, n_devices=2, B=8)
    errs_before = int(STAT_GET("wire.ici_pack_errors"))
    with inject(fail_once("wire.ici_pack")) as plan:
        degraded = _route(_StubWS(ns, cap, hot), rows, n_devices=2, B=8)
        recovered = _route(_StubWS(ns, cap, hot), rows, n_devices=2, B=8)
        assert plan.hits("wire.ici_pack") == 2
        assert plan.failures("wire.ici_pack") == 1
    assert int(STAT_GET("wire.ici_pack_errors")) - errs_before == 1
    # failed batch == uniform order bitwise
    np.testing.assert_array_equal(degraded.req_ranks, ref_uniform.req_ranks)
    np.testing.assert_array_equal(degraded.inverse, ref_uniform.inverse)
    # healed batch is hot-first again
    assert list(recovered.req_ranks[0, 0, :2]) == [3, 4]


def test_working_set_publishes_hot_rows(ici_flags):
    """PassWorkingSet.finalize derives hotness from the pulled rows' decayed
    show column when the adaptive wire is engaged, and publishes nothing
    under the ablation (packer stays on the uniform order)."""
    lay = ValueLayout(embedx_dim=4)
    config.set_flag("ici_wire_dtype", "adaptive")
    config.set_flag("ici_hot_show", 3.0)
    table = HostSparseTable(lay, SparseOptimizerConfig(), n_shards=2, seed=0)
    keys = np.array([10, 20, 30, 40], np.uint64)
    rows = table.pull_or_create(keys)
    rows[:, lay.SHOW] = [5.0, 1.0, 3.0, 0.0]  # hot, cold, hot (==thr), cold
    table.push(keys, rows)

    ws = PassWorkingSet(n_mesh_shards=2)
    ws.add_keys(keys)
    ws.finalize(table, round_to=8)
    assert ws.hot_rows is not None
    grows = ws.lookup(keys)
    np.testing.assert_array_equal(
        ws.hot_rows[grows], [True, False, True, False]
    )
    assert int(STAT_GET("wire.ici_hot_keys")) == 2

    config.set_flag("ici_wire_adaptive", False)
    ws2 = PassWorkingSet(n_mesh_shards=2)
    ws2.add_keys(keys)
    ws2.finalize(table, round_to=8)
    assert ws2.hot_rows is None


def test_distributed_ws_hot_round(ici_flags):
    """The gated ws-hot round: owners read their local tier's shows and the
    requester lands one bit per key; ablation off runs no extra round."""
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet

    class _OneRankTransport:
        rank, n_ranks = 0, 1

        def alltoall(self, payloads, tag):
            return list(payloads)

        def allgather(self, payload, tag):
            return [payload]

        def allreduce_max(self, value, tag):
            return int(value)

    lay = ValueLayout(embedx_dim=4)
    config.set_flag("ici_wire_dtype", "adaptive")
    config.set_flag("ici_hot_show", 2.0)
    table = HostSparseTable(lay, SparseOptimizerConfig(), n_shards=2, seed=0)
    keys = np.array([7, 8, 9], np.uint64)
    rows = table.pull_or_create(keys)
    rows[:, lay.SHOW] = [4.0, 0.5, 2.0]
    table.push(keys, rows)

    dws = DistributedWorkingSet(_OneRankTransport(), n_mesh_shards=2)
    dws.add_keys(keys)
    dws.finalize(table, round_to=8)
    assert dws.hot_rows is not None
    np.testing.assert_array_equal(
        dws.hot_rows[dws.lookup(keys)], [True, False, True]
    )
    assert int(STAT_GET("wire.ws_hot_bytes")) >= 1

    config.set_flag("ici_wire_adaptive", False)
    dws2 = DistributedWorkingSet(_OneRankTransport(), n_mesh_shards=2)
    dws2.add_keys(keys)
    dws2.finalize(table, round_to=8)
    assert dws2.hot_rows is None


def test_shows_peek_is_pure(ici_flags):
    """shows_peek never creates/promotes rows — absent keys read 0 and stay
    absent (both backends agree; the native path is exercised when g++ is
    available, the Python path always via PBOX_NATIVE_TABLE in CI)."""
    lay = ValueLayout(embedx_dim=4)
    table = HostSparseTable(lay, SparseOptimizerConfig(), n_shards=2, seed=0)
    keys = np.array([100, 200], np.uint64)
    rows = table.pull_or_create(keys)
    rows[:, lay.SHOW] = [9.0, 1.5]
    table.push(keys, rows)
    n_before = len(table)
    peek = table.shows_peek(np.array([100, 200, 300, 400], np.uint64))
    np.testing.assert_allclose(peek, [9.0, 1.5, 0.0, 0.0])
    assert len(table) == n_before  # 300/400 were not created


def test_mesh_trainer_adaptive_auc_neutral(tmp_path, ici_flags):
    """Convergence gate: a mesh-trained pass under the adaptive wire stays
    AUC-neutral vs fp32 (|dAUC| within the run-to-run envelope), cuts the
    compiled a2a payload >=2x, and the off-ablation trains bitwise equal."""
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from tests.test_carrier import _schema, _write_pass

    config.set_flag("ici_hot_frac", 0.25)
    config.set_flag("ici_hot_show", 3.0)

    def run(mode, adaptive_on=True):
        config.set_flag("ici_wire_dtype", mode)
        config.set_flag("ici_wire_adaptive", adaptive_on)
        layout = ValueLayout(embedx_dim=4)
        opt = SparseOptimizerConfig(embedx_threshold=0.0)
        table = HostSparseTable(layout, opt, n_shards=4, seed=0)
        plan = make_mesh(4)
        ds = BoxPSDataset(
            _schema(), table, batch_size=8, n_mesh_shards=4,
            shuffle_mode="none",
        )
        tag = f"{mode}{int(adaptive_on)}"
        f = _write_pass(tmp_path / f"i{tag}.txt", seed=0, lo=1, hi=200)
        ds.set_filelist([f])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        model = DeepFM(
            num_slots=4, feat_width=layout.pull_width, embedx_dim=4,
            hidden=(8,),
        )
        cfg = TrainStepConfig(
            num_slots=4, batch_size=2, layout=layout, sparse_opt=opt,
            auc_buckets=100, axis_name=plan.axis,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan)
        tr.init_params(jax.random.PRNGKey(0))
        out = tr.train_pass(ds)
        tab = np.asarray(tr.trained_table())
        payload = int(STAT_GET("wire.a2a_payload_bytes"))
        fp32_eq = int(STAT_GET("wire.a2a_fp32_bytes"))
        ds.end_pass(None)
        return out, tab, payload, fp32_eq

    out_f, tab_f, pay_f, _ = run("fp32")
    out_a, tab_a, pay_a, fp32_eq = run("adaptive")
    # AUC-neutrality: within the envelope the int8 boundary-wire gate uses
    assert abs(out_a["auc"] - out_f["auc"]) <= 0.02, (
        f"adaptive AUC {out_a['auc']:.4f} vs fp32 {out_f['auc']:.4f}"
    )
    assert np.isclose(out_a["loss"], out_f["loss"], atol=2e-2)
    # a real ICI payload cut vs what fp32 would ship for this shape; the
    # >=2x roadmap bar is a wide-embedding property (embedx_dim=16 —
    # asserted in test_payload_stats_match_byte_model and the soak leg),
    # while this narrow embedx_dim=4 trainer shape tops out near 1.8x
    assert fp32_eq >= 1.5 * pay_a
    assert pay_f == fp32_eq
    # show/clk ride the exact head in every mode
    lay = ValueLayout(embedx_dim=4)
    np.testing.assert_allclose(
        tab_a[..., lay.SHOW], tab_f[..., lay.SHOW], rtol=1e-6, atol=1e-6
    )
    # ablation: adaptive flag set but master gate off == fp32, bitwise
    out_o, tab_o, pay_o, _ = run("adaptive", adaptive_on=False)
    np.testing.assert_array_equal(tab_o, tab_f)
    assert pay_o == pay_f
