"""KernelPlan registry: per-shape pallas-vs-native routing for pull/push
(ops/kernel_plan.py), plan artifact round-trip, eligibility clamps, and
bitwise identity of the two implementations at eligible shapes."""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.ops.kernel_plan import (
    PALLAS_BLK,
    PALLAS_LANE,
    KernelPlan,
    PlanEntry,
    default_plan,
    get_plan,
    invalidate_plan,
    log2_bucket,
    pallas_eligible,
    resolve_plan_path,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plan_cache():
    """Every test resolves its plan fresh and leaves no cache behind."""
    invalidate_plan()
    yield
    config.set_flag("kernel_plan_path", "auto")
    config.set_flag("use_pallas_sparse", False)
    invalidate_plan()


def test_log2_bucket_boundaries():
    # all n in (2^(k-1), 2^k] share bucket k — exact powers stay put,
    # the next integer starts the next band
    assert log2_bucket(1) == 0
    assert log2_bucket(2) == 1
    assert log2_bucket(3) == 2
    assert log2_bucket(4) == 2
    assert log2_bucket(5) == 3
    for k in (10, 17, 20):
        assert log2_bucket(2**k) == k
        assert log2_bucket(2**k + 1) == k + 1
    # deterministic: same n, same bucket, always
    assert all(log2_bucket(131072) == 17 for _ in range(3))


def test_plan_round_trip(tmp_path):
    plan = KernelPlan(
        entries=[
            PlanEntry(op="pull", backend="tpu", impl="native", width=128),
            PlanEntry(
                op="push", backend="tpu", impl="pallas",
                width=128, rows_log2=20, uniq_log2=17, why="measured",
            ),
        ],
        fallback="native",
        source="test",
    )
    p = tmp_path / "plan.json"
    plan.save(str(p))
    loaded = KernelPlan.load(str(p))
    assert loaded.to_json()["entries"] == plan.to_json()["entries"]
    assert loaded.fallback == "native"
    # the loaded plan answers identically across the whole key space
    for op in ("pull", "push"):
        for backend in ("tpu", "cpu"):
            for n_rows, n_idx in ((1 << 20, 1 << 17), (100, 8)):
                assert loaded.preferred(
                    op, backend, n_rows, 128, n_idx
                ) == plan.preferred(op, backend, n_rows, 128, n_idx)


def test_unknown_shape_falls_back():
    plan = KernelPlan(
        entries=[
            PlanEntry(
                op="push", backend="tpu", impl="pallas",
                width=128, rows_log2=20, uniq_log2=17,
            )
        ],
        fallback="native",
    )
    # exact bucket hit
    assert plan.preferred("push", "tpu", 1 << 20, 128, 1 << 17) == "pallas"
    # anything off-key: other width, other bucket, other op, other backend
    assert plan.preferred("push", "tpu", 1 << 20, 256, 1 << 17) == "native"
    assert plan.preferred("push", "tpu", 1 << 10, 128, 1 << 17) == "native"
    assert plan.preferred("pull", "tpu", 1 << 20, 128, 1 << 17) == "native"
    assert plan.preferred("push", "cpu", 1 << 20, 128, 1 << 17) == "native"


def test_probe_order_specificity():
    """Exact bucket beats width-wildcards beats the (op, backend) catch-all."""
    plan = KernelPlan(
        entries=[
            PlanEntry(op="push", backend="tpu", impl="native"),  # catch-all
            PlanEntry(op="push", backend="tpu", impl="native", width=128),
            PlanEntry(
                op="push", backend="tpu", impl="pallas",
                width=128, rows_log2=20, uniq_log2=17,
            ),
        ],
        fallback="native",
    )
    assert plan.preferred("push", "tpu", 1 << 20, 128, 1 << 17) == "pallas"
    assert plan.preferred("push", "tpu", 1 << 20, 128, 1 << 10) == "native"
    assert plan.preferred("push", "tpu", 1 << 20, 64, 1 << 17) == "native"


def test_eligibility_clamps():
    """A plan may PREFER pallas; select() must clamp every ineligible
    shape to native — the artifact cannot route into a miscompile."""
    plan = KernelPlan(entries=[], fallback="pallas")
    # off-TPU: always native
    assert plan.select("pull", "cpu", 1000, 128, 64) == "native"
    # width not lane-aligned
    assert plan.select("pull", "tpu", 1000, 21, 64) == "native"
    # index count not block-aligned
    assert plan.select("pull", "tpu", 1000, 128, 63) == "native"
    # push without unique rows (dedup off): per-row SET would be
    # last-write-wins instead of merged
    assert plan.select("push", "tpu", 1000, 128, 64, unique_rows=False) == "native"
    # fully eligible: the preference goes through
    assert plan.select("pull", "tpu", 1000, 128, 64) == "pallas"
    assert plan.select("push", "tpu", 1000, 128, 64, unique_rows=True) == "pallas"
    # the clamp mirrors pallas_eligible exactly
    assert pallas_eligible("pull", "tpu", PALLAS_LANE, PALLAS_BLK)
    assert not pallas_eligible("pull", "cpu", PALLAS_LANE, PALLAS_BLK)
    assert not pallas_eligible("push", "tpu", PALLAS_LANE, PALLAS_BLK, False)


def test_plan_validation():
    with pytest.raises(ValueError, match="duplicate"):
        KernelPlan(entries=[
            PlanEntry(op="pull", backend="tpu", impl="native", width=128),
            PlanEntry(op="pull", backend="tpu", impl="pallas", width=128),
        ])
    with pytest.raises(ValueError, match="op"):
        KernelPlan(entries=[PlanEntry(op="frobnicate", backend="tpu", impl="native")])
    with pytest.raises(ValueError, match="impl"):
        KernelPlan(entries=[PlanEntry(op="pull", backend="tpu", impl="cuda")])
    with pytest.raises(ValueError, match="fallback"):
        KernelPlan(fallback="cuda")


def test_default_plan_honors_legacy_flag():
    config.set_flag("use_pallas_sparse", False)
    assert default_plan().fallback == "native"
    config.set_flag("use_pallas_sparse", True)
    assert default_plan().fallback == "pallas"


def test_plan_file_loading_via_flag(tmp_path):
    p = tmp_path / "custom_plan.json"
    KernelPlan(
        entries=[PlanEntry(op="pull", backend="cpu", impl="native", why="t")],
        source="will-be-replaced-by-path",
    ).save(str(p))
    config.set_flag("kernel_plan_path", str(p))
    invalidate_plan()
    plan = get_plan()
    assert plan.source == str(p)
    # cache keys on the flag: flipping to "off" re-resolves to builtins
    config.set_flag("kernel_plan_path", "off")
    assert get_plan().source.startswith("builtin-default")


def test_resolve_plan_path():
    for off in ("", "off", "none"):
        assert resolve_plan_path(off) is None
    with pytest.raises(FileNotFoundError):
        resolve_plan_path("/nonexistent/kernel_plan.json")
    # "auto" finds the committed artifact (this repo ships one)
    assert resolve_plan_path("auto") == os.path.join(
        REPO, "tools", "kernel_plan.json"
    )


def test_committed_plan_is_loadable_and_native_off_tpu():
    """The committed tools/kernel_plan.json must always load, and every
    selection off-TPU must be native (eligibility clamp regardless of
    artifact content)."""
    plan = KernelPlan.load(os.path.join(REPO, "tools", "kernel_plan.json"))
    for op in ("pull", "push"):
        for n_rows, width, n_idx in ((1 << 20, 128, 1 << 17), (96, 21, 24)):
            assert plan.select(op, "cpu", n_rows, width, n_idx) == "native"


def test_pallas_native_identity_pull():
    """Gather via the pallas row-DMA kernel (interpret mode) must be
    BITWISE identical to jnp.take — a DMA copies bytes, so any eligible
    shape may be routed either way without changing training."""
    from paddlebox_tpu.ops.pallas_kernels import pull_rows_pallas

    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(256, PALLAS_LANE)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 256, 64).astype(np.int32))
    via_pallas = np.asarray(pull_rows_pallas(table, rows, interpret=True))
    via_native = np.asarray(jnp.take(table, rows, axis=0))
    assert np.array_equal(via_pallas, via_native)


def test_pallas_native_identity_push_write():
    """Writeback via the pallas kernel (interpret) must be bitwise equal
    to scatter-SET of the same new rows (unique indices — the regime the
    plan's unique_rows clamp guarantees)."""
    from paddlebox_tpu.ops.pallas_kernels import write_rows_pallas

    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(128, PALLAS_LANE)).astype(np.float32))
    rows = jnp.asarray(rng.permutation(128)[:PALLAS_BLK * 3].astype(np.int32))
    new = jnp.asarray(
        rng.normal(size=(PALLAS_BLK * 3, PALLAS_LANE)).astype(np.float32)
    )
    via_pallas = np.asarray(
        write_rows_pallas(jnp.array(table), rows, new, interpret=True)
    )
    via_native = np.asarray(jnp.array(table).at[rows].set(new))
    assert np.array_equal(via_pallas, via_native)


def test_select_runs_through_pull_push():
    """The ops layer has no residual direct gate: _impl_for consults the
    active plan, so a plan swap changes routing with no code change."""
    from paddlebox_tpu.ops.pull_push import _impl_for

    t = jnp.zeros((64, 128))
    assert _impl_for("pull", t, 64) == "native"  # cpu: clamped regardless
    config.set_flag("use_pallas_sparse", True)
    config.set_flag("kernel_plan_path", "off")
    invalidate_plan()
    assert _impl_for("pull", t, 64) == "native"  # still cpu-clamped


def test_tune_kernels_default_smoke(tmp_path):
    out = tmp_path / "plan.json"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tune_kernels.py"),
         "--default", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 0, p.stderr
    plan = KernelPlan.load(str(out))
    assert {(e.op, e.impl) for e in plan.entries} == {
        ("pull", "native"), ("push", "native"),
    }
    assert all(e.why for e in plan.entries)  # provenance is mandatory


def test_tune_kernels_artifact_conversion(tmp_path):
    """A measured sweep artifact where pallas wins must produce a pallas
    push entry at the measured bucket (and its width generalization);
    a native win or a hysteresis miss must produce native."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from tune_kernels import entries_from_artifact
    finally:
        sys.path.pop(0)

    def art(native_ms, pallas_ms):
        return {
            "version": 1,
            "backend": "tpu",
            "shape": {"rows": 2514944, "u": 131072, "w": 21},
            "points": {
                "w128": {"ms": native_ms},
                "pallas": {"ms": pallas_ms},
            },
        }

    wins = entries_from_artifact(art(10.0, 5.0), min_speedup=1.1)
    assert [e.impl for e in wins] == ["pallas", "pallas"]
    assert wins[0].rows_log2 == log2_bucket(2514944)
    assert wins[0].uniq_log2 == log2_bucket(131072)
    assert wins[1].rows_log2 is None  # the width-only generalization
    loses = entries_from_artifact(art(5.0, 10.0), min_speedup=1.1)
    assert [e.impl for e in loses] == ["native", "native"]
    # hysteresis: a 5% win under a 1.1 min-speedup stays native
    close = entries_from_artifact(art(10.0, 9.5), min_speedup=1.1)
    assert [e.impl for e in close] == ["native", "native"]
    # a cpu-backend artifact proves nothing about the tpu crossover
    cpu_art = art(10.0, 5.0)
    cpu_art["backend"] = "cpu"
    assert entries_from_artifact(cpu_art, min_speedup=1.1) == []
