"""Spill-tier space management soak (csrc/host_table.cc compact_spill).

The disk tier's files are append-only between compactions: every promote
leaves its old record's bytes behind, so before round 4 a many-pass run
grew the spill without bound (VERDICT r3 missing #5). spill_cold now
compacts a shard opportunistically once dead records outnumber live, and
``compact_spill`` forces full reclaim. This soak drives >=1e7 keys through
multi-pass spill/promote cycles under a mem cap — the dimensional test of
SURVEY §7 hard part 1 (the 1e11-key design scales by shards x passes; the
per-shard mechanics are what this exercises).
"""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)


def _native_or_skip():
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native table store unavailable")


def test_spill_soak_bounded_over_passes():
    """10 passes x 4M-key working sets over a 14M key space with a 2M-row
    mem cap: every pass spills + promotes; the spill file must stay bounded
    by the LIVE cold set (x2 slack for not-yet-compacted dead records),
    and a forced compaction reclaims to exactly live x record size."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    rec = 24 + lay.width * 4  # SpillRec header + width floats
    with tempfile.TemporaryDirectory() as d:
        table = HostSparseTable(
            lay,
            SparseOptimizerConfig(show_clk_decay=0.98, shrink_threshold=0.0),
            n_shards=16,
            seed=0,
            spill_dir=d,
            mem_cap_rows=2_000_000,
        )
        rng = np.random.default_rng(0)
        saw_dead = 0
        for p in range(10):
            ws = np.unique(
                rng.integers(1, 14_000_000, 4_000_000).astype(np.uint64)
            )
            vals = table.pull_or_create(ws)
            vals[:, lay.SHOW] += 1.0
            table.push(ws, vals)
            table.decay_and_shrink()
            table.maybe_spill()
            live, dead, nbytes = table.spill_stats()
            saw_dead = max(saw_dead, dead)
            # bounded: never more than 2x the live set on disk
            assert nbytes <= max(live, 1) * rec * 2, (
                f"pass {p}: spill {nbytes}B exceeds 2x live bound "
                f"({live} live records x {rec}B)"
            )
        assert len(table) >= 10_000_000  # the soak actually hit 1e7 keys
        assert saw_dead > 1_000_000  # promote cycles really happened
        kept = table.compact_spill()
        live, dead, nbytes = table.spill_stats()
        assert dead == 0
        assert kept == live
        assert nbytes == live * rec  # fully reclaimed
        # integrity after compaction: promoted rows read back sane
        sample = np.unique(
            rng.integers(1, 14_000_000, 10_000).astype(np.uint64)
        )
        got = table.pull_or_create(sample)
        assert np.isfinite(got).all()
        assert (got[:, lay.SHOW] >= 0).all()


def test_push_superseding_spilled_rows_counts_dead():
    """A push that overwrites keys currently on disk leaves dead records —
    they must be visible to spill_stats and reclaimable (the load/restore
    workflow pushes straight over spilled keys)."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=1)
    rec = 24 + lay.width * 4
    with tempfile.TemporaryDirectory() as d:
        table = HostSparseTable(
            lay,
            SparseOptimizerConfig(show_clk_decay=1.0, shrink_threshold=0.0),
            n_shards=2,
            seed=0,
            spill_dir=d,
            mem_cap_rows=100,
        )
        keys = np.arange(1, 4_001, dtype=np.uint64)
        vals = np.ones((4_000, lay.width), np.float32)
        table.push(keys, vals)
        table.maybe_spill()
        live0, dead0, _ = table.spill_stats()
        assert live0 > 3_000 and dead0 == 0
        # push over every spilled key: all those disk records die
        table.push(keys, vals * 2)
        _, dead1, _ = table.spill_stats()
        assert dead1 == live0
        table.maybe_spill()  # re-spill; opportunistic compaction may fire
        table.compact_spill()
        live2, dead2, nbytes2 = table.spill_stats()
        assert dead2 == 0 and nbytes2 == live2 * rec


def test_compact_preserves_values_exactly():
    """Compaction must be a pure space operation: spilled rows read back
    bit-identical before and after."""
    _native_or_skip()
    lay = ValueLayout(embedx_dim=2)
    with tempfile.TemporaryDirectory() as d:
        table = HostSparseTable(
            lay,
            SparseOptimizerConfig(show_clk_decay=1.0, shrink_threshold=0.0),
            n_shards=4,
            seed=0,
            spill_dir=d,
            mem_cap_rows=1_000,
        )
        rng = np.random.default_rng(1)
        keys_a = np.arange(1, 5_001, dtype=np.uint64)
        vals_a = rng.normal(0, 1, (5_000, lay.width)).astype(np.float32)
        table.push(keys_a, vals_a)
        table.maybe_spill()  # most of A goes to disk
        # touch a different range so promotes of A later leave dead records
        keys_b = np.arange(10_001, 14_001, dtype=np.uint64)
        table.pull_or_create(keys_b)
        table.maybe_spill()
        # promote half of A (creates dead records), then force compact
        half = keys_a[::2]
        got_before = table.pull_or_create(half)
        table.maybe_spill()
        table.compact_spill()
        _, dead, _ = table.spill_stats()
        assert dead == 0
        # every original row still reads back exactly
        got_all = table.pull_or_create(keys_a)
        np.testing.assert_array_equal(got_all, vals_a)
        np.testing.assert_array_equal(got_before, vals_a[::2])
