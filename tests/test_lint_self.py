"""pbox-lint as a tier-1 self-check: the whole repo (package + tools +
tests) must lint clean against the checked-in baseline, and the gate must
actually be live (a synthetic violation fails). This is the enforcement
point — a PR that introduces a new lint error fails HERE, not in some
optional side tool."""

import os
import shutil
import subprocess
import sys

from paddlebox_tpu.analysis import (
    DEFAULT_PROFILES,
    ERROR,
    apply_baseline,
    apply_profiles,
    default_rules,
    lint_paths,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddlebox_tpu")
ROOTS = [PKG, os.path.join(REPO, "tools"), os.path.join(REPO, "tests")]
BASELINE = os.path.join(REPO, "tools", "lint_baseline.json")


def lint_repo(root=REPO, paths=None, baseline=BASELINE):
    result = lint_paths(paths or ROOTS, default_rules(), root=root)
    findings = apply_profiles(result.findings, DEFAULT_PROFILES)
    new, grandfathered, stale = apply_baseline(
        findings, load_baseline(baseline)
    )
    return result, [f for f in new if f.severity == ERROR], stale


def test_repo_lints_clean():
    # the full default scan set — package, tools AND tests — with the
    # per-root rule profiles run_lint.py applies
    result, new_errors, stale = lint_repo()
    assert result.parse_errors == [], result.parse_errors
    assert new_errors == [], "\n" + "\n".join(f.render() for f in new_errors)
    # a stale entry means a grandfathered finding was fixed but the baseline
    # kept its budget — shrink it so the debt can't silently regrow
    assert stale == [], (
        "baseline entries no longer fire — run "
        "`python tools/run_lint.py --update-baseline`: "
        f"{stale}"
    )


def test_baseline_is_empty():
    # every grandfathered finding has been burned down; the analyzer is
    # self-clean, and new debt must be fixed (or justified inline), not
    # baselined
    assert load_baseline(BASELINE) == {}


def test_synthetic_violation_fails(tmp_path):
    # copy a real module tree shape: package root + one doctored file
    pkg = tmp_path / "paddlebox_tpu"
    pkg.mkdir()
    shutil.copy(os.path.join(PKG, "config.py"), pkg / "config.py")
    (pkg / "doctored.py").write_text(
        "from paddlebox_tpu.utils.monitor import STAT_ADD\n"
        "def f(p):\n"
        "    open(p, 'w').write('x')\n"
        "    STAT_ADD('Not-A-Valid-Name')\n"
        "    try:\n"
        "        return 1\n"
        "    except Exception:\n"
        "        pass\n"
    )
    _, new_errors, _ = lint_repo(
        root=str(tmp_path), paths=[str(pkg)], baseline=BASELINE
    )
    rules = {f.rule for f in new_errors}
    assert "IO004" in rules and "MON005" in rules and "EXC007" in rules


def test_cli_gate_green_on_repo():
    # the exact invocation CI/developers run (default roots = the same
    # three-root scan)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_lint.py")],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
