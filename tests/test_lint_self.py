"""pbox-lint as a tier-1 self-check: the package must lint clean against
the checked-in baseline, and the gate must actually be live (a synthetic
violation fails). This is the enforcement point — a PR that introduces a
new lint error fails HERE, not in some optional side tool."""

import os
import shutil
import subprocess
import sys

from paddlebox_tpu.analysis import (
    ERROR,
    apply_baseline,
    default_rules,
    lint_paths,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddlebox_tpu")
BASELINE = os.path.join(REPO, "tools", "lint_baseline.json")


def lint_package(root=REPO, pkg=PKG, baseline=BASELINE):
    result = lint_paths([pkg], default_rules(), root=root)
    new, grandfathered, stale = apply_baseline(
        result.findings, load_baseline(baseline)
    )
    return result, [f for f in new if f.severity == ERROR], stale


def test_package_lints_clean():
    result, new_errors, stale = lint_package()
    assert result.parse_errors == [], result.parse_errors
    assert new_errors == [], "\n" + "\n".join(f.render() for f in new_errors)
    # a stale entry means a grandfathered finding was fixed but the baseline
    # kept its budget — shrink it so the debt can't silently regrow
    assert stale == [], (
        "baseline entries no longer fire — run "
        "`python tools/run_lint.py paddlebox_tpu/ --update-baseline`: "
        f"{stale}"
    )


def test_baseline_is_small():
    # the baseline exists to demonstrate grandfathering, not to hoard debt
    assert len(load_baseline(BASELINE)) <= 5


def test_synthetic_violation_fails(tmp_path):
    # copy a real module tree shape: package root + one doctored file
    pkg = tmp_path / "paddlebox_tpu"
    pkg.mkdir()
    shutil.copy(os.path.join(PKG, "config.py"), pkg / "config.py")
    (pkg / "doctored.py").write_text(
        "from paddlebox_tpu.utils.monitor import STAT_ADD\n"
        "def f(p):\n"
        "    open(p, 'w').write('x')\n"
        "    STAT_ADD('Not-A-Valid-Name')\n"
    )
    _, new_errors, _ = lint_package(
        root=str(tmp_path), pkg=str(pkg), baseline=BASELINE
    )
    rules = {f.rule for f in new_errors}
    assert "IO004" in rules and "MON005" in rules


def test_cli_gate_green_on_package():
    # the exact invocation CI/developers run
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_lint.py"),
         os.path.join(REPO, "paddlebox_tpu")],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
