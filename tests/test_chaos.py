"""Chaos schedules: the PassSupervisor under seeded fault injection.

The acceptance bar for the robustness tentpole: a 3-pass day that takes an
fs flake, one poisoned pass, and one torn checkpoint must complete through
PassSupervisor with the final sparse table and dense params BITWISE equal
to a never-injected run, with every revert/retry/fallback in the incident
log. Deterministic, CPU-only, fast — these run in tier-1 under the
``chaos`` marker.
"""

from __future__ import annotations

import os
from types import SimpleNamespace

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.data import (
    BoxPSDataset,
    DataPoisonedError,
    SlotInfo,
    SlotSchema,
    read_dead_letter,
)
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import (
    CheckpointManager,
    CTRTrainer,
    HealthGates,
    PassFailure,
    PassRejected,
    PassSupervisor,
    RetryPolicy,
    TrainStepConfig,
)
from paddlebox_tpu.utils.faultinject import fail_nth, fail_once, inject

pytestmark = pytest.mark.chaos

S, B = 4, 16
DATE = "20260101"
OPT = SparseOptimizerConfig(
    embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
)


@pytest.fixture(autouse=True)
def _no_retry_sleep():
    prev = config.get_flag("fs_open_backoff_s")
    config.set_flag("fs_open_backoff_s", 0.0)
    yield
    config.set_flag("fs_open_backoff_s", prev)


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def _write(path, seed, lo, hi, n=64):
    rng = np.random.default_rng(seed)
    path.parent.mkdir(parents=True, exist_ok=True)
    # fixture writer: path derives from tmp_path (helper param hides it)
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        for _ in range(n):
            parts = [f"1 {float(rng.integers(0, 2))}"]
            for _s in range(S):
                k = int(rng.integers(1, 3))
                parts.append(
                    f"{k} " + " ".join(str(v) for v in rng.integers(lo, hi, k))
                )
            f.write(" ".join(parts) + "\n")
    return str(path)


def _files(tmp_path, tag):
    return [
        _write(tmp_path / tag / f"{DATE}-{p}.txt", p, 1 + 40 * p, 161 + 40 * p)
        for p in range(3)
    ]


def _sup(tmp_path, tag, gates=None, on_give_up="raise", on_poisoned=None,
         sleep=None):
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, OPT, n_shards=2, seed=0)
    ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=layout, sparse_opt=OPT,
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    cm = CheckpointManager(str(tmp_path / f"ckpt-{tag}"))
    sup = PassSupervisor(
        ds, tr, checkpoint=cm, gates=gates,
        retry=RetryPolicy(backoff_s=0.0, sleep=sleep or (lambda s: None)),
        round_to=8, on_give_up=on_give_up, on_poisoned=on_poisoned,
    )
    return table, ds, tr, cm, sup


def _final_state(table, tr):
    k = np.sort(table.keys())
    v = table.pull_or_create(k)
    dense = [np.asarray(x) for x in jax.tree.flatten((tr.params, tr.opt_state))[0]]
    return k, v, dense


def test_chaos_day_bitwise_equals_clean_run(tmp_path):
    """fs flake + poisoned pass + torn checkpoint save: the supervised day
    completes and its final state is bitwise-identical to an uninjected
    run of the same schedule."""
    files = _files(tmp_path, "data")

    # clean run; the empty plan only counts site hits, so the injected
    # run's windows can be derived instead of hard-coded
    table_c, _, tr_c, cm_c, sup_c = _sup(tmp_path, "clean")
    with inject() as probe:
        outs_c = sup_c.run_day(DATE, [[f] for f in files])
    assert sup_c.incidents == []
    steps_per_pass = probe.hits("step.device") // 3
    saves_fires = probe.hits("checkpoint.save")
    assert saves_fires % 3 == 0
    fires_per_save = saves_fires // 3
    assert steps_per_pass >= 1 and fires_per_save >= 2

    table_i, _, tr_i, cm_i, sup_i = _sup(tmp_path, "inj")
    schedule = (
        # one input flake during load — absorbed inside the fs retry tier
        fail_once("fs.open_read"),
        # poison pass 2 mid-train — supervisor reverts and retrains it
        fail_nth("step.device", steps_per_pass + 2),
        # tear pass 2's delta save mid-publish (sparse written to .tmp,
        # unpublished) — supervisor retries the save from scratch
        fail_nth("checkpoint.save", fires_per_save + 2),
    )
    with inject(*schedule) as plan:
        outs_i = sup_i.run_day(DATE, [[f] for f in files])
    assert plan.failures("fs.open_read") == 1
    assert plan.failures("step.device") == 1
    assert plan.failures("checkpoint.save") == 1
    assert all(o is not None for o in outs_i)

    # bitwise equality of the final model state
    k_c, v_c, d_c = _final_state(table_c, tr_c)
    k_i, v_i, d_i = _final_state(table_i, tr_i)
    np.testing.assert_array_equal(k_i, k_c)
    np.testing.assert_array_equal(v_i, v_c)
    assert len(d_i) == len(d_c)
    for a, b in zip(d_i, d_c):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(
        [o["loss"] for o in outs_i], [o["loss"] for o in outs_c], atol=1e-7
    )

    # the incident log names each heal: the mid-train fault became a
    # revert+retry, the torn save became a save retry. (The fs flake is
    # invisible by design — the fs tier healed it below the supervisor.)
    kinds = [(i.kind, i.action) for i in sup_i.incidents]
    assert ("train_error", "revert_retry") in kinds
    assert ("ckpt_save_error", "retry") in kinds

    # both runs published equivalent checkpoints: same cursor, and a
    # fresh-process resume lands on the same sparse state
    assert cm_i.cursor() == cm_c.cursor()
    for cm in (cm_c, cm_i):
        assert cm.cursor()["delta_idx"] == 2
    rt_c = HostSparseTable(ValueLayout(embedx_dim=4), OPT, n_shards=2, seed=0)
    rt_i = HostSparseTable(ValueLayout(embedx_dim=4), OPT, n_shards=2, seed=0)
    cm_c.resume(rt_c)
    cm_i.resume(rt_i)
    rk_c = np.sort(rt_c.keys())
    rk_i = np.sort(rt_i.keys())
    np.testing.assert_array_equal(rk_i, rk_c)
    np.testing.assert_array_equal(
        rt_i.pull_or_create(rk_i), rt_c.pull_or_create(rk_c)
    )


def test_gate_rejection_escalates_to_resume_then_skips(tmp_path):
    """A pass whose gates never pass exhausts revert+retry, escalates to a
    checkpoint resume, re-fails, and is dropped (on_give_up='skip') with
    the base state intact."""
    files = _files(tmp_path, "edata")
    table, _, tr, cm, sup = _sup(tmp_path, "esc", on_give_up="skip")
    out = sup.run_pass([files[0]], date=DATE, save="base")
    assert out is not None
    base_keys = np.sort(table.keys()).copy()
    base_vals = table.pull_or_create(base_keys).copy()

    sup.gates.auc_absolute_floor = 2.0  # unsatisfiable: every pass rejected
    out2 = sup.run_pass([files[1]], date=DATE)
    assert out2 is None
    kinds = [(i.kind, i.action) for i in sup.incidents]
    assert ("gate_auc", "revert_retry") in kinds
    assert ("escalate_resume", "resume") in kinds
    assert ("gave_up", "skip") in kinds
    # the durable base rows came through the resume+reverts untouched
    np.testing.assert_array_equal(table.pull_or_create(base_keys), base_vals)

    # the supervisor is reusable after a skip: the next healthy pass trains
    sup.gates.auc_absolute_floor = None
    out3 = sup.run_pass([files[2]], date=DATE, save="delta")
    assert out3 is not None
    assert cm.cursor()["delta_idx"] == 1


def test_persistent_load_failure_surfaces_as_pass_failure(tmp_path):
    table, _, tr, cm, sup = _sup(tmp_path, "load")
    with pytest.raises(PassFailure, match="load failed"):
        sup.run_pass([str(tmp_path / "missing" / "nope.txt")], date=DATE)
    kinds = [(i.kind, i.action) for i in sup.incidents]
    assert ("load_error", "retry") in kinds
    assert ("load_error", "raise") in kinds


# ---- poisoned data: quarantine admission under the supervisor -----------

# every one of these fails BOTH parser tiers (bad float / bad int / torn)
GARBAGE = [
    "3 zz !! this-line-is-corrupt",
    "1 not-a-float 1 5 1 9",
    "?? ?? ??",
    "1 1.0 one 5",
    "2 0.5 x",
]


def _poison_insert(src, dst):
    """Copy ``src`` with garbage lines INSERTED at fixed offsets, so the
    surviving records are exactly the original file's records (a degrade
    run must be bitwise-equal to a run over the pre-cleaned filelist)."""
    lines = open(src).read().splitlines()
    out, injected = [], []
    for i, ln in enumerate(lines):
        if i in (3, 17, 29, 41, 57):
            bad = GARBAGE[len(injected) % len(GARBAGE)]
            out.append(bad)
            injected.append(bad)
        out.append(ln)
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text("\n".join(out) + "\n")
    return str(dst), injected


def test_poisoned_day_degrade_bitwise_equals_precleaned_run(tmp_path):
    """Acceptance: a supervised day whose middle part file is corrupted
    completes under on_poisoned='degrade' with the bad lines dead-lettered,
    and lands bitwise-identical to the same day over the pre-cleaned
    filelist."""
    files = _files(tmp_path, "pdata")
    poisoned, injected = _poison_insert(
        files[1], tmp_path / "pdata-bad" / f"{DATE}-1.txt"
    )

    table_c, _, tr_c, _, sup_c = _sup(tmp_path, "pclean")
    outs_c = sup_c.run_day(DATE, [[f] for f in files])
    assert sup_c.incidents == []

    table_d, _, tr_d, cm_d, sup_d = _sup(
        tmp_path, "pdeg", on_poisoned="degrade"
    )
    outs_d = sup_d.run_day(DATE, [[files[0]], [poisoned], [files[2]]])
    assert all(o is not None for o in outs_d)

    k_c, v_c, d_c = _final_state(table_c, tr_c)
    k_d, v_d, d_d = _final_state(table_d, tr_d)
    np.testing.assert_array_equal(k_d, k_c)
    np.testing.assert_array_equal(v_d, v_c)
    for a, b in zip(d_d, d_c):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(
        [o["loss"] for o in outs_d], [o["loss"] for o in outs_c], atol=1e-7
    )
    np.testing.assert_allclose(
        [o["auc"] for o in outs_d], [o["auc"] for o in outs_c], atol=1e-9
    )

    # the degraded pass carries its bounded loss on the pass manifest
    assert outs_d[1]["quarantined_bad_lines"] == float(len(injected))
    assert 0.0 < outs_d[1]["quarantined_line_fraction"] < 0.1
    assert "quarantined_bad_lines" not in outs_d[0]
    assert "quarantined_bad_lines" not in outs_d[2]

    # exactly one structured incident: the degrade admission, naming the
    # dead-letter file and the loss
    kinds = [(i.kind, i.action) for i in sup_d.incidents]
    assert kinds == [("data_poisoned", "degrade")]
    detail = sup_d.incidents[0].detail
    assert "dead-letter: " in detail and "loss: 5 lines" in detail

    # the named dead-letter round-trips: the injected garbage, verbatim,
    # and it lives under the supervisor-wired <ckpt_root>/quarantine
    dl_path = detail.split("dead-letter: ")[1].split(" (loss")[0]
    assert dl_path.startswith(os.path.join(cm_d.root, "quarantine"))
    dl = read_dead_letter(dl_path)
    assert [e["line"] for e in dl["entries"]] == injected
    assert all(e["file"] == poisoned for e in dl["entries"])
    assert dl["summary"]["bad_lines"] == len(injected)


def test_poisoned_pass_strict_raises_without_burning_retries(tmp_path):
    """Acceptance: under the default on_poisoned='fail' policy a corrupt
    pass raises DataPoisonedError after exactly one attempt — zero train
    steps, zero backoff sleeps, no revert/retry incidents — with a
    structured incident naming the dead-letter file."""
    files = _files(tmp_path, "sdata")
    poisoned, injected = _poison_insert(
        files[1], tmp_path / "sdata-bad" / f"{DATE}-1.txt"
    )
    sleeps = []
    table, ds, tr, cm, sup = _sup(tmp_path, "strict", sleep=sleeps.append)
    assert sup.run_pass([files[0]], date=DATE, save="base") is not None

    with inject() as probe:
        with pytest.raises(DataPoisonedError) as ei:
            sup.run_pass([poisoned], date=DATE)
    assert probe.hits("step.device") == 0  # poison resolved before training
    assert sleeps == []  # deterministic failure: no backoff retries burned
    assert ei.value.report["bad_lines"] == len(injected)
    assert ei.value.dead_letter and os.path.exists(ei.value.dead_letter)

    kinds = [(i.kind, i.action) for i in sup.incidents]
    assert kinds == [("data_poisoned", "raise")]
    assert ei.value.dead_letter in sup.incidents[0].detail

    # recovery contract: the rejected pass's staged data must be dropped
    # explicitly before the supervisor can run the next pass
    ds.drop_pass_data()
    assert sup.run_pass([files[2]], date=DATE) is not None


def test_seeded_parse_fault_strict_and_degrade(tmp_path):
    """Satellite: a seeded parser.parse_line fault inside a supervised
    3-pass day. Strict mode escalates without burning retries; degrade
    mode completes bitwise-equal to the pre-cleaned filelist and the
    dead-letter round-trips the injected-fault victim line."""
    prev_native = config.get_flag("enable_native_parser")
    config.set_flag("enable_native_parser", 0)  # native never calls parse_line
    try:
        files = _files(tmp_path, "fdata")
        raw = open(files[0]).read().splitlines()
        victim = raw[9]  # fail_nth(..., 10) kills 1-based line 10 of pass 0
        cleaned0 = tmp_path / "fdata-clean" / f"{DATE}-0.txt"
        cleaned0.parent.mkdir(parents=True, exist_ok=True)
        cleaned0.write_text("\n".join(raw[:9] + raw[10:]) + "\n")

        table_c, _, tr_c, _, sup_c = _sup(tmp_path, "fclean")
        outs_c = sup_c.run_day(
            DATE, [[str(cleaned0)], [files[1]], [files[2]]]
        )
        assert sup_c.incidents == []

        # strict: the fault poisons pass 0 and the day dies immediately
        sleeps = []
        *_, sup_s = _sup(tmp_path, "fstrict", sleep=sleeps.append)
        with inject(fail_nth("parser.parse_line", 10)) as plan:
            with pytest.raises(DataPoisonedError):
                sup_s.run_day(DATE, [[f] for f in files])
        assert plan.failures("parser.parse_line") == 1
        assert sleeps == []
        assert [(i.kind, i.action) for i in sup_s.incidents] == [
            ("data_poisoned", "raise")
        ]

        # degrade: same fault, day completes, bitwise == pre-cleaned run
        table_d, _, tr_d, _, sup_d = _sup(
            tmp_path, "fdeg", on_poisoned="degrade"
        )
        with inject(fail_nth("parser.parse_line", 10)) as plan:
            outs_d = sup_d.run_day(DATE, [[f] for f in files])
        assert plan.failures("parser.parse_line") == 1
        assert all(o is not None for o in outs_d)
        k_c, v_c, d_c = _final_state(table_c, tr_c)
        k_d, v_d, d_d = _final_state(table_d, tr_d)
        np.testing.assert_array_equal(k_d, k_c)
        np.testing.assert_array_equal(v_d, v_c)
        for a, b in zip(d_d, d_c):
            np.testing.assert_array_equal(a, b)

        detail = sup_d.incidents[0].detail
        dl = read_dead_letter(detail.split("dead-letter: ")[1].split(" (loss")[0])
        (entry,) = dl["entries"]
        assert entry["line"] == victim and entry["line_no"] == 10
        assert "injected fault" in entry["error"]
    finally:
        config.set_flag("enable_native_parser", prev_native)


# ---- gate unit behavior (no training stack needed) ----------------------


def _bare_supervisor(gates):
    return PassSupervisor(
        SimpleNamespace(table=None), trainer=None, gates=gates,
        retry=RetryPolicy(max_retries=0, sleep=lambda s: None),
    )


def test_nan_gate_rejects_poisoned_pass():
    sup = _bare_supervisor(HealthGates(nan_ratio_max=0.05))
    sup._gate({"batches": 100.0, "nan_batches": 1.0, "auc": 0.7})  # under
    with pytest.raises(PassRejected) as ei:
        sup._gate({"batches": 100.0, "nan_batches": 10.0, "auc": 0.7})
    assert ei.value.gate == "nan"


def test_auc_floor_needs_history_then_bites():
    sup = _bare_supervisor(
        HealthGates(auc_window=5, auc_min_history=3, auc_floor_margin=0.05)
    )
    # cold start: no history, nothing to compare against
    sup._gate({"batches": 1.0, "auc": 0.4})
    sup._auc_history.extend([0.80, 0.80, 0.80])
    with pytest.raises(PassRejected) as ei:
        sup._gate({"batches": 1.0, "auc": 0.70})  # floor = 0.75
    assert ei.value.gate == "auc"
    sup._gate({"batches": 1.0, "auc": 0.76})  # above the floor


def test_retry_policy_backoff_bounded():
    rp = RetryPolicy(backoff_s=0.5, backoff_mult=2.0, backoff_max_s=3.0)
    assert rp.backoff(1) == 0.5
    assert rp.backoff(2) == 1.0
    assert rp.backoff(3) == 2.0
    assert rp.backoff(4) == 3.0  # capped
    assert rp.backoff(10) == 3.0
