"""Unified telemetry plane: histograms, metric series, trace context on
the wire, and the incident flight recorder.

Covers the observability tentpole's acceptance bar end to end, on CPU,
deterministically:

- log2 histograms: exact aggregates, quantile accuracy against numpy,
  and thread-safety under concurrent observers;
- metric series: JSONL rotation + round trip, torn-tail tolerance;
- PBTX trace-context frames: flag-off frames are byte-compatible with a
  pre-extension v3 peer, flag-on frames correlate sender and receiver
  instants under one trace_id, and N ranks' traces merge into a single
  timeline with one process row per rank;
- flight recorder: bounded ring, and a REAL mid-collective peer death
  must leave an ``incident-*.json`` bundle with the last spans, the
  incident record, and a full stat snapshot.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from paddlebox_tpu import config
from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER, FlightRecorder
from paddlebox_tpu.obs.histogram import Histogram, merge_all
from paddlebox_tpu.obs.metrics_writer import (
    MetricsWriter,
    read_series,
    series_files,
    series_ranks,
)
from paddlebox_tpu.obs.trace_context import (
    EXT_LEN,
    TraceContext,
    current_trace,
    decode_ext,
    trace_span,
)
from paddlebox_tpu.parallel.transport import PeerDeadError, TcpTransport
from paddlebox_tpu.utils.monitor import (
    STAT_GET,
    STAT_HIST,
    STAT_OBSERVE,
    all_histograms,
)
from paddlebox_tpu.utils.trace import Profiler


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_exact_aggregates(self):
        h = Histogram()
        h.observe_many([3.0, 1.0, 4.0, 1.0, 5.0])
        assert h.count == 5
        assert h.sum == pytest.approx(14.0)
        assert h.min == 1.0 and h.max == 5.0

    def test_quantiles_vs_numpy(self):
        rng = np.random.default_rng(7)
        data = rng.lognormal(mean=2.0, sigma=1.2, size=20000)
        h = Histogram()
        h.observe_many(float(v) for v in data)
        for q in (0.5, 0.9, 0.99):
            est = h.quantile(q)
            ref = float(np.quantile(data, q))
            # log2 buckets: ~1 bit of relative error on the estimate
            assert abs(est - ref) / ref < 0.35, (q, est, ref)
        # extremes are exact, quantiles monotone and clamped
        qs = h.quantiles((0.0, 0.25, 0.5, 0.75, 0.99, 1.0))
        assert qs[0] == float(data.min())
        assert qs[-1] == float(data.max())
        assert all(a <= b for a, b in zip(qs, qs[1:])), qs

    def test_concurrent_observers(self):
        h = Histogram()
        n_threads, per = 8, 5000

        def pound(seed):
            r = np.random.default_rng(seed)
            for v in r.uniform(0.1, 100.0, per):
                h.observe(float(v))

        threads = [
            threading.Thread(target=pound, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == n_threads * per  # no lost updates
        assert 0.1 <= h.min <= h.max <= 100.0
        assert h.sum == pytest.approx(h.count * 50.0, rel=0.05)

    def test_nonpositive_and_roundtrip(self):
        h = Histogram()
        h.observe_many([0.0, -3.5, 2.0, 8.0])
        assert h.count == 4 and h.min == -3.5
        h2 = Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
        assert h2.summary() == h.summary()
        merged = merge_all([h, h2, None])
        assert merged.count == 8 and merged.min == -3.5

    def test_stat_observe_registry(self):
        STAT_OBSERVE("obs_test.unique_series_ms", 5.0)
        STAT_OBSERVE("obs_test.unique_series_ms", 9.0)
        h = STAT_HIST("obs_test.unique_series_ms")
        assert h is not None and h.count == 2
        assert "obs_test.unique_series_ms" in all_histograms()
        assert STAT_HIST("obs_test.never_observed") is None


# ---------------------------------------------------------------------------
# metric series
# ---------------------------------------------------------------------------


class TestMetricsSeries:
    def test_rotation_and_roundtrip(self, tmp_path):
        out = str(tmp_path)
        w = MetricsWriter(out, rank=2, interval_s=0.0, rotate_bytes=2000)
        STAT_OBSERVE("obs_test.rotate_ms", 1.0)
        for i in range(10):
            w.snapshot(f"pass:{i}", extra={"i": i})
        assert w.rotations >= 1
        files = series_files(out, rank=2)
        assert len(files) == w.rotations + 1
        assert series_ranks(out) == [2]
        recs = list(read_series(out, rank=2))
        assert [r["seq"] for r in recs] == list(range(1, 11))
        assert [r["label"] for r in recs] == [f"pass:{i}" for i in range(10)]
        assert all(r["rank"] == 2 for r in recs)
        assert recs[3]["extra"] == {"i": 3}
        assert "obs_test.rotate_ms" in recs[0]["histograms"]

    def test_deltas_are_per_window(self, tmp_path):
        from paddlebox_tpu.utils.monitor import STAT_ADD

        w = MetricsWriter(str(tmp_path), rank=0, interval_s=0.0)
        STAT_ADD("obs_test.window_ctr", 5)
        r1 = w.snapshot("pass:0")
        STAT_ADD("obs_test.window_ctr", 3)
        r2 = w.snapshot("pass:1")
        assert r1["deltas"]["obs_test.window_ctr"] >= 5
        assert r2["deltas"]["obs_test.window_ctr"] == 3  # window, not total

    def test_torn_tail_tolerated(self, tmp_path):
        w = MetricsWriter(str(tmp_path), rank=0, interval_s=0.0)
        w.snapshot("pass:0")
        w.snapshot("pass:1")
        # simulate a crash mid-append: a torn, non-JSON final line
        # pbox-lint: disable=IO004
        with open(w.path, "a") as f:
            f.write('{"t": 1.0, "rank": 0, "seq')
        before = STAT_GET("obs.metrics_bad_lines")
        recs = list(read_series(str(tmp_path), rank=0))
        assert [r["label"] for r in recs] == ["pass:0", "pass:1"]
        assert STAT_GET("obs.metrics_bad_lines") == before + 1


# ---------------------------------------------------------------------------
# trace context + wire compat
# ---------------------------------------------------------------------------


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def fast_transport():
    names = (
        "transport_heartbeat_s",
        "transport_backoff_s",
        "transport_send_retries",
        "transport_peer_dead_s",
        "transport_trace_frames",
        "obs_incident_dir",
    )
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_peer_dead_s", 60.0)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


class TestTraceContext:
    def test_ext_roundtrip_and_child(self):
        ctx = TraceContext.new()
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id
        raw = child.encode_ext()
        assert len(raw) == EXT_LEN
        back = decode_ext(raw)
        assert back.trace_id_hex == ctx.trace_id_hex

    def test_trace_span_nesting(self):
        assert current_trace() is None
        with trace_span("outer"):
            outer = current_trace()
            assert outer is not None
            with trace_span("inner"):
                inner = current_trace()
                assert inner.trace_id == outer.trace_id
                assert inner.span_id != outer.span_id
            assert current_trace() is outer
        assert current_trace() is None

    def test_flag_off_frames_match_pre_extension_v3(self, fast_transport):
        """With ``transport_trace_frames`` off (the default) the sender
        emits byte-identical frames to a pre-extension v3 peer — even
        inside an active trace span — so old and new readers interop."""
        config.set_flag("transport_trace_frames", False)  # the default
        eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        tps = [TcpTransport(r, eps, timeout=30.0) for r in range(2)]
        sent0 = STAT_GET("transport.trace_frames_sent")
        recv0 = STAT_GET("transport.trace_frames_recv")
        try:
            with trace_span("compat"):
                tps[0].send(1, "plain", b"payload")
            assert tps[1].recv("plain", 0, timeout=10.0) == b"payload"
        finally:
            for t in tps:
                t.close()
        assert STAT_GET("transport.trace_frames_sent") == sent0
        assert STAT_GET("transport.trace_frames_recv") == recv0

    def test_flag_on_correlates_across_ranks(self, fast_transport, tmp_path):
        """Flag on: the receiver's transport:deliver instant carries the
        SAME trace_id as the sender's span, and the two per-rank chrome
        traces merge into one timeline with one process row per rank and
        a cross-rank trace_id pair (the acceptance bar)."""
        import obs_report

        config.set_flag("transport_trace_frames", True)
        profs = []
        for r in range(2):
            p = Profiler(max_events=512)
            p.enable()
            p.set_process(r)
            profs.append(p)
        eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        tps = [
            TcpTransport(r, eps, timeout=30.0, profiler=profs[r])
            for r in range(2)
        ]
        recv0 = STAT_GET("transport.trace_frames_recv")
        try:
            with trace_span("xrank"):
                want_tid = current_trace().trace_id_hex
                tps[0].send(1, "traced", b"x")
            assert tps[1].recv("traced", 0, timeout=10.0) == b"x"
            # the deliver instant lands just after the inbox notify
            deadline = time.monotonic() + 5.0
            while STAT_GET("transport.trace_frames_recv") == recv0:
                assert time.monotonic() < deadline, "deliver never recorded"
                time.sleep(0.01)
        finally:
            for t in tps:
                t.close()
        paths = []
        for r, p in enumerate(profs):
            out = str(tmp_path / f"trace-{r}.json")
            p.export_chrome_trace(out)
            paths.append(out)
        with open(paths[0]) as f:
            send_evs = [
                e for e in json.load(f)["traceEvents"]
                if e.get("name") == "transport:send"
            ]
        with open(paths[1]) as f:
            dlv_evs = [
                e for e in json.load(f)["traceEvents"]
                if e.get("name") == "transport:deliver"
            ]
        assert send_evs and dlv_evs
        assert send_evs[0]["args"]["trace_id"] == want_tid
        assert dlv_evs[0]["args"]["trace_id"] == want_tid

        rep = obs_report.merge_traces(paths, str(tmp_path / "merged.json"))
        assert rep["process_rows"] == [0, 1]  # one row per rank
        assert rep["cross_rank_trace_ids"] >= 1
        with open(str(tmp_path / "merged.json")) as f:
            merged = json.load(f)
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_bound_and_dump(self, tmp_path):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.note_span(f"span{i}", "test", float(i), 1.0, {})
        fr.note_incident("test_kind", {"detail": 42})
        snap = fr.snapshot()
        assert [s["name"] for s in snap["spans"]] == [
            "span6", "span7", "span8", "span9"
        ]  # newest survive
        assert snap["incidents"][0]["kind"] == "test_kind"
        path = fr.dump("test_reason", detail="why", dir_path=str(tmp_path))
        assert path is not None and os.path.basename(path).startswith("incident-")
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "test_reason" and bundle["detail"] == "why"
        assert len(bundle["spans"]) == 4
        assert "stats" in bundle and "histograms" in bundle

    def test_dump_disabled_without_dir(self):
        fr = FlightRecorder(capacity=2)
        prev = config.get_flag("obs_incident_dir")
        config.set_flag("obs_incident_dir", "")
        try:
            assert fr.dump("nowhere") is None
        finally:
            config.set_flag("obs_incident_dir", prev)

    def test_recorder_fed_with_tracing_disabled(self):
        """The always-on property: spans reach the recorder ring even
        when the profiler is disabled (no chrome trace being kept)."""
        from paddlebox_tpu.utils.trace import Profiler

        p = Profiler(max_events=16)
        assert not p.enabled
        with p.record_event("invisible_to_trace", category="test"):
            pass
        spans = FLIGHT_RECORDER.snapshot()["spans"]
        assert any(s["name"] == "invisible_to_trace" for s in spans)
        assert len(p._events) == 0  # nothing in the trace ring itself

    def test_peer_death_leaves_incident_bundle(self, fast_transport, tmp_path):
        """The acceptance bar: a rank dying mid-collective must leave an
        ``incident-<ts>.json`` with the last spans, the stat snapshot,
        and the peer_dead reason — written by the _take_all dump hook,
        with no tracing enabled anywhere."""
        inc_dir = str(tmp_path / "incidents")
        config.set_flag("transport_peer_dead_s", 0.6)
        config.set_flag("obs_incident_dir", inc_dir)
        n = 3
        eps = [f"127.0.0.1:{p}" for p in _free_ports(n)]
        tps = [TcpTransport(r, eps, timeout=30.0) for r in range(n)]
        try:
            # mid-pass shape: real frames flow first, then rank 2 dies
            for dst in (1, 2):
                tps[0].send(dst, "warm", b"w")
                assert tps[dst].recv("warm", 0, timeout=10.0) == b"w"
            deadline = time.monotonic() + 5.0
            while any(tps[0].peer_status(r) != "alive" for r in (1, 2)):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            tps[2].close()  # dies: no more heartbeats
            with pytest.raises(PeerDeadError) as ei:
                tps[0].barrier("dead-rank-obs", timeout=30.0)
            assert ei.value.dead == [2]
        finally:
            for t in tps:
                t.close()
        bundles = sorted(
            f for f in os.listdir(inc_dir) if f.startswith("incident-")
        )
        assert bundles, "peer death left no incident bundle"
        with open(os.path.join(inc_dir, bundles[-1])) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "peer_dead"
        assert "dead rank" in bundle["detail"] or "rank(s)" in bundle["detail"]
        assert bundle["stats"], "bundle lost the stat snapshot"
        # the warm-up transfers above were recorded by the always-on ring
        assert bundle["spans"], "bundle lost the recent spans"


# ---------------------------------------------------------------------------
# golden-diff: soak report keys unchanged by the histogram port
# ---------------------------------------------------------------------------


class TestSoakReportGolden:
    def test_serve_latency_percentile_keys(self):
        """ScoreServer.latency_percentiles moved onto the shared
        histogram; the soak JSON keys must not have changed."""
        from paddlebox_tpu.serve.server import ScoreServer

        srv = ScoreServer(follower=None, scorer=None, schema=None)
        assert srv.latency_percentiles() == {"n": 0}
        for ms in (4.0, 8.0, 15.0, 16.0, 23.0, 42.0):
            srv.latency_hist.observe(ms)
        rep = srv.latency_percentiles()
        assert set(rep) == {"n", "p50_ms", "p99_ms", "max_ms"}  # golden
        assert rep["n"] == 6
        assert 0 < rep["p50_ms"] <= rep["p99_ms"] <= rep["max_ms"] == 42.0

    def test_scale_soak_zipf_pass_keys(self, tmp_path):
        """run_zipf_policy per-pass entries keep their exact key set; the
        histogram port only ADDS the pass_s_dist summary."""
        from paddlebox_tpu.utils import native

        if not native.available():
            pytest.skip("zipf soak needs the native table")
        import scale_soak

        conf = {
            "keys": 2000, "draws": 1000, "passes": 2, "mem_cap_rows": 200,
            "zipf_a": 1.2, "decay": 0.98, "pin_show": 0.0, "admit_show": 0.0,
            "admit_rate": 0.0, "n_shards": 4, "seed": 0, "embedx_dim": 4,
            "digest": False, "workdir": str(tmp_path),
        }
        out = scale_soak.run_zipf_policy("fifo", conf)
        golden = {
            "pass", "pass_s", "uniq_keys", "promotes", "spilled",
            "admitted_disk_first", "spill_hit_rate", "mem_rows", "disk_rows",
        }
        assert all(set(p) == golden for p in out["passes"])
        assert out["pass_s_dist"]["count"] == conf["passes"]
        assert out["pass_s_dist"]["max"] >= out["pass_s_dist"]["p50"] > 0


# ---------------------------------------------------------------------------
# obs_report CLI pieces
# ---------------------------------------------------------------------------


class TestObsReport:
    def test_pass_table_and_slo(self, tmp_path):
        import obs_report
        from paddlebox_tpu.utils.monitor import STAT_ADD

        w = MetricsWriter(str(tmp_path), rank=0, interval_s=0.0)
        for i in range(3):
            STAT_ADD("obs_test.report_rows", 100 + i)
            STAT_OBSERVE("obs_test.report_ms", 10.0 * (i + 1))
            w.snapshot(f"pass:{i}")
        records = obs_report.load_series(str(tmp_path))
        assert len(records) == 3
        table = obs_report.render_pass_table(records)
        assert "pass:0" in table and "pass:2" in table
        hists = obs_report.summarize_histograms(records)
        assert "obs_test.report_ms" in hists
        verdicts = obs_report.slo_verdicts(hists, [
            "obs_test.report_ms:p99<=1000",
            "obs_test.report_ms:p50>=1000000",
            "obs_test.missing_ms:p50<=1",
        ])
        assert [v["verdict"] for v in verdicts] == ["PASS", "FAIL", "NODATA"]

    def test_selfcheck_green(self):
        import obs_report

        assert obs_report.selfcheck() == 0
