"""Fault-injection framework unit tests + the retry tiers it exercises.

The injection sites are only useful if their triggers are deterministic
and hermetic — these tests pin the trigger semantics (Nth-hit, seeded
probability, fail-once-then-heal, budget exhaustion) and then point them
at the production retry paths (fs open retry, write retry, prefetch
job retry) to prove a transient flake heals invisibly while a persistent
failure still surfaces at the right place.
"""

from __future__ import annotations

import pytest

from paddlebox_tpu import config
from paddlebox_tpu.utils.faultinject import (
    InjectedFault,
    fail_always,
    fail_nth,
    fail_once,
    fail_prob,
    fire,
    inject,
)


def test_fire_without_plan_is_noop():
    for _ in range(3):
        fire("fs.open_read")  # nothing armed: must never raise


def test_fail_nth_hits_exactly_once():
    with inject(fail_nth("site.a", 3)) as plan:
        fire("site.a")
        fire("site.a")
        with pytest.raises(InjectedFault) as ei:
            fire("site.a")
        assert ei.value.site == "site.a" and ei.value.hit == 3
        # healed: the rule's budget (times=1) is spent
        for _ in range(5):
            fire("site.a")
        assert plan.hits("site.a") == 8
        assert plan.failures("site.a") == 1
    fire("site.a")  # hermetic: plan uninstalled on exit


def test_fail_once_then_heal():
    with inject(fail_once("site.b")) as plan:
        with pytest.raises(InjectedFault):
            fire("site.b")
        fire("site.b")
        assert plan.failures("site.b") == 1


def test_sites_are_independent():
    with inject(fail_once("site.a")):
        fire("site.b")  # other sites unaffected
        with pytest.raises(InjectedFault):
            fire("site.a")


def test_fail_prob_deterministic_and_budgeted():
    def run(seed, times):
        fails = []
        with inject(fail_prob("site.p", 0.5, seed=seed, times=times)):
            for i in range(20):
                try:
                    fire("site.p")
                except InjectedFault:
                    fails.append(i)
        return fails

    a, b = run(7, None), run(7, None)
    assert a == b and 0 < len(a) < 20  # seeded: same schedule both runs
    capped = run(7, 2)
    assert capped == a[:2]  # the budget truncates the same schedule


def test_injected_fault_is_oserror():
    # the fs retry tier treats OSError as transient; the injected fault
    # must ride that exact classification
    assert issubclass(InjectedFault, OSError)


def test_scope_restores_previous_plan():
    with inject(fail_always("site.x")):
        with inject():  # inner empty plan masks the outer
            fire("site.x")
        with pytest.raises(InjectedFault):
            fire("site.x")


# ---- production retry paths under injection -----------------------------


@pytest.fixture()
def fast_backoff():
    prev = config.get_flag("fs_open_backoff_s")
    config.set_flag("fs_open_backoff_s", 0.0)
    yield
    config.set_flag("fs_open_backoff_s", prev)


def test_fs_open_read_retry_absorbs_flake(tmp_path, fast_backoff):
    from paddlebox_tpu.utils.fs import fs_open_read_retry

    p = tmp_path / "d.txt"
    p.write_text("hello\n")
    with inject(fail_once("fs.open_read")) as plan:
        with fs_open_read_retry(str(p)) as f:
            assert f.read() == "hello\n"
        assert plan.failures("fs.open_read") == 1


def test_fs_open_read_retry_persistent_failure_surfaces(tmp_path, fast_backoff):
    from paddlebox_tpu.utils.fs import fs_open_read_retry

    p = tmp_path / "d.txt"
    p.write_text("hello\n")
    with inject(fail_always("fs.open_read")):
        with pytest.raises(InjectedFault):
            fs_open_read_retry(str(p))


def test_fs_read_bytes_retry_absorbs_flake(tmp_path, fast_backoff):
    from paddlebox_tpu.utils.fs import fs_read_bytes_retry

    p = tmp_path / "d.bin"
    p.write_bytes(b"\x01\x02")
    with inject(fail_once("fs.open_read")):
        assert fs_read_bytes_retry(str(p)) == b"\x01\x02"


def test_fs_open_write_retry_absorbs_flake(tmp_path, fast_backoff):
    from paddlebox_tpu.utils.fs import fs_open_read, fs_open_write_retry

    p = tmp_path / "out" / "w.txt"
    with inject(fail_once("fs.open_write")) as plan:
        with fs_open_write_retry(str(p)) as f:
            f.write("payload\n")
        assert plan.failures("fs.open_write") == 1
    with fs_open_read(str(p)) as f:
        assert f.read() == "payload\n"


def test_fs_open_write_retry_persistent_failure_surfaces(tmp_path, fast_backoff):
    from paddlebox_tpu.utils.fs import fs_open_write_retry

    with inject(fail_always("fs.open_write")):
        with pytest.raises(InjectedFault):
            fs_open_write_retry(str(tmp_path / "w.txt"))


def test_prefetch_retries_transient_job_and_keeps_order():
    from paddlebox_tpu.data.pipeline import prefetch

    with inject(fail_nth("pipeline.prefetch_job", 8)) as plan:
        out = list(prefetch(range(20), lambda x: x * x, workers=4, depth=5))
    # the flaky job healed on its in-place retry; order is untouched
    assert out == [x * x for x in range(20)]
    assert plan.failures("pipeline.prefetch_job") == 1


def test_prefetch_persistent_failure_surfaces_in_position():
    """Regression: the exception position contract survives the retry
    layer — a job that fails every attempt surfaces exactly at its
    delivery position, after every earlier result."""
    from paddlebox_tpu.data.pipeline import prefetch

    def boom(x):
        if x == 7:
            raise ValueError("boom")
        return x

    got = []
    with pytest.raises(ValueError):
        for v in prefetch(range(20), boom, workers=4, depth=5):
            got.append(v)
    assert got == list(range(7))


def test_prefetch_retry_budget_configurable():
    from paddlebox_tpu.data.pipeline import prefetch

    calls = {}

    def flaky(x):
        c = calls[x] = calls.get(x, 0) + 1
        if x == 5 and c <= 2:
            raise ValueError("flaky")
        return x

    # job 5 fails twice: the default budget (1 retry) surfaces it...
    with pytest.raises(ValueError):
        list(prefetch(range(10), flaky, workers=2, depth=3))
    calls.clear()
    # ...a budget of 2 absorbs both failures
    out = list(prefetch(range(10), flaky, workers=2, depth=3, retries=2))
    assert out == list(range(10))
