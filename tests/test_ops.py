"""Op-level numeric tests (OpTest-style parity harness, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ops import (
    cvm_transform,
    fused_seqpool_cvm,
    pull_sparse_rows,
    push_sparse_rows,
)
from paddlebox_tpu.table import SparseOptimizerConfig, ValueLayout


LAY = ValueLayout(embedx_dim=4)


def _table(rows=8, show=None, seed=0):
    rng = np.random.default_rng(seed)
    t = rng.normal(size=(rows, LAY.width)).astype(np.float32)
    t[:, LAY.SHOW] = show if show is not None else 20.0
    t[:, LAY.CLK] = 1.0
    t[:, LAY.embed_g2_col] = 0.0
    t[:, LAY.embedx_g2_col] = 0.0
    return jnp.asarray(t)


def test_pull_layout_and_gating():
    t = _table()
    t = t.at[1, LAY.SHOW].set(0.0)  # below threshold -> embedx masked
    pulled = pull_sparse_rows(t, jnp.array([0, 1]), LAY, embedx_threshold=10.0, scale=2.0)
    assert pulled.shape == (2, LAY.pull_width)
    np.testing.assert_allclose(pulled[0, :3], t[0, :3])
    np.testing.assert_allclose(pulled[0, 3:], t[0, 3:7] * 2.0, rtol=1e-6)
    np.testing.assert_allclose(pulled[1, 3:], 0.0)


def test_cvm_transform():
    pooled = jnp.array([[3.0, 1.0, 0.7, 0.2]])
    out = cvm_transform(pooled, use_cvm=True)
    np.testing.assert_allclose(out[0, 0], np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], np.log(2.0) - np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(out[0, 2:], [0.7, 0.2])
    out2 = cvm_transform(pooled, use_cvm=False)
    np.testing.assert_allclose(out2[0], [0.7, 0.2])


def test_fused_seqpool_cvm_matches_numpy():
    S, B, W = 2, 3, LAY.pull_width
    rng = np.random.default_rng(1)
    # ragged: lengths per (slot, ins)
    lens = np.array([[2, 1, 3], [1, 2, 1]])
    L = lens.sum()
    recs = np.abs(rng.normal(size=(L, W))).astype(np.float32)
    segs = np.repeat(np.arange(S * B), lens.reshape(-1)).astype(np.int32)

    out = fused_seqpool_cvm(jnp.asarray(recs), jnp.asarray(segs), S, B, use_cvm=True)
    assert out.shape == (B, S, W)

    # numpy reference
    pooled = np.zeros((S * B, W), dtype=np.float32)
    np.add.at(pooled, segs, recs)
    pooled = pooled.reshape(S, B, W)
    expect = pooled.copy()
    expect[..., 0] = np.log(pooled[..., 0] + 1)
    expect[..., 1] = np.log(pooled[..., 1] + 1) - np.log(pooled[..., 0] + 1)
    np.testing.assert_allclose(out, np.transpose(expect, (1, 0, 2)), rtol=1e-3, atol=1e-4)


def test_fused_seqpool_padding_goes_to_trash_segment():
    S, B, W = 1, 2, LAY.pull_width
    recs = jnp.ones((4, W))
    segs = jnp.array([0, 1, S * B, S * B], dtype=jnp.int32)  # 2 pads
    out = fused_seqpool_cvm(recs, segs, S, B, use_cvm=False)
    np.testing.assert_allclose(out[:, 0, :], 1.0)  # each ins pooled exactly 1 record


def test_push_updates_counters_and_weights():
    opt = SparseOptimizerConfig(embed_lr=0.1, embedx_lr=0.1, embedx_threshold=10.0)
    t = _table()
    rows = jnp.array([2, 5])
    g = jnp.ones((2, LAY.pull_width), jnp.float32) * 0.5
    show_c = jnp.array([3.0, 1.0])
    clk_c = jnp.array([1.0, 0.0])
    t2 = push_sparse_rows(t, rows, g, show_c, clk_c, LAY, opt)

    np.testing.assert_allclose(t2[2, LAY.SHOW], t[2, LAY.SHOW] + 3.0)
    np.testing.assert_allclose(t2[2, LAY.CLK], t[2, LAY.CLK] + 1.0)
    # embed_w moved against the gradient
    assert float(t2[2, LAY.embed_w_col]) < float(t[2, LAY.embed_w_col])
    # g2 accumulated
    assert float(t2[2, LAY.embed_g2_col]) > 0.0
    # untouched rows unchanged
    np.testing.assert_array_equal(t2[0], t[0])


def test_push_embedx_gated_below_threshold():
    opt = SparseOptimizerConfig(embedx_threshold=10.0)
    t = _table(show=1.0)  # below threshold
    rows = jnp.array([0])
    g = jnp.ones((1, LAY.pull_width), jnp.float32)
    t2 = push_sparse_rows(t, rows, g, jnp.ones(1), jnp.zeros(1), LAY, opt)
    # embedx unchanged, embed_w still updates
    np.testing.assert_array_equal(t2[0, LAY.embedx_col : LAY.embedx_col + 4],
                                  t[0, LAY.embedx_col : LAY.embedx_col + 4])
    assert float(t2[0, LAY.embed_w_col]) != float(t[0, LAY.embed_w_col])


def test_adagrad_step_decays_with_g2():
    opt = SparseOptimizerConfig(embed_lr=0.1, initial_g2sum=1.0)
    t = _table()
    rows = jnp.array([0])
    g = jnp.zeros((1, LAY.pull_width), jnp.float32).at[0, 2].set(1.0)
    w0 = float(t[0, LAY.embed_w_col])
    t1 = push_sparse_rows(t, rows, g, jnp.ones(1), jnp.zeros(1), LAY, opt)
    d1 = w0 - float(t1[0, LAY.embed_w_col])
    t2 = push_sparse_rows(t1, rows, g, jnp.ones(1), jnp.zeros(1), LAY, opt)
    d2 = float(t1[0, LAY.embed_w_col]) - float(t2[0, LAY.embed_w_col])
    assert 0 < d2 < d1  # adagrad: later identical grads take smaller steps
