"""Op-level numeric tests (OpTest-style parity harness, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ops import (
    cvm_transform,
    fused_seqpool_cvm,
    pull_sparse_rows,
    push_sparse_rows,
)
from paddlebox_tpu.table import SparseOptimizerConfig, ValueLayout


LAY = ValueLayout(embedx_dim=4)


def _table(rows=8, show=None, seed=0):
    rng = np.random.default_rng(seed)
    t = rng.normal(size=(rows, LAY.width)).astype(np.float32)
    t[:, LAY.SHOW] = show if show is not None else 20.0
    t[:, LAY.CLK] = 1.0
    t[:, LAY.embed_g2_col] = 0.0
    t[:, LAY.embedx_g2_col] = 0.0
    return jnp.asarray(t)


def test_pull_layout_and_gating():
    t = _table()
    t = t.at[1, LAY.SHOW].set(0.0)  # below threshold -> embedx masked
    pulled = pull_sparse_rows(t, jnp.array([0, 1]), LAY, embedx_threshold=10.0, scale=2.0)
    assert pulled.shape == (2, LAY.pull_width)
    np.testing.assert_allclose(pulled[0, :3], t[0, :3])
    np.testing.assert_allclose(pulled[0, 3:], t[0, 3:7] * 2.0, rtol=1e-6)
    np.testing.assert_allclose(pulled[1, 3:], 0.0)


def test_cvm_transform():
    pooled = jnp.array([[3.0, 1.0, 0.7, 0.2]])
    out = cvm_transform(pooled, use_cvm=True)
    np.testing.assert_allclose(out[0, 0], np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], np.log(2.0) - np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(out[0, 2:], [0.7, 0.2])
    out2 = cvm_transform(pooled, use_cvm=False)
    np.testing.assert_allclose(out2[0], [0.7, 0.2])


def test_fused_seqpool_cvm_matches_numpy():
    S, B, W = 2, 3, LAY.pull_width
    rng = np.random.default_rng(1)
    # ragged: lengths per (slot, ins)
    lens = np.array([[2, 1, 3], [1, 2, 1]])
    L = lens.sum()
    recs = np.abs(rng.normal(size=(L, W))).astype(np.float32)
    segs = np.repeat(np.arange(S * B), lens.reshape(-1)).astype(np.int32)

    out = fused_seqpool_cvm(jnp.asarray(recs), jnp.asarray(segs), S, B, use_cvm=True)
    assert out.shape == (B, S, W)

    # numpy reference
    pooled = np.zeros((S * B, W), dtype=np.float32)
    np.add.at(pooled, segs, recs)
    pooled = pooled.reshape(S, B, W)
    expect = pooled.copy()
    expect[..., 0] = np.log(pooled[..., 0] + 1)
    expect[..., 1] = np.log(pooled[..., 1] + 1) - np.log(pooled[..., 0] + 1)
    np.testing.assert_allclose(out, np.transpose(expect, (1, 0, 2)), rtol=1e-3, atol=1e-4)


def test_fused_seqpool_padding_goes_to_trash_segment():
    S, B, W = 1, 2, LAY.pull_width
    recs = jnp.ones((4, W))
    segs = jnp.array([0, 1, S * B, S * B], dtype=jnp.int32)  # 2 pads
    out = fused_seqpool_cvm(recs, segs, S, B, use_cvm=False)
    np.testing.assert_allclose(out[:, 0, :], 1.0)  # each ins pooled exactly 1 record


def test_push_updates_counters_and_weights():
    opt = SparseOptimizerConfig(embed_lr=0.1, embedx_lr=0.1, embedx_threshold=10.0)
    t = _table()
    rows = jnp.array([2, 5])
    g = jnp.ones((2, LAY.pull_width), jnp.float32) * 0.5
    show_c = jnp.array([3.0, 1.0])
    clk_c = jnp.array([1.0, 0.0])
    t2 = push_sparse_rows(t, rows, g, show_c, clk_c, LAY, opt)

    np.testing.assert_allclose(t2[2, LAY.SHOW], t[2, LAY.SHOW] + 3.0)
    np.testing.assert_allclose(t2[2, LAY.CLK], t[2, LAY.CLK] + 1.0)
    # embed_w moved against the gradient
    assert float(t2[2, LAY.embed_w_col]) < float(t[2, LAY.embed_w_col])
    # g2 accumulated
    assert float(t2[2, LAY.embed_g2_col]) > 0.0
    # untouched rows unchanged
    np.testing.assert_array_equal(t2[0], t[0])


def test_push_embedx_gated_below_threshold():
    opt = SparseOptimizerConfig(embedx_threshold=10.0)
    t = _table(show=1.0)  # below threshold
    rows = jnp.array([0])
    g = jnp.ones((1, LAY.pull_width), jnp.float32)
    t2 = push_sparse_rows(t, rows, g, jnp.ones(1), jnp.zeros(1), LAY, opt)
    # embedx unchanged, embed_w still updates
    np.testing.assert_array_equal(t2[0, LAY.embedx_col : LAY.embedx_col + 4],
                                  t[0, LAY.embedx_col : LAY.embedx_col + 4])
    assert float(t2[0, LAY.embed_w_col]) != float(t[0, LAY.embed_w_col])


def test_adagrad_step_decays_with_g2():
    opt = SparseOptimizerConfig(embed_lr=0.1, initial_g2sum=1.0)
    t = _table()
    rows = jnp.array([0])
    g = jnp.zeros((1, LAY.pull_width), jnp.float32).at[0, 2].set(1.0)
    w0 = float(t[0, LAY.embed_w_col])
    t1 = push_sparse_rows(t, rows, g, jnp.ones(1), jnp.zeros(1), LAY, opt)
    d1 = w0 - float(t1[0, LAY.embed_w_col])
    t2 = push_sparse_rows(t1, rows, g, jnp.ones(1), jnp.zeros(1), LAY, opt)
    d2 = float(t1[0, LAY.embed_w_col]) - float(t2[0, LAY.embed_w_col])
    assert 0 < d2 < d1  # adagrad: later identical grads take smaller steps


def test_variable_feature_type_graded_dims():
    """B3 VARIABLE: effective embedx dim unlocks in quarters as show crosses
    doubling thresholds (cvm_offset stays 3, same row width)."""
    import jax.numpy as jnp

    from paddlebox_tpu.ops.pull_push import pull_sparse_rows
    from paddlebox_tpu.table.value_layout import FeatureType, ValueLayout

    lay = ValueLayout(embedx_dim=8, feature_type=FeatureType.VARIABLE)
    assert lay.cvm_offset == 3
    assert lay.width == ValueLayout(embedx_dim=8).width

    T = 10.0
    table = np.ones((5, lay.width), np.float32)
    # shows: cold, >=T, >=2T, >=4T, >=8T
    table[:, lay.SHOW] = [1.0, 10.0, 20.0, 40.0, 80.0]
    rows = jnp.arange(5, dtype=jnp.int32)
    out = np.asarray(pull_sparse_rows(jnp.asarray(table), rows, lay, T, 1.0))
    emb = out[:, lay.cvm_offset :]
    active_dims = (emb != 0).sum(axis=1)
    assert list(active_dims) == [0, 2, 4, 6, 8]
    # threshold 0 == full dims everywhere (plain behavior)
    out0 = np.asarray(pull_sparse_rows(jnp.asarray(table), rows, lay, 0.0, 1.0))
    assert ((out0[:, lay.cvm_offset :] != 0).sum(axis=1) == 8).all()


def test_variable_feature_type_trains():
    """Masked dims receive no gradient; training stays finite and learns."""
    import jax
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.table import (
        HostSparseTable,
        PassWorkingSet,
        SparseOptimizerConfig,
    )
    from paddlebox_tpu.table.value_layout import FeatureType, ValueLayout
    from paddlebox_tpu.data.slot_record import SlotRecord, build_batch
    from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
    from paddlebox_tpu.data.device_pack import pack_batch
    from paddlebox_tpu.train import TrainStepConfig
    from paddlebox_tpu.train.train_step import (
        init_train_state,
        jit_train_step,
        make_train_step,
    )

    lay = ValueLayout(embedx_dim=8, feature_type=FeatureType.VARIABLE)
    opt = SparseOptimizerConfig(embed_lr=0.3, embedx_threshold=4.0, initial_range=0.01)
    rng = np.random.default_rng(0)
    NS, B = 3, 16
    recs = []
    for _ in range(4 * B):
        keys = rng.integers(1, 40, NS).astype(np.uint64)  # hot: shows accumulate
        recs.append(SlotRecord(
            u64_values=keys,
            u64_offsets=np.arange(NS + 1, dtype=np.uint32),
            f_values=np.array([float(keys[0] % 2)], np.float32),
            f_offsets=np.array([0, 1], np.uint32),
        ))
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    table = HostSparseTable(lay, opt, n_shards=2, seed=0)
    ws = PassWorkingSet()
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)
    model = LogisticRegression(num_slots=NS, feat_width=lay.pull_width)
    cfg = TrainStepConfig(num_slots=NS, batch_size=B, layout=lay,
                          sparse_opt=opt, auc_buckets=500)
    step = jit_train_step(make_train_step(model.apply, optax.adam(1e-2), cfg))
    state = init_train_state(
        jnp.asarray(dev.reshape(-1, lay.width)),
        model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 500,
    )
    losses = []
    for ep in range(6):
        for bi in range(4):
            batch = build_batch(recs[bi * B : (bi + 1) * B], schema)
            db = pack_batch(batch, ws, schema, bucket=64)
            state, m = step(state, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
            losses.append(float(m["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    tbl = np.asarray(state.table)
    assert np.isfinite(tbl).all()


def test_variable_locked_dims_never_trained():
    """Push applies the same graded mask as pull: a locked quarter-dim
    receives no update and no g2 energy even when the model's gradient
    w.r.t. the (zeroed) pulled value is nonzero."""
    import jax.numpy as jnp

    from paddlebox_tpu.ops.pull_push import sparse_update_rows
    from paddlebox_tpu.table import SparseOptimizerConfig
    from paddlebox_tpu.table.value_layout import FeatureType, ValueLayout

    lay = ValueLayout(embedx_dim=8, feature_type=FeatureType.VARIABLE)
    opt = SparseOptimizerConfig(embedx_threshold=10.0, embedx_lr=0.5)
    old = np.ones((2, lay.width), np.float32)
    old[0, lay.SHOW] = 20.0  # half the dims unlocked (>=T, >=2T)
    old[1, lay.SHOW] = 160.0  # all unlocked
    grads = np.full((2, lay.pull_width), 1.0, np.float32)  # phantom grads too
    new = np.asarray(
        sparse_update_rows(
            jnp.asarray(old), jnp.asarray(grads),
            jnp.zeros(2), jnp.zeros(2), lay, opt,
        )
    )
    co = lay.cvm_offset
    emb_old, emb_new = old[:, co : co + 8], new[:, co : co + 8]
    # row 0: first 4 dims trained, locked upper 4 bit-identical
    assert (emb_new[0, :4] != emb_old[0, :4]).all()
    np.testing.assert_array_equal(emb_new[0, 4:], emb_old[0, 4:])
    # row 1: everything trained
    assert (emb_new[1] != emb_old[1]).all()
    # g2 energy reflects only unlocked dims: row 0 accumulated half of row 1
    g2 = new[:, lay.embedx_g2_col] - old[:, lay.embedx_g2_col]
    assert abs(g2[0] - 0.5 * g2[1]) < 1e-6
