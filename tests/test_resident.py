"""Resident device feed (train/resident_step.py): parity with the classic
host-packed path on ragged data, plus mode coverage (eval, NaN guard,
wrap-around lockstep batches).

The resident tier reuses make_train_step's body, so any numeric divergence
must come from batch assembly — these tests pin assembly equivalence
through full train_pass outcomes (losses, trained table, AUC)."""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

S, B, N = 5, 8, 64


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def _write_files(tmp_path, seed=0, n=N, vocab=300):
    """Ragged slot files: 1-3 keys per slot (the line protocol forbids
    zero-count slots — generators pad, slot_parser.cc:205)."""
    rng = np.random.default_rng(seed)
    tmp_path.mkdir(parents=True, exist_ok=True)
    path = tmp_path / "part-000.txt"
    with open(path, "w") as f:
        for _ in range(n):
            parts = [f"1 {float(rng.integers(0, 2))}"]
            for _s in range(S):
                k = int(rng.integers(1, 4))
                vals = rng.integers(1, vocab, k)
                parts.append(f"{k} " + " ".join(str(v) for v in vals))
            f.write(" ".join(parts) + "\n")
    return [str(path)]


def _fresh(tmp_path, seed=0, batch_size=B, embedx=4):
    schema = _schema()
    layout = ValueLayout(embedx_dim=embedx)
    table = HostSparseTable(
        layout, SparseOptimizerConfig(embedx_threshold=0.0), n_shards=2, seed=0
    )
    ds = BoxPSDataset(schema, table, batch_size=batch_size, shuffle_mode="none")
    ds.set_filelist(_write_files(tmp_path, seed))
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=embedx, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S,
        batch_size=batch_size,
        layout=layout,
        sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    return ds, tr, table


def _run(tmp_path, resident: bool, n_batches, seed=0, eval_after=False):
    prev_flag = config.get_flag("enable_resident_feed")
    config.set_flag("enable_resident_feed", 1 if resident else 0)
    try:
        ds, tr, table = _fresh(tmp_path, seed)
        out = tr.train_pass(ds, n_batches=n_batches)
        trained = np.asarray(tr.trained_table())
        extra = None
        if eval_after:
            tr.set_test_mode(True)
            eval_out = tr.train_pass(ds, n_batches=n_batches)
            tr.set_test_mode(False)
            after = np.asarray(tr.trained_table())
            extra = (eval_out, after)
        ds.end_pass(tr.trained_table())
        return out, trained, tr, extra
    finally:
        config.set_flag("enable_resident_feed", prev_flag)


def test_resident_matches_classic_full_pass(tmp_path):
    """Losses, AUC, and the trained table agree with host packing (ragged
    records, empty slots, cross-slot duplicate keys)."""
    out_c, table_c, _, _ = _run(tmp_path / "c", resident=False, n_batches=8)
    out_r, table_r, _, _ = _run(tmp_path / "r", resident=True, n_batches=8)
    assert out_r["batches"] == out_c["batches"] == 8
    assert np.isclose(out_r["loss"], out_c["loss"], atol=1e-5)
    assert np.isclose(out_r["auc"], out_c["auc"], atol=1e-6)
    np.testing.assert_allclose(table_r, table_c, atol=1e-4)


def test_resident_wraparound_lockstep(tmp_path):
    """More batches than the pass holds: wrap-around indices must reuse
    records exactly like the classic path (equalized lockstep counts)."""
    out_c, table_c, _, _ = _run(tmp_path / "c", resident=False, n_batches=13)
    out_r, table_r, _, _ = _run(tmp_path / "r", resident=True, n_batches=13)
    assert np.isclose(out_r["loss"], out_c["loss"], atol=1e-5)
    np.testing.assert_allclose(table_r, table_c, atol=1e-4)


def test_resident_eval_mode_is_identity(tmp_path):
    """SetTestMode parity via the resident path: an eval pass changes
    neither the table nor the dense params, and still produces metrics."""
    out, trained, tr, extra = _run(
        tmp_path, resident=True, n_batches=4, eval_after=True
    )
    eval_out, after = extra
    np.testing.assert_array_equal(trained, after)
    assert 0.0 <= eval_out["auc"] <= 1.0 and eval_out["batches"] == 4


def test_resident_scan_chunking_matches_per_batch(tmp_path):
    """resident_scan_batches=1 (per-batch dispatch) and =4 (scan) produce
    identical results — the scan is pure restructuring."""
    prev_k = config.get_flag("resident_scan_batches")
    try:
        config.set_flag("resident_scan_batches", 1)
        out_1, table_1, _, _ = _run(tmp_path / "a", resident=True, n_batches=8)
        config.set_flag("resident_scan_batches", 4)
        out_4, table_4, _, _ = _run(tmp_path / "b", resident=True, n_batches=8)
    finally:
        config.set_flag("resident_scan_batches", prev_k)
    assert np.isclose(out_1["loss"], out_4["loss"], atol=1e-6)
    np.testing.assert_allclose(table_1, table_4, atol=1e-5)


def test_resident_nan_containment(tmp_path):
    """check_nan inside the scan: a poisoned batch is skipped (table
    untouched by it) and reported, matching the classic path."""
    schema = _schema()
    layout = ValueLayout(embedx_dim=4)

    results = {}
    prev_flag = config.get_flag("enable_resident_feed")
    for name, resident in (("classic", 0), ("resident", 1)):
        config.set_flag("enable_resident_feed", resident)
        try:
            table = HostSparseTable(
                layout, SparseOptimizerConfig(embedx_threshold=0.0), n_shards=2,
                seed=0,
            )
            ds = BoxPSDataset(schema, table, batch_size=B, shuffle_mode="none")
            # tiny vocab: batch 0's pushed keys reappear later -> trigger
            ds.set_filelist(_write_files(tmp_path / name, vocab=20))
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            model = DeepFM(
                num_slots=S, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )

            class PoisonModel:
                """Poison by data, deterministically across both paths:
                feats[..., 0] is log(show+1); a fresh table has show 0
                everywhere, so batch 0 is clean, and once batch 0's push
                lands, key reuse (tiny vocab) makes later batches carry
                positive shows -> NaN -> skipped. Exercises the gflat/param
                zeroing inside the lax.scan body, per iteration."""

                def init(self, rng):
                    return model.init(rng)

                def apply(self, p, feats, dense=None):
                    logits = model.apply(p, feats, dense)
                    trigger = jnp.sum(feats[:, :, 0], axis=1) > 0.3
                    return jnp.where(trigger, jnp.nan, logits)

            cfg = TrainStepConfig(
                num_slots=S, batch_size=B, layout=layout,
                sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
                auc_buckets=100, check_nan=True,
            )
            tr = CTRTrainer(PoisonModel(), cfg, dense_opt=optax.adam(1e-2))
            tr.init_params(jax.random.PRNGKey(0))
            out = tr.train_pass(ds, n_batches=4)
            results[name] = (out["nan_batches"], out["loss"])
        finally:
            config.set_flag("enable_resident_feed", prev_flag)
    # the trigger must actually fire (not a vacuous no-NaN comparison) and
    # batch 0 must stay clean (fresh table: shows are all zero)
    assert 0 < results["resident"][0] < 4
    assert results["classic"] == results["resident"]


def test_resident_registry_and_dump_consumers(tmp_path):
    """Registry + on_batch consumers see per-batch metrics identical to the
    classic path (stacked-slice delivery)."""
    from paddlebox_tpu.metrics.registry import MetricRegistry

    per_batch = {}
    prev_flag = config.get_flag("enable_resident_feed")
    for name, resident in (("classic", 0), ("resident", 1)):
        config.set_flag("enable_resident_feed", resident)
        try:
            schema = _schema()
            layout = ValueLayout(embedx_dim=4)
            table = HostSparseTable(
                layout, SparseOptimizerConfig(embedx_threshold=0.0), n_shards=2,
                seed=0,
            )
            ds = BoxPSDataset(schema, table, batch_size=B, shuffle_mode="none")
            ds.set_filelist(_write_files(tmp_path / name))
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            model = DeepFM(
                num_slots=S, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )
            cfg = TrainStepConfig(
                num_slots=S, batch_size=B, layout=layout,
                sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
                auc_buckets=100,
            )
            reg = MetricRegistry()
            reg.init_metric("auc", "auc", phase=-1)
            tr = CTRTrainer(
                model, cfg, dense_opt=optax.adam(1e-2), metric_registry=reg
            )
            tr.init_params(jax.random.PRNGKey(0))
            seen = []
            tr.train_pass(
                ds, n_batches=4,
                on_batch=lambda i, m: seen.append((i, float(m["loss"]))),
            )
            per_batch[name] = (seen, reg.get_metric("auc")["auc"])
        finally:
            config.set_flag("enable_resident_feed", prev_flag)
    (seen_c, auc_c), (seen_r, auc_r) = per_batch["classic"], per_batch["resident"]
    assert [i for i, _ in seen_r] == [i for i, _ in seen_c] == list(range(4))
    for (_, lc), (_, lr) in zip(seen_c, seen_r):
        assert np.isclose(lc, lr, atol=1e-5)
    assert np.isclose(auc_c, auc_r, atol=1e-6)


def test_resident_mesh_matches_host_packed_mesh(tmp_path):
    """Single-host mesh: the device-built route buckets (sort-based shard
    grouping) train to the same losses/table as the host-packed
    pack_batch_sharded path — internal bucket order may differ, sums
    must not."""
    from paddlebox_tpu.parallel import make_mesh

    from paddlebox_tpu.metrics.registry import MetricRegistry

    def run(resident):
        prev = config.get_flag("enable_resident_feed")
        config.set_flag("enable_resident_feed", resident)
        try:
            schema = _schema()
            layout = ValueLayout(embedx_dim=4)
            table = HostSparseTable(
                layout, SparseOptimizerConfig(embedx_threshold=0.0),
                n_shards=4, seed=0,
            )
            plan = make_mesh(4)
            ds = BoxPSDataset(
                schema, table, batch_size=16, n_mesh_shards=4,
                shuffle_mode="none",
            )
            ds.set_filelist(_write_files(tmp_path / f"r{resident}", n=64))
            ds.load_into_memory()
            ds.begin_pass(round_to=16)
            model = DeepFM(
                num_slots=S, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )
            cfg = TrainStepConfig(
                num_slots=S, batch_size=4, layout=layout,
                sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
                auc_buckets=100, axis_name=plan.axis,
            )
            reg = MetricRegistry()
            reg.init_metric("auc", "auc", phase=-1)
            tr = CTRTrainer(
                model, cfg, dense_opt=optax.adam(1e-2), plan=plan,
                metric_registry=reg,
            )
            tr.init_params(jax.random.PRNGKey(0))
            out = tr.train_pass(ds)
            return out, np.asarray(tr.trained_table()), reg.get_metric("auc")
        finally:
            config.set_flag("enable_resident_feed", prev)

    out_h, table_h, reg_h = run(0)
    out_r, table_r, reg_r = run(1)
    assert out_r["batches"] == out_h["batches"]
    assert np.isclose(out_r["loss"], out_h["loss"], atol=1e-5)
    assert np.isclose(out_r["auc"], out_h["auc"], atol=1e-6)
    np.testing.assert_allclose(table_r, table_h, atol=1e-4)
    # consumers must see EVERY device's slice of each batch (a wrong
    # scan-axis spec would hand the registry 1/n_dev of the data)
    assert reg_r["ins_num"] == reg_h["ins_num"] == 64
    assert np.isclose(reg_r["auc"], reg_h["auc"], atol=1e-6)


def test_resident_mesh_dense_features_match(tmp_path):
    """Dense float features flow through the mesh resident build (a feed
    that silently dropped them would diverge from the host-packed path)."""
    from paddlebox_tpu.parallel import make_mesh

    def write(tmp):
        rng = np.random.default_rng(3)
        tmp.mkdir(parents=True, exist_ok=True)
        p = tmp / "d.txt"
        with open(p, "w") as f:
            for _ in range(32):
                ks = rng.integers(1, 100, S)
                dvals = rng.random(3)
                f.write(
                    f"1 {int(ks[0]) % 2}.0 "
                    + "3 " + " ".join(f"{v:.3f}" for v in dvals) + " "
                    + " ".join(f"1 {k}" for k in ks)
                    + "\n"
                )
        return [str(p)]

    schema = SlotSchema(
        [
            SlotInfo("label", type="float", dense=True, dim=1),
            SlotInfo("dfeat", type="float", dense=True, dim=3),
        ]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )

    class DenseAwareModel:
        def __init__(self, base):
            self.base = base

        def init(self, rng):
            p = self.base.init(rng)
            p["dw"] = jnp.ones((3,), jnp.float32) * 0.5
            return p

        def apply(self, p, feats, dense=None):
            logit = self.base.apply(
                {k: v for k, v in p.items() if k != "dw"}, feats, None
            )
            if dense is not None:
                logit = logit + dense @ p["dw"]
            return logit

    def run(resident):
        prev = config.get_flag("enable_resident_feed")
        config.set_flag("enable_resident_feed", resident)
        try:
            layout = ValueLayout(embedx_dim=4)
            table = HostSparseTable(
                layout, SparseOptimizerConfig(embedx_threshold=0.0),
                n_shards=4, seed=0,
            )
            plan = make_mesh(4)
            ds = BoxPSDataset(
                schema, table, batch_size=16, n_mesh_shards=4,
                shuffle_mode="none",
            )
            ds.set_filelist(write(tmp_path / f"r{resident}"))
            ds.load_into_memory()
            ds.begin_pass(round_to=16)
            base = DeepFM(
                num_slots=S, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )
            cfg = TrainStepConfig(
                num_slots=S, batch_size=4, layout=layout,
                sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
                auc_buckets=100, axis_name=plan.axis,
            )
            tr = CTRTrainer(
                DenseAwareModel(base), cfg, dense_opt=optax.adam(1e-2),
                plan=plan, dense_slot="dfeat", dense_dim=3,
            )
            tr.init_params(jax.random.PRNGKey(0))
            out = tr.train_pass(ds)
            return out, np.asarray(tr.trained_table())
        finally:
            config.set_flag("enable_resident_feed", prev)

    out_h, table_h = run(0)
    out_r, table_r = run(1)
    assert np.isclose(out_r["loss"], out_h["loss"], atol=1e-5)
    np.testing.assert_allclose(table_r, table_h, atol=1e-4)


def test_prepare_pass_prefreezes_shapes(tmp_path):
    """After prepare_pass over the full partition, train_pass must not grow
    the pads or build a second superstep (the warm-start contract bench.py
    relies on to keep compiles out of its timed region)."""
    ds, tr, _ = _fresh(tmp_path)
    tr.prepare_pass(ds, n_batches=8)
    rp = tr._get_resident(ds)
    pads_before = (rp.L_pad, rp.U_pad)
    assert pads_before[0] > 0 and pads_before[1] > 0
    tr.train_pass(ds, n_batches=8)
    assert (rp.L_pad, rp.U_pad) == pads_before
    assert len(tr._sstep_cache) == 1  # one train superstep, no regrowth
