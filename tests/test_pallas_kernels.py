"""Pallas sparse kernels (interpret mode on CPU; compiled path runs on TPU
via bench.py with use_pallas_sparse=1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.ops.pallas_kernels import (
    backend_is_tpu,
    pull_rows_pallas,
    write_rows_pallas,
)


def test_gather_matches_take():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(128, 22)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 128, 64).astype(np.int32))  # dups fine
    got = pull_rows_pallas(table, rows, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(table)[np.asarray(rows)], rtol=1e-6
    )


def test_writeback_matches_scatter_set():
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(96, 20)).astype(np.float32))
    uniq = jnp.asarray(rng.permutation(96)[:24].astype(np.int32))
    new = jnp.asarray(rng.normal(size=(24, 20)).astype(np.float32))
    got = write_rows_pallas(jnp.array(table), uniq, new, interpret=True)
    want = np.asarray(table).copy()
    want[np.asarray(uniq)] = np.asarray(new)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_writeback_repeated_pad_row_identical_content():
    """The packer repeats the padding row with identical updated contents —
    repeated writes of the same value are well-defined."""
    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    pad = 31
    rows = jnp.asarray([3, pad, 7, pad, pad, pad, pad, pad], np.int32)
    pad_content = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    new = jnp.stack(
        [jnp.full((8,), 1.0), pad_content, jnp.full((8,), 2.0)]
        + [pad_content] * 5
    ).astype(jnp.float32)
    got = np.asarray(write_rows_pallas(jnp.array(table), rows, new, interpret=True))
    np.testing.assert_allclose(got[3], np.full(8, 1.0))
    np.testing.assert_allclose(got[7], np.full(8, 2.0))
    np.testing.assert_allclose(got[pad], np.asarray(pad_content), rtol=1e-6)


def test_flag_gating():
    """The legacy use_pallas_sparse opt-in (now the builtin plan's fallback
    preference) must not engage off-TPU, with unaligned widths, or with
    unaligned index counts — the plan's eligibility clamp, exercised
    through the same _impl_for lookup the pull/push ops use."""
    from paddlebox_tpu import config
    from paddlebox_tpu.ops.kernel_plan import invalidate_plan
    from paddlebox_tpu.ops.pull_push import _impl_for

    t_ok = jnp.zeros((64, 128))
    t_narrow = jnp.zeros((64, 21))
    on_tpu = backend_is_tpu()  # conftest forces CPU, but stay portable
    config.set_flag("kernel_plan_path", "off")  # builtin defaults only
    config.set_flag("use_pallas_sparse", True)
    invalidate_plan()
    try:
        assert (_impl_for("pull", t_ok, 64) == "pallas") == on_tpu
        assert _impl_for("pull", t_narrow, 64) == "native"  # width unaligned
        assert _impl_for("pull", t_ok, 63) == "native"      # U not 8-aligned
        # pallas push is per-row SET: without dedup'd (unique) rows it
        # must clamp to native even where pull would engage
        assert _impl_for("push", t_ok, 64, unique_rows=False) == "native"
    finally:
        config.set_flag("use_pallas_sparse", False)
        config.set_flag("kernel_plan_path", "auto")
        invalidate_plan()
    assert _impl_for("pull", t_ok, 64) == "native"          # flag off
