"""Native host table store: RAM tier parity, disk spill tier, throughput.

The reference's host table is the closed libbox_ps.so mem/SSD store
(box_wrapper.cc:1325 LoadSSD2Mem); these tests pin the open C++ analog
(csrc/host_table.cc): same observable behavior as the Python fallback,
plus the disk tier the fallback doesn't have.
"""

import os

import numpy as np
import pytest

from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(
    initial_range=0.1, show_clk_decay=0.5, shrink_threshold=1.0
)


def test_native_backend_selected():
    t = HostSparseTable(LAYOUT, OPT, n_shards=4)
    assert t.native


def test_init_deterministic_and_in_range():
    keys = np.array([7, 123456789, 1 << 60], dtype=np.uint64)
    t1 = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=3)
    t2 = HostSparseTable(LAYOUT, OPT, n_shards=8, seed=3)  # sharding-independent
    r1, r2 = t1.pull_or_create(keys), t2.pull_or_create(keys[::-1])[::-1]
    np.testing.assert_array_equal(r1, r2)
    assert np.all(np.abs(r1[:, LAYOUT.embed_w_col]) <= 0.1)
    emb = r1[:, LAYOUT.embedx_col : LAYOUT.embedx_col + LAYOUT.embedx_dim]
    assert np.all(np.abs(emb) <= 0.1)
    assert not np.allclose(emb, 0.0)
    # optimizer-state columns start at zero
    assert np.all(r1[:, LAYOUT.SHOW] == 0)
    # different seed -> different init
    t3 = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=4)
    assert not np.array_equal(t3.pull_or_create(keys), r1)


def test_spill_and_promote(tmp_path):
    t = HostSparseTable(
        LAYOUT, OPT, n_shards=4, seed=0, spill_dir=str(tmp_path / "spill")
    )
    keys = np.arange(1, 2001, dtype=np.uint64)
    rows = t.pull_or_create(keys)
    rows[:, LAYOUT.SHOW] = 100.0
    t.push(keys, rows)
    assert t.mem_rows == 2000 and t.disk_rows == 0

    spilled = t.spill_cold(500)
    assert spilled == 1500
    assert t.mem_rows == 500 and t.disk_rows == 1500
    assert len(t) == 2000

    # promotion returns the exact spilled rows
    got = t.pull_or_create(keys)
    np.testing.assert_array_equal(got, rows)
    assert t.disk_rows == 0 and t.mem_rows == 2000


def test_spill_catchup_decay(tmp_path):
    t = HostSparseTable(
        LAYOUT, OPT, n_shards=2, seed=0, spill_dir=str(tmp_path / "spill")
    )
    keys = np.array([10, 20], dtype=np.uint64)
    rows = t.pull_or_create(keys)
    rows[:, LAYOUT.SHOW] = [64.0, 1.5]  # key 20 will lazily shrink
    t.push(keys, rows)
    t.save_base(str(tmp_path / "b"))  # clears touched so spill evicts all
    t.spill_cold(0)
    assert t.disk_rows == 2
    # two pass boundaries of decay (0.5 each) happen while spilled
    t.decay_and_shrink()
    t.decay_and_shrink()
    got = t.pull_or_create(keys)
    # key 10: 64 * 0.25 = 16 survives; key 20: 1.5*0.25 < 1.0 -> lazily
    # dropped and recreated fresh (show back to 0)
    assert got[0, LAYOUT.SHOW] == pytest.approx(16.0)
    assert got[1, LAYOUT.SHOW] == 0.0


def test_delta_save_sees_spilled_touched_rows(tmp_path):
    t = HostSparseTable(
        LAYOUT, OPT, n_shards=2, seed=0, spill_dir=str(tmp_path / "spill")
    )
    keys = np.arange(1, 101, dtype=np.uint64)
    rows = t.pull_or_create(keys)
    t.push(keys, rows + 1.0)  # all touched
    t.spill_cold(0)  # touched rows forced to disk, bit preserved
    assert t.disk_rows == 100
    n = t.save_delta(str(tmp_path / "delta"))
    assert n == 100
    # delta cleared the touched bits, including on-disk ones
    assert t.save_delta(str(tmp_path / "d2")) == 0
    # round-trip through a fresh table
    t2 = HostSparseTable(LAYOUT, OPT, n_shards=2)
    t2.apply_delta(str(tmp_path / "delta"))
    np.testing.assert_allclose(t2.pull_or_create(keys), rows + 1.0)


def test_train_pass_with_table_over_ram_cap(tmp_path):
    """A pass trains correctly while the host table exceeds mem_cap_rows:
    pass keys promote from disk at finalize, writeback lands, cold rows
    re-spill at the pass-end hook."""
    t = HostSparseTable(
        LAYOUT,
        SparseOptimizerConfig(initial_range=0.1, embedx_threshold=0.0),
        n_shards=4,
        seed=0,
        spill_dir=str(tmp_path / "spill"),
        mem_cap_rows=300,
    )
    # pre-populate 1000 keys then evict: table is 3x over its RAM cap
    all_keys = np.arange(1, 1001, dtype=np.uint64)
    base = t.pull_or_create(all_keys)
    t.maybe_spill()
    assert t.mem_rows <= 300 and t.disk_rows >= 700

    # a pass touching a 200-key working subset
    pass_keys = all_keys[100:300]
    ws = PassWorkingSet(n_mesh_shards=1)
    ws.add_keys(pass_keys)
    dev = ws.finalize(t, round_to=64)
    flat = dev.reshape(-1, LAYOUT.width)
    np.testing.assert_array_equal(flat[ws.lookup(pass_keys)], base[100:300])

    flat[ws.lookup(pass_keys)] += 2.0
    ws.writeback(flat.reshape(dev.shape))
    spilled = t.maybe_spill()
    assert t.mem_rows <= 300
    assert spilled > 0
    # trained values survive the spill round-trip
    got = t.pull_or_create(pass_keys)
    np.testing.assert_allclose(got, base[100:300] + 2.0)
    # untouched keys unchanged
    np.testing.assert_array_equal(t.pull_or_create(all_keys[:100]), base[:100])


def test_python_fallback_matches_contract(tmp_path, monkeypatch):
    """The dict fallback still honors the same surface (no spill)."""
    monkeypatch.setenv("PBOX_NATIVE_TABLE", "0")
    t = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=1)
    assert not t.native
    keys = np.array([1, 2, 3], dtype=np.uint64)
    rows = t.pull_or_create(keys)
    np.testing.assert_array_equal(t.pull_or_create(keys), rows)
    with pytest.raises(RuntimeError):
        HostSparseTable(LAYOUT, OPT, spill_dir=str(tmp_path / "s"))
    with pytest.raises(RuntimeError):
        t.spill_cold(10)


def test_pull_or_create_throughput():
    """The native store must beat the measured dict-store wall (~160k/s) by
    a wide margin; the VERDICT target is >=10M keys/s on unique pulls."""
    import time

    t = HostSparseTable(ValueLayout(embedx_dim=16), OPT, n_shards=64, seed=0)
    n = 2_000_000
    keys = np.random.default_rng(0).permutation(np.arange(1, n + 1)).astype(np.uint64)
    t0 = time.perf_counter()
    rows = t.pull_or_create(keys)
    create_s = time.perf_counter() - t0
    pull_s = min(
        (lambda t0: (t.pull_or_create(keys), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(3)
    )
    t0 = time.perf_counter()
    t.push(keys, rows)
    push_s = time.perf_counter() - t0
    rate = n / max(pull_s, 1e-9)
    print(
        f"\nnative table: create {n/create_s/1e6:.1f}M/s, "
        f"pull {rate/1e6:.1f}M/s, push {n/push_s/1e6:.1f}M/s"
    )
    if rate <= 4e6 and os.getloadavg()[0] > os.cpu_count():
        # a throughput floor is meaningless on a contended machine (the
        # store threads across shards; a saturated box halves its rate) —
        # skip rather than flake, but only when load proves contention
        pytest.skip(
            f"machine contended (load {os.getloadavg()[0]:.1f} > "
            f"{os.cpu_count()} cpus); pull rate {rate/1e6:.1f}M/s not probative"
        )
    assert rate > 4e6, f"native pull rate {rate/1e6:.1f}M/s below floor"


def test_distributed_ws_over_spilled_table(tmp_path):
    """DistributedWorkingSet.finalize promotes this host's owned keys from
    the disk tier exactly like the local working set does."""
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet

    class _OneRankTransport:
        rank, n_ranks = 0, 1

        def alltoall(self, payloads, tag):
            return list(payloads)

        def allgather(self, payload, tag):
            return [payload]

        def allreduce_max(self, value, tag):
            return int(value)

    t = HostSparseTable(
        LAYOUT, OPT, n_shards=4, seed=0, spill_dir=str(tmp_path / "spill")
    )
    keys = np.arange(1, 501, dtype=np.uint64)
    base = t.pull_or_create(keys)
    t.push(keys, base + 1.0)
    t.save_base(str(tmp_path / "b"))  # clear touched so everything spills
    t.spill_cold(0)
    assert t.disk_rows == 500

    dws = DistributedWorkingSet(_OneRankTransport(), n_mesh_shards=2)
    dws.add_keys(keys[:200])
    dev = dws.finalize(t, round_to=32)
    flat = dev.reshape(-1, LAYOUT.width)
    np.testing.assert_array_equal(
        flat[dws.lookup(keys[:200])], base[:200] + 1.0
    )
    # untouched keys stayed on disk; the pass promoted only what it needed
    assert t.disk_rows == 300
