"""ReplicaCache + InputTable (B16) and extended/expand pull (B12) tests."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.ops import pull_sparse_rows_extended, push_sparse_rows
from paddlebox_tpu.ops.pull_push import sparse_update_rows
from paddlebox_tpu.table import (
    FeatureType,
    HostSparseTable,
    InputTable,
    ReplicaCache,
    SparseOptimizerConfig,
    ValueLayout,
    pull_cache_value,
)


# ---- value layout with expand block ------------------------------------

def test_expand_layout_columns():
    lay = ValueLayout(embedx_dim=8, expand_embed_dim=4)
    assert lay.expand_dim == 4
    assert lay.expand_col == lay.cvm_offset + 8
    assert lay.embed_g2_col == lay.cvm_offset + 12
    assert lay.expand_g2_col == lay.embed_g2_col + 2
    assert lay.width == lay.cvm_offset + 8 + 4 + 3
    assert lay.pull_width == lay.cvm_offset + 8
    assert lay.extended_push_width == lay.pull_width + 4
    # no expand: unchanged classic layout
    base = ValueLayout(embedx_dim=8)
    assert base.expand_dim == 0 and base.width == base.cvm_offset + 8 + 2
    with pytest.raises(ValueError):
        _ = base.expand_g2_col
    # SHARE_EMBEDDING folds expand into cvm block: no trailing expand block
    share = ValueLayout(embedx_dim=8, expand_embed_dim=4,
                        feature_type=FeatureType.SHARE_EMBEDDING)
    assert share.expand_dim == 0 and share.cvm_offset == 6


def test_extended_pull_and_push():
    lay = ValueLayout(embedx_dim=4, expand_embed_dim=3)
    opt = SparseOptimizerConfig(embedx_threshold=2.0, embed_lr=0.1, embedx_lr=0.1)
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(16, lay.width)).astype(np.float32))
    # row shows: rows 0..7 active (show >= 2), rows 8+ inactive
    table = table.at[:, lay.SHOW].set(jnp.where(jnp.arange(16) < 8, 5.0, 0.0))
    rows = jnp.array([1, 3, 9], jnp.int32)

    rec, expand = pull_sparse_rows_extended(table, rows, lay, opt.embedx_threshold)
    assert rec.shape == (3, lay.pull_width)
    assert expand.shape == (3, 3)
    np.testing.assert_allclose(
        expand[0], table[1, lay.expand_col : lay.expand_col + 3], rtol=1e-6
    )
    np.testing.assert_array_equal(expand[2], np.zeros(3))  # gated

    # push with expand grads: expand weights move for active rows only
    grads = jnp.ones((3, lay.extended_push_width), jnp.float32)
    new_table = push_sparse_rows(
        table, rows, grads, jnp.ones(3), jnp.zeros(3), lay, opt
    )
    before = np.asarray(table)[:, lay.expand_col : lay.expand_col + 3]
    after = np.asarray(new_table)[:, lay.expand_col : lay.expand_col + 3]
    assert not np.allclose(before[1], after[1])
    np.testing.assert_allclose(before[9], after[9])  # inactive: untouched
    # expand g2 accumulated for active rows
    assert np.asarray(new_table)[1, lay.expand_g2_col] > np.asarray(table)[1, lay.expand_g2_col]

    # plain (non-extended) push on an expand layout leaves expand block alone
    new2 = push_sparse_rows(
        table, rows, grads[:, : lay.push_width], jnp.ones(3), jnp.zeros(3), lay, opt
    )
    np.testing.assert_allclose(
        np.asarray(new2)[:, lay.expand_col : lay.expand_col + 3], before, rtol=1e-6
    )


def test_host_table_inits_expand_block():
    lay = ValueLayout(embedx_dim=4, expand_embed_dim=3)
    opt = SparseOptimizerConfig(initial_range=0.1)
    t = HostSparseTable(lay, opt, n_shards=2, seed=0)
    rows = t.pull_or_create(np.arange(1, 50, dtype=np.uint64))
    ex = rows[:, lay.expand_col : lay.expand_col + 3]
    assert np.abs(ex).max() > 0 and np.abs(ex).max() <= 0.1
    assert (rows[:, lay.expand_g2_col] == 0).all()


# ---- replica cache -----------------------------------------------------

def test_replica_cache_threaded_add_and_gather():
    cache = ReplicaCache(dim=4)
    ids = {}

    def add(tid):
        for i in range(50):
            ids[(tid, i)] = cache.add_items(np.full(4, tid * 100 + i, np.float32))

    ts = [threading.Thread(target=add, args=(t,)) for t in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(cache) == 200
    dev = cache.to_device()
    # every returned id maps to the row that was added under it
    for (tid, i), rid in ids.items():
        np.testing.assert_array_equal(
            np.asarray(dev[rid]), np.full(4, tid * 100 + i, np.float32)
        )
    got = pull_cache_value(dev, jnp.array([ids[(2, 7)], ids[(0, 0)]]))
    np.testing.assert_array_equal(np.asarray(got[0]), np.full(4, 207.0))

    with pytest.raises(ValueError):
        cache.add_items(np.zeros(5, np.float32))


def test_replica_cache_add_items_rejects_multirow_block():
    """add_items is a one-row API: a [n>1, d] block must raise (it used to
    be silently flattened into garbage ids), and the error names the bulk
    path. [1, dim] still squeezes for parser convenience."""
    cache = ReplicaCache(dim=4)
    cache.add_items(np.zeros(4, np.float32))
    cache.add_items(np.zeros((1, 4), np.float32))
    assert len(cache) == 2
    with pytest.raises(ValueError, match="add_batch"):
        cache.add_items(np.zeros((2, 2), np.float32))
    with pytest.raises(ValueError, match="add_batch"):
        cache.add_items(np.zeros((3, 4), np.float32))
    assert len(cache) == 2  # rejected blocks appended nothing


def test_replica_cache_add_batch_and_serve_stats():
    from paddlebox_tpu.utils.monitor import STAT_GET

    cache = ReplicaCache(dim=4)
    ids = cache.add_batch(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_array_equal(ids, [0, 1, 2])
    ids2 = cache.add_batch(np.ones((2, 4), np.float32))
    np.testing.assert_array_equal(ids2, [3, 4])
    assert len(cache) == 5
    host = cache.host_array()
    assert host.shape == (5, 4)
    np.testing.assert_array_equal(host[1], [4, 5, 6, 7])
    with pytest.raises(ValueError, match="dim-mismatched"):
        cache.add_batch(np.zeros((2, 3), np.float32))
    with pytest.raises(ValueError, match="add_items"):
        cache.add_batch(np.zeros(4, np.float32))  # 1-D: not a block
    cache.publish_serve_stats()
    assert STAT_GET("serve.replica_rows") == 5
    assert STAT_GET("serve.replica_mem_mb") > 0


def test_input_table_default_miss_and_upsert():
    t = InputTable(dim=3)
    assert len(t) == 1  # default row
    a = t.add_index_data("ad-1", [1, 2, 3])
    b = t.add_index_data("ad-2", [4, 5, 6])
    assert (a, b) == (1, 2)
    assert t.get_index_offset("ad-2") == 2
    assert t.get_index_offset("nope") == 0 and t.miss == 1
    # upsert keeps row id
    assert t.add_index_data("ad-1", [9, 9, 9]) == 1
    got = t.lookup_input(np.array([0, 1, 2]))
    np.testing.assert_array_equal(got[0], np.zeros(3))
    np.testing.assert_array_equal(got[1], [9, 9, 9])
    dev = t.to_device()
    np.testing.assert_array_equal(np.asarray(pull_cache_value(dev, jnp.array([2]))[0]), [4, 5, 6])


# ---- feed integration ---------------------------------------------------

def test_replica_cache_line_parser_end_to_end(tmp_path):
    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.data.parser import ReplicaCacheLineParser

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1),
         SlotInfo("cache_idx"), SlotInfo("s0"), SlotInfo("s1")],
        label_slot="label",
    )
    cache = ReplicaCache(dim=2)
    # two cache groups; records after each '#' line use its row
    lines = [
        "# 1.5 2.5",
        "1 1.0 1 7 1 11 1 21",
        "1 0.0 1 7 1 12 1 22",
        "# 3.5 4.5",
        "1 1.0 1 7 1 13 1 23",
    ]
    p = tmp_path / "part-000.txt"
    p.write_text("\n".join(lines) + "\n")

    lay = ValueLayout(embedx_dim=4)
    table = HostSparseTable(lay, SparseOptimizerConfig(), n_shards=2)
    ds = BoxPSDataset(
        schema, table, batch_size=3, read_threads=1,
        line_parser=ReplicaCacheLineParser(cache, "cache_idx"),
        drop_remainder=False,
    )
    ds.set_date("20260101")
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert len(cache) == 2
    assert ds.memory_data_size() == 3
    # cache_idx slot (sparse slot 0) carries row ids 0,0,1
    got = sorted(int(r.slot_keys(0)[0]) for r in ds.records)
    assert got == [0, 0, 1]
    dev = cache.to_device()
    np.testing.assert_array_equal(np.asarray(pull_cache_value(dev, jnp.array([1]))[0]), [3.5, 4.5])


def test_replica_cache_parser_file_boundary_and_dim_mismatch(tmp_path):
    """A file without a leading '#' line must raise in strict mode (no
    state leaking from the previous file on the same thread); oversize
    cache lines must raise."""
    from paddlebox_tpu import config
    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.data.parser import ReplicaCacheLineParser

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1),
         SlotInfo("cache_idx"), SlotInfo("s0")],
        label_slot="label",
    )
    (tmp_path / "a.txt").write_text("# 1 2\n1 1.0 1 7 1 11\n")
    (tmp_path / "b.txt").write_text("1 1.0 1 7 1 12\n")  # no '#' line
    cache = ReplicaCache(dim=2)
    lay = ValueLayout(embedx_dim=4)
    table = HostSparseTable(lay, SparseOptimizerConfig(), n_shards=2)
    ds = BoxPSDataset(
        schema, table, batch_size=2, read_threads=1,
        line_parser=ReplicaCacheLineParser(cache, "cache_idx"),
    )
    ds.set_date("20260101")
    ds.set_filelist([str(tmp_path / "a.txt"), str(tmp_path / "b.txt")])
    prev = config.get_flag("data_quarantine")
    config.set_flag("data_quarantine", 0)  # strict: first bad line is fatal
    try:
        with pytest.raises(ValueError, match="cache line"):
            ds.load_into_memory()
    finally:
        config.set_flag("data_quarantine", prev)

    parser = ReplicaCacheLineParser(ReplicaCache(dim=2), "cache_idx")
    parser.begin_file("x")
    with pytest.raises(ValueError):  # 3 floats into a dim-2 cache
        parser("# 1 2 3", schema)


def test_replica_cache_parser_quarantine_mode(tmp_path):
    """The two ReplicaCacheLineParser failure modes — record line before
    any '#' cache line, and a cache-dim mismatch — through
    load_into_memory: quarantined (counted + dead-lettered) with
    data_quarantine on, fatal with it off (covered above)."""
    from paddlebox_tpu.data import (
        BoxPSDataset, SlotInfo, SlotSchema, read_dead_letter,
    )
    from paddlebox_tpu.data.parser import ReplicaCacheLineParser

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1),
         SlotInfo("cache_idx"), SlotInfo("s0")],
        label_slot="label",
    )
    lines = [
        "1 1.0 1 7 1 11",   # record BEFORE any '#' line: quarantined
        "# 1 2 3",          # 3 floats into a dim-2 cache: quarantined
        "# 1 2",            # good cache row 0
        "1 1.0 1 7 1 12",   # good record, uses cache row 0
    ]
    p = tmp_path / "a.txt"
    p.write_text("\n".join(lines) + "\n")
    cache = ReplicaCache(dim=2)
    lay = ValueLayout(embedx_dim=4)
    table = HostSparseTable(lay, SparseOptimizerConfig(), n_shards=2)
    ds = BoxPSDataset(
        schema, table, batch_size=2, read_threads=1,
        line_parser=ReplicaCacheLineParser(cache, "cache_idx"),
        quarantine_dir=str(tmp_path / "q"),
    )
    ds.set_date("20260101")
    ds.set_filelist([str(p)])
    ds.load_into_memory()

    st = ds.stats
    assert (st.lines, st.parsed, st.skipped_benign, st.bad_lines) == (4, 1, 1, 2)
    assert st.bad_by_file == {str(p): 2}
    assert len(cache) == 1 and ds.memory_data_size() == 1
    # the surviving record carries cache row 0 in the cache slot
    assert int(ds.records[0].slot_keys(0)[0]) == 0
    dl = read_dead_letter(st.dead_letter)
    assert dl["summary"]["bad_lines"] == 2
    assert [e["line"] for e in dl["entries"]] == [lines[0], lines[1]]
    assert [e["line_no"] for e in dl["entries"]] == [1, 2]
    assert ds.admission_report()["poisoned"]  # 2/4 lines over the default


# ---- extended pull through the train step (single device vs mesh) -------

class ExpandModel:
    """Tiny model consuming (slot_feats, dense, expand[B,S,E])."""

    def __init__(self, num_slots, feat_width, expand_dim):
        self.num_slots, self.feat_width, self.expand_dim = (
            num_slots, feat_width, expand_dim,
        )

    def init(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        return {
            "w": jax.random.normal(k1, (self.num_slots * self.feat_width,)) * 0.05,
            "we": jax.random.normal(k2, (self.num_slots * self.expand_dim,)) * 0.05,
        }

    def apply(self, p, slot_feats, dense=None, expand=None):
        B = slot_feats.shape[0]
        return (
            slot_feats.reshape(B, -1) @ p["w"]
            + expand.reshape(B, -1) @ p["we"]
        )


def test_extended_train_step_single_vs_mesh():
    import jax
    import optax

    from paddlebox_tpu.data.device_pack import pack_batch, pack_batch_sharded
    from paddlebox_tpu.data.slot_record import build_batch
    from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.table import PassWorkingSet
    from paddlebox_tpu.train import TrainStepConfig, make_train_step
    from paddlebox_tpu.train.sharded_step import (
        init_sharded_train_state,
        make_sharded_train_step,
    )
    from paddlebox_tpu.train.train_step import init_train_state, jit_train_step
    from test_train_step import synth_records

    S, B, NDEV = 4, 32, 8
    lay = ValueLayout(embedx_dim=4, expand_embed_dim=3)
    opt = SparseOptimizerConfig(
        embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.05,
        show_clk_decay=1.0, shrink_threshold=0.0,
    )
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )
    rng = np.random.default_rng(3)
    table = HostSparseTable(lay, opt, n_shards=4, seed=0)
    recs = synth_records(rng, B * 4, schema)
    ws = PassWorkingSet(n_mesh_shards=NDEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev_table = ws.finalize(table, round_to=32)

    model = ExpandModel(S, lay.pull_width, lay.expand_dim)
    params = model.init(jax.random.PRNGKey(1))
    paramsN = model.init(jax.random.PRNGKey(1))
    dense_opt = optax.adam(1e-2)

    cfg1 = TrainStepConfig(num_slots=S, batch_size=B, layout=lay,
                           sparse_opt=opt, auc_buckets=1000, use_expand=True)
    step1 = jit_train_step(make_train_step(model.apply, dense_opt, cfg1))
    st1 = init_train_state(
        jnp.asarray(dev_table.reshape(-1, lay.width)), params, dense_opt, 1000
    )
    t0 = np.asarray(st1.table).copy()

    plan = make_mesh(NDEV)
    cfgN = TrainStepConfig(num_slots=S, batch_size=B // NDEV, layout=lay,
                           sparse_opt=opt, auc_buckets=1000,
                           axis_name=plan.axis, use_expand=True)
    stepN = make_sharded_train_step(model.apply, dense_opt, cfgN, plan)
    stN = init_sharded_train_state(plan, dev_table, paramsN, dense_opt, 1000)

    for i in range(4):
        batch_recs = [recs[(i * B + j) % len(recs)] for j in range(B)]
        batch = build_batch(batch_recs, schema)
        db1 = pack_batch(batch, ws, schema, bucket=64)
        st1, m1 = step1(st1, {k: jnp.asarray(v) for k, v in db1.as_dict().items()})
        dbN = pack_batch_sharded(batch, ws, schema, NDEV, bucket=32)
        feed = {k: jax.device_put(v, plan.batch_sharding) for k, v in dbN.as_dict().items()}
        stN, mN = stepN(stN, feed)
        np.testing.assert_allclose(float(m1["loss"]), float(mN["loss"]), rtol=3e-4)

    t1 = np.asarray(st1.table)
    # expand block trained (changed) for touched rows
    exp0 = t0[:, lay.expand_col : lay.expand_col + lay.expand_dim]
    exp1 = t1[:, lay.expand_col : lay.expand_col + lay.expand_dim]
    assert np.abs(exp1 - exp0).max() > 1e-5
    # expand g2 accumulated
    assert t1[:, lay.expand_g2_col].max() > 0
    # sharded table matches single-device row-for-row
    tN = np.asarray(stN.table).reshape(-1, lay.width)
    np.testing.assert_allclose(t1, tN, rtol=1e-3, atol=5e-4)
