"""Sparse table tests: host store, pass working set, persistence."""

import numpy as np
import pytest

from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.table.sparse_table import key_to_shard


LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(initial_range=0.1, show_clk_decay=0.5, shrink_threshold=1.0)


def test_layout_columns():
    lay = ValueLayout(embedx_dim=8)
    assert lay.cvm_offset == 3
    assert lay.embed_w_col == 2
    assert lay.embedx_col == 3
    assert lay.width == 3 + 8 + 2
    assert lay.pull_width == 11


def test_pull_or_create_and_persistence(tmp_path):
    t = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=1)
    keys = np.array([1, 2, 3, 1 << 50], dtype=np.uint64)
    rows = t.pull_or_create(keys)
    assert rows.shape == (4, LAYOUT.width)
    assert len(t) == 4
    # embed_w initialized in range
    assert np.all(np.abs(rows[:, LAYOUT.embed_w_col]) <= 0.1)
    # idempotent pull returns same rows
    rows2 = t.pull_or_create(keys)
    np.testing.assert_array_equal(rows, rows2)

    rows[:, LAYOUT.SHOW] = 5.0
    t.push(keys, rows)
    t.save_base(str(tmp_path / "base"))

    t2 = HostSparseTable(LAYOUT, OPT, n_shards=4)
    t2.load(str(tmp_path / "base"))
    got = t2.pull_or_create(keys)
    np.testing.assert_array_equal(got, rows)


def test_save_delta_only_touched(tmp_path):
    t = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    keys = np.arange(1, 11, dtype=np.uint64)
    rows = t.pull_or_create(keys)
    t.save_base(str(tmp_path / "base"))  # clears touched
    sub = keys[:3]
    t.push(sub, rows[:3] + 1.0)
    n = t.save_delta(str(tmp_path / "delta"))
    assert n == 3
    # apply delta onto a fresh load of base
    t2 = HostSparseTable(LAYOUT, OPT, n_shards=2)
    t2.load(str(tmp_path / "base"))
    t2.apply_delta(str(tmp_path / "delta"))
    got = t2.pull_or_create(sub)
    np.testing.assert_allclose(got, rows[:3] + 1.0)


def test_decay_and_shrink():
    t = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    hot = np.array([100], dtype=np.uint64)
    cold = np.array([200], dtype=np.uint64)
    rows = t.pull_or_create(np.concatenate([hot, cold]))
    rows[0, LAYOUT.SHOW] = 10.0  # decays to 5 -> kept
    rows[1, LAYOUT.SHOW] = 1.0  # decays to 0.5 -> dropped
    t.push(np.concatenate([hot, cold]), rows)
    dropped = t.decay_and_shrink()
    assert dropped == 1
    assert len(t) == 1
    got = t.pull_or_create(hot)
    np.testing.assert_allclose(got[0, LAYOUT.SHOW], 5.0)


@pytest.mark.parametrize("n_mesh_shards", [1, 4])
def test_working_set_roundtrip(n_mesh_shards):
    t = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=2)
    ws = PassWorkingSet(n_mesh_shards=n_mesh_shards)
    k1 = np.array([5, 9, 13], dtype=np.uint64)
    k2 = np.array([9, 21, 1 << 40], dtype=np.uint64)
    ws.add_keys(k1)
    ws.add_keys(k2)
    dev = ws.finalize(t, round_to=8)
    assert ws.n_keys == 5
    assert dev.shape[0] == n_mesh_shards
    assert dev.shape[1] % 8 == 0

    all_keys = np.unique(np.concatenate([k1, k2]))
    rows = ws.lookup(all_keys)
    # every key's row holds the host store's values
    host_rows = t.pull_or_create(all_keys)
    flat = dev.reshape(-1, LAYOUT.width)
    np.testing.assert_array_equal(flat[rows], host_rows)
    # mesh shard assignment consistent with hashing
    shard_of_row = rows // ws.capacity
    np.testing.assert_array_equal(shard_of_row, key_to_shard(all_keys, n_mesh_shards))

    # writeback flushes mutations
    flat[rows] += 1.0
    ws.writeback(flat.reshape(dev.shape))
    got = t.pull_or_create(all_keys)
    np.testing.assert_allclose(got, host_rows + 1.0)


def test_lookup_missing_key_raises():
    t = HostSparseTable(LAYOUT, OPT, n_shards=2)
    ws = PassWorkingSet()
    ws.add_keys(np.array([1, 2], dtype=np.uint64))
    ws.finalize(t, round_to=8)
    with pytest.raises(KeyError):
        ws.lookup(np.array([999], dtype=np.uint64))


def test_save_cache_model_hot_keys(tmp_path):
    """save_cache_model parity: threshold admits ~cache_rate of keys, the
    cache dir round-trips as a loadable table subset."""
    t = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    keys = np.arange(1, 101, dtype=np.uint64)
    rows = t.pull_or_create(keys)
    rows[:, LAYOUT.SHOW] = np.arange(100, dtype=np.float32)  # show = rank
    t.push(keys, rows)

    thr = t.cache_threshold(cache_rate=0.2)
    assert 75.0 <= thr <= 85.0  # ~top 20%
    n = t.save_cache(str(tmp_path / "cache"), thr)
    assert 15 <= n <= 25
    t2 = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    t2.load(str(tmp_path / "cache"))
    assert len(t2) == n
    hot = np.sort(t2.keys())
    got = t2.pull_or_create(hot)
    np.testing.assert_array_equal(got, rows[np.isin(keys, hot)])
    assert (got[:, LAYOUT.SHOW] >= thr).all()


def test_save_with_whitelist(tmp_path):
    t = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    keys = np.arange(1, 51, dtype=np.uint64)
    rows = t.pull_or_create(keys)
    wl = np.array([3, 7, 999], dtype=np.uint64)  # 999 not in the table
    n = t.save_with_whitelist(str(tmp_path / "wl"), wl)
    assert n == 2
    t2 = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    t2.load(str(tmp_path / "wl"))
    np.testing.assert_array_equal(np.sort(t2.keys()), [3, 7])
    np.testing.assert_array_equal(
        t2.pull_or_create(np.array([3, 7], np.uint64)),
        rows[np.isin(keys, [3, 7])],
    )


def test_boxwrapper_cache_and_whitelist_surface(tmp_path):
    from paddlebox_tpu.boxps import BoxWrapper

    box = BoxWrapper(embedx_dim=4, sparse_opt=OPT, n_host_shards=4)
    keys = np.arange(1, 41, dtype=np.uint64)
    rows = box.table.pull_or_create(keys)
    rows[:, LAYOUT.SHOW] = np.arange(40, dtype=np.float32)
    box.table.push(keys, rows)
    n = box.save_cache_model(str(tmp_path), "20260101", cache_rate=0.25)
    assert 5 <= n <= 15
    assert (tmp_path / "20260101" / "cache" / "meta.json").exists()
    nw = box.save_model_with_whitelist(str(tmp_path), "20260101", keys[:5])
    assert nw == 5


def test_cache_threshold_tie_resistant(tmp_path):
    """Heavy show ties (cold keys at 0) must not blow the cache up to the
    whole table: the closest achievable fraction wins."""
    t = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    keys = np.arange(1, 1001, dtype=np.uint64)
    rows = t.pull_or_create(keys)
    rows[:, LAYOUT.SHOW] = 0.0  # 90% stone cold, all tied
    rows[:100, LAYOUT.SHOW] = 50.0  # 10% hot, tied among themselves
    t.push(keys, rows)
    thr = t.cache_threshold(cache_rate=0.1)
    assert thr == 50.0  # NOT 0.0 (which would admit everything)
    assert t.save_cache(str(tmp_path / "cache"), thr) == 100
