"""Recovery tests for the fault sites no other test file targets.

pbox-lint FLT008 demands every ``faultinject.KNOWN_SITES`` entry be
exercised by at least one test — a site that fires in package code but has
no test aimed at it guards a recovery path with zero coverage. This file
closes the four gaps the rule found: ``fs.atomic_write``,
``checkpoint.load``, ``transport.connect`` and ``transport.heartbeat``.
Each test asserts the actual recovery CONTRACT around the site, not just
that the fault fired.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.parallel.transport import TcpTransport
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train.checkpoint import CheckpointManager
from paddlebox_tpu.utils.faultinject import (
    InjectedFault,
    fail_nth,
    fail_once,
    inject,
)
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.utils.monitor import STAT_GET

from tests.test_chaos_dist import _free_ports


# ---------------------------------------------------------------------------
# fs.atomic_write: the site fires between write and publish — the exact
# window the atomicity claim is about.


def test_atomic_write_crash_window_keeps_previous_content(tmp_path):
    path = str(tmp_path / "report.json")
    with atomic_write(path) as f:
        f.write("v1")
    with inject(fail_once("fs.atomic_write")) as plan:
        with pytest.raises(InjectedFault):
            with atomic_write(path) as f:
                f.write("v2-torn")
        assert plan.failures("fs.atomic_write") == 1
        # the torn bytes landed in the tmp file; the published path is
        # untouched by the failed publish
        with open(path) as f:
            assert f.read() == "v1"
        # fail_once heals: the retried publish commits and cleans the tmp
        with atomic_write(path) as f:
            f.write("v2")
    with open(path) as f:
        assert f.read() == "v2"
    assert not os.path.exists(path + ".tmp")


# ---------------------------------------------------------------------------
# checkpoint.load: resume() is read-only on the checkpoint tree, so a load
# crash must be fully retryable — the retried resume lands on the same
# state a never-crashed resume would have.


LAYOUT = ValueLayout(embedx_dim=2)
OPT = SparseOptimizerConfig()


def _seeded_root(root):
    cm = CheckpointManager(root)
    t = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(1, 300, 40).astype(np.uint64))
    rows = t.pull_or_create(keys)
    rows += rng.standard_normal(rows.shape).astype(np.float32)
    t.push(keys, rows)
    cm.save_base("20260101", t, None)
    rows2 = t.pull_or_create(keys)
    rows2 += 1.0
    t.push(keys, rows2)
    cm.save_delta("20260101", t, None)
    return t


def _resume_fresh(root):
    t = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    st = CheckpointManager(root).resume(t, None)
    return st, t


@pytest.mark.parametrize("hit", [1, 2])  # base load, then delta apply
def test_checkpoint_load_crash_is_retryable(tmp_path, hit):
    root = str(tmp_path / "ckpt")
    ref = _seeded_root(root)
    with inject(fail_nth("checkpoint.load", hit)) as plan:
        with pytest.raises(InjectedFault):
            _resume_fresh(root)
        assert plan.failures("checkpoint.load") == 1
        # same plan, fault budget spent: the retry inside the same process
        # (supervisor escalation re-enters resume) must succeed
        st, t = _resume_fresh(root)
    assert st["delta_idx"] == 1
    keys = np.sort(ref.keys())
    np.testing.assert_array_equal(np.sort(t.keys()), keys)
    np.testing.assert_array_equal(
        t.pull_or_create(keys), ref.pull_or_create(keys)
    )


# ---------------------------------------------------------------------------
# transport.connect / transport.heartbeat: a connect flake is absorbed by
# the send path's reconnect-with-backoff; a heartbeat flake is counted and
# never takes down the beat loop or the data path.


@pytest.fixture()
def _fast_transport_flags():
    names = ("transport_heartbeat_s", "transport_backoff_s",
             "transport_send_retries")
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_send_retries", 4)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _pair(hb=0.0):
    config.set_flag("transport_heartbeat_s", hb)
    eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    return [TcpTransport(r, eps, timeout=10.0) for r in range(2)]


def test_connect_flake_absorbed_by_send_retry(_fast_transport_flags):
    ts = _pair()
    try:
        with inject(fail_once("transport.connect")) as plan:
            ts[0].send(1, "t", b"payload-after-connect-flake")
            assert ts[1].recv("t", 0) == b"payload-after-connect-flake"
            assert plan.failures("transport.connect") == 1
    finally:
        for t in ts:
            t.close()


def test_heartbeat_flake_counted_and_survived(_fast_transport_flags):
    ts = _pair(hb=0.05)
    try:
        before = STAT_GET("transport.heartbeat_errors")
        with inject(fail_once("transport.heartbeat")) as plan:
            deadline = time.monotonic() + 10.0
            while plan.failures("transport.heartbeat") == 0:
                assert time.monotonic() < deadline, "heartbeat never fired"
                time.sleep(0.01)
        assert STAT_GET("transport.heartbeat_errors") == before + 1
        # the loop survived the flake and the data path never noticed
        ts[0].send(1, "t", b"after-heartbeat-flake")
        assert ts[1].recv("t", 0) == b"after-heartbeat-flake"
    finally:
        for t in ts:
            t.close()
