"""Multi-host made real: localhost process clusters, real sockets, real mesh.

The reference proves its distributed tier with localhost subprocess
clusters (test_dist_fleet_base.py:158-260); same pattern here, at 2 AND 4
ranks (the reference's dualbox math is rank-count-general,
data_set.cc:1452-1464 — 2 is the weakest test of generality). Worker
processes each own a slice of the global device mesh (jax.distributed,
gloo CPU collectives) and of the sparse table:

- test_two_process_training_matches_single_process: striped files, no
  shuffle, one trained pass through TcpTransport + DistributedWorkingSet +
  the sharded mesh step — asserted EQUAL (layout exactly, values to f32
  reduction tolerance) to the same pass run single-process.
- test_global_shuffle_and_lockstep_unequal_records: ins_id-routed global
  shuffle over TcpShuffleRouter (record multiset preserved, routing
  deterministic by hash) + automatic allreduce-max'd batch counts when
  ranks hold unequal record counts (compute_thread_batch_nccl parity).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from paddlebox_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="multi-host fast path needs the native tier"
)

NS, D = 4, 4
GLOBAL_BATCH = 64  # 2 hosts x 32; 16 per device


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _write_files(tmp_path, sizes, with_ins_id=False):
    rng = np.random.default_rng(7)
    files = []
    rec_id = 0
    for fi, n in enumerate(sizes):
        path = str(tmp_path / f"part-{fi}.txt")
        with open(path, "w") as f:
            for _ in range(n):
                keys = rng.integers(1, 500, NS)
                pre = f"1 ins{rec_id:05d} " if with_ins_id else ""
                f.write(
                    pre
                    + f"1 {int(keys[0]) % 2}.0 "
                    + " ".join(f"1 {k}" for k in keys)
                    + "\n"
                )
                rec_id += 1
        files.append(path)
    return files


def _run_cluster(
    tmp_path, mode, files, local_batch, parse_ins_id, round_to=32,
    extra_env=None, extra_conf=None, n_ranks=2, local_devices=2,
):
    ports = _free_ports(1 + n_ranks)
    conf = dict(
        coord_port=ports[0],
        tp_ports=ports[1:],
        files=files,
        local_batch=local_batch,
        num_slots=NS,
        embedx_dim=D,
        parse_ins_id=parse_ins_id,
        round_to=round_to,
        n_ranks=n_ranks,
        local_devices=local_devices,
    )
    if extra_conf:
        conf.update(extra_conf)
    with open(tmp_path / "conf.json", "w") as f:
        json.dump(conf, f)
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(repo, "tests", "multihost_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, mode, str(r), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for r in range(n_ranks)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240 * max(1, n_ranks // 2))
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-4000:]}"
    return [np.load(tmp_path / f"rank{r}.npz") for r in range(n_ranks)]


def _single_process_reference(files, local_batch, n_ranks=2, local_devices=2):
    """The same pass, one process: global batches composed exactly as the
    n-host run composes them (rank-local blocks concatenated), trained on
    an equal-size local mesh."""
    import jax
    import optax

    from paddlebox_tpu.data import SlotInfo, SlotSchema
    from paddlebox_tpu.data.parser import parse_line
    from paddlebox_tpu.data.slot_record import build_batch
    from paddlebox_tpu.data.device_pack import pack_batch_sharded
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.table import (
        HostSparseTable,
        PassWorkingSet,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import TrainStepConfig
    from paddlebox_tpu.train.sharded_step import (
        init_sharded_train_state,
        make_sharded_train_step,
    )
    from paddlebox_tpu.metrics.auc import auc_compute

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    layout = ValueLayout(embedx_dim=D)
    opt_cfg = SparseOptimizerConfig(
        embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.01
    )
    table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)

    n_global = n_ranks * local_devices
    stripes = [[] for _ in range(n_ranks)]
    for r in range(n_ranks):
        for path in files[r::n_ranks]:
            with open(path) as f:
                for line in f:
                    rec = parse_line(line.rstrip("\n"), schema)
                    if rec is not None:
                        stripes[r].append(rec)
    ws = PassWorkingSet(n_mesh_shards=n_global)
    for stripe in stripes:
        for rec in stripe:
            ws.add_keys(rec.u64_values)
    dev_table = ws.finalize(table, round_to=32)

    model = DeepFM(num_slots=NS, feat_width=layout.pull_width,
                   embedx_dim=D, hidden=(16,))
    plan = make_mesh(n_global)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=local_batch // local_devices, layout=layout,
        sparse_opt=opt_cfg, auc_buckets=1000, axis_name=plan.axis,
    )
    step = make_sharded_train_step(model.apply, optax.adam(1e-2), cfg, plan)
    state = init_sharded_train_state(
        plan, dev_table, model.init(jax.random.PRNGKey(0)),
        optax.adam(1e-2), 1000,
    )
    n_batches = len(stripes[0]) // local_batch
    for i in range(n_batches):
        block = slice(i * local_batch, (i + 1) * local_batch)
        recs = sum((s[block] for s in stripes), [])
        batch = build_batch(recs, schema)
        db = pack_batch_sharded(batch, ws, schema, n_global, bucket=256)
        feed = {
            k: jax.device_put(v, plan.batch_sharding)
            for k, v in db.as_dict().items()
        }
        state, m = step(state, feed)
    trained = np.asarray(state.table)  # [4, cap, width]
    ws.writeback(trained)
    auc = auc_compute(
        type(state.auc)(pos=np.asarray(state.auc.pos), neg=np.asarray(state.auc.neg))
    )["auc"]
    keys = np.sort(table.keys())
    return dict(
        ws=ws, trained=trained, auc=auc,
        host_keys=keys, host_vals=table.pull_or_create(keys),
    )


def _check_train_matches_reference(dumps, ref, num_batches=4):
    # pass layout identical: capacity + every referenced key's global row
    for d in dumps:
        assert d["capacity"][0] == ref["ws"].capacity
        np.testing.assert_array_equal(
            d["rows"], ref["ws"].lookup(d["sorted_keys"]).astype(np.int64)
        )
        assert d["num_batches"][0] == num_batches

    # trained table: hosts' shard blocks assemble into the reference table
    merged = np.concatenate([d["local_table"] for d in dumps])
    assert merged.shape == ref["trained"].shape
    np.testing.assert_allclose(merged, ref["trained"], rtol=2e-3, atol=1e-4)

    # host tables after writeback: disjoint ownership, union == reference
    for a in range(len(dumps)):
        for b in range(a + 1, len(dumps)):
            assert len(np.intersect1d(
                dumps[a]["host_keys"], dumps[b]["host_keys"]
            )) == 0
    all_keys = np.concatenate([d["host_keys"] for d in dumps])
    all_vals = np.concatenate([d["host_vals"] for d in dumps])
    order = np.argsort(all_keys)
    np.testing.assert_array_equal(all_keys[order], ref["host_keys"])
    np.testing.assert_allclose(
        all_vals[order], ref["host_vals"], rtol=2e-3, atol=1e-4
    )

    # online AUC agrees (same batches, f32 bucket-edge tolerance)
    assert abs(dumps[0]["auc"][0] - ref["auc"]) < 5e-3
    for d in dumps[1:]:
        assert abs(dumps[0]["auc"][0] - d["auc"][0]) < 1e-9


def test_two_process_training_matches_single_process(tmp_path):
    """Default path — now the multi-host RESIDENT feed (per-device host
    copies of the pass arrays, transport-locksteped pads, position feed)."""
    files = _write_files(tmp_path, [64, 64, 64, 64])
    dumps = _run_cluster(tmp_path, "train", files, GLOBAL_BATCH // 2, False)
    for d in dumps:
        assert d["used_resident"][0] == 1  # the fast tier actually ran
    ref = _single_process_reference(files, GLOBAL_BATCH // 2)
    _check_train_matches_reference(dumps, ref)


def test_two_process_training_host_packed(tmp_path):
    """The transport-locksteped host packer (resident disabled) stays
    correct — same reference equality."""
    files = _write_files(tmp_path, [64, 64, 64, 64])
    dumps = _run_cluster(
        tmp_path, "train", files, GLOBAL_BATCH // 2, False,
        extra_env={"PBOX_ENABLE_RESIDENT_FEED": "0"},
    )
    for d in dumps:
        assert d["used_resident"][0] == 0
    ref = _single_process_reference(files, GLOBAL_BATCH // 2)
    _check_train_matches_reference(dumps, ref)


def test_four_process_training_matches_single_process(tmp_path):
    """Rank-count generality (the reference's dualbox math is
    rank-general, data_set.cc:1452-1464): the DWS key exchange, resident
    placement, and striped batching at FOUR ranks x 2 local devices must
    equal the same pass on one 8-device process."""
    files = _write_files(tmp_path, [32] * 8)
    local_batch = 16  # 4 ranks x 16 = 64 global, 8 per device
    dumps = _run_cluster(
        tmp_path, "train", files, local_batch, False, n_ranks=4,
    )
    for d in dumps:
        assert d["used_resident"][0] == 1
    ref = _single_process_reference(files, local_batch, n_ranks=4)
    _check_train_matches_reference(dumps, ref)


def test_four_process_pv_join_update_lockstep(tmp_path):
    """The pv ghost lockstep at 4 ranks: search_id shuffle over 4 owners,
    unequal local pv loads, batch counts allreduce-max'd, every real ad
    trained exactly once globally; resident pv tier == host-packed."""
    files, total = _write_pv_files(
        tmp_path, n_even_queries=30, n_odd_queries=8, n_files=4
    )
    outs = _run_cluster(tmp_path, "pv", files, 16, False, n_ranks=4)
    assert int(outs[0]["join_resident"][0]) == 1

    (tmp_path / "hp").mkdir()
    hp = _run_cluster(
        tmp_path / "hp", "pv", files, 16, False, n_ranks=4,
        extra_env={"PBOX_ENABLE_RESIDENT_FEED": "0"},
    )
    assert int(hp[0]["join_resident"][0]) == 0
    for key, tol in (
        ("join_loss", 1e-5), ("join_auc", 1e-6), ("upd_loss", 1e-5),
    ):
        assert abs(float(outs[0][key][0]) - float(hp[0][key][0])) < tol, key
    # lockstep across ALL ranks: same join batch count = max local need
    jb = [int(r["join_batches"][0]) for r in outs]
    assert len(set(jb)) == 1
    local = [int(r["local_pv_batches"][0]) for r in outs]
    assert jb[0] == max(local)
    assert max(local) > min(local), "4-way split should be uneven"
    # every real ad trained exactly once globally on every rank's count
    for r in outs:
        assert int(r["join_ins"][0]) == total
        assert np.isfinite(r["join_loss"][0]) and np.isfinite(r["upd_loss"][0])
    ub = [int(r["upd_batches"][0]) for r in outs]
    assert len(set(ub)) == 1 and ub[0] > 0


def test_global_shuffle_and_lockstep_unequal_records(tmp_path):
    # rank 0 gets 96 records, rank 1 gets 32 — shuffle rebalances by
    # ins_id hash, lockstep equalizes the batch count automatically
    files = _write_files(tmp_path, [96, 32], with_ins_id=True)
    dumps = _run_cluster(tmp_path, "shuffle", files, 16, True)

    # global shuffle preserved the record multiset across the cluster
    merged_ins = np.sort(np.concatenate([d["ins_ids"] for d in dumps]))
    assert len(merged_ins) == 128
    assert merged_ins[0] == "ins00000" and merged_ins[-1] == "ins00127"
    assert len(np.unique(merged_ins)) == 128
    # routing moved records off the overloaded rank
    n0, n1 = int(dumps[0]["n_records"][0]), int(dumps[1]["n_records"][0])
    assert n0 + n1 == 128 and n1 > 32

    # lockstep: both ranks agreed on the max batch count and ran it
    nb0, nb1 = int(dumps[0]["num_batches"][0]), int(dumps[1]["num_batches"][0])
    assert nb0 == nb1 == max(n0 // 16, n1 // 16)
    assert int(dumps[0]["batches_run"][0]) == int(dumps[1]["batches_run"][0]) == nb0
    for d in dumps:
        assert np.isfinite(d["loss"][0]) and 0.0 < d["auc"][0] <= 1.0


def test_zero1_across_processes(tmp_path):
    """ZeRO-1 optimizer-state sharding over a 2-process mesh, two passes:
    each host updates only its chunk of the moments; the chunked state
    carries across passes as a non-addressable global array."""
    files = _write_files(tmp_path, [64, 64])
    dumps = _run_cluster(tmp_path, "zero", files, GLOBAL_BATCH // 2, False)
    for d in dumps:
        assert np.isfinite(d["loss"][0]) and 0.0 < d["auc"][0] <= 1.0
    # both ranks agree on the replicated metrics after the second pass
    assert abs(dumps[0]["loss"][0] - dumps[1]["loss"][0]) < 1e-9
    # trained shard blocks are disjoint and real
    assert not np.array_equal(dumps[0]["local_table"], dumps[1]["local_table"])


def _write_overlapping_pass_files(tmp_path, n_passes, files_per_pass, n=48):
    """Per-pass file groups whose key ranges overlap pass-to-pass (the CTR
    stream shape the carried boundary exploits: most keys survive, some
    depart, some are new)."""
    rng = np.random.default_rng(23)
    files = []
    for p in range(n_passes):
        # ~80% key-range overlap pass-to-pass (CTR-like recurrence)
        lo, hi = 1 + 80 * p, 400 + 80 * p
        for fi in range(files_per_pass):
            path = str(tmp_path / f"pass{p}-part{fi}.txt")
            with open(path, "w") as f:
                for _ in range(n):
                    keys = rng.integers(lo, hi, NS)
                    f.write(
                        f"1 {int(keys[0]) % 2}.0 "
                        + " ".join(f"1 {k}" for k in keys)
                        + "\n"
                    )
            files.append(path)
    return files


def test_two_process_carried_boundary_matches_classic(tmp_path):
    """Multi-host device-carried pass boundary (per-host MultiHostCarrier
    splice over the DistributedWorkingSet): a 3-pass day loop over
    overlapping key streams must produce EXACTLY the host tables and
    metrics of the classic full-writeback boundary, while moving only the
    key-set delta over the host<->device wire (EndPass warm-cache parity
    on every node, box_wrapper.cc:627-651)."""
    files = _write_overlapping_pass_files(tmp_path, n_passes=3, files_per_pass=2)
    conf = {"files_per_pass": 2}
    (tmp_path / "car").mkdir()
    car = _run_cluster(
        tmp_path / "car", "carried", files, GLOBAL_BATCH // 2, False,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "1"}, extra_conf=conf,
    )
    (tmp_path / "cls").mkdir()
    cls = _run_cluster(
        tmp_path / "cls", "carried", files, GLOBAL_BATCH // 2, False,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "0"}, extra_conf=conf,
    )
    # the carried run actually spliced (passes 2 and 3), and the boundary
    # moved only the delta: every splice found surviving rows, so uploads
    # + departures stay strictly below the full-table traffic the classic
    # boundary pays twice per pass
    for r in range(2):
        assert int(car[r]["spliced_passes"][0]) == 2
        assert int(car[r]["splice_common"][0]) > 0
        assert int(cls[r]["spliced_passes"][0]) == 0
    common = sum(int(car[r]["splice_common"][0]) for r in range(2))
    moved = sum(
        int(car[r]["splice_new"][0]) + int(car[r]["splice_departed"][0])
        for r in range(2)
    )
    # classic boundary traffic = full writeback (common+departed) + full
    # re-upload (common+new) = 2*common + moved; the carrier ships only
    # the key-set delta, so the host wire carries well under that
    classic_traffic = 2 * common + moved
    assert moved < 0.7 * classic_traffic

    # carried == classic: per-pass metrics and the final host tables
    for r in range(2):
        np.testing.assert_allclose(
            car[r]["losses"], cls[r]["losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_allclose(
            car[r]["aucs"], cls[r]["aucs"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_array_equal(car[r]["host_keys"], cls[r]["host_keys"])
        np.testing.assert_allclose(
            car[r]["host_vals"], cls[r]["host_vals"], rtol=1e-5, atol=1e-6
        )


def test_four_process_carried_boundary_matches_classic(tmp_path):
    """The per-host carrier is rank-count-general too: same carried ==
    classic equality at 4 ranks x 2 local devices (8 table shards, 2 per
    host, 1 per device)."""
    files = _write_overlapping_pass_files(tmp_path, n_passes=2, files_per_pass=4)
    conf = {"files_per_pass": 4}
    (tmp_path / "car").mkdir()
    car = _run_cluster(
        tmp_path / "car", "carried", files, 16, False, n_ranks=4,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "1"}, extra_conf=conf,
    )
    (tmp_path / "cls").mkdir()
    cls = _run_cluster(
        tmp_path / "cls", "carried", files, 16, False, n_ranks=4,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "0"}, extra_conf=conf,
    )
    for r in range(4):
        assert int(car[r]["spliced_passes"][0]) == 1
        assert int(car[r]["splice_common"][0]) > 0
        assert int(cls[r]["spliced_passes"][0]) == 0
        np.testing.assert_allclose(
            car[r]["losses"], cls[r]["losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_array_equal(car[r]["host_keys"], cls[r]["host_keys"])
        np.testing.assert_allclose(
            car[r]["host_vals"], cls[r]["host_vals"], rtol=1e-5, atol=1e-6
        )


def _write_pv_files(
    tmp_path, n_even_queries, n_odd_queries, n_files=2, lo=1, hi=500,
    prefix="part", seed=11,
):
    """Logkey'd pv data with a skewed search_id parity split: after
    search_id-mode global shuffle, rank 0 owns ~n_even and rank 1 ~n_odd
    page views — unequal join batch counts force ghost equalization."""
    rng = np.random.default_rng(seed)
    sids = [2 * (i + 1) for i in range(n_even_queries)] + [
        2 * (i + 1) + 1 for i in range(n_odd_queries)
    ]
    rng.shuffle(sids)
    files = [str(tmp_path / f"{prefix}-{i}.txt") for i in range(n_files)]
    handles = [open(p, "w") for p in files]
    total = 0
    for qi, sid in enumerate(sids):
        n_ads = int(rng.integers(1, 4))
        for rank in range(1, n_ads + 1):
            keys = rng.integers(lo, hi, NS)
            cmatch = 222 if rng.random() < 0.8 else 999  # some rank-invalid
            logkey = "0" * 11 + f"{cmatch:03x}" + f"{rank:02x}" + f"{sid:016x}"
            handles[qi % len(handles)].write(
                f"1 {logkey} 1 {int(keys[0]) % 2}.0 "
                + " ".join(f"1 {k}" for k in keys)
                + "\n"
            )
            total += 1
    for h in handles:
        h.close()
    return files, total


def test_two_process_pv_carried_day_loop_matches_classic(tmp_path):
    """The two flagship multi-host tiers COMPOSED: a 2-pass join->update
    (pv) day loop on the resident pv tier where every boundary hands
    end_pass the live device table. Carried (per-host splice of the
    update-phase-trained rows) must equal the classic full writeback on
    metrics and host tables."""
    files = []
    for p in range(2):
        fs, _ = _write_pv_files(
            tmp_path, n_even_queries=20, n_odd_queries=8,
            lo=1 + 120 * p, hi=400 + 120 * p, prefix=f"pass{p}",
            seed=11 + p,
        )
        files.extend(fs)
    conf = {"files_per_pass": 2}
    (tmp_path / "car").mkdir()
    car = _run_cluster(
        tmp_path / "car", "pv2", files, GLOBAL_BATCH // 2, False,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "1"}, extra_conf=conf,
    )
    (tmp_path / "cls").mkdir()
    cls = _run_cluster(
        tmp_path / "cls", "pv2", files, GLOBAL_BATCH // 2, False,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "0"}, extra_conf=conf,
    )
    for r in range(2):
        assert int(car[r]["join_resident"][0]) == 1  # resident pv tier ran
        assert int(car[r]["spliced_passes"][0]) == 1  # pass 2 spliced
        assert int(cls[r]["spliced_passes"][0]) == 0
        np.testing.assert_allclose(
            car[r]["join_losses"], cls[r]["join_losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_allclose(
            car[r]["upd_losses"], cls[r]["upd_losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_array_equal(car[r]["host_keys"], cls[r]["host_keys"])
        np.testing.assert_allclose(
            car[r]["host_vals"], cls[r]["host_vals"], rtol=1e-5, atol=1e-6
        )


def test_two_process_carried_day_loop_resume_matches_uninterrupted(tmp_path):
    """Checkpoint/resume ON the multi-host path: 2 carried passes +
    per-host save_base, then everything rebuilt from fresh objects and
    resumed from disk alone, then pass 3 — must equal an UNINTERRUPTED
    3-pass carried run on losses and final host tables (each host
    checkpoints its own slice; dense is replicated; decay epochs are
    checkpoint-stamped so resumed counters match the live table)."""
    files = _write_overlapping_pass_files(tmp_path, n_passes=3, files_per_pass=2)
    conf = {"files_per_pass": 2}
    env = {"PBOX_ENABLE_CARRIED_TABLE": "1"}
    (tmp_path / "ref").mkdir()
    ref = _run_cluster(
        tmp_path / "ref", "carried", files, GLOBAL_BATCH // 2, False,
        extra_env=env, extra_conf=conf,
    )
    (tmp_path / "res").mkdir()
    res = _run_cluster(
        tmp_path / "res", "carried_resume", files, GLOBAL_BATCH // 2, False,
        extra_env=env, extra_conf=conf,
    )
    for r in range(2):
        np.testing.assert_allclose(
            res[r]["losses"], ref[r]["losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_array_equal(res[r]["host_keys"], ref[r]["host_keys"])
        np.testing.assert_allclose(
            res[r]["host_vals"], ref[r]["host_vals"], rtol=1e-5, atol=1e-6
        )


def test_four_process_pv_carried_day_loop_matches_classic(tmp_path):
    """pv x carried at 4 ranks: the composed day loop is rank-general."""
    files = []
    for p in range(2):
        fs, _ = _write_pv_files(
            tmp_path, n_even_queries=24, n_odd_queries=12,
            lo=1 + 120 * p, hi=400 + 120 * p, prefix=f"pass{p}",
            seed=17 + p, n_files=4,
        )
        files.extend(fs)
    conf = {"files_per_pass": 4}
    (tmp_path / "car").mkdir()
    car = _run_cluster(
        tmp_path / "car", "pv2", files, 16, False, n_ranks=4,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "1"}, extra_conf=conf,
    )
    (tmp_path / "cls").mkdir()
    cls = _run_cluster(
        tmp_path / "cls", "pv2", files, 16, False, n_ranks=4,
        extra_env={"PBOX_ENABLE_CARRIED_TABLE": "0"}, extra_conf=conf,
    )
    for r in range(4):
        assert int(car[r]["spliced_passes"][0]) == 1
        assert int(cls[r]["spliced_passes"][0]) == 0
        np.testing.assert_allclose(
            car[r]["join_losses"], cls[r]["join_losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_allclose(
            car[r]["upd_losses"], cls[r]["upd_losses"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_array_equal(car[r]["host_keys"], cls[r]["host_keys"])
        np.testing.assert_allclose(
            car[r]["host_vals"], cls[r]["host_vals"], rtol=1e-5, atol=1e-6
        )


def test_two_process_pv_join_update_lockstep(tmp_path):
    """Multi-host join-phase (pv) training — now on the RESIDENT pv tier
    (device-sharded PvPlan stacks, ghost batches locksteped): search_id
    shuffle co-locates queries, batch counts + pads lockstep, rank_offset
    stays device-local, and the update phase reuses the join-trained
    table. Asserts equality with the host-packed pv path run on the same
    data (resident disabled via env)."""
    files, total = _write_pv_files(tmp_path, n_even_queries=30, n_odd_queries=8)
    outs = _run_cluster(tmp_path, "pv", files, GLOBAL_BATCH // 2, False)
    r0, r1 = outs
    assert int(r0["join_resident"][0]) == 1  # the new tier actually ran

    # host-packed reference on identical data: metrics must agree exactly
    (tmp_path / "hp").mkdir()
    hp = _run_cluster(
        tmp_path / "hp", "pv", files, GLOBAL_BATCH // 2, False,
        extra_env={"PBOX_ENABLE_RESIDENT_FEED": "0"},
    )
    assert int(hp[0]["join_resident"][0]) == 0
    for key, tol in (
        ("join_loss", 1e-5), ("join_auc", 1e-6), ("upd_loss", 1e-5),
    ):
        assert abs(float(r0[key][0]) - float(hp[0][key][0])) < tol, key
    assert int(r0["join_batches"][0]) == int(hp[0]["join_batches"][0])
    assert int(r0["join_ins"][0]) == int(hp[0]["join_ins"][0])
    # lockstep: both ranks ran the SAME number of join batches...
    assert int(r0["join_batches"][0]) == int(r1["join_batches"][0])
    # ...which is the max of the two local needs (ghosts on the short rank)
    local = sorted(
        (int(r0["local_pv_batches"][0]), int(r1["local_pv_batches"][0]))
    )
    assert local[0] < local[1], "test data must give unequal pv loads"
    assert int(r0["join_batches"][0]) == local[1]
    # every real ad trained exactly once globally: the psum'd AUC bucket
    # totals count real instances only (ghosts masked), same on both ranks
    assert int(r0["join_ins"][0]) == int(r1["join_ins"][0]) == total
    # update phase ran in lockstep too, losses finite everywhere
    assert int(r0["upd_batches"][0]) == int(r1["upd_batches"][0]) > 0
    for r in outs:
        assert np.isfinite(r["join_loss"][0]) and np.isfinite(r["upd_loss"][0])
        assert 0.0 <= r["join_auc"][0] <= 1.0


def test_shuffle_round_no_double_delivery_after_reconnect():
    """TcpShuffleRouter round isolation under faults: a sender knocked over
    mid-round reconnects and REPLAYS its retained frames — per-destination
    sequence dedup must drop the replayed duplicates so collect() sees each
    sub-chunk of the round exactly once (no double-delivered records), and
    the next round stays clean too. In-process, threads + localhost TCP —
    no subprocess cluster needed."""
    import threading

    from paddlebox_tpu import config
    from paddlebox_tpu.data.record_store import ColumnarRecords
    from paddlebox_tpu.data.slot_record import SlotRecord
    from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
    from paddlebox_tpu.parallel.transport import TcpShuffleRouter, TcpTransport
    from paddlebox_tpu.utils.faultinject import fail_nth, inject
    from paddlebox_tpu.utils.monitor import STAT_GET

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1), SlotInfo("s0")],
        label_slot="label",
        parse_ins_id=True,
    )

    def mk_store(tag, n):
        recs = [
            SlotRecord(
                u64_values=np.array([i + 1], np.uint64),
                u64_offsets=np.array([0, 1], np.uint32),
                f_values=np.array([float(i % 2)], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
                ins_id=f"{tag}-{i:03d}",
            )
            for i in range(n)
        ]
        return ColumnarRecords.from_records(recs, schema)

    prev = {
        n: config.get_flag(n)
        for n in ("transport_backoff_s", "transport_send_retries",
                  "shuffle_chunk_bytes")
    }
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_send_retries", 6)
    # tiny sub-chunks => many frames per round => replays have duplicates
    # to offer the dedup layer
    config.set_flag("shuffle_chunk_bytes", 64)
    eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    tps = [TcpTransport(r, eps, timeout=20.0) for r in range(2)]
    try:
        routers = [TcpShuffleRouter(t) for t in tps]
        for rnd in range(2):
            stores = [mk_store(f"r{rank}n{rnd}", 20 + 10 * rank)
                      for rank in range(2)]
            resent_before = STAT_GET("transport.frames_resent")

            def run(rank, out, rnd=rnd):
                st = stores[rank]
                half = len(st) // 2
                parts = [
                    st.select(np.arange(0, half)),
                    st.select(np.arange(half, len(st))),
                ]
                routers[rank].exchange(rank, parts)
                out[rank] = routers[rank].collect(rank)

            out = {}
            if rnd == 0:
                # kill rank 0's connection twice mid-round: the replayed
                # retained tail carries frames rank 1 already delivered
                with inject(fail_nth("transport.recv_frame", 4, times=1),
                            fail_nth("transport.recv_frame", 9, times=1)):
                    ths = [threading.Thread(target=run, args=(r, out))
                           for r in range(2)]
                    for t in ths:
                        t.start()
                    for t in ths:
                        t.join(60)
                assert (
                    STAT_GET("transport.frames_resent") > resent_before
                ), "no replay happened — the schedule tested nothing"
            else:
                # the round AFTER the faulted one must be clean as well
                ths = [threading.Thread(target=run, args=(r, out))
                       for r in range(2)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join(60)

            # exactly-once: the collected multiset == what was addressed
            # here, with NO record duplicated by the replay
            for rank in range(2):
                got = sorted(
                    ins
                    for c in out[rank]
                    for ins in (c.ins_id(i) for i in range(len(c)))
                )
                want = sorted(
                    stores[src].ins_id(i)
                    for src in range(2)
                    for i in range(len(stores[src]))
                    if (i < len(stores[src]) // 2) == (rank == 0)
                )
                assert got == want, f"round {rnd} rank {rank}"
    finally:
        for t in tps:
            t.close()
        for n, v in prev.items():
            config.set_flag(n, v)


def test_duplicate_replayed_frames_dropped_by_seq():
    """The dedup layer itself, deterministically: a sender that reconnects
    and replays frames WITHOUT honoring the delivered-count ack (e.g. the
    ack reply was lost) re-offers already-delivered sequence numbers — the
    receiver must drop every one of them by (src, seq) and deliver each
    tagged frame exactly once."""
    import socket as _socket
    import struct as _struct
    import zlib as _zlib

    from paddlebox_tpu.parallel.transport import (
        TcpTransport,
        _CODEC_RAW,
        _FRAME,
        _HELLO,
        _HELLO_REPLY,
        _KIND_DATA,
        _MAGIC,
        _VERSION,
    )
    from paddlebox_tpu.utils.monitor import STAT_GET

    eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    t0 = TcpTransport(0, eps, timeout=10.0)

    def frame(seq, tag, payload):
        body = tag.encode() + payload
        return (
            _FRAME.pack(seq, _KIND_DATA, _CODEC_RAW, len(tag.encode()),
                        len(payload), _zlib.crc32(body))
            + body
        )

    def connect():
        s = _socket.create_connection(("127.0.0.1", t0.port), timeout=5.0)
        s.sendall(_HELLO.pack(_MAGIC, _VERSION, 1))
        buf = b""
        while len(buf) < _HELLO_REPLY.size:
            buf += s.recv(_HELLO_REPLY.size - len(buf))
        magic, version, delivered = _HELLO_REPLY.unpack(buf)
        assert magic == _MAGIC and version == _VERSION
        return s, delivered

    try:
        s, acked = connect()
        assert acked == 0
        for seq, tag in ((1, "shuffle:0/n"), (2, "shuffle:0/0"),
                         (3, "shuffle:0/1")):
            s.sendall(frame(seq, tag, f"payload-{seq}".encode()))
        # wait until all three delivered (the ack state is live)
        assert t0.recv("shuffle:0/n", 1, timeout=5.0) == b"payload-1"
        s.close()

        # "reconnect" that ignores the ack and replays the whole round.
        # Seqs 2-3 are delivered by the receiver thread and can lag this
        # reconnect under load: poll until the advertised count covers the
        # whole round before replaying.
        import time as _time

        dups_before = STAT_GET("transport.dup_frames_dropped")
        deadline = _time.monotonic() + 5.0
        while True:
            s2, acked = connect()
            if acked == 3 or _time.monotonic() > deadline:
                break
            s2.close()
            _time.sleep(0.05)
        assert acked == 3, "receiver must advertise the delivered count"
        for seq, tag in ((1, "shuffle:0/n"), (2, "shuffle:0/0"),
                         (3, "shuffle:0/1"), (4, "shuffle:0/2")):
            s2.sendall(frame(seq, tag, f"payload-{seq}".encode()))
        # the genuinely-new frame arrives...
        assert t0.recv("shuffle:0/2", 1, timeout=5.0) == b"payload-4"
        # ...the replayed ones were dropped by seq, exactly once each
        assert STAT_GET("transport.dup_frames_dropped") >= dups_before + 3
        assert t0.recv("shuffle:0/0", 1, timeout=1.0) == b"payload-2"
        assert t0.recv("shuffle:0/1", 1, timeout=1.0) == b"payload-3"
        import pytest as _pytest

        from paddlebox_tpu.parallel.transport import TransportTimeout

        with _pytest.raises(TransportTimeout):
            t0.recv("shuffle:0/n", 1, timeout=0.3)  # NOT delivered twice
        s2.close()
    finally:
        t0.close()
