"""Metric registry tests (reference: MetricMsg hierarchy box_wrapper.h:281-361,
phase filtering boxps_worker.cc:413, init_metric/get_metric_msg
box_helper_py.cc:87-97)."""

import numpy as np
import pytest

from paddlebox_tpu.metrics.registry import (
    CmatchRankMetricMsg,
    MetricRegistry,
    parse_cmatch_rank_group,
)


def _outputs(preds, labels, **extra):
    out = {"preds": np.asarray(preds, np.float32), "labels": np.asarray(labels, np.float32)}
    out.update({k: np.asarray(v) for k, v in extra.items()})
    return out


def _perfect(n=64):
    """Separable preds: label 1 ~ high score, label 0 ~ low score."""
    labels = np.tile([0.0, 1.0], n // 2)
    preds = np.where(labels > 0.5, 0.9, 0.1)
    return preds, labels


def test_parse_cmatch_rank_group():
    assert parse_cmatch_rank_group("401:0,401:1") == [(401, 0), (401, 1)]
    assert parse_cmatch_rank_group("401_0") == [(401, 0)]
    assert parse_cmatch_rank_group("401, 402") == [(401, -1), (402, -1)]
    assert parse_cmatch_rank_group("") == []


def test_basic_metric_and_reset():
    reg = MetricRegistry()
    reg.init_metric("join_auc", bucket_size=1000)
    preds, labels = _perfect()
    reg.add_all(_outputs(preds, labels))
    m = reg.get_metric("join_auc")
    assert m["auc"] > 0.99
    assert m["ins_num"] == 64
    # get resets (reference compute-and-reset contract)
    m2 = reg.get_metric("join_auc")
    assert m2["ins_num"] == 0


def test_phase_filtering():
    reg = MetricRegistry()
    reg.init_metric("join_only", phase=1, bucket_size=1000)
    reg.init_metric("update_only", phase=0, bucket_size=1000)
    reg.init_metric("both", phase=-1, bucket_size=1000)
    preds, labels = _perfect()
    counted = reg.add_all(_outputs(preds, labels), phase=1)
    assert counted == 2  # join_only + both
    assert reg.get_metric("join_only")["ins_num"] == 64
    assert reg.get_metric("update_only")["ins_num"] == 0
    assert reg.get_metric("both")["ins_num"] == 64


def test_mask_metric():
    reg = MetricRegistry()
    reg.init_metric("masked", mask_var="sample_mask", bucket_size=1000)
    preds, labels = _perfect(8)
    mask = np.array([1, 1, 0, 0, 1, 0, 1, 0])
    reg.add_all(_outputs(preds, labels, sample_mask=mask))
    assert reg.get_metric("masked")["ins_num"] == 4


def test_multi_task_cmatch_filter():
    reg = MetricRegistry()
    reg.init_metric(
        "mt", method="multi_task_auc", cmatch_rank_group="401,402", bucket_size=1000
    )
    preds, labels = _perfect(8)
    cmatch = np.array([401, 401, 402, 999, 999, 401, 402, 0])
    reg.add_all(_outputs(preds, labels, cmatch=cmatch))
    assert reg.get_metric("mt")["ins_num"] == 5


def test_cmatch_rank_pairs_and_ignore_rank():
    preds, labels = _perfect(8)
    cmatch = np.array([401, 401, 401, 401, 402, 402, 402, 402])
    rank = np.array([0, 1, 2, 0, 0, 1, 0, 1])
    m = CmatchRankMetricMsg("cr", "401:0,402:1", bucket_size=1000)
    m.add_data(_outputs(preds, labels, cmatch=cmatch, rank=rank))
    assert m.get_metric()["ins_num"] == 4  # 401/0 x2, 402/1 x2
    m2 = CmatchRankMetricMsg("cr2", "401:0", ignore_rank=True, bucket_size=1000)
    m2.add_data(_outputs(preds, labels, cmatch=cmatch, rank=rank))
    assert m2.get_metric()["ins_num"] == 4  # all cmatch==401


def test_cmatch_rank_mask_combined():
    reg = MetricRegistry()
    reg.init_metric(
        "crm", cmatch_rank_group="401:0", mask_var="ok", bucket_size=1000
    )
    preds, labels = _perfect(4)
    reg.add_all(
        _outputs(
            preds,
            labels,
            cmatch=np.array([401, 401, 401, 999]),
            rank=np.array([0, 0, 1, 0]),
            ok=np.array([1, 0, 1, 1]),
        )
    )
    assert reg.get_metric("crm")["ins_num"] == 1  # only ins 0 passes both


def test_metric_msg_string_format():
    reg = MetricRegistry()
    reg.init_metric("fmt", bucket_size=1000)
    preds, labels = _perfect()
    reg.add_all(_outputs(preds, labels))
    msg = reg.get_metric_msg("fmt")
    for field in ("AUC=", "BUCKET_ERROR=", "MAE=", "RMSE=", "Actual CTR=", "COPC=", "INS_NUM="):
        assert field in msg, msg


def test_unknown_method_rejected():
    reg = MetricRegistry()
    with pytest.raises(ValueError):
        reg.init_metric("bad", method="wuauc")


def test_trainer_integration_exposes_preds():
    """Train-step metrics must carry preds/labels for the registry feed."""
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.table import (
        HostSparseTable,
        PassWorkingSet,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train.train_step import (
        TrainStepConfig,
        init_train_state,
        jit_train_step,
        make_train_step,
    )

    lay = ValueLayout(embedx_dim=4)
    table = HostSparseTable(lay, SparseOptimizerConfig(embedx_threshold=0.0))
    ws = PassWorkingSet()
    keys = np.arange(1, 50, dtype=np.uint64)
    ws.add_keys(keys)
    dev = ws.finalize(table, round_to=64)

    B, S = 8, 3
    model = DeepFM(num_slots=S, feat_width=lay.pull_width, embedx_dim=4, hidden=(8,))
    cfg = TrainStepConfig(num_slots=S, batch_size=B, layout=lay, auc_buckets=100)
    opt = optax.sgd(0.1)
    step = jit_train_step(make_train_step(model.apply, opt, cfg))
    state = init_train_state(
        jnp.asarray(dev.reshape(-1, dev.shape[-1])),
        model.init(__import__("jax").random.PRNGKey(0)),
        opt,
        100,
    )
    rows = ws.lookup(np.arange(1, 1 + B * S, dtype=np.uint64) % 49 + 1)
    feed = {
        "uniq_rows": np.pad(np.unique(rows), (0, 64 - len(np.unique(rows))), constant_values=ws.padding_row).astype(np.int32),
        "inverse": np.pad(np.searchsorted(np.unique(rows), rows), (0, 64 - len(rows)), constant_values=63).astype(np.int32),
        "segments": np.pad(np.arange(B * S) % (S * B), (0, 64 - B * S), constant_values=S * B).astype(np.int32),
        "labels": np.tile([0.0, 1.0], B // 2).astype(np.float32),
    }
    state, m = step(state, feed)
    assert m["preds"].shape == (B,)
    assert m["labels"].shape == (B,)

    reg = MetricRegistry()
    reg.init_metric("e2e", bucket_size=100)
    reg.add_all(m)
    assert reg.get_metric("e2e")["ins_num"] == B
