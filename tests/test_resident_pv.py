"""Resident join-phase (pv) feed: PvPlan + device-resident rank_offset/
ins_weight stacks (train/resident_step.py pv tier).

Equality contract: the resident pv tier, the plan-driven host packer, and
the original record-level pv path all train to the same losses / AUC /
trained table — batch composition is identical by construction (PvPlan is
pack_pv_batches materialized), so any divergence is a batch-assembly bug.
"""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.data.pv_instance import build_pv_plan, pack_pv_batches
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
from tests.test_pv_phase import RankDeepFM, _logkey

S = 3  # sparse slots


def _write_pv_file(path, rng, n_queries=40, n_slots=S):
    lines = []
    for q in range(1, n_queries + 1):
        n_ads = int(rng.integers(1, 4))
        for r in range(1, n_ads + 1):
            keys = rng.integers(1, 150, n_slots)
            label = 1.0 if (keys % 5 == 0).any() else 0.0
            parts = [f"1 {_logkey(q, 222, r)}", f"1 {label}"] + [
                f"1 {k}" for k in keys
            ]
            lines.append(" ".join(parts))
    # fixture writer: path derives from tmp_path (helper param hides it)
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
        parse_logkey=True,
    )


def _fresh(tmp_path, batch_size=16, mesh=None, n_shards=2):
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(
        layout, SparseOptimizerConfig(embedx_threshold=0.0),
        n_shards=n_shards, seed=0,
    )
    kw = {"n_mesh_shards": n_shards} if mesh is not None else {}
    ds = BoxPSDataset(
        _schema(), table, batch_size=batch_size, shuffle_mode="none", **kw
    )
    path = tmp_path / "pv.txt"
    tmp_path.mkdir(parents=True, exist_ok=True)
    _write_pv_file(str(path), np.random.default_rng(0))
    ds.set_filelist([str(path)])
    ds.load_into_memory()
    ds.begin_pass(round_to=16)
    model = RankDeepFM(S, layout.pull_width, layout.embedx_dim)
    per_dev = batch_size // (mesh.n_devices if mesh is not None else 1)
    cfg = TrainStepConfig(
        num_slots=S, batch_size=per_dev, layout=layout,
        sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
        auc_buckets=1000, model_takes_rank_offset=True,
        axis_name=mesh.axis if mesh is not None else None,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=mesh)
    tr.init_params(jax.random.PRNGKey(0))
    return ds, tr


def _train_join(tmp_path, resident: bool, plan_feed: bool = True, mesh_n: int = 0):
    """One join-phase pass; returns (metrics, trained table)."""
    prev = config.get_flag("enable_resident_feed")
    config.set_flag("enable_resident_feed", 1 if resident else 0)
    try:
        mesh = None
        if mesh_n:
            from paddlebox_tpu.parallel import make_mesh

            mesh = make_mesh(mesh_n)
        ds, tr = _fresh(tmp_path, mesh=mesh, n_shards=mesh_n or 2)
        if not plan_feed:
            # force the original record-level pv path
            ds.pv_plan = lambda *a, **k: None
        ds.set_current_phase(1)
        ds.preprocess_instance()
        out = tr.train_pass(ds)
        return out, np.asarray(tr.trained_table())
    finally:
        config.set_flag("enable_resident_feed", prev)


def test_pv_plan_materializes_pack_pv_batches(tmp_path):
    """plan.idx/rank_offset/ins_weight == the record-level pack stream."""
    ds, _ = _fresh(tmp_path)
    ds.set_current_phase(1)
    ds.preprocess_instance()
    plan = build_pv_plan(ds.pvs, ds.batch_size, n_devices=2)
    ref = list(pack_pv_batches(ds.pvs, ds.batch_size, n_devices=2))
    assert plan.n_batches == len(ref)
    for i, (recs, ro, w) in enumerate(ref):
        np.testing.assert_array_equal(
            plan.idx[i], [r._store_idx for r in recs]
        )
        np.testing.assert_array_equal(plan.rank_offset[i], ro)
        np.testing.assert_array_equal(plan.ins_weight[i], w)


def test_resident_pv_matches_host_packed(tmp_path):
    """Three-way equality: resident pv == plan-driven packer == original
    record-level path (losses, AUC, trained table)."""
    out_rec, tab_rec = _train_join(tmp_path / "rec", resident=False, plan_feed=False)
    out_pln, tab_pln = _train_join(tmp_path / "pln", resident=False)
    out_res, tab_res = _train_join(tmp_path / "res", resident=True)
    assert out_res["batches"] == out_pln["batches"] == out_rec["batches"]
    assert out_res["ins_num"] == out_pln["ins_num"] == out_rec["ins_num"]
    for a, b in ((out_pln, out_rec), (out_res, out_rec)):
        assert np.isclose(a["loss"], b["loss"], atol=1e-5)
        assert np.isclose(a["auc"], b["auc"], atol=1e-6)
    np.testing.assert_allclose(tab_pln, tab_rec, atol=1e-4)
    np.testing.assert_allclose(tab_res, tab_rec, atol=1e-4)


def test_resident_pv_mesh_matches_host_packed(tmp_path):
    """Single-host mesh join phase: resident pv (device-sharded plan
    stacks) == host-packed mesh pv."""
    out_h, tab_h = _train_join(tmp_path / "h", resident=False, mesh_n=4)
    out_r, tab_r = _train_join(tmp_path / "r", resident=True, mesh_n=4)
    assert out_r["batches"] == out_h["batches"]
    assert out_r["ins_num"] == out_h["ins_num"]
    assert np.isclose(out_r["loss"], out_h["loss"], atol=1e-5)
    assert np.isclose(out_r["auc"], out_h["auc"], atol=1e-6)
    np.testing.assert_allclose(tab_r, tab_h, atol=1e-4)


def test_resident_pv_eval_mode_is_identity(tmp_path):
    """Join-phase EVAL (set_test_mode) on the resident pv tier: metrics
    match the host-packed eval and state returns bit-identical."""
    prev = config.get_flag("enable_resident_feed")
    try:
        outs = {}
        for resident in (0, 1):
            config.set_flag("enable_resident_feed", resident)
            ds, tr = _fresh(tmp_path / f"e{resident}")
            ds.set_current_phase(1)
            ds.preprocess_instance()
            tr.train_pass(ds)  # one trained epoch first
            before = np.asarray(tr.trained_table())
            tr.set_test_mode(True)
            ev = tr.train_pass(ds)
            tr.set_test_mode(False)
            after = np.asarray(tr.trained_table())
            np.testing.assert_array_equal(before, after)  # eval writes nothing
            outs[resident] = ev
        assert np.isclose(outs[1]["loss"], outs[0]["loss"], atol=1e-5)
        assert np.isclose(outs[1]["auc"], outs[0]["auc"], atol=1e-6)
        assert outs[1]["ins_num"] == outs[0]["ins_num"]
    finally:
        config.set_flag("enable_resident_feed", prev)


def test_resident_pv_then_update_phase(tmp_path):
    """The resident join phase hands off to a resident update phase within
    one pass (two-phase lifecycle on the fast tier end-to-end)."""
    prev = config.get_flag("enable_resident_feed")
    config.set_flag("enable_resident_feed", 1)
    try:
        ds, tr = _fresh(tmp_path)
        ds.set_current_phase(1)
        n_pvs = ds.preprocess_instance()
        assert n_pvs == 40
        m_join = tr.train_pass(ds)
        assert np.isfinite(m_join["loss"])
        assert m_join["ins_num"] == ds.memory_data_size()  # ghosts masked
        tr.handoff_table(ds)
        ds.set_current_phase(0)
        ds.postprocess_instance()
        layout = ValueLayout(embedx_dim=4)
        cfg_upd = TrainStepConfig(
            num_slots=S, batch_size=16, layout=layout,
            sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
            auc_buckets=1000,
        )
        model2 = DeepFM(
            num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
        )
        tr2 = CTRTrainer(model2, cfg_upd, dense_opt=optax.adam(1e-2))
        tr2.init_params(jax.random.PRNGKey(0))
        m_upd = tr2.train_pass(ds)
        assert np.isfinite(m_upd["loss"])
        ds.end_pass(tr2.trained_table())
    finally:
        config.set_flag("enable_resident_feed", prev)
