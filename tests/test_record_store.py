"""Columnar record store + native batch packer: parity with the slow path.

The ColumnarRecords/BatchPacker tier re-expresses SlotRecord lists +
build_batch/pack_batch (data_feed.h:777-852 SlotRecord pool + data_feed.h:
1418-1542 MiniBatchGpuPack); these tests pin exact semantic equivalence so
the fast path can never drift from the oracle."""

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.data.device_pack import BatchPacker, pack_batch, pack_batch_sharded
from paddlebox_tpu.data.record_store import ColumnarRecords, _ragged_indices
from paddlebox_tpu.data.slot_record import SlotRecord, build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)

NS = 5


def make_schema(with_logkey=False):
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
        parse_logkey=with_logkey,
    )


def make_records(rng, n, with_meta=False):
    recs = []
    for i in range(n):
        lens = rng.integers(1, 4, NS)
        total = int(lens.sum())
        recs.append(
            SlotRecord(
                u64_values=rng.integers(1, 1000, total).astype(np.uint64),
                u64_offsets=np.concatenate([[0], np.cumsum(lens)]).astype(np.uint32),
                f_values=np.array([float(rng.integers(0, 2))], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
                ins_id=f"ins{i}" if with_meta else "",
                search_id=int(rng.integers(0, 50)) if with_meta else 0,
                cmatch=int(rng.integers(0, 4)) if with_meta else 0,
                rank=int(rng.integers(0, 3)) if with_meta else 0,
            )
        )
    return recs


def test_ragged_indices():
    starts = np.array([5, 0, 10], np.int64)
    lens = np.array([2, 0, 3], np.int64)
    assert _ragged_indices(starts, lens).tolist() == [5, 6, 10, 11, 12]
    assert len(_ragged_indices(np.zeros(0, np.int64), np.zeros(0, np.int64))) == 0


def test_from_records_roundtrip():
    rng = np.random.default_rng(0)
    schema = make_schema(with_logkey=True)
    recs = make_records(rng, 17, with_meta=True)
    store = ColumnarRecords.from_records(recs, schema)
    assert len(store) == 17
    back = store.records()
    for a, b in zip(recs, back):
        np.testing.assert_array_equal(a.u64_values, b.u64_values)
        np.testing.assert_array_equal(a.u64_offsets, b.u64_offsets)
        np.testing.assert_array_equal(a.f_values, b.f_values)
        assert (a.ins_id, a.search_id, a.cmatch, a.rank) == (
            b.ins_id, b.search_id, b.cmatch, b.rank,
        )


def test_select_and_concat():
    rng = np.random.default_rng(1)
    schema = make_schema(with_logkey=True)
    recs = make_records(rng, 20, with_meta=True)
    store = ColumnarRecords.from_records(recs, schema)
    idx = np.array([3, 0, 19, 7, 7])
    sel = store.select(idx)
    for j, i in enumerate(idx):
        a, b = recs[i], sel.record(j)
        np.testing.assert_array_equal(a.u64_values, b.u64_values)
        assert a.ins_id == b.ins_id and a.search_id == b.search_id
    cat = ColumnarRecords.concat([store.select(np.arange(10)), store.select(np.arange(10, 20))])
    assert len(cat) == 20
    for i in (0, 9, 10, 19):
        np.testing.assert_array_equal(cat.record(i).u64_values, recs[i].u64_values)
        assert cat.record(i).ins_id == recs[i].ins_id


# ---------------------------------------------------------------------------
# wire format v2 (compact raw column blocks, shuffle router payloads)
# ---------------------------------------------------------------------------

_WIRE_COLS = (
    "u64_values", "u64_offsets", "u64_base", "f_values", "f_offsets",
    "f_base", "search_ids", "cmatch", "rank",
)


def _assert_stores_equal(a, b):
    for col in _WIRE_COLS:
        np.testing.assert_array_equal(getattr(a, col), getattr(b, col))
    if a.ins_id_off is None:
        assert b.ins_id_off is None
    else:
        np.testing.assert_array_equal(a.ins_id_off, b.ins_id_off)
    assert bytes(a.ins_id_chars) == bytes(b.ins_id_chars)


@pytest.mark.parametrize("with_meta", [False, True])
def test_wire_v2_roundtrip(with_meta):
    rng = np.random.default_rng(7)
    schema = make_schema(with_logkey=with_meta)
    store = ColumnarRecords.from_records(
        make_records(rng, 23, with_meta=with_meta), schema
    )
    blob = store.to_bytes()
    assert blob[:4] == ColumnarRecords._WIRE_MAGIC
    back = ColumnarRecords.from_bytes(blob)
    _assert_stores_equal(store, back)
    assert back.record(5).ins_id == store.record(5).ins_id
    # decoded arrays stay writable (slots_shuffle mutates keys in place)
    assert back.u64_values.flags.writeable or len(back.u64_values) == 0


def test_wire_v2_empty_store():
    store = ColumnarRecords.empty(NS, 1)
    back = ColumnarRecords.from_bytes(store.to_bytes())
    assert len(back) == 0
    assert back.n_sparse == NS and back.n_float == 1


def test_wire_v2_smaller_than_npz():
    """The point of v2: no zip container, no per-array .npy headers."""
    import io

    rng = np.random.default_rng(9)
    store = ColumnarRecords.from_records(
        make_records(rng, 30, with_meta=True), make_schema(with_logkey=True)
    )
    bio = io.BytesIO()
    np.savez(
        bio,
        **{c: getattr(store, c) for c in _WIRE_COLS},
        ins_id_off=store.ins_id_off,
        ins_id_chars=np.frombuffer(store.ins_id_chars, np.uint8),
    )
    assert len(store.to_bytes()) < len(bio.getvalue())


def test_wire_v1_npz_still_decodes():
    """Back-compat: a legacy np.savez payload (zip magic) still loads."""
    import io

    rng = np.random.default_rng(11)
    store = ColumnarRecords.from_records(
        make_records(rng, 12, with_meta=True), make_schema(with_logkey=True)
    )
    bio = io.BytesIO()
    np.savez(
        bio,
        **{c: getattr(store, c) for c in _WIRE_COLS},
        ins_id_off=store.ins_id_off,
        ins_id_chars=np.frombuffer(store.ins_id_chars, np.uint8),
    )
    back = ColumnarRecords.from_bytes(bio.getvalue())
    _assert_stores_equal(store, back)


def test_wire_v2_malformed_rejected():
    rng = np.random.default_rng(13)
    store = ColumnarRecords.from_records(
        make_records(rng, 8, with_meta=True), make_schema(with_logkey=True)
    )
    blob = store.to_bytes()
    with pytest.raises(ValueError):
        ColumnarRecords.from_bytes(b"garbage-not-a-payload")
    with pytest.raises(ValueError):
        ColumnarRecords.from_bytes(blob[:-3])  # truncated columns
    with pytest.raises(ValueError):
        ColumnarRecords.from_bytes(blob + b"xx")  # trailing bytes
    bad = bytearray(blob)
    bad[4] = 99  # unsupported version
    with pytest.raises(ValueError):
        ColumnarRecords.from_bytes(bytes(bad))


def _setup_pass(rng, n, n_mesh=1):
    schema = make_schema()
    recs = make_records(rng, n)
    store = ColumnarRecords.from_records(recs, schema)
    layout = ValueLayout(embedx_dim=8)
    table = HostSparseTable(layout, SparseOptimizerConfig(), n_shards=4)
    ws = PassWorkingSet(n_mesh_shards=n_mesh)
    ws.add_keys(store.u64_values)
    ws.finalize(table, round_to=64)
    return schema, recs, store, ws


@pytest.mark.parametrize("use_native", [True, False])
def test_packer_matches_pack_batch(use_native):
    rng = np.random.default_rng(2)
    schema, recs, store, ws = _setup_pass(rng, 24)
    old = config.get_flag("enable_native_parser")
    config.set_flag("enable_native_parser", use_native)
    try:
        packer = BatchPacker(store, ws, schema, bucket=16)
        idx = np.arange(8)
        fast = packer.pack(idx)
        slow = pack_batch(build_batch(recs[:8], schema), ws, schema, bucket=16)
        # semantics: identical flat (row, segment) streams and label vector;
        # dedup ordering may differ (sorted vs first-occurrence)
        assert fast.n_keys == slow.n_keys and fast.n_uniq == slow.n_uniq
        L = fast.n_keys
        np.testing.assert_array_equal(fast.segments[:L], slow.segments[:L])
        np.testing.assert_array_equal(
            fast.uniq_rows[fast.inverse[:L]], slow.uniq_rows[slow.inverse[:L]]
        )
        np.testing.assert_array_equal(
            np.sort(fast.uniq_rows[: fast.n_uniq]),
            np.sort(slow.uniq_rows[: slow.n_uniq]),
        )
        np.testing.assert_array_equal(fast.labels, slow.labels)
        packer.close()
    finally:
        config.set_flag("enable_native_parser", old)


@pytest.mark.parametrize("use_native", [True, False])
def test_packer_sharded_matches(use_native):
    rng = np.random.default_rng(3)
    schema, recs, store, ws = _setup_pass(rng, 32, n_mesh=4)
    old = config.get_flag("enable_native_parser")
    config.set_flag("enable_native_parser", use_native)
    try:
        packer = BatchPacker(store, ws, schema, bucket=8)
        idx = np.arange(16)
        fast = packer.pack_sharded(idx, 4)
        slow = pack_batch_sharded(build_batch(recs[:16], schema), ws, schema, 4, bucket=8)

        # K differs by design (fast adds first-batch headroom); compare the
        # decoded per-key table rows, which must be identical
        def flat_rows(sdb):
            K = sdb.req_ranks.shape[2]
            out = []
            for d in range(4):
                inv = sdb.inverse[d]
                s, j = inv // K, inv % K
                out.append(
                    sdb.req_ranks[d, s, j].astype(np.int64) + s * ws.capacity
                )
            return np.stack(out)

        np.testing.assert_array_equal(flat_rows(fast), flat_rows(slow))
        np.testing.assert_array_equal(fast.segments, slow.segments)
        np.testing.assert_array_equal(fast.labels, slow.labels)
        packer.close()
    finally:
        config.set_flag("enable_native_parser", old)


def test_native_columnar_parse_matches_python(tmp_path):
    from paddlebox_tpu.data.parser import parse_line
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(4)
    schema = make_schema()
    lines = []
    for _ in range(30):
        parts = [f"1 {float(rng.integers(0, 2))}"]
        for _ in range(NS):
            n = int(rng.integers(1, 4))
            parts.append(f"{n} " + " ".join(str(rng.integers(1, 500)) for _ in range(n)))
        lines.append(" ".join(parts))
    p = tmp_path / "f.txt"
    p.write_text("\n".join(lines) + "\n")
    store = native.parse_file_columnar(str(p), schema)
    pys = [r for r in (parse_line(l, schema) for l in lines) if r is not None]
    assert len(store) == len(pys)
    for i, r in enumerate(pys):
        got = store.record(i)
        np.testing.assert_array_equal(got.u64_values, r.u64_values)
        np.testing.assert_array_equal(got.u64_offsets, r.u64_offsets)
        np.testing.assert_array_equal(got.f_values, r.f_values)


def test_prefetch_order_and_errors():
    from paddlebox_tpu.data.pipeline import prefetch

    out = list(prefetch(range(20), lambda x: x * x, workers=4, depth=5))
    assert out == [x * x for x in range(20)]

    def boom(x):
        if x == 7:
            raise ValueError("boom")
        return x

    got = []
    with pytest.raises(ValueError):
        for v in prefetch(range(20), boom, workers=4, depth=5):
            got.append(v)
    assert got == list(range(7))


def test_store_path_train_matches_slow_path(tmp_path):
    """End-to-end: native columnar store pipeline trains bit-identically to
    the SlotRecord list path (dedup order differs, results must not)."""
    import optax

    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.models import WideDeep
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(5)
    schema = make_schema()
    files = []
    for fi in range(2):
        lines = []
        for _ in range(40):
            parts = [f"1 {float(rng.integers(0, 2))}"]
            for _ in range(NS):
                n = int(rng.integers(1, 3))
                parts.append(
                    f"{n} " + " ".join(str(rng.integers(1, 300)) for _ in range(n))
                )
            lines.append(" ".join(parts))
        p = tmp_path / f"part-{fi}.txt"
        p.write_text("\n".join(lines) + "\n")
        files.append(str(p))

    layout = ValueLayout(embedx_dim=8)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
    losses = {}
    for native_on in (True, False):
        old = config.get_flag("enable_native_parser")
        config.set_flag("enable_native_parser", native_on)
        try:
            table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)
            ds = BoxPSDataset(schema, table, batch_size=16, shuffle_mode="local", seed=7)
            ds.set_filelist(files)
            ds.load_into_memory()
            assert (ds.store is not None) == native_on
            ds.begin_pass(round_to=64)
            model = WideDeep(
                num_slots=NS, feat_width=layout.pull_width, hidden=(16,)
            )
            cfg = TrainStepConfig(
                num_slots=NS, batch_size=16, layout=layout, sparse_opt=opt_cfg,
                auc_buckets=100,
            )
            tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), pack_bucket=32)
            tr.init_params(jax.random.PRNGKey(0))
            per_batch = []
            out = tr.train_pass(ds, on_batch=lambda i, m: per_batch.append(float(m["loss"])))
            ds.end_pass(tr.trained_table())
            losses[native_on] = (per_batch, out["auc"])
        finally:
            config.set_flag("enable_native_parser", old)
    assert losses[True][0] == losses[False][0]
    assert losses[True][1] == losses[False][1]


import jax  # noqa: E402  (used by the end-to-end test)
from paddlebox_tpu import config as config  # noqa: F811


def test_failing_pack_thread_mid_pass_surfaces_cleanly(tmp_path):
    """A pack worker dying mid-pass must surface its error at the failing
    batch's position (no hang, no silent truncation), and the trainer must
    stay usable for a retrain (the recovery path confirm/revert relies on)."""
    import optax

    from paddlebox_tpu import config
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.data.device_pack import BatchPacker
    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(8)
    schema = make_schema()
    lines = []
    for _ in range(96):
        parts = [f"1 {float(rng.integers(0, 2))}"]
        for _ in range(NS):
            parts.append(f"1 {rng.integers(1, 200)}")
        lines.append(" ".join(parts))
    p = tmp_path / "d.txt"
    p.write_text("\n".join(lines) + "\n")

    layout = ValueLayout(embedx_dim=4)
    opt = SparseOptimizerConfig(embedx_threshold=0.0)
    table = HostSparseTable(layout, opt, n_shards=2, seed=0)
    ds = BoxPSDataset(schema, table, batch_size=16, seed=0)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    model = LogisticRegression(num_slots=NS, feat_width=layout.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=16, layout=layout, sparse_opt=opt,
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))

    real_pack = BatchPacker.pack
    calls = {"n": 0}

    def failing_pack(self, idx):
        calls["n"] += 1
        # persistent death (not a one-shot hiccup, which the pipeline's
        # retry-once would heal): every call from the 4th on fails
        if calls["n"] >= 4:
            raise RuntimeError("pack thread died")
        return real_pack(self, idx)

    prev = config.get_flag("enable_resident_feed")
    config.set_flag("enable_resident_feed", 0)  # exercise the threaded packer
    try:
        BatchPacker.pack = failing_pack
        seen = []
        with pytest.raises(RuntimeError, match="pack thread died"):
            tr.train_pass(ds, n_batches=6, on_batch=lambda i, m: seen.append(i))
        # batches before the failing position were consumed in order
        assert seen == [0, 1, 2]
        BatchPacker.pack = real_pack
        out = tr.train_pass(ds, n_batches=6)  # trainer still usable
        assert out["batches"] == 6 and np.isfinite(out["loss"])
    finally:
        BatchPacker.pack = real_pack
        config.set_flag("enable_resident_feed", prev)


def test_frozen_shapes_compile_once_across_growing_batches(tmp_path):
    """freeze_shapes pins L/U pads from the whole partition upfront: a pass
    whose later batches have more keys/uniques than its first must still
    compile exactly ONE device program (classic path) / one scan program
    per chunk length (resident path)."""
    import optax

    from paddlebox_tpu import config
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(9)
    schema = make_schema()
    lines = []
    # keys-per-slot GROWS through the file: early batches are small, late
    # batches have 3x the keys and far more uniques
    for i in range(128):
        parts = [f"1 {float(rng.integers(0, 2))}"]
        n = 1 if i < 64 else 3
        for _ in range(NS):
            parts.append(
                f"{n} " + " ".join(str(rng.integers(1, 5000)) for _ in range(n))
            )
        lines.append(" ".join(parts))
    p = tmp_path / "d.txt"
    p.write_text("\n".join(lines) + "\n")

    def run(resident):
        layout = ValueLayout(embedx_dim=4)
        opt = SparseOptimizerConfig(embedx_threshold=0.0)
        table = HostSparseTable(layout, opt, n_shards=2, seed=0)
        ds = BoxPSDataset(schema, table, batch_size=16, shuffle_mode="none")
        ds.set_filelist([str(p)])
        ds.load_into_memory()
        ds.begin_pass(round_to=32)
        model = LogisticRegression(num_slots=NS, feat_width=layout.pull_width)
        cfg = TrainStepConfig(
            num_slots=NS, batch_size=16, layout=layout, sparse_opt=opt,
            auc_buckets=100,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr.init_params(jax.random.PRNGKey(0))
        prev = config.get_flag("enable_resident_feed")
        config.set_flag("enable_resident_feed", resident)
        try:
            tr.train_pass(ds)
        finally:
            config.set_flag("enable_resident_feed", prev)
        return tr

    tr = run(resident=0)
    assert tr._step._cache_size() == 1, "classic path must compile once"
    tr = run(resident=1)
    sizes = [s._cache_size() for s in tr._sstep_cache.values()]
    assert sizes and all(s <= 2 for s in sizes), (
        "resident superstep must compile once per chunk length "
        f"(full + tail), got cache sizes {sizes}"
    )
