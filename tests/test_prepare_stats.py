"""Native pass-prepare sweep (pbx_block_stats): the one-call counter sweep
must equal the per-block numpy unique/bincount it replaces (the reference
equalizes pass shapes with counters + one allreduce, data_set.cc:2069-2135
— this is the counter side, off the Python critical path)."""

import types

import numpy as np
import pytest

from paddlebox_tpu.train import resident_step
from paddlebox_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native tier unavailable"
)


def _synthetic_pass(rng, n_records=200, ns=4, cap=64, max_keys=7):
    counts = rng.integers(1, max_keys, n_records).astype(np.int64)
    base = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int64)
    total = int(counts.sum())
    rows = rng.integers(0, ns * cap, total).astype(np.int32)
    return rows, base, counts, ns, cap


def _oracle(rows, base, counts, blocks, cap, ns):
    Ls, bms = [], []
    for blk in blocks:
        rs = np.concatenate(
            [rows[base[r] : base[r] + counts[r]] for r in blk]
        ) if len(blk) else np.zeros(0, np.int32)
        Ls.append(len(rs))
        if len(rs):
            uniq = np.unique(rs)
            bms.append(int(np.bincount(uniq // cap, minlength=ns).max()))
        else:
            bms.append(0)
    return np.array(Ls), np.array(bms)


def test_block_stats_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    rows, base, counts, ns, cap = _synthetic_pass(rng)
    blocks = rng.integers(0, 200, (12, 16)).astype(np.int64)
    L, bm = native.block_stats(rows, base, counts, blocks, cap, ns)
    oL, obm = _oracle(rows, base, counts, blocks, cap, ns)
    np.testing.assert_array_equal(L, oL)
    np.testing.assert_array_equal(bm, obm)


def test_block_stats_single_shard_counts_total_uniques():
    """ns=1 is the single-device ensure() form: bmax == total uniques."""
    rng = np.random.default_rng(1)
    rows, base, counts, ns, cap = _synthetic_pass(rng, ns=1, cap=256)
    blocks = rng.integers(0, 200, (5, 32)).astype(np.int64)
    _, bm = native.block_stats(rows, base, counts, blocks, cap, 1)
    for i, blk in enumerate(blocks):
        rs = np.concatenate([rows[base[r] : base[r] + counts[r]] for r in blk])
        assert bm[i] == len(np.unique(rs))


def test_block_stats_rejects_out_of_range():
    rng = np.random.default_rng(2)
    rows, base, counts, ns, cap = _synthetic_pass(rng)
    bad = np.array([[0, 1, 10_000]], dtype=np.int64)  # record id OOR
    with pytest.raises(ValueError):
        native.block_stats(rows, base, counts, bad, cap, ns)


def _mk_rp(rng, ns, cap):
    rows, base, counts, _, _ = _synthetic_pass(rng, ns=ns, cap=cap)
    rp = types.SimpleNamespace(
        _host_rows=rows,
        _key_counts=counts,
        _mesh_cache={},
        _uniq_cache={},
        store=types.SimpleNamespace(u64_base=base),
        ws=types.SimpleNamespace(capacity=cap, n_mesh_shards=ns),
        transport=None,
        bucket=32,
        L_pad=0,
        K_pad=0,
        U_pad=0,
        n_table_rows=ns * cap,
        _seq=0,
    )
    return rp


def test_ensure_sharded_native_equals_python_fallback(monkeypatch):
    """The frozen pads must be identical whichever sweep computed them."""
    rng = np.random.default_rng(3)
    batches = [rng.integers(0, 200, 24) for _ in range(6)]

    rp_nat = _mk_rp(np.random.default_rng(3), ns=4, cap=64)
    resident_step.ensure_sharded(rp_nat, batches, n_devices=4)

    rp_py = _mk_rp(np.random.default_rng(3), ns=4, cap=64)
    monkeypatch.setattr(native, "available", lambda: False)
    resident_step.ensure_sharded(rp_py, batches, n_devices=4)

    assert (rp_nat.L_pad, rp_nat.K_pad) == (rp_py.L_pad, rp_py.K_pad)
    assert rp_nat._mesh_cache == rp_py._mesh_cache
    assert rp_nat.L_pad > 0 and rp_nat.K_pad > 0


def test_ensure_native_equals_python_fallback(monkeypatch):
    """Single-device ensure(): L_pad/U_pad identical under both sweeps."""
    rng = np.random.default_rng(4)
    batches = [rng.integers(0, 200, 16) for _ in range(5)]

    rp_nat = _mk_rp(np.random.default_rng(4), ns=1, cap=512)
    resident_step.ResidentPass.ensure(rp_nat, batches)

    rp_py = _mk_rp(np.random.default_rng(4), ns=1, cap=512)
    monkeypatch.setattr(native, "available", lambda: False)
    resident_step.ResidentPass.ensure(rp_py, batches)

    assert (rp_nat.L_pad, rp_nat.U_pad) == (rp_py.L_pad, rp_py.U_pad)
    assert rp_nat._uniq_cache == rp_py._uniq_cache
    assert rp_nat.U_pad > 1
