"""Pass pipeline tests: file load, striping, shuffle routing, preload overlap,
pass lifecycle, and the multi-pass trainer loop.

Model: the reference's dataset permutation tests (test_dataset.py,
test_paddlebox_datafeed.py) — tiny inline files through the real pipeline.
"""

import os
import threading

import numpy as np
import pytest

from paddlebox_tpu.data import BoxPSDataset, LocalShuffleRouter, SlotInfo, SlotSchema
from paddlebox_tpu.data.dataset import shuffle_route
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)

NUM_SLOTS = 4
VOCAB = 80
LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(
    embed_lr=0.3, embedx_lr=0.3, embedx_threshold=0.0, initial_range=0.01,
    show_clk_decay=1.0, shrink_threshold=0.0,
)


def make_schema(with_logkey=False):
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NUM_SLOTS)],
        label_slot="label",
        parse_logkey=with_logkey,
    )


def write_files(tmp, n_files, lines_per_file, rng, with_logkey=False, key_w=None):
    paths = []
    if key_w is None:
        key_w = rng.normal(size=VOCAB + 2)
    for fi in range(n_files):
        lines = []
        for li in range(lines_per_file):
            ks = rng.integers(1, VOCAB + 1, NUM_SLOTS)
            lab = 1.0 if key_w[ks].sum() + rng.normal() * 0.3 > 0 else 0.0
            parts = []
            if with_logkey:
                sid = int(rng.integers(0, 8))
                # logkey layout: [0:11 pad][11:14 cmatch][14:16 rank][16:32 search_id]
                logkey = "0" * 11 + f"{li % 7:03x}" + f"{li % 3:02x}" + f"{sid:016x}"
                parts.append(f"1 {logkey}")
            parts.append(f"1 {lab:.1f}")
            parts += [f"1 {k}" for k in ks]
            lines.append(" ".join(parts))
        p = os.path.join(tmp, f"part-{fi:03d}.txt")
        # fixture writer: tmp is the caller's tmp_path
        # pbox-lint: disable=IO004
        open(p, "w").write("\n".join(lines) + "\n")
        paths.append(p)
    return paths


def test_load_begin_end(tmp_path):
    rng = np.random.default_rng(0)
    schema = make_schema()
    files = write_files(str(tmp_path), 3, 20, rng)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    ds = BoxPSDataset(schema, table, batch_size=8, read_threads=2)
    ds.set_date("20260101")
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.stats.files == 3
    assert ds.stats.lines == 60
    assert ds.memory_data_size() == 60
    dev = ds.begin_pass(round_to=32)
    assert dev.ndim == 3 and dev.shape[0] == 1
    assert ds.stats.keys == ds.ws.n_keys > 0
    assert ds.num_batches() == 60 // 8
    batches = list(ds.batches())
    assert len(batches) == 7
    assert all(b.batch_size == 8 for b in batches)
    info = ds.end_pass(trained_table=dev)
    assert ds.records == [] and ds.ws is None
    # all pass keys flushed into the host store
    assert len(table) > 0
    # glob patterns expand
    ds2 = BoxPSDataset(schema, table, batch_size=8)
    ds2.set_filelist([str(tmp_path / "part-*.txt")])
    assert len(ds2._filelist) == 3


def test_rank_striping(tmp_path):
    rng = np.random.default_rng(1)
    schema = make_schema()
    files = write_files(str(tmp_path), 5, 4, rng)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    seen = []
    for r in range(2):
        ds = BoxPSDataset(schema, table, batch_size=2, rank=r, nranks=2)
        ds.set_filelist(files)
        seen.append(set(ds._filelist))
    assert seen[0] | seen[1] == set(files)
    assert not (seen[0] & seen[1])
    assert len(seen[0]) == 3 and len(seen[1]) == 2  # strided, not blocked


def test_preload_overlap(tmp_path):
    rng = np.random.default_rng(2)
    schema = make_schema()
    files = write_files(str(tmp_path), 2, 30, rng)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    ds = BoxPSDataset(schema, table, batch_size=8)
    ds.set_filelist(files)
    ds.preload_into_memory()
    ds.wait_preload_done()
    assert ds.memory_data_size() == 60
    # preload error surfaces at wait
    ds2 = BoxPSDataset(schema, table, batch_size=8)
    ds2.set_filelist(["/nonexistent/file.txt"])
    ds2.preload_into_memory()
    with pytest.raises(FileNotFoundError):
        ds2.wait_preload_done()


def test_pipe_command(tmp_path):
    rng = np.random.default_rng(3)
    schema = make_schema()
    files = write_files(str(tmp_path), 1, 10, rng)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    ds = BoxPSDataset(schema, table, batch_size=2, pipe_command="cat")
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.memory_data_size() == 10


def test_global_shuffle_search_id_routing(tmp_path):
    rng = np.random.default_rng(4)
    schema = make_schema(with_logkey=True)
    files = write_files(str(tmp_path), 4, 25, rng, with_logkey=True)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    router = LocalShuffleRouter(2)
    nodes = []
    for r in range(2):
        ds = BoxPSDataset(
            schema, table, batch_size=4, rank=r, nranks=2,
            shuffle_mode="search_id", router=router,
        )
        ds.set_filelist(files)
        nodes.append(ds)
    # the reference loads nodes concurrently; exchange() interleaves
    ts = [threading.Thread(target=d.load_into_memory) for d in nodes]
    [t.start() for t in ts]
    [t.join() for t in ts]
    total = sum(d.memory_data_size() for d in nodes)
    assert total == 100
    for r, d in enumerate(nodes):
        assert d.memory_data_size() > 0
        for rec in d.records:
            assert rec.search_id % 2 == r


def test_shuffle_route_modes():
    from paddlebox_tpu.data.slot_record import SlotRecord

    recs = [
        SlotRecord(
            u64_values=np.array([1], np.uint64),
            u64_offsets=np.array([0, 1], np.uint32),
            f_values=np.zeros(0, np.float32),
            f_offsets=np.array([0], np.uint32),
            ins_id=f"ins{i}",
            search_id=i,
        )
        for i in range(20)
    ]
    assert shuffle_route(recs, 4, "search_id", 0) == [i % 4 for i in range(20)]
    by_ins = shuffle_route(recs, 4, "ins_id", 0)
    assert by_ins == shuffle_route(recs, 4, "ins_id", 99)  # seed-independent
    assert len(set(by_ins)) > 1
    r1 = shuffle_route(recs, 4, "random", 5)
    assert r1 == shuffle_route(recs, 4, "random", 5)
    with pytest.raises(ValueError):
        shuffle_route(recs, 4, "bogus", 0)


def test_trainer_multi_pass_with_preload(tmp_path):
    import optax

    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    rng = np.random.default_rng(5)
    key_w = rng.normal(size=VOCAB + 2) * 1.2
    schema = make_schema()
    day_files = {
        d: write_files(str(tmp_path / d), 2, 64, rng, key_w=key_w)
        for d in ("20260101", "20260102")
        if (tmp_path / d).mkdir() or True
    }
    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    ds = BoxPSDataset(schema, table, batch_size=16, shuffle_mode="local")
    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width, embedx_dim=4, hidden=(32, 16))
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=16, layout=LAYOUT, sparse_opt=OPT, auc_buckets=1000
    )
    trainer = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), pack_bucket=64)

    ds.set_date("20260101")
    ds.set_filelist(day_files["20260101"])
    ds.load_into_memory()
    results = []
    for i, day in enumerate(("20260101", "20260102")):
        ds.begin_pass(round_to=64)
        if i == 0:
            # next day's IO overlaps THIS pass's training (double buffering,
            # PreLoadIntoMemory parity)
            ds.set_date("20260102")
            ds.set_filelist(day_files["20260102"])
            ds.preload_into_memory()
        m = trainer.train_pass(ds)
        results.append(m)
        delta_dir = str(tmp_path / f"delta-{day}")
        info = ds.end_pass(
            trainer.trained_table(), need_save_delta=True, delta_dir=delta_dir
        )
        assert info["delta_keys"] > 0
        assert os.path.exists(os.path.join(delta_dir, "meta.json"))
        if i == 0:
            ds.wait_preload_done()
    assert results[0]["batches"] == 8.0
    # second day starts from day-1 embeddings: better than chance quickly
    assert results[1]["auc"] > 0.55
    assert results[1]["loss"] < results[0]["loss"] + 0.05

    # dense checkpoint roundtrip
    ckpt = str(tmp_path / "dense.npz")
    trainer.save_dense(ckpt)
    before = [np.asarray(x) for x in __import__("jax").tree.leaves(trainer.params)]
    trainer.load_dense(ckpt)
    after = [np.asarray(x) for x in __import__("jax").tree.leaves(trainer.params)]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)


def test_end_pass_async_overlaps_next_load(tmp_path):
    """end_pass_async runs writeback/decay in the background while the next
    pass loads; begin_pass barriers on it. Final table state must equal the
    fully-synchronous sequence."""
    import optax

    from paddlebox_tpu.models import LogisticRegression
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    rng = np.random.default_rng(5)
    files = write_files(tmp_path, 2, 64, rng)

    def run(async_end):
        layout = ValueLayout(embedx_dim=4)
        opt = SparseOptimizerConfig(embedx_threshold=0.0)
        table = HostSparseTable(layout, opt, n_shards=2, seed=0)
        ds = BoxPSDataset(make_schema(), table, batch_size=16, seed=0)
        model = LogisticRegression(num_slots=NUM_SLOTS, feat_width=layout.pull_width)
        cfg = TrainStepConfig(
            num_slots=NUM_SLOTS, batch_size=16, layout=layout,
            sparse_opt=opt, auc_buckets=100,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr.init_params(jax.random.PRNGKey(0))
        outs = []
        for day, fl in (("20260101", files), ("20260102", files)):
            ds.set_date(day)
            ds.set_filelist(fl)
            ds.load_into_memory()
            ds.begin_pass(round_to=32)
            tr.train_pass(ds)
            if async_end:
                ds.end_pass_async(tr.trained_table())
            else:
                outs.append(ds.end_pass(tr.trained_table()))
        if async_end:
            outs.append(ds.wait_end_pass())
        keys = np.sort(table.keys())
        return keys, table.pull_or_create(keys), outs[-1]

    import jax

    k_sync, v_sync, out_sync = run(False)
    k_async, v_async, out_async = run(True)
    np.testing.assert_array_equal(k_sync, k_async)
    np.testing.assert_allclose(v_sync, v_async, atol=0)
    assert out_sync["dropped"] == out_async["dropped"]


def test_end_pass_async_rejects_double_call(tmp_path):
    rng = np.random.default_rng(6)
    files = write_files(tmp_path, 1, 32, rng)
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(
        layout, SparseOptimizerConfig(embedx_threshold=0.0), n_shards=2, seed=0
    )
    ds = BoxPSDataset(make_schema(), table, batch_size=16, seed=0)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    ds.end_pass_async(None)
    with pytest.raises(RuntimeError, match="begin_pass first"):
        ds.end_pass_async(None)  # pass already closed
    ds.wait_end_pass()


def test_end_pass_async_failure_is_recoverable(tmp_path):
    """A worker failure (e.g. delta save to a broken path) re-opens the
    pass: begin_pass refuses to start a new one, and a retried end_pass
    completes with the same final state as a never-failed run."""
    rng = np.random.default_rng(7)
    files = write_files(tmp_path, 1, 32, rng)
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(
        layout, SparseOptimizerConfig(embedx_threshold=0.0), n_shards=2, seed=0
    )
    ds = BoxPSDataset(make_schema(), table, batch_size=16, seed=0)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    n_records = ds.memory_data_size()

    real_save = type(table).save_delta
    type(table).save_delta = lambda self, d: (_ for _ in ()).throw(
        OSError("disk full")
    )
    try:
        ds.end_pass_async(None, need_save_delta=True, delta_dir=str(tmp_path / "d"))
        with pytest.raises(OSError, match="disk full"):
            ds.wait_end_pass()
    finally:
        type(table).save_delta = real_save
    # the pass re-opened: data intact, new pass refused
    assert ds.memory_data_size() == n_records and ds.ws is not None
    with pytest.raises(RuntimeError, match="still open"):
        ds.begin_pass(round_to=32)
    # retry succeeds now that the fault is fixed
    out = ds.end_pass(None, need_save_delta=True, delta_dir=str(tmp_path / "d"))
    assert out["delta_keys"] >= 0
    assert not ds._in_pass
