"""pv-merge + rank_offset + two-phase join/update tests.

Mirrors the reference sequence (test_paddlebox_datafeed.py:103-119):
set_current_phase(1) -> preprocess_instance -> train -> set_current_phase(0)
-> postprocess_instance -> train -> end_pass; rank_offset semantics from
GetRankOffset (data_feed.cc:2531-2580)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data import (
    BoxPSDataset,
    SlotInfo,
    SlotSchema,
    build_rank_offset,
    merge_pv_instances,
    pack_pv_batches,
)
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ops import rank_attention
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train.train_step import TrainStepConfig
from paddlebox_tpu.train.trainer import CTRTrainer


def _rec(search_id, cmatch, rank, keys, label):
    keys = np.asarray(keys, np.uint64)
    return SlotRecord(
        u64_values=keys,
        u64_offsets=np.arange(len(keys) + 1, dtype=np.uint32),
        f_values=np.array([label], np.float32),
        f_offsets=np.array([0, 1], np.uint32),
        ins_id=f"ins_{search_id}_{rank}",
        search_id=search_id,
        cmatch=cmatch,
        rank=rank,
    )


def test_merge_and_flatten_roundtrip():
    recs = [
        _rec(7, 222, 1, [1, 2], 1.0),
        _rec(3, 222, 1, [3, 4], 0.0),
        _rec(7, 222, 2, [5, 6], 0.0),
        _rec(3, 223, 2, [7, 8], 1.0),
    ]
    pvs = merge_pv_instances(recs)
    assert [pv.search_id for pv in pvs] == [3, 7]
    assert [len(pv.ads) for pv in pvs] == [2, 2]


def test_rank_offset_matrix_reference_semantics():
    # pv of 3 ads ranks 1,2,3 + one invalid-cmatch ad
    recs = [
        _rec(1, 222, 1, [1], 0),
        _rec(1, 223, 2, [2], 0),
        _rec(1, 222, 3, [3], 0),
        _rec(1, 999, 1, [4], 0),  # cmatch not in {222,223} -> rank -1
    ]
    pvs = merge_pv_instances(recs, sort=False)
    ro = build_rank_offset(pvs, ins_number=5, max_rank=3)
    assert ro.shape == (5, 7)
    assert ro[0, 0] == 1 and ro[1, 0] == 2 and ro[2, 0] == 3
    assert ro[3, 0] == -1  # invalid cmatch
    assert ro[4, 0] == -1  # ghost row
    # peer columns bucket by peer rank: col 2m+1 = rank m+1, col 2m+2 = row
    for i in range(3):
        assert list(ro[i, 1::2]) == [1, 2, 3]
        assert list(ro[i, 2::2]) == [0, 1, 2]
    # invalid ad doesn't fill peer columns
    assert list(ro[3, 1:]) == [-1] * 6


def test_pack_pv_batches_whole_pv_and_ghosts():
    recs = [
        _rec(1, 222, 1, [1], 1),
        _rec(1, 222, 2, [2], 0),
        _rec(2, 222, 1, [3], 0),
        _rec(3, 222, 1, [4], 1),
        _rec(3, 222, 2, [5], 0),
    ]
    pvs = merge_pv_instances(recs)
    batches = list(pack_pv_batches(pvs, batch_size=4))
    assert len(batches) == 2
    recs0, ro0, w0 = batches[0]
    assert len(recs0) == 4
    # first batch holds pv1 (2 ads) + pv2 (1 ad) + 1 ghost
    assert list(w0) == [1, 1, 1, 0]
    assert ro0[3, 0] == -1  # ghost row rankless
    recs1, ro1, w1 = batches[1]
    assert list(w1) == [1, 1, 0, 0]
    # oversize pv rejected
    big = merge_pv_instances([_rec(9, 222, r + 1, [r + 10], 0) for r in range(5)])
    with pytest.raises(ValueError):
        list(pack_pv_batches(big, batch_size=4))


class RankDeepFM:
    """DeepFM + rank_attention tower over the pv rank matrix."""

    def __init__(self, num_slots, feat_width, embedx_dim, max_rank=3, hidden=(16,)):
        self.base = DeepFM(num_slots, feat_width, embedx_dim, hidden=hidden)
        self.max_rank = max_rank
        self.in_dim = num_slots * feat_width

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "base": self.base.init(k1),
            "rank_param": 0.01
            * jax.random.normal(k2, (self.max_rank * self.max_rank * self.in_dim, 1)),
        }

    def apply(self, params, slot_feats, dense=None, rank_offset=None):
        logit = self.base.apply(params["base"], slot_feats, dense)
        if rank_offset is not None:
            x = slot_feats.reshape(slot_feats.shape[0], -1)
            att = rank_attention(x, rank_offset, params["rank_param"], self.max_rank)
            logit = logit + att[:, 0]
        return logit


def _logkey(search_id, cmatch, rank):
    return "0" * 11 + format(cmatch, "03x") + format(rank, "02x") + format(search_id, "016x")


def _write_pv_file(path, rng, n_queries=60, n_slots=3):
    lines = []
    for q in range(1, n_queries + 1):
        n_ads = int(rng.integers(1, 4))
        for r in range(1, n_ads + 1):
            keys = rng.integers(1, 200, n_slots)
            label = 1.0 if (keys % 5 == 0).any() else 0.0
            parts = [f"1 {_logkey(q, 222, r)}", f"1 {label}"] + [f"1 {k}" for k in keys]
            lines.append(" ".join(parts))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def test_two_phase_join_update_end_to_end(tmp_path):
    """The full reference sequence on a tiny pv dataset."""
    rng = np.random.default_rng(0)
    n_slots = 3
    path = str(tmp_path / "pv.txt")
    _write_pv_file(path, rng, n_queries=60, n_slots=n_slots)

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(n_slots)],
        label_slot="label",
        parse_logkey=True,
    )
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, SparseOptimizerConfig(embedx_threshold=0.0))
    ds = BoxPSDataset(schema, table, batch_size=16)
    ds.set_date("20260729")
    ds.set_filelist([path])
    ds.load_into_memory()
    ds.begin_pass(round_to=64)

    model = RankDeepFM(n_slots, layout.pull_width, layout.embedx_dim)
    cfg_join = TrainStepConfig(
        num_slots=n_slots, batch_size=16, layout=layout,
        sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
        auc_buckets=1000, model_takes_rank_offset=True,
    )
    trainer = CTRTrainer(model, cfg_join, dense_opt=optax.adam(1e-2))

    # ---- join phase: pv-merged batches with rank_offset
    ds.set_current_phase(1)
    n_pvs = ds.preprocess_instance()
    assert n_pvs == 60
    m_join = trainer.train_pass(ds)
    assert np.isfinite(m_join["loss"])
    assert m_join["batches"] > 0
    # ghosts masked: counted instances == real records
    assert m_join["ins_num"] == ds.memory_data_size()

    # ---- update phase: flat batches, same trained table carries on
    ds.set_current_phase(0)
    ds.postprocess_instance()
    cfg_upd = TrainStepConfig(
        num_slots=n_slots, batch_size=16, layout=layout,
        sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0), auc_buckets=1000,
    )
    trainer2 = CTRTrainer(model, cfg_upd, dense_opt=optax.adam(1e-2))
    trainer2.params = trainer.params  # dense params carry across phases
    trainer2.opt_state = None
    trainer2.init_params = lambda rng=None: None  # keep carried params
    trainer2.opt_state = optax.adam(1e-2).init(trainer.params)
    m_upd = trainer2.train_pass(ds)
    assert np.isfinite(m_upd["loss"])

    out = ds.end_pass(trainer2.trained_table())
    assert out["dropped"] >= 0


def test_rank_attention_changes_join_logits(tmp_path):
    """rank_offset actually reaches the model in the join step."""
    n_slots = 2
    layout = ValueLayout(embedx_dim=4)
    model = RankDeepFM(n_slots, layout.pull_width, layout.embedx_dim)
    params = model.init(jax.random.PRNGKey(0))
    params["rank_param"] = params["rank_param"] + 1.0  # make attention visible
    B = 4
    feats = jnp.ones((B, n_slots, layout.pull_width))
    ro = np.full((B, 7), -1, np.int32)
    ro[0] = [1, 1, 0, 2, 1, -1, -1]
    ro[1] = [2, 1, 0, 2, 1, -1, -1]
    with_ro = model.apply(params, feats, None, jnp.asarray(ro))
    without = model.apply(params, feats, None, None)
    assert abs(float(with_ro[0] - without[0])) > 1e-3
    assert abs(float(with_ro[3] - without[3])) < 1e-6  # rankless row unchanged
