"""pv-merge + rank_offset + two-phase join/update tests.

Mirrors the reference sequence (test_paddlebox_datafeed.py:103-119):
set_current_phase(1) -> preprocess_instance -> train -> set_current_phase(0)
-> postprocess_instance -> train -> end_pass; rank_offset semantics from
GetRankOffset (data_feed.cc:2531-2580)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data import (
    BoxPSDataset,
    SlotInfo,
    SlotSchema,
    build_rank_offset,
    merge_pv_instances,
    pack_pv_batches,
)
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ops import rank_attention
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train.train_step import TrainStepConfig
from paddlebox_tpu.train.trainer import CTRTrainer


def _rec(search_id, cmatch, rank, keys, label):
    keys = np.asarray(keys, np.uint64)
    return SlotRecord(
        u64_values=keys,
        u64_offsets=np.arange(len(keys) + 1, dtype=np.uint32),
        f_values=np.array([label], np.float32),
        f_offsets=np.array([0, 1], np.uint32),
        ins_id=f"ins_{search_id}_{rank}",
        search_id=search_id,
        cmatch=cmatch,
        rank=rank,
    )


def test_merge_and_flatten_roundtrip():
    recs = [
        _rec(7, 222, 1, [1, 2], 1.0),
        _rec(3, 222, 1, [3, 4], 0.0),
        _rec(7, 222, 2, [5, 6], 0.0),
        _rec(3, 223, 2, [7, 8], 1.0),
    ]
    pvs = merge_pv_instances(recs)
    assert [pv.search_id for pv in pvs] == [3, 7]
    assert [len(pv.ads) for pv in pvs] == [2, 2]


def test_rank_offset_matrix_reference_semantics():
    # pv of 3 ads ranks 1,2,3 + one invalid-cmatch ad
    recs = [
        _rec(1, 222, 1, [1], 0),
        _rec(1, 223, 2, [2], 0),
        _rec(1, 222, 3, [3], 0),
        _rec(1, 999, 1, [4], 0),  # cmatch not in {222,223} -> rank -1
    ]
    pvs = merge_pv_instances(recs, sort=False)
    ro = build_rank_offset(pvs, ins_number=5, max_rank=3)
    assert ro.shape == (5, 7)
    assert ro[0, 0] == 1 and ro[1, 0] == 2 and ro[2, 0] == 3
    assert ro[3, 0] == -1  # invalid cmatch
    assert ro[4, 0] == -1  # ghost row
    # peer columns bucket by peer rank: col 2m+1 = rank m+1, col 2m+2 = row
    for i in range(3):
        assert list(ro[i, 1::2]) == [1, 2, 3]
        assert list(ro[i, 2::2]) == [0, 1, 2]
    # invalid ad doesn't fill peer columns
    assert list(ro[3, 1:]) == [-1] * 6


def test_pack_pv_batches_whole_pv_and_ghosts():
    recs = [
        _rec(1, 222, 1, [1], 1),
        _rec(1, 222, 2, [2], 0),
        _rec(2, 222, 1, [3], 0),
        _rec(3, 222, 1, [4], 1),
        _rec(3, 222, 2, [5], 0),
    ]
    pvs = merge_pv_instances(recs)
    batches = list(pack_pv_batches(pvs, batch_size=4))
    assert len(batches) == 2
    recs0, ro0, w0 = batches[0]
    assert len(recs0) == 4
    # first batch holds pv1 (2 ads) + pv2 (1 ad) + 1 ghost
    assert list(w0) == [1, 1, 1, 0]
    assert ro0[3, 0] == -1  # ghost row rankless
    recs1, ro1, w1 = batches[1]
    assert list(w1) == [1, 1, 0, 0]
    # oversize pv rejected
    big = merge_pv_instances([_rec(9, 222, r + 1, [r + 10], 0) for r in range(5)])
    with pytest.raises(ValueError):
        list(pack_pv_batches(big, batch_size=4))


def RankDeepFM(num_slots, feat_width, embedx_dim, max_rank=3, hidden=(16,)):
    """Test-shaped factory over the shared join-phase model
    (paddlebox_tpu.models.RankDeepFM)."""
    from paddlebox_tpu.models import RankDeepFM as _Shared

    return _Shared(
        DeepFM(num_slots, feat_width, embedx_dim, hidden=hidden),
        num_slots * feat_width,
        max_rank=max_rank,
    )


def _logkey(search_id, cmatch, rank):
    return "0" * 11 + format(cmatch, "03x") + format(rank, "02x") + format(search_id, "016x")


def _write_pv_file(path, rng, n_queries=60, n_slots=3):
    lines = []
    for q in range(1, n_queries + 1):
        n_ads = int(rng.integers(1, 4))
        for r in range(1, n_ads + 1):
            keys = rng.integers(1, 200, n_slots)
            label = 1.0 if (keys % 5 == 0).any() else 0.0
            parts = [f"1 {_logkey(q, 222, r)}", f"1 {label}"] + [f"1 {k}" for k in keys]
            lines.append(" ".join(parts))
    # fixture writer: path derives from tmp_path (helper param hides it)
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def test_two_phase_join_update_end_to_end(tmp_path):
    """The full reference sequence on a tiny pv dataset."""
    rng = np.random.default_rng(0)
    n_slots = 3
    path = str(tmp_path / "pv.txt")
    _write_pv_file(path, rng, n_queries=60, n_slots=n_slots)

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(n_slots)],
        label_slot="label",
        parse_logkey=True,
    )
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, SparseOptimizerConfig(embedx_threshold=0.0))
    ds = BoxPSDataset(schema, table, batch_size=16)
    ds.set_date("20260729")
    ds.set_filelist([path])
    ds.load_into_memory()
    ds.begin_pass(round_to=64)

    model = RankDeepFM(n_slots, layout.pull_width, layout.embedx_dim)
    cfg_join = TrainStepConfig(
        num_slots=n_slots, batch_size=16, layout=layout,
        sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0),
        auc_buckets=1000, model_takes_rank_offset=True,
    )
    trainer = CTRTrainer(model, cfg_join, dense_opt=optax.adam(1e-2))

    # ---- join phase: pv-merged batches with rank_offset
    ds.set_current_phase(1)
    n_pvs = ds.preprocess_instance()
    assert n_pvs == 60
    m_join = trainer.train_pass(ds)
    assert np.isfinite(m_join["loss"])
    assert m_join["batches"] > 0
    # ghosts masked: counted instances == real records
    assert m_join["ins_num"] == ds.memory_data_size()

    # ---- update phase: flat batches, same trained table carries on
    trainer.handoff_table(ds)  # join-phase sparse updates feed phase 2
    np.testing.assert_array_equal(
        ds.device_table.reshape(-1, layout.width), trainer.trained_table()
    )
    ds.set_current_phase(0)
    ds.postprocess_instance()
    cfg_upd = TrainStepConfig(
        num_slots=n_slots, batch_size=16, layout=layout,
        sparse_opt=SparseOptimizerConfig(embedx_threshold=0.0), auc_buckets=1000,
    )
    trainer2 = CTRTrainer(model, cfg_upd, dense_opt=optax.adam(1e-2))
    trainer2.params = trainer.params  # dense params carry across phases
    trainer2.opt_state = None
    trainer2.init_params = lambda rng=None: None  # keep carried params
    trainer2.opt_state = optax.adam(1e-2).init(trainer.params)
    m_upd = trainer2.train_pass(ds)
    assert np.isfinite(m_upd["loss"])

    out = ds.end_pass(trainer2.trained_table())
    assert out["dropped"] >= 0


def test_rank_attention_changes_join_logits(tmp_path):
    """rank_offset actually reaches the model in the join step."""
    n_slots = 2
    layout = ValueLayout(embedx_dim=4)
    model = RankDeepFM(n_slots, layout.pull_width, layout.embedx_dim)
    params = model.init(jax.random.PRNGKey(0))
    params["rank_param"] = params["rank_param"] + 1.0  # make attention visible
    B = 4
    feats = jnp.ones((B, n_slots, layout.pull_width))
    ro = np.full((B, 7), -1, np.int32)
    ro[0] = [1, 1, 0, 2, 1, -1, -1]
    ro[1] = [2, 1, 0, 2, 1, -1, -1]
    with_ro = model.apply(params, feats, None, jnp.asarray(ro))
    without = model.apply(params, feats, None, None)
    assert abs(float(with_ro[0] - without[0])) > 1e-3
    assert abs(float(with_ro[3] - without[3])) < 1e-6  # rankless row unchanged


def test_pack_pv_batches_device_blocked():
    """n_devices > 1: whole pvs stay inside one device block, rank_offset
    peer rows are device-local, tail batches pad every block."""
    recs = []
    for q in range(1, 8):
        for r in range(1, (q % 3) + 2):
            recs.append(_rec(q, 222, r, [q * 10 + r], 0))
    pvs = merge_pv_instances(recs)
    batches = list(pack_pv_batches(pvs, batch_size=8, n_devices=2))
    b = 4
    for recs_out, ro, w in batches:
        assert len(recs_out) == 8 and ro.shape == (8, 7) and w.shape == (8,)
        for d in range(2):
            block = recs_out[d * b : (d + 1) * b]
            blk_w = w[d * b : (d + 1) * b]
            # no pv split across blocks: every real record's search_id
            # appears only within this block
            sids = {r.search_id for r, wt in zip(block, blk_w) if wt > 0}
            for other in range(2):
                if other == d:
                    continue
                oblock = recs_out[other * b : (other + 1) * b]
                ow = w[other * b : (other + 1) * b]
                assert not sids & {
                    r.search_id for r, wt in zip(oblock, ow) if wt > 0
                }
            # rank_offset peer rows are LOCAL to the block
            peers = ro[d * b : (d + 1) * b, 2::2]
            assert peers.max() < b


def test_mesh_join_matches_single_device(tmp_path):
    """The sharded join step over device-blocked pv batches computes the
    same training as the single-device step fed identical batches (with
    rank_offset globalized for the flat layout)."""
    from paddlebox_tpu.data.device_pack import pack_batch, pack_batch_sharded
    from paddlebox_tpu.data.slot_record import build_batch
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.table import PassWorkingSet
    from paddlebox_tpu.train.train_step import (
        init_train_state,
        jit_train_step,
        make_train_step,
    )
    from paddlebox_tpu.train.sharded_step import (
        init_sharded_train_state,
        make_sharded_train_step,
    )

    rng = np.random.default_rng(1)
    n_slots, N_DEV, B = 3, 4, 16
    b = B // N_DEV
    layout = ValueLayout(embedx_dim=4)
    opt = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
    recs = []
    for q in range(1, 40):
        for r in range(1, int(rng.integers(1, 4)) + 1):
            keys = rng.integers(1, 150, n_slots)
            recs.append(_rec(q, 222, r, keys, float(keys[0] % 2)))
    pvs = merge_pv_instances(recs)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(n_slots)],
        label_slot="label",
    )
    model = RankDeepFM(n_slots, layout.pull_width, layout.embedx_dim)

    def run(mesh):
        table = HostSparseTable(layout, opt, n_shards=4, seed=0)
        ws = PassWorkingSet(n_mesh_shards=N_DEV if mesh else 1)
        for r in recs:
            ws.add_keys(r.u64_values)
        dev_table = ws.finalize(table, round_to=32)
        cfg = TrainStepConfig(
            num_slots=n_slots, batch_size=b if mesh else B, layout=layout,
            sparse_opt=opt, auc_buckets=500, model_takes_rank_offset=True,
            axis_name="dp" if mesh else None,
        )
        import jax.numpy as jnp

        if mesh:
            plan = make_mesh(N_DEV)
            step = make_sharded_train_step(model.apply, optax.adam(1e-2), cfg, plan)
            state = init_sharded_train_state(
                plan, dev_table, model.init(jax.random.PRNGKey(0)),
                optax.adam(1e-2), 500,
            )
        else:
            step = jit_train_step(make_train_step(model.apply, optax.adam(1e-2), cfg))
            state = init_train_state(
                jnp.asarray(dev_table.reshape(-1, layout.width)),
                model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 500,
            )
        losses = []
        # BOTH runs use the device-blocked packing so batches are identical
        for recs_b, ro, w in pack_pv_batches(pvs, B, n_devices=N_DEV):
            batch = build_batch(recs_b, schema)
            if mesh:
                db = pack_batch_sharded(batch, ws, schema, N_DEV, bucket=32)
                feed = {
                    k: jax.device_put(v, plan.batch_sharding)
                    for k, v in db.as_dict().items()
                }
                feed["ins_weight"] = jax.device_put(
                    w.reshape(N_DEV, b), plan.batch_sharding
                )
                feed["rank_offset"] = jax.device_put(
                    np.ascontiguousarray(ro.reshape(N_DEV, b, -1)),
                    plan.batch_sharding,
                )
            else:
                # globalize the device-local peer rows for the flat layout
                ro_g = ro.copy()
                for d in range(N_DEV):
                    blk = ro_g[d * b : (d + 1) * b, 2::2]
                    blk[blk >= 0] += d * b
                db = pack_batch(batch, ws, schema, bucket=128)
                feed = {k: jnp.asarray(v) for k, v in db.as_dict().items()}
                feed["ins_weight"] = jnp.asarray(w)
                feed["rank_offset"] = jnp.asarray(ro_g)
            state, m = step(state, feed)
            losses.append(float(m["loss"]))
        tbl = np.asarray(state.table).reshape(-1, layout.width)
        keys = ws.sorted_keys
        return losses, tbl[ws.lookup(keys)], keys

    losses1, t1, k1 = run(mesh=False)
    lossesN, tN, kN = run(mesh=True)
    np.testing.assert_allclose(losses1[0], lossesN[0], rtol=1e-5)
    np.testing.assert_allclose(losses1, lossesN, rtol=6e-3)
    # same keys, same trained values (row layouts differ 1- vs 4-shard)
    np.testing.assert_array_equal(k1, kN)
    np.testing.assert_allclose(t1, tN, rtol=2e-3, atol=1e-3)


def test_two_phase_join_update_on_mesh(tmp_path):
    """The full join(pv) -> update sequence through CTRTrainer on a
    4-device mesh (the config trainer.py:329-333 used to reject)."""
    from paddlebox_tpu.parallel import make_mesh

    rng = np.random.default_rng(0)
    n_slots, N_DEV = 3, 4
    path = str(tmp_path / "pv.txt")
    _write_pv_file(path, rng, n_queries=60, n_slots=n_slots)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(n_slots)],
        label_slot="label",
        parse_logkey=True,
    )
    layout = ValueLayout(embedx_dim=4)
    opt = SparseOptimizerConfig(embedx_threshold=0.0)
    table = HostSparseTable(layout, opt)
    ds = BoxPSDataset(schema, table, batch_size=16, n_mesh_shards=N_DEV)
    ds.set_date("20260729")
    ds.set_filelist([path])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)

    plan = make_mesh(N_DEV)
    model = RankDeepFM(n_slots, layout.pull_width, layout.embedx_dim)
    cfg = TrainStepConfig(
        num_slots=n_slots, batch_size=4, layout=layout, sparse_opt=opt,
        auc_buckets=1000, model_takes_rank_offset=True, axis_name=plan.axis,
    )
    trainer = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan)

    ds.set_current_phase(1)
    assert ds.preprocess_instance() == 60
    m_join = trainer.train_pass(ds)
    assert np.isfinite(m_join["loss"]) and m_join["batches"] > 0
    assert m_join["ins_num"] == ds.memory_data_size()  # ghosts masked

    ds.set_current_phase(0)
    ds.postprocess_instance()
    m_upd = trainer.train_pass(ds)
    assert np.isfinite(m_upd["loss"])
    out = ds.end_pass(trainer.trained_table())
    assert out["dropped"] >= 0
    # join-phase training actually landed in the host table
    got = table.pull_or_create(np.unique(np.concatenate(
        [r.u64_values for r in ds.records] if ds.records else [np.zeros(0, np.uint64)]
    )))
    assert np.all(got[:, layout.SHOW] > 0)
