"""Pipelined pass boundary: bitwise equivalence + fault healing.

The boundary pipeline (data/dataset.py feed stage, sparse_table prefetch
consumption, supervisor prefetch kick) is a pure overlap optimization — a
pipelined run must be BITWISE equal to the sequential boundary
(``boundary_pipeline=0``): same host rows, same dense params, same losses.
These tests pin that, plus the healing story for the three boundary fault
sites (a failed feed stage or writeback must never wedge the day loop).
Deterministic, CPU-only, tier-1.
"""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.table.sparse_table import PassWorkingSet
from paddlebox_tpu.train import (
    CheckpointManager,
    CTRTrainer,
    PassSupervisor,
    RetryPolicy,
    TrainStepConfig,
)
from paddlebox_tpu.utils.faultinject import (
    InjectedFault,
    fail_nth,
    fail_once,
    inject,
)

pytestmark = pytest.mark.chaos

S, B = 4, 16
DATE = "20260101"
# shrink_threshold=0 keeps the host-prefetch gate open (a shrinking table
# can drop prefetched keys at the boundary, so the gate disables the pull)
OPT = SparseOptimizerConfig(
    embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
)

FLAGS = ("boundary_pipeline", "boundary_prefetch_pull", "boundary_merge_threads")


@pytest.fixture(autouse=True)
def _restore_flags():
    prev = {f: config.get_flag(f) for f in FLAGS}
    prev_backoff = config.get_flag("fs_open_backoff_s")
    config.set_flag("fs_open_backoff_s", 0.0)
    yield
    for f, v in prev.items():
        config.set_flag(f, v)
    config.set_flag("fs_open_backoff_s", prev_backoff)


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def _write(path, seed, lo, hi, n=64):
    rng = np.random.default_rng(seed)
    path.parent.mkdir(parents=True, exist_ok=True)
    # fixture writer: path derives from tmp_path (helper param hides it)
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        for _ in range(n):
            parts = [f"1 {float(rng.integers(0, 2))}"]
            for _s in range(S):
                k = int(rng.integers(1, 3))
                parts.append(
                    f"{k} " + " ".join(str(v) for v in rng.integers(lo, hi, k))
                )
            f.write(" ".join(parts) + "\n")
    return str(path)


def _files(tmp_path, tag):
    # per-pass key ranges overlap partially, so every boundary sees both
    # carried-over keys (excluded from the prefetch) and genuinely new ones
    return [
        _write(tmp_path / tag / f"{DATE}-{p}.txt", p, 1 + 40 * p, 161 + 40 * p)
        for p in range(3)
    ]


def _stack(tag):
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, OPT, n_shards=2, seed=0)
    ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=layout, sparse_opt=OPT,
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    return table, ds, tr


def _final_state(table, tr):
    k = np.sort(table.keys())
    v = table.pull_or_create(k)
    dense = [np.asarray(x) for x in jax.tree.flatten((tr.params, tr.opt_state))[0]]
    return k, v, dense


def _assert_state_equal(a, b):
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    assert len(a[2]) == len(b[2])
    for x, y in zip(a[2], b[2]):
        np.testing.assert_array_equal(x, y)


# ---- direct two-pass flow: the prefetch is staged DETERMINISTICALLY by a
# synchronous in-pass load (no thread race on the _in_pass gate) ----------


def _two_pass(tmp_path, tag, pipeline):
    config.set_flag("boundary_pipeline", 1 if pipeline else 0)
    files = _files(tmp_path, tag)
    table, ds, tr = _stack(tag)
    ds.set_filelist([files[0]])
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    tr.prepare_pass(ds)
    outs = [tr.train_pass(ds)]
    # load pass 2 while pass 1 is live: the feed stage premerges and (gated)
    # prefetches host rows — its epoch stamp predates end_pass's decay, so
    # the consumer's decay compensation path is exercised for real
    ds.set_filelist([files[1]])
    ds.load_into_memory()
    prefetch = ds._boundary_prefetch
    ds.end_pass(tr.trained_table())
    ds.begin_pass(round_to=8)
    tr.prepare_pass(ds)
    outs.append(tr.train_pass(ds))
    ds.end_pass(tr.trained_table())
    return table, tr, outs, prefetch


def test_prefetch_consumed_bitwise_equals_sequential(tmp_path):
    t_on, tr_on, o_on, pf = _two_pass(tmp_path, "on", pipeline=True)
    # the pipelined run really staged a host prefetch (new keys exist in
    # pass 2, the live pass was finalized, shrink is off)
    assert pf is not None and len(pf["keys"]) > 0
    t_off, tr_off, o_off, pf_off = _two_pass(tmp_path, "off", pipeline=False)
    assert pf_off is None
    _assert_state_equal(_final_state(t_on, tr_on), _final_state(t_off, tr_off))
    for a, b in zip(o_on, o_off):
        assert a["loss"] == b["loss"] and a["auc"] == b["auc"]


def test_stage_pull_fault_heals_with_reload(tmp_path):
    """An injected failure in the feed stage's host prefetch fails that
    load cleanly (staged slot discarded, no wedge) and a plain reload
    stages it again — final state bitwise equals the never-faulted run."""
    config.set_flag("boundary_pipeline", 1)
    files = _files(tmp_path, "sp")
    table, ds, tr = _stack("sp")
    ds.set_filelist([files[0]])
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    tr.prepare_pass(ds)
    outs = [tr.train_pass(ds)]
    ds.set_filelist([files[1]])
    with inject(fail_once("boundary.stage_pull")) as plan:
        with pytest.raises(InjectedFault):
            ds.load_into_memory()
    assert plan.failures("boundary.stage_pull") == 1
    assert ds._staged is None and ds._boundary_prefetch is None
    ds.load_into_memory()  # heal: plain reload re-stages load AND prefetch
    assert ds._boundary_prefetch is not None
    ds.end_pass(tr.trained_table())
    ds.begin_pass(round_to=8)
    tr.prepare_pass(ds)
    outs.append(tr.train_pass(ds))
    ds.end_pass(tr.trained_table())

    t_c, tr_c, o_c, _ = _two_pass(tmp_path, "spc", pipeline=True)
    _assert_state_equal(_final_state(table, tr), _final_state(t_c, tr_c))
    for a, b in zip(outs, o_c):
        assert a["loss"] == b["loss"]


def test_writeback_fault_heals_on_endpass_retry(tmp_path):
    """boundary.writeback fires at the top of the end_pass worker: the
    failed end_pass re-opens the pass and a retried end_pass completes,
    with the staged next pass (and its prefetch) surviving untouched."""
    config.set_flag("boundary_pipeline", 1)
    files = _files(tmp_path, "wb")
    table, ds, tr = _stack("wb")
    ds.set_filelist([files[0]])
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    tr.prepare_pass(ds)
    outs = [tr.train_pass(ds)]
    ds.set_filelist([files[1]])
    ds.load_into_memory()
    assert ds._boundary_prefetch is not None
    with inject(fail_once("boundary.writeback")) as plan:
        with pytest.raises(InjectedFault):
            ds.end_pass(tr.trained_table())
    assert plan.failures("boundary.writeback") == 1
    assert ds._in_pass  # failed publish re-opened the pass
    assert ds._boundary_prefetch is not None  # staged next pass survives
    ds.end_pass(tr.trained_table())  # retry heals
    ds.begin_pass(round_to=8)
    tr.prepare_pass(ds)
    outs.append(tr.train_pass(ds))
    ds.end_pass(tr.trained_table())

    t_c, tr_c, o_c, _ = _two_pass(tmp_path, "wbc", pipeline=True)
    _assert_state_equal(_final_state(table, tr), _final_state(t_c, tr_c))
    for a, b in zip(outs, o_c):
        assert a["loss"] == b["loss"]


# ---- supervised day loop: prefetch kick + adoption + revert cancel ------


def _run_day(tmp_path, tag, pipeline, schedule=()):
    config.set_flag("boundary_pipeline", 1 if pipeline else 0)
    files = _files(tmp_path, tag)
    table, ds, tr = _stack(tag)
    cm = CheckpointManager(str(tmp_path / f"ckpt-{tag}"))
    sup = PassSupervisor(
        ds, tr, checkpoint=cm,
        retry=RetryPolicy(backoff_s=0.0, sleep=lambda s: None),
        round_to=8,
    )
    with inject(*schedule) as plan:
        outs = sup.run_day(DATE, [[f] for f in files])
    return table, ds, tr, sup, outs, plan


def test_supervised_day_pipelined_bitwise_equals_sequential(tmp_path):
    t_on, ds_on, tr_on, sup_on, o_on, probe = _run_day(
        tmp_path, "don", pipeline=True
    )
    # the kick staged every non-first pass's load through the feed stage
    assert probe.hits("boundary.premerge") >= 2
    assert sup_on.incidents == []
    assert ds_on._staged is None and ds_on._boundary_prefetch is None
    t_off, ds_off, tr_off, sup_off, o_off, _ = _run_day(
        tmp_path, "doff", pipeline=False
    )
    assert sup_off.incidents == []
    _assert_state_equal(
        _final_state(t_on, tr_on), _final_state(t_off, tr_off)
    )
    for a, b in zip(o_on, o_off):
        assert a["loss"] == b["loss"] and a["auc"] == b["auc"]


def test_mid_overlap_fault_cancels_staged_pass_and_retries(tmp_path):
    """A device fault mid-pass-2 — while pass 3's load may be staged or in
    flight — must revert pass 2, cancel the staged pass 3, retry, and
    land bitwise on the sequential run's state."""
    t_c, _, tr_c, _, o_c, probe = _run_day(tmp_path, "mc", pipeline=True)
    steps_per_pass = probe.hits("step.device") // 3
    assert steps_per_pass >= 1

    t_i, ds_i, tr_i, sup_i, o_i, plan = _run_day(
        tmp_path, "mi", pipeline=True,
        schedule=(fail_nth("step.device", steps_per_pass + 2),),
    )
    assert plan.failures("step.device") == 1
    kinds = [(i.kind, i.action) for i in sup_i.incidents]
    assert ("train_error", "revert_retry") in kinds
    assert all(o is not None for o in o_i)
    assert ds_i._staged is None and ds_i._boundary_prefetch is None
    _assert_state_equal(_final_state(t_i, tr_i), _final_state(t_c, tr_c))
    for a, b in zip(o_i, o_c):
        assert a["loss"] == b["loss"]

    # and the whole faulted pipelined day equals the sequential day too
    t_s, _, tr_s, _, o_s, _ = _run_day(tmp_path, "ms", pipeline=False)
    _assert_state_equal(_final_state(t_i, tr_i), _final_state(t_s, tr_s))


def test_premerge_fault_becomes_load_retry(tmp_path):
    """boundary.premerge failing inside a kicked (or direct) load must
    surface as a plain load failure the supervisor's load retry absorbs —
    never a wedged 'staged pass not yet consumed' state."""
    t_i, ds_i, tr_i, sup_i, o_i, plan = _run_day(
        tmp_path, "pm", pipeline=True,
        schedule=(fail_once("boundary.premerge"),),
    )
    assert plan.failures("boundary.premerge") == 1
    assert all(o is not None for o in o_i)
    t_c, _, tr_c, _, o_c, _ = _run_day(tmp_path, "pmc", pipeline=True)
    _assert_state_equal(_final_state(t_i, tr_i), _final_state(t_c, tr_c))
    for a, b in zip(o_i, o_c):
        assert a["loss"] == b["loss"]


# ---- working-set mechanics ----------------------------------------------


def test_premerge_preserves_finalize_bitwise():
    """premerge (threaded) -> finalize must produce the identical working
    set to a finalize over the raw chunks: same keys, same row layout,
    same device table."""
    rng = np.random.default_rng(7)
    chunks = [rng.integers(1, 50_000, 4096).astype(np.uint64) for _ in range(5)]
    layout = ValueLayout(embedx_dim=4)

    def build(premerge):
        table = HostSparseTable(layout, OPT, n_shards=2, seed=0)
        ws = PassWorkingSet(n_mesh_shards=2)
        for c in chunks:
            ws.add_keys(c)
        if premerge:
            ws.premerge(threads=4)
        dev = ws.finalize(table, round_to=8)
        return ws, np.asarray(dev)

    ws_a, dev_a = build(premerge=False)
    ws_b, dev_b = build(premerge=True)
    np.testing.assert_array_equal(ws_b.sorted_keys, ws_a.sorted_keys)
    np.testing.assert_array_equal(ws_b.row_of_sorted, ws_a.row_of_sorted)
    assert ws_b.capacity == ws_a.capacity
    np.testing.assert_array_equal(dev_b, dev_a)


def test_premerge_after_finalize_rejected():
    ws = PassWorkingSet(n_mesh_shards=2)
    ws.add_keys(np.arange(1, 100, dtype=np.uint64))
    table = HostSparseTable(ValueLayout(embedx_dim=4), OPT, n_shards=2, seed=0)
    ws.finalize(table, round_to=8)
    with pytest.raises(RuntimeError, match="finalized"):
        ws.premerge()
