"""Fleet tier tests: DistributedStrategy translation, role maker env
parsing, ZeRO-1 optimizer-state sharding exactness on the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data.device_pack import pack_batch_sharded
from paddlebox_tpu.data.slot_record import build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.fleet import DistributedStrategy, RoleMaker, Zero1Optimizer
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import (
    TrainStepConfig,
    init_sharded_train_state,
    make_sharded_train_step,
)

from test_train_step import synth_records

NUM_SLOTS = 4
BATCH = 64
N_DEV = 8
LAYOUT = ValueLayout(embedx_dim=8)
OPT = SparseOptimizerConfig(
    embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.01,
    show_clk_decay=1.0, shrink_threshold=0.0,
)


# ---- strategy -----------------------------------------------------------

def test_strategy_translation():
    base = TrainStepConfig(num_slots=2, batch_size=8, layout=LAYOUT)
    s = DistributedStrategy()
    cfg, opt, _ = s.apply(base, optax.adam(1e-3))
    assert cfg.dense_sync_mode == "step"

    s = DistributedStrategy(a_sync=True)
    assert s.dense_sync_mode == "async"
    s = DistributedStrategy(a_sync=True, a_sync_configs={"k_steps": 8})
    assert s.dense_sync_mode == "kstep" and s.k_steps == 8
    s = DistributedStrategy(localsgd=True, localsgd_configs={"k_steps": 5})
    cfg, _, _ = s.apply(base, optax.adam(1e-3))
    assert cfg.dense_sync_mode == "kstep" and cfg.param_sync_step == 5

    s = DistributedStrategy(sharding=True)
    _, opt, _ = s.apply(base, optax.adam(1e-3), n_dev=4)
    assert isinstance(opt, Zero1Optimizer) and opt.n_dev == 4

    with pytest.raises(ValueError):
        DistributedStrategy(a_sync=True, localsgd=True)

    # recompute/amp wrap the model apply
    calls = []

    def apply_fn(p, x):
        calls.append(x.dtype)
        return jnp.sum(p["w"] * x)

    s = DistributedStrategy(amp=True)
    _, _, wrapped = s.apply(base, optax.adam(1e-3), model_apply=apply_fn)
    out = wrapped({"w": jnp.ones(3)}, jnp.ones(3))
    assert calls[-1] == jnp.bfloat16
    assert out.dtype == jnp.float32


def test_role_maker_env_dialects():
    r = RoleMaker.from_env({})
    assert r.rank == 0 and r.world == 1 and r.is_first_worker
    r = RoleMaker.from_env({"JAX_PROCESS_ID": "2", "JAX_NUM_PROCESSES": "4",
                            "JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234"})
    assert (r.rank, r.world, r.coordinator) == (2, 4, "10.0.0.1:1234")
    r = RoleMaker.from_env({"PADDLE_TRAINER_ID": "1", "PADDLE_TRAINERS_NUM": "2",
                            "POD_IP": "10.0.0.2", "PADDLE_PORT": "6170"})
    assert (r.rank, r.world, r.coordinator) == (1, 2, "10.0.0.2:6170")
    with pytest.raises(ValueError, match="coordinator"):
        RoleMaker.from_env({"PADDLE_TRAINER_ID": "1", "PADDLE_TRAINERS_NUM": "2"})
    with pytest.raises(ValueError, match="range"):
        RoleMaker.from_env({"JAX_PROCESS_ID": "5", "JAX_NUM_PROCESSES": "2",
                            "JAX_COORDINATOR_ADDRESS": "x:1"})


def test_role_maker_env_validation_names_offending_variable():
    """A malformed scheduler env must fail AT ROLE RESOLUTION with the
    variable named — not minutes later inside socket/rendezvous code."""
    # non-numeric rank, per dialect
    with pytest.raises(ValueError, match="JAX_PROCESS_ID='two'"):
        RoleMaker.from_env({"JAX_PROCESS_ID": "two", "JAX_NUM_PROCESSES": "4",
                            "JAX_COORDINATOR_ADDRESS": "x:1"})
    with pytest.raises(ValueError, match="PADDLE_TRAINER_ID='abc'"):
        RoleMaker.from_env({"PADDLE_TRAINER_ID": "abc",
                            "PADDLE_TRAINERS_NUM": "2",
                            "POD_IP": "10.0.0.2", "PADDLE_PORT": "6170"})
    # non-numeric world size
    with pytest.raises(ValueError, match="JAX_NUM_PROCESSES='many'"):
        RoleMaker.from_env({"JAX_PROCESS_ID": "0",
                            "JAX_NUM_PROCESSES": "many",
                            "JAX_COORDINATOR_ADDRESS": "x:1"})
    with pytest.raises(ValueError, match="PADDLE_TRAINERS_NUM=' '"):
        # whitespace-only is set-but-garbage, not unset
        RoleMaker.from_env({"PADDLE_TRAINER_ID": "0",
                            "PADDLE_TRAINERS_NUM": " "})
    # non-positive world
    with pytest.raises(ValueError, match="JAX_NUM_PROCESSES='0'"):
        RoleMaker.from_env({"JAX_PROCESS_ID": "0", "JAX_NUM_PROCESSES": "0"})
    # rank >= world names BOTH sources
    with pytest.raises(
        ValueError, match="PADDLE_TRAINER_ID='3'.*world 2"
    ):
        RoleMaker.from_env({"PADDLE_TRAINER_ID": "3",
                            "PADDLE_TRAINERS_NUM": "2",
                            "POD_IP": "h", "PADDLE_PORT": "1"})
    # missing coordinator names the world-size source that demanded one
    with pytest.raises(ValueError, match="JAX_NUM_PROCESSES='2'"):
        RoleMaker.from_env({"JAX_PROCESS_ID": "0", "JAX_NUM_PROCESSES": "2"})
    # POD_IP without PADDLE_PORT is still a missing coordinator
    with pytest.raises(ValueError, match="coordinator"):
        RoleMaker.from_env({"PADDLE_TRAINER_ID": "0",
                            "PADDLE_TRAINERS_NUM": "2", "POD_IP": "10.0.0.2"})


# ---- zero-1 -------------------------------------------------------------

def test_zero1_chunking_roundtrip():
    z = Zero1Optimizer(optax.adam(1e-2), axis_name="dp", n_dev=4)
    tree = {"a": jnp.arange(10, dtype=jnp.float32), "b": jnp.ones((3, 3))}
    chunks, unravel, n = z._chunks(tree)
    assert chunks.shape[0] == 4 and n == 19
    back = unravel(chunks.reshape(-1)[:n])
    for k in tree:
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(tree[k]))
    st = z.init_stacked(tree)
    # adam mu leaf is chunked [n_dev, c]
    mu = jax.tree.leaves(st)[1]
    assert mu.shape[0] == 4


def test_zero1_sharded_step_matches_plain(tmp_path):
    """ZeRO-1 trajectory must equal the replicated-adam trajectory exactly
    (adam is elementwise), with 1/n moment memory per device."""
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NUM_SLOTS)],
        label_slot="label",
    )
    rng = np.random.default_rng(21)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    recs = synth_records(rng, BATCH * 3, schema)
    ws = PassWorkingSet(n_mesh_shards=N_DEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev_table = ws.finalize(table, round_to=32)

    plan = make_mesh(N_DEV)
    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                   embedx_dim=8, hidden=(16,))
    params = model.init(jax.random.PRNGKey(0))
    paramsZ = model.init(jax.random.PRNGKey(0))
    cfg = TrainStepConfig(num_slots=NUM_SLOTS, batch_size=BATCH // N_DEV,
                          layout=LAYOUT, sparse_opt=OPT, auc_buckets=1000,
                          axis_name=plan.axis)

    plain = optax.adam(1e-2)
    zero = Zero1Optimizer(optax.adam(1e-2), axis_name=plan.axis, n_dev=N_DEV)
    stepP = make_sharded_train_step(model.apply, plain, cfg, plan)
    stepZ = make_sharded_train_step(model.apply, zero, cfg, plan)
    stP = init_sharded_train_state(plan, dev_table, params, plain, 1000)
    stZ = init_sharded_train_state(plan, dev_table, paramsZ, zero, 1000)

    # moment leaves really are 1/n per device
    mu_plain = sum(x.size for x in jax.tree.leaves(stP.opt_state))
    mu_zero_per_dev = sum(
        x.size // N_DEV for x in jax.tree.leaves(stZ.opt_state)
    )
    assert mu_zero_per_dev <= mu_plain // N_DEV + N_DEV * 4

    for i in range(5):
        batch_recs = [recs[(i * BATCH + j) % len(recs)] for j in range(BATCH)]
        db = pack_batch_sharded(build_batch(batch_recs, schema), ws, schema,
                                N_DEV, bucket=32)
        feed = {k: jax.device_put(v, plan.batch_sharding) for k, v in db.as_dict().items()}
        feed2 = jax.tree.map(jnp.copy, feed)
        stP, mP = stepP(stP, feed)
        stZ, mZ = stepZ(stZ, feed2)
        np.testing.assert_allclose(float(mP["loss"]), float(mZ["loss"]), rtol=1e-5)

    for a, b in zip(jax.tree.leaves(stP.params), jax.tree.leaves(stZ.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="kstep"):
        make_sharded_train_step(
            model.apply, zero,
            TrainStepConfig(num_slots=NUM_SLOTS, batch_size=8, layout=LAYOUT,
                            dense_sync_mode="kstep", axis_name=plan.axis),
            plan,
        )
