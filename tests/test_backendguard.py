"""Backend-init watchdog (utils/backendguard.py) and the persistent XLA
compile cache (utils/compilecache.py): wedged init must fall back to CPU
inside the configured deadline, and a warm cache must report hits."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.utils import compilecache
from paddlebox_tpu.utils.backendguard import (
    BackendVerdict,
    ensure_backend,
    probe_backend,
    probe_backend_with_retries,
)
from paddlebox_tpu.utils.faultinject import fail_always, fail_once, inject
from paddlebox_tpu.utils.monitor import STAT_GET


def test_wedged_init_falls_back_to_cpu_within_deadline():
    """The acceptance scenario: every probe wedges (injected at the
    backend.init site), and ensure_backend must return a labeled
    fallback_cpu verdict within retries x timeout — not hang."""
    timeout_s, retries = 2.0, 3
    deadline = retries * timeout_s + 5.0
    slept = []
    t0 = time.monotonic()
    with inject(fail_always("backend.init")) as plan:
        v = ensure_backend(
            timeout_s=timeout_s,
            retries=retries,
            backoff_s=0.0,
            probe="always",
            sleep=slept.append,  # no real sleeping between probes
        )
        assert plan.failures("backend.init") == retries
    elapsed = time.monotonic() - t0
    assert elapsed <= deadline
    assert v.verdict == "fallback_cpu"
    assert v.wedged and v.probed
    assert v.platform == "cpu" and v.n_devices >= 1
    assert "wedged" in (v.error or "")
    assert len(v.probe_log) == retries
    assert all(not e["ok"] for e in v.probe_log)
    assert len(slept) == retries - 1  # backoff between probes, not after last
    assert STAT_GET("backend.init_wedged") == 1
    # work continues on the fallback: the process has a live CPU backend
    assert float(jnp.sum(jnp.ones(4))) == 4.0


def test_wedged_verdict_serializes_for_artifacts():
    with inject(fail_always("backend.init")):
        v = ensure_backend(
            timeout_s=1.0, retries=1, probe="always", sleep=lambda s: None
        )
    d = v.as_dict()
    assert d["verdict"] == "fallback_cpu"
    assert d["wedged"] is True
    assert d["error"] and d["probe_log"]
    # ok verdicts omit the failure fields entirely
    ok = BackendVerdict(platform="cpu", n_devices=1, verdict="ok").as_dict()
    assert "error" not in ok and "probe_log" not in ok


def test_initialized_backend_short_circuits():
    """probe='auto' with a live in-process backend: no subprocess, verdict
    ok immediately (the zero-cost CI path)."""
    jnp.zeros(1).block_until_ready()  # force backend init
    before = STAT_GET("backend.init_probes")
    v = ensure_backend()
    assert v.verdict == "ok" and not v.probed and not v.wedged
    assert v.platform == jax.default_backend()
    assert STAT_GET("backend.init_probes") == before  # no probe ran


@pytest.mark.slow
def test_real_subprocess_probe_succeeds_on_cpu():
    """The actual watchdog path: a child python initializes jax and
    reports its platform (CPU here; TPU on hardware)."""
    info, err = probe_backend(timeout_s=180.0)
    assert err is None, err
    assert info["platform"] in ("cpu", "tpu", "gpu")
    assert info["n_devices"] >= 1


@pytest.mark.slow
def test_retry_recovers_from_transient_wedge():
    """fail_once wedges the first probe only; the second real probe
    succeeds and the log records one failure then one success."""
    with inject(fail_once("backend.init")) as plan:
        info, log = probe_backend_with_retries(
            timeout_s=180.0, retries=2, backoff_s=0.0, sleep=lambda s: None
        )
        assert plan.failures("backend.init") == 1
    assert info is not None
    assert [e["ok"] for e in log] == [False, True]


def test_ensure_backend_rejects_bad_probe_mode():
    with pytest.raises(ValueError):
        ensure_backend(probe="sometimes")


def test_resolve_dir_policy(tmp_path):
    for off in ("", "off", "none", None):
        assert compilecache.resolve_dir(off) is None
    # "auto" only engages under a durable checkpoint root
    assert compilecache.resolve_dir("auto") is None
    assert compilecache.resolve_dir("auto", ckpt_root=str(tmp_path)) == str(
        tmp_path / "compile_cache"
    )
    explicit = str(tmp_path / "cc")
    assert compilecache.resolve_dir(explicit) == explicit


def test_compile_cache_counts_hits(tmp_path):
    """Enable the persistent cache, compile the same program twice from
    distinct function objects: the second compile must be served from disk
    and counted as a hit — the mechanism behind the cold/warm warmup_s
    acceptance check in bench.py."""
    cache_dir = str(tmp_path / "compile_cache")
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        got = compilecache.enable(cache_dir)
        assert got == cache_dir and os.path.isdir(cache_dir)
        assert compilecache.enabled_dir() == cache_dir

        hits0 = STAT_GET("compile_cache.hits")
        misses0 = STAT_GET("compile_cache.misses")
        x = jnp.arange(64, dtype=jnp.float32)

        f_cold = jax.jit(lambda v: v * 3.0 + 1.0)
        cold = np.asarray(f_cold(x))
        assert STAT_GET("compile_cache.misses") > misses0  # populated disk
        assert len(os.listdir(cache_dir)) > 0

        # a DISTINCT function object with an identical jaxpr: jax's
        # in-memory jit cache can't serve it, the persistent cache must
        f_warm = jax.jit(lambda v: v * 3.0 + 1.0)
        warm = np.asarray(f_warm(x))
        assert STAT_GET("compile_cache.hits") > hits0
        np.testing.assert_array_equal(cold, warm)

        s = compilecache.stats()
        assert s["enabled"] and s["dir"] == cache_dir
        assert s["hits"] >= 1 and s["misses"] >= 1
        assert s["requests"] >= s["hits"] + s["misses"] - 1
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)


def test_legacy_env_maps_to_flags(monkeypatch):
    """PBOX_BENCH_INIT_* env (the pre-flag interface tpu_probe_loop and
    operators already use) must keep working by mapping onto the
    backend_init_* flags."""
    import bench

    old = {k: config.get_flag(k) for k in
           ("backend_init_timeout_s", "backend_init_retries",
            "backend_init_backoff_s")}
    monkeypatch.setenv("PBOX_BENCH_INIT_TIMEOUT", "7.5")
    monkeypatch.setenv("PBOX_BENCH_INIT_RETRIES", "2")
    monkeypatch.setenv("PBOX_BENCH_INIT_BACKOFF", "0.25")
    try:
        bench.apply_legacy_init_env()
        assert float(config.get_flag("backend_init_timeout_s")) == 7.5
        assert int(config.get_flag("backend_init_retries")) == 2
        assert float(config.get_flag("backend_init_backoff_s")) == 0.25
    finally:
        for k, v in old.items():
            config.set_flag(k, v)
