"""NaN/overflow guardrails (check_nan_var_names parity, trainer_desc.proto:43).

A batch whose loss or gradients go non-finite must be contained: no sparse
push, no dense update, no AUC contribution — the table state after the
poisoned batch equals the state before it, and training continues.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data.device_pack import pack_batch, pack_batch_sharded
from paddlebox_tpu.data.slot_record import SlotRecord, build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.metrics.auc import AUC_BUCKET_CAP, auc_compute, auc_init, auc_update
from paddlebox_tpu.models import LogisticRegression
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import TrainStepConfig
from paddlebox_tpu.train.train_step import (
    init_train_state,
    jit_train_step,
    make_train_step,
)

NS, B = 3, 8
LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)


def _records(rng, n, poison_labels=None):
    recs = []
    for i in range(n):
        keys = rng.integers(1, 100, NS).astype(np.uint64)
        label = float(keys[0] % 2)
        if poison_labels is not None and i in poison_labels:
            label = float("nan")
        recs.append(
            SlotRecord(
                u64_values=keys,
                u64_offsets=np.arange(NS + 1, dtype=np.uint32),
                f_values=np.array([label], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
            )
        )
    return recs


def _setup(check_nan, recs):
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ws = PassWorkingSet()
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)
    model = LogisticRegression(num_slots=NS, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=LAYOUT, sparse_opt=OPT,
        auc_buckets=100, check_nan=check_nan,
    )
    step = jit_train_step(make_train_step(model.apply, optax.adam(1e-2), cfg))
    state = init_train_state(
        jnp.asarray(dev.reshape(-1, LAYOUT.width)),
        model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 100,
    )
    return schema, ws, step, state


def test_poisoned_batch_contained():
    rng = np.random.default_rng(0)
    recs = _records(rng, 3 * B, poison_labels={B + 2})  # batch 1 poisoned
    schema, ws, step, state = _setup(True, recs)

    for bi in range(3):
        batch = build_batch(recs[bi * B : (bi + 1) * B], schema)
        db = pack_batch(batch, ws, schema, bucket=32)
        before_table = np.asarray(state.table)
        before_params = [np.asarray(x) for x in jax.tree.leaves(state.params)]
        before_auc = np.asarray(state.auc.pos).sum() + np.asarray(state.auc.neg).sum()
        state, m = step(state, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
        if bi == 1:
            assert int(m["nan_skipped"]) == 1
            # full containment: table, dense, AUC all untouched
            np.testing.assert_array_equal(np.asarray(state.table), before_table)
            for a, b in zip(jax.tree.leaves(state.params), before_params):
                np.testing.assert_array_equal(np.asarray(a), b)
            assert (
                np.asarray(state.auc.pos).sum() + np.asarray(state.auc.neg).sum()
                == before_auc
            )
        else:
            assert int(m["nan_skipped"]) == 0
            assert np.isfinite(float(m["loss"]))
            assert not np.array_equal(np.asarray(state.table), before_table)


def test_without_guard_poison_spreads():
    """The default (reference-default) path really is unguarded — pins that
    check_nan=True is what does the containment."""
    rng = np.random.default_rng(0)
    recs = _records(rng, 2 * B, poison_labels={2})
    schema, ws, step, state = _setup(False, recs)
    batch = build_batch(recs[:B], schema)
    db = pack_batch(batch, ws, schema, bucket=32)
    state, m = step(state, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
    assert "nan_skipped" not in m
    assert not np.isfinite(np.asarray(state.table)).all()


def test_mesh_poisoned_batch_contained():
    """One poisoned device skips the batch on EVERY device (shared table)."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train.sharded_step import (
        init_sharded_train_state,
        make_sharded_train_step,
    )

    N_DEV = 4
    rng = np.random.default_rng(1)
    recs = _records(rng, 2 * N_DEV * B, poison_labels={N_DEV * B + 3})
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ws = PassWorkingSet(n_mesh_shards=N_DEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)
    plan = make_mesh(N_DEV)
    model = LogisticRegression(num_slots=NS, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=LAYOUT, sparse_opt=OPT,
        auc_buckets=100, check_nan=True, axis_name=plan.axis,
    )
    step = make_sharded_train_step(model.apply, optax.adam(1e-2), cfg, plan)
    state = init_sharded_train_state(
        plan, dev, model.init(jax.random.PRNGKey(0)), optax.adam(1e-2), 100
    )
    GB = N_DEV * B
    for bi in range(2):
        batch = build_batch(recs[bi * GB : (bi + 1) * GB], schema)
        db = pack_batch_sharded(batch, ws, schema, N_DEV, bucket=32)
        feed = {
            k: jax.device_put(v, plan.batch_sharding)
            for k, v in db.as_dict().items()
        }
        before = np.asarray(state.table)
        state, m = step(state, feed)
        if bi == 1:
            assert int(m["nan_skipped"]) == 1
            np.testing.assert_array_equal(np.asarray(state.table), before)
        else:
            assert int(m["nan_skipped"]) == 0
            assert not np.array_equal(np.asarray(state.table), before)


def test_trainer_reports_and_continues(tmp_path):
    """End-to-end: poisoned batch mid-pass -> out['nan_batches']==1, pass
    loss finite, training still learns."""
    import optax

    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.train import CTRTrainer

    rng = np.random.default_rng(0)
    path = tmp_path / "d.txt"
    with open(path, "w") as f:
        for i in range(96):
            keys = rng.integers(1, 200, NS)
            label = "nan" if i == 20 else f"{int(keys[0]) % 2}.0"
            f.write(f"1 {label} " + " ".join(f"1 {k}" for k in keys) + "\n")
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ds = BoxPSDataset(schema, table, batch_size=16, seed=0)
    ds.set_filelist([str(path)])
    model = LogisticRegression(num_slots=NS, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=16, layout=LAYOUT,
        sparse_opt=SparseOptimizerConfig(
            embed_lr=0.3, embedx_threshold=0.0, initial_range=0.01
        ),
        auc_buckets=500, check_nan=True,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    for _ in range(3):
        ds.load_into_memory()
        ds.begin_pass(round_to=32)
        out = tr.train_pass(ds)
        ds.end_pass(tr.trained_table(), shrink=False)
    assert out["nan_batches"] == 1.0
    assert np.isfinite(out["loss"])
    assert out["auc"] > 0.8  # the other batches still learned
    assert np.isfinite(table.pull_or_create(np.sort(table.keys()))).all()


def test_auc_bucket_saturation_guard():
    st = auc_init(4)
    st = st._replace(pos=jnp.full((4,), AUC_BUCKET_CAP - 1, jnp.int32))
    preds = jnp.full((16,), 0.6, jnp.float32)
    st = auc_update(st, preds, jnp.ones(16))
    # saturates at the cap instead of wrapping negative
    assert int(st.pos[2]) == int(AUC_BUCKET_CAP)
    out = auc_compute(st)
    assert out["saturated"] == 1.0
    st2 = auc_update(auc_init(4), preds, jnp.ones(16))
    assert auc_compute(st2)["saturated"] == 0.0


def test_train_pass_profile_stage_table(tmp_path):
    """TrainFilesWithProfiler parity: profile=True returns per-stage wall
    clock through utils/timer."""
    import optax

    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.train import CTRTrainer

    rng = np.random.default_rng(0)
    path = tmp_path / "d.txt"
    with open(path, "w") as f:
        for _ in range(64):
            keys = rng.integers(1, 100, NS)
            f.write(f"1 {int(keys[0]) % 2}.0 " + " ".join(f"1 {k}" for k in keys) + "\n")
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ds = BoxPSDataset(schema, table, batch_size=16, seed=0)
    ds.set_filelist([str(path)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)
    model = LogisticRegression(num_slots=NS, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=16, layout=LAYOUT, sparse_opt=OPT, auc_buckets=100
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    out = tr.train_pass(ds, profile=True)
    prof = out["profile"]
    assert set(prof) == {
        "feed_wait_s", "step_dispatch_s", "device_step_s", "host_metrics_s"
    }
    assert all(v >= 0 for v in prof.values())
    assert prof["device_step_s"] > 0  # profiling blocks per batch
    # unprofiled pass carries no table
    out2 = tr.train_pass(ds)
    assert "profile" not in out2


def test_kstep_sync_cadence_survives_skipped_boundary_batch():
    """A NaN-skipped batch doesn't advance the step counter, so a skipped
    param-sync boundary is retried on the next real batch instead of
    drifting for another K steps."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train.sharded_step import (
        init_sharded_train_state,
        make_sharded_train_step,
    )

    N_DEV, K = 4, 2
    rng = np.random.default_rng(3)
    # batch 1 poisoned: with K=2 it would have been the first sync boundary
    recs = _records(rng, 4 * N_DEV * B, poison_labels={N_DEV * B + 1})
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    table = HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)
    ws = PassWorkingSet(n_mesh_shards=N_DEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev = ws.finalize(table, round_to=32)
    plan = make_mesh(N_DEV)
    model = LogisticRegression(num_slots=NS, feat_width=LAYOUT.pull_width)
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=LAYOUT, sparse_opt=OPT,
        auc_buckets=100, check_nan=True, axis_name=plan.axis,
        dense_sync_mode="kstep", param_sync_step=K,
    )
    step = make_sharded_train_step(model.apply, optax.sgd(0.1), cfg, plan)
    state = init_sharded_train_state(
        plan, dev, model.init(jax.random.PRNGKey(0)), optax.sgd(0.1), 100,
        local_dense=True,
    )
    GB = N_DEV * B

    def replicas_equal(st):
        leaves = [np.asarray(x) for x in jax.tree.leaves(st.params)]
        return all(
            all(np.array_equal(leaf[0], leaf[d]) for d in range(1, N_DEV))
            for leaf in leaves
        )

    skipped = []
    for bi in range(4):
        batch = build_batch(recs[bi * GB : (bi + 1) * GB], schema)
        db = pack_batch_sharded(batch, ws, schema, N_DEV, bucket=32)
        feed = {
            k: jax.device_put(v, plan.batch_sharding)
            for k, v in db.as_dict().items()
        }
        state, m = step(state, feed)
        skipped.append(int(m["nan_skipped"]))
    assert skipped == [0, 1, 0, 0]
    # batches counted: 0, skip, 1, 2 -> step == 3; sync fired at step 2
    # (the retried boundary), so after the local step 3 replicas have
    # diverged again by exactly one local update from a COMMON sync point
    assert int(np.asarray(state.step)) == 3
    # rerun the boundary check: one more real batch lands step 4 == 2K -> sync
    batch = build_batch(recs[:GB], schema)
    db = pack_batch_sharded(batch, ws, schema, N_DEV, bucket=32)
    feed = {k: jax.device_put(v, plan.batch_sharding) for k, v in db.as_dict().items()}
    state, m = step(state, feed)
    assert int(np.asarray(state.step)) == 4
    assert replicas_equal(state), "2nd boundary sync must fire despite the skip"
