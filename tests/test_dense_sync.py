"""Dense sync modes: K-step/LocalSGD on the mesh + async dense table (B5/B6).

Model: the reference's sync_mode_ switch (DenseKStepNode/DenseKStepALL,
boxps_worker.cc:239-240, SyncParam :359-398) and BoxPSAsynDenseTable
(:35-237).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.data.device_pack import pack_batch, pack_batch_sharded
from paddlebox_tpu.data.slot_record import build_batch
from paddlebox_tpu.data.slot_schema import SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.table import (
    HostSparseTable,
    PassWorkingSet,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import (
    AsyncDenseTable,
    TrainStepConfig,
    init_sharded_train_state,
    kstep_sync_params,
    make_sharded_train_step,
    make_train_step,
)
from paddlebox_tpu.train.train_step import init_train_state, jit_train_step

from test_train_step import synth_records

NUM_SLOTS = 4
BATCH = 64
N_DEV = 8
LAYOUT = ValueLayout(embedx_dim=8)
OPT = SparseOptimizerConfig(
    embed_lr=0.2, embedx_lr=0.2, embedx_threshold=0.0, initial_range=0.01,
    show_clk_decay=1.0, shrink_threshold=0.0,
)


@pytest.fixture(scope="module")
def schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NUM_SLOTS)],
        label_slot="label",
    )


@pytest.fixture(scope="module")
def setup(schema):
    rng = np.random.default_rng(11)
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    recs = synth_records(rng, BATCH * 4, schema)
    ws = PassWorkingSet(n_mesh_shards=N_DEV)
    for r in recs:
        ws.add_keys(r.u64_values)
    dev_table = ws.finalize(table, round_to=32)
    return table, recs, ws, dev_table


def param_spread(state):
    """Max across leaves of max-abs spread between device replicas."""
    s = 0.0
    for x in jax.tree.leaves(state.params):
        x = np.asarray(x).astype(np.float64)
        s = max(s, np.abs(x - x[:1]).max())
    return s


def test_kstep_localsgd_mesh(schema, setup):
    table, recs, ws, dev_table = setup
    plan = make_mesh(N_DEV)
    K = 4
    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                   embedx_dim=8, hidden=(16,))
    dense_opt = optax.adam(1e-2)
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=BATCH // N_DEV, layout=LAYOUT,
        sparse_opt=OPT, auc_buckets=1000, axis_name=plan.axis,
        dense_sync_mode="kstep", param_sync_step=K,
    )
    step = make_sharded_train_step(model.apply, dense_opt, cfg, plan)
    st = init_sharded_train_state(
        plan, dev_table, model.init(jax.random.PRNGKey(0)), dense_opt, 1000,
        local_dense=True,
    )
    assert param_spread(st) == 0.0

    losses = []
    spreads = []
    for i in range(2 * K):
        batch_recs = [recs[(i * BATCH + j) % len(recs)] for j in range(BATCH)]
        db = pack_batch_sharded(build_batch(batch_recs, schema), ws, schema, N_DEV, bucket=32)
        feed = {k: jax.device_put(v, plan.batch_sharding) for k, v in db.as_dict().items()}
        st, m = step(st, feed)
        losses.append(float(m["loss"]))
        spreads.append(param_spread(st))

    # replicas diverge between syncs and re-converge exactly on sync steps
    # (steps are 1-based in the cond: sync when step % K == 0)
    for i, s in enumerate(spreads):
        if (i + 1) % K == 0:
            assert s < 1e-6, (i, s)
        else:
            assert s > 0, (i, s)
    assert losses[-1] < losses[0]

    # desync once more, then the pass-end sync equalizes replicas
    batch_recs = [recs[j % len(recs)] for j in range(BATCH)]
    db = pack_batch_sharded(build_batch(batch_recs, schema), ws, schema, N_DEV, bucket=32)
    feed = {k: jax.device_put(v, plan.batch_sharding) for k, v in db.as_dict().items()}
    st, _ = step(st, feed)
    assert param_spread(st) > 0
    st = kstep_sync_params(st, plan)
    assert param_spread(st) < 1e-6
    # a replicated ('step'-mode) state is rejected, not silently averaged
    rep_st = init_sharded_train_state(
        plan, dev_table, model.init(jax.random.PRNGKey(1)), dense_opt, 1000
    )
    with pytest.raises(ValueError, match="replica axis"):
        kstep_sync_params(rep_st, plan)


def test_async_dense_update_rule():
    """One pushed grad package must apply the exact reference rule
    (mom 0.99/0.9999, eps 1e-8, boxps_worker.cc:166-175)."""
    p0 = {"w": np.full(4, 1.0, np.float32), "b": np.zeros(2, np.float32)}
    t = AsyncDenseTable(p0, base_lr=0.1, lr_map={"b": 0.5})
    g = {"w": np.full(4, 2.0, np.float32), "b": np.ones(2, np.float32)}
    t.push_dense(g)
    deadline = time.time() + 5
    while t.n_updates < 1 and time.time() < deadline:
        time.sleep(0.01)
    got = t.finalize()
    m1w, m2w = 0.01 * 2.0, 0.0001 * 4.0
    want_w = 1.0 - 0.1 * (m1w / (np.sqrt(m2w) + 1e-8))
    np.testing.assert_allclose(got["w"], np.full(4, want_w), rtol=1e-6)
    m1b, m2b = 0.01 * 1.0, 0.0001 * 1.0
    want_b = 0.0 - 0.5 * (m1b / (np.sqrt(m2b) + 1e-8))  # lr_map override
    np.testing.assert_allclose(got["b"], np.full(2, want_b), rtol=1e-6)
    with pytest.raises(RuntimeError):
        t.push_dense(g)


def test_async_dense_training(schema, setup):
    """End-to-end async mode: device pushes grads, host table optimizes."""
    table, recs, ws, dev_table = setup
    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                   embedx_dim=8, hidden=(16,))
    dense_opt = optax.adam(1e-2)  # unused by the step in async mode
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=BATCH, layout=LAYOUT, sparse_opt=OPT,
        auc_buckets=1000, dense_sync_mode="async",
    )
    step = jit_train_step(make_train_step(model.apply, dense_opt, cfg))
    params0 = model.init(jax.random.PRNGKey(0))
    adt = AsyncDenseTable(params0, base_lr=0.05)
    st = init_train_state(
        jnp.asarray(dev_table.reshape(-1, LAYOUT.width)), params0, dense_opt, 1000
    )
    losses = []
    for i in range(24):
        st = st._replace(params=jax.device_put(adt.pull_dense()))
        batch_recs = [recs[(i * BATCH + j) % len(recs)] for j in range(BATCH)]
        db = pack_batch(build_batch(batch_recs, schema), ws, schema, bucket=64)
        st, m = step(st, {k: jnp.asarray(v) for k, v in db.as_dict().items()})
        adt.push_dense(jax.tree.map(np.asarray, m["gparams"]))
        losses.append(float(m["loss"]))
    final = adt.finalize()
    assert adt.n_updates > 0
    # params moved and training improved
    moved = max(
        np.abs(np.asarray(a) - np.asarray(b)).max()
        for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(params0))
    )
    assert moved > 1e-4
    assert np.mean(losses[-6:]) < np.mean(losses[:6])


def test_mode_validation():
    with pytest.raises(ValueError, match="dense_sync_mode"):
        TrainStepConfig(num_slots=2, batch_size=4, layout=LAYOUT,
                        dense_sync_mode="k-step")
    from paddlebox_tpu.models import DeepFM as _D
    plan = make_mesh(N_DEV)
    cfg = TrainStepConfig(num_slots=2, batch_size=4, layout=LAYOUT,
                          dense_sync_mode="async")
    m = _D(num_slots=2, feat_width=LAYOUT.pull_width, embedx_dim=8, hidden=(4,))
    # async on a single-host mesh is supported (round 4); ZeRO + async is
    # contradictory (the host owns the optimizer)
    from paddlebox_tpu.fleet.zero import Zero1Optimizer

    with pytest.raises(ValueError, match="ZeRO"):
        make_sharded_train_step(
            m.apply, Zero1Optimizer(optax.adam(1e-3), axis_name=plan.axis),
            cfg, plan,
        )
    from paddlebox_tpu.train import CTRTrainer
    with pytest.raises(ValueError, match="AsyncDenseTable"):
        CTRTrainer(m, cfg)


def test_async_lr_map_suffix_matching():
    p = {"mlp": {"w0": np.zeros(2, np.float32), "w1": np.zeros(2, np.float32)},
         "w": np.zeros(2, np.float32)}
    t = AsyncDenseTable(p, base_lr=0.1, lr_map={"w0": 0.5, "mlp/w1": 0.25})
    try:
        lrs = dict(zip(["mlp/w0", "mlp/w1", "w"], t._leaf_lr))
        assert lrs["mlp/w0"] == np.float32(0.5)
        assert lrs["mlp/w1"] == np.float32(0.25)
        assert lrs["w"] == np.float32(0.1)  # "w" must NOT match "w0"/"w1"
    finally:
        t.finalize()


def test_trainer_async_dense_integration(tmp_path, schema):
    """CTRTrainer drives the pull/push loop itself in async mode."""
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.train import CTRTrainer

    rng = np.random.default_rng(5)
    key_w = rng.normal(size=70) * 1.5
    lines = []
    for _ in range(256):
        ks = rng.integers(1, 65, NUM_SLOTS)
        lab = 1.0 if key_w[ks].sum() + rng.normal() * 0.3 > 0 else 0.0
        lines.append(f"1 {lab:.1f} " + " ".join(f"1 {k}" for k in ks))
    p = tmp_path / "f.txt"
    p.write_text("\n".join(lines) + "\n")

    table = HostSparseTable(LAYOUT, OPT, n_shards=4)
    ds = BoxPSDataset(schema, table, batch_size=32, read_threads=1)
    ds.set_date("20260101")
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)

    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                   embedx_dim=8, hidden=(16,))
    params0 = model.init(jax.random.PRNGKey(0))
    adt = AsyncDenseTable(params0, base_lr=0.05)
    cfg = TrainStepConfig(num_slots=NUM_SLOTS, batch_size=32, layout=LAYOUT,
                          sparse_opt=OPT, auc_buckets=1000,
                          dense_sync_mode="async")
    tr = CTRTrainer(model, cfg, async_dense=adt)
    tr.params = params0
    tr.opt_state = tr.dense_opt.init(params0)
    m = tr.train_pass(ds)
    assert m["batches"] == 8
    assert adt.n_updates > 0
    moved = max(
        np.abs(np.asarray(a) - np.asarray(b)).max()
        for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(params0))
    )
    assert moved > 1e-5
    adt.finalize()
    ds.end_pass(tr.trained_table())


def test_trainer_async_dense_on_mesh(tmp_path, schema):
    """Async dense under the full mesh trainer (boxps_worker.cc:35-237 runs
    the async CPU dense table under the multi-GPU trainer): the shard_map'd
    step returns globally-reduced gparams, the host table optimizes, fresh
    params replicate back each batch. Training must move params and reduce
    loss; sparse training must match host expectations (real batches)."""
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.train import CTRTrainer

    rng = np.random.default_rng(7)
    key_w = rng.normal(size=70) * 1.5
    lines = []
    for _ in range(256):
        ks = rng.integers(1, 65, NUM_SLOTS)
        lab = 1.0 if key_w[ks].sum() + rng.normal() * 0.3 > 0 else 0.0
        lines.append(f"1 {lab:.1f} " + " ".join(f"1 {k}" for k in ks))
    p = tmp_path / "f.txt"
    p.write_text("\n".join(lines) + "\n")

    plan = make_mesh(N_DEV)
    table = HostSparseTable(LAYOUT, OPT, n_shards=N_DEV)
    ds = BoxPSDataset(
        schema, table, batch_size=32, read_threads=1, n_mesh_shards=N_DEV
    )
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    ds.begin_pass(round_to=32)

    model = DeepFM(num_slots=NUM_SLOTS, feat_width=LAYOUT.pull_width,
                   embedx_dim=8, hidden=(16,))
    params0 = model.init(jax.random.PRNGKey(0))
    adt = AsyncDenseTable(params0, base_lr=0.05)
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=32 // N_DEV, layout=LAYOUT,
        sparse_opt=OPT, auc_buckets=1000, dense_sync_mode="async",
        axis_name=plan.axis,
    )
    tr = CTRTrainer(model, cfg, async_dense=adt, plan=plan)
    tr.params = params0
    tr.opt_state = tr.dense_opt.init(params0)
    losses = []
    m = tr.train_pass(ds, on_batch=lambda i, mm: losses.append(float(mm["loss"])))
    assert m["batches"] == 8
    assert adt.n_updates > 0
    moved = max(
        np.abs(np.asarray(a) - np.asarray(b)).max()
        for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(params0))
    )
    assert moved > 1e-5
    assert np.isfinite(m["loss"]) and np.isfinite(m["auc"])
    adt.finalize()
    ds.end_pass(tr.trained_table())
