"""Device-carried pass boundary (table/carrier.py): the trained table stays
in HBM across passes; the next finalize splices surviving rows on device and
the host store is owed only the departing slice (+ drain on any save).

Equality contract: with shrink_threshold=0 (no cold-key drops) the carried
boundary produces bit-for-bit the same host table and training trajectory as
the classic full writeback + full re-upload.
"""

from __future__ import annotations

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

S, B = 4, 8


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def _write_pass(path, seed, lo, hi, n=48):
    """Records whose keys come from [lo, hi): consecutive passes overlap."""
    rng = np.random.default_rng(seed)
    path.parent.mkdir(parents=True, exist_ok=True)
    # fixture writer: path derives from tmp_path (helper param hides it)
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        for _ in range(n):
            parts = [f"1 {float(rng.integers(0, 2))}"]
            for _s in range(S):
                k = int(rng.integers(1, 3))
                vals = rng.integers(lo, hi, k)
                parts.append(f"{k} " + " ".join(str(v) for v in vals))
            f.write(" ".join(parts) + "\n")
    return str(path)


def _opt():
    return SparseOptimizerConfig(
        embedx_threshold=0.0, show_clk_decay=0.95, shrink_threshold=0.0
    )


def _run_two_passes(tmp_path, carried: bool):
    """Train two overlapping passes; return (host table snapshot fn output,
    per-pass losses)."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1 if carried else 0)
    try:
        layout = ValueLayout(embedx_dim=4)
        table = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
        ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
        model = DeepFM(
            num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
        )
        cfg = TrainStepConfig(
            num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
            auc_buckets=100,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr.init_params(jax.random.PRNGKey(0))
        losses = []
        # pass key ranges overlap heavily: [1, 200) then [100, 300)
        for i, (lo, hi) in enumerate([(1, 200), (100, 300)]):
            f = _write_pass(tmp_path / f"p{i}.txt", seed=i, lo=lo, hi=hi)
            ds.set_filelist([f])
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            out = tr.train_pass(ds)
            losses.append(out["loss"])
            ds.end_pass(
                tr.trained_table_device() if carried else tr.trained_table()
            )
        table.drain_pending()  # final flush so the host view is complete
        keys = np.sort(table.keys())
        vals = table.pull_or_create(keys)
        return keys, vals, losses
    finally:
        config.set_flag("enable_carried_table", prev)


def test_carried_boundary_matches_classic(tmp_path):
    k_c, v_c, l_c = _run_two_passes(tmp_path / "classic", carried=False)
    k_d, v_d, l_d = _run_two_passes(tmp_path / "carried", carried=True)
    np.testing.assert_array_equal(k_d, k_c)
    # identical training trajectory: pass-2 initial rows must match, so
    # losses and the final host table agree to float tolerance
    np.testing.assert_allclose(l_d, l_c, atol=1e-6)
    np.testing.assert_allclose(v_d, v_c, atol=1e-5)


def test_save_drains_carried_values(tmp_path):
    """A save while values are device-carried must include them."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        layout = ValueLayout(embedx_dim=4)
        table = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
        ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
        f = _write_pass(tmp_path / "p0.txt", seed=0, lo=1, hi=200)
        ds.set_filelist([f])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        model = DeepFM(
            num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
        )
        cfg = TrainStepConfig(
            num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
            auc_buckets=100,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr.init_params(jax.random.PRNGKey(0))
        tr.train_pass(ds)
        dev_vals = np.asarray(tr.trained_table_device())
        ws = ds.ws
        ds.end_pass(tr.trained_table_device())  # carried: host not written yet
        # save must drain: saved rows == decayed trained device rows
        table.save_base(str(tmp_path / "base"))
        fresh = HostSparseTable(layout, _opt(), n_shards=2, seed=1)
        fresh.load(str(tmp_path / "base"))
        got = fresh.pull_or_create(ws.sorted_keys)
        want = dev_vals[ws.row_of_sorted]
        want[:, layout.SHOW] *= 0.95
        want[:, layout.CLK] *= 0.95
        np.testing.assert_allclose(got, want, atol=1e-5)
    finally:
        config.set_flag("enable_carried_table", prev)


def _mk(tmp_path, seed=0, lo=1, hi=200):
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
    ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    f = _write_pass(tmp_path / f"p{seed}.txt", seed=seed, lo=lo, hi=hi)
    ds.set_filelist([f])
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    return layout, table, ds, tr


def test_classic_writeback_supersedes_stale_carrier(tmp_path):
    """Pass 1 carried, pass 2 ends with a CLASSIC (numpy) writeback: the
    stale carrier must go inert — a later save must not resurrect pass-1
    values over pass-2 training."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        layout, table, ds, tr = _mk(tmp_path, seed=0)
        tr.train_pass(ds)
        ds.end_pass(tr.trained_table_device())  # carried
        f1 = _write_pass(tmp_path / "p1.txt", seed=1, lo=100, hi=300)
        ds.set_filelist([f1])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)  # splices the carrier
        tr.train_pass(ds)
        keys2 = ds.ws.sorted_keys.copy()
        classic = tr.trained_table()  # numpy -> classic writeback
        rows2 = classic.reshape(-1, layout.width)[ds.ws.row_of_sorted].copy()
        ds.end_pass(classic)
        # drain must be a no-op now: host rows == pass-2 trained (+decay)
        table.drain_pending()
        got = table.pull_or_create(keys2)
        rows2[:, layout.SHOW] *= 0.95
        rows2[:, layout.CLK] *= 0.95
        np.testing.assert_allclose(got, rows2, atol=1e-5)
    finally:
        config.set_flag("enable_carried_table", prev)


def test_decay_accumulates_across_kept_boundaries(tmp_path):
    """A carrier kept pending across TWO decaying boundaries (an eval pass
    writes nothing back) owes two decays at flush, like host rows would."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        layout, table, ds, tr = _mk(tmp_path, seed=0)
        tr.train_pass(ds)
        dev = np.asarray(tr.trained_table_device())
        ws1 = ds.ws
        ds.end_pass(tr.trained_table_device())  # boundary 1: decay noted
        # boundary 2: an eval-ish pass over fresh DISJOINT keys ends with
        # nothing to write back; the carrier stays pending and its keys are
        # NOT in this pass (disjoint), so no splice supersedes them
        f1 = _write_pass(tmp_path / "p1.txt", seed=1, lo=1000, hi=1200)
        ds.set_filelist([f1])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        ds.end_pass(None)  # boundary 2: decay noted again
        table.drain_pending()
        got = table.pull_or_create(ws1.sorted_keys)
        want = dev[ws1.row_of_sorted]
        want[:, layout.SHOW] *= 0.95 * 0.95
        want[:, layout.CLK] *= 0.95 * 0.95
        np.testing.assert_allclose(got, want, atol=1e-5)
    finally:
        config.set_flag("enable_carried_table", prev)


def test_eager_flush_frees_carrier(tmp_path):
    """carried_eager_flush=1: the splice is followed by a background full
    flush, so the carrier goes inert without any explicit drain."""
    prev = config.get_flag("enable_carried_table")
    prev_e = config.get_flag("carried_eager_flush")
    config.set_flag("enable_carried_table", 1)
    config.set_flag("carried_eager_flush", 1)
    try:
        layout, table, ds, tr = _mk(tmp_path, seed=0)
        tr.train_pass(ds)
        dev = np.asarray(tr.trained_table_device())
        ws1 = ds.ws
        ds.end_pass(tr.trained_table_device())
        carrier = ds._carrier
        f1 = _write_pass(tmp_path / "p1.txt", seed=1, lo=100, hi=300)
        ds.set_filelist([f1])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)  # splice + background flush
        import time

        for _ in range(100):
            if carrier.flushed:
                break
            time.sleep(0.05)
        assert carrier.flushed and carrier.dev_flat is None
        got = table.pull_or_create(ws1.sorted_keys)
        want = dev[ws1.row_of_sorted]
        want[:, layout.SHOW] *= 0.95
        want[:, layout.CLK] *= 0.95
        np.testing.assert_allclose(got, want, atol=1e-5)
        ds.end_pass(None)
    finally:
        config.set_flag("enable_carried_table", prev)
        config.set_flag("carried_eager_flush", prev_e)


def test_carried_boundary_on_single_host_mesh(tmp_path):
    """The carrier accepts the single-host MESH table (3-D, device-axis
    sharded): rows stay in-shard across passes, the splice runs on the
    sharded array, and two carried passes equal the classic mesh run."""
    from paddlebox_tpu.parallel import make_mesh

    N_DEV = 4

    def run(carried):
        prev = config.get_flag("enable_carried_table")
        config.set_flag("enable_carried_table", 1 if carried else 0)
        try:
            layout = ValueLayout(embedx_dim=4)
            table = HostSparseTable(layout, _opt(), n_shards=N_DEV, seed=0)
            plan = make_mesh(N_DEV)
            ds = BoxPSDataset(
                _schema(), table, batch_size=B, shuffle_mode="none",
                n_mesh_shards=N_DEV,
            )
            model = DeepFM(
                num_slots=S, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )
            cfg = TrainStepConfig(
                num_slots=S, batch_size=B // N_DEV, layout=layout,
                sparse_opt=_opt(), auc_buckets=100, axis_name=plan.axis,
            )
            tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan)
            tr.init_params(jax.random.PRNGKey(0))
            losses = []
            for i, (lo, hi) in enumerate([(1, 200), (100, 300)]):
                f = _write_pass(tmp_path / f"m{carried}" / f"p{i}.txt",
                                seed=i, lo=lo, hi=hi)
                ds.set_filelist([f])
                ds.load_into_memory()
                ds.begin_pass(round_to=8)
                out = tr.train_pass(ds)
                losses.append(out["loss"])
                ds.end_pass(
                    tr.trained_table_device() if carried else tr.trained_table()
                )
            table.drain_pending()
            keys = np.sort(table.keys())
            return losses, keys, table.pull_or_create(keys)
        finally:
            config.set_flag("enable_carried_table", prev)

    l_c, k_c, v_c = run(False)
    l_d, k_d, v_d = run(True)
    np.testing.assert_array_equal(k_d, k_c)
    np.testing.assert_allclose(l_d, l_c, atol=1e-6)
    np.testing.assert_allclose(v_d, v_c, atol=1e-5)


def test_two_phase_passes_across_carried_boundaries(tmp_path):
    """Round-4 features composed: consecutive TWO-PHASE passes (join on the
    resident pv tier -> device handoff -> update on the resident flat
    tier) across CARRIED boundaries must equal the classic-writeback run."""
    from paddlebox_tpu.data import SlotInfo, SlotSchema
    from tests.test_pv_phase import RankDeepFM, _logkey

    def schema():
        return SlotSchema(
            [SlotInfo("label", type="float", dense=True, dim=1)]
            + [SlotInfo(f"s{i}") for i in range(S)],
            label_slot="label",
            parse_logkey=True,
        )

    def write_pv(path, seed, lo, hi):
        rng = np.random.default_rng(seed)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            for q in range(1, 25):
                for r in range(1, int(rng.integers(1, 3)) + 1):
                    keys = rng.integers(lo, hi, S)
                    lab = 1.0 if (keys % 5 == 0).any() else 0.0
                    f.write(
                        " ".join(
                            [f"1 {_logkey(q, 222, r)}", f"1 {lab}"]
                            + [f"1 {k}" for k in keys]
                        )
                        + "\n"
                    )
        return str(path)

    def run(carried):
        prev = config.get_flag("enable_carried_table")
        config.set_flag("enable_carried_table", 1 if carried else 0)
        try:
            layout = ValueLayout(embedx_dim=4)
            table = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
            ds = BoxPSDataset(schema(), table, batch_size=B, shuffle_mode="none")
            join_model = RankDeepFM(S, layout.pull_width, layout.embedx_dim)
            cfg_j = TrainStepConfig(
                num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
                auc_buckets=100, model_takes_rank_offset=True,
            )
            tr_j = CTRTrainer(join_model, cfg_j, dense_opt=optax.adam(1e-2))
            tr_j.init_params(jax.random.PRNGKey(0))
            upd_model = DeepFM(
                num_slots=S, feat_width=layout.pull_width, embedx_dim=4,
                hidden=(8,),
            )
            cfg_u = TrainStepConfig(
                num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
                auc_buckets=100,
            )
            tr_u = CTRTrainer(upd_model, cfg_u, dense_opt=optax.adam(1e-2))
            tr_u.init_params(jax.random.PRNGKey(1))
            losses = []
            for i, (lo, hi) in enumerate([(1, 150), (80, 230)]):
                f = write_pv(tmp_path / f"c{carried}" / f"p{i}.txt", i, lo, hi)
                ds.set_filelist([f])
                ds.load_into_memory()
                ds.begin_pass(round_to=8)
                ds.set_current_phase(1)
                ds.preprocess_instance()
                mj = tr_j.train_pass(ds)
                tr_j.handoff_table(ds)
                ds.set_current_phase(0)
                ds.postprocess_instance()
                mu = tr_u.train_pass(ds)
                losses += [mj["loss"], mu["loss"]]
                ds.end_pass(
                    tr_u.trained_table_device()
                    if carried
                    else tr_u.trained_table()
                )
            table.drain_pending()
            keys = np.sort(table.keys())
            return losses, keys, table.pull_or_create(keys)
        finally:
            config.set_flag("enable_carried_table", prev)

    l_c, k_c, v_c = run(False)
    l_d, k_d, v_d = run(True)
    np.testing.assert_array_equal(k_d, k_c)
    np.testing.assert_allclose(l_d, l_c, atol=1e-5)
    np.testing.assert_allclose(v_d, v_c, atol=1e-4)


def test_revert_after_carried_boundary(tmp_path):
    """begin_pass(enable_revert=True) drains the carrier first so the
    snapshot (and a revert) sees true pre-pass values."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        layout = ValueLayout(embedx_dim=4)
        table = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
        ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
        model = DeepFM(
            num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
        )
        cfg = TrainStepConfig(
            num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
            auc_buckets=100,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr.init_params(jax.random.PRNGKey(0))
        f0 = _write_pass(tmp_path / "p0.txt", seed=0, lo=1, hi=200)
        ds.set_filelist([f0])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        tr.train_pass(ds)
        ds.end_pass(tr.trained_table_device())  # carried
        # pass 2 armed for revert: carrier must flush before the snapshot
        f1 = _write_pass(tmp_path / "p1.txt", seed=1, lo=100, hi=300)
        ds.set_filelist([f1])
        ds.load_into_memory()
        ds.begin_pass(round_to=8, enable_revert=True, trainer=tr)
        keys2 = ds.ws.sorted_keys.copy()
        pre = table.pull_or_create(keys2).copy()
        tr.train_pass(ds)
        ds.revert_pass()
        post = table.pull_or_create(keys2)
        np.testing.assert_allclose(post, pre, atol=0)
    finally:
        config.set_flag("enable_carried_table", prev)


def test_failed_departure_push_retried_by_flush(tmp_path):
    """A FAILED background departure push must leave those rows owed: the
    retry flush re-pushes them, so the host table ends identical to a run
    where the push never failed (durability under transient IO errors)."""
    prev = config.get_flag("enable_carried_table")
    config.set_flag("enable_carried_table", 1)
    try:
        layout = ValueLayout(embedx_dim=4)
        table = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
        ds = BoxPSDataset(_schema(), table, batch_size=B, shuffle_mode="none")
        model = DeepFM(
            num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
        )
        cfg = TrainStepConfig(
            num_slots=S, batch_size=B, layout=layout, sparse_opt=_opt(),
            auc_buckets=100,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr.init_params(jax.random.PRNGKey(0))
        f0 = _write_pass(tmp_path / "p0.txt", seed=0, lo=1, hi=200)
        ds.set_filelist([f0])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        tr.train_pass(ds)
        ds.end_pass(tr.trained_table_device())  # carried

        # pass 2 with a DISJOINT key range: most pass-1 keys depart and the
        # boundary dispatches a background departure push — which we fail
        fail = {"on": True}
        orig_push = table.push

        def flaky_push(keys, vals):
            if fail["on"]:
                fail["on"] = False
                raise OSError("injected departure push failure")
            return orig_push(keys, vals)

        table.push = flaky_push
        f1 = _write_pass(tmp_path / "p1.txt", seed=1, lo=500, hi=700)
        ds.set_filelist([f1])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)  # splice dispatches the departure push
        tr.train_pass(ds)
        # the failure surfaces at the first join (drain via end_pass or an
        # explicit drain); the carrier must survive it
        with pytest.raises(OSError):
            table.drain_pending()
        assert table._pending_carriers, "failed drain dropped the carrier"
        n = table.drain_pending()  # retry: departed rows re-pushed
        assert n > 0
        table.push = orig_push
        ds.end_pass(tr.trained_table_device())
        table.drain_pending()
        got_keys = np.sort(table.keys())

        # reference run: same two passes, no failure
        table2 = HostSparseTable(layout, _opt(), n_shards=2, seed=0)
        ds2 = BoxPSDataset(_schema(), table2, batch_size=B, shuffle_mode="none")
        tr2 = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
        tr2.init_params(jax.random.PRNGKey(0))
        for i, f in enumerate([f0, f1]):
            ds2.set_filelist([f])
            ds2.load_into_memory()
            ds2.begin_pass(round_to=8)
            tr2.train_pass(ds2)
            ds2.end_pass(tr2.trained_table_device())
        table2.drain_pending()
        np.testing.assert_array_equal(got_keys, np.sort(table2.keys()))
        np.testing.assert_allclose(
            table.pull_or_create(got_keys),
            table2.pull_or_create(got_keys),
            atol=1e-5,
        )
    finally:
        config.set_flag("enable_carried_table", prev)
