"""Per-rule pbox-lint coverage: each rule fires on a violation, stays quiet
on clean code, and honors inline suppressions; plus baseline round-trip and
the CLI exit-code contract (docs/STATIC_ANALYSIS.md)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddlebox_tpu.analysis import (
    ERROR,
    WARNING,
    apply_baseline,
    default_rules,
    lint_paths,
    load_baseline,
    save_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_source(tmp_path, source, name="mod.py", extra_files=()):
    """Write ``source`` (and any (name, src) extras) under tmp_path, lint."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    paths = [str(p)]
    for fname, src in extra_files:
        q = tmp_path / fname
        q.parent.mkdir(parents=True, exist_ok=True)
        q.write_text(textwrap.dedent(src))
        paths.append(str(q))
    return lint_paths(paths, default_rules(), root=str(tmp_path))


def rule_findings(result, rule):
    return [f for f in result.findings if f.rule == rule]


# ---- JIT001 ----------------------------------------------------------------


class TestJitPurity:
    def test_positive(self, tmp_path):
        res = lint_source(tmp_path, """
            import time
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                t = time.time()
                y = x.item()
                z = float(x) + int(x)
                w = np.asarray(x)
                if x > 0:
                    y = 1.0
                return y
        """)
        msgs = [f.message for f in rule_findings(res, "JIT001")]
        assert any("host clock" in m for m in msgs)
        assert any(".item()" in m for m in msgs)
        assert any("float()" in m for m in msgs)
        assert any("np.asarray()" in m for m in msgs)
        assert any("Python `if`" in m for m in msgs)

    def test_call_form_and_partial(self, tmp_path):
        # jitted by reference (jax.jit(step)) and via functools.partial
        res = lint_source(tmp_path, """
            import functools
            import jax

            def step(x):
                return x.item()

            fast = jax.jit(step)

            @functools.partial(jax.jit, static_argnames=("mode",))
            def go(x, mode):
                if mode:          # static arg: fine
                    return x
                return float(x)   # traced arg: flagged
        """)
        msgs = [f.message for f in rule_findings(res, "JIT001")]
        assert any(".item()" in m for m in msgs)
        assert any("float()" in m for m in msgs)
        assert not any("Python `if`" in m for m in msgs)

    def test_clean(self, tmp_path):
        # shape reads, is-None checks, jnp use: all trace-static
        res = lint_source(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x, mask):
                if x.ndim != 2:
                    raise ValueError(x.shape)
                if mask is None:
                    mask = jnp.ones(x.shape[0])
                return jnp.where(mask > 0, x.sum(axis=1), 0.0)

            def host_side(arr):
                return float(arr.sum())  # not jitted: fine
        """)
        assert rule_findings(res, "JIT001") == []

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            import jax

            @jax.jit
            def step(x):
                return x.item()  # pbox-lint: disable=JIT001
        """)
        assert rule_findings(res, "JIT001") == []


# ---- THR002 ----------------------------------------------------------------


class TestLockDiscipline:
    def test_thread_reachable_is_error(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._data = []  # guarded-by: _lock
                    self._lock = threading.Lock()
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self._data.append(1)
        """)
        errs = [f for f in rule_findings(res, "THR002") if f.severity == ERROR]
        assert len(errs) == 1
        assert "thread entry point" in errs[0].message

    def test_unreachable_is_warning_and_locked_is_clean(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._data = []  # guarded-by: _lock
                    self._lock = threading.Lock()

                def locked(self):
                    with self._lock:
                        return len(self._data)

                def bare(self):
                    return self._data
        """)
        found = rule_findings(res, "THR002")
        assert len(found) == 1
        assert found[0].severity == WARNING
        assert "Box.bare" in found[0].message

    def test_module_global_and_submit_entry(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading
            from concurrent.futures import ThreadPoolExecutor

            _lock = threading.Lock()
            _count = 0  # guarded-by: _lock

            def worker():
                global _count
                _count += 1

            def launch(ex: ThreadPoolExecutor):
                ex.submit(worker)

            def safe():
                with _lock:
                    return _count
        """)
        errs = [f for f in rule_findings(res, "THR002") if f.severity == ERROR]
        assert len(errs) == 1
        assert "worker" in errs[0].message

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._data = []  # guarded-by: _lock
                    self._lock = threading.Lock()

                def bare(self):
                    return self._data  # pbox-lint: disable=THR002
        """)
        assert rule_findings(res, "THR002") == []


# ---- REG003 ----------------------------------------------------------------

FAULTINJECT_STUB = """
    KNOWN_SITES = ("good.site",)

    def fire(site):
        pass
"""


class TestRegistryConsistency:
    def test_undefined_read_and_dead_define(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu import config

            config.define_flag("lonely_knob", 1, "never read")

            def use():
                return config.get_flag("phantom_knob")
        """)
        errs = [f for f in rule_findings(res, "REG003") if f.severity == ERROR]
        warns = [f for f in rule_findings(res, "REG003") if f.severity == WARNING]
        assert len(errs) == 1 and "phantom_knob" in errs[0].message
        assert len(warns) == 1 and "lonely_knob" in warns[0].message

    def test_unknown_fault_site(self, tmp_path):
        res = lint_source(
            tmp_path,
            """
            from paddlebox_tpu.utils.faultinject import fire

            def f():
                fire("good.site")
                fire("typo.site")
            """,
            extra_files=[("utils/faultinject.py", FAULTINJECT_STUB)],
        )
        errs = [f for f in rule_findings(res, "REG003") if f.severity == ERROR]
        assert len(errs) == 1
        assert "typo.site" in errs[0].message

    def test_clean_and_dynamic_names_skipped(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu import config

            config.define_flag("real_knob", 2, "read below")

            def use(name):
                config.get_flag(name)  # dynamic: not checkable
                return config.get_flag("real_knob")
        """)
        assert rule_findings(res, "REG003") == []

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu import config

            def use():
                return config.get_flag("phantom")  # pbox-lint: disable=REG003
        """)
        assert rule_findings(res, "REG003") == []


# ---- IO004 -----------------------------------------------------------------


class TestDurableWrite:
    def test_positive_all_write_modes(self, tmp_path):
        res = lint_source(tmp_path, """
            def bad(p):
                open(p, "w").write("x")
                open(p, "wb").write(b"x")
                open(p, "a").write("x")
                open(p, mode="r+").write("x")
        """)
        assert len(rule_findings(res, "IO004")) == 4

    def test_clean(self, tmp_path):
        res = lint_source(tmp_path, """
            def good(p, m):
                open(p).read()
                open(p, "rb").read()
                open(p, m).read()  # non-literal mode: skipped
        """)
        assert rule_findings(res, "IO004") == []

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            def wrapper(p):
                return open(p, "w")  # pbox-lint: disable=IO004
        """)
        assert rule_findings(res, "IO004") == []


# ---- MON005 ----------------------------------------------------------------


class TestStatNames:
    def test_positive(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_SET

            def f(kind):
                STAT_ADD("Bad-Name")
                STAT_SET(f"dyn_{kind}", 1)
        """)
        msgs = [f.message for f in rule_findings(res, "MON005")]
        assert len(msgs) == 2
        assert any("Bad-Name" in m for m in msgs)
        assert any("string literal" in m for m in msgs)

    def test_clean(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_GET

            def f(name):
                STAT_ADD("pass.auc_updates", 2)
                STAT_GET(name)  # reads may be programmatic
        """)
        assert rule_findings(res, "MON005") == []

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu.utils.monitor import STAT_ADD

            def f(kind):
                STAT_ADD(f"sup_{kind}")  # pbox-lint: disable=MON005
        """)
        assert rule_findings(res, "MON005") == []

    def test_observe_covered(self, tmp_path):
        # STAT_OBSERVE mints histogram names into the same enumerable
        # namespace as the counters — same literal discipline
        res = lint_source(tmp_path, """
            from paddlebox_tpu.utils.monitor import STAT_OBSERVE

            def f(name, v):
                STAT_OBSERVE("serve.latency_ms", v)  # ok
                STAT_OBSERVE("serve.request_ms", v)  # ok (the SLO series)
                STAT_OBSERVE("Bad-Hist", v)
                STAT_OBSERVE(name, v)
        """)
        msgs = [f.message for f in rule_findings(res, "MON005")]
        assert len(msgs) == 2
        assert any("Bad-Hist" in m for m in msgs)
        assert any("string literal" in m for m in msgs)


# ---- THR006 ----------------------------------------------------------------


class TestRaceDetector:
    def test_positive(self, tmp_path):
        # _push is reachable from BOTH the spawned thread (via _worker)
        # and the main thread (via the uncalled root `add`) with no lock
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self.items = []
                    threading.Thread(target=self._worker).start()

                def _push(self):
                    self.items.append(1)

                def _worker(self):
                    self._push()

                def add(self):
                    self._push()
        """)
        errs = rule_findings(res, "THR006")
        assert errs, "two-thread unlocked mutation must fire"
        assert any("items" in f.message for f in errs)

    def test_locked_on_both_sides_is_quiet(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self.items = []
                    self._lock = threading.Lock()
                    threading.Thread(target=self._worker).start()

                def _worker(self):
                    with self._lock:
                        self.items.append(1)

                def add(self, x):
                    with self._lock:
                        self.items.append(x)
        """)
        assert rule_findings(res, "THR006") == []

    def test_lock_held_on_call_path_is_quiet(self, tmp_path):
        # the callee never takes the lock itself — every caller does; the
        # meet-over-paths propagation must see it as protected
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self.items = []
                    self._lock = threading.Lock()
                    threading.Thread(target=self._worker).start()

                def _grow(self):
                    self.items.append(0)

                def _worker(self):
                    with self._lock:
                        self._grow()

                def add(self):
                    with self._lock:
                        self._grow()
        """)
        assert rule_findings(res, "THR006") == []

    def test_single_thread_is_quiet(self, tmp_path):
        res = lint_source(tmp_path, """
            class Box:
                def __init__(self):
                    self.items = []

                def add(self, x):
                    self.items.append(x)
        """)
        assert rule_findings(res, "THR006") == []

    def test_synchronized_by_annotation_is_quiet(self, tmp_path):
        # same two-thread _stage shape as the positive, but the init site
        # documents the non-lock mechanism — the annotation exempts it
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self.staged = None  # synchronized-by: worker join handoff
                    self._t = threading.Thread(target=self._worker)
                    self._t.start()

                def _stage(self, v):
                    self.staged = v

                def _worker(self):
                    self._stage([1])

                def consume(self):
                    self._t.join()
                    self._stage(None)
        """)
        assert rule_findings(res, "THR006") == []

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self.items = []
                    threading.Thread(target=self._worker).start()

                def _push(self):
                    self.items.append(1)  # pbox-lint: disable=THR006

                def _worker(self):
                    self._push()

                def add(self):
                    self._push()
        """)
        assert rule_findings(res, "THR006") == []


# ---- EXC007 ----------------------------------------------------------------


class TestExceptionFlow:
    def test_positive_silent_swallow(self, tmp_path):
        res = lint_source(tmp_path, """
            def f():
                try:
                    return 1
                except Exception:
                    pass
        """)
        errs = rule_findings(res, "EXC007")
        assert len(errs) == 1
        assert "silently swallows" in errs[0].message

    def test_counted_or_recorded_swallow_is_quiet(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu.utils.monitor import STAT_ADD

            def counted():
                try:
                    return 1
                except OSError:
                    STAT_ADD("x.oserrors")

            def logged(log):
                try:
                    return 1
                except Exception as e:
                    log.warning("boom %r", e)
        """)
        assert rule_findings(res, "EXC007") == []

    def test_deferred_surface_is_quiet(self, tmp_path):
        # storing or handing off the bound exception is a deferred
        # re-raise, not a swallow
        res = lint_source(tmp_path, """
            def stored(self):
                try:
                    return 1
                except Exception as e:
                    self._exc = e

            def handed(errors):
                try:
                    return 1
                except BaseException as e:
                    errors.append(e)
        """)
        assert rule_findings(res, "EXC007") == []

    def test_narrow_handler_is_quiet(self, tmp_path):
        res = lint_source(tmp_path, """
            def f():
                try:
                    return 1
                except (KeyError, ValueError):
                    return None
        """)
        assert rule_findings(res, "EXC007") == []

    def test_suppressed_next_line_directive(self, tmp_path):
        res = lint_source(tmp_path, """
            def f():
                try:
                    return 1
                # absence probe: None IS the answer
                # pbox-lint: disable=EXC007
                except OSError:
                    return None
        """)
        assert rule_findings(res, "EXC007") == []


# ---- FLT008 ----------------------------------------------------------------

FAULT_CATALOG_STUB = """
    KNOWN_SITES = (
        "covered.site",
        "dead.site",
        "untested.site",
    )

    def fire(site):
        pass
"""


class TestFaultSiteCoverage:
    def fixture(self, tmp_path, test_src):
        return lint_source(
            tmp_path,
            """
            from paddlebox_tpu.utils.faultinject import fire

            def a():
                fire("covered.site")

            def b():
                fire("untested.site")
            """,
            name="pkg_mod.py",
            extra_files=[
                ("utils/faultinject.py", FAULT_CATALOG_STUB),
                ("tests/test_cov.py", test_src),
            ],
        )

    def test_dead_and_untested_sites_fire(self, tmp_path):
        res = self.fixture(tmp_path, """
            def test_covered():
                assert "covered.site"
        """)
        msgs = [f.message for f in rule_findings(res, "FLT008")]
        # dead.site draws both findings (never fired AND never referenced)
        assert len(msgs) == 3
        assert any("dead.site" in m and "never fired" in m for m in msgs)
        assert any(
            "untested.site" in m and "not referenced" in m for m in msgs
        )
        assert not any("covered.site" in m for m in msgs)

    def test_full_coverage_is_quiet(self, tmp_path):
        res = self.fixture(tmp_path, """
            SCHEDULE = ["covered.site", "untested.site", "dead.site"]
        """)
        msgs = [f.message for f in rule_findings(res, "FLT008")]
        # dead.site is still never FIRED by package code
        assert len(msgs) == 1 and "dead.site" in msgs[0]


# ---- DST009 ----------------------------------------------------------------


class TestDistributedDiscipline:
    def test_black_holed_send(self, tmp_path):
        res = lint_source(tmp_path, """
            def push(tp):
                tp.send(1, "ctl:orphan:ping", b"")

            def paired(tp):
                tp.send(1, "ctl:pair:pong", b"")

            def pull(tp):
                return tp.recv("ctl:pair:pong", 0)
        """)
        msgs = [f.message for f in rule_findings(res, "DST009")]
        assert len(msgs) == 1
        assert "ctl:orphan:ping" in msgs[0] and "black-holed" in msgs[0]

    def test_rank_conditional_collective(self, tmp_path):
        res = lint_source(tmp_path, """
            def lopsided(tp):
                if tp.rank == 0:
                    tp.allgather(b"", "ctl:member:probe")

            def symmetric(tp):
                if tp.rank == 0:
                    tp.allgather(b"lead", "barrier:x")
                else:
                    tp.allgather(b"flw", "barrier:x")

            def pull(tp):
                # the lopsided member tag still needs a nominal receiver
                return tp.recv("ctl:member:probe", 0)
        """)
        msgs = [f.message for f in rule_findings(res, "DST009")]
        assert len(msgs) == 1
        assert "static deadlock" in msgs[0] and "allgather" in msgs[0]

    def test_verdict_discipline(self, tmp_path):
        res = lint_source(tmp_path, """
            class Sup:
                def exchange_verdict(self, key, ok, detail="", fatal=False):
                    return ok

                def unfenced(self, tp):
                    tp.allgather(b"", "ctl:verdict:load")

                def unfingerprinted(self, ok):
                    self.exchange_verdict("migrate", ok, fatal=True)

                def fenced_commit(self, ok, m):
                    key = "migrate:" + m.fingerprint()
                    self.exchange_verdict(key, ok, fatal=True)
        """)
        msgs = [f.message for f in rule_findings(res, "DST009")]
        assert any("no @e epoch" in m and "split-brain" in m for m in msgs)
        assert any("fingerprint()" in m and "fatal=True" in m for m in msgs)
        assert len(msgs) == 2  # fenced_commit stays quiet

    def test_clean_protocol_is_quiet(self, tmp_path):
        res = lint_source(tmp_path, """
            def exchange(tp, epoch):
                tp.send(1, f"ctl:state:{tp.rank}@e{epoch}", b"")
                got = tp.recv(f"ctl:state:{1 - tp.rank}@e{epoch}", 1 - tp.rank)
                tp.allgather(got, f"ctl:round:sync@e{epoch}")
        """)
        assert rule_findings(res, "DST009") == []

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            def push(tp):
                # best-effort diagnostic frame; loss is acceptable
                # pbox-lint: disable=DST009
                tp.send(1, "ctl:orphan:ping", b"")
        """)
        assert rule_findings(res, "DST009") == []


# ---- RES010 ----------------------------------------------------------------


class TestResourceLifecycle:
    def test_thread_positive(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            def fire_and_forget(fn):
                threading.Thread(target=fn).start()

            def bound_but_abandoned(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t
        """)
        msgs = [f.message for f in rule_findings(res, "RES010")]
        assert any("never joinable" in m for m in msgs)
        assert any('"t" is never joined' in m for m in msgs)

    def test_thread_clean(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            class Box:
                def spawn(self, fn):
                    self._th = threading.Thread(target=fn, daemon=False)
                    self._th.start()
                    w = threading.Thread(target=fn, daemon=True)
                    w.start()

                def stop(self):
                    t = getattr(self, "_th", None)
                    if t is not None:
                        t.join()
        """)
        assert rule_findings(res, "RES010") == []

    def test_socket_shutdown_before_close(self, tmp_path):
        res = lint_source(tmp_path, """
            import socket

            def bad_teardown(srv):
                conn, addr = srv.accept()
                conn.close()

            def good_teardown(srv):
                peer, addr = srv.accept()
                peer.shutdown(socket.SHUT_RDWR)
                peer.close()
        """)
        msgs = [f.message for f in rule_findings(res, "RES010")]
        assert len(msgs) == 1
        assert '"conn"' in msgs[0] and "shutdown()" in msgs[0]

    def test_listening_socket(self, tmp_path):
        res = lint_source(tmp_path, """
            import socket

            def serve_bad():
                s = socket.socket()
                s.listen(8)
                s.close()

            def port_pick_ok():
                # bind-only probe: no peer is ever blocked on it
                s2 = socket.socket()
                s2.bind(("127.0.0.1", 0))
                port = s2.getsockname()[1]
                s2.close()
                return port
        """)
        msgs = [f.message for f in rule_findings(res, "RES010")]
        assert len(msgs) == 1 and '"s"' in msgs[0]

    def test_executor_and_open(self, tmp_path):
        res = lint_source(tmp_path, """
            from concurrent.futures import ThreadPoolExecutor

            def leaky(fn, path):
                ex = ThreadPoolExecutor(2)
                ex.submit(fn)
                f = open(path)
                return f.read()

            def tidy(fn, path):
                with ThreadPoolExecutor(2) as ex:
                    ex.submit(fn)
                pool = ThreadPoolExecutor(2)
                pool.submit(fn)
                pool.shutdown(wait=True)
                with open(path) as f:
                    return f.read()
        """)
        msgs = [f.message for f in rule_findings(res, "RES010")]
        assert any('"ex"' in m and "shutdown()" in m for m in msgs)
        assert any('"f"' in m and "close()" in m for m in msgs)
        assert len(msgs) == 2

    def test_suppressed(self, tmp_path):
        res = lint_source(tmp_path, """
            import threading

            def watchdog(fn):
                # process-lifetime watcher; joined by interpreter exit
                # pbox-lint: disable=RES010
                threading.Thread(target=fn).start()
        """)
        assert rule_findings(res, "RES010") == []


# ---- baseline round-trip ---------------------------------------------------


class TestBaseline:
    def test_add_then_remove_round_trip(self, tmp_path):
        src = """
            def bad(p):
                open(p, "w").write("x")
        """
        res = lint_source(tmp_path, src)
        assert len(res.errors) == 1

        bl_path = str(tmp_path / "baseline.json")
        save_baseline(bl_path, res.findings)
        bl = load_baseline(bl_path)
        assert len(bl) == 1

        # grandfathered: same finding no longer gates
        new, old, stale = apply_baseline(res.findings, bl)
        assert [f for f in new if f.severity == ERROR] == []
        assert len(old) == 1 and stale == []

        # a SECOND identical violation exceeds the budget and gates
        res2 = lint_source(
            tmp_path,
            """
            def bad(p):
                open(p, "w").write("x")
                open(p, "w").write("y")
            """,
        )
        new2, old2, _ = apply_baseline(res2.findings, bl)
        assert len([f for f in new2 if f.severity == ERROR]) == 1
        assert len(old2) == 1

        # violation fixed -> baseline entry reported stale
        res3 = lint_source(tmp_path, "def ok():\n    return 1\n")
        new3, old3, stale3 = apply_baseline(res3.findings, bl)
        assert new3 == [] and old3 == [] and len(stale3) == 1

    def test_warnings_never_consume_budget(self, tmp_path):
        res = lint_source(tmp_path, """
            from paddlebox_tpu import config

            config.define_flag("dead_knob", 1, "warned, not gated")
        """)
        assert res.errors == []
        save_baseline(str(tmp_path / "b.json"), res.findings)
        assert load_baseline(str(tmp_path / "b.json")) == {}


# ---- CLI contract ----------------------------------------------------------


def run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_lint.py"), *args],
        capture_output=True, text=True, timeout=120,
    )


class TestCli:
    def test_exit_codes_and_json(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text('def f(p):\n    open(p, "w")\n')
        bl = str(tmp_path / "bl.json")

        r = run_cli(str(bad), "--baseline", bl)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "IO004" in r.stdout

        r = run_cli(str(bad), "--baseline", bl, "--format=json")
        assert r.returncode == 1
        payload = json.loads(r.stdout)
        assert payload["ok"] is False
        assert payload["new_errors"][0]["rule"] == "IO004"

        # baseline the finding -> clean exit; then fix -> stale reported
        r = run_cli(str(bad), "--baseline", bl, "--update-baseline")
        assert r.returncode == 0
        r = run_cli(str(bad), "--baseline", bl)
        assert r.returncode == 0
        assert "baseline" in r.stdout

        bad.write_text("def f(p):\n    return p\n")
        r = run_cli(str(bad), "--baseline", bl, "--format=json")
        assert r.returncode == 0
        payload = json.loads(r.stdout)
        assert payload["ok"] is True
        assert len(payload["stale_baseline"]) == 1

        r = run_cli(str(tmp_path / "no_such_dir"))
        assert r.returncode == 2

    def test_syntax_error_gates(self, tmp_path):
        broken = tmp_path / "broken.py"
        broken.write_text("def f(:\n")
        r = run_cli(str(broken), "--no-baseline")
        assert r.returncode == 1
        assert "syntax error" in r.stdout
