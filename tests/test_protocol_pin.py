"""Pin the static protocol model to runtime reality.

Two drift checks in the FLT008 spirit (a registry is only trustworthy if
a test fails when code and registry diverge):

- every control tag a real 2-rank cluster puts on the wire while running
  the membership rounds (agreement, mapsync, migrate, barrier) must be
  covered by the analysis/protocol.py extraction — if someone mints a
  new ``ctl:`` tag the extractor cannot see, this fails before DST009
  silently under-reports;
- every ``wire.*``/``membership.*``/``serve.*`` counter the bench/soak
  harnesses export via ``STAT_GET`` must be a name package code actually
  bumps — bench blocks must not export dead gauges.
"""

from __future__ import annotations

import ast
import os
import socket
import threading

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.analysis import extract_protocol
from paddlebox_tpu.analysis.core import ModuleCtx, iter_py_files
from paddlebox_tpu.analysis.protocol import CONTROL_PREFIXES
from paddlebox_tpu.parallel.membership import (
    OwnershipMap,
    agree_membership,
    migrate_ranges,
    sync_map,
)
from paddlebox_tpu.parallel.transport import TcpTransport
from paddlebox_tpu.table.sparse_table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddlebox_tpu")


@pytest.fixture(scope="module")
def pkg_model():
    mods = []
    for p in iter_py_files([PKG]):
        rel = os.path.relpath(p, REPO).replace(os.sep, "/")
        mods.append(ModuleCtx.parse(p, rel))
    return extract_protocol(mods)


# ---- runtime control-tag coverage ------------------------------------------


@pytest.fixture(autouse=True)
def _fast_transport():
    names = ("transport_heartbeat_s", "transport_backoff_s")
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _run_ranks(fn, n):
    results = [None] * n
    errors = []

    def wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[0][1]
    return results


def test_runtime_control_tags_are_covered_by_extraction(pkg_model):
    """Run the same membership rounds tests/test_elastic.py exercises and
    check every control frame's tag against the static vocabulary."""
    eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    tps = [TcpTransport(r, eps, timeout=30.0) for r in range(2)]
    seen = set()
    lock = threading.Lock()
    for tp in tps:
        orig = tp.send

        def send(dst, tag, payload, _orig=orig):
            with lock:
                seen.add(tag)
            return _orig(dst, tag, payload)

        tp.send = send

    layout = ValueLayout(embedx_dim=2)
    opt = SparseOptimizerConfig(embedx_threshold=0.0)
    old_map = OwnershipMap(4, [0, 1], [0, 2, 4], epoch=0)
    new_map = OwnershipMap(4, [0, 1], [0, 3, 4], epoch=1)

    def run(rank):
        tp = tps[rank]
        try:
            assert agree_membership(tp, "pin") == []
            got = sync_map(tp, "pin", [], old_map)
            assert got.epoch == old_map.epoch
            table = HostSparseTable(layout, opt, n_shards=4, seed=rank)
            table.pull_or_create(
                (rank * 7 + 1) + 2 * np.arange(3, dtype=np.int64))
            migrate_ranges(tp, table, old_map, new_map, "pin", 1)
            tp.barrier("pin-done")
        finally:
            tp.close()

    _run_ranks(run, 2)

    control = {t for t in seen if t.startswith(CONTROL_PREFIXES)}
    # the exercise itself must have produced the core families
    for family in ("ctl:member:", "ctl:mapsync:", "migrate:", "barrier:"):
        assert any(t.startswith(family) for t in control), (
            f"round exercise produced no {family!r} frames: {sorted(seen)}"
        )
    uncovered = sorted(t for t in control if not pkg_model.covers_tag(t))
    assert not uncovered, (
        "runtime control tags unknown to analysis/protocol.py "
        f"(extend the extractor or fix the tag): {uncovered}"
    )


def test_stream_boundary_tags_are_covered_by_extraction(pkg_model):
    """Run the streaming micro-pass boundary rounds (cut + confirm, the
    PR 20 vocabulary) live on a 2-rank cluster and check every control
    tag against the static extraction — same contract as the membership
    capture above."""
    from paddlebox_tpu.train.stream import stream_cut_round, stream_confirm_round
    from paddlebox_tpu.train.supervisor import EpochCoordinator

    eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    tps = [TcpTransport(r, eps, timeout=30.0) for r in range(2)]
    seen = set()
    lock = threading.Lock()
    for tp in tps:
        orig = tp.send

        def send(dst, tag, payload, _orig=orig):
            with lock:
                seen.add(tag)
            return _orig(dst, tag, payload)

        tp.send = send

    def run(rank):
        tp = tps[rank]
        try:
            coord = EpochCoordinator(tp)
            ok, _ = stream_cut_round(coord, 1)
            assert ok
            ok, _ = stream_confirm_round(coord, 1)
            assert ok
            # epoch fencing: the round after a revert rides the bumped
            # suffix, exactly like every other verdict exchange
            coord.advance()
            ok, _ = stream_cut_round(coord, 2)
            assert ok
            tp.barrier("stream-pin-done")
        finally:
            tp.close()

    _run_ranks(run, 2)

    control = {t for t in seen if t.startswith(CONTROL_PREFIXES)}
    for family in ("ctl:verdict:stream-cut:", "ctl:verdict:stream-confirm:"):
        assert any(t.startswith(family) for t in control), (
            f"round exercise produced no {family!r} frames: {sorted(seen)}"
        )
    assert any(t.startswith("ctl:verdict:stream-cut:2@e1") for t in control), (
        f"epoch fence missing from the post-advance cut round: {sorted(seen)}"
    )
    uncovered = sorted(t for t in control if not pkg_model.covers_tag(t))
    assert not uncovered, (
        "runtime stream tags unknown to analysis/protocol.py "
        f"(extend the extractor or fix the tag): {uncovered}"
    )


# ---- stat-name drift --------------------------------------------------------


def _literal_stat_names(path, fn_names):
    out = set()
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if (
            name in fn_names
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.add(node.args[0].value)
    return out


def test_bench_exported_stats_are_bumped_in_package():
    exported = set()
    for p in iter_py_files([os.path.join(REPO, "tools")]):
        exported |= _literal_stat_names(p, {"STAT_GET"})
    exported = {
        n for n in exported
        if n.startswith(("wire.", "membership.", "serve."))
    }
    assert exported, "the bench/soak harnesses export no counters?"

    bumped = set()
    for p in iter_py_files([PKG]):
        bumped |= _literal_stat_names(
            p, {"STAT_ADD", "STAT_SET", "STAT_OBSERVE"})

    dead = sorted(exported - bumped)
    assert not dead, (
        "bench/soak harnesses export counters no package code bumps "
        f"(dead gauges): {dead}"
    )


def test_serve_fleet_runtime_tags_are_covered_by_extraction(pkg_model, tmp_path):
    """Run a live 1-follower serving fleet — request/response framing,
    health gossip, and a confirmed drain — and check every control tag it
    puts on the wire against the static vocabulary, same contract as the
    membership-round capture above."""
    import json as _json
    import time as _time

    from paddlebox_tpu.serve import FleetClient, FleetFollower, Follower
    from paddlebox_tpu.serve import fleet as fleet_mod

    prev_beat = config.get_flag("serve_health_beat_s")
    config.set_flag("serve_health_beat_s", 0.05)
    eps = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    tps = [TcpTransport(r, eps, timeout=30.0) for r in range(2)]
    seen = set()
    lock = threading.Lock()
    for tp in tps:
        orig = tp.send

        def send(dst, tag, payload, _orig=orig):
            with lock:
                seen.add(tag)
            return _orig(dst, tag, payload)

        tp.send = send

    class _BoomCfg:
        batch_size = 8

    class _BoomScorer:
        # the capture needs frames, not scores: every request answers on
        # the typed error path, which still rides serve:resp
        cfg = _BoomCfg()

        def score_records(self, *a, **k):
            raise RuntimeError("no model in the tag-capture fleet")

    layout = ValueLayout(embedx_dim=2)
    opt = SparseOptimizerConfig(embedx_threshold=0.0)
    fol = Follower(str(tmp_path), layout, opt, n_host_shards=2, trainer=None)
    ff = FleetFollower(tps[1], 0, fol, _BoomScorer(), None)
    client = FleetClient(tps[0], [1])
    try:
        ff.start(poll=False)
        client.start()
        # gossip up (ctl:serve:health), then a confirmed drain round trip
        # (ctl:serve:drain) and one raw request (serve:req -> serve:resp;
        # the draining follower answers with the typed refusal)
        deadline = _time.monotonic() + 10
        while client.view.gossip_state(1) is None and _time.monotonic() < deadline:
            _time.sleep(0.02)
        assert client.drain(1, wait_s=10.0) is True
        tps[0].send(
            1, fleet_mod._REQ_TAG,
            _json.dumps({"id": 7, "deadline_ms": 2000.0, "lines": ["x"]}).encode(),
        )
        want = {
            fleet_mod._REQ_TAG, fleet_mod._RESP_TAG,
            fleet_mod._HEALTH_TAG, fleet_mod._DRAIN_TAG,
        }
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            with lock:
                if want <= seen:
                    break
            _time.sleep(0.02)
    finally:
        client.stop()
        ff.stop()
        for tp in tps:
            tp.close()
        config.set_flag("serve_health_beat_s", prev_beat)

    with lock:
        control = {t for t in seen if t.startswith(CONTROL_PREFIXES)}
    assert want <= seen, f"fleet exercise missed frames: {sorted(seen)}"
    uncovered = sorted(t for t in control if not pkg_model.covers_tag(t))
    assert not uncovered, (
        "runtime serve tags unknown to analysis/protocol.py "
        f"(extend the extractor or fix the tag): {uncovered}"
    )
