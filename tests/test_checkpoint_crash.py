"""Crash-window tests for CheckpointManager durability.

Every ``checkpoint.save`` injection hit is one durability boundary inside
save_base/save_delta (4 fires per save call):

    hit 1   nothing written yet
    hit 2   sparse snapshot in the .tmp dir, unpublished
    hit 3   sparse published, dense not yet written
    hit 4   sparse + dense durable, cursor still stale

A "crash" in any window must leave resume() landing on the previous
consistent (sparse, dense) pair, and a retried save must commit the same
state a never-crashed save would have.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.train.checkpoint import (
    MANIFEST_NAME,
    CheckpointManager,
    verify_snapshot,
)
from paddlebox_tpu.utils.faultinject import InjectedFault, fail_nth, inject
from paddlebox_tpu.utils.monitor import STAT_GET

LAYOUT = ValueLayout(embedx_dim=2)
OPT = SparseOptimizerConfig()
DATE, DATE2 = "20260101", "20260102"


class DenseStub:
    """Minimal trainer-shaped object for the dense half of a checkpoint:
    the manager only needs params/init_params/save_dense/load_dense."""

    def __init__(self):
        self.params = None

    def init_params(self, *_):
        self.params = np.zeros(3, dtype=np.float32)

    def bump(self, v):
        if self.params is None:
            self.init_params()
        self.params = self.params + np.float32(v)

    def save_dense(self, path):
        np.savez(path, params=self.params)

    def load_dense(self, path):
        with np.load(path) as z:
            self.params = z["params"]


def make_table():
    return HostSparseTable(LAYOUT, OPT, n_shards=2, seed=0)


def mutate(table, seed, lo=1, hi=400, n=48):
    rng = np.random.default_rng(seed)
    keys = np.unique(rng.integers(lo, hi, n).astype(np.uint64))
    rows = table.pull_or_create(keys)
    rows += rng.standard_normal(rows.shape).astype(np.float32)
    table.push(keys, rows)


def state_of(table):
    k = np.sort(table.keys())
    return k, table.pull_or_create(k)


def resume_fresh(root):
    """Resume into a brand-new table+dense, as a restarted process would."""
    t, d = make_table(), DenseStub()
    st = CheckpointManager(root).resume(t, d)
    return st, t, d


def assert_same_resume(root, ref):
    st, t, d = resume_fresh(root)
    ref_st, ref_t, ref_d = ref
    assert st == ref_st
    k, v = state_of(t)
    rk, rv = state_of(ref_t)
    np.testing.assert_array_equal(k, rk)
    np.testing.assert_array_equal(v, rv)
    np.testing.assert_array_equal(d.params, ref_d.params)


def seeded_day(root):
    """base + one delta, all committed; returns the live objects and the
    reference resume state at this consistent point."""
    cm = CheckpointManager(root)
    t, d = make_table(), DenseStub()
    d.init_params()
    mutate(t, 1)
    d.bump(1.0)
    cm.save_base(DATE, t, d)
    mutate(t, 2, lo=100, hi=500)
    d.bump(1.0)
    cm.save_delta(DATE, t, d)
    return cm, t, d, resume_fresh(root)


@pytest.mark.parametrize("hit", [1, 2, 3, 4])
def test_base_crash_windows_keep_previous_state(tmp_path, hit):
    """A crash in ANY window of a day-2 save_base leaves resume() on the
    day-1 state — in particular the window between the base publish and
    the cursor write (hits 3/4)."""
    root = str(tmp_path / "ckpt")
    cm, t, d, ref = seeded_day(root)
    mutate(t, 3)
    d.bump(2.0)
    with inject(fail_nth("checkpoint.save", hit)):
        with pytest.raises(InjectedFault):
            cm.save_base(DATE2, t, d)
    if hit <= 2:
        # nothing published under the final name, only (at most) a .tmp
        assert not os.path.isdir(os.path.join(root, DATE2, "base"))
    assert cm.cursor() == {"date": DATE, "delta_idx": 1,
                           "ownership_epoch": 0, "dense": "dense-0001.npz"}
    assert_same_resume(root, ref)
    # the retried save commits, and a restart then sees the live state
    cm.save_base(DATE2, t, d)
    st, t2, d2 = resume_fresh(root)
    assert st == {"date": DATE2, "delta_idx": 0,
                  "ownership_epoch": 0, "dense": "dense-0000.npz"}
    k, v = state_of(t2)
    lk, lv = state_of(t)
    np.testing.assert_array_equal(k, lk)
    np.testing.assert_array_equal(v, lv)
    np.testing.assert_array_equal(d2.params, d.params)


@pytest.mark.parametrize("hit", [1, 2, 3, 4])
def test_delta_crash_windows_keep_previous_pair(tmp_path, hit):
    """A crash in any window of save_delta — most importantly between the
    delta sparse publish and the dense write (hit 3) — leaves resume() on
    the previous consistent (sparse, dense) pair, and the retry commits
    the exact same delta a never-crashed save would (the touched-key set
    survives the crash)."""
    root = str(tmp_path / "ckpt")
    cm, t, d, ref = seeded_day(root)
    mutate(t, 4, lo=200, hi=700)
    d.bump(2.0)
    with inject(fail_nth("checkpoint.save", hit)):
        with pytest.raises(InjectedFault):
            cm.save_delta(DATE, t, d)
    if hit == 2:
        # torn attempt is invisible: only the .tmp sibling exists
        assert os.path.isdir(os.path.join(root, DATE, "delta-0002.tmp"))
        assert not os.path.isdir(os.path.join(root, DATE, "delta-0002"))
    assert cm.cursor() == {"date": DATE, "delta_idx": 1,
                           "ownership_epoch": 0, "dense": "dense-0001.npz"}
    assert_same_resume(root, ref)
    # retry: same delta index, same keys (deferred touched-clear), commits
    cm.save_delta(DATE, t, d)
    assert not os.path.isdir(os.path.join(root, DATE, "delta-0002.tmp"))
    st, t2, d2 = resume_fresh(root)
    assert st == {"date": DATE, "delta_idx": 2,
                  "ownership_epoch": 0, "dense": "dense-0002.npz"}
    k, v = state_of(t2)
    lk, lv = state_of(t)
    np.testing.assert_array_equal(k, lk)
    np.testing.assert_array_equal(v, lv)
    np.testing.assert_array_equal(d2.params, d.params)


def test_torn_published_delta_truncates_chain(tmp_path):
    """Corruption of an already-published delta (bit rot / torn copy) is
    caught by manifest verification; resume walks back to the previous
    consistent pair and re-pairs the dense file."""
    root = str(tmp_path / "ckpt")
    cm, t, d, ref = seeded_day(root)
    mutate(t, 5, lo=300, hi=900)
    d.bump(2.0)
    cm.save_delta(DATE, t, d)  # delta-0002, clean
    # flip bytes in one shard of delta-0002 (size preserved: CRC must catch)
    day = os.path.join(root, DATE)
    shard = os.path.join(day, "delta-0002", "shard-00000.npz")
    raw = bytearray(open(shard, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(shard, "wb").write(bytes(raw))
    before = STAT_GET("ckpt_resume_fallbacks")
    assert_same_resume(root, ref)  # landed on delta-0001 + dense-0001 pair
    assert STAT_GET("ckpt_resume_fallbacks") == before + 1


def test_torn_base_falls_back_to_prev_cursor(tmp_path):
    """When the newest cursor's base itself is torn, resume falls back to
    the previous cursor's day and reports it; if that is torn too, it
    raises instead of loading garbage."""
    root = str(tmp_path / "ckpt")
    cm, t, d, ref = seeded_day(root)
    mutate(t, 6)
    d.bump(3.0)
    cm.save_base(DATE2, t, d)  # cursor -> day2, prev cursor -> day1
    base2 = os.path.join(root, DATE2, "base")
    os.remove(os.path.join(base2, "shard-00001.npz"))
    before = STAT_GET("ckpt_resume_fallbacks")
    assert_same_resume(root, ref)  # day1's delta-0001 state
    assert STAT_GET("ckpt_resume_fallbacks") == before + 1
    # both candidates torn: refuse
    os.remove(os.path.join(root, DATE, "base", "shard-00001.npz"))
    with pytest.raises(RuntimeError, match="no consistent checkpoint"):
        resume_fresh(root)


def test_torn_cursor_falls_back_to_prev(tmp_path):
    root = str(tmp_path / "ckpt")
    cm, t, d, _ = seeded_day(root)
    mutate(t, 7)
    cm.save_delta(DATE, t, d)  # rotates cursor.prev.json to delta_idx=1
    ref_prev = resume_fresh(root)  # resume of the CURRENT state...
    with open(os.path.join(root, "cursor.json"), "w") as f:
        f.write("{torn")  # half-written json
    st, t2, d2 = resume_fresh(root)
    # ...is unreachable; the prev cursor (delta_idx=1) is the landing spot
    assert st["delta_idx"] == 1
    assert ref_prev[0]["delta_idx"] == 2


def test_verify_snapshot_catalogue(tmp_path):
    root = str(tmp_path / "ckpt")
    seeded_day(root)
    base = os.path.join(root, DATE, "base")
    assert verify_snapshot(base)
    # size mismatch
    shard = os.path.join(base, "shard-00000.npz")
    with open(shard, "ab") as f:
        f.write(b"x")
    assert not verify_snapshot(base)
    data = open(shard, "rb").read()[:-1]
    open(shard, "wb").write(data)
    assert verify_snapshot(base)
    # missing file
    os.rename(shard, shard + ".bak")
    assert not verify_snapshot(base)
    os.rename(shard + ".bak", shard)
    # legacy (pre-manifest) snapshot: accepted, but a manifest can be
    # demanded
    os.remove(os.path.join(base, MANIFEST_NAME))
    assert verify_snapshot(base)
    assert not verify_snapshot(base, require_manifest=True)
    # an empty/garbage dir is never a snapshot
    assert not verify_snapshot(os.path.join(root, "nope"))


def test_save_without_dense_carries_dense_name_forward(tmp_path):
    """Sparse-only deltas (trainer=None) keep naming the last dense file
    in the cursor, so resume still restores a consistent pair."""
    root = str(tmp_path / "ckpt")
    cm, t, d, _ = seeded_day(root)
    mutate(t, 8)
    cm.save_delta(DATE, t)  # no trainer
    st, t2, d2 = resume_fresh(root)
    assert st == {"date": DATE, "delta_idx": 2,
                  "ownership_epoch": 0, "dense": "dense-0001.npz"}
    np.testing.assert_array_equal(d2.params, d.params)
    k, v = state_of(t2)
    lk, lv = state_of(t)
    np.testing.assert_array_equal(k, lk)
    np.testing.assert_array_equal(v, lv)
