"""Networked serving fleet tests: framing, gossip, drain, and the FLT008
recovery contracts for the three serve fault sites.

The contracts pinned here (mirrors of what chaos_probe --serve-fleet
drives at soak scale):

- ``serve.request_recv``: a request frame lost after transport delivery is
  counted and the CLIENT's retry/hedge budget absorbs it — the caller
  still gets a bitwise-correct answer.
- ``serve.fleet_stage``: a torn stage fetch never advances the stage
  watermark, so followers can never observe a partial version; the retry
  is idempotent and catches up.
- ``serve.drain``: a dropped drain command is counted and the client
  re-sends until the follower's own gossip confirms the state — drain
  and admit are idempotent end to end.

Plus the degradation tentpole pieces that don't need a soak: typed
load-shedding past ``serve_shed_queue_depth``, and hedged re-dispatch
rescuing a silent follower inside the request deadline.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import serve_soak as ss

from paddlebox_tpu import config
from paddlebox_tpu.parallel.transport import TcpTransport
from paddlebox_tpu.serve import (
    FleetClient,
    FleetFollower,
    FleetStage,
    Follower,
    Scorer,
    ServeOverloadError,
    table_source,
)
from paddlebox_tpu.train import read_watermark
from paddlebox_tpu.utils.faultinject import (
    InjectedFault,
    fail_always,
    fail_once,
    inject,
)
from paddlebox_tpu.utils.monitor import STAT_GET

_FAST = {
    "transport_heartbeat_s": 0.05,
    "transport_backoff_s": 0.01,
    "serve_health_beat_s": 0.05,
    "serve_health_dead_s": 1.0,
    "serve_hedge_ms": 100.0,
    "serve_client_retries": 4,
    "serve_client_backoff_s": 0.02,
    "serve_request_timeout_ms": 15000.0,
}


@pytest.fixture(autouse=True)
def _fast_fleet_flags():
    prev = {n: config.get_flag(n) for n in _FAST}
    for n, v in _FAST.items():
        config.set_flag(n, v)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


class MiniFleet:
    """A 1-host fleet for tests: producer + shared stage + N networked
    followers (each with its OWN Scorer so one can be stalled) + client."""

    def __init__(self, tmp, n_followers=2):
        self.tmp = str(tmp)
        self.root = os.path.join(self.tmp, "ckpt")
        self.stage_dir = os.path.join(self.tmp, "stage")
        self.rng = np.random.default_rng(0)
        self.table, self.ds, self.cfg, self.trainer, self.mgr = ss.make_stack(
            self.root
        )
        self.pass0 = os.path.join(self.tmp, "pass-0.txt")
        self.lines = ss.write_pass_file(self.rng, self.pass0, 96, 1)
        self.probe_lines = self.lines[:16]
        self.n_passes = 0

        self.stage = FleetStage(self.root, self.stage_dir)
        self.stage_stop = threading.Event()
        self.stage_thread = threading.Thread(
            target=self.stage.run, args=(self.stage_stop, 0.02), daemon=True
        )
        self.stage_thread.start()

        eps = [f"127.0.0.1:{p}" for p in ss._free_ports(n_followers + 1)]
        self.client_tp = TcpTransport(0, eps, timeout=30.0)
        self.ranks = list(range(1, n_followers + 1))
        self.fleet = {}
        for r in self.ranks:
            tp = TcpTransport(r, eps, timeout=30.0)
            fol, scorer = ss.make_follower(self.stage_dir, self.cfg)
            ff = FleetFollower(tp, 0, fol, scorer, ss.SCHEMA, poll_interval_s=0.02)
            ff.start()
            self.fleet[r] = (tp, ff)
        self.client = FleetClient(self.client_tp, self.ranks, ss.SCHEMA)
        self.client.start()

    def publish(self):
        """Train one pass and publish (base first, deltas after)."""
        path = self.pass0
        if self.n_passes:
            path = os.path.join(self.tmp, f"pass-{self.n_passes}.txt")
            ss.write_pass_file(self.rng, path, 96, 1 + self.n_passes * 120)
        self.ds.set_filelist([path])
        self.ds.load_into_memory()
        self.ds.begin_pass(round_to=8)
        self.trainer.train_pass(self.ds)
        self.ds.end_pass(self.trainer.trained_table_device())
        self.table.drain_pending()
        if self.n_passes == 0:
            self.mgr.save_base(ss.DATE, self.table, self.trainer)
        else:
            self.mgr.save_delta(ss.DATE, self.table, self.trainer)
        self.n_passes += 1

    def reference(self):
        """Trainer-direct probe scores (the bitwise-parity truth)."""
        _tp, ff = self.fleet[self.ranks[0]]
        probe = [ss.parse_line(ln, ss.SCHEMA) for ln in self.probe_lines]
        return ff.server.scorer.score_records(
            probe, ss.SCHEMA, table_source(ss.LAYOUT, self.table),
            self.trainer.params, self.trainer.opt_state,
        )

    def wait_queryable(self, want, timeout=20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if set(self.client.view.queryable()) >= set(want):
                return
            time.sleep(0.02)
        raise AssertionError(
            f"fleet never became queryable: want {sorted(want)}, "
            f"view {self.client.view.snapshot()}"
        )

    def close(self):
        self.client.stop()
        for tp, ff in self.fleet.values():
            ff.stop()
            tp.close()
        self.client_tp.close()
        self.stage_stop.set()
        self.stage_thread.join(timeout=10)


@pytest.fixture
def fleet(tmp_path):
    mf = MiniFleet(tmp_path)
    yield mf
    mf.close()


# ---- FLT008 recovery contracts ---------------------------------------------


def test_request_recv_fault_absorbed_by_client_retry(fleet):
    """Fault site ``serve.request_recv``: the frame is consumed off the
    wire and then lost — counted, and the client's retry/hedge budget gets
    the caller a bitwise-correct answer anyway."""
    fleet.publish()
    fleet.wait_queryable(fleet.ranks)
    ref = fleet.reference()
    errors0 = STAT_GET("serve.request_recv_errors")

    with inject(fail_once("serve.request_recv")) as plan:
        preds, meta = fleet.client.score_lines(fleet.probe_lines[:8], timeout=15)
    assert plan.failures("serve.request_recv") == 1
    assert STAT_GET("serve.request_recv_errors") == errors0 + 1
    np.testing.assert_array_equal(preds, ref[:8])
    assert meta["delta_idx"] == 0


def test_fleet_stage_fault_never_surfaces_partial_version(tmp_path):
    """Fault site ``serve.fleet_stage``: a torn stage fetch leaves the
    stage watermark unwritten (followers keep their last version), and the
    idempotent retry catches the stage up bitwise."""
    root = os.path.join(str(tmp_path), "ckpt")
    stage_dir = os.path.join(str(tmp_path), "stage")
    rng = np.random.default_rng(0)
    table, ds, cfg, trainer, mgr = ss.make_stack(root)
    path = os.path.join(str(tmp_path), "p0.txt")
    lines = ss.write_pass_file(rng, path, 96, 1)
    ds.set_filelist([path])
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    trainer.train_pass(ds)
    ds.end_pass(trainer.trained_table_device())
    table.drain_pending()
    mgr.save_base(ss.DATE, table, trainer)

    stage = FleetStage(root, stage_dir)
    with inject(fail_always("serve.fleet_stage", times=2)) as plan:
        for _ in range(2):
            with pytest.raises(InjectedFault):
                stage.stage_once()
            # the torn fetch never advanced the stage watermark: a
            # follower tailing the stage sees NO version, not a partial one
            assert read_watermark(stage_dir) is None
        assert stage.stage_once() is True  # healed retry is idempotent
    assert plan.failures("serve.fleet_stage") == 2
    assert read_watermark(stage_dir) == read_watermark(root)

    # and the staged chain actually serves: bitwise parity vs the trainer
    fol, scorer = ss.make_follower(stage_dir, cfg)
    assert fol.poll_once() is True
    probe = [ss.parse_line(ln, ss.SCHEMA) for ln in lines[:8]]
    from paddlebox_tpu.serve import version_source

    v = fol.version()
    got = scorer.score_records(
        probe, ss.SCHEMA, version_source(ss.LAYOUT, v), v.params, v.opt_state
    )
    ref = scorer.score_records(
        probe, ss.SCHEMA, table_source(ss.LAYOUT, table),
        trainer.params, trainer.opt_state,
    )
    np.testing.assert_array_equal(got, ref)


def test_drain_fault_client_resends_until_gossip_confirms(fleet):
    """Fault site ``serve.drain``: the first drain command is consumed and
    dropped — counted — and the client's re-send loop converges: the
    follower drains (refuses new work), the view stops routing to it, and
    admit restores it. Both commands are idempotent."""
    fleet.publish()
    fleet.wait_queryable(fleet.ranks)
    victim = fleet.ranks[0]
    errors0 = STAT_GET("serve.drain_errors")

    with inject(fail_once("serve.drain")) as plan:
        assert fleet.client.drain(victim, wait_s=10.0) is True
    assert plan.failures("serve.drain") == 1
    assert STAT_GET("serve.drain_errors") == errors0 + 1
    assert fleet.client.view.status(victim) in ("draining", "drained")
    _tp, ff = fleet.fleet[victim]
    assert ff.draining

    # while drained, requests are served — by the OTHER follower only
    for _ in range(4):
        _preds, meta = fleet.client.score_lines(fleet.probe_lines[:8], timeout=15)
        assert meta["src"] != victim

    # drain is idempotent; admit restores rotation
    assert fleet.client.drain(victim, wait_s=10.0) is True
    assert fleet.client.admit(victim, wait_s=10.0) is True
    assert not ff.draining
    fleet.wait_queryable(fleet.ranks)


# ---- graceful degradation --------------------------------------------------


def test_overload_shed_is_typed_and_counted(tmp_path):
    """Past ``serve_shed_queue_depth`` the in-process front-end refuses
    with the typed ServeOverloadError (retriable on another follower)
    instead of growing the backlog, and counts every shed."""
    root = os.path.join(str(tmp_path), "ckpt")
    rng = np.random.default_rng(0)
    table, ds, cfg, trainer, mgr = ss.make_stack(root)
    path = os.path.join(str(tmp_path), "p0.txt")
    lines = ss.write_pass_file(rng, path, 96, 1)
    ds.set_filelist([path])
    ds.load_into_memory()
    ds.begin_pass(round_to=8)
    trainer.train_pass(ds)
    ds.end_pass(trainer.trained_table_device())
    table.drain_pending()
    mgr.save_base(ss.DATE, table, trainer)
    fol, scorer = ss.make_follower(root, cfg)
    fol.poll_once()
    probe = [ss.parse_line(ln, ss.SCHEMA) for ln in lines[:8]]

    from paddlebox_tpu.serve import ScoreServer

    real = scorer.score_records

    def stalled(*a, **k):
        time.sleep(0.3)
        return real(*a, **k)

    scorer.score_records = stalled
    srv = ScoreServer(fol, scorer, ss.SCHEMA)
    srv.start()
    prev = config.get_flag("serve_shed_queue_depth")
    config.set_flag("serve_shed_queue_depth", 1)
    shed0 = STAT_GET("serve.shed_requests")
    try:
        pendings = [srv.submit(probe)]  # soaks up the batcher
        time.sleep(0.05)
        pendings.append(srv.submit(probe))  # sits in the queue (depth 1)
        with pytest.raises(ServeOverloadError):
            for _ in range(8):
                pendings.append(srv.submit(probe))
        assert STAT_GET("serve.shed_requests") > shed0
        for p in pendings:  # the admitted work still completes
            assert p.result(10.0).shape == (8,)
    finally:
        config.set_flag("serve_shed_queue_depth", prev)
        scorer.score_records = real
        srv.stop()


def test_hedge_rescues_silent_follower(fleet):
    """A follower that accepts a request and then stalls past
    ``serve_hedge_ms`` does not consume the whole deadline: the client
    re-dispatches to the second follower and the first answer wins."""
    fleet.publish()
    fleet.wait_queryable(fleet.ranks)
    ref = fleet.reference()

    slow_rank = fleet.ranks[0]
    _tp, slow_ff = fleet.fleet[slow_rank]
    real = slow_ff.server.scorer.score_records

    def stalled(*a, **k):
        time.sleep(1.5)  # >> serve_hedge_ms (100ms)
        return real(*a, **k)

    slow_ff.server.scorer.score_records = stalled
    hedges0 = STAT_GET("serve.hedges")
    try:
        # round-robin guarantees the slow rank is primary within 2 requests
        t0 = time.monotonic()
        for _ in range(2):
            preds, _meta = fleet.client.score_lines(fleet.probe_lines[:8], timeout=15)
            np.testing.assert_array_equal(preds, ref[:8])
        assert STAT_GET("serve.hedges") > hedges0
        # the hedge answered well inside the stall, not after it
        assert time.monotonic() - t0 < 3.0
    finally:
        slow_ff.server.scorer.score_records = real
