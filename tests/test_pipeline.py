"""Pipeline parallelism tests (SectionWorker/PipelineTrainer parity).

The pipelined program must be numerically identical to running the stages
sequentially on one device — the schedule changes wall-clock structure, not
math (like the reference's sections running one program's pieces).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.parallel import (
    PipelineSpec,
    init_pipeline_state,
    make_mesh,
    make_pipeline_train_step,
    pipeline_forward,
)
from paddlebox_tpu.parallel.pipeline import mlp_stage_apply, mlp_stage_init
from paddlebox_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

N_STAGES = 4
HID = 16
MB = 8
M = 6  # microbatches


@pytest.fixture(scope="module")
def stages():
    return mlp_stage_init(jax.random.PRNGKey(0), HID, layers_per_stage=2,
                          n_stages=N_STAGES)


def sequential_forward(stages, x):
    for sp in stages:
        x = mlp_stage_apply(sp, x)
    return x


def test_pipeline_forward_matches_sequential(stages):
    plan = make_mesh(N_STAGES, axis="pp")
    spec = PipelineSpec(n_micro=M, axis_name="pp")
    fwd = pipeline_forward(mlp_stage_apply, spec)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(M, MB, HID)).astype(np.float32))

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)

    def run(params, xm):
        return fwd(jax.tree.map(lambda a: a[0], params), xm)

    mapped = jax.jit(
        shard_map(
            run, mesh=plan.mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(mapped(jax.device_put(stacked, plan.batch_sharding), x))
    want = np.asarray(jax.vmap(lambda xx: sequential_forward(stages, xx))(x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_pipeline_train_matches_sequential(stages):
    """Grads through ppermute == sequential grads; training converges."""
    plan = make_mesh(N_STAGES, axis="pp")
    spec = PipelineSpec(n_micro=M, axis_name="pp")
    opt = optax.adam(1e-2)

    def loss_fn(y, tgt):
        return jnp.mean((y - tgt) ** 2)

    step = make_pipeline_train_step(mlp_stage_apply, loss_fn, opt, spec, plan)
    state = init_pipeline_state(plan, stages, opt)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(M, MB, HID)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(M, MB, HID))).astype(np.float32))

    # sequential reference: same loss, same params after one sgd step
    seq_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)

    def seq_loss(stacked_p):
        ps = [jax.tree.map(lambda a: a[s], stacked_p) for s in range(N_STAGES)]
        y = jax.vmap(lambda xx: sequential_forward(ps, xx))(x)
        return jnp.mean(jax.vmap(loss_fn)(y, tgt))

    l0, g0 = jax.value_and_grad(seq_loss)(seq_stacked)
    upd, _ = opt.update(g0, opt.init(seq_stacked), seq_stacked)
    seq_after = optax.apply_updates(seq_stacked, upd)

    state, loss = step(state, x, tgt)
    np.testing.assert_allclose(float(loss), float(l0), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(state[0]), jax.tree.leaves(seq_after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)

    # optimization sanity: loss falls steadily (deep relu net memorizing
    # random targets converges slowly; exact math parity is checked above)
    losses = [float(loss)]
    for _ in range(50):
        state, loss = step(state, x, tgt)
        losses.append(float(loss))
    assert losses[-1] < 0.85 * losses[0]
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


def test_pipeline_stage_count_mismatch(stages):
    plan = make_mesh(N_STAGES, axis="pp")
    with pytest.raises(ValueError, match="stages"):
        init_pipeline_state(plan, stages[:2], optax.sgd(0.1))


def test_pipeline_composes_with_zero1_sharding():
    """pp x dp + ZeRO-1 over dp: optimizer moments shard 1/n_dp per stage
    replica, and the trajectory is BIT-compatible with the plain inner
    adam (elementwise chunked update == full update) — the fleet sharding
    meta-optimizer layered under PipelineTrainer sections."""
    from paddlebox_tpu.fleet import Zero1Optimizer
    from paddlebox_tpu.parallel.mesh import make_mesh_2d

    n_pp, n_dp = 2, 2
    stages2 = mlp_stage_init(
        jax.random.PRNGKey(5), HID, layers_per_stage=2, n_stages=n_pp
    )

    def loss_fn(y, tgt):
        return jnp.mean((y - tgt) ** 2)

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(M, MB, HID)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(M, MB, HID))).astype(np.float32))
    spec = PipelineSpec(n_micro=M, axis_name="pp")

    plan = make_mesh_2d(n_pp, n_dp)
    ref_opt = optax.adam(1e-2)
    step_ref = make_pipeline_train_step(
        mlp_stage_apply, loss_fn, ref_opt, spec, plan, dp_axis="dp"
    )
    st_ref = init_pipeline_state(plan, stages2, ref_opt, axis="pp")

    zopt = Zero1Optimizer(optax.adam(1e-2), axis_name="dp", n_dev=n_dp)
    step_z = make_pipeline_train_step(
        mlp_stage_apply, loss_fn, zopt, spec, plan, dp_axis="dp"
    )
    st_z = init_pipeline_state(plan, stages2, zopt, axis="pp", dp_axis="dp")
    # moments physically carry the [n_pp, n_dp, chunk] layout
    for leaf in jax.tree.leaves(st_z[1]):
        if leaf.ndim >= 2:
            assert leaf.shape[:2] == (n_pp, n_dp)

    for i in range(3):
        st_ref, loss_r = step_ref(st_ref, x, tgt)
        st_z, loss_z = step_z(st_z, x, tgt)
        np.testing.assert_allclose(
            float(loss_z), float(loss_r), rtol=1e-6, err_msg=f"step {i}"
        )
    for a, b in zip(jax.tree.leaves(st_z[0]), jax.tree.leaves(st_ref[0])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )
    # guard-rail: ZeRO pipeline without a dp axis must be rejected
    plan1 = make_mesh(n_pp, axis="pp")
    with pytest.raises(ValueError, match="dp axis|dp_axis"):
        make_pipeline_train_step(
            mlp_stage_apply, loss_fn, zopt, spec, plan1
        )


def test_pipeline_composes_with_dp():
    """pp x dp 2-D mesh: each pipeline replica trains its dp-shard of every
    microbatch; grads pmean over dp. One step must equal the 1-D pipeline
    over the same GLOBAL data (the PipelineTrainer-sections x fleet-DP
    layering of the reference, optimizer.py:5194 + fleet ranks)."""
    from paddlebox_tpu.parallel.mesh import make_mesh_2d

    n_pp, n_dp = 2, 2
    stages2 = mlp_stage_init(
        jax.random.PRNGKey(3), HID, layers_per_stage=2, n_stages=n_pp
    )
    opt = optax.adam(1e-2)

    def loss_fn(y, tgt):
        return jnp.mean((y - tgt) ** 2)

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(M, MB, HID)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(M, MB, HID))).astype(np.float32))

    # 1-D reference: pp-only mesh over the same global microbatches
    plan1 = make_mesh(n_pp, axis="pp")
    spec = PipelineSpec(n_micro=M, axis_name="pp")
    step1 = make_pipeline_train_step(mlp_stage_apply, loss_fn, opt, spec, plan1)
    st1 = init_pipeline_state(plan1, stages2, opt)
    st1, loss1 = step1(st1, x, tgt)

    # 2-D: same data, mb axis split across dp replicas
    plan2 = make_mesh_2d(n_pp, n_dp)
    assert plan2.axis == "dp"
    step2 = make_pipeline_train_step(
        mlp_stage_apply, loss_fn, opt, spec, plan2, dp_axis="dp"
    )
    st2 = init_pipeline_state(plan2, stages2, opt, axis="pp")
    st2, loss2 = step2(st2, x, tgt)

    # equal-sized dp shards: mean-of-shard-means == global mean, so loss
    # and the updated stage params agree with the 1-D run
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(st2[0]), jax.tree.leaves(st1[0])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )
    # and it trains
    for _ in range(20):
        st2, loss2 = step2(st2, x, tgt)
    assert float(loss2) < float(loss1)
