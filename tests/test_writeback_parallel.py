"""Parallel end-of-pass writeback: bitwise identity, exact stats, recovery.

PR claim under test: the writer-pool push (``pbx_table_push_mt``), the
chunked ``PassWorkingSet.writeback`` pipeline, and the overlapped
boundary kick are *pure mechanism* — every value the host table holds
afterwards is bit-for-bit what the legacy serial path
(``writeback_threads=1`` -> plain ``table.push``) produces, at every
thread count and chunk size, with and without the disk spill tier in
play. The fault half pins the recovery contracts for the two new sites:
an injected ``table.writeback_worker`` failure mid-day surfaces as the
typed SpillIOError, the supervisor's revert restores pre-pass rows
bitwise, and the retry lands a final state identical to a never-faulted
run; an injected ``spill.stage_flush`` failure dies loudly without
corrupting the resident tier.
"""

from __future__ import annotations

import hashlib
import tempfile
import threading

import jax
import numpy as np
import optax
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    SpillIOError,
    ValueLayout,
)
from paddlebox_tpu.table.sparse_table import WritebackCancelled
from paddlebox_tpu.train import (
    CTRTrainer,
    PassSupervisor,
    RetryPolicy,
    TrainStepConfig,
)
from paddlebox_tpu.utils.faultinject import fail_nth, fail_once, inject
from paddlebox_tpu.utils.monitor import STAT_GET

WB_FLAGS = (
    "writeback_threads", "writeback_chunk_keys", "overlap_writeback",
    "spill_pin_show", "spill_admit_show",
)


@pytest.fixture(autouse=True)
def _restore_wb_flags():
    saved = {n: config.get_flag(n) for n in WB_FLAGS}
    yield
    for n, v in saved.items():
        config.set_flag(n, v)


def _native_or_skip():
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native table store unavailable")


def _digest(table) -> str:
    """sha256 over the key-sorted full snapshot: bitwise table identity."""
    k = np.sort(table.keys())
    v = table.pull_or_create(k)
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(k).tobytes())
    h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------- native tier

LAY = ValueLayout(embedx_dim=2)
TOPT = SparseOptimizerConfig(show_clk_decay=0.9, shrink_threshold=0.0)


def _grow(spill_dir, threads, passes=3) -> HostSparseTable:
    """Deterministic multi-pass grow/update/spill schedule; ``threads<=1``
    routes every update through the serial push, otherwise through the
    writer-pool push — the ONLY difference between two calls."""
    table = HostSparseTable(
        LAY, TOPT, n_shards=8, seed=0, spill_dir=spill_dir,
    )
    rng = np.random.default_rng(7)
    for p in range(passes):
        keys = np.unique(rng.integers(1, 4000, 1500).astype(np.uint64))
        rows = table.pull_or_create(keys)
        rows = rows + np.sin(
            keys[:, None].astype(np.float64) * (p + 1)
        ).astype(np.float32)
        if threads <= 1:
            table.push(keys, rows)
        else:
            table.push_writeback(keys, rows, threads)
        table.decay_and_shrink()
        if spill_dir is not None:
            table.spill_cold(800)  # force disk-tier victims + promotes
    return table


@pytest.mark.parametrize("threads", [2, 3, 7])
def test_push_mt_bitwise_equals_serial_with_spill(tmp_path, threads):
    """Writer-pool push over sharded+spilled tables == serial push, bit
    for bit, at several pool sizes (including one above n_shards/2 so
    strided shard ownership wraps)."""
    _native_or_skip()
    config.set_flag("spill_pin_show", 3.0)   # exercise pin/admission
    config.set_flag("spill_admit_show", 0.5)
    with tempfile.TemporaryDirectory() as d_ref:
        ref = _digest(_grow(d_ref, threads=1))
    with tempfile.TemporaryDirectory() as d:
        got = _digest(_grow(d, threads=threads))
    assert got == ref


def test_push_mt_stats_exact_vs_serial(tmp_path):
    """Per-shard occupancy and every cumulative flow counter after the
    parallel push equal the serial run exactly — the per-shard stats
    merge cannot drop or double-count under the pool."""
    _native_or_skip()
    with tempfile.TemporaryDirectory() as d_ref:
        t_ref = _grow(d_ref, threads=1)
        st_ref = t_ref.tier_stats()
        n_ref = len(t_ref)
    with tempfile.TemporaryDirectory() as d:
        t_par = _grow(d, threads=4)
        st_par = t_par.tier_stats()
        assert len(t_par) == n_ref
        assert st_par == st_ref
        io = t_par._native.io_stats()
        # the double-buffered stage writers actually ran on this schedule
        assert io["stage_bytes"] > 0 and io["stage_flushes"] > 0


def test_push_disk_hit_prepass_bitwise_and_counted(tmp_path):
    """Pushing straight onto spilled rows (no pull first — the upsert
    shape checkpoint resume and shard adoption use) routes through the
    sorted-offset header pre-pass; with thousands of hits per shard the
    double-buffered reader thread engages, and the result must be
    bitwise- and counter-identical to the serial pre-pass."""
    _native_or_skip()

    def run(threads):
        with tempfile.TemporaryDirectory() as d:
            table = HostSparseTable(LAY, TOPT, n_shards=2, seed=0,
                                    spill_dir=d)
            keys = np.arange(1, 6001, dtype=np.uint64)
            rows = table.pull_or_create(keys) + 1.0
            table.push(keys, rows)
            table.spill_cold(64)  # ~3k disk rows per shard
            if threads <= 1:
                table.push(keys, rows * 2.0)
            else:
                table.push_writeback(keys, rows * 2.0, threads)
            pre_ns = table._native.io_stats()["prepass_read_ns"]
            return _digest(table), pre_ns, table.tier_stats()

    d1, pre1, st1 = run(1)
    d4, pre4, st4 = run(4)
    assert d1 == d4
    assert pre1 > 0 and pre4 > 0  # the pre-pass actually read headers
    assert st1 == st4


# ----------------------------------------------------- working-set writeback

NS, B = 4, 16
OPT = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
TRAIN_LAY = ValueLayout(embedx_dim=4)


def _write(tmp_path, name="d.txt", seed=5, n=96):
    rng = np.random.default_rng(seed)
    path = tmp_path / name
    with open(path, "w") as f:
        for _ in range(n):
            keys = rng.integers(1, 400, NS)
            f.write(
                f"1 {int(keys[0]) % 2}.0 "
                + " ".join(f"1 {k}" for k in keys) + "\n"
            )
    return str(path)


def _build(path):
    table = HostSparseTable(TRAIN_LAY, OPT, n_shards=4, seed=0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    ds = BoxPSDataset(schema, table, batch_size=B, seed=0)
    ds.set_filelist([path])
    model = DeepFM(num_slots=NS, feat_width=TRAIN_LAY.pull_width,
                   embedx_dim=4, hidden=(8,))
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=TRAIN_LAY, sparse_opt=OPT,
        auc_buckets=500,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    return table, ds, tr


def _one_pass_state(path, threads, chunk):
    config.set_flag("writeback_threads", threads)
    config.set_flag("writeback_chunk_keys", chunk)
    table, ds, tr = _build(path)
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    tr.train_pass(ds)
    ds.end_pass(tr.trained_table(), shrink=False)
    k = np.sort(table.keys())
    return k, table.pull_or_create(k)


@pytest.mark.parametrize("threads,chunk", [(4, 37), (4, 10_000), (7, 64)])
def test_ws_writeback_chunked_bitwise_equals_serial(tmp_path, threads, chunk):
    """The chunked single-slot writeback pipeline (gather overlapping the
    in-flight push) lands the identical host table as the legacy serial
    one-shot push, across chunk sizes that split the key batch many ways
    and one that doesn't split it at all."""
    _native_or_skip()
    path = _write(tmp_path)
    k_ref, v_ref = _one_pass_state(path, threads=1, chunk=1_000_000)
    k, v = _one_pass_state(path, threads=threads, chunk=chunk)
    np.testing.assert_array_equal(k, k_ref)
    np.testing.assert_array_equal(v, v_ref)
    if chunk == 37:
        # the pipeline really chunked (not one degenerate mega-chunk)
        assert STAT_GET("table.writeback.chunks") == -(-len(k_ref) // 37)
        assert STAT_GET("table.writeback.threads") == 4


def test_ws_writeback_cancel_then_revert_restores_bitwise(tmp_path):
    """Cancelling mid-writeback stops at a chunk boundary (typed
    WritebackCancelled, a strict prefix of the key batch landed) and the
    armed guard's revert then restores the pre-pass rows bitwise — the
    revert-cancels-kick path in miniature, made deterministic by setting
    the cancel event from the first chunk's push."""
    _native_or_skip()
    config.set_flag("writeback_threads", 4)
    config.set_flag("writeback_chunk_keys", 29)
    path = _write(tmp_path)
    table, ds, tr = _build(path)
    ds.load_into_memory()
    ds.begin_pass(round_to=64, enable_revert=True, trainer=tr)
    pre_keys = ds.ws.sorted_keys.copy()
    pre_vals = table.pull_or_create(pre_keys).copy()
    tr.train_pass(ds, n_batches=3)

    cancel = threading.Event()
    orig = table.push_writeback

    def arm_then_push(keys, rows, threads):
        cancel.set()  # next chunk boundary must observe the cancellation
        orig(keys, rows, threads)

    table.push_writeback = arm_then_push
    try:
        with pytest.raises(WritebackCancelled) as ei:
            ds.ws.writeback(tr.trained_table(), cancel=cancel)
    finally:
        table.push_writeback = orig
    assert 0 < ei.value.done_keys < ei.value.total_keys
    assert ei.value.done_keys % 29 == 0  # cut exactly at a chunk boundary

    ds.revert_pass()
    np.testing.assert_array_equal(table.pull_or_create(pre_keys), pre_vals)


# -------------------------------------------------------------- fault sites

S = 3
DATE = "20260807"


def _day_files(tmp_path, tag):
    return [
        _write(tmp_path, f"{tag}-{p}.txt", seed=11 + p, n=48)
        for p in range(3)
    ]


def _day_sup(tmp_path, path_list):
    table, ds, tr = _build(path_list[0])
    sup = PassSupervisor(
        ds, tr, retry=RetryPolicy(backoff_s=0.0, sleep=lambda s: None),
        round_to=64, on_give_up="raise",
    )
    return table, ds, tr, sup


def test_writeback_worker_fault_midday_revert_retry_bitwise(tmp_path):
    """Inject a worker failure into pass 2's overlapped writeback kick of
    a supervised 3-pass day: the SpillIOError propagates through the
    boundary worker, the supervisor reverts (restoring pre-pass rows) and
    retries, and the day's final table is bitwise-identical to a
    never-faulted run."""
    _native_or_skip()
    config.set_flag("writeback_threads", 4)
    config.set_flag("writeback_chunk_keys", 1_000_000)
    files = _day_files(tmp_path, "wb")

    table_c, _, tr_c, sup_c = _day_sup(tmp_path, files)
    with inject() as probe:
        outs_c = sup_c.run_day(DATE, [[f] for f in files])
    assert sup_c.incidents == []
    assert all(o is not None for o in outs_c)
    hits_per_pass = probe.hits("table.writeback_worker") // 3
    assert hits_per_pass >= 1  # the kick actually routed through the pool

    table_i, _, tr_i, sup_i = _day_sup(tmp_path, files)
    with inject(
        fail_nth("table.writeback_worker", hits_per_pass + 1)
    ) as plan:
        outs_i = sup_i.run_day(DATE, [[f] for f in files])
    assert plan.failures("table.writeback_worker") == 1
    assert all(o is not None for o in outs_i)
    assert [i.kind for i in sup_i.incidents] == ["train_error"]

    k_c = np.sort(table_c.keys())
    k_i = np.sort(table_i.keys())
    np.testing.assert_array_equal(k_i, k_c)
    np.testing.assert_array_equal(
        table_i.pull_or_create(k_i), table_c.pull_or_create(k_c)
    )
    for a, b in zip(jax.tree.leaves(tr_i.params), jax.tree.leaves(tr_c.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_writeback_worker_fault_surfaces_typed_error():
    """Outside any supervisor, the armed site turns a push_writeback call
    into the typed SpillIOError and counts it — the contract the boundary
    worker's failure path keys off."""
    _native_or_skip()
    table = HostSparseTable(LAY, TOPT, n_shards=2, seed=0)
    keys = np.arange(1, 64, dtype=np.uint64)
    rows = table.pull_or_create(keys)
    before = STAT_GET("table.spill_errors")
    with inject(fail_once("table.writeback_worker")):
        with pytest.raises(SpillIOError):
            table.push_writeback(keys, rows, 2)
        # heals: the retry lands and the table is intact
        table.push_writeback(keys, rows + 1.0, 2)
    assert STAT_GET("table.spill_errors") == before + 1
    np.testing.assert_array_equal(table.pull_or_create(keys), rows + 1.0)


def test_stage_flush_fault_dies_loudly_keeps_resident_tier():
    """An injected spill.stage_flush failure (the double-buffered stage
    writer's fwrite handoff dying mid-sweep) surfaces as SpillIOError,
    and the rows the sweep was about to spill are still served bitwise
    from the resident tier; the healed retry then spills clean."""
    _native_or_skip()
    with tempfile.TemporaryDirectory() as d:
        table = HostSparseTable(
            LAY, TOPT, n_shards=2, seed=0, spill_dir=d,
        )
        keys = np.arange(1, 901, dtype=np.uint64)
        rows = table.pull_or_create(keys).copy()
        before = STAT_GET("table.spill_errors")
        with inject(fail_once("spill.stage_flush")):
            with pytest.raises(SpillIOError):
                table.spill_cold(100)
            np.testing.assert_array_equal(table.pull_or_create(keys), rows)
            assert table.spill_cold(100) == 800  # healed retry spills clean
        assert STAT_GET("table.spill_errors") == before + 1
        np.testing.assert_array_equal(table.pull_or_create(keys), rows)
