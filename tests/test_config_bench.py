"""tools/config_bench.py smoke: all five BASELINE configs run end to end
through the trainer machinery and emit valid JSON."""

import json
import os
import subprocess
import sys

import pytest

pytest.importorskip("jax")


def test_all_five_configs_run(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PBOX_BENCH_INIT_RETRIES="1",
        PBOX_BENCH_INIT_TIMEOUT="5",
    )
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "tools", "config_bench.py"),
            "--rows", "4096",
            "--batches", "3",
        ],
        capture_output=True,
        text=True,
        timeout=540,
        cwd=repo,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    assert len(lines) == 5
    names = [l["config"] for l in lines]
    assert names == [
        "1-lr-criteo",
        "2-widedeep",
        "3-deepfm-small",
        "4-dcn-multislot",
        "5-mmoe",
    ]
    for l in lines:
        assert "error" not in l, l
        assert l["samples_per_sec"] > 0
        assert 0.0 <= l["auc"] <= 1.0


def test_all_five_configs_run_real_format(tmp_path):
    """--data-dir: every config trains the converted Kaggle-format fixture
    (3k lines incl. malformed — reject path exercised), so the day real
    CTR data appears nothing breaks (dist_fleet_ctr.py:1 parity)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data_dir = tmp_path / "criteo"
    data_dir.mkdir()
    import shutil

    shutil.copy(
        os.path.join(repo, "tests", "fixtures", "criteo_train_sample.txt"),
        data_dir / "train.txt",
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PBOX_BENCH_INIT_RETRIES="1",
        PBOX_BENCH_INIT_TIMEOUT="5",
    )
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "tools", "config_bench.py"),
            "--batches", "3",
            "--data-dir", str(data_dir),
        ],
        capture_output=True,
        text=True,
        timeout=540,
        cwd=repo,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    head, results = lines[0], lines[1:]
    assert head["accepted"] == 3020 and head["rejected"] == 60
    assert len(results) == 5
    for l in results:
        assert "error" not in l, l
        assert l["real_format"] is True
        assert l["rejected_lines"] == 60
        assert l["slots"] == 39
        assert l["samples_per_sec"] > 0
        assert 0.0 <= l["auc"] <= 1.0
