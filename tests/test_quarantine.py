"""Data-plane quarantine: per-line/per-file capture, dead-letter files,
and the bounded-loss admission gate (data/quarantine.py + dataset glue).

Chaos-path coverage (supervised days, poison-aware supervisor, 3-rank
lockstep) lives in tests/test_chaos.py / tests/test_chaos_dist.py; this
file pins the unit semantics both build on.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from paddlebox_tpu import config
from paddlebox_tpu.data import (
    BoxPSDataset,
    DataPoisonedError,
    SlotInfo,
    SlotSchema,
    parse_logkey,
    read_dead_letter,
)
from paddlebox_tpu.table import (
    HostSparseTable,
    SparseOptimizerConfig,
    ValueLayout,
)
from paddlebox_tpu.utils.faultinject import fail_nth, inject


@pytest.fixture(autouse=True)
def _quarantine_flags():
    """Pin the flags this file exercises; restore whatever was set."""
    names = (
        "data_quarantine",
        "max_bad_line_fraction",
        "max_bad_file_fraction",
        "data_quarantine_dir",
        "fs_open_backoff_s",
        "enable_native_parser",
    )
    prev = {n: config.get_flag(n) for n in names}
    config.set_flag("fs_open_backoff_s", 0.0)
    yield
    for n, v in prev.items():
        config.set_flag(n, v)


def _schema():
    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1),
         SlotInfo("s0"), SlotInfo("s1")],
        label_slot="label",
    )


def _ds(tmp_path, **kw):
    table = HostSparseTable(
        ValueLayout(embedx_dim=4), SparseOptimizerConfig(), n_shards=2, seed=0
    )
    kw.setdefault("quarantine_dir", str(tmp_path / "quarantine"))
    return BoxPSDataset(_schema(), table, batch_size=2, **kw)


GOOD = ["1 1.0 1 5 1 9", "1 0.5 2 6 7 1 3", "1 1.0 1 8 1 2"]
BAD = ["garbage !! not-a-line", "1 1.0 1", "1 0.0 0 1 4"]
BENIGN = "1 1.0 1 0 1 0"  # all-zero sparse keys -> parser returns None


def _write(path, lines):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("\n".join(lines) + "\n")
    return str(path)


# ---- parse_logkey validation (satellite) --------------------------------

def test_parse_logkey_named_validation_errors():
    ok = "0" * 11 + "0ab" + "03" + "0000000000000111"
    assert parse_logkey(ok) == (0x111, 0xAB, 3)
    with pytest.raises(ValueError, match="too short.*'deadbeef'"):
        parse_logkey("deadbeef")
    with pytest.raises(ValueError, match="non-hex cmatch field 'xyz'"):
        parse_logkey("0" * 11 + "xyz" + "03" + "0" * 16)
    with pytest.raises(ValueError, match="non-hex rank field"):
        parse_logkey("0" * 11 + "0ab" + "zz" + "0" * 16)
    with pytest.raises(ValueError, match="non-hex search_id field"):
        parse_logkey("0" * 11 + "0ab" + "03" + "nothexnothexnoth")


def test_parse_logkey_length_floor_matches_native():
    # the native tier requires > 16 hex chars; 17 must parse in both
    assert parse_logkey("0" * 17) == (0, 0, 0)
    with pytest.raises(ValueError, match="too short"):
        parse_logkey("0" * 16)


# ---- per-line quarantine + dead-letter ----------------------------------

def test_quarantine_counters_and_dead_letter_roundtrip(tmp_path):
    config.set_flag("enable_native_parser", 0)
    lines = [GOOD[0], BAD[0], GOOD[1], BENIGN, BAD[1], "", GOOD[2]]
    f0 = _write(tmp_path / "part-0.txt", lines)
    f1 = _write(tmp_path / "part-1.txt", GOOD)
    ds = _ds(tmp_path, read_threads=1)
    ds.set_date("20260101")
    ds.set_filelist([f0, f1])
    ds.load_into_memory()

    st = ds.stats
    assert st.files == 2
    assert st.lines == 9  # blank line not counted
    assert st.parsed == 6 and st.records == 6
    assert st.skipped_benign == 1
    assert st.bad_lines == 2 and st.bad_files == 0
    assert st.bad_by_file == {f0: 2}

    dl = read_dead_letter(st.dead_letter)
    assert dl["summary"]["bad_lines"] == 2
    assert dl["summary"]["truncated"] is False
    assert [e["line"] for e in dl["entries"]] == [BAD[0], BAD[1]]
    assert [e["line_no"] for e in dl["entries"]] == [2, 5]
    assert all(e["file"] == f0 and e["error"] for e in dl["entries"])


def test_native_and_python_tiers_report_identically(tmp_path):
    """Same corrupt file through both parser tiers: identical PassStats
    accounting and identical surviving records (the native tier's corrupt
    buffer re-parses per line and stays columnar)."""
    from paddlebox_tpu.utils import native

    if not native.available():
        pytest.skip("native parser unavailable")
    lines = [GOOD[0], BAD[0], GOOD[1], BENIGN, BAD[2], GOOD[2]]
    f = _write(tmp_path / "part-0.txt", lines)

    def load(native_on):
        config.set_flag("enable_native_parser", native_on)
        ds = _ds(tmp_path / f"n{native_on}", read_threads=1)
        ds.set_date("20260101")
        ds.set_filelist([f])
        ds.load_into_memory()
        return ds

    a, b = load(1), load(0)
    for st in (a.stats, b.stats):
        assert (st.lines, st.parsed, st.skipped_benign, st.bad_lines) == (6, 3, 1, 2)
    assert a.store is not None, "corrupt file knocked the pass off columnar"
    assert len(a.records) == len(b.records) == 3
    for ra, rb in zip(a.records, b.records):
        np.testing.assert_array_equal(ra.u64_values, rb.u64_values)
        np.testing.assert_array_equal(ra.f_values, rb.f_values)


def test_strict_mode_first_bad_line_raises(tmp_path):
    config.set_flag("data_quarantine", 0)
    config.set_flag("enable_native_parser", 0)
    f = _write(tmp_path / "part-0.txt", [GOOD[0], BAD[0]])
    ds = _ds(tmp_path)
    ds.set_filelist([f])
    with pytest.raises(ValueError):
        ds.load_into_memory()
    assert ds.stats.bad_lines == 0  # nothing was quarantined


# ---- file-level quarantine ----------------------------------------------

def test_unreadable_file_quarantined_but_missing_file_raises(tmp_path):
    config.set_flag("enable_native_parser", 0)
    f_ok = _write(tmp_path / "part-0.txt", GOOD)
    # a synthetic unreadable file via the data.file_read fault site
    ds = _ds(tmp_path, read_threads=1)
    ds.set_date("20260101")
    with inject(fail_nth("data.file_read", 1)):
        ds.set_filelist([f_ok, f_ok])
        ds.load_into_memory()
    st = ds.stats
    assert st.bad_files == 1 and st.records == len(GOOD)
    rep = ds.admission_report()
    assert rep["poisoned"] and rep["file_fraction"] == 0.5
    dl = read_dead_letter(st.dead_letter)
    assert dl["entries"][0]["kind"] == "file"
    assert "injected fault" in dl["entries"][0]["error"]

    # a MISSING input is transient (late upstream drop): never quarantined
    ds2 = _ds(tmp_path)
    ds2.set_filelist([str(tmp_path / "never.txt")])
    with pytest.raises(FileNotFoundError):
        ds2.load_into_memory()


def test_truncated_gz_quarantined(tmp_path):
    import gzip

    whole = gzip.compress(("\n".join(GOOD) + "\n").encode())
    torn = tmp_path / "part-0.txt.gz"
    torn.write_bytes(whole[: len(whole) // 2])
    ok = tmp_path / "part-1.txt.gz"
    ok.write_bytes(whole)
    ds = _ds(tmp_path, read_threads=1)
    ds.set_filelist([str(torn), str(ok)])
    ds.load_into_memory()
    assert ds.stats.bad_files == 1
    assert ds.stats.records == len(GOOD)


# ---- admission gate ------------------------------------------------------

def test_admission_gate_rejects_and_admit_poisoned_overrides(tmp_path):
    config.set_flag("enable_native_parser", 0)
    f = _write(tmp_path / "part-0.txt", GOOD + [BAD[0]])
    ds = _ds(tmp_path)
    ds.set_date("20260101")
    ds.set_filelist([f])
    ds.load_into_memory()
    with pytest.raises(DataPoisonedError) as ei:
        ds.begin_pass(round_to=8)
    assert ei.value.dead_letter and os.path.exists(ei.value.dead_letter)
    assert ei.value.dead_letter in str(ei.value)
    assert ei.value.report["bad_lines"] == 1
    assert not ds._in_pass  # nothing armed/finalized by the rejection
    # degrade override: same pass trains over the surviving records
    ds.begin_pass(round_to=8, admit_poisoned=True)
    assert ds._in_pass and ds.memory_data_size() == len(GOOD)
    ds.end_pass(None, shrink=False)


def test_admission_thresholds_bound_loss(tmp_path):
    config.set_flag("enable_native_parser", 0)
    # 1 bad line in 100: under the default 1% line threshold -> admitted
    f = _write(tmp_path / "part-0.txt", GOOD * 33 + [BAD[0]])
    ds = _ds(tmp_path)
    ds.set_filelist([f])
    ds.load_into_memory()
    rep = ds.admission_report()
    assert not rep["poisoned"] and rep["bad_lines"] == 1
    ds.begin_pass(round_to=8)
    ds.end_pass(None, shrink=False)
    # tightening the knob re-poisons the same stats
    config.set_flag("max_bad_line_fraction", 0.0)
    assert ds.admission_report()["poisoned"]


def test_drop_pass_data_clears_unbegun_pass(tmp_path):
    config.set_flag("enable_native_parser", 0)
    f = _write(tmp_path / "part-0.txt", GOOD + [BAD[0]])
    ds = _ds(tmp_path)
    ds.set_filelist([f])
    ds.load_into_memory()
    ds.drop_pass_data()
    assert ds.memory_data_size() == 0 and ds.ws is None
    assert not ds.admission_report()["poisoned"]  # fresh stats
    with pytest.raises(RuntimeError, match="load_into_memory"):
        ds.begin_pass()
