"""Eval/infer mode: SetTestMode gates a metrics-only pass.

Parity: BoxWrapper::SetTestMode (box_wrapper.cc:623) + infer_from_dataset
(executor.py:1520). An eval pass must leave the sparse table, dense params,
and optimizer state BIT-identical while still producing AUC/loss metrics —
this is what makes AucRunner slots-shuffle evaluation sound (the shuffled
pass must not train on shuffled features).
"""

import jax
import numpy as np
import optax
import pytest

from paddlebox_tpu.boxps import BoxWrapper
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(embedx_threshold=0.0, initial_range=0.01)
NS, B = 4, 16


def _build(tmp_path, box, n_mesh_shards=1):
    rng = np.random.default_rng(0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    path = tmp_path / "data.txt"
    with open(path, "w") as f:
        for _ in range(96):
            keys = rng.integers(1, 300, NS)
            f.write(
                f"1 {int(keys[0]) % 2}.0 "
                + " ".join(f"1 {k}" for k in keys) + "\n"
            )
    ds = box.make_dataset(
        schema, batch_size=B, seed=0, n_mesh_shards=n_mesh_shards
    )
    ds.set_filelist([str(path)])
    return ds


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree.leaves(tree)]


@pytest.mark.parametrize("mesh", [False, True])
def test_eval_pass_bit_identical_state(tmp_path, mesh):
    box = BoxWrapper(embedx_dim=4, sparse_opt=OPT, n_host_shards=4)
    ds = _build(tmp_path, box, n_mesh_shards=4 if mesh else 1)
    model = DeepFM(num_slots=NS, feat_width=LAYOUT.pull_width,
                   embedx_dim=4, hidden=(8,))
    plan = None
    bs = B
    if mesh:
        from paddlebox_tpu.parallel import make_mesh

        plan = make_mesh(4)
        bs = B // 4
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=bs, layout=LAYOUT, sparse_opt=OPT,
        auc_buckets=500, axis_name=plan.axis if plan else None,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan, box=box)
    tr.init_params(jax.random.PRNGKey(0))

    # pass 1: train normally
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    tr.train_pass(ds)
    table_before = tr.trained_table().copy()
    params_before = _leaves(tr.params)
    opt_before = _leaves(tr.opt_state)

    # pass continues in eval mode over the same working set
    box.set_test_mode(True)
    out = tr.train_pass(ds)
    assert out["batches"] > 0 and np.isfinite(out["loss"])
    assert 0.0 < out["auc"] <= 1.0  # metrics still flow

    table_after = tr.trained_table()
    np.testing.assert_array_equal(table_after, table_before)
    for a, b in zip(_leaves(tr.params), params_before):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_leaves(tr.opt_state), opt_before):
        np.testing.assert_array_equal(a, b)

    # end_pass writeback lands exactly the PRE-eval trained rows: the eval
    # pass contributed nothing to what reaches the host table
    keys = ds.ws.sorted_keys.copy()
    rows = ds.ws.row_of_sorted.copy()
    ds.end_pass(tr.trained_table(), shrink=False)
    flat = table_before.reshape(-1, LAYOUT.width)
    np.testing.assert_array_equal(box.table.pull_or_create(keys), flat[rows])

    # clearing test_mode resumes real training
    box.set_test_mode(False)
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    tr.train_pass(ds)
    assert not np.array_equal(tr.trained_table(), table_before)


def test_eval_forward_preds_bitwise_match_train_forward(tmp_path):
    """The forward-only step the serving plane compiles (eval_mode=True)
    must produce bitwise-identical preds to the TRAINING step's forward at
    equal params — same state, same batch, two programs. This is what lets
    the follower's scorer (serve/server.py) stand in for the trainer's
    eval numerics without a tolerance."""
    import jax.numpy as jnp

    from paddlebox_tpu.data.device_pack import pack_batch
    from paddlebox_tpu.data.parser import parse_line
    from paddlebox_tpu.data.slot_record import build_batch
    from paddlebox_tpu.metrics.auc import auc_init
    from paddlebox_tpu.table import HostSparseTable, PassWorkingSet
    from paddlebox_tpu.train import TrainState, make_train_step

    rng = np.random.default_rng(1)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NS)],
        label_slot="label",
    )
    lines = [
        f"1 {int(k[0]) % 2}.0 " + " ".join(f"1 {x}" for x in k)
        for k in (rng.integers(1, 300, NS) for _ in range(B))
    ]
    records = [parse_line(ln, schema) for ln in lines]
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    batch = build_batch(records, schema)
    ws = PassWorkingSet(n_mesh_shards=1)
    ws.add_keys(batch.keys)
    dev = ws.finalize(table, round_to=64)
    db = pack_batch(batch, ws, schema, bucket=64)
    feed = {k: jnp.asarray(v) for k, v in db.as_dict().items()}

    model = DeepFM(num_slots=NS, feat_width=LAYOUT.pull_width,
                   embedx_dim=4, hidden=(8,))
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=LAYOUT, sparse_opt=OPT, auc_buckets=500
    )
    dense_opt = optax.adam(1e-2)
    params = model.init(jax.random.PRNGKey(0))
    state = TrainState(
        table=jnp.asarray(dev.reshape(-1, LAYOUT.width)),
        params=params,
        opt_state=dense_opt.init(params),
        auc=auc_init(500),
        step=jnp.zeros((), jnp.int32),
    )
    # no donation on either side: the same state feeds both programs
    step_train = jax.jit(make_train_step(model.apply, dense_opt, cfg))
    step_eval = jax.jit(make_train_step(model.apply, dense_opt, cfg, eval_mode=True))
    _, m_train = step_train(state, feed)
    st_eval, m_eval = step_eval(state, feed)
    np.testing.assert_array_equal(
        np.asarray(m_eval["preds"]), np.asarray(m_train["preds"])
    )
    np.testing.assert_array_equal(
        float(m_eval["loss"]), float(m_train["loss"])
    )
    # and the eval step really is forward-only
    np.testing.assert_array_equal(np.asarray(st_eval.table), np.asarray(state.table))


def test_trainer_local_test_mode_flag(tmp_path):
    """trainer.set_test_mode works without a BoxWrapper binding."""
    box = BoxWrapper(embedx_dim=4, sparse_opt=OPT, n_host_shards=4)
    ds = _build(tmp_path, box)
    model = DeepFM(num_slots=NS, feat_width=LAYOUT.pull_width,
                   embedx_dim=4, hidden=(8,))
    cfg = TrainStepConfig(
        num_slots=NS, batch_size=B, layout=LAYOUT, sparse_opt=OPT, auc_buckets=500
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    ds.load_into_memory()
    ds.begin_pass(round_to=64)
    tr.train_pass(ds)
    t0 = tr.trained_table().copy()
    tr.set_test_mode(True)
    tr.train_pass(ds)
    np.testing.assert_array_equal(tr.trained_table(), t0)
    tr.set_test_mode(False)
    tr.train_pass(ds)
    assert not np.array_equal(tr.trained_table(), t0)
