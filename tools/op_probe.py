"""Accurate device-op timing immune to tunnel latency: each op is iterated
K times inside ONE jitted fori_loop with a data dependency between
iterations, so per-op device time = (blocked wall - overhead) / K.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

NUM_SLOTS = 39
BATCH = 4096
ROWS = 2_514_944
L = NUM_SLOTS * BATCH
U = 131_072
W = 21
PW = 19
K = 30  # iterations inside the loop


def timed_loop(name, body, init):
    """body(carry, salt) -> carry. Chained K times inside one jit."""

    @jax.jit
    def run(init):
        def f(i, c):
            return body(c, i)

        return jax.lax.fori_loop(0, K, f, init)

    out = run(init)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = run(init)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / K * 1e3
    print(f"{name:44s} {dt:9.3f} ms")
    return dt


SWEEP_WIDTHS = (8, 16, 21, 24, 32, 64, 128)


def sweep_point_names():
    """Addressable scatter-sweep probe points, in run order. Drivers (see
    tools/tpu_capture.py) give each point its own subprocess + timeout so
    one wedged point can't eat the whole sweep budget."""
    return [f"w{w}" for w in SWEEP_WIDTHS] + [
        "hints", "gather_set", "bf16", "pallas",
    ]


SWEEP_ARTIFACT_VERSION = 1


def _sweep_shape() -> dict:
    return {"rows": ROWS, "u": U, "w": W}


def load_sweep_artifact(path: str):
    """Partial sweep artifact at ``path``, or None if absent/stale.

    Stale = different schema version, probe shape, or backend: measured
    points from a different experiment must not be "resumed" into this
    one, so the sweep starts fresh (the old file is overwritten on the
    first completed point)."""
    import jax

    try:
        with open(path) as f:
            art = json.load(f)
    # absence/corruption probe: None (cache miss, re-sweep) IS the answer
    # pbox-lint: disable=EXC007
    except (OSError, ValueError):
        return None
    if (
        art.get("version") != SWEEP_ARTIFACT_VERSION
        or art.get("shape") != _sweep_shape()
        or art.get("backend") != jax.default_backend()
        or not isinstance(art.get("points"), dict)
    ):
        return None
    return art


def new_sweep_artifact() -> dict:
    import jax

    return {
        "version": SWEEP_ARTIFACT_VERSION,
        "shape": _sweep_shape(),
        "backend": jax.default_backend(),
        "points": {},
    }


def main():
    if "--list-sweep-points" in sys.argv:
        print("\n".join(sweep_point_names()))
        return
    only = None
    artifact_path = None
    for a in sys.argv[1:]:
        if a.startswith("--scatter-sweep="):
            only = a.split("=", 1)[1]
        if a.startswith("--sweep-artifact="):
            artifact_path = a.split("=", 1)[1]
    if only is not None:
        # single-point mode: skip the baseline probes so the per-point
        # subprocess pays backend init + ONE probe, nothing else
        if only not in sweep_point_names():
            print(f"unknown sweep point {only!r}; known: "
                  + " ".join(sweep_point_names()), file=sys.stderr)
            sys.exit(2)
        scatter_sweep(
            np.random.default_rng(0), only=only, artifact_path=artifact_path
        )
        return
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.standard_normal((ROWS, W)).astype(np.float32) * 0.01)
    rows_u = jnp.asarray(rng.integers(0, ROWS, U).astype(np.int32))
    rows_l = jnp.asarray(rng.integers(0, ROWS, L).astype(np.int32))
    inverse = jnp.asarray(rng.integers(0, U, L).astype(np.int32))
    gflat = jnp.asarray(rng.standard_normal((L, PW)).astype(np.float32))
    gu = jnp.asarray(rng.standard_normal((U, W)).astype(np.float32))
    preds = jnp.asarray(rng.random(BATCH).astype(np.float32))
    labels = jnp.asarray((rng.random(BATCH) < 0.2).astype(np.float32))

    # gather [U] rows from table
    timed_loop(
        "gather U=131k rows [2.5M,21]",
        lambda c, i: (c[0], c[1], jnp.take(c[0], c[1], axis=0).sum() + c[2] * 0),
        (table, rows_u, jnp.float32(0)),
    )

    # gather [L] rows
    timed_loop(
        "gather L=160k rows",
        lambda c, i: (c[0], c[1], jnp.take(c[0], c[1], axis=0).sum() + c[2] * 0),
        (table, rows_l, jnp.float32(0)),
    )

    # scatter-add U unique rows into table
    timed_loop(
        "scatter-add U=131k uniq [U,21] -> table",
        lambda c, i: (c[0].at[rows_u].add(c[1] * 1e-6), c[1]),
        (table, gu),
    )

    # scatter-add L dup rows into table-shaped accumulator
    timed_loop(
        "scatter-add L=160k dup [L,19] -> table acc",
        lambda c, i: (c[0].at[rows_l].add(c[1] * 1e-6), c[1]),
        (jnp.zeros((ROWS, PW)), gflat),
    )

    # segment_sum L->U
    timed_loop(
        "segment_sum L->U width 19",
        lambda c, i: (
            jax.ops.segment_sum(c[1], inverse, num_segments=U) * 1e-6 + c[0] * 0,
            c[1],
        ),
        (jnp.zeros((U, PW)), gflat),
    )

    # full-table elementwise update (adagrad-ish math on every row)
    def full_update(c, i):
        t, acc = c
        g = acc[:, :PW]
        g2 = t[:, 3:4] + jnp.sum(g * g, axis=1, keepdims=True)
        nt = t.at[:, 2 : 2 + PW].add(-0.05 * g / jnp.sqrt(g2 + 1e-8) * 0 + 1e-9)
        return (nt, acc)

    timed_loop(
        "full-table rowwise update [2.5M,21]",
        full_update,
        (table, jnp.zeros((ROWS, PW + 2))),
    )

    # AUC scatter 4096 -> 100k + saturation min
    def auc_body(c, i):
        pos, neg = c
        bucket = jnp.clip((preds * 100_000).astype(jnp.int32), 0, 99_999)
        il = (labels > 0.5).astype(jnp.int32)
        return (
            jnp.minimum(pos.at[bucket].add(il), 1 << 30),
            jnp.minimum(neg.at[bucket].add(1 - il), 1 << 30),
        )

    timed_loop(
        "auc update (2 scatters 4k->100k + min)",
        auc_body,
        (jnp.zeros(100_000, jnp.int32), jnp.zeros(100_000, jnp.int32)),
    )

    # device sort of L i32 (for on-device dedup option)
    timed_loop(
        "sort 160k i32 + argsort payload",
        lambda c, i: (jax.lax.sort_key_val(c[0] + i, c[1])[0], c[1]),
        (rows_l, jnp.arange(L, dtype=jnp.int32)),
    )

    # repeat/ragged expansion: cumsum + searchsorted at L
    lens = jnp.asarray(rng.integers(0, 3, NUM_SLOTS * BATCH).astype(np.int32))

    def ragged(c, i):
        ln = c[0]
        starts = jnp.cumsum(ln) - ln
        seg = jnp.searchsorted(
            jnp.cumsum(ln), jnp.arange(L, dtype=jnp.int32), side="right"
        )
        return (ln, seg.astype(jnp.float32).sum() * 0 + starts.astype(jnp.float32).sum() * 0)

    timed_loop("ragged expand (cumsum+searchsorted L)", ragged, (lens, jnp.float32(0)))

    if "--scatter-sweep" in sys.argv:
        scatter_sweep(rng, artifact_path=artifact_path)


def scatter_sweep(rng, only=None, artifact_path=None):
    """Candidate strategies against the ~16 ms scatter-add floor at
    U=131k/W=21 (VERDICT r4 item 5; box_wrapper.cu:31-456 PushCopy is the
    reference's hand-written answer to the same problem). Run on a HEALTHY
    chip; each row prints device ms/op. Interpretation notes inline.

    ``only`` restricts the run to one point of :func:`sweep_point_names`
    — the per-point subprocess mode tools/tpu_capture.py uses so a single
    wedged probe costs its own timeout, not the whole sweep.

    ``artifact_path`` makes the sweep RESUMABLE: each finished point is
    recorded (atomically) into a structured JSON artifact, and points
    already present are skipped — so a sweep killed by a wedge/timeout
    halfway keeps its measurements, and re-running finishes only the
    remainder. tools/tune_kernels.py consumes this artifact."""
    art = None
    done = set()
    if artifact_path is not None:
        art = load_sweep_artifact(artifact_path) or new_sweep_artifact()
        done = set(art["points"])

    skip_printed = set()

    def want(name):
        if only is not None and only != name:
            return False
        if name in done:
            if name not in skip_printed:
                skip_printed.add(name)
                print(f"{name:44s} skipped (already in {artifact_path})")
            return False
        return True

    def finish(name, ms=None, skipped=None):
        """Record one completed point and publish the artifact NOW — the
        next point may be the one that wedges."""
        if art is None:
            return
        entry = {
            "finished_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        }
        if ms is not None:
            entry["ms"] = round(float(ms), 3)
        if skipped is not None:
            entry["skipped"] = skipped
        art["points"][name] = entry
        from paddlebox_tpu.utils.fs import atomic_write

        with atomic_write(artifact_path) as f:
            json.dump(art, f, indent=1)

    if only is None:
        print("\n--- scatter strategy sweep (U=131k unique rows) ---")
    rows_np = np.sort(rng.choice(ROWS, U, replace=False).astype(np.int32))
    rows_s = jnp.asarray(rows_np)

    # width variants: the known non-monotonicity (W=8 fast, W=21 slow,
    # W=128 medium). A padded-width TABLE trades HBM for scatter speed.
    for w in SWEEP_WIDTHS:
        if not want(f"w{w}"):
            continue
        t = jnp.zeros((ROWS, w), jnp.float32)
        g = jnp.asarray(rng.standard_normal((U, w)).astype(np.float32))
        dt = timed_loop(
            f"scatter-add uniq sorted W={w:<3d}",
            lambda c, i: (c[0].at[rows_s].add(c[1] * 1e-6), c[1]),
            (t, g),
        )
        finish(f"w{w}", ms=dt)

    if want("hints") or want("gather_set") or want("bf16"):
        t21 = jnp.zeros((ROWS, W), jnp.float32)
        g21 = jnp.asarray(rng.standard_normal((U, W)).astype(np.float32))

    # sorted + hint combos at W=21 (hints measured no-op before; re-check)
    if want("hints"):
        dt = timed_loop(
            "scatter-add W=21 hints(sorted+unique)",
            lambda c, i: (
                c[0].at[rows_s].add(
                    c[1] * 1e-6, indices_are_sorted=True, unique_indices=True
                ),
                c[1],
            ),
            (t21, g21),
        )
        finish("hints", ms=dt)

    # gather-modify-SET (unique rows): scatter with set semantics instead
    # of add — different lowering, sometimes different cost
    if want("gather_set"):
        dt = timed_loop(
            "gather+set W=21 (set semantics)",
            lambda c, i: (
                c[0].at[rows_s].set(jnp.take(c[0], rows_s, axis=0) + c[1] * 1e-6),
                c[1],
            ),
            (t21, g21),
        )
        finish("gather_set", ms=dt)

    # bf16 update payload into an f32 table (half the update bytes; the
    # read-modify-write of the table itself is unchanged)
    if want("bf16"):
        dt = timed_loop(
            "scatter-add W=21 bf16 updates",
            lambda c, i: (
                c[0].at[rows_s].add((c[1] * 1e-6).astype(jnp.bfloat16).astype(jnp.float32)),
                c[1],
            ),
            (t21, g21),
        )
        finish("bf16", ms=dt)

    # Pallas per-row DMA set on a lane-aligned (W=128) table: the write
    # path the flag-gated kernel family already implements — viable only
    # if the padded table's HBM cost is acceptable
    if want("pallas"):
        try:
            from paddlebox_tpu.ops.pallas_kernels import (
                backend_is_tpu,
                write_rows_pallas,
            )

            if backend_is_tpu():
                t128 = jnp.zeros((ROWS, 128), jnp.float32)
                g128 = jnp.asarray(rng.standard_normal((U, 128)).astype(np.float32))
                dt = timed_loop(
                    "pallas write_rows W=128 (set)",
                    lambda c, i: (write_rows_pallas(c[0], rows_s, c[1]), c[1]),
                    (t128, g128),
                )
                finish("pallas", ms=dt)
            else:
                print("pallas W=128 probe skipped: backend is not tpu")
                finish("pallas", skipped="backend is not tpu")
        except Exception as e:  # pragma: no cover
            print(f"pallas W=128 probe skipped: {e}")
            finish("pallas", skipped=str(e)[:200])


if __name__ == "__main__":
    main()
