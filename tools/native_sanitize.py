"""Native-tier memory-safety replay: ASan+UBSan over the C++ sources.

The native tier (csrc/slot_parser.cc, batch_packer.cc, host_table.cc) is
plain C-ABI C++ driven through ctypes — a heap overflow or misaligned
read there corrupts the Python process silently; no test assertion ever
sees it. This driver rebuilds the three translation units with
``-fsanitize=address,undefined``, points the whole native tier at the
instrumented library via the ``PBOX_NATIVE_LIB`` override
(utils/native.py), and replays every native-touching test file against
it. Any sanitizer report is a hard failure.

Usage:
  python tools/native_sanitize.py [--quick] [--tsan] [--json PATH] [--keep]

``--quick`` replays only the parser+table suites (the two that drive the
bulk of the native surface); the default replays all native-importing
test files. ``--tsan`` switches to ThreadSanitizer: the sources rebuild
with ``-fsanitize=thread`` and the replay set narrows to the writeback/
table suites that drive the parallel writer pool and the double-buffered
spill stage — the races ASan structurally cannot see. ``--json`` writes
a machine-readable report (atomic). ``--keep`` leaves the instrumented
.so in csrc/build/ for reuse.

Exit codes: 0 clean (or environment cannot build — skipped with a
message, so CI lanes without g++ stay green), 1 sanitizer report or test
failure, 2 driver error.

Mechanics worth knowing (they are why this file exists instead of a
two-line Makefile rule):

- Python itself is not ASan-instrumented, so the runtime must come in
  through ``LD_PRELOAD`` (libasan + libubsan, resolved via
  ``gcc -print-file-name``) — otherwise dlopen of the instrumented .so
  fails with unresolved ``__asan_*`` symbols.
- ``ASAN_OPTIONS=detect_leaks=0``: LeakSanitizer sees the entire Python
  heap at exit and drowns the signal in CPython-internal "leaks".
- Throughput-assertion tests are deselected: the ~3x sanitizer tax makes
  their floors meaningless, and a perf floor is not a memory-safety
  claim.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_SRCS = [
    os.path.join(_REPO, "csrc", "slot_parser.cc"),
    os.path.join(_REPO, "csrc", "batch_packer.cc"),
    os.path.join(_REPO, "csrc", "host_table.cc"),
]
SAN_LIB = os.path.join(_REPO, "csrc", "build", "libpbx_parser_san.so")
TSAN_LIB = os.path.join(_REPO, "csrc", "build", "libpbx_parser_tsan.so")

# every test file that imports the native binding (the replay set); the
# quick set is the pair that drives most of the native surface area.
# test_multihost.py is deliberately absent: its native use happens inside
# spawned jax subprocess clusters, and LD_PRELOADing ASan into a full jax
# runtime breaks the CPU multiprocess collectives themselves (XLA refuses
# "multiprocess computations on the CPU backend") — a jax perturbation,
# not a native-tier signal; the same table/parser surface is replayed
# in-process by the files below
ALL_TESTS = (
    "tests/test_native_parser.py",
    "tests/test_native_table.py",
    "tests/test_record_store.py",
    "tests/test_tiered_store.py",
    "tests/test_spill_compaction.py",
    "tests/test_quarantine.py",
    "tests/test_prepare_stats.py",
    "tests/test_utils.py",
    "tests/test_advice_regressions.py",
)
QUICK_TESTS = ALL_TESTS[:2]

# the --tsan replay set: the suites that drive the parallel writeback
# writer pool, the double-buffered spill stage writers, and the pre-pass
# reader handoff — the thread-interleaving surface ASan cannot see
WRITEBACK_TESTS = (
    "tests/test_writeback_parallel.py",
    "tests/test_native_table.py",
    "tests/test_tiered_store.py",
)

# sanitizer report markers in pytest/stderr output; any hit fails the run
_SAN_MARKERS = (
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "AddressSanitizer:DEADLYSIGNAL",
    "runtime error:",  # UBSan
    "SUMMARY: UndefinedBehaviorSanitizer",
)
_TSAN_MARKERS = (
    "WARNING: ThreadSanitizer",
    "SUMMARY: ThreadSanitizer",
    "ThreadSanitizer:DEADLYSIGNAL",
)


def _runtime_libs(tsan: bool = False) -> list:
    """Sanitizer runtime paths for LD_PRELOAD (empty when unresolvable)."""
    libs = []
    names = ("libtsan.so",) if tsan else ("libasan.so", "libubsan.so")
    for name in names:
        try:
            p = subprocess.check_output(
                ["gcc", "-print-file-name=" + name], text=True, timeout=30
            ).strip()
        # availability probe: [] (no runtimes -> clean SKIP) IS the answer
        # pbox-lint: disable=EXC007
        except (OSError, subprocess.SubprocessError):
            return []
        if not os.path.isabs(p):  # gcc echoes the name back when unknown
            return []
        libs.append(p)
    return libs


def build_instrumented(tsan: bool = False) -> bool:
    """Compile the native sources with ASan+UBSan (or TSan) into
    SAN_LIB (TSAN_LIB)."""
    lib = TSAN_LIB if tsan else SAN_LIB
    san = "thread" if tsan else "address,undefined"
    os.makedirs(os.path.dirname(lib), exist_ok=True)
    tmp = f"{lib}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O1", "-g", "-fno-omit-frame-pointer", "-shared",
             "-fPIC", "-std=c++17", f"-fsanitize={san}",
             "-o", tmp] + _SRCS,
            check=True, capture_output=True, timeout=300,
        )
        os.replace(tmp, lib)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        out = getattr(e, "stderr", b"") or b""
        print(f"[native-sanitize] instrumented build failed: {e}")
        if out:
            print(out.decode(errors="replace")[-2000:])
        try:
            os.unlink(tmp)
        # pbox-lint: disable=EXC007 — tmp may never have been created
        except OSError:
            pass
        return False


def replay(tests, timeout_s: int, tsan: bool = False) -> dict:
    """Run ``tests`` against the instrumented lib; return the verdict."""
    env = dict(os.environ)
    if tsan:
        env.update(
            JAX_PLATFORMS="cpu",
            PBOX_NATIVE_LIB=TSAN_LIB,
            LD_PRELOAD=" ".join(_runtime_libs(tsan=True)),
            # second_deadlock_stack aids triage; halt_on_error turns the
            # first genuine race into a loud pytest failure.
            # ignore_noninstrumented_modules scopes checking to the one
            # TSan-built module (races in our writer pool / spill stage
            # still fire — verified with a deliberate-race probe); without
            # it, jax's uninstrumented XLA runtime drowns the run in
            # module-internal false positives. The suppressions file backs
            # that up for reports interceptors still attribute to XLA.
            TSAN_OPTIONS=(
                "halt_on_error=1:second_deadlock_stack=1"
                ":ignore_noninstrumented_modules=1:suppressions="
                + os.path.join(_REPO, "tools", "tsan.supp")
            ),
        )
    else:
        env.update(
            JAX_PLATFORMS="cpu",
            PBOX_NATIVE_LIB=SAN_LIB,
            LD_PRELOAD=" ".join(_runtime_libs()),
            ASAN_OPTIONS="detect_leaks=0:halt_on_error=1:abort_on_error=1",
            UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1",
        )
    cmd = [
        sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
        "-m", "not slow", "-k", "not throughput and not perf",
        *tests,
    ]
    proc = subprocess.run(
        cmd, cwd=_REPO, env=env, capture_output=True, text=True,
        timeout=timeout_s,
    )
    out = proc.stdout + proc.stderr
    markers = _TSAN_MARKERS if tsan else _SAN_MARKERS
    reports = sorted({m for m in markers if m in out})
    return {
        "returncode": proc.returncode,
        "sanitizer_reports": reports,
        "tail": out[-3000:],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="replay only the parser+table suites")
    ap.add_argument("--tsan", action="store_true",
                    help="ThreadSanitizer mode: rebuild with "
                         "-fsanitize=thread and replay the writeback/"
                         "table suites (writer-pool race coverage)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write a machine-readable report here (atomic)")
    ap.add_argument("--keep", action="store_true",
                    help="leave the instrumented .so in csrc/build/")
    ap.add_argument("--timeout", type=int, default=900,
                    help="replay wall-clock budget in seconds")
    args = ap.parse_args(argv)

    mode = "TSan" if args.tsan else "ASan+UBSan"
    report = {
        "tool": "native_sanitize", "ok": False, "skipped": False,
        "mode": mode,
    }
    if shutil.which("g++") is None or not _runtime_libs(tsan=args.tsan):
        # no compiler / no sanitizer runtime in this image: nothing to
        # verify here, and failing would just turn every such lane red
        report.update(ok=True, skipped=True,
                      reason="g++ or sanitizer runtimes unavailable")
        print("[native-sanitize] SKIP: g++ or sanitizer runtimes unavailable")
    elif not all(os.path.exists(s) for s in _SRCS):
        report.update(ok=True, skipped=True, reason="native sources absent")
        print("[native-sanitize] SKIP: native sources absent")
    elif not build_instrumented(tsan=args.tsan):
        report.update(reason="instrumented build failed")
        print("[native-sanitize] FAIL: instrumented build failed")
    else:
        if args.tsan:
            tests = WRITEBACK_TESTS
        else:
            tests = QUICK_TESTS if args.quick else ALL_TESTS
        verdict = replay(tests, args.timeout, tsan=args.tsan)
        report.update(
            tests=list(tests),
            returncode=verdict["returncode"],
            sanitizer_reports=verdict["sanitizer_reports"],
        )
        clean = (
            verdict["returncode"] == 0 and not verdict["sanitizer_reports"]
        )
        report["ok"] = clean
        if clean:
            print(f"[native-sanitize] PASS: {len(tests)} file(s) replayed "
                  f"under {mode}, zero reports")
        else:
            print("[native-sanitize] FAIL: "
                  f"pytest rc={verdict['returncode']}, sanitizer markers="
                  f"{verdict['sanitizer_reports'] or 'none'}")
            print(verdict["tail"])
        if not args.keep:
            try:
                os.unlink(TSAN_LIB if args.tsan else SAN_LIB)
            # pbox-lint: disable=EXC007 — absence is the goal state
            except OSError:
                pass

    if args.json:
        from paddlebox_tpu.utils.fs import atomic_write

        with atomic_write(args.json) as f:
            json.dump(report, f, indent=2)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
