"""Chaos probe: longer seeded fault-injection schedules through the
PassSupervisor, as a command-line soak.

tests/test_chaos.py pins one 3-pass schedule in tier-1; this probe runs
configurable multi-day schedules with probabilistic flakes layered over
deterministic crash windows, and reports the incident log plus an
equality check against a clean twin run. Exit code 0 iff the injected
run completes AND matches the clean run bitwise.

Usage:
  JAX_PLATFORMS=cpu python tools/chaos_probe.py \
      [--days N] [--passes N] [--rows N] [--seed N] \
      [--fs-flake-prob P] [--step-faults N] [--save-faults N] [--json]

``--corrupt-rate P`` switches to the data-poisoning soak: every data line
is corrupted with iid probability P (a seeded token flip that defeats both
parser tiers), the supervisor runs the dirty schedule under
``on_poisoned='degrade'``, and the run must (a) quarantine EXACTLY the
injected lines — ``data.quarantine.bad_lines_total`` delta == injected
count — and (b) finish bitwise-equal to a clean twin over the pre-cleaned
filelist (the same files with the corrupted lines removed):

  JAX_PLATFORMS=cpu python tools/chaos_probe.py --corrupt-rate 0.05 \
      [--days N] [--passes N] [--rows N] [--seed N] [--json]

``--distributed N`` switches to the multi-rank soak instead: an N-rank
in-process cluster (threads, real localhost TCP) runs ``--passes``
shuffled distributed passes — ins_id shuffle through TcpShuffleRouter,
working-set key exchange through DistributedWorkingSet, deterministic
train + writeback — under seeded ``transport.send`` /
``transport.recv_frame`` faults, and the run must be bitwise-equal
(row assignment, host tables, predictions) to a fault-free twin:

  JAX_PLATFORMS=cpu python tools/chaos_probe.py --distributed 3 \
      [--passes N] [--rows N] [--seed N] [--send-flake-prob P] [--json]

``--ici-wire`` is the frequency-adaptive wire A/B: four mesh-trainer days
over the SAME zipf-keyed day (4 virtual devices, embedx_dim=16) in fp32 /
bf16 / adaptive / adaptive-with-ablation-off, reporting the compiled
``wire.a2a_payload_bytes`` per mode plus AUC. Green iff the adaptive
payload is >=2x under fp32 and below uniform bf16, the adaptive day is
AUC-neutral vs fp32 (|delta| <= 0.02), hotness engaged, and the ablation
day matches fp32 bitwise:

  JAX_PLATFORMS=cpu python tools/chaos_probe.py --ici-wire \\
      [--passes N] [--rows N] [--seed N] [--json]

``--kill-rank R`` is the elastic-membership soak: an N-rank supervised
day (``--ranks``, default 4) loses rank R at the top of pass 1; the
survivors run the membership verdict round, adopt the dead rank's shard
ranges from its last checkpoint, revert the in-flight pass and finish
the day — and the final ownership-filtered digest plus per-pass global
AUC must be bitwise-equal to a FRESH (N-1)-rank run of the same day:

  JAX_PLATFORMS=cpu python tools/chaos_probe.py --kill-rank 1 \
      [--ranks N] [--passes N] [--rows N] [--seed N] [--json]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

S, B = 4, 16


def make_schema():
    from paddlebox_tpu.data import SlotInfo, SlotSchema

    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def write_day_files(tmpdir, date, n_passes, rows, seed):
    rng = np.random.default_rng(seed)
    files = []
    for p in range(n_passes):
        path = os.path.join(tmpdir, f"{date}-{p}.txt")
        lo = 1 + 40 * p
        with open(path, "w") as f:
            for _ in range(rows):
                parts = [f"1 {float(rng.integers(0, 2))}"]
                for _s in range(S):
                    k = int(rng.integers(1, 3))
                    parts.append(
                        f"{k} "
                        + " ".join(str(v) for v in rng.integers(lo, lo + 160, k))
                    )
                f.write(" ".join(parts) + "\n")
        files.append(path)
    return files


def build_supervisor(ckpt_root, on_poisoned=None):
    import jax
    import optax

    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import (
        CheckpointManager,
        CTRTrainer,
        PassSupervisor,
        RetryPolicy,
        TrainStepConfig,
    )

    opt = SparseOptimizerConfig(
        embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
    )
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, opt, n_shards=2, seed=0)
    ds = BoxPSDataset(make_schema(), table, batch_size=B, shuffle_mode="none")
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=layout, sparse_opt=opt,
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    sup = PassSupervisor(
        ds, tr, checkpoint=CheckpointManager(ckpt_root),
        retry=RetryPolicy(backoff_s=0.0, sleep=lambda s: None),
        round_to=8, on_poisoned=on_poisoned,
    )
    return table, tr, sup


def final_state(table, tr):
    import jax

    k = np.sort(table.keys())
    v = table.pull_or_create(k)
    dense = [
        np.asarray(x) for x in jax.tree.flatten((tr.params, tr.opt_state))[0]
    ]
    return k, v, dense


def run_schedule(tmpdir, tag, days, rules, on_poisoned=None):
    from paddlebox_tpu.utils.faultinject import inject

    table, tr, sup = build_supervisor(
        os.path.join(tmpdir, f"ckpt-{tag}"), on_poisoned=on_poisoned
    )
    t0 = time.perf_counter()
    with inject(*rules) as plan:
        for date, files in days:
            sup.run_day(date, [[f] for f in files])
    wall = time.perf_counter() - t0
    return table, tr, sup, plan, wall


def corrupt_day_files(files, out_dirty, out_clean, rate, seed):
    """Write a dirty twin (iid token flips at ``rate``) and a pre-cleaned
    twin (the corrupted lines REMOVED) of each file. Every flip replaces a
    random token with a non-numeric one, so both parser tiers reject the
    line. Returns (dirty_files, clean_files, n_corrupted)."""
    rng = np.random.default_rng(seed + 77)
    dirty_files, clean_files, n_bad = [], [], 0
    for path in files:
        lines = open(path).read().splitlines()
        dirty, clean = [], []
        for ln in lines:
            if rng.random() < rate:
                toks = ln.split(" ")
                toks[int(rng.integers(0, len(toks)))] = (
                    "!x%04x" % int(rng.integers(0, 1 << 16))
                )
                dirty.append(" ".join(toks))
                n_bad += 1
            else:
                dirty.append(ln)
                clean.append(ln)
        base = os.path.basename(path)
        dp = os.path.join(out_dirty, base)
        cp = os.path.join(out_clean, base)
        # scratch split files, consumed by this same process
        # pbox-lint: disable=IO004
        with open(dp, "w") as f:
            f.write("\n".join(dirty) + "\n")
        # pbox-lint: disable=IO004
        with open(cp, "w") as f:
            f.write("\n".join(clean) + "\n" if clean else "")
        dirty_files.append(dp)
        clean_files.append(cp)
    return dirty_files, clean_files, n_bad


def run_corrupt(args):
    """Data-poisoning soak: dirty schedule under on_poisoned='degrade'
    vs a clean twin over the pre-cleaned filelist. Exit 0 iff the
    quarantine counters account for every injected line AND the final
    state is bitwise-equal."""
    from paddlebox_tpu import config
    from paddlebox_tpu.utils.monitor import STAT_GET

    config.set_flag("fs_open_backoff_s", 0.0)
    with tempfile.TemporaryDirectory() as tmpdir:
        dirty_days, clean_days, injected = [], [], 0
        for d in range(args.days):
            date = f"202601{d + 1:02d}"
            src = os.path.join(tmpdir, f"src-{d}")
            dd = os.path.join(tmpdir, f"dirty-{d}")
            cd = os.path.join(tmpdir, f"cleaned-{d}")
            for p in (src, dd, cd):
                os.makedirs(p)
            files = write_day_files(
                src, date, args.passes, args.rows, args.seed + d
            )
            df, cf, nb = corrupt_day_files(
                files, dd, cd, args.corrupt_rate, args.seed + d
            )
            dirty_days.append((date, df))
            clean_days.append((date, cf))
            injected += nb

        table_c, tr_c, sup_c, _, wall_c = run_schedule(
            tmpdir, "clean", clean_days, ()
        )
        before = STAT_GET("data.quarantine.bad_lines_total")
        table_i, tr_i, sup_i, _, wall_i = run_schedule(
            tmpdir, "dirty", dirty_days, (), on_poisoned="degrade"
        )
        quarantined = int(STAT_GET("data.quarantine.bad_lines_total") - before)

        k_c, v_c, d_c = final_state(table_c, tr_c)
        k_i, v_i, d_i = final_state(table_i, tr_i)
        equal = (
            np.array_equal(k_i, k_c)
            and np.array_equal(v_i, v_c)
            and len(d_i) == len(d_c)
            and all(np.array_equal(a, b) for a, b in zip(d_i, d_c))
        )
        counts_match = quarantined == injected
        report = {
            "mode": "corrupt-soak",
            "corrupt_rate": args.corrupt_rate,
            "days": args.days,
            "passes_per_day": args.passes,
            "injected_bad_lines": injected,
            "quarantined_bad_lines": quarantined,
            "counts_match": counts_match,
            "degrade_incidents": sum(
                1 for i in sup_i.incidents if i.kind == "data_poisoned"
            ),
            "incidents": [i.as_dict() for i in sup_i.incidents],
            "bitwise_equal_to_clean": bool(equal),
            "wall_clean_s": round(wall_c, 2),
            "wall_injected_s": round(wall_i, 2),
        }
        print(json.dumps(report, indent=None if args.json else 2))
        return 0 if (equal and counts_match) else 1


def run_wedge_backend(args):
    """Wedged-backend smoke: arm the ``backend.init`` fault site so every
    watchdog probe sees a wedged runtime, then prove the triad contract —
    (a) ``ensure_backend`` lands on the labeled CPU fallback within the
    retry x timeout deadline, (b) a supervised mini-day still trains end to
    end on the fallback, and (c) ``tools/last_good_tpu_capture.json`` is
    byte-for-byte untouched (the watchdog must never clobber the last
    healthy chip's evidence). Exit 0 iff all three hold.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --wedge-backend [--json]
    """
    from paddlebox_tpu import config
    from paddlebox_tpu.utils.backendguard import ensure_backend
    from paddlebox_tpu.utils.faultinject import fail_always, inject
    from paddlebox_tpu.utils.monitor import STAT_GET

    capture_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "last_good_tpu_capture.json",
    )

    def capture_sig():
        try:
            st = os.stat(capture_path)
            return (st.st_mtime_ns, st.st_size)
        # absence probe: None (no capture yet) IS the answer
        # pbox-lint: disable=EXC007
        except OSError:
            return None

    sig_before = capture_sig()
    timeout_s, retries = 2.0, 2
    config.set_flag("fs_open_backoff_s", 0.0)
    deadline_s = retries * timeout_s + 5.0  # probes fail instantly when
    # injected; the slack covers CPU backend bring-up, not probe time
    t0 = time.perf_counter()
    with inject(fail_always("backend.init")) as plan:
        verdict = ensure_backend(
            timeout_s=timeout_s, retries=retries, backoff_s=0.0,
            probe="always", sleep=lambda s: None,
        )
    fallback_s = time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as tmpdir:
        date = "20260101"
        files = write_day_files(tmpdir, date, args.passes, args.rows, args.seed)
        table, tr, sup = build_supervisor(os.path.join(tmpdir, "ckpt-wedge"))
        t0 = time.perf_counter()
        sup.run_day(date, [[f] for f in files])
        day_s = time.perf_counter() - t0
        n_keys = len(table.keys())

    ok = (
        verdict.verdict == "fallback_cpu"
        and verdict.wedged
        and verdict.platform == "cpu"
        and fallback_s <= deadline_s
        and plan.failures("backend.init") == retries
        and capture_sig() == sig_before
        and n_keys > 0
    )
    report = {
        "mode": "wedge-backend",
        "verdict": verdict.as_dict(),
        "fallback_s": round(fallback_s, 2),
        "deadline_s": deadline_s,
        "probes_wedged": plan.failures("backend.init"),
        "stat_init_wedged": int(STAT_GET("backend.init_wedged")),
        "capture_untouched": capture_sig() == sig_before,
        "fallback_day_trained_keys": n_keys,
        "fallback_day_s": round(day_s, 2),
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def run_serve(args):
    """Serving-chain corruption smoke (``--serve``): a follower tailing a
    live publish stream must SKIP a corrupted delta with an alarm — same
    version served, bitwise-same scores — and catch up once the publisher
    repairs it. Exercises the deep per-file CRC gate: the corrupted byte
    lives inside a shard npz, so the watermark's manifest-CRC pin still
    matches and only verify_snapshot can catch it.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --serve [--json]
    """
    import serve_soak

    from paddlebox_tpu.data.parser import parse_line
    from paddlebox_tpu.serve import table_source, version_source
    from paddlebox_tpu.utils.monitor import STAT_GET

    with tempfile.TemporaryDirectory() as tmpdir:
        root = os.path.join(tmpdir, "ckpt")
        table, ds, cfg, trainer, mgr = serve_soak.make_stack(root)
        fol, scorer = serve_soak.make_follower(root, cfg)
        rng = np.random.default_rng(args.seed)
        date = serve_soak.DATE

        p0 = os.path.join(tmpdir, "pass-0.txt")
        lines = serve_soak.write_pass_file(rng, p0, args.rows, 1)
        probe = [parse_line(ln, serve_soak.SCHEMA) for ln in lines[:16]]

        def one_pass(lo, path=None):
            if path is None:
                path = os.path.join(tmpdir, f"pass-{lo}.txt")
                serve_soak.write_pass_file(rng, path, args.rows, lo)
            ds.set_filelist([path])
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            trainer.train_pass(ds)
            ds.end_pass(trainer.trained_table_device())
            table.drain_pending()

        def follower_scores(v):
            return scorer.score_records(
                probe, serve_soak.SCHEMA,
                version_source(serve_soak.LAYOUT, v), v.params, v.opt_state,
            )

        one_pass(1, path=p0)
        mgr.save_base(date, table, trainer)
        one_pass(120)
        mgr.save_delta(date, table, trainer)
        assert fol.poll_once()
        v1 = fol.version()
        good = follower_scores(v1)

        # publish delta-0002, then flip one byte inside a shard npz
        one_pass(260)
        mgr.save_delta(date, table, trainer)
        delta_dir = os.path.join(root, date, "delta-0002")
        victim = next(
            os.path.join(delta_dir, n)
            for n in sorted(os.listdir(delta_dir)) if n.endswith(".npz")
        )
        original = open(victim, "rb").read()
        # deliberate corruption of a published delta (raw is the point)
        # pbox-lint: disable=IO004
        with open(victim, "wb") as f:  # same size, one byte flipped
            f.write(original[:20] + bytes([original[20] ^ 0xFF]) + original[21:])

        skipped_before = STAT_GET("serve.corrupt_skipped")
        applied_corrupt = fol.poll_once()
        v_after = fol.version()
        scores_after = follower_scores(v_after)
        skipped = int(STAT_GET("serve.corrupt_skipped") - skipped_before)
        held = (
            not applied_corrupt
            and v_after is v1
            and np.array_equal(scores_after, good)
            and skipped >= 1
        )

        # deliberate in-place repair (raw is the point)
        # pbox-lint: disable=IO004
        with open(victim, "wb") as f:  # publisher repairs the delta
            f.write(original)
        caught_up = fol.poll_once()
        v2 = fol.version()
        ref = scorer.score_records(
            probe, serve_soak.SCHEMA,
            table_source(serve_soak.LAYOUT, table),
            trainer.params, trainer.opt_state,
        )
        recovered = (
            caught_up
            and v2.delta_idx == 2
            and np.array_equal(follower_scores(v2), ref)
        )

    ok = held and recovered
    report = {
        "mode": "serve",
        "corrupt_delta_skipped": skipped,
        "served_idx_during_corruption": v_after.delta_idx,
        "scores_held_bitwise": bool(held),
        "caught_up_after_repair": bool(caught_up),
        "final_served_idx": v2.delta_idx,
        "parity_after_repair_bitwise": bool(recovered),
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def run_stream(args):
    """Streaming-plane fault sweep (``--stream``): seeded faults on ALL
    THREE streaming sites — ``stream.tail_read`` (read error holds the
    cursor, zero loss), ``stream.cut_publish`` (kill in the durable-intent
    window, restart replays the spool exactly once), and ``ckpt.compact``
    (kill mid-fold leaves the old chain servable; the healed retry folds
    bitwise). Every site must FIRE, and the final table must be
    bitwise-identical to an uninterrupted clean twin over the same
    records.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --stream [--json]
    """
    import serve_soak

    from paddlebox_tpu.table import HostSparseTable
    from paddlebox_tpu.train.stream import StreamSupervisor
    from paddlebox_tpu.train.supervisor import HealthGates, PassSupervisor
    from paddlebox_tpu.utils.faultinject import InjectedFault, fail_nth, inject
    from paddlebox_tpu.utils.monitor import STAT_GET

    date = serve_soak.DATE
    chunks = 4

    def digest(table):
        k = np.sort(table.keys())
        v = table.pull_or_create(k)
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(k).tobytes())
        h.update(np.ascontiguousarray(v).tobytes())
        return h.hexdigest()

    def build(root, stream_dir, resume=False):
        table, ds, cfg, trainer, mgr = serve_soak.make_stack(root)
        sup = PassSupervisor(
            ds, trainer, checkpoint=mgr,
            gates=HealthGates(auc_min_history=99),
        )
        if resume:
            mgr.resume(table, trainer)  # before recovery replays the spool
        st = StreamSupervisor(
            sup, stream_dir, date, pattern="*.txt", compact_every=0,
        )
        return table, trainer, mgr, st

    def append(stream_dir, rng, lo):
        lines = []
        for _ in range(args.rows):
            keys = rng.integers(lo, lo + 200, 4)
            lines.append(
                f"1 {float(keys[0] % 2)} " + " ".join(f"1 {k}" for k in keys)
            )
        # the upstream appender the tailer follows
        # pbox-lint: disable=IO004
        with open(os.path.join(stream_dir, "events.txt"), "a") as f:
            f.write("\n".join(lines) + "\n")

    fired = {}
    with tempfile.TemporaryDirectory() as tmpdir:
        clean_root = os.path.join(tmpdir, "clean-ckpt")
        clean_stream = os.path.join(tmpdir, "clean-stream")
        root = os.path.join(tmpdir, "ckpt")
        stream_dir = os.path.join(tmpdir, "stream")
        os.makedirs(clean_stream)
        os.makedirs(stream_dir)

        rng = np.random.default_rng(args.seed)
        clean_table, _, _, clean_st = build(clean_root, clean_stream)
        for c in range(chunks):
            append(clean_stream, rng, 1 + c * 120)
            clean_st.step()
        want = digest(clean_table)

        rng = np.random.default_rng(args.seed)  # same records, faulted leg
        table, trainer, mgr, st = build(root, stream_dir)

        # site 1: a transient read error holds the cursor — the healed
        # retry consumes the SAME bytes (latency, never records)
        append(stream_dir, rng, 1)
        with inject(fail_nth("stream.tail_read", 1)) as plan:
            no_cut = st.step()  # read swallowed, nothing consumed
            fired["stream.tail_read"] = plan.failures("stream.tail_read")
        tail_held = no_cut is None
        st.step()  # healed: the chunk cuts now

        # site 2: kill in the durable-intent window; the restart stack
        # must replay the spool exactly once
        append(stream_dir, rng, 121)
        replays0 = STAT_GET("stream.replays")
        with inject(fail_nth("stream.cut_publish", 1)) as plan:
            try:
                st.step()
                cut_killed = False
            except InjectedFault:
                cut_killed = True
            fired["stream.cut_publish"] = plan.failures("stream.cut_publish")
        table, trainer, mgr, st = build(root, stream_dir, resume=True)
        replayed = int(STAT_GET("stream.replays") - replays0)

        for c in range(2, chunks):
            append(stream_dir, rng, 1 + c * 120)
            st.step()

        # site 3: kill mid-fold — the cursor never names a torn fold, so
        # the old chain resumes bitwise; the healed retry folds bitwise
        with inject(fail_nth("ckpt.compact", 2)) as plan:
            try:
                mgr.compact(
                    date,
                    HostSparseTable(
                        serve_soak.LAYOUT, serve_soak.OPT, n_shards=4, seed=0
                    ),
                )
                compact_killed = False
            except InjectedFault:
                compact_killed = True
            fired["ckpt.compact"] = plan.failures("ckpt.compact")
        from paddlebox_tpu.train import CheckpointManager

        t_held = HostSparseTable(
            serve_soak.LAYOUT, serve_soak.OPT, n_shards=4, seed=0
        )
        CheckpointManager(root).resume(t_held)
        held_bitwise = digest(t_held) == digest(table)
        folded = mgr.compact(
            date,
            HostSparseTable(
                serve_soak.LAYOUT, serve_soak.OPT, n_shards=4, seed=0
            ),
        ) is not None
        t_comp = HostSparseTable(
            serve_soak.LAYOUT, serve_soak.OPT, n_shards=4, seed=0
        )
        state = CheckpointManager(root).resume(t_comp)

        ok = (
            all(n >= 1 for n in fired.values())
            and tail_held
            and cut_killed
            and replayed == 1
            and compact_killed
            and held_bitwise
            and folded
            and int(state.get("compact") or 0) == chunks - 1
            and digest(table) == want
            and digest(t_comp) == want
        )
        report = {
            "mode": "stream",
            "sites_fired": fired,
            "tail_read_held_cursor": bool(tail_held),
            "cut_publish_killed": bool(cut_killed),
            "spool_replays": replayed,
            "compact_killed": bool(compact_killed),
            "old_chain_held_bitwise": bool(held_bitwise),
            "healed_fold_published": bool(folded),
            "compact_covers": int(state.get("compact") or 0),
            "final_bitwise_vs_clean": bool(digest(table) == want),
            "compacted_resume_bitwise": bool(digest(t_comp) == want),
            "ok": bool(ok),
        }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def run_serve_shard(args):
    """Mesh-sharded tier crash probe (``--serve-shard``): a follower with
    the device scoring tier ON takes an injected crash mid-tier-build
    (fault site ``serve.tier_build``) while applying a fresh delta. The
    FLT008 contract under test: the commit aborts whole — the previously
    served version (object identity, its tier, its scores) is untouched
    and no partial tier is ever visible — and the healed retry lands the
    same delta bitwise with the tier rebuilt.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --serve-shard [--json]
    """
    import serve_soak

    from paddlebox_tpu import config
    from paddlebox_tpu.data.parser import parse_line
    from paddlebox_tpu.serve import table_source, version_source
    from paddlebox_tpu.utils.faultinject import InjectedFault, fail_once, inject

    prev = {
        n: config.get_flag(n)
        for n in ("device_scoring_tier", "device_tier_hot_show")
    }
    config.set_flag("device_scoring_tier", "on")
    config.set_flag("device_tier_hot_show", 0.0)  # every published row is hot
    try:
        with tempfile.TemporaryDirectory() as tmpdir:
            root = os.path.join(tmpdir, "ckpt")
            table, ds, cfg, trainer, mgr = serve_soak.make_stack(root)
            fol, scorer = serve_soak.make_follower(root, cfg)
            rng = np.random.default_rng(args.seed)
            date = serve_soak.DATE

            p0 = os.path.join(tmpdir, "pass-0.txt")
            lines = serve_soak.write_pass_file(rng, p0, args.rows, 1)
            probe = [parse_line(ln, serve_soak.SCHEMA) for ln in lines[:16]]

            def one_pass(lo, path=None):
                if path is None:
                    path = os.path.join(tmpdir, f"pass-{lo}.txt")
                    serve_soak.write_pass_file(rng, path, args.rows, lo)
                ds.set_filelist([path])
                ds.load_into_memory()
                ds.begin_pass(round_to=8)
                trainer.train_pass(ds)
                ds.end_pass(trainer.trained_table_device())
                table.drain_pending()

            def follower_scores(v):
                return scorer.score_records(
                    probe, serve_soak.SCHEMA,
                    version_source(serve_soak.LAYOUT, v), v.params, v.opt_state,
                )

            one_pass(1, path=p0)
            mgr.save_base(date, table, trainer)
            assert fol.poll_once()
            v0 = fol.version()
            tier0 = v0.device_tier
            tier_on = tier0 is not None and tier0.n_rows > 0
            good = follower_scores(v0)

            one_pass(120)
            mgr.save_delta(date, table, trainer)
            with inject(fail_once("serve.tier_build")) as plan:
                crashed = False
                try:
                    fol.poll_once()
                except InjectedFault:
                    crashed = True
                v_mid = fol.version()
                held = (
                    crashed
                    and v_mid is v0
                    and v_mid.device_tier is tier0
                    and np.array_equal(follower_scores(v_mid), good)
                )
                # healed retry inside the same plan (fault budget spent):
                # staging re-apply is idempotent, the tier rebuilds
                caught_up = fol.poll_once()
            fired = plan.failures("serve.tier_build")
            v1 = fol.version()
            ref = scorer.score_records(
                probe, serve_soak.SCHEMA,
                table_source(serve_soak.LAYOUT, table),
                trainer.params, trainer.opt_state,
            )
            recovered = (
                caught_up
                and v1.delta_idx == 1
                and v1.device_tier is not None
                and v1.device_tier.n_rows > 0
                and np.array_equal(follower_scores(v1), ref)
            )
    finally:
        for n, v in prev.items():
            config.set_flag(n, v)

    ok = tier_on and held and recovered and fired == 1
    report = {
        "mode": "serve-shard",
        "tier_on_base": bool(tier_on),
        "tier_build_faults_fired": int(fired),
        "old_version_held_bitwise": bool(held),
        "healed_retry_caught_up": bool(caught_up),
        "final_served_idx": v1.delta_idx,
        "final_tier_rows": 0 if v1.device_tier is None else v1.device_tier.n_rows,
        "parity_after_heal_bitwise": bool(recovered),
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def run_serve_fleet(args):
    """Fleet churn soak under injected serve faults (``--serve-fleet``):
    the full networked day — N followers over one shared stage, follower
    kill + drain/admit + rejoin during concurrent publishes — run with
    faults firing at all three serve sites (a lost request, a torn stage
    fetch, a dropped drain command). The acceptance gate is unchanged:
    zero client-visible failures, bitwise parity live and offline, drain
    honored, single disk fetch per publish — the client's retry/hedge
    budget and the stager's idempotent retry must absorb every fault.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --serve-fleet [--json]
    """
    import serve_soak

    from paddlebox_tpu.utils.faultinject import fail_nth, inject

    with tempfile.TemporaryDirectory() as tmpdir:
        with inject(
            fail_nth("serve.request_recv", 5),
            fail_nth("serve.request_recv", 40),
            fail_nth("serve.fleet_stage", 2),
            fail_nth("serve.drain", 1),
        ) as plan:
            report = serve_soak.run_fleet_soak(
                tmpdir,
                n_followers=max(2, args.ranks - 1),
                # the churn script (kill@2, drain@3, admit+rejoin@4) needs
                # at least one clean publish after the rejoin
                passes=max(args.passes, 6),
                rows=args.rows,
                qps=30.0,
                probe_n=32,
            )
    faults = {
        "serve.request_recv": plan.failures("serve.request_recv"),
        "serve.fleet_stage": plan.failures("serve.fleet_stage"),
        "serve.drain": plan.failures("serve.drain"),
    }
    ok = report["ok"] and all(n > 0 for n in faults.values())
    report = {
        "mode": "serve-fleet",
        "faults_fired": faults,
        "soak": report,
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def run_proto_check(args):
    """Membership-protocol model check (``--proto-check``): explore the
    bounded elastic state machine (deaths, joins and no-votes injectable
    at every step) to a fixpoint, require zero invariant violations, and
    require every deliberately broken protocol variant to be caught on
    exactly the invariant it breaks — the checker demonstrates it can
    fail before its clean pass counts.

      python tools/chaos_probe.py --proto-check [--json]
    """
    import proto_check

    clean = proto_check.Checker(
        ranks=min(args.ranks, 3), deaths=1, joins=1, nos=1, max_epochs=2
    ).run()
    variants = {}
    ok = clean.complete and clean.ok
    for name in sorted(proto_check.BROKEN):
        inv, _desc, bounds = proto_check.BROKEN[name]
        res = proto_check.Checker(broken=name, **bounds).run()
        caught = bool(res.violations) and all(
            v["invariant"] == inv for v in res.violations
        )
        variants[name] = {
            "invariant": inv,
            "caught": caught,
            "states": res.states,
        }
        ok = ok and caught
    report = {
        "mode": "proto-check",
        "clean": clean.as_dict(),
        "broken": variants,
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def _ici_zipf_day(tmpdir, n_passes, rows, seed):
    """A zipf-keyed day: a small hot set dominates the traffic, the long
    tail shows up once or twice — the distribution the adaptive wire is
    built for. Labels are learnable so AUC is meaningful."""
    rng = np.random.default_rng(seed)
    files = []
    n_keys = 300
    for p in range(n_passes):
        path = os.path.join(tmpdir, f"zipf-{p}.txt")
        with open(path, "w") as f:
            for _ in range(rows):
                keys = np.minimum(rng.zipf(1.3, S), n_keys)
                keys = keys + np.arange(S) * n_keys  # per-slot key spaces
                label = 1.0 if (keys % 7 == 0).any() else 0.0
                parts = [f"1 {label}"] + [f"1 {k}" for k in keys]
                f.write(" ".join(parts) + "\n")
        files.append(path)
    return files


def run_ici_wire(args):
    """A/B the frequency-adaptive ICI wire against the uniform modes.

    Four mesh-trainer days over the SAME zipf day (4 virtual devices,
    embedx_dim=16): fp32, bf16, adaptive, and adaptive with the
    ici_wire_adaptive ablation off. Gates: the compiled a2a payload must
    shrink >=2x vs fp32 and below uniform bf16, the adaptive day must stay
    AUC-neutral vs fp32 (|delta| <= 0.02), hotness must actually engage
    (hot keys > 0 once shows accumulate), and the ablation day must finish
    bitwise-identical to fp32.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import optax

    from paddlebox_tpu import config
    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from paddlebox_tpu.utils.monitor import STAT_GET

    n_dev = 4
    config.set_flag("ici_hot_frac", 0.125)
    config.set_flag("ici_hot_show", 3.0)

    def day(mode, adaptive_on, files):
        config.set_flag("ici_wire_dtype", mode)
        config.set_flag("ici_wire_adaptive", adaptive_on)
        layout = ValueLayout(embedx_dim=16)
        opt = SparseOptimizerConfig(
            embedx_threshold=0.0, show_clk_decay=0.98, shrink_threshold=0.0
        )
        table = HostSparseTable(layout, opt, n_shards=n_dev, seed=0)
        plan = make_mesh(n_dev)
        ds = BoxPSDataset(
            make_schema(), table, batch_size=B, n_mesh_shards=n_dev,
            shuffle_mode="none",
        )
        model = DeepFM(
            num_slots=S, feat_width=layout.pull_width,
            embedx_dim=layout.embedx_dim, hidden=(16,),
        )
        cfg = TrainStepConfig(
            num_slots=S, batch_size=B // n_dev, layout=layout,
            sparse_opt=opt, auc_buckets=100, axis_name=plan.axis,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2), plan=plan)
        tr.init_params(jax.random.PRNGKey(0))
        overflow0 = int(STAT_GET("wire.ici_hot_overflow_keys"))
        auc = float("nan")
        for f in files:
            ds.set_filelist([f])
            ds.load_into_memory()
            ds.begin_pass(round_to=8)
            out = tr.train_pass(ds)
            auc = float(out["auc"])
            ds.end_pass(tr.trained_table())
        keys = np.sort(table.keys())
        from paddlebox_tpu.ops import wire_quant

        # wire.ici_hot_keys is a gauge (STAT_SET at ws finalize) — a leg
        # that never engages the adaptive wire would read the previous
        # leg's stale value
        engaged = wire_quant.ici_adaptive_engaged()
        return {
            "auc": auc,
            "payload_bytes": int(STAT_GET("wire.a2a_payload_bytes")),
            "fp32_bytes": int(STAT_GET("wire.a2a_fp32_bytes")),
            "dtype_bits": int(STAT_GET("wire.a2a_dtype_bits")),
            "hot_keys": int(STAT_GET("wire.ici_hot_keys")) if engaged else 0,
            "hot_overflow": int(STAT_GET("wire.ici_hot_overflow_keys"))
            - overflow0,
            "table": (keys, table.pull_or_create(keys)),
        }

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmpdir:
        files = _ici_zipf_day(tmpdir, args.passes, args.rows, args.seed)
        legs = {
            "fp32": day("fp32", True, files),
            "bf16": day("bf16", True, files),
            "adaptive": day("adaptive", True, files),
            "ablation": day("adaptive", False, files),
        }
    wall = time.perf_counter() - t0

    kf, vf = legs["fp32"].pop("table")
    ko, vo = legs["ablation"].pop("table")
    legs["bf16"].pop("table")
    legs["adaptive"].pop("table")
    ablation_bitwise = bool(
        np.array_equal(kf, ko) and np.array_equal(vf, vo)
    )
    pay = {m: legs[m]["payload_bytes"] for m in legs}
    ratio_fp32 = _ratio(legs["adaptive"]["fp32_bytes"], pay["adaptive"])
    auc_delta = abs(legs["adaptive"]["auc"] - legs["fp32"]["auc"])
    ok = (
        ratio_fp32 >= 2.0
        and pay["adaptive"] < pay["bf16"]
        and auc_delta <= 0.02
        and legs["adaptive"]["hot_keys"] > 0
        and ablation_bitwise
        and legs["ablation"]["payload_bytes"] == pay["fp32"]
    )
    report = {
        "probe": "ici_wire",
        "passes": args.passes,
        "rows": args.rows,
        "seed": args.seed,
        "devices": n_dev,
        "legs": {
            m: {k: v for k, v in r.items() if k != "table"}
            for m, r in legs.items()
        },
        "payload_ratio_fp32_over_adaptive": round(ratio_fp32, 3),
        "auc_delta_adaptive_vs_fp32": round(auc_delta, 5),
        "adaptive_below_bf16": bool(pay["adaptive"] < pay["bf16"]),
        "ablation_bitwise_fp32": ablation_bitwise,
        "wall_s": round(wall, 2),
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def _dist_free_ports(n):
    import socket

    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _dist_rank_records(rank, rows, seed, schema, pass_idx):
    from paddlebox_tpu.data.record_store import ColumnarRecords
    from paddlebox_tpu.data.slot_record import SlotRecord

    rng = np.random.default_rng(seed * 1009 + rank * 31 + pass_idx)
    recs = []
    for i in range(rows + 4 * rank):  # unequal loads across ranks
        keys, offs = [], [0]
        for _s in range(S):
            nk = int(rng.integers(1, 4))
            keys.extend(int(k) for k in rng.integers(1, 800, nk))
            offs.append(offs[-1] + nk)
        recs.append(
            SlotRecord(
                u64_values=np.array(keys, np.uint64),
                u64_offsets=np.array(offs, np.uint32),
                f_values=np.array([float(rng.integers(0, 2))], np.float32),
                f_offsets=np.array([0, 1], np.uint32),
                ins_id=f"p{pass_idx}-r{rank}-{i:05d}",
            )
        )
    return ColumnarRecords.from_records(recs, schema)


def _dist_soak_once(n_ranks, passes, rows, seed, rules, trace_dir=None):
    """One N-rank in-process soak under the given fault rules. Returns the
    per-rank observable digest the equality check compares. With
    ``trace_dir`` each rank records into its OWN Profiler (pid=rank) and
    exports ``trace-<rank>.json`` there — the merge-traces input."""
    import threading

    from paddlebox_tpu.data import SlotInfo, SlotSchema
    from paddlebox_tpu.data.dataset import shuffle_route_store
    from paddlebox_tpu.data.record_store import ColumnarRecords
    from paddlebox_tpu.obs.trace_context import trace_span
    from paddlebox_tpu.parallel.transport import TcpShuffleRouter, TcpTransport
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet
    from paddlebox_tpu.utils.faultinject import inject
    from paddlebox_tpu.utils.trace import Profiler

    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
        parse_ins_id=True,
    )
    profilers = None
    if trace_dir is not None:
        profilers = []
        for r in range(n_ranks):
            pr = Profiler()
            pr.enable()
            pr.set_process(r)
            profilers.append(pr)
    eps = [f"127.0.0.1:{p}" for p in _dist_free_ports(n_ranks)]
    tps = [
        TcpTransport(
            r, eps, timeout=60.0,
            profiler=profilers[r] if profilers else None,
        )
        for r in range(n_ranks)
    ]
    routers = [TcpShuffleRouter(t) for t in tps]
    layout = ValueLayout(embedx_dim=4)
    tables = [
        HostSparseTable(
            layout, SparseOptimizerConfig(embedx_threshold=0.0),
            n_shards=2, seed=0,
        )
        for _ in range(n_ranks)
    ]
    results = [None] * n_ranks
    errors = []

    def worker(rank):
        t = tps[rank]
        digest = []
        for p in range(passes):
            # the span context rides outbound PBTX frames (when
            # transport_trace_frames is on), so every rank's deliver
            # instants share this rank's trace_id — the merge evidence
            with trace_span(f"pass-{p}"):
                store = _dist_rank_records(rank, rows, seed, schema, p)
                dest = shuffle_route_store(store, n_ranks, "ins_id", seed=seed)
                routers[rank].exchange(
                    rank,
                    [store.select(np.nonzero(dest == d)[0])
                     for d in range(n_ranks)],
                )
                got = [c for c in routers[rank].collect(rank) if len(c)]
                mine = ColumnarRecords.concat(got)
                ws = DistributedWorkingSet(t, n_ranks, pass_id=p)
                ws.add_keys(mine.u64_values)
                dev = ws.finalize(tables[rank], round_to=8)
                dev = dev * 1.01 + 0.25  # deterministic "training"
                ws.writeback(dev)
                rows_of = ws.lookup(mine.u64_values)
                digest.append(
                    dict(
                        n_records=len(mine),
                        capacity=ws.capacity,
                        rows=rows_of,
                        sorted_keys=ws.sorted_keys,
                    )
                )
                t.barrier(f"probe-pass-{p}")
        keys = np.sort(tables[rank].keys())
        return dict(
            digest=digest,
            host_keys=keys,
            host_vals=tables[rank].pull_or_create(keys),
        )

    def wrap(r):
        try:
            results[r] = worker(r)
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errors.append((r, e))

    t0 = time.perf_counter()
    try:
        with inject(*rules) as plan:
            threads = [
                threading.Thread(target=wrap, args=(r,))
                for r in range(n_ranks)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join(300)
    finally:
        for t in tps:
            t.close()
    if errors:
        raise errors[0][1]
    if profilers is not None:
        for r, pr in enumerate(profilers):
            pr.export_chrome_trace(os.path.join(trace_dir, f"trace-{r}.json"))
    return results, plan, time.perf_counter() - t0


_WIRE_COUNTERS = (
    "wire.host_bytes_sent",
    "wire.host_raw_bytes_sent",
    "wire.host_bytes_recv",
    "wire.host_raw_bytes_recv",
    "wire.ws_req_bytes",
    "wire.ws_req_raw_bytes",
    "wire.ws_rep_bytes",
    "wire.ws_rep_raw_bytes",
)


def _wire_snapshot():
    from paddlebox_tpu.utils.monitor import STAT_GET

    return {k: int(STAT_GET(k)) for k in _WIRE_COUNTERS}


def _wire_delta(before, after):
    return {k: after[k] - before[k] for k in _WIRE_COUNTERS}


def _ratio(num, den):
    return round(num / den, 2) if den else None


def _digests_equal(a, b, n):
    equal = True
    for r in range(n):
        c, f = a[r], b[r]
        equal &= np.array_equal(c["host_keys"], f["host_keys"])
        equal &= np.array_equal(c["host_vals"], f["host_vals"])
        for dc, df in zip(c["digest"], f["digest"]):
            equal &= dc["n_records"] == df["n_records"]
            equal &= dc["capacity"] == df["capacity"]
            equal &= np.array_equal(dc["rows"], df["rows"])
            equal &= np.array_equal(dc["sorted_keys"], df["sorted_keys"])
    return bool(equal)


def _flight_recorder_smoke(inc_dir):
    """Provoke a REAL mid-collective peer death and check the flight
    recorder left an incident bundle: rank 1 stops beating, rank 0's
    barrier must raise PeerDeadError, and the dump hook on _take_all must
    land exactly one ``incident-*.json`` in ``inc_dir``."""
    from paddlebox_tpu import config
    from paddlebox_tpu.parallel.transport import PeerDeadError, TcpTransport

    saved = {
        n: config.get_flag(n)
        for n in ("transport_peer_dead_s", "obs_incident_dir")
    }
    config.set_flag("transport_peer_dead_s", 0.6)
    config.set_flag("obs_incident_dir", inc_dir)
    eps = [f"127.0.0.1:{p}" for p in _dist_free_ports(2)]
    tps = [TcpTransport(r, eps, timeout=30.0) for r in range(2)]
    raised = False
    try:
        tps[0].send(1, "fr-smoke", b"x")
        assert tps[1].recv("fr-smoke", 0, timeout=5.0) == b"x"
        deadline = time.monotonic() + 5.0
        while tps[0].peer_status(1) != "alive":
            assert time.monotonic() < deadline, "peers never connected"
            time.sleep(0.01)
        tps[1].close()  # rank 1 dies mid-run: no more heartbeats
        try:
            tps[0].barrier("fr-smoke-dead", timeout=30.0)
        except PeerDeadError:
            raised = True  # expected: detector names the dead rank
    finally:
        for t in tps:
            t.close()
        for name, v in saved.items():
            config.set_flag(name, v)
    bundles = sorted(
        f for f in os.listdir(inc_dir) if f.startswith("incident-")
    ) if os.path.isdir(inc_dir) else []
    return raised, bundles


def run_distributed(args):
    from paddlebox_tpu import config
    from paddlebox_tpu.utils.faultinject import fail_nth, fail_prob
    from paddlebox_tpu.utils.monitor import STAT_GET

    import obs_report

    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    # fault budget (times=) below the per-send retry budget: exhaustion is
    # impossible by construction, every injected schedule must heal
    config.set_flag("transport_send_retries", 6)
    n = args.distributed

    # soak 1: clean, codec on (the default wire)
    config.set_flag("host_wire_codec", True)
    w0 = _wire_snapshot()
    clean, _, wall_c = _dist_soak_once(n, args.passes, args.rows, args.seed, ())
    codec_wire = _wire_delta(w0, _wire_snapshot())

    # soak 2: faulted, codec on — send/recv flakes plus decode faults at
    # the new wire.host_decode site (a corrupt-after-CRC inflate kills the
    # connection; resync must replay exactly-once). This soak also runs
    # with per-rank profilers AND the PBTX trace-context frame extension
    # on: tracing must survive the fault schedule, and the exported
    # traces must merge into one timeline with cross-rank trace_id pairs.
    rules = [
        fail_prob("transport.send", args.send_flake_prob,
                  seed=args.seed + 1, times=6),
        fail_nth("transport.recv_frame", 7 + args.seed % 5, times=1),
        fail_nth("transport.recv_frame", 23 + args.seed % 7, times=1),
        fail_nth("wire.host_decode", 2 + args.seed % 3, times=1),
        fail_nth("wire.host_decode", 9 + args.seed % 5, times=1),
    ]
    with tempfile.TemporaryDirectory(prefix="chaos-traces-") as trace_dir:
        config.set_flag("transport_trace_frames", True)
        try:
            faulted, plan, wall_i = _dist_soak_once(
                n, args.passes, args.rows, args.seed, rules,
                trace_dir=trace_dir,
            )
        finally:
            config.set_flag("transport_trace_frames", False)
        merge = obs_report.merge_traces(
            [os.path.join(trace_dir, f"trace-{r}.json") for r in range(n)],
            os.path.join(trace_dir, "merged.json"),
        )

    # soak 3: clean, raw ablation — same results, more bytes; the
    # cross-soak host_bytes_sent ratio is the measured compression win
    config.set_flag("host_wire_codec", False)
    w0 = _wire_snapshot()
    try:
        raw, _, wall_r = _dist_soak_once(
            n, args.passes, args.rows, args.seed, ()
        )
    finally:
        config.set_flag("host_wire_codec", True)
    raw_wire = _wire_delta(w0, _wire_snapshot())

    # flight-recorder smoke: real peer death -> incident bundle on disk
    with tempfile.TemporaryDirectory() as inc_dir:
        fr_raised, fr_bundles = _flight_recorder_smoke(inc_dir)

    equal = _digests_equal(clean, faulted, n)
    equal_raw = _digests_equal(clean, raw, n)
    trace_ok = (
        len(merge["process_rows"]) == n
        and merge["cross_rank_trace_ids"] >= 1
    )
    fr_ok = fr_raised and len(fr_bundles) >= 1
    report = {
        "mode": "distributed",
        "ranks": n,
        "passes": args.passes,
        "faults_injected": {
            site: plan.failures(site)
            for site in (
                "transport.send", "transport.recv_frame", "wire.host_decode",
            )
        },
        "transport_stats": {
            k: STAT_GET(k)
            for k in (
                "transport.send_retries",
                "transport.frames_resent",
                "transport.reconnects",
                "transport.dup_frames_dropped",
                "transport.decode_errors",
            )
        },
        "host_wire": {
            "codec": codec_wire,
            "raw": raw_wire,
            # ≥2x is the ROADMAP item 2 gate: actual frame bytes, codec
            # soak vs raw-ablation soak of the identical schedule
            "host_bytes_ratio_raw_over_codec": _ratio(
                raw_wire["wire.host_bytes_sent"],
                codec_wire["wire.host_bytes_sent"],
            ),
            # per-exchange-round ratios inside the codec soak: raw-
            # equivalent bytes over encoded bytes
            "ws_req_ratio": _ratio(
                codec_wire["wire.ws_req_raw_bytes"],
                codec_wire["wire.ws_req_bytes"],
            ),
            "ws_rep_ratio": _ratio(
                codec_wire["wire.ws_rep_raw_bytes"],
                codec_wire["wire.ws_rep_bytes"],
            ),
            # frame-level ratio inside the codec soak (what v2 framing
            # would have shipped over what v3 shipped)
            "frame_ratio": _ratio(
                codec_wire["wire.host_raw_bytes_sent"],
                codec_wire["wire.host_bytes_sent"],
            ),
        },
        "trace_merge": {
            "process_rows": merge["process_rows"],
            "events": merge["events"],
            "trace_ids": merge["trace_ids"],
            "cross_rank_trace_ids": merge["cross_rank_trace_ids"],
            "trace_frames_sent": int(STAT_GET("transport.trace_frames_sent")),
            "trace_frames_recv": int(STAT_GET("transport.trace_frames_recv")),
            "ok": trace_ok,
        },
        "flight_recorder": {
            "peer_dead_raised": fr_raised,
            "incident_bundles": len(fr_bundles),
            "ok": fr_ok,
        },
        "bitwise_equal_to_clean": equal,
        "bitwise_equal_raw_vs_codec": equal_raw,
        "wall_clean_s": round(wall_c, 2),
        "wall_injected_s": round(wall_i, 2),
        "wall_raw_s": round(wall_r, 2),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if equal and equal_raw and trace_ok and fr_ok else 1


class _ProbeRankKilled(BaseException):
    """Escapes every supervisor except-Exception tier, like a real death."""


_ELASTIC_MESH = 8


def _elastic_records(seed, pass_idx, n_records):
    """One pass's GLOBAL record stream — identical for every membership;
    routing (record i -> sorted(live)[i % n_live]) decides who trains it."""
    rng = np.random.default_rng(1000 * seed + pass_idx)
    pool = rng.integers(1, 160, 4096).astype(np.uint64)
    recs = []
    for _ in range(n_records):
        nk = int(rng.integers(1, 4))
        keys = np.unique(rng.choice(pool, nk))
        recs.append((keys, float(rng.integers(0, 2))))
    return recs


def _elastic_mk_sup(rank, tps, root, seed, n_records, recorder, kill_at=None):
    from types import SimpleNamespace

    from paddlebox_tpu.parallel.membership import OwnershipMap
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet
    from paddlebox_tpu.train.checkpoint import CheckpointManager, rank_root
    from paddlebox_tpu.train.supervisor import (
        ElasticConfig,
        HealthGates,
        PassSupervisor,
        RetryPolicy,
    )

    table = HostSparseTable(
        ValueLayout(embedx_dim=2), SparseOptimizerConfig(embedx_threshold=0.0),
        n_shards=2, seed=0,
    )

    class _DS:
        """Dataset double over a REAL table + DistributedWorkingSet (the
        same harness tests/test_elastic.py pins in tier-1)."""

        def __init__(self):
            self.transport = tps[rank]
            self.table = table
            self.n_mesh_shards = _ELASTIC_MESH
            self.ownership = None
            self.pass_epoch = 0
            self._in_pass = False
            self.pass_idx = -1
            self.ws = None
            self.dev = None
            self.my_records = []

        def set_date(self, date):
            pass

        def set_filelist(self, files):
            self._files = list(files)

        def load_into_memory(self):
            self.pass_idx = int(self._files[0].rsplit("-", 1)[1])

        def _omap(self):
            return self.ownership or OwnershipMap.even(
                self.n_mesh_shards, self.transport.n_ranks
            )

        def begin_pass(self, round_to=8, enable_revert=True, trainer=None):
            live = list(self._omap().live_ranks)
            recs = _elastic_records(seed, self.pass_idx, n_records)
            me = self.transport.rank
            self.my_records = [
                rec for i, rec in enumerate(recs)
                if live[i % len(live)] == me
            ]
            ws = DistributedWorkingSet(
                self.transport, self.n_mesh_shards, pass_id=self.pass_idx,
                epoch=self.pass_epoch, ownership=self._omap(),
            )
            for keys, _ in self.my_records:
                ws.add_keys(keys)
            self.dev = ws.finalize(self.table, round_to=8)
            self.ws = ws
            self._in_pass = True

        def end_pass(self, table_, shrink=True):
            self.ws.writeback(self.dev)
            self._in_pass = False

        def revert_pass(self):
            # rows were only CREATED in finalize (deterministic init),
            # never trained: dropping the device slice reverts the pass
            self.ws = None
            self.dev = None
            self._in_pass = False
            self.pass_epoch += 1

    ds = _DS()

    def train_pass(_ds, n_batches=None):
        if kill_at is not None and ds.pass_idx == kill_at:
            ds.transport.close()
            raise _ProbeRankKilled()
        ds.dev = ds.dev * np.float32(1.01) + np.float32(0.25)
        preds, labels = [], []
        for keys, label in ds.my_records:
            rows = ds.ws.lookup(keys).astype(np.int64)
            preds.append(((int(rows.sum()) + ds.pass_idx) % 97) / 97.0)
            labels.append(label)
        recorder[(rank, ds.pass_idx)] = (
            np.array(preds, np.float32), np.array(labels, np.float32),
        )
        return {"batches": 1.0, "nan_batches": 0.0, "auc": 0.5}

    tr = SimpleNamespace(
        params=None,
        prepare_pass=lambda _ds, n: None,
        train_pass=train_pass,
        trained_table=lambda: None,
        init_params=lambda *a, **k: None,
        load_dense=lambda path: None,
        save_dense=lambda path: np.savez(path, z=np.zeros(1, np.float32)),
        _state=None,
        _state_ws=None,
    )
    sup = PassSupervisor(
        ds, tr,
        checkpoint=CheckpointManager(rank_root(root, rank)),
        gates=HealthGates(auc_min_history=99),
        retry=RetryPolicy(max_retries=2, backoff_s=0.0, sleep=lambda s: None),
        round_to=8,
        transport=tps[rank],
        elastic=ElasticConfig(shared_root=root, member_timeout=5.0),
    )
    return sup, ds


def _probe_run_threads(fn, n, join_s=300.0):
    """Run fn(rank) on n threads; each rank's state (supervisor, table,
    transport) is thread-confined — fn(r) only ever touches rank r's
    objects. Returns (results, errors)."""
    import threading

    results, errors = [None] * n, []

    def _wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001 - re-raised by caller
            errors.append((r, e))

    threads = [threading.Thread(target=_wrap, args=(r,)) for r in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(join_s)
    return results, errors


def _elastic_run_day(n, root, seed, n_records, passes, recorder,
                     kill_rank=None, kill_at=None):
    from paddlebox_tpu.parallel.transport import TcpTransport

    eps = [f"127.0.0.1:{p}" for p in _dist_free_ports(n)]
    tps = [TcpTransport(r, eps, timeout=60.0) for r in range(n)]
    sups = [
        _elastic_mk_sup(
            r, tps, root, seed, n_records, recorder,
            kill_at=(kill_at if r == kill_rank else None),
        )[0]
        for r in range(n)
    ]
    files = [[f"pass-{p}"] for p in range(passes)]

    def day(r):
        try:
            return sups[r].run_day("20260101", files)
        except _ProbeRankKilled:
            return "killed"

    t0 = time.perf_counter()
    try:
        results, errors = _probe_run_threads(day, n)
    finally:
        for t in tps:
            t.close()
    if errors:
        raise errors[0][1]
    return sups, results, time.perf_counter() - t0


def _elastic_merged_digest(sups, ranks):
    """Ownership-filtered global digest: every key exactly once, under its
    CURRENT owner."""
    from paddlebox_tpu.table.sparse_table import key_to_shard

    keys_parts, row_parts = [], []
    for r in ranks:
        sup = sups[r]
        lo, hi = sup.ds._omap().range_of(sup.coord.transport.rank)
        k = np.sort(sup.table.keys())
        sh = key_to_shard(k, _ELASTIC_MESH)
        k = k[(sh >= lo) & (sh < hi)]
        keys_parts.append(k)
        row_parts.append(sup.table.pull_or_create(k))
    keys = np.concatenate(keys_parts)
    rows = np.concatenate(row_parts)
    order = np.argsort(keys, kind="stable")
    assert len(keys) == len(np.unique(keys)), "ownership ranges overlap"
    return keys[order], rows[order]


def _elastic_pass_auc(recorder, p):
    import jax.numpy as jnp

    from paddlebox_tpu.metrics.auc import auc_compute, auc_init, auc_update

    entries = [v for (r, pp), v in sorted(recorder.items()) if pp == p]
    preds = np.concatenate([e[0] for e in entries])
    labels = np.concatenate([e[1] for e in entries])
    state = auc_update(auc_init(1000), jnp.asarray(preds), jnp.asarray(labels))
    return np.asarray(auc_compute(state))


def _elastic_run_day_rejoin(n, root, seed, n_records, passes, recorder,
                            join_rank):
    """N-rank day where ``join_rank`` dies at the top of pass 1 and a
    successor incarnation of the SAME rank rejoins mid-day. The rejoin
    waits until every survivor has INSTALLED the shrink (ownership epoch
    >= 1) — the earliest announce point that cannot mask the old
    incarnation's silence from the failure detector — so the join lands
    with the most day left to train."""
    from paddlebox_tpu.parallel.transport import TcpTransport

    eps = [f"127.0.0.1:{p}" for p in _dist_free_ports(n)]
    tps = [TcpTransport(r, eps, timeout=60.0) for r in range(n)]
    sups = [
        _elastic_mk_sup(
            r, tps, root, seed, n_records, recorder,
            kill_at=(1 if r == join_rank else None),
        )[0]
        for r in range(n)
    ]
    files = [[f"pass-{p}"] for p in range(passes)]
    survivors = [r for r in range(n) if r != join_rank]

    def day(r):
        if r != join_rank:
            return sups[r].run_day("20260101", files)
        try:
            sups[r].run_day("20260101", files)
            raise AssertionError("join rank was not killed")
        except _ProbeRankKilled:
            pass
        deadline = time.monotonic() + 120.0
        while not all(
            sups[s].ds.ownership is not None
            and sups[s].ds.ownership.epoch >= 1
            for s in survivors
        ):
            if time.monotonic() >= deadline:
                raise AssertionError("survivors never installed the shrink")
            time.sleep(0.02)
        tps[r] = TcpTransport(r, eps, timeout=60.0)
        sups[r] = _elastic_mk_sup(r, tps, root, seed, n_records, recorder)[0]
        return sups[r].join_day(files, timeout=120.0)

    t0 = time.perf_counter()
    try:
        results, errors = _probe_run_threads(day, n)
    finally:
        for t in tps:
            t.close()
    if errors:
        raise errors[0][1]
    return sups, results, time.perf_counter() - t0


def run_join_rank(args):
    """Elastic grow soak (``--join-rank=R``): an N-rank supervised day
    loses rank R at the top of pass 1 (shrink, epoch 1); a successor
    incarnation of the same rank announces once the shrunk fleet has
    installed the shrink, catches up from the published chains, receives
    its carved ranges through stage-then-commit migration and the fleet
    flips to epoch 2 — and the final ownership-filtered digest plus
    per-pass global AUC must be bitwise-equal to a FRESH fixed-size
    N-rank run of the same day. Exit 0 iff every gate holds.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --join-rank 1 \\
          --passes 5 [--json]
    """
    import glob as globmod

    from paddlebox_tpu import config
    from paddlebox_tpu.train.checkpoint import (
        rank_root,
        read_watermark,
        validate_watermark,
    )
    from paddlebox_tpu.utils.monitor import STAT_GET

    n, join_rank, passes = args.ranks, args.join_rank, args.passes
    if not (0 <= join_rank < n):
        print(f"--join-rank must be in [0, {n})", file=sys.stderr)
        return 2
    if passes < 4:
        print("--passes must be >= 4 (the kill, the shrink and the "
              "rejoin all land mid-day)", file=sys.stderr)
        return 2
    n_records = args.rows
    saved = {
        name: config.get_flag(name)
        for name in (
            "transport_heartbeat_s", "transport_backoff_s",
            "transport_send_retries", "transport_peer_dead_s",
        )
    }
    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_send_retries", 6)
    joins_before = STAT_GET("membership.joins_total")
    try:
        with tempfile.TemporaryDirectory() as tmpdir:
            # the elastic day: rank R dies at pass 1, rejoins mid-day
            config.set_flag("transport_peer_dead_s", 0.6)
            rec_e = {}
            root_e = os.path.join(tmpdir, "elastic")
            sups_e, res_e, wall_e = _elastic_run_day_rejoin(
                n, root_e, args.seed, n_records, passes, rec_e,
                join_rank=join_rank,
            )
            config.set_flag("transport_peer_dead_s", 60.0)
            survivors = [r for r in range(n) if r != join_rank]
            finished_ok = all(
                isinstance(res_e[r], list) and len(res_e[r]) == passes
                for r in survivors
            )
            rejoined_passes = (
                len(res_e[join_rank])
                if isinstance(res_e[join_rank], list) else -1
            )
            epochs = [
                sups_e[r].ds.ownership.epoch
                if sups_e[r].ds.ownership is not None else 0
                for r in range(n)
            ]
            live_after = (
                list(sups_e[0].ds.ownership.live_ranks)
                if sups_e[0].ds.ownership is not None else []
            )
            kinds_surv = sorted({
                i.kind for r in survivors for i in sups_e[r].incidents
            })
            joiner_kinds = sorted({
                i.kind for i in sups_e[join_rank].incidents
            })
            bundles = sum(
                len(globmod.glob(os.path.join(
                    rank_root(root_e, r), "obs", "incidents",
                    "incident-*.json",
                )))
                for r in range(n)
            )
            wm = read_watermark(rank_root(root_e, join_rank))
            validate_watermark(wm)
            wm_epoch = int(wm["ownership_epoch"])
            wm_live = list(wm.get("live_ranks", []))

            # the reference: a FRESH fixed-size N-rank run of the same day
            rec_f = {}
            sups_f, res_f, wall_f = _elastic_run_day(
                n, os.path.join(tmpdir, "fresh"), args.seed,
                n_records, passes, rec_f,
            )
            fresh_ok = all(
                isinstance(r, list) and len(r) == passes for r in res_f
            )
            ek, ev = _elastic_merged_digest(sups_e, list(range(n)))
            fk, fv = _elastic_merged_digest(sups_f, list(range(n)))
            digest_equal = bool(
                np.array_equal(ek, fk) and np.array_equal(ev, fv)
            )
            auc_equal = all(
                np.array_equal(
                    _elastic_pass_auc(rec_e, p), _elastic_pass_auc(rec_f, p)
                )
                for p in range(passes)
            )
    finally:
        for name, v in saved.items():
            config.set_flag(name, v)

    joins = int(STAT_GET("membership.joins_total") - joins_before)
    ok = (
        finished_ok and fresh_ok and rejoined_passes >= 1
        and all(e == 2 for e in epochs) and live_after == list(range(n))
        and wm_epoch == 2 and wm_live == list(range(n))
        and "rank_death" in kinds_surv and "rank_join" in kinds_surv
        and "rank_join" in joiner_kinds
        and joins >= n and bundles >= 1
        and digest_equal and auc_equal
    )
    report = {
        "mode": "join-rank",
        "ranks": n,
        "join_rank": join_rank,
        "kill_at_pass": 1,
        "passes": passes,
        "records_per_pass": n_records,
        "survivors_finished": bool(finished_ok),
        "rejoined_trained_passes": rejoined_passes,
        "ownership_epoch_after": epochs[0] if epochs else None,
        "live_ranks_after": live_after,
        "watermark_ownership_epoch": wm_epoch,
        "watermark_live_ranks": wm_live,
        "membership_joins": joins,
        "incident_kinds": sorted(set(kinds_surv) | set(joiner_kinds)),
        "incident_bundles": bundles,
        "digest_keys": int(len(ek)),
        "bitwise_equal_to_fresh_grown_run": digest_equal,
        "auc_equal_per_pass": bool(auc_equal),
        "wall_elastic_s": round(wall_e, 2),
        "wall_fresh_s": round(wall_f, 2),
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def run_kill_rank(args):
    """Elastic-membership soak (``--kill-rank=R``): an N-rank supervised
    day loses rank R mid-pass; survivors agree on the shrunk membership,
    adopt the dead rank's shard ranges from its checkpoint, revert the
    in-flight pass and finish the day — and the final ownership-filtered
    sparse digest AND per-pass global AUC must be bitwise-equal to a
    FRESH (N-1)-rank run of the same day. Exit 0 iff every gate holds.

      JAX_PLATFORMS=cpu python tools/chaos_probe.py --kill-rank 1 [--json]
    """
    import glob as globmod

    from paddlebox_tpu import config
    from paddlebox_tpu.train.checkpoint import (
        rank_root,
        read_watermark,
        validate_watermark,
    )
    from paddlebox_tpu.utils.monitor import STAT_GET

    n, kill_rank, passes, kill_at = args.ranks, args.kill_rank, args.passes, 1
    if not (0 <= kill_rank < n):
        print(f"--kill-rank must be in [0, {n})", file=sys.stderr)
        return 2
    if passes < 2:
        print("--passes must be >= 2 (the kill lands mid-day)",
              file=sys.stderr)
        return 2
    n_records = args.rows
    saved = {
        name: config.get_flag(name)
        for name in (
            "transport_heartbeat_s", "transport_backoff_s",
            "transport_send_retries", "transport_peer_dead_s",
        )
    }
    config.set_flag("transport_heartbeat_s", 0.05)
    config.set_flag("transport_backoff_s", 0.005)
    config.set_flag("transport_send_retries", 6)
    adopts_before = STAT_GET("membership.adopts")
    try:
        with tempfile.TemporaryDirectory() as tmpdir:
            # the elastic day: N ranks, one dies at the top of pass 1
            config.set_flag("transport_peer_dead_s", 0.6)
            rec_e = {}
            root_e = os.path.join(tmpdir, "elastic")
            sups_e, res_e, wall_e = _elastic_run_day(
                n, root_e, args.seed, n_records, passes, rec_e,
                kill_rank=kill_rank, kill_at=kill_at,
            )
            config.set_flag("transport_peer_dead_s", 60.0)
            survivors = [r for r in range(n) if r != kill_rank]
            killed_ok = res_e[kill_rank] == "killed"
            finished_ok = all(
                isinstance(res_e[r], list) and len(res_e[r]) == passes
                for r in survivors
            )
            epochs = [
                sups_e[r].ds.ownership.epoch
                if sups_e[r].ds.ownership is not None else 0
                for r in survivors
            ]
            kinds = sorted({
                i.kind for r in survivors for i in sups_e[r].incidents
            })
            bundles = sum(
                len(globmod.glob(os.path.join(
                    rank_root(root_e, r), "obs", "incidents",
                    "incident-*.json",
                )))
                for r in survivors
            )
            wm = read_watermark(rank_root(root_e, survivors[0]))
            validate_watermark(wm)
            wm_epoch = int(wm["ownership_epoch"])

            # the reference: a FRESH (N-1)-rank run of the same day
            rec_f = {}
            sups_f, res_f, wall_f = _elastic_run_day(
                n - 1, os.path.join(tmpdir, "fresh"), args.seed,
                n_records, passes, rec_f,
            )
            fresh_ok = all(
                isinstance(r, list) and len(r) == passes for r in res_f
            )
            ek, ev = _elastic_merged_digest(sups_e, survivors)
            fk, fv = _elastic_merged_digest(sups_f, list(range(n - 1)))
            digest_equal = bool(
                np.array_equal(ek, fk) and np.array_equal(ev, fv)
            )
            auc_equal = all(
                np.array_equal(
                    _elastic_pass_auc(rec_e, p), _elastic_pass_auc(rec_f, p)
                )
                for p in range(passes)
            )
    finally:
        for name, v in saved.items():
            config.set_flag(name, v)

    adopts = int(STAT_GET("membership.adopts") - adopts_before)
    ok = (
        killed_ok and finished_ok and fresh_ok
        and all(e == 1 for e in epochs) and wm_epoch == 1
        and "rank_death" in kinds and bundles >= len(survivors)
        and adopts >= 1 and digest_equal and auc_equal
    )
    report = {
        "mode": "kill-rank",
        "ranks": n,
        "killed_rank": kill_rank,
        "kill_at_pass": kill_at,
        "passes": passes,
        "records_per_pass": n_records,
        "survivors": survivors,
        "survivors_finished": bool(finished_ok),
        "ownership_epoch_after": epochs[0] if epochs else None,
        "watermark_ownership_epoch": wm_epoch,
        "membership_adopts": adopts,
        "incident_kinds": kinds,
        "incident_bundles": bundles,
        "digest_keys": int(len(ek)),
        "bitwise_equal_to_fresh_shrunk_run": digest_equal,
        "auc_equal_per_pass": bool(auc_equal),
        "wall_elastic_s": round(wall_e, 2),
        "wall_fresh_s": round(wall_f, 2),
        "ok": bool(ok),
    }
    print(json.dumps(report, indent=None if args.json else 2))
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--days", type=int, default=2)
    ap.add_argument("--passes", type=int, default=3, help="passes per day")
    ap.add_argument("--rows", type=int, default=64, help="rows per pass file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fs-flake-prob", type=float, default=0.05,
                    help="iid flake probability at fs.open_read")
    ap.add_argument("--step-faults", type=int, default=2,
                    help="poisoned device steps across the schedule")
    ap.add_argument("--save-faults", type=int, default=2,
                    help="torn checkpoint-save windows across the schedule")
    ap.add_argument("--distributed", type=int, default=0, metavar="N",
                    help="soak an N-rank in-process cluster under seeded "
                         "transport faults instead of the single-rank "
                         "supervisor schedule")
    ap.add_argument("--send-flake-prob", type=float, default=0.15,
                    help="iid flake probability at transport.send "
                         "(--distributed mode)")
    ap.add_argument("--kill-rank", type=int, default=None, metavar="R",
                    help="elastic-membership soak: an N-rank supervised "
                         "day loses rank R mid-pass; survivors must adopt "
                         "its shard ranges and finish bitwise-equal to a "
                         "fresh (N-1)-rank run of the same day")
    ap.add_argument("--join-rank", type=int, default=None, metavar="R",
                    help="elastic grow soak: rank R dies at pass 1 "
                         "(shrink), a successor incarnation rejoins once "
                         "the survivors installed the shrink (grow, epoch "
                         "2), and the day must finish bitwise-equal to a "
                         "fresh fixed-size N-rank run")
    ap.add_argument("--ranks", type=int, default=4,
                    help="cluster size for the --kill-rank / --join-rank "
                         "soaks")
    ap.add_argument("--corrupt-rate", type=float, default=0.0, metavar="P",
                    help="iid per-line data corruption probability; "
                         "switches to the quarantine/degrade soak "
                         "(single-rank only)")
    ap.add_argument("--wedge-backend", action="store_true",
                    help="simulate a wedged TPU runtime at the backend.init "
                         "fault site: ensure_backend must fall back to CPU "
                         "within the watchdog deadline, a mini supervised "
                         "day must still train, and the last-good TPU "
                         "capture must remain untouched")
    ap.add_argument("--native-sanitize", action="store_true",
                    help="memory-safety soak instead: rebuild the native "
                         "tier under ASan+UBSan and replay the native test "
                         "files against the instrumented library "
                         "(tools/native_sanitize.py, full set)")
    ap.add_argument("--tsan", action="store_true",
                    help="with --native-sanitize: ThreadSanitizer mode — "
                         "rebuild with -fsanitize=thread and replay the "
                         "parallel-writeback suites (writer-pool race "
                         "coverage)")
    ap.add_argument("--serve", action="store_true",
                    help="serving-chain corruption smoke: a follower must "
                         "skip a corrupted published delta with an alarm, "
                         "keep serving the last good version bitwise, and "
                         "catch up once the delta is repaired")
    ap.add_argument("--serve-fleet", action="store_true",
                    help="fleet churn soak under injected serve faults: "
                         "the networked serving day (kill + drain/admit + "
                         "rejoin over a shared stage) with lost requests, "
                         "a torn stage fetch, and a dropped drain command "
                         "injected — zero client-visible failures and "
                         "bitwise parity must survive all of it")
    ap.add_argument("--serve-shard", action="store_true",
                    help="mesh-sharded tier crash probe: a follower with "
                         "the device scoring tier on takes an injected "
                         "crash mid-tier-build (serve.tier_build) — the "
                         "old version must keep serving bitwise with no "
                         "partial tier, and the healed retry must land "
                         "the delta bitwise with the tier rebuilt")
    ap.add_argument("--ici-wire", action="store_true",
                    help="A/B the frequency-adaptive ICI wire: mesh-trainer "
                         "days over one zipf-keyed day in fp32 / bf16 / "
                         "adaptive / ablation, gating the >=2x payload cut "
                         "vs fp32, adaptive < bf16, AUC neutrality, and the "
                         "off-ablation bitwise match")
    ap.add_argument("--proto-check", action="store_true",
                    help="model-check the bounded elastic membership "
                         "protocol instead: the clean model must reach a "
                         "fixpoint with zero invariant violations and "
                         "every broken variant must be caught on its "
                         "invariant (tools/proto_check.py)")
    ap.add_argument("--stream", action="store_true",
                    help="streaming-plane fault sweep: seeded faults on "
                         "stream.tail_read, stream.cut_publish and "
                         "ckpt.compact (all must fire), recovery bitwise "
                         "vs an uninterrupted clean twin")
    ap.add_argument("--json", action="store_true", help="machine output only")
    args = ap.parse_args(argv)

    if args.native_sanitize:
        import native_sanitize

        return native_sanitize.main(["--tsan"] if args.tsan else [])
    if args.proto_check:
        return run_proto_check(args)
    if args.ici_wire:
        return run_ici_wire(args)
    if args.serve_shard:
        return run_serve_shard(args)
    if args.serve_fleet:
        return run_serve_fleet(args)
    if args.stream:
        return run_stream(args)
    if args.serve:
        return run_serve(args)
    if args.wedge_backend:
        return run_wedge_backend(args)
    if args.join_rank is not None:
        return run_join_rank(args)
    if args.kill_rank is not None:
        return run_kill_rank(args)
    if args.distributed:
        return run_distributed(args)
    if args.corrupt_rate > 0:
        return run_corrupt(args)

    from paddlebox_tpu import config
    from paddlebox_tpu.utils.faultinject import fail_nth, fail_prob
    from paddlebox_tpu.utils.monitor import STAT_GET

    config.set_flag("fs_open_backoff_s", 0.0)
    with tempfile.TemporaryDirectory() as tmpdir:
        days = []
        for d in range(args.days):
            date = f"202601{d + 1:02d}"
            days.append(
                (date, write_day_files(
                    tmpdir, date, args.passes, args.rows, args.seed + d))
            )

        # clean twin (an empty plan counts hits so fault schedules can be
        # sized relative to the real hit volume)
        table_c, tr_c, sup_c, probe, wall_c = run_schedule(
            tmpdir, "clean", days, ()
        )
        n_steps = probe.hits("step.device")
        n_saves = probe.hits("checkpoint.save")

        rng = np.random.default_rng(args.seed)
        rules = [fail_prob("fs.open_read", args.fs_flake_prob,
                           seed=args.seed, times=None)]
        for h in sorted(rng.choice(
                np.arange(2, max(3, n_steps)), size=min(args.step_faults,
                max(1, n_steps - 2)), replace=False).tolist()):
            rules.append(fail_nth("step.device", int(h)))
        for h in sorted(rng.choice(
                np.arange(2, max(3, n_saves)), size=min(args.save_faults,
                max(1, n_saves - 2)), replace=False).tolist()):
            rules.append(fail_nth("checkpoint.save", int(h)))

        table_i, tr_i, sup_i, plan, wall_i = run_schedule(
            tmpdir, "inj", days, rules
        )

        k_c, v_c, d_c = final_state(table_c, tr_c)
        k_i, v_i, d_i = final_state(table_i, tr_i)
        equal = (
            np.array_equal(k_i, k_c)
            and np.array_equal(v_i, v_c)
            and len(d_i) == len(d_c)
            and all(np.array_equal(a, b) for a, b in zip(d_i, d_c))
        )
        report = {
            "days": args.days,
            "passes_per_day": args.passes,
            "faults_injected": {
                site: plan.failures(site)
                for site in ("fs.open_read", "step.device", "checkpoint.save")
            },
            "incidents": [i.as_dict() for i in sup_i.incidents],
            "stat_faults_injected": STAT_GET("faults_injected"),
            "bitwise_equal_to_clean": bool(equal),
            "wall_clean_s": round(wall_c, 2),
            "wall_injected_s": round(wall_i, 2),
        }
        print(json.dumps(report if args.json else report, indent=None if args.json else 2))
        return 0 if equal else 1


if __name__ == "__main__":
    sys.exit(main())
