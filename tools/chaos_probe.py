"""Chaos probe: longer seeded fault-injection schedules through the
PassSupervisor, as a command-line soak.

tests/test_chaos.py pins one 3-pass schedule in tier-1; this probe runs
configurable multi-day schedules with probabilistic flakes layered over
deterministic crash windows, and reports the incident log plus an
equality check against a clean twin run. Exit code 0 iff the injected
run completes AND matches the clean run bitwise.

Usage:
  JAX_PLATFORMS=cpu python tools/chaos_probe.py \
      [--days N] [--passes N] [--rows N] [--seed N] \
      [--fs-flake-prob P] [--step-faults N] [--save-faults N] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

S, B = 4, 16


def make_schema():
    from paddlebox_tpu.data import SlotInfo, SlotSchema

    return SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(S)],
        label_slot="label",
    )


def write_day_files(tmpdir, date, n_passes, rows, seed):
    rng = np.random.default_rng(seed)
    files = []
    for p in range(n_passes):
        path = os.path.join(tmpdir, f"{date}-{p}.txt")
        lo = 1 + 40 * p
        with open(path, "w") as f:
            for _ in range(rows):
                parts = [f"1 {float(rng.integers(0, 2))}"]
                for _s in range(S):
                    k = int(rng.integers(1, 3))
                    parts.append(
                        f"{k} "
                        + " ".join(str(v) for v in rng.integers(lo, lo + 160, k))
                    )
                f.write(" ".join(parts) + "\n")
        files.append(path)
    return files


def build_supervisor(ckpt_root):
    import jax
    import optax

    from paddlebox_tpu.data import BoxPSDataset
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import (
        CheckpointManager,
        CTRTrainer,
        PassSupervisor,
        RetryPolicy,
        TrainStepConfig,
    )

    opt = SparseOptimizerConfig(
        embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
    )
    layout = ValueLayout(embedx_dim=4)
    table = HostSparseTable(layout, opt, n_shards=2, seed=0)
    ds = BoxPSDataset(make_schema(), table, batch_size=B, shuffle_mode="none")
    model = DeepFM(
        num_slots=S, feat_width=layout.pull_width, embedx_dim=4, hidden=(8,)
    )
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=layout, sparse_opt=opt,
        auc_buckets=100,
    )
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    tr.init_params(jax.random.PRNGKey(0))
    sup = PassSupervisor(
        ds, tr, checkpoint=CheckpointManager(ckpt_root),
        retry=RetryPolicy(backoff_s=0.0, sleep=lambda s: None),
        round_to=8,
    )
    return table, tr, sup


def final_state(table, tr):
    import jax

    k = np.sort(table.keys())
    v = table.pull_or_create(k)
    dense = [
        np.asarray(x) for x in jax.tree.flatten((tr.params, tr.opt_state))[0]
    ]
    return k, v, dense


def run_schedule(tmpdir, tag, days, rules):
    from paddlebox_tpu.utils.faultinject import inject

    table, tr, sup = build_supervisor(os.path.join(tmpdir, f"ckpt-{tag}"))
    t0 = time.perf_counter()
    with inject(*rules) as plan:
        for date, files in days:
            sup.run_day(date, [[f] for f in files])
    wall = time.perf_counter() - t0
    return table, tr, sup, plan, wall


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--days", type=int, default=2)
    ap.add_argument("--passes", type=int, default=3, help="passes per day")
    ap.add_argument("--rows", type=int, default=64, help="rows per pass file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fs-flake-prob", type=float, default=0.05,
                    help="iid flake probability at fs.open_read")
    ap.add_argument("--step-faults", type=int, default=2,
                    help="poisoned device steps across the schedule")
    ap.add_argument("--save-faults", type=int, default=2,
                    help="torn checkpoint-save windows across the schedule")
    ap.add_argument("--json", action="store_true", help="machine output only")
    args = ap.parse_args(argv)

    from paddlebox_tpu import config
    from paddlebox_tpu.utils.faultinject import fail_nth, fail_prob
    from paddlebox_tpu.utils.monitor import STAT_GET

    config.set_flag("fs_open_backoff_s", 0.0)
    with tempfile.TemporaryDirectory() as tmpdir:
        days = []
        for d in range(args.days):
            date = f"202601{d + 1:02d}"
            days.append(
                (date, write_day_files(
                    tmpdir, date, args.passes, args.rows, args.seed + d))
            )

        # clean twin (an empty plan counts hits so fault schedules can be
        # sized relative to the real hit volume)
        table_c, tr_c, sup_c, probe, wall_c = run_schedule(
            tmpdir, "clean", days, ()
        )
        n_steps = probe.hits("step.device")
        n_saves = probe.hits("checkpoint.save")

        rng = np.random.default_rng(args.seed)
        rules = [fail_prob("fs.open_read", args.fs_flake_prob,
                           seed=args.seed, times=None)]
        for h in sorted(rng.choice(
                np.arange(2, max(3, n_steps)), size=min(args.step_faults,
                max(1, n_steps - 2)), replace=False).tolist()):
            rules.append(fail_nth("step.device", int(h)))
        for h in sorted(rng.choice(
                np.arange(2, max(3, n_saves)), size=min(args.save_faults,
                max(1, n_saves - 2)), replace=False).tolist()):
            rules.append(fail_nth("checkpoint.save", int(h)))

        table_i, tr_i, sup_i, plan, wall_i = run_schedule(
            tmpdir, "inj", days, rules
        )

        k_c, v_c, d_c = final_state(table_c, tr_c)
        k_i, v_i, d_i = final_state(table_i, tr_i)
        equal = (
            np.array_equal(k_i, k_c)
            and np.array_equal(v_i, v_c)
            and len(d_i) == len(d_c)
            and all(np.array_equal(a, b) for a, b in zip(d_i, d_c))
        )
        report = {
            "days": args.days,
            "passes_per_day": args.passes,
            "faults_injected": {
                site: plan.failures(site)
                for site in ("fs.open_read", "step.device", "checkpoint.save")
            },
            "incidents": [i.as_dict() for i in sup_i.incidents],
            "stat_faults_injected": STAT_GET("faults_injected"),
            "bitwise_equal_to_clean": bool(equal),
            "wall_clean_s": round(wall_c, 2),
            "wall_injected_s": round(wall_i, 2),
        }
        print(json.dumps(report if args.json else report, indent=None if args.json else 2))
        return 0 if equal else 1


if __name__ == "__main__":
    sys.exit(main())
