"""Characterize H2D transfer behavior on the live backend (axon tunnel).

Answers: is device_put latency- or bandwidth-bound? do concurrent
device_puts from threads pipeline? does a transfer overlap device compute?
"""

from __future__ import annotations

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    print("platform:", jax.devices()[0].platform)

    # latency vs size
    for nbytes in (4_096, 65_536, 524_288, 2_097_152, 8_388_608, 33_554_432):
        a = np.ones(nbytes // 4, np.float32)
        x = jax.device_put(a)
        jax.block_until_ready(x)
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            jax.block_until_ready(jax.device_put(a))
        dt = (time.perf_counter() - t0) / n
        print(f"device_put {nbytes/1e6:8.3f} MB: {dt*1e3:8.2f} ms  "
              f"({nbytes/dt/1e6:8.1f} MB/s)")

    # D2H for comparison
    big = jax.device_put(np.ones(8_388_608 // 4, np.float32))
    jax.block_until_ready(big)
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(big)
    print(f"D2H 8.4 MB: {(time.perf_counter()-t0)/5*1e3:8.2f} ms")

    # 4 arrays of 0.5MB: sequential vs one fused 2MB
    arrs = [np.ones(131_072, np.float32) for _ in range(4)]
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        outs = [jax.device_put(a) for a in arrs]
        jax.block_until_ready(outs)
    print(f"4x0.5MB seq device_put: {(time.perf_counter()-t0)/n*1e3:8.2f} ms")

    fused = np.concatenate(arrs)
    t0 = time.perf_counter()
    for _ in range(n):
        jax.block_until_ready(jax.device_put(fused))
    print(f"1x2MB fused device_put: {(time.perf_counter()-t0)/n*1e3:8.2f} ms")

    # threaded: 4 device_puts from 4 threads
    ex = ThreadPoolExecutor(4)
    t0 = time.perf_counter()
    for _ in range(n):
        futs = [ex.submit(lambda a=a: jax.block_until_ready(jax.device_put(a))) for a in arrs]
        [f.result() for f in futs]
    print(f"4x0.5MB threaded:       {(time.perf_counter()-t0)/n*1e3:8.2f} ms")

    # overlap with compute: run a ~30ms matmul loop while a transfer flies
    m = jax.device_put(np.ones((8192, 8192), np.float32))

    @jax.jit
    def burn(m):
        for _ in range(12):
            m = m @ m * 1e-4
        return m

    jax.block_until_ready(burn(m))
    t0 = time.perf_counter()
    for _ in range(5):
        jax.block_until_ready(burn(m))
    tc = (time.perf_counter() - t0) / 5
    print(f"compute alone: {tc*1e3:8.2f} ms")

    t0 = time.perf_counter()
    for _ in range(5):
        f = ex.submit(lambda: jax.block_until_ready(jax.device_put(fused)))
        r = burn(m)
        jax.block_until_ready(r)
        f.result()
    to = (time.perf_counter() - t0) / 5
    print(f"compute + 2MB transfer concurrent: {to*1e3:8.2f} ms "
          f"(sum would be {tc*1e3 + 14.5:,.1f}+)")

    # dispatch latency of a trivial jitted fn (tunnel RPC round trip)
    @jax.jit
    def tiny(x):
        return x + 1

    s = jax.device_put(np.float32(1))
    jax.block_until_ready(tiny(s))
    t0 = time.perf_counter()
    for _ in range(20):
        jax.block_until_ready(tiny(s))
    print(f"tiny jit round-trip: {(time.perf_counter()-t0)/20*1e3:8.2f} ms")

    ex.shutdown(wait=True)


if __name__ == "__main__":
    main()
