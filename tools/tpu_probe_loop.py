#!/usr/bin/env python
"""Background TPU-health probe loop — now self-capturing.

Appends one JSON line per probe to tools/tpu_probe_log.jsonl:
    {"ts": ..., "ok": ..., "elapsed_s": ..., "detail": ...}

On the FIRST healthy probe (and whenever the existing capture artifact is
missing, incomplete, or stale vs the current bench config) it immediately
runs the full capture — ``tools/tpu_capture.py``: headline bench +
carrier/wire/pv ablations + scatter sweep + knob sweep — so a healthy
window between driver runs produces the measured TPU artifact, not just a
log line. The capture writes tools/last_good_tpu_capture.json
incrementally (headline first), so even a window shorter than the full
capture yields the headline number; bench.py embeds the artifact as
"tpu_capture" in any later CPU-fallback JSON.

Reuses backendguard.probe_backend (one watchdogged subprocess per probe —
the axon backend init is known to wedge for hours inside
make_c_api_client, and a hung child is killable while a hung in-process
import is not).

Usage: nohup python tools/tpu_probe_loop.py &  (from the repo root)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import (  # noqa: E402
    PROBE_LOOP_LOG,
    bench_config_id,
    read_last_capture,
)
from paddlebox_tpu.utils.backendguard import probe_backend  # noqa: E402


def _log(entry: dict) -> None:
    # append-only probe journal; atomic_write cannot append
    # pbox-lint: disable=IO004
    with open(PROBE_LOOP_LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")


def _ts(t: float | None = None) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


def capture_needed() -> str | None:
    """Why a (re)capture is needed, or None if the artifact is good."""
    cap = read_last_capture()
    if cap is None:
        return "no capture artifact"
    if cap.get("bench_config") != bench_config_id():
        return "bench config changed since last capture"
    head = cap.get("headline") or {}
    if head.get("platform") != "tpu":
        return "last capture's headline did not land on tpu"
    if "finished_at" not in cap:
        return "last capture incomplete (window closed mid-run)"
    return None


def run_capture(reason: str) -> None:
    _log({"ts": _ts(), "ok": True, "event": "capture_start", "reason": reason})
    t0 = time.time()
    # default must exceed the sum of tpu_capture's own per-stage budgets
    # (~6700s worst case) or a slow-but-healthy window gets killed
    # mid-sweep and the incomplete artifact forces a from-scratch
    # recapture on every later probe
    budget = float(os.environ.get("PBOX_CAPTURE_TIMEOUT", "7800"))
    # own session: on timeout the WHOLE process group dies — killing only
    # the direct child would orphan an in-flight bench.py grandchild,
    # which could sit on a wedged backend init forever holding the chip
    import signal

    proc = subprocess.Popen(
        [sys.executable, "tools/tpu_capture.py"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True, start_new_session=True,
    )
    try:
        _, err = proc.communicate(timeout=budget)
        rc, tail = proc.returncode, (err or "").strip().splitlines()[-3:]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
        rc, tail = -1, ["capture timed out (partial artifact kept)"]
    _log({
        "ts": _ts(), "ok": rc == 0, "event": "capture_end",
        "elapsed_s": round(time.time() - t0, 1), "rc": rc,
        "detail": " | ".join(tail)[:400],
    })


def main() -> None:
    interval = float(os.environ.get("PBOX_PROBE_INTERVAL", "420"))
    healthy_interval = float(os.environ.get("PBOX_PROBE_HEALTHY_INTERVAL", "1800"))
    timeout_s = float(os.environ.get("PBOX_BENCH_INIT_TIMEOUT", "150"))
    while True:
        t0 = time.time()
        info, err = probe_backend(timeout_s)
        entry = {
            "ts": _ts(t0),
            "ok": err is None,
            "elapsed_s": round(time.time() - t0, 1),
            "detail": json.dumps(info) if err is None else err[:200],
        }
        _log(entry)
        if err is None and info.get("platform") == "tpu":
            reason = capture_needed()
            if reason is not None:
                run_capture(reason)
        time.sleep(healthy_interval if err is None else interval)


if __name__ == "__main__":
    main()
