#!/usr/bin/env python
"""Background TPU-health probe loop.

Appends one JSON line per probe to tools/tpu_probe_log.jsonl:
    {"ts": ..., "ok": ..., "elapsed_s": ..., "detail": ...}

Reuses bench.probe_backend (one watchdogged subprocess per probe — the axon
backend init is known to wedge for hours inside make_c_api_client, and a hung
child is killable while a hung in-process import is not). The log is the
long-horizon wedge evidence bench.py attaches to its output JSON when the
chip never comes up during a run.

Usage: nohup python tools/tpu_probe_loop.py &  (from the repo root)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import PROBE_LOOP_LOG, probe_backend  # noqa: E402


def main() -> None:
    interval = float(os.environ.get("PBOX_PROBE_INTERVAL", "420"))
    healthy_interval = float(os.environ.get("PBOX_PROBE_HEALTHY_INTERVAL", "1800"))
    timeout_s = float(os.environ.get("PBOX_BENCH_INIT_TIMEOUT", "150"))
    while True:
        t0 = time.time()
        info, err = probe_backend(timeout_s)
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t0)),
            "ok": err is None,
            "elapsed_s": round(time.time() - t0, 1),
            "detail": json.dumps(info) if err is None else err[:200],
        }
        with open(PROBE_LOOP_LOG, "a") as f:
            f.write(json.dumps(entry) + "\n")
        time.sleep(healthy_interval if err is None else interval)


if __name__ == "__main__":
    main()
