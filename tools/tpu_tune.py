"""One-command TPU tuning sweep for when the backend is healthy.

Runs bench.py across (resident_scan_batches x max_inflight_steps) combos
at reduced batch count, prints a ranked table, and re-runs the best combo
at full TRAIN_BATCHES. Use after a backend wedge clears to re-validate the
recorded numbers and pick per-environment knobs.

  python tools/tpu_tune.py [--quick]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(env_extra, timeout=420):
    env = dict(os.environ)
    env.update({k: str(v) for k, v in env_extra.items()})
    try:
        p = subprocess.run(
            [sys.executable, "bench.py"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def main():
    quick = "--quick" in sys.argv
    combos = (
        [(8, 2), (16, 2)]
        if quick
        else [(4, 2), (8, 1), (8, 2), (8, 4), (16, 2), (32, 2)]
    )
    results = []
    for scan_k, inflight in combos:
        out = run_bench(
            {
                "PBOX_RESIDENT_SCAN_BATCHES": scan_k,
                "PBOX_MAX_INFLIGHT_STEPS": inflight,
                "PBOX_BENCH_INIT_TIMEOUT": 120,
                # one probe per combo: tune runs on a healthy chip; the
                # multi-probe budget is bench.py's own wedge protocol
                "PBOX_BENCH_INIT_RETRIES": 1,
            }
        )
        if out is None or out.get("platform") != "tpu":
            print(f"scan={scan_k:3d} inflight={inflight}: "
                  f"{'timeout' if out is None else out.get('tpu_error', out.get('platform'))}")
            continue
        results.append((out["value"], scan_k, inflight, out))
        print(f"scan={scan_k:3d} inflight={inflight}: "
              f"{out['value']:>9.1f} sps  train={out['train_pass_s']:.2f}s "
              f"fin={out['finalize_s']:.2f}s wb={out['writeback_s']:.2f}s "
              f"bnd={out.get('boundary_s', float('nan')):.2f}s")
    if not results:
        print("no TPU results (backend unhealthy?)")
        sys.exit(1)
    results.sort(reverse=True)
    best = results[0]
    print(f"\nbest: scan={best[1]} inflight={best[2]} -> {best[0]:.1f} sps "
          f"({best[3]['vs_baseline']}x baseline)")
    def show(label, out):
        if out is None or out.get("platform") != "tpu":
            detail = (
                "timeout"
                if out is None
                else out.get("tpu_error", out.get("platform"))
            )
            print(f"{label}: FAILED ({detail})")
            return
        print(f"{label}: {out['value']:>9.1f} sps  "
              f"boundary={out.get('boundary_s', float('nan')):.2f}s "
              f"(wb={out['writeback_s']:.2f} "
              f"fin2={out.get('finalize2_s', float('nan')):.2f}) "
              f"auc={out['auc']}")

    # wire-format ablation at the best combo: the sweep already measured
    # the bf16 default (bench.py's PBOX_WIRE_DTYPE default), so only fp32
    # needs a fresh run
    show("wire=bf16 (from sweep)", best[3])
    show(
        "wire=fp32",
        run_bench(
            {
                "PBOX_RESIDENT_SCAN_BATCHES": best[1],
                "PBOX_MAX_INFLIGHT_STEPS": best[2],
                "PBOX_WIRE_DTYPE": "fp32",
                "PBOX_BENCH_INIT_TIMEOUT": 120,
                "PBOX_BENCH_INIT_RETRIES": 1,
            }
        ),
    )
    show(
        "wire=int8",
        run_bench(
            {
                "PBOX_RESIDENT_SCAN_BATCHES": best[1],
                "PBOX_MAX_INFLIGHT_STEPS": best[2],
                "PBOX_WIRE_DTYPE": "int8",
                "PBOX_BENCH_INIT_TIMEOUT": 120,
                "PBOX_BENCH_INIT_RETRIES": 1,
            }
        ),
    )
    # bytes-per-boundary-row under each wire format at the bench layout
    # (what the ablation rows above are actually trading against quality)
    sys.path.insert(0, REPO)
    from bench import EMBEDX_DIM
    from paddlebox_tpu.ops.wire_quant import row_wire_nbytes
    from paddlebox_tpu.table import ValueLayout

    lay = ValueLayout(embedx_dim=EMBEDX_DIM)
    per_m = {m: row_wire_nbytes(1_000_000, lay, m) / 1e6 for m in
             ("fp32", "bf16", "int8")}
    print("row wire MB per 1M rows: "
          + "  ".join(f"{m}={v:.1f}" for m, v in per_m.items()))
    # carried-table ablation: classic full writeback + re-upload boundary
    show(
        "carried=off",
        run_bench(
            {
                "PBOX_RESIDENT_SCAN_BATCHES": best[1],
                "PBOX_MAX_INFLIGHT_STEPS": best[2],
                "PBOX_ENABLE_CARRIED_TABLE": 0,
                "PBOX_BENCH_INIT_TIMEOUT": 120,
                "PBOX_BENCH_INIT_RETRIES": 1,
            }
        ),
    )


if __name__ == "__main__":
    main()
